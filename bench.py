#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the headline metric.

Measures the BASELINE.json config matrix on the default JAX backend —
the bench environment's real TPU.  If the accelerator cannot be
reached within ``BENCH_BACKEND_TIMEOUT`` seconds (subprocess probe: a
dead tunnel hangs in-process backend init), the run falls back to CPU
with a shrunk config set and a clearly labeled ``backend`` field; the
probe costs one extra backend bring-up on healthy runs.  Sections:

- batched RSA-2048 e=65537 verify kernel throughput at batch
  {256, 1024, 4096} vs the single-core host ``pow`` baseline
  (reference hot loop: crypto/pgp/crypto_pgp.go:485-500);
- full-exponent modexp (threshold-RSA partial signing / TPA DH,
  reference: crypto/threshold/rsa/rsa.go:140-178);
- signed writes/sec + p50/p99 write latency through in-process
  clusters (4 / 16 / 64 replicas) with the cross-request verify
  dispatcher installed — the analog of the reference's only perf
  instrument, ``TestManyWrites``/``TestManyReads``
  (protocol/rw_test.go:65-109) and ``scripts/test.go:36-58``;
- batched revoke-on-read equivocation tally at 256 simulated
  replicas (BASELINE config 5).

Headline metric: signed writes/sec on the largest cluster measured;
``vs_baseline`` is the ratio against BASELINE.json's 50k-writes/sec
north star. Everything else rides in ``extra``.

Env knobs: BENCH_CONFIGS=kernel,c4,c16,c64,tally  BENCH_WRITERS=N
BENCH_WRITES=N  BENCH_KERNEL_BATCHES=256,1024,4096  BENCH_FAST=1
BENCH_BATCH=N (batched-pipeline sections)  BENCH_BACKEND_TIMEOUT=secs
BENCH_ZIPF=S (or ``--zipf S``): zipf-skewed key popularity for the
cluster sections — writers draw from one shared hot-key distribution
(exponent S, e.g. 1.1) instead of disjoint uniform keys; same-key
write races then surface as counted ``write_conflicts``, not errors.
BENCH_OPEN_LOOP=RATE (or ``--open-loop RATE``): cluster writers and
the gateway readers run open-loop at RATE ops/s — latency measured
from each op's scheduled arrival (coordinated-omission-corrected)
instead of throughput at saturation.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

NORTH_STAR_WRITES_PER_SEC = 50_000.0
# What one replica must verify/sec for the north star (44 verifies per
# cluster write at n=64 — docs/PERFORMANCE.md "The scaling math").
NORTH_STAR_VERIFIES_PER_SEC = 2_200_000.0

FAST = os.environ.get("BENCH_FAST") == "1"


def _env_list(name: str, default: str) -> list[str]:
    return [s for s in os.environ.get(name, default).split(",") if s]


# ---------------------------------------------------------------------------
# Kernel benchmarks
# ---------------------------------------------------------------------------



def _mfu(rate_per_sec: float, flops_per_op: float) -> float:
    """Percent of bf16 peak (v5e ≈ 197 TFLOP/s; override
    BENCH_PEAK_TFLOPS) the measured rate corresponds to — the MXU-dot
    FLOPs only, so this is a lower bound on utilization."""
    peak = float(os.environ.get("BENCH_PEAK_TFLOPS", "197")) * 1e12
    return round(100.0 * rate_per_sec * flops_per_op / peak, 3)


def _rns_verify_flops() -> float:
    """MXU FLOPs per RSA-2048 RNS verify: 19 Montgomery products
    (to-Mont + 17 for e=65537 + from-Mont), each 12 bf16 dots of
    (T,k)x(k,k+1) → 2·k·(k+1) FLOP/row/dot, plus the digit→residue
    conversion matmuls."""
    from bftkv_tpu.ops import rns

    k = rns.context().k
    mont = 12 * 2 * k * (k + 1)
    conv = 2 * 6 * 2 * (2 * 128) * (2 * k + 1) / 2  # two operands, 6 dots
    return 19 * mont + conv


def _rns_sign_flops() -> float:
    """MXU FLOPs per RSA-2048 CRT signature: two 1024-bit windowed
    modexp rows, each ~1299 Montgomery products (256 steps × 5 + the
    16-entry table + framing)."""
    from bftkv_tpu.ops import rns

    k = rns.context(64, 1024).k
    mont = 12 * 2 * k * (k + 1)
    return 2 * 1299 * mont


def _pallas_status() -> dict:
    """Whether the fused Pallas chains ran, fell back, or went unused
    in THIS process (cluster sections run them via auto mode once the
    kernel sections have written the proven marker)."""
    from bftkv_tpu.ops import rns

    return rns.pallas_status()


def _verify_operands(batch: int, nlimbs: int = 128):
    """(sig, em, n, n', r2) arrays for a batch of genuine signatures.

    Signs a small distinct set on host and tiles it: verification cost
    is identical for repeated rows, and host signing 4096 items would
    dominate setup time.
    """
    from bftkv_tpu.crypto import rsa
    from bftkv_tpu.ops import bigint, limb

    key = rsa.generate(nlimbs * 16)
    dom = bigint.MontgomeryDomain(key.n, nlimbs)
    base = min(batch, 32)
    sigs, ems = [], []
    for i in range(base):
        msg = b"bench-%d" % i
        s = int.from_bytes(rsa.sign(msg, key), "big")
        em = rsa.emsa_pkcs1v15_sha256(msg, key.size_bytes)
        sigs.append(limb.int_to_limbs(s, nlimbs))
        ems.append(limb.int_to_limbs(em, nlimbs))
    reps = -(-batch // base)
    sig = np.tile(np.stack(sigs), (reps, 1))[:batch]
    em = np.tile(np.stack(ems), (reps, 1))[:batch]
    rep = lambda row: np.broadcast_to(row, (batch, nlimbs)).copy()
    return key, sig, em, rep(dom.n), rep(dom.n_prime), rep(dom.r2), rep(dom.one_mont)


def bench_kernel_verify(batches: list[int]) -> dict:
    """Device verifies/sec per batch size + host pow baseline."""
    import jax

    from bftkv_tpu.ops import rsa as rsa_ops

    out: dict = {"batch": {}}
    key, sig, em, n, npr, r2, _one = _verify_operands(max(batches))
    for b in sorted(batches):
        args = [jax.device_put(a[:b]) for a in (sig, em, n, npr, r2)]
        t0 = time.perf_counter()
        ok = np.asarray(rsa_ops.verify_batch_e65537(*args))
        compile_s = time.perf_counter() - t0
        assert ok.all(), "bench verify kernel returned false on genuine sigs"
        # Timed iterations on device-resident operands.
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 2.0) or iters < 3:
            jax.block_until_ready(rsa_ops.verify_batch_e65537(*args))
            iters += 1
            elapsed = time.perf_counter() - t0
        rate = b * iters / elapsed
        out["batch"][str(b)] = {
            "verifies_per_sec": round(rate, 1),
            "first_call_s": round(compile_s, 2),
            "iters": iters,
        }
    # Host single-core baseline: raw pow() as the reference's math/big does.
    from bftkv_tpu.ops import limb

    s_int = limb.limbs_to_ints(sig[:64])
    em_int = limb.limbs_to_ints(em[:64])
    t0 = time.perf_counter()
    for s, e in zip(s_int, em_int):
        assert pow(s, 65537, key.n) == e
    host_rate = 64 / (time.perf_counter() - t0)
    out["host_pow_verifies_per_sec"] = round(host_rate, 1)
    best = max(v["verifies_per_sec"] for v in out["batch"].values())
    out["best_verifies_per_sec"] = best
    out["speedup_vs_host_pow"] = round(best / host_rate, 2)
    return out


def bench_kernel_modexp(batch: int = 256) -> dict:
    """Full 2048-bit-exponent modexp (threshold-RSA partial sign / TPA)."""
    import jax

    from bftkv_tpu.ops import limb
    from bftkv_tpu.ops import rsa as rsa_ops

    key, sig, _em, n, npr, r2, one = _verify_operands(batch)
    e = np.broadcast_to(limb.int_to_limbs(key.d, 128), (batch, 128)).copy()
    args = [jax.device_put(a) for a in (sig, e, n, npr, r2, one)]
    t0 = time.perf_counter()
    jax.block_until_ready(rsa_ops.power_batch(*args))
    compile_s = time.perf_counter() - t0
    iters, elapsed = 0, 0.0
    t0 = time.perf_counter()
    while elapsed < (0.5 if FAST else 2.0) or iters < 2:
        jax.block_until_ready(rsa_ops.power_batch(*args))
        iters += 1
        elapsed = time.perf_counter() - t0
    rate = batch * iters / elapsed
    # Host baseline on 8 items.
    s_int = limb.limbs_to_ints(sig[:8])
    t0 = time.perf_counter()
    for s in s_int:
        pow(s, key.d, key.n)
    host_rate = 8 / (time.perf_counter() - t0)
    return {
        "batch": batch,
        "modexps_per_sec": round(rate, 1),
        "host_pow_modexps_per_sec": round(host_rate, 1),
        "speedup_vs_host_pow": round(rate / host_rate, 2),
        "first_call_s": round(compile_s, 2),
    }


def bench_kernel_rns(batches=(4096, 16384, 65536)) -> dict:
    """RSA-2048 e=65537 verifies/sec on the RNS (MXU/f32) kernel — the
    default verify backend; ~19x the limb kernel at large batch."""
    import jax

    from bftkv_tpu.ops import rns

    ctx = rns.context()
    out: dict = {"batch": {}}
    key, sig, em, _n, _npr, _r2, _one = _verify_operands(32)
    row = [np.asarray(r) for r in ctx.key_rows(key.n)]
    f = rns._jitted_verify()
    for b in sorted(batches):
        sig_d = np.tile(sig, (b // 32 + 1, 1))[:b]
        em_d = np.tile(em, (b // 32 + 1, 1))[:b]
        kr = tuple(
            jax.device_put(
                np.broadcast_to(r, (b,) + r.shape).copy()
                if r.ndim
                else np.full((b, 1), r, dtype=np.float32)
            )
            for r in row
        )
        sh = jax.device_put(rns.digits_to_halves(sig_d))
        eh = jax.device_put(rns.digits_to_halves(em_d))
        t0 = time.perf_counter()
        ok = np.asarray(f(sh, eh, kr))
        compile_s = time.perf_counter() - t0
        assert ok.all(), "RNS bench kernel returned false on genuine sigs"
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 3.0) or iters < 3:
            jax.block_until_ready(f(sh, eh, kr))
            iters += 1
            elapsed = time.perf_counter() - t0
        out["batch"][str(b)] = {
            "verifies_per_sec": round(b * iters / elapsed, 1),
            "first_call_s": round(compile_s, 2),
        }
    # Production-path comparison (verify_e65537_rns_indexed: u8
    # transfer + on-device key gather) under BOTH backends — XLA at the
    # two largest batches, Pallas at the largest only (each batch shape
    # is its own Mosaic compile).  Forced-Pallas completing here writes
    # the proven marker that arms auto mode for the cluster sections;
    # the exported pallas_status says whether the fused chain really
    # ran or the loud XLA fallback fired (VERDICT r4 item 3).
    urows = rns.stack_key_rows([row])
    # Forced-Pallas only on real TPU: interpret mode on CPU takes
    # minutes per batch and proves nothing about the Mosaic path.
    modes = ("xla", "pallas") if jax.default_backend() == "tpu" else ("xla",)
    for mode in modes:
        dest = out.setdefault(f"indexed_{mode}", {"batch": {}})["batch"]
        os.environ["BFTKV_RNS_VERIFY_BACKEND"] = mode
        try:
            # Pallas at the largest batch only (one Mosaic compile per
            # window); XLA keeps two sizes for the amortization curve.
            for b in sorted(batches)[-2:] if mode == "xla" else sorted(batches)[-1:]:
                sig_d = np.tile(sig, (b // 32 + 1, 1))[:b]
                em_d = np.tile(em, (b // 32 + 1, 1))[:b]
                idx = np.zeros(b, dtype=np.int32)
                t0 = time.perf_counter()
                ok = np.asarray(
                    rns.verify_e65537_rns_indexed(sig_d, em_d, idx, urows)
                )
                compile_s = time.perf_counter() - t0
                assert ok.all(), "indexed verify returned false on genuine sigs"
                iters, elapsed = 0, 0.0
                t0 = time.perf_counter()
                while elapsed < (0.5 if FAST else 3.0) or iters < 3:
                    np.asarray(
                        rns.verify_e65537_rns_indexed(sig_d, em_d, idx, urows)
                    )
                    iters += 1
                    elapsed = time.perf_counter() - t0
                dest[str(b)] = {
                    "verifies_per_sec": round(b * iters / elapsed, 1),
                    "first_call_s": round(compile_s, 2),
                }
        finally:
            os.environ.pop("BFTKV_RNS_VERIFY_BACKEND", None)
    out["pallas_status"] = rns.pallas_status()["verify"]
    rates = [v["verifies_per_sec"] for v in out["batch"].values()]
    for mode in modes:
        rates += [
            v["verifies_per_sec"]
            for v in out[f"indexed_{mode}"]["batch"].values()
        ]
    out["best_verifies_per_sec"] = max(rates)
    out["mfu_pct"] = _mfu(out["best_verifies_per_sec"], _rns_verify_flops())
    return out


def bench_kernel_sign(batches=(256, 1024, 4096)) -> dict:
    """Batched RSA-2048 CRT signs/sec through SignerDomain (the RNS
    windowed-modexp path; reference hot loop: crypto_pgp.go:346-371)
    vs single-core host CRT signing.

    Runs BOTH modexp backends on identical operands — forced-XLA at
    every batch, the fused Pallas chain at the two largest — and
    exports ``pallas_status`` so a fallen-back XLA rate can never be
    misattributed to the Pallas kernels (VERDICT r4 item 3).  A
    completed Pallas run writes the proven marker that arms auto mode
    for the cluster sections (rns._use_pallas)."""
    import jax

    from bftkv_tpu.crypto import rsa as rsamod
    from bftkv_tpu.ops import rns

    key = rsamod.generate(2048)
    sd = rsamod.SignerDomain(host_threshold=0)
    out: dict = {"batch": {}, "backend": sd.backend}
    plan = [("xla", sorted(batches))]
    if jax.default_backend() == "tpu":  # interpret mode proves nothing
        # Largest batch only: every batch shape is its own Mosaic
        # compile, and a short tunnel window should spend its minutes
        # measuring, not compiling.
        plan.append(("pallas", sorted(batches)[-1:]))
    for mode, bs in plan:
        dest = (
            out["batch"]
            if mode == "xla"
            else out.setdefault("pallas", {"batch": {}})["batch"]
        )
        os.environ["BFTKV_RNS_POW_BACKEND"] = mode
        try:
            for b in bs:
                items = [(b"sign-%d" % i, key) for i in range(b)]
                t0 = time.perf_counter()
                sigs = sd.sign_batch(items)
                compile_s = time.perf_counter() - t0
                assert sigs[0] == rsamod.sign(b"sign-0", key)
                iters, elapsed = 0, 0.0
                t0 = time.perf_counter()
                while elapsed < (0.5 if FAST else 2.0) or iters < 2:
                    sd.sign_batch(items)
                    iters += 1
                    elapsed = time.perf_counter() - t0
                dest[str(b)] = {
                    "signs_per_sec": round(b * iters / elapsed, 1),
                    "first_call_s": round(compile_s, 2),
                }
        finally:
            os.environ.pop("BFTKV_RNS_POW_BACKEND", None)
    out["pallas_status"] = rns.pallas_status()["pow"]
    t0 = time.perf_counter()
    for i in range(8):
        rsamod.sign(b"host-%d" % i, key)
    host_rate = 8 / (time.perf_counter() - t0)
    rates = [v["signs_per_sec"] for v in out["batch"].values()]
    if "pallas" in out:
        rates += [v["signs_per_sec"] for v in out["pallas"]["batch"].values()]
    best = max(rates)
    out["host_signs_per_sec"] = round(host_rate, 1)
    out["best_signs_per_sec"] = best
    out["speedup_vs_host"] = round(best / host_rate, 2)
    out["mfu_pct"] = _mfu(best, _rns_sign_flops())
    return out


def bench_kernel_ec(batches=(64, 256, 1024, 4096)) -> dict:
    """Batched P-256 scalar-mults/sec, BOTH backends (limb Jacobian vs
    the RNS/MXU field core, ops/ec_rns) vs the host oracle
    (threshold-ECDSA hot loop, reference: crypto/threshold/ecdsa/
    ecdsa.go:31-59; VERDICT r3 item 5)."""
    import secrets

    import jax

    from bftkv_tpu.crypto.ec import P256
    from bftkv_tpu.ops import ec as ec_ops
    from bftkv_tpu.ops import ec_rns

    d = ec_ops.p256()
    out: dict = {"limb": {}, "rns": {}}
    bmax = max(batches)
    pts = [P256.scalar_base_mult(i + 1) for i in range(min(16, bmax))]
    pts = (pts * (bmax // len(pts) + 1))[:bmax]
    ks = [secrets.randbelow(P256.n) for _ in range(bmax)]
    X, Y, Z = d.encode_points(pts)
    K = d.encode_scalars(ks)
    for b in sorted(batches):
        args = [jax.device_put(a[:b]) for a in (X, Y, Z, K)]
        t0 = time.perf_counter()
        jax.block_until_ready(ec_ops.scalar_mult_jac(*args))
        compile_s = time.perf_counter() - t0
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 2.0) or iters < 2:
            jax.block_until_ready(ec_ops.scalar_mult_jac(*args))
            iters += 1
            elapsed = time.perf_counter() - t0
        out["limb"][str(b)] = {
            "scalar_mults_per_sec": round(b * iters / elapsed, 1),
            "first_call_s": round(compile_s, 2),
        }
        # RNS field core on the same operands (device-resident after
        # the first call; encode/decode stay host-side by design, so
        # this rate is end-to-end including codecs).
        eng = ec_rns._engine()
        Xr, Yr, Zr = eng.encode_points(pts[:b])
        nib = ec_rns._nibbles(ks[:b])
        fn = ec_rns._scalar_mult_fn()
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*Xr, *Yr, *Zr, nib)[2][0])
        compile_s = time.perf_counter() - t0
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 2.0) or iters < 2:
            jax.block_until_ready(fn(*Xr, *Yr, *Zr, nib)[2][0])
            iters += 1
            elapsed = time.perf_counter() - t0
        out["rns"][str(b)] = {
            "scalar_mults_per_sec": round(b * iters / elapsed, 1),
            "first_call_s": round(compile_s, 2),
        }
    # Host oracle baseline + correctness spot check of both backends.
    got = ec_ops.scalar_mult_hosts(pts[:8], ks[:8])
    got_rns = ec_rns.scalar_mult_hosts(pts[:8], ks[:8])
    t0 = time.perf_counter()
    want = [P256.scalar_mult(p, k) for p, k in zip(pts[:8], ks[:8])]
    host_rate = 8 / (time.perf_counter() - t0)
    assert got == want, "EC limb kernel/oracle mismatch"
    assert got_rns == want, "EC RNS kernel/oracle mismatch"
    out["host_scalar_mults_per_sec"] = round(host_rate, 1)
    best = max(
        v["scalar_mults_per_sec"]
        for bk in ("limb", "rns")
        for v in out[bk].values()
    )
    out["best_scalar_mults_per_sec"] = best
    out["speedup_vs_host"] = round(best / host_rate, 2)
    return out


# ---------------------------------------------------------------------------
# Cluster benchmarks (the TestManyWrites/TestManyReads analog)
# ---------------------------------------------------------------------------


def _warm_items(count: int) -> list:
    """Synthetic (message, sig, key) triples for bucket warm-up."""
    from bftkv_tpu.crypto import rsa

    key = rsa.generate(2048)
    msg = b"bench-warm"
    sig = rsa.sign(msg, key)
    return [(msg, sig, key.public)] * count


def _warm_dispatchers(clients, bucket_max: int) -> None:
    """Pre-compile every device bucket shape a cluster run can hit:
    verify buckets (floor 256) up to the power-of-two ceiling of
    ``bucket_max`` and sign buckets up to the sign dispatcher's
    ``max_batch``, skipping sizes below the host crossovers."""
    from bftkv_tpu.ops import dispatch

    d = dispatch.get()
    bucket_max = max(256, 1 << (bucket_max - 1).bit_length())
    warm_items = _warm_items(bucket_max)
    bucket = 256
    while bucket <= bucket_max:
        if bucket >= d.verifier.host_threshold:
            d.verifier.verify_batch(warm_items[:bucket])
        bucket *= 2
    ds = dispatch.get_signer()
    sign_items = [(m, clients[0].crypt.signer.key) for m, _s, _k in warm_items]
    bucket = 16
    while bucket <= ds.max_batch:
        if bucket >= ds.signer.host_threshold:
            ds.signer.sign_batch(sign_items[:bucket])
        bucket *= 2


def _hot_loop_metrics(snap: dict) -> dict:
    """Write-path hot-loop series every cluster section reports: the
    verified-signature memo's hit rate and the HTTP connection pool's
    reuse counters (zero on loopback sections, where there is no TCP)."""
    hits = snap.get("verify.cache.hits", 0)
    misses = snap.get("verify.cache.misses", 0)
    return {
        "verify_cache_hits": hits,
        "verify_cache_misses": misses,
        "verify_cache_hit_rate": round(hits / (hits + misses), 4)
        if hits + misses
        else 0.0,
        "conn_reused": snap.get("transport.conn.reused", 0),
        "conn_dialed": snap.get("transport.conn.dialed", 0),
        # Round-collapse series (PR 8): how many writes took the
        # collapsed path, how many fell back, how many in-round
        # timestamp retries the optimistic leases cost, and whether any
        # async tail failed to certify (tail_starved must be 0 on a
        # healthy run).
        "piggyback_ok": snap.get("client.piggyback.ok", 0),
        "piggyback_fallback": snap.get("client.piggyback.fallback", 0),
        "piggyback_retry_t": snap.get("client.piggyback.retry_t", 0),
        "backfills": snap.get("client.write.backfill", 0),
        "tail_starved": snap.get("client.tail.starved", 0),
    }


def _capacity_series(snap: dict, elapsed_s: float = 1.0) -> dict:
    """USE capacity rows + the device-occupancy extract over a
    section's final metrics snapshot (DESIGN.md §20).  compute_member
    with an empty baseline reads counter deltas as section totals —
    the honest single-window reading — so every committed round
    carries where the box queued, not just how fast it went."""
    from bftkv_tpu.obs.capacity import _index, compute_member

    rows = compute_member(_index(snap), {}, max(elapsed_s, 1e-9))
    cap = {
        res: {
            "utilization": round(row["utilization"], 4),
            "saturation": round(row["saturation"], 4),
            "errors": row["errors"],
        }
        for res, row in rows.items()
    }
    occ = {}
    for name, d in (rows.get("dispatch", {}).get("dispatchers") or {}).items():
        for w, o in sorted((d.get("device_occupancy") or {}).items()):
            occ[f"{name}[{w}]"] = round(o, 4)
    return {"capacity": cap, "device_occupancy": occ}


def _round_breakdown(since_cursor: int) -> dict:
    """Per-round write-latency breakdown, derived from the tracer ring
    (the per-process half of the PR 7 stitched-trace plane): p50 of
    every ``phase.*`` span recorded after ``since_cursor``.  Keys are
    the round names — classic ``time``/``sign``/``write`` on the
    fallback path, ``write_sign`` (the combined fan-out the caller
    waits on) and ``ack`` (the async share/back-fill tail) on the
    collapsed path — so the bench record shows exactly where a write's
    wall-clock went."""
    from bftkv_tpu import trace as trmod

    spans = trmod.tracer.export(since_cursor)["spans"]
    byname: dict[str, list[float]] = {}
    for s in spans:
        n = s["name"]
        if n.startswith("phase."):
            byname.setdefault(n[len("phase."):], []).append(s["duration"])
    out = {}
    for name, durs in sorted(byname.items()):
        durs.sort()
        out[name] = round(durs[len(durs) // 2], 4)
    return out


def _phase_budget(since_cursor: int) -> dict:
    """Critical-path attribution over the section's own traces
    (bftkv_tpu/obs/critpath.py): every ``client.write``/``client.read``
    root recorded after ``since_cursor`` is decomposed into exclusive
    per-phase seconds, and the section reports each phase's SHARE of
    total root wall clock — the numbers that enter the committed
    trajectory as the compact sections' 5th element, so "where did this
    round's latency go" is answerable from BENCH_r*.json alone."""
    from bftkv_tpu import trace as trmod
    from bftkv_tpu.obs.critpath import attribute

    spans = trmod.tracer.export(since_cursor)["spans"]
    traces: dict[str, list] = {}
    for s in spans:
        traces.setdefault(s["trace"], []).append(s)
    sums: dict[str, float] = {}
    total = 0.0
    for tspans in traces.values():
        bd = attribute(tspans)
        if bd is None or bd["op"] != "write":
            continue
        total += bd["root_s"]
        for phase, secs in bd["phases"].items():
            sums[phase] = sums.get(phase, 0.0) + secs
    if total <= 0:
        return {}
    return {
        phase: round(secs / total, 4)
        for phase, secs in sorted(sums.items(), key=lambda kv: -kv[1])
        if secs / total >= 0.0005
    }


def _make_cluster(
    n_servers: int, n_rw: int, n_users: int, storage_factory,
    transport: str = "loop", alg: str = "rsa",
):
    """One cluster builder for tests and bench: tests/cluster_utils."""
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(
        n_servers,
        n_users,
        n_rw,
        storage_factory=storage_factory,
        transport=transport,
        alg=alg,
    )
    return cluster.all_servers, cluster.clients


def _zipf_probs(k: int, s: float) -> np.ndarray:
    """Zipf(s) pmf over ranks 1..k (the workload-diversity knob:
    ROADMAP item 5's hot-key shape)."""
    ranks = np.arange(1, k + 1, dtype=np.float64)
    p = ranks**-s
    return p / p.sum()


def _zipf_key(rng, ci: int, probs: np.ndarray) -> bytes:
    """One zipf-skewed key from writer ``ci``'s slice (per-writer: a
    writer identity OWNS a variable under TOFU, so the skew is in key
    popularity, not cross-writer contention)."""
    return b"bench/zipf/%d/%d" % (ci, int(rng.choice(len(probs), p=probs)))


#: Errors that are EXPECTED when zipf-skewed writes race on a hot key
#: (same timestamp picked twice, the quorum let exactly one through;
#: in-flight overwrite colliding with read-repair).  Counted, not
#: raised.  Keys are per-writer (one writer identity OWNS a variable
#: under TOFU — cross-writer hot keys would measure TOFU rejections,
#: not hot-key throughput), so the skew is in key popularity.
def _is_write_conflict(e: Exception) -> bool:
    from bftkv_tpu import errors as er

    return e in (
        er.ERR_INVALID_SIGN_REQUEST,
        er.ERR_EQUIVOCATION,
        er.ERR_BAD_TIMESTAMP,
        er.ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES,
        er.ERR_INSUFFICIENT_NUMBER_OF_VALID_RESPONSES,
        er.ERR_INSUFFICIENT_NUMBER_OF_RESPONSES,
    )


# Open-loop arrival scheduling moved to the workload subsystem (PR 20):
# one implementation, now with backlog accounting at sustained overload
# (latency still measured from the SCHEDULED start; the scheduling lag
# is reported, never silently absorbed).
from bftkv_tpu.workload.driver import OpenLoop as _OpenLoop  # noqa: E402


def _ol_stats(lats: list[float], rate: float, elapsed: float, n: int) -> dict:
    lats = sorted(lats)
    return {
        "offered_rate_per_sec": rate,
        "achieved_rate_per_sec": round(n / elapsed, 2) if elapsed else 0,
        "p50_offered_s": round(lats[len(lats) // 2], 4) if lats else 0,
        "p99_offered_s": round(
            lats[min(len(lats) - 1, int(len(lats) * 0.99))], 4
        )
        if lats
        else 0,
    }


def bench_cluster(
    n_servers: int,
    n_rw: int,
    writers: int,
    writes_per_writer: int,
    *,
    value_size: int = 1024,
    dispatch_batch: int = 256,
    storage: str = "mem",
    read_fraction: float = 0.0,
    transport: str = "loop",
    alg: str = "rsa",
    zipf: float = 0.0,
    open_loop: float = 0.0,
) -> dict:
    """Signed writes/sec (+ optional read mix) through a live in-process
    cluster with the verify dispatcher installed.  ``zipf > 0`` draws
    keys from one shared Zipf(s) hot-key distribution instead of
    per-writer disjoint keys (write races on a hot key are counted as
    ``write_conflicts``)."""
    import tempfile

    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch

    tmp = None
    if storage == "plain":
        from bftkv_tpu.storage.plain import PlainStorage

        tmp = tempfile.TemporaryDirectory(prefix="bftkv-bench-")
        counter = [0]

        def storage_factory():
            counter[0] += 1
            path = os.path.join(tmp.name, f"db{counter[0]}")
            return PlainStorage(path)

    elif storage == "log":
        from bftkv_tpu.storage.logkv import LogStorage

        tmp = tempfile.TemporaryDirectory(prefix="bftkv-bench-")
        counter = [0]

        def storage_factory():
            counter[0] += 1
            path = os.path.join(tmp.name, f"db{counter[0]}")
            # The daemon's durable default: every commit hits an fsync
            # barrier (group-committed across concurrent writers).
            return LogStorage(path)

    else:
        from bftkv_tpu.storage.memkv import MemStorage

        storage_factory = MemStorage

    t_setup = time.perf_counter()
    servers, clients = _make_cluster(
        n_servers, n_rw, writers, storage_factory, transport, alg
    )
    setup_s = time.perf_counter() - t_setup

    try:
        metrics.reset()
        dispatch.install(dispatch.VerifyDispatcher(max_batch=dispatch_batch))
        dispatch.install_signer(
            dispatch.SignDispatcher(max_batch=max(dispatch_batch // 2, 64))
        )
        value = os.urandom(value_size)
        # Warm the protocol path and the device bucket shapes the run can hit
        # (pays XLA compilation outside the timed region). A write burst at n
        # replicas produces ~n·suff verifies, padded to power-of-two buckets.
        clients[0].write(b"bench/warmup", value)
        clients[0].read(b"bench/warmup")
        # Establish every writer client's transport sessions outside
        # the timed region: a cold client's first fan-out pays one
        # bootstrap envelope (RSA sign + per-recipient OAEP) per peer
        # group, which is connection setup, not steady-state write
        # cost.  One write touches all three phase quorums.
        for ci, c in enumerate(clients[1:writers]):
            c.write(b"bench/warmup/%d" % ci, value)
        # The dispatcher chunks flushes at max_batch, so the padded device
        # shape never exceeds the next power of two above dispatch_batch —
        # warming larger buckets would compile kernels the run cannot hit.
        _warm_dispatchers(clients, dispatch_batch)
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()  # warmup tails stay out of the timed region
        metrics.reset()
        from bftkv_tpu import trace as _trmod

        trace_cur0 = _trmod.tracer.cursor()

        errors: list = []
        reads_by_thread = [0] * writers
        conflicts_by_thread = [0] * writers
        ol = _OpenLoop(open_loop, writers) if open_loop > 0 else None
        ol_lats: list[list[float]] = [[] for _ in range(writers)]
        zipf_probs = (
            _zipf_probs(max(writers * writes_per_writer, 16), zipf)
            if zipf > 0
            else None
        )

        def run(ci: int, client) -> None:
            rng = np.random.default_rng(ci)
            try:
                reads_per_write = (
                    read_fraction / (1 - read_fraction) if read_fraction else 0.0
                )
                for i in range(writes_per_writer):
                    if zipf_probs is None:
                        var = b"bench/%d/%d" % (ci, i)
                    else:
                        var = _zipf_key(rng, ci, zipf_probs)
                    due = ol.wait(ci, i) if ol is not None else None
                    try:
                        client.write(var, value)
                        if due is not None:
                            ol_lats[ci].append(time.perf_counter() - due)
                    except Exception as e:
                        if zipf_probs is None or not _is_write_conflict(e):
                            raise
                        conflicts_by_thread[ci] += 1
                    k = int(reads_per_write)
                    if rng.random() < reads_per_write - k:
                        k += 1
                    for _ in range(k):
                        if zipf_probs is None:
                            rv = b"bench/%d/%d" % (ci, rng.integers(0, i + 1))
                        else:
                            rv = _zipf_key(rng, ci, zipf_probs)
                        try:
                            client.read(rv)
                        except Exception as e:
                            # Zipf mode: a hot key racing its own
                            # overwrite can fail transiently with an
                            # interned protocol error; anything else
                            # (and anything in uniform mode) is a real
                            # failure.  Failed reads are NOT counted.
                            from bftkv_tpu.errors import Error

                            if zipf_probs is None or not isinstance(
                                e, Error
                            ):
                                raise
                        else:
                            reads_by_thread[ci] += 1
            except Exception as e:  # surfaced below; bench must not hang
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(ci, c), daemon=True)
            for ci, c in enumerate(clients[:writers])
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]
        # Quiesce the async write tails before the snapshot: elapsed
        # (and writes/s) measure time-to-commit — the client contract —
        # while the back-fill/starvation counters below must reflect a
        # settled cluster, not a race with the snapshot.
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()

        total_writes = writers * writes_per_writer - sum(conflicts_by_thread)
        total_reads = sum(reads_by_thread)
        # Correctness spot check before reporting a rate.  Zipf runs
        # use a fresh sentinel key — any hot key may have lost every
        # race on this writer's attempts.
        if zipf_probs is None:
            got = clients[0].read(b"bench/0/%d" % (writes_per_writer - 1))
        else:
            clients[0].write(b"bench/zipf-check", value)
            got = clients[0].read(b"bench/zipf-check")
        assert got == value, "read-back mismatch"

        snap = metrics.snapshot()
        flushes = snap.get("dispatch.flushes", 0)
        res = {
            "replicas": n_servers,
            "rw_nodes": n_rw,
            "writers": writers,
            "writes": total_writes,
            "reads": total_reads,
            "value_bytes": value_size,
            "storage": storage,
            "transport": transport,
            "writes_per_sec": round(total_writes / elapsed, 2),
            "ops_per_sec": round((total_writes + total_reads) / elapsed, 2),
            "write_p50_s": round(snap.get("client.write.latency.p50", 0), 4),
            "write_p99_s": round(snap.get("client.write.latency.p99", 0), 4),
            "read_p50_s": round(snap.get("client.read.latency.p50", 0), 4),
            "dispatch_flushes": flushes,
            "dispatch_verifies": snap.get("dispatch.verifies", 0),
            "dispatch_batch_mean": round(
                snap.get("dispatch.verifies", 0) / flushes, 2
            )
            if flushes
            else 0,
            "dispatch_batch_p50": snap.get("dispatch.batch.p50", 0),
            "verifies_host": snap.get("verify.host", 0),
            "verifies_device": snap.get("verify.device", 0),
            "signs_host": snap.get("sign.host", 0),
            "signs_device": snap.get("sign.device", 0),
            "sign_batch_p50": snap.get("signdispatch.batch.p50", 0),
            "rns_pallas": _pallas_status(),
            "setup_s": round(setup_s, 1),
        }
        if zipf > 0:
            res["zipf_s"] = zipf
            res["write_conflicts"] = sum(conflicts_by_thread)
        if ol is not None:
            # Latency AT a target offered load, not throughput at
            # saturation: p50/p99 measured from each op's scheduled
            # arrival (queueing delay included).
            res["open_loop"] = _ol_stats(
                [x for l in ol_lats for x in l],
                open_loop,
                elapsed,
                total_writes,
            )
        res["round_p50_s"] = _round_breakdown(trace_cur0)
        res["phase_budget"] = _phase_budget(trace_cur0)
        res.update(_hot_loop_metrics(snap))
        res.update(_capacity_series(snap, elapsed))
        return res
    finally:
        # One failing section must not leak dispatchers, server
        # threads, or temp dirs into the next section.
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()
            closer = getattr(s.storage, "close", None)
            if closer is not None:
                closer()
        if tmp is not None:
            tmp.cleanup()


def _fill_sweep(cap: int) -> dict:
    """Raw-engine fill scaling: write p50 (µs) at 10k/100k/1M resident
    keys (points above ``cap`` skipped), log engine vs the plain-file
    control, both with fsync off so the numbers isolate index+append
    cost from disk flush latency.  The acceptance bound rides the log
    row: p50 at the largest point within 1.3x of the 10k point."""
    import statistics
    import tempfile

    from bftkv_tpu.storage.logkv import LogStorage
    from bftkv_tpu.storage.plain import PlainStorage

    points = [p for p in (10_000, 100_000, 1_000_000) if p <= cap]
    if not points:
        points = [cap]
    payload = b"p" * 64
    out: dict = {"keyspace_points": points}
    for engine in ("log", "plain"):
        row = {}
        with tempfile.TemporaryDirectory(prefix="bftkv-fill-") as d:
            filled = 0
            if engine == "log":
                s = LogStorage(os.path.join(d, "db"), fsync=False)
            else:
                s = PlainStorage(os.path.join(d, "db"), fsync=False)
            for n in points:
                while filled < n:
                    s.write(b"fill-%09d" % filled, 1, payload)
                    filled += 1
                lat = []
                for i in range(2000):
                    t0 = time.perf_counter()
                    s.write(b"probe-%d-%09d" % (n, i), 1, payload)
                    lat.append(time.perf_counter() - t0)
                row["p50_us_at_%d" % n] = round(
                    statistics.median(lat) * 1e6, 2
                )
            closer = getattr(s, "close", None)
            if closer is not None:
                closer()
        out[engine] = row
    log_row = out["log"]
    first, last = points[0], points[-1]
    if last > first:
        out["log_p50_ratio_%dx" % (last // first)] = round(
            log_row["p50_us_at_%d" % last]
            / max(log_row["p50_us_at_%d" % first], 1e-9),
            3,
        )
    return out


def bench_cluster_log(
    writers: int,
    writes_per_writer: int,
    *,
    keyspace: int,
    zipf: float = 0.0,
    open_loop: float = 0.0,
) -> dict:
    """The §19 log engine under the cluster_4 fleet (durable default:
    group-committed fsync per commit) plus the raw-engine keyspace
    fill sweep the issue's O(changed)/flat-p50 claims are judged on."""
    res = bench_cluster(
        4, 4, writers, writes_per_writer, storage="log",
        dispatch_batch=256, zipf=zipf, open_loop=open_loop,
    )
    res["fill_sweep"] = _fill_sweep(keyspace)
    return res


def bench_cluster_gray(
    n_servers: int = 4,
    n_rw: int = 4,
    writers: int = 8,
    writes_per_writer: int = 10,
    *,
    value_size: int = 512,
    delay_s: float = 0.35,
) -> dict:
    """Gray-failure section (DESIGN.md §13): one clique member of a
    4-node loopback cluster delayed ``delay_s`` per inbound post (a
    slow-but-ALIVE peer, ~5-10x a loopback p99) while writers run —
    hedging + health-aware staging ON vs OFF, plus the recovery
    plane's repair counters.  The headline rate is the hedged run,
    and ``gray_slowdown_hedged`` is GATED by tools/bench_compare.py
    (absolute ≤2x bound) on every committed round."""
    from bftkv_tpu import transport as tptr
    from bftkv_tpu.faults import failpoint as fp
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage
    from bftkv_tpu.sync import SyncDaemon

    servers, clients = _make_cluster(n_servers, n_rw, writers, MemStorage)
    hedge_env = os.environ.get("BFTKV_HEDGE")
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        value = os.urandom(value_size)
        for ci, c in enumerate(clients[:writers]):
            c.write(b"gray/warm/%d" % ci, value)
        for c in clients[:writers]:
            c.drain_tails()
        tptr.peer_latency.reset()

        def run_phase(tag: str) -> tuple[float, float]:
            """(p50 seconds, writes/s) over one threaded write burst."""
            lats: list[list[float]] = [[] for _ in range(writers)]
            errors: list = []

            def run(ci: int, client) -> None:
                try:
                    for i in range(writes_per_writer):
                        var = f"gray/{tag}/{ci}/{i}".encode()
                        t0 = time.perf_counter()
                        client.write(var, value)
                        lats[ci].append(time.perf_counter() - t0)
                except Exception as e:
                    errors.append(e)

            threads = [
                threading.Thread(target=run, args=(ci, c), daemon=True)
                for ci, c in enumerate(clients[:writers])
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            if errors:
                raise errors[0]
            for c in clients[:writers]:
                c.drain_tails()
            flat = sorted(x for l in lats for x in l)
            return flat[len(flat) // 2], len(flat) / elapsed

        # Fault-free floor (also seeds the latency tracker).
        p50_free, _rate_free = run_phase("free")

        # The gray member: the first clique seat of the owner quorum —
        # guaranteed inside the minimal interleaved WRITE_SIGN prefix.
        from bftkv_tpu import quorum as qmod

        gray_node = qmod.choose_quorum_for(
            clients[0].qs, b"gray/x", qmod.AUTH
        ).nodes()[0]
        target = fp.link_of(gray_node.address)

        metrics.reset()
        os.environ["BFTKV_HEDGE"] = "on"
        fp.arm(17)
        fp.registry.add(
            "transport.send", "delay", match={"dst": target},
            seconds=delay_s, rule_id=f"slow_node:{target}",
        )
        try:
            p50_on, rate_on = run_phase("hedged")
        finally:
            fp.disarm()
        snap_on = metrics.snapshot()
        hedge_sent = sum(
            v for k, v in snap_on.items()
            if k.startswith("transport.hedge.sent")
        )
        hedge_wasted = sum(
            v for k, v in snap_on.items()
            if k.startswith("transport.hedge.wasted")
        )

        os.environ["BFTKV_HEDGE"] = "off"
        tptr.peer_latency.reset()  # no carried gray flags for the control
        fp.arm(17)
        fp.registry.add(
            "transport.send", "delay", match={"dst": target},
            seconds=delay_s, rule_id=f"slow_node:{target}",
        )
        try:
            p50_off, _rate_off = run_phase("unhedged")
        finally:
            fp.disarm()

        # Recovery plane: one clique replica's repair pass certifies
        # the commit-pending residue the collapsed writes leave on the
        # sign plane (the client back-fill covers the write plane).
        metrics.reset()
        os.environ.pop("BFTKV_HEDGE", None)
        repair_srv = servers[0]
        SyncDaemon(repair_srv, interval=999).repair_once()
        snap_rep = metrics.snapshot()

        return {
            "replicas": n_servers,
            "rw_nodes": n_rw,
            "writers": writers,
            "writes": writers * writes_per_writer,
            "gray_target": target,
            "gray_delay_s": delay_s,
            "writes_per_sec": round(rate_on, 2),
            "write_p50_s": round(p50_on, 4),
            "write_p50_hedge_off_s": round(p50_off, 4),
            "write_p50_fault_free_s": round(p50_free, 4),
            "gray_slowdown_hedged": round(p50_on / p50_free, 2)
            if p50_free
            else 0.0,
            "gray_slowdown_unhedged": round(p50_off / p50_free, 2)
            if p50_free
            else 0.0,
            "hedge_sent": hedge_sent,
            "hedge_wasted": hedge_wasted,
            "repair_certified": snap_rep.get("sync.repair.certified", 0),
            "repair_demoted": snap_rep.get("sync.repair.demoted", 0),
            **_capacity_series(snap_rep),
        }
    finally:
        if hedge_env is None:
            os.environ.pop("BFTKV_HEDGE", None)
        else:
            os.environ["BFTKV_HEDGE"] = hedge_env
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()


def bench_cluster_gateway(
    n_servers: int = 4,
    n_rw: int = 4,
    n_gateways: int = 2,
    readers: int = 8,
    # 120 reads/reader (was 40): like cluster_shards, the 320-read
    # burst finished in ~2 s and sampled 0.8-2.9k reads/s across
    # same-code runs on the 1-core driver box; 3x the burst tightens
    # the committed number without changing the metric.
    reads_per_reader: int = 120,
    writers: int = 4,
    writes_per_writer: int = 5,
    *,
    value_size: int = 512,
    hot_keys: int = 16,
    bits: int = 1024,
    open_loop: float = 0.0,
) -> dict:
    """Edge gateway tier proof (ROADMAP item 1, DESIGN.md §14): the
    same reader pool drives a hot keyset DIRECT (full quorum fan-out
    per read) and then THROUGH N stacked gateways (one front-door post;
    certified read-through cache) — the headline is the gateway
    aggregate read rate with its speedup and steady-state hit rate.
    Writes run both ways too: concurrent front-door writes coalesce
    into shared rounds and must be no worse than the direct path.
    ``open_loop > 0`` additionally measures gateway read latency at
    that offered load (ops/s) instead of at saturation."""
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage
    from tests.cluster_utils import start_cluster

    t_setup = time.perf_counter()
    cluster = start_cluster(
        n_servers,
        max(readers, writers),
        n_rw,
        bits=bits,
        storage_factory=MemStorage,
        n_gateways=n_gateways,
    )
    setup_s = time.perf_counter() - t_setup
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        value = os.urandom(value_size)
        clients = cluster.clients
        gw_clients = [
            cluster.gateway_client(i) for i in range(readers)
        ]
        keys = [b"gwbench/hot/%d" % i for i in range(hot_keys)]
        # Seed the hot keyset through the front door (the gateway tier
        # owns it under TOFU) and warm every reader's sessions + the
        # verify memo on both paths.
        for k in keys:
            gw_clients[0].write(k, value)
        for ci in range(readers):
            clients[ci].read(keys[ci % hot_keys])
            gw_clients[ci].read(keys[ci % hot_keys])
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()
        for gw in cluster.gateways:
            gw.client.drain_tails()

        def read_phase(fn) -> tuple[float, float, list[float]]:
            """(elapsed, reads/s, per-op latencies) over the pool."""
            errors: list = []
            lats: list[list[float]] = [[] for _ in range(readers)]
            ol = (
                _OpenLoop(open_loop, readers) if open_loop > 0 else None
            )

            def run(ci: int) -> None:
                rng = np.random.default_rng(ci)
                try:
                    for i in range(reads_per_reader):
                        k = keys[int(rng.integers(0, hot_keys))]
                        due = (
                            ol.wait(ci, i) if ol is not None else
                            time.perf_counter()
                        )
                        got = fn(ci, k)
                        lats[ci].append(time.perf_counter() - due)
                        assert got == value, "read-back mismatch"
                except Exception as e:
                    errors.append(e)

            threads = [
                threading.Thread(target=run, args=(ci,), daemon=True)
                for ci in range(readers)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            if errors:
                raise errors[0]
            n = readers * reads_per_reader
            return elapsed, n / elapsed, sorted(
                x for l in lats for x in l
            )

        # Direct: the classic quorum read every client pays today.
        el_d, direct_rate, lats_d = read_phase(
            lambda ci, k: clients[ci].read(k)
        )
        # Gateway: one front-door post, served from the certified
        # cache (client-side re-verification stays ON — that cost is
        # part of the honest number).
        metrics.reset()
        el_g, gw_rate, lats_g = read_phase(
            lambda ci, k: gw_clients[ci].read(k)
        )
        snap = metrics.snapshot()
        hits = snap.get("gateway.cache.hits", 0)
        misses = snap.get("gateway.cache.misses", 0)
        hit_rate = hits / (hits + misses) if hits + misses else 0.0

        # Writes, both ways, on disjoint keyspaces (TOFU owns a
        # variable per writing identity).  Concurrent front-door
        # writers meet in the coalescer, so distinct-variable bursts
        # batch per shard (write_many) — same-variable collapse is
        # covered by tests/test_gateway.py; here the apples-to-apples
        # workload is distinct keys on both paths.
        def write_phase(fn, tag: bytes) -> float:
            errors: list = []

            def run(ci: int) -> None:
                try:
                    for i in range(writes_per_writer):
                        fn(ci, b"gwbench/w/%s/%d/%d" % (tag, ci, i))
                except Exception as e:
                    errors.append(e)

            threads = [
                threading.Thread(target=run, args=(ci,), daemon=True)
                for ci in range(writers)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            if errors:
                raise errors[0]
            return writers * writes_per_writer / elapsed

        direct_wrate = write_phase(
            lambda ci, k: clients[ci].write(k, value), b"direct"
        )
        w0 = metrics.snapshot()
        gw_wrate = write_phase(
            lambda ci, k: gw_clients[ci].write(k, value), b"gw"
        )
        w1 = metrics.snapshot()
        for c in clients[:writers]:
            c.drain_tails()
        for gw in cluster.gateways:
            gw.client.drain_tails()

        res = {
            # Headline FIRST: the compact record keys off the first
            # *_per_sec field.
            "reads_per_sec": round(gw_rate, 2),
            "direct_reads_per_sec": round(direct_rate, 2),
            "speedup_vs_direct": round(gw_rate / direct_rate, 2)
            if direct_rate
            else 0.0,
            "cache_hit_rate": round(hit_rate, 4),
            "cache_hits": hits,
            "cache_misses": misses,
            "read_p50_s": round(lats_g[len(lats_g) // 2], 5),
            "direct_read_p50_s": round(lats_d[len(lats_d) // 2], 5),
            "writes_per_sec_gateway": round(gw_wrate, 2),
            "writes_per_sec_direct": round(direct_wrate, 2),
            "write_ratio_vs_direct": round(gw_wrate / direct_wrate, 2)
            if direct_wrate
            else 0.0,
            "writes_coalesced": w1.get("gateway.write.coalesced", 0)
            - w0.get("gateway.write.coalesced", 0),
            "write_batched_rounds": w1.get(
                "gateway.write.batched_rounds", 0
            )
            - w0.get("gateway.write.batched_rounds", 0),
            "gateways": n_gateways,
            "replicas": n_servers + n_rw,
            "readers": readers,
            "reads": readers * reads_per_reader,
            "writers": writers,
            "value_bytes": value_size,
            "bits": bits,
            "shed": sum(
                v
                for k, v in w1.items()
                if k.startswith("gateway.shed")
            ),
            "verify_fail": w1.get("gateway.cache.verify_fail", 0),
            "setup_s": round(setup_s, 1),
        }
        res.update(_capacity_series(w1))
        if open_loop > 0:
            res["open_loop"] = _ol_stats(
                lats_g, open_loop, el_g, readers * reads_per_reader
            )
        return res
    finally:
        dispatch.uninstall_all()
        cluster.stop()


def bench_cluster_wan(
    n_servers: int = 4,
    n_rw: int = 4,
    n_regions: int = 3,
    readers: int = 4,
    reads_per_reader: int = 25,
    writers: int = 4,
    writes_per_writer: int = 6,
    *,
    value_size: int = 512,
    hot_keys: int = 8,
    bits: int = 1024,
    rtt_spec: str = "wan3",
) -> dict:
    """Multi-region WAN plane proof (DESIGN.md §21): the cluster_4
    fleet labeled into N regions under a deterministic RTT matrix —
    the failpoint link-delay program that treats geography as an
    environment, not a fault.  Three claims, measured:

    - a same-region gateway read of a hot key is served at CACHE
      latency — the region-local read tier never pays a WAN round
      trip (client, gateway and the cached copy all sit in r0);
    - the direct write p50 sits within ~1 nearest-cross-region RTT of
      the loopback floor — the 2f+1 threshold forces exactly one
      cross-region hop, and locality-aware staging keeps it the
      NEAREST one instead of a far-region fan-out;
    - a WHOLE region loses its WAN egress (region_partition) with
      ZERO failed writes, while the fleet collector names the outage
      as a ``region_down`` anomaly carrying the negative region-level
      budget.

    The result carries a ``wan:<spec>`` marker that lands in the
    backend label, so bench_compare files WAN rounds as their own
    backend class — reported, never gated against loopback numbers."""
    from bftkv_tpu import regions as rg
    from bftkv_tpu.faults import failpoint as fp
    from bftkv_tpu.faults.nemesis import _ChaosProbeSource
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.obs import FleetCollector, LocalSource
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.regions.topology import install_matrix
    from bftkv_tpu.storage.memkv import MemStorage
    from tests.cluster_utils import start_cluster

    t_setup = time.perf_counter()
    cluster = start_cluster(
        n_servers,
        max(readers, writers),
        n_rw,
        bits=bits,
        storage_factory=MemStorage,
        n_gateways=1,
        n_regions=n_regions,
    )
    setup_s = time.perf_counter() - t_setup
    reg = fp.registry
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        value = os.urandom(value_size)
        clients = cluster.clients
        gw_clients = [cluster.gateway_client(i) for i in range(readers)]
        keys = [b"wanbench/hot/%d" % i for i in range(hot_keys)]
        # Seed the hot keyset through the front door and warm every
        # reader's sessions + the verify memo on the cached path.
        for k in keys:
            gw_clients[0].write(k, value)
        for ci in range(readers):
            gw_clients[ci].read(keys[ci % hot_keys])
        # Warm the DIRECT write path per writer too (sessions + sign/
        # verify memos): the loopback floor below must measure steady
        # state, not first-write compilation.
        for ci in range(writers):
            clients[ci].write(b"wanbench/warm/%d" % ci, value)
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()
        for gw in cluster.gateways:
            gw.client.drain_tails()

        def write_phase(
            tag: bytes, idxs: list | None = None
        ) -> tuple[float, float, int]:
            """(p50_s, writes/s, failed) over the writer pool."""
            if idxs is None:
                idxs = list(range(writers))
            lats: dict = {ci: [] for ci in idxs}
            failed = {ci: 0 for ci in idxs}

            def run(ci: int) -> None:
                for i in range(writes_per_writer):
                    k = b"wanbench/w/%s/%d/%d" % (tag, ci, i)
                    t0 = time.perf_counter()
                    try:
                        clients[ci].write(k, value)
                    except Exception:
                        failed[ci] += 1
                        continue
                    lats[ci].append(time.perf_counter() - t0)

            threads = [
                threading.Thread(target=run, args=(ci,), daemon=True)
                for ci in idxs
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            flat = sorted(x for l in lats.values() for x in l)
            p50 = flat[len(flat) // 2] if flat else 0.0
            return p50, len(flat) / elapsed, sum(failed.values())

        # Readers round-robin across regions like every other plane
        # (u01→r0, u02→r1, …), and the gateway lives in ONE of them —
        # the §21 claim is about the SAME-REGION readers, so the read
        # phase keys its latencies by the reader's region.
        gw_region = cluster.universe.gateways[0].region
        same_idx = [
            ci
            for ci in range(readers)
            if cluster.universe.users[ci].region == gw_region
        ]

        def _p50(xs: list) -> float:
            return sorted(xs)[len(xs) // 2] if xs else 0.0

        def read_phase() -> tuple[float, float, float]:
            """(same-region p50, cross-region p50, reads/s): hot-key
            reads through the gateway, split by reader locality."""
            lats: list[list[float]] = [[] for _ in range(readers)]
            errors: list = []

            def run(ci: int) -> None:
                rng = np.random.default_rng(ci)
                try:
                    for _ in range(reads_per_reader):
                        k = keys[int(rng.integers(0, hot_keys))]
                        t0 = time.perf_counter()
                        got = gw_clients[ci].read(k)
                        lats[ci].append(time.perf_counter() - t0)
                        assert got == value, "read-back mismatch"
                except Exception as e:
                    errors.append(e)

            threads = [
                threading.Thread(target=run, args=(ci,), daemon=True)
                for ci in range(readers)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            if errors:
                raise errors[0]
            same = [x for ci in same_idx for x in lats[ci]]
            cross = [
                x
                for ci in range(readers)
                if ci not in same_idx
                for x in lats[ci]
            ]
            n = sum(len(l) for l in lats)
            return _p50(same), _p50(cross), n / elapsed

        # Phase 1 — loopback floor: regions labeled, no matrix armed
        # (failpoints disarmed, so the hook sites cost one bool test).
        floor_w_p50, _floor_wrate, floor_w_fail = write_phase(b"floor")
        floor_r_p50, _floor_cross, _ = read_phase()

        # Phase 2 — the same fleet under the WAN matrix.  arm() clears
        # all rules, so the matrix installs AFTER it.
        fp.arm(17)
        matrix, _program = install_matrix(reg, rtt_spec)
        wan_w_p50, wan_wrate, wan_w_fail = write_phase(b"wan")
        metrics.reset()
        wan_r_p50, wan_r_cross_p50, wan_rrate = read_phase()
        snap = metrics.snapshot()
        hits = snap.get("gateway.cache.hits", 0)
        misses = snap.get("gateway.cache.misses", 0)

        # Phase 3 — whole-region outage.  Cut the FARTHEST region that
        # hosts neither the gateway nor the seed writer: every link
        # crossing its boundary drops while the WAN delays stay armed.
        # Writers living INSIDE the cut region sit this phase out —
        # they are part of the outage; the zero-failed-writes bar is
        # for everyone else.  The collector watches through probes
        # that observe armed drop rules side-effect-free
        # (nemesis._ChaosProbeSource).
        barred = {gw_region, cluster.universe.users[0].region}
        candidates = [
            r for r in sorted(rg.regionmap.regions()) if r not in barred
        ]
        cut = candidates[-1]
        part_writers = [
            ci
            for ci in range(writers)
            if cluster.universe.users[ci].region != cut
        ]
        idents = cluster.universe.servers + cluster.universe.storage_nodes
        sources = [
            _ChaosProbeSource(
                LocalSource(ident.name, lambda s=srv: s), reg
            )
            for ident, srv in zip(idents, cluster.all_servers)
        ]
        for gw in cluster.gateways:
            sources.append(
                _ChaosProbeSource(
                    LocalSource(gw.self_node.name, lambda g=gw: g), reg
                )
            )
        coll = FleetCollector(sources)
        coll.scrape_once()  # baseline: every member up, seats on file

        def crosses(ctx: dict, _r=cut) -> bool:
            return (rg.region_of(ctx.get("src") or "") == _r) != (
                rg.region_of(ctx.get("dst") or "") == _r
            )

        rule = reg.add(
            "transport.send",
            "drop",
            match=crosses,
            rule_id=f"region_partition:{cut}",
        )
        part_w_p50, part_wrate, part_w_fail = write_phase(
            b"part", part_writers
        )
        detected = False
        for attempt in range(24):
            if attempt:
                time.sleep(0.25)
            coll.scrape_once()
            if any(
                a["kind"] == "region_down" and a["source"] == cut
                for a in coll.anomalies(0)
            ):
                detected = True
                break
            regs_doc = coll.health().get("regions") or {}
            row = (regs_doc.get("rows") or {}).get(cut)
            if row and row.get("dark"):
                detected = True
                break
        reg.remove(rule)  # heal: WAN delays stay, the cut lifts
        for c in clients[:writers]:
            c.drain_tails()
        for gw in cluster.gateways:
            gw.client.drain_tails()

        near_rtt = matrix.min_cross_s()
        return {
            # Headline FIRST: the compact record keys off the first
            # *_per_sec field.  This is the WAN write rate — the whole
            # point of the section is what geography costs.
            "writes_per_sec": round(wan_wrate, 2),
            "write_p50_s": round(wan_w_p50, 5),
            "write_p50_floor_s": round(floor_w_p50, 5),
            "write_rtt_overhead_s": round(wan_w_p50 - floor_w_p50, 5),
            "nearest_cross_rtt_s": round(near_rtt, 5),
            # The acceptance claim, self-judged: one nearest-cross RTT
            # (plus scheduling slack) over the floor, not a far fan-out.
            "write_within_one_rtt": bool(
                wan_w_p50 - floor_w_p50 <= 1.5 * near_rtt + 0.05
            ),
            "gw_reads_per_sec": round(wan_rrate, 2),
            # Same-region readers only — the §21 cache-latency claim.
            "gw_read_p50_s": round(wan_r_p50, 6),
            "gw_read_p50_floor_s": round(floor_r_p50, 6),
            # Cross-region readers pay ~1 RTT to the front door —
            # reported for the geo story, not part of the claim.
            "gw_read_cross_p50_s": round(wan_r_cross_p50, 6),
            "read_at_cache_latency": bool(
                wan_r_p50 <= max(5.0 * floor_r_p50, 0.01)
            ),
            "cache_hits": hits,
            "cache_misses": misses,
            "write_failures": floor_w_fail + wan_w_fail,
            "partition_region": cut,
            "partition_failed_writes": part_w_fail,
            "partition_writes_per_sec": round(part_wrate, 2),
            "partition_write_p50_s": round(part_w_p50, 5),
            "partition_region_down_detected": detected,
            "rtt_matrix": matrix.describe(),
            "regions": n_regions,
            "replicas": n_servers + n_rw,
            "writers": writers,
            "readers": readers,
            "bits": bits,
            "setup_s": round(setup_s, 1),
            # Lands in the backend label ("cpu/8+wan:wan3") so
            # bench_compare files WAN rounds as their own class.
            "wan_marker": f"wan:{rtt_spec}",
        }
    finally:
        fp.disarm()
        dispatch.uninstall_all()
        cluster.stop()


def bench_cluster_batch(
    n_servers: int,
    n_rw: int,
    writers: int,
    batch: int,
    rounds: int,
    *,
    value_size: int = 1024,
    dispatch_batch: int = 4096,
    transport: str = "loop",
    read_fraction: float = 0.0,
    alg: str = "rsa",
) -> dict:
    """Signed writes/sec through the batched pipeline (``write_many``):
    B independent writes per protocol round, server-side crypto in
    shared device batches.  ``read_fraction`` adds ``read_many`` rounds
    for the BASELINE config-4 mix.  This is the TPU-native throughput
    shape — the per-write path (``bench_cluster``) measures latency."""
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage

    t_setup = time.perf_counter()
    servers, clients = _make_cluster(
        n_servers, n_rw, writers, MemStorage, transport, alg
    )
    setup_s = time.perf_counter() - t_setup
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=dispatch_batch))
        dispatch.install_signer(
            dispatch.SignDispatcher(max_batch=dispatch_batch)
        )
        value = os.urandom(value_size)
        # Warm every device bucket shape the run can hit (pays XLA
        # compilation outside the timed region; the persistent compile
        # cache makes repeat runs cheap).
        _warm_dispatchers(clients, dispatch_batch)
        clients[0].write_many(
            [(b"bench/warm/%d" % i, value) for i in range(min(batch, 64))]
        )
        metrics.reset()

        errors: list = []
        reads_done = [0] * writers
        reads_per_round = (
            int(batch * read_fraction / (1 - read_fraction))
            if read_fraction
            else 0
        )

        def run(ci: int, client) -> None:
            rng = np.random.default_rng(ci)
            try:
                for r in range(rounds):
                    items = [
                        (b"bench/%d/%d/%d" % (ci, r, i), value)
                        for i in range(batch)
                    ]
                    errs = client.write_many(items)
                    bad = [e for e in errs if e is not None]
                    if bad:
                        raise bad[0]
                    for off in range(0, reads_per_round, batch):
                        nread = min(batch, reads_per_round - off)
                        got = client.read_many(
                            [
                                b"bench/%d/%d/%d"
                                % (ci, r, rng.integers(0, batch))
                                for _ in range(nread)
                            ]
                        )
                        # Every bench key was just written, so anything
                        # but value bytes (None included) is a failure;
                        # errors are interned Error classes/instances.
                        bad = [g for g in got if not isinstance(g, bytes)]
                        if bad:
                            raise AssertionError(f"bench read failed: {bad[0]!r}")
                        reads_done[ci] += nread
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(ci, c), daemon=True)
            for ci, c in enumerate(clients[:writers])
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]

        total = writers * rounds * batch
        total_reads = sum(reads_done)
        got = clients[0].read(b"bench/0/0/%d" % (batch - 1))
        assert got == value, "read-back mismatch"

        snap = metrics.snapshot()
        flushes = snap.get("dispatch.flushes", 0)
        return {
            **_hot_loop_metrics(snap),
            **_capacity_series(snap, elapsed),
            "replicas": n_servers,
            "rw_nodes": n_rw,
            "writers": writers,
            "batch": batch,
            "rounds": rounds,
            "writes": total,
            "reads": total_reads,
            "ops_per_sec": round((total + total_reads) / elapsed, 2),
            "value_bytes": value_size,
            "transport": transport,
            "writes_per_sec": round(total / elapsed, 2),
            "batch_latency_p50_s": round(
                snap.get("client.write_many.latency.p50", 0), 4
            ),
            # A production replica has its own TPU; the in-process bench
            # time-slices one chip across all n. Per-replica handler
            # capacity is the deployment-shaped number.
            "replica_sign_handler_items_per_sec": round(
                batch / h, 1
            )
            if (h := snap.get("server.batch_sign.handler.p50", 0))
            else 0,
            "replica_write_handler_items_per_sec": round(
                batch / h, 1
            )
            if (h := snap.get("server.batch_write.handler.p50", 0))
            else 0,
            "dispatch_flushes": flushes,
            "dispatch_verifies": snap.get("dispatch.verifies", 0),
            "dispatch_batch_p50": snap.get("dispatch.batch.p50", 0),
            "verifies_host": snap.get("verify.host", 0),
            "verifies_device": snap.get("verify.device", 0),
            "signs_host": snap.get("sign.host", 0),
            "signs_device": snap.get("sign.device", 0),
            "sign_batch_p50": snap.get("signdispatch.batch.p50", 0),
            "rns_pallas": _pallas_status(),
            "setup_s": round(setup_s, 1),
        }
    finally:
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()


def bench_cluster_shards(
    total_servers: int = 16,
    total_rw: int = 16,
    writers: int = 8,
    writes_per_writer: int = 18,
    shard_counts: tuple = (1, 2, 4),
    *,
    value_size: int = 512,
    bits: int = 1024,
    zipf: float = 0.0,
    rate: float | None = None,
) -> dict:
    """Horizontal keyspace sharding proof (ROADMAP item 2): the SAME
    replica budget (``total_servers`` quorum servers + ``total_rw``
    storage nodes) and the SAME client count, re-partitioned into
    1 / 2 / 4 hash-routed shards.  One 16-clique pays ~``suff(16)=11``
    share signatures per write; four 4-cliques pay 3 and run
    concurrently.

    The measured region is now a FIXED OFFERED LOAD (the ``shards``
    workload preset through the open-loop driver): every config sees
    the same ops/s schedule, so the gateable number is the achieved
    rate against that schedule and the CO-corrected p50/p99 — not a
    closed-loop burst whose rate swings with scheduler luck (the
    spread that kept this section REPORT_ONLY).  Sharding shows up as
    lower queueing delay (``p99_offered_s``/``backlog``) at the same
    offered load, on top of the per-shard route counters and the
    bucket-assignment balance."""
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage
    from bftkv_tpu.workload.driver import run_in_process
    from bftkv_tpu.workload.spec import WorkloadSpec, flag_overrides
    from tests.cluster_utils import start_cluster

    env = flag_overrides()
    offered = rate if rate is not None else env.get("rate", 40.0)
    seed = env.get("seed", 12)
    total_ops = writers * writes_per_writer
    over: dict = dict(
        rate=offered, duration_s=total_ops / offered, owners=writers,
        value_size=value_size, size_max=value_size, seed=seed,
    )
    if zipf > 0:
        over.update(keys="zipf", zipf_s=zipf)
    spec = WorkloadSpec.preset("shards", **over)

    configs: list[dict] = []
    for nsh in shard_counts:
        if total_servers % nsh or total_rw % nsh:
            raise ValueError("total replica counts must divide shard count")
        t_setup = time.perf_counter()
        cluster = start_cluster(
            total_servers // nsh,
            writers,
            total_rw // nsh,
            bits=bits,
            storage_factory=MemStorage,
            n_shards=nsh,
        )
        setup_s = time.perf_counter() - t_setup
        servers, clients = cluster.all_servers, cluster.clients
        try:
            dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
            dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
            value = os.urandom(value_size)
            # Session + route-cache warmup: one write per (client,
            # shard) so every client has live transport sessions to
            # every clique before the timed region — the 1-shard config
            # warms its whole fleet in one write, the sharded ones must
            # not pay bootstrap envelopes mid-measurement.
            shard_of = clients[0].qs.shard_of
            for ci, c in enumerate(clients[:writers]):
                seen: set = set()
                k = 0
                while len(seen) < nsh and k < 4096:
                    key = b"bench/warm/%d/%d" % (ci, k)
                    si = shard_of(key)
                    if si not in seen:
                        seen.add(si)
                        c.write(key, value)
                    k += 1
            for c in clients[:writers]:
                if hasattr(c, "drain_tails"):
                    c.drain_tails()
            metrics.reset()
            from bftkv_tpu import trace as _trmod

            trace_cur0 = _trmod.tracer.cursor()

            wl = run_in_process(spec, clients[:writers])
            if wl["errors"]:
                raise RuntimeError(
                    f"workload errors at {nsh} shards: "
                    f"{wl['error_samples']}"
                )
            for c in clients[:writers]:
                if hasattr(c, "drain_tails"):
                    c.drain_tails()
            writes_ok = wl["offered_ops"] - wl["errors"]
            elapsed = wl["elapsed_s"]
            got = clients[0].read(b"bench/warm/0/0")
            assert got == value, "read-back mismatch"

            snap = metrics.snapshot()
            route_counts = {
                k.split("shard=")[-1].rstrip("}"): v
                for k, v in snap.items()
                if k.startswith("quorum.route.shard{")
            }
            # Per-shard write latency from the shard-labeled series the
            # fleet collector merges — a straggling shard is visible
            # here, not averaged away in the fleet-wide p50.
            shard_p50 = {
                k.split("shard=")[-1].rstrip("}"): round(v, 4)
                for k, v in snap.items()
                if k.startswith("client.write.latency.p50{")
            }
            wrong_shard = sum(
                v
                for k, v in snap.items()
                if k.startswith("server.wrong_shard")
                and ".count" not in k
            )
            buckets = clients[0].qs.shard_buckets()
            entry = {
                "shards": nsh,
                "servers_per_shard": total_servers // nsh,
                "rw_per_shard": total_rw // nsh,
                "replicas": total_servers + total_rw,
                "writers": writers,
                "writes": writes_ok,
                "writes_per_sec": wl["achieved_rate_per_sec"],
                "offered_rate_per_sec": wl["offered_rate_per_sec"],
                # CO-corrected ladder quantiles: measured from each
                # op's SCHEDULED start, so a queueing config shows its
                # backlog here instead of shedding offered load.
                "p50_offered_s": wl["p50_offered_s"],
                "p99_offered_s": wl["p99_offered_s"],
                "backlog": wl["backlog"],
                "write_p50_s": round(
                    snap.get("client.write.latency.p50", 0), 4
                ),
                "write_p99_s": round(
                    snap.get("client.write.latency.p99", 0), 4
                ),
                "route_counts": route_counts,
                "write_p50_by_shard": shard_p50,
                "wrong_shard_rejects": wrong_shard,
                "bucket_counts": buckets,
                "bucket_balance_max_min": round(
                    max(buckets) / max(min(buckets), 1), 3
                ),
                "quorum_cache_hits": snap.get("quorum.cache.hits", 0),
                "quorum_cache_misses": snap.get("quorum.cache.misses", 0),
                "round_p50_s": _round_breakdown(trace_cur0),
                "phase_budget": _phase_budget(trace_cur0),
                "setup_s": round(setup_s, 1),
            }
            entry.update(
                {
                    k: v
                    for k, v in _hot_loop_metrics(snap).items()
                    if k.startswith(("piggyback", "backfills", "tail"))
                }
            )
            entry.update(_capacity_series(snap, elapsed))
            if zipf > 0:
                entry["zipf_s"] = zipf
            configs.append(entry)
        finally:
            dispatch.uninstall_all()
            for s in servers:
                s.tr.stop()

    by_shards = {c["shards"]: c for c in configs}
    base = by_shards.get(1, configs[0])
    top = by_shards.get(max(by_shards), configs[-1])
    out = {
        "configs": configs,
        "value_bytes": value_size,
        "bits": bits,
        "workload": spec.canonical(),
        # Headline for this section: the widest sharding's ACHIEVED
        # rate against the fixed offered schedule (stable across runs
        # by construction — the promotion out of REPORT_ONLY), plus
        # the queueing comparison that now carries the scaling story.
        "writes_per_sec": top["writes_per_sec"],
        "offered_rate_per_sec": spec.mean_rate(),
        "p99_offered_by_shards": {
            str(c["shards"]): c["p99_offered_s"] for c in configs
        },
        "scaling_vs_single_quorum": round(
            top["writes_per_sec"] / max(base["writes_per_sec"], 1e-9), 2
        ),
    }
    return out


def bench_cluster_workload(
    presets: tuple = ("read_heavy", "write_heavy", "storm", "ramp"),
    *,
    workers: int = 4,
    rate: float = 25.0,
    duration_s: float = 4.0,
    procs: int = 2,
    mp_rate: float = 120.0,
    mp_duration_s: float = 1.5,
    bits: int = 1024,
) -> dict:
    """Production workload engine proof (DESIGN.md §23): the declarative
    presets driven through the open-loop engine against one loopback
    fleet, then the GIL-wall pair — the SAME fixed offered schedule
    driven by in-process threads vs worker PROCESSES over the real HTTP
    transport.

    Two claims land in the committed record:

    - each preset's CO-corrected p50/p99 (latency from the SCHEDULED
      start on the fleet bucket ladder) plus the capacity plane's
      bottleneck verdict for that op mix — "where does this shape
      queue" is answerable from BENCH_r*.json alone;
    - the GIL pair: one arrival schedule driven by in-process threads
      vs worker PROCESSES over HTTP, merged by bucket-vector
      summation, with ``cpu_count`` recorded alongside.  Interpreter
      parallelism only pays where there are CORES to run on — past
      one interpreter's capacity the process driver's achieved rate
      beats the thread pool's on a multi-core box, while on 1 core
      both modes are CPU-bound and the process boundary's per-RPC
      context switches make parity-to-penalty the honest expectation.
      The record carries the evidence either way.
    """
    import shutil
    import tempfile

    from bftkv_tpu import flags as _flags
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.obs.capacity import CapacityPlane
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage
    from bftkv_tpu.workload.driver import run_in_process, run_multiprocess
    from bftkv_tpu.workload.spec import WorkloadSpec, flag_overrides
    from tests.cluster_utils import start_cluster

    over = flag_overrides()
    seed = over.get("seed", 12)
    rate = over.get("rate", rate)
    duration_s = over.get("duration_s", duration_s)
    procs = _flags.get_int("BFTKV_WORKLOAD_PROCS") or procs
    from bftkv_tpu import trace as _trmod

    out: dict = {"presets": {}}
    cluster = start_cluster(
        4, workers, 4, bits=bits, storage_factory=MemStorage
    )
    clients = cluster.clients
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        for name in presets:
            spec = WorkloadSpec.preset(
                name, rate=rate, duration_s=duration_s, seed=seed
            )
            # Warm outside the window: each worker prefills the HOT
            # ranks of its own owner slots (write_many batches), so
            # the read mix hits committed records instead of quorum
            # misses and the route/session caches are live.
            for ci, c in enumerate(clients[:workers]):
                for owner in range(ci, spec.owners, workers):
                    items = [
                        (spec.key_bytes(owner, r), b"warm")
                        for r in range(min(8, spec.keyspace))
                    ]
                    errs = [e for e in c.write_many(items) if e]
                    if errs:
                        raise errs[0]
                if hasattr(c, "drain_tails"):
                    c.drain_tails()
            metrics.reset()
            cur0 = _trmod.tracer.cursor()
            wl = run_in_process(spec, clients[:workers])
            for c in clients[:workers]:
                if hasattr(c, "drain_tails"):
                    c.drain_tails()
            snap = metrics.snapshot()
            budget = _phase_budget(cur0)
            plane = CapacityPlane()
            plane.observe("bench", {}, now=0.0)
            plane.observe("bench", snap, now=max(wl["elapsed_s"], 1e-9))
            verdict = plane.verdict(budget)
            entry = {
                k: wl[k]
                for k in (
                    "offered_rate_per_sec", "offered_ops",
                    "achieved_rate_per_sec", "elapsed_s", "p50_offered_s",
                    "p99_offered_s", "mean_offered_s", "ops", "errors",
                    "backlog",
                )
            }
            entry["spec"] = wl["spec"]
            entry["write_p50_s"] = round(
                snap.get("client.write.latency.p50", 0), 4
            )
            entry["phase_budget"] = budget
            entry["capacity_verdict"] = verdict["summary"]
            if verdict["top"]:
                entry["capacity_top"] = verdict["top"]
            entry.update(_capacity_series(snap, wl["elapsed_s"]))
            out["presets"][name] = entry
    finally:
        dispatch.uninstall_all()
        cluster.stop()

    first = out["presets"][presets[0]]
    # Compact-line headline: the first preset's achieved rate against
    # its fixed offered schedule, with its CO-corrected write p50.
    out["ops_per_sec"] = first["achieved_rate_per_sec"]
    out["offered_rate_per_sec"] = first["offered_rate_per_sec"]
    out["write_p50_s"] = first["write_p50_s"]
    out["p99_offered_s"] = first["p99_offered_s"]
    out["capacity_verdict"] = first["capacity_verdict"]

    # -- the GIL wall, measured: same schedule, threads vs processes --
    spec_mp = WorkloadSpec.preset(
        "shards", rate=mp_rate, duration_s=mp_duration_s, owners=procs,
        value_size=256, size_max=256, seed=seed,
    )
    cluster = start_cluster(
        4, procs, 4, bits=bits, storage_factory=MemStorage,
        transport="http",
    )
    homes = tempfile.mkdtemp(prefix="bftkv-wl-homes-")
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        for ci, c in enumerate(cluster.clients[:procs]):
            c.write(spec_mp.key_bytes(ci % spec_mp.owners, 0), b"warm")
            if hasattr(c, "drain_tails"):
                c.drain_tails()
        inproc = run_in_process(spec_mp, cluster.clients[:procs])
        mp = run_multiprocess(spec_mp, cluster, homes, procs=procs)
        pick = (
            "offered_rate_per_sec", "achieved_rate_per_sec", "elapsed_s",
            "p50_offered_s", "p99_offered_s", "errors", "backlog",
        )
        out["gil_wall"] = {
            "spec": spec_mp.canonical(),
            "procs": procs,
            # Interpreter parallelism only pays where there are cores
            # to run on: on a 1-core box both modes are CPU-bound and
            # the honest expectation is parity, not a win.
            "cpu_count": os.cpu_count(),
            "in_process": {k: inproc[k] for k in pick},
            "multi_process": {k: mp[k] for k in pick},
            "mp_over_inproc": round(
                mp["achieved_rate_per_sec"]
                / max(inproc["achieved_rate_per_sec"], 1e-9),
                2,
            ),
        }
    finally:
        dispatch.uninstall_all()
        cluster.stop()
        shutil.rmtree(homes, ignore_errors=True)
    return out


def bench_cluster_split(
    servers_per_shard: int = 4,
    rw_per_shard: int = 4,
    writers: int = 8,
    writes_per_phase: int = 20,
    *,
    value_size: int = 512,
    bits: int = 1024,
    zipf: float = 1.1,
) -> dict:
    """Elastic topology autopilot proof (DESIGN.md §15): a zipf-skewed
    workload whose hot keys all hash-route to ONE shard of a 2-shard
    fleet triggers an AUTOMATIC hot-shard split — no manual
    intervention — and aggregate writes/s rises once the hot buckets
    spread across both cliques.  Three measured phases:

    - **pre**: closed-loop writers on the hot key set (all on the hot
      shard; the other clique idles);
    - **flip window**: the same writers keep writing WHILE the
      autopilot detects the skew and executes pre-copy → flip → drain;
      per-write success is recorded — write availability must never
      drop to zero across the flip (stale writers re-route in-round
      off hinted declines);
    - **post**: the same workload on the rebalanced table.

    Reports pre/post rates, the flip-window p99 and failure count, and
    the route-table epochs the fleet traversed."""
    from bftkv_tpu.autopilot import Autopilot
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage
    from tests.cluster_utils import start_cluster

    t_setup = time.perf_counter()
    cluster = start_cluster(
        servers_per_shard,
        writers,
        rw_per_shard,
        bits=bits,
        storage_factory=MemStorage,
        n_shards=2,
    )
    setup_s = time.perf_counter() - t_setup
    servers, clients = cluster.all_servers, cluster.clients
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        value = os.urandom(value_size)
        qs0 = clients[0].qs
        hot_shard = 0
        # Hot key set: per-writer slices, every key routed to ONE shard
        # (the zipf knob then skews popularity INSIDE the set — the
        # workload shape ROADMAP item 4 names).
        hot_keys: dict[int, list[bytes]] = {}
        for ci in range(writers):
            ks, i = [], 0
            while len(ks) < max(writes_per_phase, 8) and i < 65536:
                k = b"bench/split/%d/%d" % (ci, i)
                i += 1
                if qs0.shard_of(k) == hot_shard:
                    ks.append(k)
            hot_keys[ci] = ks
        probs = _zipf_probs(max(writes_per_phase, 8), zipf)

        # Warmup: one write per (writer, shard) for sessions + leases.
        for ci, c in enumerate(clients[:writers]):
            seen: set = set()
            k = 0
            while len(seen) < 2 and k < 4096:
                key = b"bench/split/warm/%d/%d" % (ci, k)
                si = qs0.shard_of(key)
                if si not in seen:
                    seen.add(si)
                    c.write(key, value)
                k += 1
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()
        for c in clients[:writers]:
            c.qs.reset_bucket_load()

        lock = threading.Lock()
        samples: list[tuple[float, float, bool]] = []  # (ts, dt, ok)

        def run_phase(tag: str, stop_evt=None, n=writes_per_phase,
                      think: float = 0.0):
            """One write burst; returns (ok_writes, elapsed).  ``think``
            paces the loop (the flip window wants CONTINUOUS
            availability probes, not saturation — an unpaced window
            writes thousands of versions whose churn would dominate
            the post-phase measurement)."""
            errors: list = []

            def run(ci: int, client) -> None:
                rng = np.random.default_rng(7000 + ci)
                i = 0
                while (
                    (stop_evt is None and i < n)
                    or (stop_evt is not None and not stop_evt.is_set())
                ):
                    i += 1
                    ks = hot_keys[ci]
                    var = ks[int(rng.choice(len(probs), p=probs)) % len(ks)]
                    t1 = time.perf_counter()
                    try:
                        client.write(var, value + i.to_bytes(4, "big"))
                        ok = True
                    except Exception as e:
                        ok = _is_write_conflict(e)
                        if not ok:
                            errors.append(e)
                            ok = False
                    with lock:
                        samples.append(
                            (t1, time.perf_counter() - t1, ok)
                        )
                    if think:
                        time.sleep(think)

            threads = [
                threading.Thread(target=run, args=(ci, c), daemon=True)
                for ci, c in enumerate(clients[:writers])
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            el = time.perf_counter() - t0
            with lock:
                ok_n = sum(1 for ts, _dt, ok in samples if ok and ts >= t0)
            return ok_n, el, errors

        # Phase 1 — pre-split rate (hot shard only).
        ok_pre, el_pre, _ = run_phase("pre")
        pre_rate = ok_pre / el_pre

        # Phase 2 — the autopilot decides + executes WHILE writers run.
        metrics.reset()  # reroute/decline counters cover flip + post
        ap = Autopilot.for_cluster(cluster)
        plan = ap.decide()
        auto_decided = plan is not None
        stop = threading.Event()
        flip_fail = [0]
        mig: dict = {}

        def migrate():
            try:
                if plan is not None:
                    mig.update(ap.execute(plan, pace=0.05))
                else:
                    mig.update(ap.force_split(hot_shard, pace=0.05))
            finally:
                stop.set()

        t_flip0 = time.perf_counter()
        mthread = threading.Thread(target=migrate, daemon=True)
        mthread.start()
        ok_flip, el_flip, errs_flip = run_phase(
            "flip", stop_evt=stop, think=0.05
        )
        mthread.join(timeout=120)
        flip_fail[0] = len(errs_flip)
        flip_samples = [
            dt for ts, dt, ok in samples if ok and ts >= t_flip0
        ]
        flip_p99 = (
            round(float(np.percentile(flip_samples, 99)), 4)
            if flip_samples
            else None
        )

        # Phase 3 — post-split rate on the rebalanced table.
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()
        ok_post, el_post, _ = run_phase("post")
        post_rate = ok_post / el_post
        for c in clients[:writers]:
            if hasattr(c, "drain_tails"):
                c.drain_tails()

        snap = metrics.snapshot()
        moved = sum(
            1
            for ci in range(writers)
            for k in hot_keys[ci]
            if qs0.shard_of(k) != hot_shard
        )
        total_keys = sum(len(v) for v in hot_keys.values())
        return {
            "shards": 2,
            "writers": writers,
            "zipf_s": zipf,
            "auto_decided": auto_decided,
            "migration_ok": bool(mig.get("ok")),
            "epoch": mig.get("final_epoch") or mig.get("epoch"),
            "moved_hot_keys": moved,
            "hot_keys": total_keys,
            "pre_writes_per_sec": round(pre_rate, 2),
            "writes_per_sec": round(post_rate, 2),  # headline: post
            "post_writes_per_sec": round(post_rate, 2),
            "speedup_post_vs_pre": round(post_rate / max(pre_rate, 1e-9), 2),
            "flip_window_s": round(el_flip, 3),
            "flip_window_writes": ok_flip,
            "flip_window_failures": flip_fail[0],
            "flip_window_errors": sorted(
                {repr(e)[:80] for e in errs_flip}
            )[:5],
            "flip_window_p99_s": flip_p99,
            "availability_held": ok_flip > 0 and flip_fail[0] == 0,
            "rerouted": snap.get("client.route.rerouted", 0),
            "write_p50_s": round(
                snap.get("client.write.latency.p50", 0), 4
            ),
            "setup_s": round(setup_s, 1),
            **_capacity_series(snap),
        }
    finally:
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()


def _sidecar_tenant_main(argv: list[str]) -> None:
    """One tenant PROCESS of the cluster_sidecar bench (spawned as
    ``bench.py --sidecar-tenant ...``): signs/verifies batches either
    locally (the per-process dispatcher baseline — on a CPU-calibrated
    box that is the inline native-Montgomery host path) or through the
    shared sidecar, and reports its own measured window so the parent
    aggregates overlapping tenants honestly."""
    import argparse
    import statistics

    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True)
    ap.add_argument("--mode", choices=["local", "remote"], required=True)
    ap.add_argument("--role", choices=["replica", "gateway"],
                    default="replica")
    ap.add_argument("--rounds", type=int, default=24)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--bits", type=int, default=2048)
    ap.add_argument("--interval-ms", type=float, default=0.0,
                    help="open-loop arrival interval per batch (0 = "
                         "closed loop); latency is measured from the "
                         "SCHEDULED time, so backlog is charged to the "
                         "laggard (coordinated-omission corrected)")
    ap.add_argument("--start-at", type=float, default=0.0)
    ap.add_argument("--out", required=True)
    args = ap.parse_args(argv)

    from bftkv_tpu.crypto import rsa as rsamod
    from bftkv_tpu.crypto.remote_verify import (
        RemoteSignerDomain,
        RemoteVerifierDomain,
    )

    # Deployment-shaped keys: replica share issuance is RSA-2048.
    key = rsamod.generate(args.bits)
    vitems = [
        (b"sct-%d" % i, rsamod.sign(b"sct-%d" % i, key), key.public)
        for i in range(args.batch)
    ]
    signer = RemoteSignerDomain(args.addr) if args.mode == "remote" else None
    verifier = (
        RemoteVerifierDomain(args.addr) if args.mode == "remote" else None
    )
    # Warm the connection + handle registration outside the window.
    if signer is not None and args.role == "replica":
        signer.sign_batch([(b"warm", key)])
    if verifier is not None:
        verifier.verify_batch(vitems[:1])
    now = time.time()
    if args.start_at > now:
        time.sleep(args.start_at - now)  # overlap gate across tenants

    interval = args.interval_ms / 1000.0
    sign_lats: list[float] = []
    verify_lats: list[float] = []
    # One _OpenLoop per tenant process (coordinated-omission-corrected
    # latency from the DUE time); interval 0 = closed loop.
    ol = _OpenLoop(1.0 / interval, 1) if interval else None
    t0 = ol.t0 if ol else time.perf_counter()
    for r in range(args.rounds):
        due = ol.wait(0, r) if ol else time.perf_counter()
        if args.role == "replica":
            msgs = [(b"sg-%d-%d" % (r, i), key) for i in range(args.batch)]
            if signer is not None:
                sigs = signer.sign_batch(msgs)
            else:
                sigs = [rsamod.sign(m, k) for m, k in msgs]
            sign_lats.append(time.perf_counter() - due)
            assert all(sigs)
        else:
            if verifier is not None:
                ok = verifier.verify_batch(vitems)
            else:
                ok = [rsamod.verify_host(m, s, k) for m, s, k in vitems]
            verify_lats.append(time.perf_counter() - due)
            assert all(ok)
    elapsed = time.perf_counter() - t0
    ops = (len(sign_lats) + len(verify_lats)) * args.batch
    with open(args.out, "w") as f:
        json.dump(
            {
                "role": args.role,
                "mode": args.mode,
                "elapsed_s": elapsed,
                "ops": ops,
                "sign_batch_p50_s": (
                    statistics.median(sign_lats) if sign_lats else None
                ),
                "verify_batch_p50_s": (
                    statistics.median(verify_lats) if verify_lats else None
                ),
                "batch": args.batch,
            },
            f,
        )


def _sidecar_megabatch_dryrun(
    threads: int = 16, items_per_submit: int = 64, submits: int = 4
) -> dict:
    """Mega-batch occupancy probe (ISSUE 19): ``threads`` concurrent
    tenants each submit ``submits`` batches of ``items_per_submit``
    modexp items — two limb-width classes mixed — into ONE wide-window
    dispatcher, the super-flush shape the r11 device plane coalesces
    into width-keyed launches.  Measured on ANY backend: the dry run
    pins always-host so the occupancy number (items per LAUNCH) is
    about the coalescing machinery, not kernel speed — on an
    accelerator box the identical shape rides the width-grouped
    shard_map fan-out.  Results are spot-checked against ``pow``."""
    import threading as _threading

    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch as dmod

    before = metrics.snapshot()
    d = dmod.ModexpDispatcher(
        max_batch=4096,
        max_wait=0.05,
        calibrate=False,
        device_threshold=dmod.ALWAYS_HOST,
    ).start()
    # Two width classes (the RSA-2048 / RSA-3072 CRT-half shapes):
    # interleaved per submit, so every super-flush carries both.
    m512 = (1 << 511) + 187
    m768 = (1 << 767) + 183
    errs: list = []
    gate = _threading.Barrier(threads)

    def tenant(tid: int) -> None:
        try:
            gate.wait(timeout=30)
            for s in range(submits):
                items = [
                    (3 + tid + i, 65537, m512 if i % 2 else m768)
                    for i in range(items_per_submit)
                ]
                out = d.submit(items)
                i0 = (tid + s) % items_per_submit
                b, e, m = items[i0]
                if out[i0] != pow(b, e, m):
                    raise AssertionError("megabatch parity")
        except Exception as e:
            errs.append(e)

    ths = [
        _threading.Thread(target=tenant, args=(i,)) for i in range(threads)
    ]
    t0 = time.perf_counter()
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=300)
    elapsed = time.perf_counter() - t0
    d.stop()
    if errs:
        raise errs[0]
    after = metrics.snapshot()

    def delta(name: str) -> float:
        return after.get(name, 0) - before.get(name, 0)

    items = delta("modexpdispatch.items")
    launches = delta("modexpdispatch.launches")
    flushes = delta("modexpdispatch.flushes")
    return {
        "threads": threads,
        "items": int(items),
        "flushes": int(flushes),
        "launches": int(launches),
        "occupancy_items_per_launch": round(items / launches, 2)
        if launches
        else None,
        "elapsed_s": round(elapsed, 3),
        "items_per_sec": round(items / elapsed, 1) if elapsed > 0 else None,
    }


def bench_cluster_sidecar(
    replicas: int = 2,
    gateways: int = 1,
    rounds: int = 40,
    batch: int = 16,
    bits: int = 2048,
    sign_interval_ms: float = 110.0,
    verify_interval_ms: float = 50.0,
) -> dict:
    """Shared crypto sidecar vs per-process dispatchers (ROADMAP item
    2, DESIGN.md §17): N replica-shaped tenant PROCESSES (sign bursts)
    plus a gateway-shaped one (verify bursts) offer the SAME open-loop
    load twice on the same box —

    - **baseline**: each process on its own crypto (the per-process
      dispatcher shape; CPU calibration makes that the inline native-
      Montgomery host path) — concurrent bursts contend fair-share;
    - **shared**: every process through ONE sidecar over a unix
      socket, where cross-tenant batches coalesce in the service's
      dispatchers (clients still self-check signatures and spot-check
      verdicts — the untrusted-service tax is IN the measurement).

    Latency is measured from each burst's SCHEDULED arrival
    (coordinated-omission corrected, the ``--open-loop`` precedent).
    The claims the section carries: sidecar batch occupancy > 1 item
    per launch with ≥2 tenant processes (cross-process coalescing is
    real), and shared sign p50 at or under the per-process baseline at
    the same offered load — central FIFO service beats fair-share
    interleaving for equal-size bursts (classic M/D/1-vs-PS), and on
    an accelerator box the gap widens further by the
    launch-amortization the kernel sections measure."""
    import statistics
    import subprocess
    import tempfile

    from bftkv_tpu.cmd import verify_sidecar as vs
    from bftkv_tpu.metrics import registry as metrics

    tmp = tempfile.mkdtemp(prefix="bftkv-bench-sidecar-")
    addr = "unix:" + os.path.join(tmp, "crypto.sock")
    t_setup = time.perf_counter()
    srv, _t = vs.serve(addr)
    setup_s = time.perf_counter() - t_setup

    def run_phase(mode: str) -> dict:
        outs = []
        procs = []
        start_at = time.time() + 8.0  # interpreter+keygen outside window
        roles = ["replica"] * replicas + ["gateway"] * gateways
        gw_rounds = max(
            1, int(rounds * sign_interval_ms / verify_interval_ms)
        )
        for i, role in enumerate(roles):
            out = os.path.join(tmp, f"{mode}-{i}.json")
            outs.append(out)
            interval = (
                sign_interval_ms if role == "replica"
                else verify_interval_ms
            )
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, os.path.abspath(__file__),
                        "--sidecar-tenant",
                        "--addr", addr, "--mode", mode, "--role", role,
                        "--rounds",
                        str(rounds if role == "replica" else gw_rounds),
                        "--batch", str(batch),
                        "--bits", str(bits),
                        "--interval-ms", str(interval),
                        "--start-at", str(start_at), "--out", out,
                    ],
                    env=dict(os.environ, JAX_PLATFORMS="cpu"),
                )
            )
        for p in procs:
            p.wait(timeout=600)
        docs = []
        for out in outs:
            with open(out) as f:
                docs.append(json.load(f))
        sign_p50s = [
            d["sign_batch_p50_s"] for d in docs if d["sign_batch_p50_s"]
        ]
        verify_p50s = [
            d["verify_batch_p50_s"]
            for d in docs
            if d["verify_batch_p50_s"]
        ]
        return {
            "ops": sum(d["ops"] for d in docs),
            "elapsed_s": max(d["elapsed_s"] for d in docs),
            "sign_batch_p50_s": round(statistics.median(sign_p50s), 5)
            if sign_p50s
            else None,
            "sign_p50_ms_per_op": round(
                statistics.median(sign_p50s) / batch * 1000, 3
            )
            if sign_p50s
            else None,
            "verify_batch_p50_s": round(
                statistics.median(verify_p50s), 5
            )
            if verify_p50s
            else None,
        }

    try:
        baseline = run_phase("local")
        metrics.reset()
        shared = run_phase("remote")
        # Mega-batch open-loop dry-run BEFORE the final snapshot, so
        # its modexpdispatch occupancy/launch series ride the section's
        # capacity + device_occupancy extract.
        mega = _sidecar_megabatch_dryrun()
        snap = metrics.snapshot()

        def occ(name: str):
            flushes = snap.get(f"{name}.flushes", 0)
            return (
                round(snap.get(f"{name}.items", 0) / flushes, 2)
                if flushes
                else None
            )

        shared_rate = shared["ops"] / shared["elapsed_s"]
        sp50 = shared["sign_p50_ms_per_op"]
        bp50 = baseline["sign_p50_ms_per_op"]
        return {
            "tenants": replicas + gateways,
            "replicas": replicas,
            "gateways": gateways,
            "rounds": rounds,
            "batch": batch,
            "bits": bits,
            "sidecar_ops_per_sec": round(shared_rate, 2),
            "baseline_ops_per_sec": round(
                baseline["ops"] / baseline["elapsed_s"], 2
            ),
            "sign_p50_ms_per_op": {
                "per_process": bp50,
                "shared_sidecar": sp50,
            },
            "sign_p50_shared_vs_baseline": round(sp50 / bp50, 3)
            if sp50 and bp50
            else None,
            "verify_batch_p50_s": {
                "per_process": baseline["verify_batch_p50_s"],
                "shared_sidecar": shared["verify_batch_p50_s"],
            },
            "sign_occupancy_per_launch": occ("signdispatch"),
            "verify_occupancy_per_launch": occ("dispatch"),
            "megabatch": mega,
            "megabatch_occupancy_items_per_launch": mega[
                "occupancy_items_per_launch"
            ],
            "coalesced": bool(
                (occ("signdispatch") or 0) > 1
                or (occ("dispatch") or 0) > 1
            ),
            "shed": srv.service.admission.shed,
            "sign_remote": snap.get("sidecar.items{op=sign}", 0),
            "verify_remote": snap.get("sidecar.items{op=verify}", 0),
            "setup_s": round(setup_s, 1),
            **_capacity_series(snap, shared["elapsed_s"]),
        }
    finally:
        srv.service.stop()
        srv.shutdown()
        srv.server_close()


def bench_threshold(rounds: int = 3) -> dict:
    """BASELINE config 3/4 signing: live (t,n)=(5,9) threshold CA over a
    9-replica cluster — RSA-2048 and ECDSA P-256 dist_sign rounds
    (reference analog: protocol/dist_test.go:29-105)."""
    from bftkv_tpu.crypto import rsa as rsamod
    from bftkv_tpu.crypto.threshold import ThresholdAlgo
    from bftkv_tpu.crypto.threshold.ecdsa import generate as ec_generate
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage

    servers, clients = _make_cluster(9, 4, 1, MemStorage)
    dispatch.install()
    dispatch.install_signer()
    c = clients[0]
    out: dict = {"t": 5, "n": 9}
    try:
        ca_rsa = rsamod.generate(2048)
        c.distribute("bench-rsa", ca_rsa)
        ca_ec = ec_generate()
        c.distribute("bench-ecdsa", ca_ec)
        for algo, name in (
            (ThresholdAlgo.RSA, "rsa2048"),
            (ThresholdAlgo.ECDSA, "ecdsa_p256"),
        ):
            caname = "bench-" + ("rsa" if algo == ThresholdAlgo.RSA else "ecdsa")
            c.dist_sign(caname, b"warm", algo, "sha256")  # compile warm-up
            t0 = time.perf_counter()
            for i in range(rounds):
                sig = c.dist_sign(caname, b"bench-tbs-%d" % i, algo, "sha256")
                assert sig
            el = time.perf_counter() - t0
            out[name] = {
                "signs_per_sec": round(rounds / el, 3),
                "sign_latency_s": round(el / rounds, 3),
            }
    finally:
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()
    return out


# ---------------------------------------------------------------------------
# Batched revoke-on-read tally (BASELINE config 5)
# ---------------------------------------------------------------------------


def bench_tally(universe: int = 256, n_byz: int = 85, batch: int = 4096) -> dict:
    """Equivocation tally over 256 simulated replicas, f=85 colluders."""
    import jax

    from bftkv_tpu.ops import tally

    rng = np.random.default_rng(7)
    honest = np.zeros((2, universe), dtype=bool)
    honest[0, : universe // 2] = True
    honest[1, universe // 2 : universe - n_byz] = True
    byz = np.zeros((2, universe), dtype=bool)
    byz[:, universe - n_byz :] = True  # colluders sign both values
    signer_sets = honest | byz
    mask = np.asarray(tally.equivocation_pairs(jax.device_put(signer_sets)))
    assert mask.sum() == n_byz, (mask.sum(), n_byz)
    # Throughput: batch of independent tallies via vmap.
    sets = np.broadcast_to(signer_sets, (batch,) + signer_sets.shape).copy()
    fn = jax.jit(jax.vmap(tally.equivocation_pairs))
    jax.block_until_ready(fn(sets))
    iters, elapsed = 0, 0.0
    t0 = time.perf_counter()
    while elapsed < (0.3 if FAST else 1.0) or iters < 3:
        jax.block_until_ready(fn(sets))
        iters += 1
        elapsed = time.perf_counter() - t0
    return {
        "universe": universe,
        "byzantine": n_byz,
        "tallies_per_sec": round(batch * iters / elapsed, 1),
        "detected": int(mask.sum()),
    }


# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Orchestration — flap-proof, per-section subprocess isolation
#
# The TPU here rides a tunnel that can die at any moment; a dead tunnel
# makes jax backend init (and any in-flight device call) hang forever.
# Round 3 lost its entire evidence record to a single late tunnel flap
# because the bench probed once at startup and ran everything in one
# process.  The orchestrator below never imports jax itself; each
# section runs in a SUBPROCESS with a timeout, the backend is re-probed
# around failures, and every TPU-captured section result is persisted
# to BENCH_partial.json the moment it completes — so a later run (e.g.
# the driver's end-of-round run) can fall back to the cached TPU
# measurement, clearly labeled with its capture time, instead of
# degrading the whole record to CPU numbers.
# ---------------------------------------------------------------------------

PARTIAL_PATH = os.path.join(REPO, "BENCH_partial.json")
DETAIL_PATH = os.path.join(REPO, "BENCH_detail.json")


@functools.lru_cache(maxsize=1)
def _code_fingerprint() -> str:
    """Short hash over the framework + bench sources.

    Cached TPU captures are stamped with this so a capture made before a
    kernel change is visibly stale (`cached_stale_code`) when spliced
    into a later record.  Docs/tests don't affect it: only code that can
    change a measurement (bftkv_tpu/, native/, bench.py) is hashed.
    """
    import hashlib

    h = hashlib.sha256()
    roots = [os.path.join(REPO, "bftkv_tpu"), os.path.join(REPO, "native")]
    files = [os.path.join(REPO, "bench.py")]
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames
                if f.endswith((".py", ".c", ".cpp", ".cc", ".h", ".hpp"))
            )
    for path in sorted(files):
        try:
            with open(path, "rb") as f:
                # Relative paths: the fingerprint must survive the repo
                # being checked out elsewhere.
                h.update(os.path.relpath(path, REPO).encode())
                h.update(f.read())
        except OSError:
            pass
    return h.hexdigest()[:12]

# token -> extra-dict section name.  Order = run order.
SECTION_NAMES = {
    "kernel": "verify_kernel",
    "rns": "rns_kernel",
    "sign": "sign_kernel",
    "modexp": "modexp_kernel",
    "ec": "ec_kernel",
    "c4": "cluster_4",
    "c4http": "cluster_4_http",
    "c16": "cluster_16",
    "c64": "cluster_64",
    "mix64": "cluster_64_mix",
    "c4ec": "cluster_4_ec",
    "b16": "cluster_16_batched",
    "b64": "cluster_64_batched",
    "bmix64": "cluster_64_batched_mix",
    "bmix64ec": "cluster_64_batched_mix_ec",
    "cshards": "cluster_shards",
    "cwl": "cluster_workload",
    "csplit": "cluster_split",
    "csc": "cluster_sidecar",
    "c4gray": "cluster_4_gray",
    "c4log": "cluster_4_log",
    "cgw": "cluster_gateway",
    "cwan": "cluster_wan",
    "thr": "threshold_5_9",
    "tally": "revoke_tally_256",
}

# Sections cheap enough to measure on CPU when the accelerator is
# unreachable AND no cached TPU measurement exists (last resort).
# cluster_shards is a self-relative scaling ratio, meaningful on any
# backend; cluster_4_gray is hedged-vs-unhedged on the same box, also
# self-relative; cluster_gateway is gateway-vs-direct on the same box,
# likewise self-relative.
# cluster_sidecar is shared-vs-per-process on the same box, also
# self-relative.
# cluster_wan is WAN-vs-loopback physics on the same box (the RTT
# matrix dominates both paths identically) — self-relative too.
# cluster_workload is achieved-vs-offered at a fixed schedule plus a
# threads-vs-processes pair on the same box — self-relative as well.
CPU_OK = {"tally", "c4", "cshards", "csplit", "c4gray", "cgw", "csc",
          "c4log", "cwan", "cwl"}

# Per-section subprocess timeouts (seconds).  The flapping tunnel makes
# a hung section indistinguishable from a slow one until the timeout
# fires, so each section gets a budget sized to its honest worst case
# (compiles included) instead of one 30-minute blanket: a mid-run
# tunnel death costs minutes, not the rest of the run.  BENCH_SECTION_
# TIMEOUT overrides everything when set.
TOKEN_TIMEOUT = {
    "kernel": 600, "modexp": 600, "tally": 600,
    "rns": 900, "sign": 900, "ec": 900, "thr": 900,
    "c4": 900, "c4http": 900, "c4ec": 900, "c16": 900, "c4gray": 900,
    "c4log": 900, "cgw": 900, "cwan": 900,
    "b16": 1200, "b64": 1500, "bmix64": 1500, "bmix64ec": 1500,
    "c64": 1500, "mix64": 1500, "cshards": 1500, "csplit": 900,
    "csc": 900, "cwl": 1500,
}

# Headline preference: batched 64-replica pipeline first (the TPU-native
# throughput shape), then per-write clusters by size, then raw kernels.
HEADLINE_ORDER = [
    ("cluster_64_batched", "writes_per_sec", "signed_writes_per_sec_64replica_batched", "writes/s"),
    ("cluster_16_batched", "writes_per_sec", "signed_writes_per_sec_16replica_batched", "writes/s"),
    ("cluster_64", "writes_per_sec", "signed_writes_per_sec_64replica", "writes/s"),
    ("cluster_16", "writes_per_sec", "signed_writes_per_sec_16replica", "writes/s"),
    ("cluster_4", "writes_per_sec", "signed_writes_per_sec_4replica", "writes/s"),
    ("rns_kernel", "best_verifies_per_sec", "rsa2048_verifies_per_sec", "verifies/s"),
    ("verify_kernel", "best_verifies_per_sec", "rsa2048_verifies_per_sec", "verifies/s"),
]


def _section_spec(token: str):
    """(section_name, zero-arg callable) for one config token.

    Resolved in the CHILD process: env knobs and FAST sizing are read
    here so the orchestrator stays jax-free.
    """
    batches = [int(b) for b in _env_list("BENCH_KERNEL_BATCHES", "256,1024,4096")]
    # Throughput is occupancy-driven (shared device launches amortize
    # across concurrent writers), so the default is deliberately high.
    writers = int(os.environ.get("BENCH_WRITERS", "4" if FAST else "16"))
    writes = int(os.environ.get("BENCH_WRITES", "4" if FAST else "16"))
    batch_size = int(os.environ.get("BENCH_BATCH", "256" if FAST else "1024"))
    zipf = float(os.environ.get("BENCH_ZIPF", "0") or 0)
    open_loop = float(os.environ.get("BENCH_OPEN_LOOP", "0") or 0)
    rtt_matrix = os.environ.get("BENCH_RTT_MATRIX", "") or "wan3"
    specs = {
        "kernel": lambda: bench_kernel_verify(batches),
        "rns": lambda: bench_kernel_rns(
            (1024, 4096) if FAST else (4096, 16384, 65536)
        ),
        "sign": lambda: bench_kernel_sign(
            (256, 1024) if FAST else (256, 1024, 4096)
        ),
        "modexp": lambda: bench_kernel_modexp(64 if FAST else 256),
        # Two batch points only: every (batch, backend) pair is its own
        # compile, and the tunnel window should measure, not compile.
        # 4096 is BASELINE config 4's batch; 256 anchors the small end.
        "ec": lambda: bench_kernel_ec(
            (64,) if FAST else (256, 4096)
        ),
        "c4": lambda: bench_cluster(
            4, 4, writers, writes, storage="plain", dispatch_batch=256,
            zipf=zipf, open_loop=open_loop,
        ),
        "c4http": lambda: bench_cluster(
            4, 4, writers, writes, storage="mem", dispatch_batch=256,
            transport="http", zipf=zipf,
        ),
        # BASELINE config 4's key type: ECDSA P-256 identity certs.
        "c4ec": lambda: bench_cluster(
            4, 4, writers, writes, storage="mem", dispatch_batch=256,
            alg="p256", zipf=zipf,
        ),
        "c16": lambda: bench_cluster(
            16, 4, writers, writes, storage="mem", dispatch_batch=256,
            zipf=zipf,
        ),
        # 8 rw storage nodes: with none, W = U - {Ci} + R is empty and
        # writes have nowhere to land (wotqs.go:72-115).
        "c64": lambda: bench_cluster(
            64, 8, writers, max(2, writes // 4), storage="mem",
            dispatch_batch=1024, zipf=zipf,
        ),
        # BASELINE config 4: 64 replicas, 80/20 read/write mix.
        "mix64": lambda: bench_cluster(
            64, 8, writers, max(2, writes // 4), storage="mem",
            dispatch_batch=1024, read_fraction=0.8, zipf=zipf,
        ),
        # ROADMAP item 2: same fleet + client count re-partitioned into
        # 1/2/4 hash-routed shards; writes/s must scale near-linearly.
        "cshards": lambda: bench_cluster_shards(
            shard_counts=(1, 2) if FAST else (1, 2, 4),
            writes_per_writer=3 if FAST else 18,
            zipf=zipf,
        ),
        # Production workload engine (DESIGN.md §23): declarative
        # presets through the open-loop driver (CO-corrected ladder
        # quantiles + capacity verdict per op mix), then the GIL pair
        # — in-process threads vs worker processes at the same fixed
        # offered load.  BFTKV_WORKLOAD_{SEED,RATE,DURATION,PROCS}
        # override the schedule.
        "cwl": lambda: bench_cluster_workload(
            presets=(
                ("read_heavy", "write_heavy")
                if FAST
                else ("read_heavy", "write_heavy", "storm", "ramp")
            ),
            workers=2 if FAST else 4,
            rate=10.0 if FAST else 25.0,
            duration_s=1.5 if FAST else 4.0,
            procs=2,
            mp_rate=60.0 if FAST else 120.0,
            mp_duration_s=1.0 if FAST else 1.5,
        ),
        # Elastic topology autopilot (ROADMAP item 4): a zipf-skewed
        # hot-shard workload must trigger an AUTOMATIC split with no
        # manual intervention; reports pre/post rates and the
        # flip-window availability/p99 (DESIGN.md §15).
        "csplit": lambda: bench_cluster_split(
            writers=4 if FAST else 8,
            writes_per_phase=6 if FAST else 20,
            zipf=zipf if zipf > 0 else 1.1,
        ),
        # Gray failure: one slow-but-alive clique member; hedging +
        # health-aware staging vs the fixed-timeout behavior, plus the
        # repair daemon's certified/demoted counters (DESIGN.md §13).
        "c4gray": lambda: bench_cluster_gray(
            writers=4 if FAST else 8,
            writes_per_writer=4 if FAST else 10,
        ),
        # Log-structured engine (DESIGN.md §19): cluster_4 fleet on
        # --storage log (group-committed durable writes) + the raw
        # keyspace fill sweep (write p50 at 10k/100k/1M resident keys;
        # --keyspace / BENCH_KEYSPACE caps the sweep).
        "c4log": lambda: bench_cluster_log(
            writers=4 if FAST else 8,
            writes_per_writer=4 if FAST else 10,
            keyspace=int(
                os.environ.get("BENCH_KEYSPACE", "")
                or ("100000" if FAST else "1000000")
            ),
            zipf=zipf,
            open_loop=open_loop,
        ),
        # Edge gateway tier (ROADMAP item 1): N stacked gateways in
        # front of the quorums — certified-cache read throughput vs
        # direct quorum reads, coalesced front-door writes vs direct.
        "cgw": lambda: bench_cluster_gateway(
            readers=4 if FAST else 8,
            reads_per_reader=10 if FAST else 120,
            writers=2 if FAST else 4,
            writes_per_writer=3 if FAST else 5,
            open_loop=open_loop,
        ),
        # Multi-region WAN plane (DESIGN.md §21): 3-region cluster_4
        # fleet under a deterministic RTT matrix — same-region cached
        # read vs WAN write p50 vs the loopback floor, plus a whole-
        # region partition window that must lose ZERO writes while the
        # collector names the region_down.  --rtt-matrix / BENCH_RTT_
        # MATRIX picks the geography (named or raw ms spec).
        "cwan": lambda: bench_cluster_wan(
            readers=2 if FAST else 4,
            reads_per_reader=10 if FAST else 25,
            writers=2 if FAST else 4,
            writes_per_writer=3 if FAST else 6,
            rtt_spec=rtt_matrix,
        ),
        # Shared crypto sidecar (ROADMAP item 2): tenant processes
        # sign+verify through ONE box-wide service vs per-process
        # crypto; cross-process batch occupancy and sign/verify p50.
        "csc": lambda: bench_cluster_sidecar(
            replicas=1 if FAST else 2,
            rounds=10 if FAST else 24,
            batch=8 if FAST else 16,
        ),
        "b16": lambda: bench_cluster_batch(
            16, 4, 2 if FAST else 4, batch_size, 1 if FAST else 2
        ),
        "b64": lambda: bench_cluster_batch(
            64, 8, 2 if FAST else 4, batch_size, 1 if FAST else 2
        ),
        # BASELINE config 4, batched: 64 replicas, 80/20 read/write.
        "bmix64": lambda: bench_cluster_batch(
            64, 8, 2 if FAST else 4, batch_size, 1, read_fraction=0.8
        ),
        # BASELINE config 4 as WRITTEN: ECDSA P-256 identity keys,
        # 64 replicas, 80/20 read/write mix, batched pipeline.
        "bmix64ec": lambda: bench_cluster_batch(
            64, 8, 2 if FAST else 4, batch_size, 1, read_fraction=0.8,
            alg="p256",
        ),
        # BASELINE config 3/4: threshold (5,9) RSA + ECDSA signing.
        "thr": lambda: bench_threshold(2 if FAST else 4),
        "tally": lambda: bench_tally(),
    }
    return SECTION_NAMES[token], specs[token]


def _child_main(token: str, out_path: str) -> None:
    """Run ONE section in this (sub)process and dump its payload."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        from bftkv_tpu.hostcpu import force_cpu

        force_cpu(1)
    import jax

    try:  # persistent compile cache: repeat runs skip XLA compilation
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/jax_bftkv"),
        )
    except Exception:
        pass

    name, fn = _section_spec(token)
    t0 = time.perf_counter()
    try:
        result = fn()
        result["section_s"] = round(time.perf_counter() - t0, 1)
    except Exception as e:
        result = {"error": f"{type(e).__name__}: {e}"}
    payload = {
        "section": name,
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "jax": jax.__version__,
        "result": result,
    }
    with open(out_path, "w") as f:
        json.dump(payload, f)


def _section_backend(result: dict, backend: str) -> str:
    """Backend label for one section's record.  A WAN section carries
    its RTT matrix in the label ("cpu/8+wan:wan3"): geography changes
    the physics, so bench_compare files such rounds as their own
    backend class — reported, never compared against loopback runs."""
    mark = result.get("wan_marker") if isinstance(result, dict) else None
    if not mark:
        return backend
    # Into the FIRST token: "cpu/8 (fallback…)" → "cpu/8+wan:… (…)",
    # so _compact_extra's token-splitting status keeps the class.
    base, sep, rest = backend.partition(" ")
    return f"{base}+{mark}{sep}{rest}"


def _probe_backend(timeout_s: float) -> bool:
    """True iff a non-CPU jax backend initializes within the timeout.

    Runs in a subprocess: a hung probe thread would wedge jax's
    in-process backend lock.  Exit 0 with backend "cpu" means jax
    *silently* fell back — the accelerator is just as unreachable as
    in the hang case.
    """
    import subprocess

    try:
        res = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            timeout=timeout_s,
        )
        return res.returncode == 0 and res.stdout.strip() != b"cpu"
    except Exception:
        return False


def _run_child(token: str, timeout_s: float, force_cpu: bool):
    """Run one section subprocess; parse its payload (None on hang/crash)."""
    import subprocess
    import tempfile

    env = dict(os.environ)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
        # A CPU child must start even when the accelerator tunnel
        # blackholes: the ambient sitecustomize dials the tunnel at
        # interpreter start when this var is set, and the hang would
        # eat the whole section budget before our code runs.
        env["PALLAS_AXON_POOL_IPS"] = ""
    else:
        env.pop("BENCH_FORCE_CPU", None)
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        out_path = tf.name
    try:
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--run-section",
             token, "--out", out_path],
            env=env,
            timeout=timeout_s,
            capture_output=True,
        )
        with open(out_path) as f:
            return json.load(f)
    except Exception:
        return None
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def _load_partial() -> dict:
    try:
        with open(PARTIAL_PATH) as f:
            data = json.load(f)
        if isinstance(data.get("sections"), dict):
            return data
    except Exception:
        pass
    return {"sections": {}}


def _save_partial(partial: dict) -> None:
    partial["updated"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    tmp = PARTIAL_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(partial, f, indent=1, sort_keys=True)
    os.replace(tmp, PARTIAL_PATH)


def main() -> None:
    t_start = time.perf_counter()
    probe_timeout = float(os.environ.get("BENCH_BACKEND_TIMEOUT", "90"))
    timeout_override = os.environ.get("BENCH_SECTION_TIMEOUT")
    section_timeout = lambda token: (
        float(timeout_override)
        if timeout_override
        else float(TOKEN_TIMEOUT.get(token, 1800))
    )
    deliberate_cpu = os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
    use_cache = os.environ.get("BENCH_NO_CACHE") != "1"

    if FAST:
        default_configs = (
            "rns,sign,b16,kernel,modexp,ec,c4,c16,cshards,cwl,c4gray,"
            "c4log,cgw,cwan,csc,tally"
        )
    else:
        # Short kernel sections FIRST: the tunnel flaps and its live
        # windows have been minutes long, so each window should bank
        # the most captures (and the rns/sign sections also prove the
        # Pallas chains, arming auto mode for the clusters).  Then the
        # headline-bearing batched clusters, then the long tail.
        # BENCH_partial.json keeps whatever landed.
        default_configs = (
            "rns,sign,kernel,ec,modexp,b16,b64,bmix64,bmix64ec,"
            "c4,c16,c64,c4http,c4ec,cshards,cwl,c4gray,c4log,cgw,cwan,"
            "csc,thr,tally"
        )
    configs = [t for t in _env_list("BENCH_CONFIGS", default_configs)
               if t in SECTION_NAMES]

    partial = _load_partial()
    extra: dict = {"fast_mode": FAST}
    meta: dict = {}  # first live child's jax/devices info
    counts = {"tpu": 0, "cached": 0, "cpu": 0, "skipped": 0}
    cached_sections: list[str] = []
    healthy: bool | None = None  # None = unknown, re-probe before use
    probe_fails = 0  # consecutive failed probes; stop probing at 3

    for token in configs:
        name = SECTION_NAMES[token]

        if deliberate_cpu:
            # Operator's choice (JAX_PLATFORMS=cpu): run everything on
            # CPU, plainly labeled; never consult or write the TPU
            # cache.  The operator also owns BENCH_CONFIGS sizing.
            payload = _run_child(token, section_timeout(token), force_cpu=True)
            if payload is None:
                extra[name] = {"error": "section subprocess hung or crashed"}
            else:
                extra[name] = payload["result"]
                # Core count IS the CPU backend class: the cluster
                # sections saturate threads, so a 1-core box and an
                # 8-core box produce incomparable numbers — the same
                # reported-never-compared rule as tpu-vs-cpu
                # (tools/bench_compare.py).
                extra[name]["backend"] = _section_backend(
                    extra[name], f"cpu/{os.cpu_count()}"
                )
                meta = meta or payload
            counts["cpu"] += 1
            continue

        # Probe whenever the tunnel isn't known-good: the tunnel flaps,
        # so a probe that failed before section 2 says nothing about
        # section 10 — but cap consecutive failures so a dead-all-day
        # tunnel doesn't spend 90 s x sections at driver time.
        if healthy is not True and probe_fails < 3:
            healthy = _probe_backend(probe_timeout)
            probe_fails = 0 if healthy else probe_fails + 1

        if healthy:
            payload = _run_child(token, section_timeout(token), force_cpu=False)
            if payload is not None and payload["backend"] != "cpu" and (
                "error" not in payload["result"]
            ):
                extra[name] = payload["result"]
                extra[name]["backend"] = _section_backend(
                    extra[name], payload["backend"]
                )
                meta = meta or payload
                counts["tpu"] += 1
                partial["sections"][name] = {
                    "backend": payload["backend"],
                    "jax": payload["jax"],
                    "devices": payload["devices"],
                    "captured": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                    ),
                    "fast_mode": FAST,
                    "code": _code_fingerprint(),
                    "result": payload["result"],
                }
                _save_partial(partial)
                continue
            if payload is not None and "error" in payload["result"]:
                # Genuine section bug (process alive, backend up): record
                # the error; don't mask it with a stale cached success.
                extra[name] = payload["result"]
                counts["skipped"] += 1
                continue
            # Hang/crash or silent CPU fallback: tunnel likely died
            # mid-run.  Unknown health → re-probe before next section.
            healthy = None

        # Accelerator unreachable for this section: cached TPU result?
        # Only a capture from the SAME sizing mode may stand in — a
        # FAST-mode smoke capture is not evidence for a full-matrix
        # record (batch sizes and write counts differ).
        cached = partial["sections"].get(name) if use_cache else None
        if cached is not None and cached.get("fast_mode") != FAST:
            cached = None
        if cached and cached.get("backend") not in (None, "cpu"):
            extra[name] = dict(cached["result"])
            extra[name]["backend"] = _section_backend(
                extra[name], cached["backend"]
            )
            extra[name]["cached_from"] = cached["captured"]
            if cached.get("code") and cached["code"] != _code_fingerprint():
                # The capture predates a source change (ADVICE r4 #2).
                # Still the best evidence available, but say so: the
                # number measured different code than HEAD.
                extra[name]["cached_stale_code"] = True
            cached_sections.append(name)
            counts["cached"] += 1
        elif token in CPU_OK:
            payload = _run_child(token, section_timeout(token), force_cpu=True)
            if payload is None:
                extra[name] = {"error": "section subprocess hung or crashed"}
            else:
                extra[name] = payload["result"]
                extra[name]["backend"] = _section_backend(
                    extra[name],
                    f"cpu/{os.cpu_count()} "
                    "(accelerator unreachable; CPU fallback)",
                )
            counts["cpu"] += 1
        else:
            extra[name] = {
                "skipped": "accelerator unreachable; no cached TPU measurement"
            }
            counts["skipped"] += 1

    # Aggregate backend label.  "tpu" only when every recorded section
    # is TPU-backed; cached sections are enumerated honestly.
    n_tpu = counts["tpu"] + counts["cached"]
    if deliberate_cpu:
        backend = f"cpu/{os.cpu_count()}"
    elif n_tpu and not counts["cpu"] and not counts["skipped"]:
        backend = "tpu"
    elif n_tpu:
        backend = (
            f"tpu (partial: {n_tpu}/{len(configs)} sections on tpu; "
            f"{counts['cpu']} cpu, {counts['skipped']} skipped)"
        )
    else:
        backend = "cpu (accelerator unreachable; CPU fallback)"
    extra["backend"] = backend
    if cached_sections:
        extra["cached_sections"] = cached_sections
    if meta:
        extra["jax"] = meta["jax"]
        extra["devices"] = meta["devices"]
    elif cached_sections:
        src = partial["sections"][cached_sections[0]]
        extra["jax"] = src.get("jax")
        extra["devices"] = src.get("devices")
    extra["total_s"] = round(time.perf_counter() - t_start, 1)

    value, metric, unit = 0.0, "no_configs_selected", "writes/s"
    headline_from = None
    # Preference tiers, best first: live TPU, cached same-code TPU,
    # freshly measured CPU, cached-stale TPU.  Two invariants: a
    # TPU-backed section outranks a CPU-fallback one (r04's headline
    # was the CPU cluster_4 while a real TPU capture sat lower), and a
    # cached capture of OLD code is never promoted over anything
    # freshly measured (r05's headline was a cached-stale rns_kernel
    # while a live cluster_4 measurement sat right there).
    for tier in range(4):
        for name, field, m, u in HEADLINE_ORDER:
            sec = extra.get(name)
            if not (isinstance(sec, dict) and field in sec):
                continue
            if _headline_tier(sec) != tier:
                continue
            value, metric, unit, headline_from = sec[field], m, u, name
            break
        if headline_from:
            break
    is_writes = unit == "writes/s" and metric != "no_configs_selected"
    if is_writes:
        vs = round(value / NORTH_STAR_WRITES_PER_SEC, 5)
    elif unit == "verifies/s":
        # Kernel headline (no TPU cluster capture yet): ratio against
        # the per-replica verify rate the 50k-writes/s north star
        # implies, so the driver still gets a meaningful fraction.
        vs = round(value / NORTH_STAR_VERIFIES_PER_SEC, 5)
    else:
        vs = None
    record = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs,
        "extra": extra,
    }

    # Full record -> BENCH_detail.json + stderr; stdout gets ONLY a
    # compact line, printed LAST.  The driver keeps a bounded tail of
    # stdout: in r04 the all-sections-inline line outgrew that window
    # and the record's beginning -- the headline itself -- was lost
    # (BENCH_r04.json "parsed": null).  The compact line is unit-tested
    # to stay under 1 KB even when every section reports.
    try:
        tmp = DETAIL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        os.replace(tmp, DETAIL_PATH)
    except OSError:
        pass
    print(json.dumps(record), file=sys.stderr)
    record["extra"] = _compact_extra(extra, configs, headline_from)
    # Compact separators: the full 22-section matrix must stay under the
    # driver's bounded stdout tail (test_final_stdout_line_stays_small).
    print(json.dumps(record, separators=(",", ":")))


def _headline_tier(sec: dict) -> int:
    """0 live TPU · 1 cached same-code TPU · 2 fresh CPU · 3 cached-stale."""
    if sec.get("cached_stale_code"):
        return 3
    if "cached_from" in sec:
        return 1
    if str(sec.get("backend", "")).startswith("cpu"):
        return 2
    return 0


def _compact_extra(extra: dict, configs: list, headline_from) -> dict:
    """Small (<1 KB) summary of ``extra`` for the final stdout line.

    Per section: ``[status, headline number]`` where status is one of
    tpu / cached / cached-stale / cpu / cpu-fallback / skip / err.
    Full per-section dicts live in BENCH_detail.json and on stderr.
    """
    sections: dict = {}
    skipped: list = []
    for token in configs:
        name = SECTION_NAMES[token]
        sec = extra.get(name)
        if not isinstance(sec, dict):
            continue
        if "skipped" in sec:
            # One "skip" status per section costs len(name)+9 bytes a
            # dozen times over on a dead-tunnel run (r04's shape); a
            # single token list says the same thing in one field.
            skipped.append(token)
            continue
        if "error" in sec:
            sections[name] = "err"
            continue
        backend = str(sec.get("backend", "?"))
        if "cached_from" in sec:
            status = "cached-stale" if sec.get("cached_stale_code") else "cached"
        elif backend.startswith("cpu") and "(" in backend:
            # Keep the core-count class in the compact status:
            # "cpu/8 (accelerator unreachable…)" → "cpu/8-fallback".
            status = backend.split(" ", 1)[0] + "-fallback"
        else:
            status = backend
        num = next(
            (
                round(v, 2)
                for k, v in sec.items()
                if k.endswith("_per_sec") and isinstance(v, (int, float))
            ),
            None,
        )
        # Cluster sections additionally carry write p50 as a third
        # element, so the driver round records gate LATENCY regressions
        # too (tools/bench_compare.py; two-element records stay valid).
        # The gray section carries its hedged slowdown ratio as a
        # FOURTH element — bench_compare holds it under the absolute
        # ≤2x acceptance bound.  A section with a phase budget carries
        # it FIFTH (gray slot null-padded), so the attribution numbers
        # enter the committed trajectory (DESIGN.md §18).  The sidecar
        # section's mega-batch occupancy (items per device launch under
        # the open-loop dry run — the §22 coalescing-health axis) rides
        # SIXTH, earlier slots null-padded; bench_compare reports it,
        # never gates it.  All of those axes gate CLUSTER sections
        # only, so non-cluster entries stay [status, number] — part of
        # keeping the full-matrix worst case under the 1 KB tail
        # budget.
        if not name.startswith("cluster"):
            sections[name] = [status, num] if num is not None else status
            continue
        p50 = sec.get("write_p50_s")
        gray = sec.get("gray_slowdown_hedged")
        pb = sec.get("phase_budget")
        occ = sec.get("megabatch_occupancy_items_per_launch")
        if num is not None and isinstance(p50, (int, float)) and p50 > 0:
            compact = [status, num, p50]
        elif num is not None:
            compact = [status, num]
        else:
            sections[name] = status
            continue
        if isinstance(gray, (int, float)) and gray > 0:
            while len(compact) < 3:
                compact.append(None)
            compact.append(gray)
        if isinstance(pb, dict) and pb:
            while len(compact) < 4:
                compact.append(None)
            compact.append(pb)
        if isinstance(occ, (int, float)) and occ > 0:
            while len(compact) < 5:
                compact.append(None)
            compact.append(round(occ, 1))
        sections[name] = compact
    # The top-level backend rides the compact line in CLASS form only:
    # "cpu/1 (accelerator unreachable…)" → "cpu/1-fallback" — the
    # parenthetical prose lives in BENCH_detail.json, and the class is
    # what bench_compare keys comparability on.
    backend = str(extra.get("backend") or "")
    if backend.startswith("cpu") and "(" in backend:
        backend = backend.split(" ", 1)[0] + "-fallback"
    out = {
        "backend": backend or None,
        "fast_mode": extra.get("fast_mode"),
        "sections": sections,
        "total_s": extra.get("total_s"),
        "detail": "BENCH_detail.json",
    }
    # Metadata that buys nothing on the bounded stdout line stays in
    # BENCH_detail.json and the stderr full record: jax/devices were
    # dropped outright when the 23rd section outgrew the 1 KB tail
    # budget (bench_compare never reads them), and null/false fields
    # cost bytes without information.
    for key in ("fast_mode",):
        if not out[key]:
            del out[key]
    if skipped:
        out["skipped"] = ",".join(skipped)
    if headline_from:
        out["headline_from"] = headline_from
    return out


if __name__ == "__main__":
    # --zipf S: hot-key skew for the cluster sections, exported as
    # BENCH_ZIPF so section subprocesses inherit it.
    if "--zipf" in sys.argv:
        i = sys.argv.index("--zipf")
        os.environ["BENCH_ZIPF"] = sys.argv[i + 1]
        del sys.argv[i : i + 2]
    # --open-loop RATE: cluster writers (and the gateway readers) run
    # at a target offered load (ops/s) with coordinated-omission-
    # corrected latency, instead of closed-loop at saturation.
    if "--open-loop" in sys.argv:
        i = sys.argv.index("--open-loop")
        os.environ["BENCH_OPEN_LOOP"] = sys.argv[i + 1]
        del sys.argv[i : i + 2]
    # --rtt-matrix SPEC: geography for the cluster_wan section — a
    # named topology (wan2, wan3) or a raw ms spec ("20/80/150"),
    # exported as BENCH_RTT_MATRIX so section subprocesses inherit it.
    if "--rtt-matrix" in sys.argv:
        i = sys.argv.index("--rtt-matrix")
        os.environ["BENCH_RTT_MATRIX"] = sys.argv[i + 1]
        del sys.argv[i : i + 2]
    # --keyspace N: cap for the cluster_4_log fill sweep (resident-key
    # points 10k/100k/1M, skipping points above N), exported as
    # BENCH_KEYSPACE so section subprocesses inherit it.
    if "--keyspace" in sys.argv:
        i = sys.argv.index("--keyspace")
        os.environ["BENCH_KEYSPACE"] = sys.argv[i + 1]
        del sys.argv[i : i + 2]
    if len(sys.argv) >= 2 and sys.argv[1] == "--sidecar-tenant":
        _sidecar_tenant_main(sys.argv[2:])
    elif len(sys.argv) >= 5 and sys.argv[1] == "--run-section":
        _child_main(sys.argv[2], sys.argv[4])
    else:
        main()
