#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the headline metric.

Measures the BASELINE.json config matrix on the default JAX backend —
the bench environment's real TPU.  If the accelerator cannot be
reached within ``BENCH_BACKEND_TIMEOUT`` seconds (subprocess probe: a
dead tunnel hangs in-process backend init), the run falls back to CPU
with a shrunk config set and a clearly labeled ``backend`` field; the
probe costs one extra backend bring-up on healthy runs.  Sections:

- batched RSA-2048 e=65537 verify kernel throughput at batch
  {256, 1024, 4096} vs the single-core host ``pow`` baseline
  (reference hot loop: crypto/pgp/crypto_pgp.go:485-500);
- full-exponent modexp (threshold-RSA partial signing / TPA DH,
  reference: crypto/threshold/rsa/rsa.go:140-178);
- signed writes/sec + p50/p99 write latency through in-process
  clusters (4 / 16 / 64 replicas) with the cross-request verify
  dispatcher installed — the analog of the reference's only perf
  instrument, ``TestManyWrites``/``TestManyReads``
  (protocol/rw_test.go:65-109) and ``scripts/test.go:36-58``;
- batched revoke-on-read equivocation tally at 256 simulated
  replicas (BASELINE config 5).

Headline metric: signed writes/sec on the largest cluster measured;
``vs_baseline`` is the ratio against BASELINE.json's 50k-writes/sec
north star. Everything else rides in ``extra``.

Env knobs: BENCH_CONFIGS=kernel,c4,c16,c64,tally  BENCH_WRITERS=N
BENCH_WRITES=N  BENCH_KERNEL_BATCHES=256,1024,4096  BENCH_FAST=1
BENCH_BATCH=N (batched-pipeline sections)  BENCH_BACKEND_TIMEOUT=secs
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

NORTH_STAR_WRITES_PER_SEC = 50_000.0

FAST = os.environ.get("BENCH_FAST") == "1"


def _env_list(name: str, default: str) -> list[str]:
    return [s for s in os.environ.get(name, default).split(",") if s]


# ---------------------------------------------------------------------------
# Kernel benchmarks
# ---------------------------------------------------------------------------


def _verify_operands(batch: int, nlimbs: int = 128):
    """(sig, em, n, n', r2) arrays for a batch of genuine signatures.

    Signs a small distinct set on host and tiles it: verification cost
    is identical for repeated rows, and host signing 4096 items would
    dominate setup time.
    """
    from bftkv_tpu.crypto import rsa
    from bftkv_tpu.ops import bigint, limb

    key = rsa.generate(nlimbs * 16)
    dom = bigint.MontgomeryDomain(key.n, nlimbs)
    base = min(batch, 32)
    sigs, ems = [], []
    for i in range(base):
        msg = b"bench-%d" % i
        s = int.from_bytes(rsa.sign(msg, key), "big")
        em = rsa.emsa_pkcs1v15_sha256(msg, key.size_bytes)
        sigs.append(limb.int_to_limbs(s, nlimbs))
        ems.append(limb.int_to_limbs(em, nlimbs))
    reps = -(-batch // base)
    sig = np.tile(np.stack(sigs), (reps, 1))[:batch]
    em = np.tile(np.stack(ems), (reps, 1))[:batch]
    rep = lambda row: np.broadcast_to(row, (batch, nlimbs)).copy()
    return key, sig, em, rep(dom.n), rep(dom.n_prime), rep(dom.r2), rep(dom.one_mont)


def bench_kernel_verify(batches: list[int]) -> dict:
    """Device verifies/sec per batch size + host pow baseline."""
    import jax

    from bftkv_tpu.ops import rsa as rsa_ops

    out: dict = {"batch": {}}
    key, sig, em, n, npr, r2, _one = _verify_operands(max(batches))
    for b in sorted(batches):
        args = [jax.device_put(a[:b]) for a in (sig, em, n, npr, r2)]
        t0 = time.perf_counter()
        ok = np.asarray(rsa_ops.verify_batch_e65537(*args))
        compile_s = time.perf_counter() - t0
        assert ok.all(), "bench verify kernel returned false on genuine sigs"
        # Timed iterations on device-resident operands.
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 2.0) or iters < 3:
            jax.block_until_ready(rsa_ops.verify_batch_e65537(*args))
            iters += 1
            elapsed = time.perf_counter() - t0
        rate = b * iters / elapsed
        out["batch"][str(b)] = {
            "verifies_per_sec": round(rate, 1),
            "first_call_s": round(compile_s, 2),
            "iters": iters,
        }
    # Host single-core baseline: raw pow() as the reference's math/big does.
    from bftkv_tpu.ops import limb

    s_int = limb.limbs_to_ints(sig[:64])
    em_int = limb.limbs_to_ints(em[:64])
    t0 = time.perf_counter()
    for s, e in zip(s_int, em_int):
        assert pow(s, 65537, key.n) == e
    host_rate = 64 / (time.perf_counter() - t0)
    out["host_pow_verifies_per_sec"] = round(host_rate, 1)
    best = max(v["verifies_per_sec"] for v in out["batch"].values())
    out["best_verifies_per_sec"] = best
    out["speedup_vs_host_pow"] = round(best / host_rate, 2)
    return out


def bench_kernel_modexp(batch: int = 256) -> dict:
    """Full 2048-bit-exponent modexp (threshold-RSA partial sign / TPA)."""
    import jax

    from bftkv_tpu.ops import limb
    from bftkv_tpu.ops import rsa as rsa_ops

    key, sig, _em, n, npr, r2, one = _verify_operands(batch)
    e = np.broadcast_to(limb.int_to_limbs(key.d, 128), (batch, 128)).copy()
    args = [jax.device_put(a) for a in (sig, e, n, npr, r2, one)]
    t0 = time.perf_counter()
    jax.block_until_ready(rsa_ops.power_batch(*args))
    compile_s = time.perf_counter() - t0
    iters, elapsed = 0, 0.0
    t0 = time.perf_counter()
    while elapsed < (0.5 if FAST else 2.0) or iters < 2:
        jax.block_until_ready(rsa_ops.power_batch(*args))
        iters += 1
        elapsed = time.perf_counter() - t0
    rate = batch * iters / elapsed
    # Host baseline on 8 items.
    s_int = limb.limbs_to_ints(sig[:8])
    t0 = time.perf_counter()
    for s in s_int:
        pow(s, key.d, key.n)
    host_rate = 8 / (time.perf_counter() - t0)
    return {
        "batch": batch,
        "modexps_per_sec": round(rate, 1),
        "host_pow_modexps_per_sec": round(host_rate, 1),
        "speedup_vs_host_pow": round(rate / host_rate, 2),
        "first_call_s": round(compile_s, 2),
    }


def bench_kernel_rns(batches=(4096, 16384, 65536)) -> dict:
    """RSA-2048 e=65537 verifies/sec on the RNS (MXU/f32) kernel — the
    default verify backend; ~19x the limb kernel at large batch."""
    import jax

    from bftkv_tpu.ops import rns

    ctx = rns.context()
    out: dict = {"batch": {}}
    key, sig, em, _n, _npr, _r2, _one = _verify_operands(32)
    row = [np.asarray(r) for r in ctx.key_rows(key.n)]
    f = rns._jitted_verify()
    for b in sorted(batches):
        sig_d = np.tile(sig, (b // 32 + 1, 1))[:b]
        em_d = np.tile(em, (b // 32 + 1, 1))[:b]
        kr = tuple(
            jax.device_put(
                np.broadcast_to(r, (b,) + r.shape).copy()
                if r.ndim
                else np.full((b, 1), r, dtype=np.float32)
            )
            for r in row
        )
        sh = jax.device_put(rns.digits_to_halves(sig_d))
        eh = jax.device_put(rns.digits_to_halves(em_d))
        t0 = time.perf_counter()
        ok = np.asarray(f(sh, eh, kr))
        compile_s = time.perf_counter() - t0
        assert ok.all(), "RNS bench kernel returned false on genuine sigs"
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 3.0) or iters < 3:
            jax.block_until_ready(f(sh, eh, kr))
            iters += 1
            elapsed = time.perf_counter() - t0
        out["batch"][str(b)] = {
            "verifies_per_sec": round(b * iters / elapsed, 1),
            "first_call_s": round(compile_s, 2),
        }
    out["best_verifies_per_sec"] = max(
        v["verifies_per_sec"] for v in out["batch"].values()
    )
    return out


def bench_kernel_sign(batches=(256, 1024, 4096)) -> dict:
    """Batched RSA-2048 CRT signs/sec through SignerDomain (the RNS
    windowed-modexp path; reference hot loop: crypto_pgp.go:346-371)
    vs single-core host CRT signing."""
    from bftkv_tpu.crypto import rsa as rsamod

    key = rsamod.generate(2048)
    sd = rsamod.SignerDomain(host_threshold=0)
    out: dict = {"batch": {}, "backend": sd.backend}
    for b in sorted(batches):
        items = [(b"sign-%d" % i, key) for i in range(b)]
        t0 = time.perf_counter()
        sigs = sd.sign_batch(items)
        compile_s = time.perf_counter() - t0
        assert sigs[0] == rsamod.sign(b"sign-0", key)
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 2.0) or iters < 2:
            sd.sign_batch(items)
            iters += 1
            elapsed = time.perf_counter() - t0
        out["batch"][str(b)] = {
            "signs_per_sec": round(b * iters / elapsed, 1),
            "first_call_s": round(compile_s, 2),
        }
    t0 = time.perf_counter()
    for i in range(8):
        rsamod.sign(b"host-%d" % i, key)
    host_rate = 8 / (time.perf_counter() - t0)
    best = max(v["signs_per_sec"] for v in out["batch"].values())
    out["host_signs_per_sec"] = round(host_rate, 1)
    out["best_signs_per_sec"] = best
    out["speedup_vs_host"] = round(best / host_rate, 2)
    return out


def bench_kernel_ec(batches=(64, 256)) -> dict:
    """Batched P-256 scalar-mults/sec vs the host oracle (threshold-ECDSA
    hot loop, reference: crypto/threshold/ecdsa/ecdsa.go:31-59)."""
    import secrets

    import jax

    from bftkv_tpu.crypto.ec import P256
    from bftkv_tpu.ops import ec as ec_ops

    d = ec_ops.p256()
    out: dict = {"batch": {}}
    bmax = max(batches)
    pts = [P256.scalar_base_mult(i + 1) for i in range(min(16, bmax))]
    pts = (pts * (bmax // len(pts) + 1))[:bmax]
    ks = [secrets.randbelow(P256.n) for _ in range(bmax)]
    X, Y, Z = d.encode_points(pts)
    K = d.encode_scalars(ks)
    for b in sorted(batches):
        args = [jax.device_put(a[:b]) for a in (X, Y, Z, K)]
        t0 = time.perf_counter()
        jax.block_until_ready(ec_ops.scalar_mult_jac(*args))
        compile_s = time.perf_counter() - t0
        iters, elapsed = 0, 0.0
        t0 = time.perf_counter()
        while elapsed < (0.5 if FAST else 2.0) or iters < 2:
            jax.block_until_ready(ec_ops.scalar_mult_jac(*args))
            iters += 1
            elapsed = time.perf_counter() - t0
        out["batch"][str(b)] = {
            "scalar_mults_per_sec": round(b * iters / elapsed, 1),
            "first_call_s": round(compile_s, 2),
        }
    # Host oracle baseline + correctness spot check.
    got = ec_ops.scalar_mult_hosts(pts[:8], ks[:8])
    t0 = time.perf_counter()
    want = [P256.scalar_mult(p, k) for p, k in zip(pts[:8], ks[:8])]
    host_rate = 8 / (time.perf_counter() - t0)
    assert got == want, "EC kernel/oracle mismatch"
    out["host_scalar_mults_per_sec"] = round(host_rate, 1)
    best = max(v["scalar_mults_per_sec"] for v in out["batch"].values())
    out["best_scalar_mults_per_sec"] = best
    out["speedup_vs_host"] = round(best / host_rate, 2)
    return out


# ---------------------------------------------------------------------------
# Cluster benchmarks (the TestManyWrites/TestManyReads analog)
# ---------------------------------------------------------------------------


def _warm_items(count: int) -> list:
    """Synthetic (message, sig, key) triples for bucket warm-up."""
    from bftkv_tpu.crypto import rsa

    key = rsa.generate(2048)
    msg = b"bench-warm"
    sig = rsa.sign(msg, key)
    return [(msg, sig, key.public)] * count


def _warm_dispatchers(clients, bucket_max: int) -> None:
    """Pre-compile every device bucket shape a cluster run can hit:
    verify buckets (floor 256) up to the power-of-two ceiling of
    ``bucket_max`` and sign buckets up to the sign dispatcher's
    ``max_batch``, skipping sizes below the host crossovers."""
    from bftkv_tpu.ops import dispatch

    d = dispatch.get()
    bucket_max = max(256, 1 << (bucket_max - 1).bit_length())
    warm_items = _warm_items(bucket_max)
    bucket = 256
    while bucket <= bucket_max:
        if bucket >= d.verifier.host_threshold:
            d.verifier.verify_batch(warm_items[:bucket])
        bucket *= 2
    ds = dispatch.get_signer()
    sign_items = [(m, clients[0].crypt.signer.key) for m, _s, _k in warm_items]
    bucket = 16
    while bucket <= ds.max_batch:
        if bucket >= ds.signer.host_threshold:
            ds.signer.sign_batch(sign_items[:bucket])
        bucket *= 2


def _make_cluster(
    n_servers: int, n_rw: int, n_users: int, storage_factory, transport: str = "loop"
):
    """One cluster builder for tests and bench: tests/cluster_utils."""
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(
        n_servers,
        n_users,
        n_rw,
        storage_factory=storage_factory,
        transport=transport,
    )
    return cluster.all_servers, cluster.clients


def bench_cluster(
    n_servers: int,
    n_rw: int,
    writers: int,
    writes_per_writer: int,
    *,
    value_size: int = 1024,
    dispatch_batch: int = 256,
    storage: str = "mem",
    read_fraction: float = 0.0,
    transport: str = "loop",
) -> dict:
    """Signed writes/sec (+ optional read mix) through a live in-process
    cluster with the verify dispatcher installed."""
    import tempfile

    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch

    tmp = None
    if storage == "plain":
        from bftkv_tpu.storage.plain import PlainStorage

        tmp = tempfile.TemporaryDirectory(prefix="bftkv-bench-")
        counter = [0]

        def storage_factory():
            counter[0] += 1
            path = os.path.join(tmp.name, f"db{counter[0]}")
            return PlainStorage(path)

    else:
        from bftkv_tpu.storage.memkv import MemStorage

        storage_factory = MemStorage

    t_setup = time.perf_counter()
    servers, clients = _make_cluster(
        n_servers, n_rw, writers, storage_factory, transport
    )
    setup_s = time.perf_counter() - t_setup

    try:
        metrics.reset()
        dispatch.install(dispatch.VerifyDispatcher(max_batch=dispatch_batch))
        dispatch.install_signer(
            dispatch.SignDispatcher(max_batch=max(dispatch_batch // 2, 64))
        )
        value = os.urandom(value_size)
        # Warm the protocol path and the device bucket shapes the run can hit
        # (pays XLA compilation outside the timed region). A write burst at n
        # replicas produces ~n·suff verifies, padded to power-of-two buckets.
        clients[0].write(b"bench/warmup", value)
        clients[0].read(b"bench/warmup")
        # The dispatcher chunks flushes at max_batch, so the padded device
        # shape never exceeds the next power of two above dispatch_batch —
        # warming larger buckets would compile kernels the run cannot hit.
        _warm_dispatchers(clients, dispatch_batch)
        metrics.reset()

        errors: list = []
        reads_by_thread = [0] * writers

        def run(ci: int, client) -> None:
            rng = np.random.default_rng(ci)
            try:
                reads_per_write = (
                    read_fraction / (1 - read_fraction) if read_fraction else 0.0
                )
                for i in range(writes_per_writer):
                    client.write(b"bench/%d/%d" % (ci, i), value)
                    k = int(reads_per_write)
                    if rng.random() < reads_per_write - k:
                        k += 1
                    for _ in range(k):
                        client.read(b"bench/%d/%d" % (ci, rng.integers(0, i + 1)))
                        reads_by_thread[ci] += 1
            except Exception as e:  # surfaced below; bench must not hang
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(ci, c), daemon=True)
            for ci, c in enumerate(clients[:writers])
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]

        total_writes = writers * writes_per_writer
        total_reads = sum(reads_by_thread)
        # Correctness spot check before reporting a rate.
        got = clients[0].read(b"bench/0/%d" % (writes_per_writer - 1))
        assert got == value, "read-back mismatch"

        snap = metrics.snapshot()
        flushes = snap.get("dispatch.flushes", 0)
        res = {
            "replicas": n_servers,
            "rw_nodes": n_rw,
            "writers": writers,
            "writes": total_writes,
            "reads": total_reads,
            "value_bytes": value_size,
            "storage": storage,
            "transport": transport,
            "writes_per_sec": round(total_writes / elapsed, 2),
            "ops_per_sec": round((total_writes + total_reads) / elapsed, 2),
            "write_p50_s": round(snap.get("client.write.latency.p50", 0), 4),
            "write_p99_s": round(snap.get("client.write.latency.p99", 0), 4),
            "read_p50_s": round(snap.get("client.read.latency.p50", 0), 4),
            "dispatch_flushes": flushes,
            "dispatch_verifies": snap.get("dispatch.verifies", 0),
            "dispatch_batch_mean": round(
                snap.get("dispatch.verifies", 0) / flushes, 2
            )
            if flushes
            else 0,
            "dispatch_batch_p50": snap.get("dispatch.batch.p50", 0),
            "verifies_host": snap.get("verify.host", 0),
            "verifies_device": snap.get("verify.device", 0),
            "signs_host": snap.get("sign.host", 0),
            "signs_device": snap.get("sign.device", 0),
            "sign_batch_p50": snap.get("signdispatch.batch.p50", 0),
            "setup_s": round(setup_s, 1),
        }
        return res
    finally:
        # One failing section must not leak dispatchers, server
        # threads, or temp dirs into the next section.
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()
        if tmp is not None:
            tmp.cleanup()


def bench_cluster_batch(
    n_servers: int,
    n_rw: int,
    writers: int,
    batch: int,
    rounds: int,
    *,
    value_size: int = 1024,
    dispatch_batch: int = 4096,
    transport: str = "loop",
    read_fraction: float = 0.0,
) -> dict:
    """Signed writes/sec through the batched pipeline (``write_many``):
    B independent writes per protocol round, server-side crypto in
    shared device batches.  ``read_fraction`` adds ``read_many`` rounds
    for the BASELINE config-4 mix.  This is the TPU-native throughput
    shape — the per-write path (``bench_cluster``) measures latency."""
    from bftkv_tpu.metrics import registry as metrics
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage

    t_setup = time.perf_counter()
    servers, clients = _make_cluster(
        n_servers, n_rw, writers, MemStorage, transport
    )
    setup_s = time.perf_counter() - t_setup
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=dispatch_batch))
        dispatch.install_signer(
            dispatch.SignDispatcher(max_batch=dispatch_batch)
        )
        value = os.urandom(value_size)
        # Warm every device bucket shape the run can hit (pays XLA
        # compilation outside the timed region; the persistent compile
        # cache makes repeat runs cheap).
        _warm_dispatchers(clients, dispatch_batch)
        clients[0].write_many(
            [(b"bench/warm/%d" % i, value) for i in range(min(batch, 64))]
        )
        metrics.reset()

        errors: list = []
        reads_done = [0] * writers
        reads_per_round = (
            int(batch * read_fraction / (1 - read_fraction))
            if read_fraction
            else 0
        )

        def run(ci: int, client) -> None:
            rng = np.random.default_rng(ci)
            try:
                for r in range(rounds):
                    items = [
                        (b"bench/%d/%d/%d" % (ci, r, i), value)
                        for i in range(batch)
                    ]
                    errs = client.write_many(items)
                    bad = [e for e in errs if e is not None]
                    if bad:
                        raise bad[0]
                    for off in range(0, reads_per_round, batch):
                        nread = min(batch, reads_per_round - off)
                        got = client.read_many(
                            [
                                b"bench/%d/%d/%d"
                                % (ci, r, rng.integers(0, batch))
                                for _ in range(nread)
                            ]
                        )
                        # Every bench key was just written, so anything
                        # but value bytes (None included) is a failure;
                        # errors are interned Error classes/instances.
                        bad = [g for g in got if not isinstance(g, bytes)]
                        if bad:
                            raise AssertionError(f"bench read failed: {bad[0]!r}")
                        reads_done[ci] += nread
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(ci, c), daemon=True)
            for ci, c in enumerate(clients[:writers])
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        if errors:
            raise errors[0]

        total = writers * rounds * batch
        total_reads = sum(reads_done)
        got = clients[0].read(b"bench/0/0/%d" % (batch - 1))
        assert got == value, "read-back mismatch"

        snap = metrics.snapshot()
        flushes = snap.get("dispatch.flushes", 0)
        return {
            "replicas": n_servers,
            "rw_nodes": n_rw,
            "writers": writers,
            "batch": batch,
            "rounds": rounds,
            "writes": total,
            "reads": total_reads,
            "ops_per_sec": round((total + total_reads) / elapsed, 2),
            "value_bytes": value_size,
            "transport": transport,
            "writes_per_sec": round(total / elapsed, 2),
            "batch_latency_p50_s": round(
                snap.get("client.write_many.latency.p50", 0), 4
            ),
            # A production replica has its own TPU; the in-process bench
            # time-slices one chip across all n. Per-replica handler
            # capacity is the deployment-shaped number.
            "replica_sign_handler_items_per_sec": round(
                batch / h, 1
            )
            if (h := snap.get("server.batch_sign.handler.p50", 0))
            else 0,
            "replica_write_handler_items_per_sec": round(
                batch / h, 1
            )
            if (h := snap.get("server.batch_write.handler.p50", 0))
            else 0,
            "dispatch_flushes": flushes,
            "dispatch_verifies": snap.get("dispatch.verifies", 0),
            "dispatch_batch_p50": snap.get("dispatch.batch.p50", 0),
            "verifies_host": snap.get("verify.host", 0),
            "verifies_device": snap.get("verify.device", 0),
            "signs_host": snap.get("sign.host", 0),
            "signs_device": snap.get("sign.device", 0),
            "sign_batch_p50": snap.get("signdispatch.batch.p50", 0),
            "setup_s": round(setup_s, 1),
        }
    finally:
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()


def bench_threshold(rounds: int = 3) -> dict:
    """BASELINE config 3/4 signing: live (t,n)=(5,9) threshold CA over a
    9-replica cluster — RSA-2048 and ECDSA P-256 dist_sign rounds
    (reference analog: protocol/dist_test.go:29-105)."""
    from bftkv_tpu.crypto import rsa as rsamod
    from bftkv_tpu.crypto.threshold import ThresholdAlgo
    from bftkv_tpu.crypto.threshold.ecdsa import generate as ec_generate
    from bftkv_tpu.ops import dispatch
    from bftkv_tpu.storage.memkv import MemStorage

    servers, clients = _make_cluster(9, 4, 1, MemStorage)
    dispatch.install()
    dispatch.install_signer()
    c = clients[0]
    out: dict = {"t": 5, "n": 9}
    try:
        ca_rsa = rsamod.generate(2048)
        c.distribute("bench-rsa", ca_rsa)
        ca_ec = ec_generate()
        c.distribute("bench-ecdsa", ca_ec)
        for algo, name in (
            (ThresholdAlgo.RSA, "rsa2048"),
            (ThresholdAlgo.ECDSA, "ecdsa_p256"),
        ):
            caname = "bench-" + ("rsa" if algo == ThresholdAlgo.RSA else "ecdsa")
            c.dist_sign(caname, b"warm", algo, "sha256")  # compile warm-up
            t0 = time.perf_counter()
            for i in range(rounds):
                sig = c.dist_sign(caname, b"bench-tbs-%d" % i, algo, "sha256")
                assert sig
            el = time.perf_counter() - t0
            out[name] = {
                "signs_per_sec": round(rounds / el, 3),
                "sign_latency_s": round(el / rounds, 3),
            }
    finally:
        dispatch.uninstall_all()
        for s in servers:
            s.tr.stop()
    return out


# ---------------------------------------------------------------------------
# Batched revoke-on-read tally (BASELINE config 5)
# ---------------------------------------------------------------------------


def bench_tally(universe: int = 256, n_byz: int = 85, batch: int = 4096) -> dict:
    """Equivocation tally over 256 simulated replicas, f=85 colluders."""
    import jax

    from bftkv_tpu.ops import tally

    rng = np.random.default_rng(7)
    honest = np.zeros((2, universe), dtype=bool)
    honest[0, : universe // 2] = True
    honest[1, universe // 2 : universe - n_byz] = True
    byz = np.zeros((2, universe), dtype=bool)
    byz[:, universe - n_byz :] = True  # colluders sign both values
    signer_sets = honest | byz
    mask = np.asarray(tally.equivocation_pairs(jax.device_put(signer_sets)))
    assert mask.sum() == n_byz, (mask.sum(), n_byz)
    # Throughput: batch of independent tallies via vmap.
    sets = np.broadcast_to(signer_sets, (batch,) + signer_sets.shape).copy()
    fn = jax.jit(jax.vmap(tally.equivocation_pairs))
    jax.block_until_ready(fn(sets))
    iters, elapsed = 0, 0.0
    t0 = time.perf_counter()
    while elapsed < (0.3 if FAST else 1.0) or iters < 3:
        jax.block_until_ready(fn(sets))
        iters += 1
        elapsed = time.perf_counter() - t0
    return {
        "universe": universe,
        "byzantine": n_byz,
        "tallies_per_sec": round(batch * iters / elapsed, 1),
        "detected": int(mask.sum()),
    }


# ---------------------------------------------------------------------------


def _init_backend(probe_timeout: float = 120.0):
    """Import jax and initialize the default backend, falling back to
    CPU if the accelerator does not come up in time.

    The TPU here rides a tunnel; when the tunnel is down, backend
    initialization blocks indefinitely — and a bench that hangs records
    nothing at all.  The probe runs in a SUBPROCESS: a blocked probe
    thread would wedge jax's in-process backend lock and deadlock the
    CPU fallback itself.  On timeout/failure the in-process CPU repair
    (hostcpu.force_cpu) runs before any backend initialization here,
    yielding a measurable, clearly-labeled run.
    """
    import subprocess

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # Deliberate CPU run (operator's choice): no probe, no label;
        # the operator also owns BENCH_CONFIGS sizing.  The in-process
        # repair still runs — an ambient accelerator plugin otherwise
        # initializes (and hangs on a dead tunnel) regardless of the
        # env var, exactly as in the daemon's CPU lane.
        from bftkv_tpu.hostcpu import force_cpu

        force_cpu(1)
        return jax, False
    try:
        res = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            timeout=probe_timeout,
        )
        # Exit 0 with backend "cpu" means jax *silently* fell back —
        # the accelerator is just as unreachable as in the hang case,
        # so it must be labeled (and the config matrix shrunk) too.
        healthy = res.returncode == 0 and res.stdout.strip() != b"cpu"
    except Exception:
        healthy = False
    if not healthy:
        from bftkv_tpu.hostcpu import force_cpu

        force_cpu(1)
        return jax, True
    return jax, False


def main() -> None:
    t_start = time.perf_counter()
    jax, cpu_fallback = _init_backend(
        float(os.environ.get("BENCH_BACKEND_TIMEOUT", "120"))
    )

    try:  # persistent compile cache: repeat runs skip XLA compilation
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/jax_bftkv"),
        )
    except Exception:
        pass

    extra: dict = {
        "jax": jax.__version__,
        "backend": jax.default_backend()
        + (" (accelerator unreachable; CPU fallback)" if cpu_fallback else ""),
        "devices": [str(d) for d in jax.devices()],
        "fast_mode": FAST,
    }

    if cpu_fallback:
        # A CPU run of the full matrix would take hours; measure the
        # cheap sections so the record still parses and is labeled.
        default_configs = "tally,c4"
    elif FAST:
        default_configs = "kernel,rns,sign,modexp,ec,c4,c16,b16,tally"
    else:
        default_configs = (
            "kernel,rns,sign,modexp,ec,c4,c4http,c16,c64,b16,b64,bmix64,thr,tally"
        )
    configs = _env_list("BENCH_CONFIGS", default_configs)
    batches = [int(b) for b in _env_list("BENCH_KERNEL_BATCHES", "256,1024,4096")]
    # Throughput is occupancy-driven (shared device launches amortize
    # across concurrent writers), so the default is deliberately high.
    writers = int(os.environ.get("BENCH_WRITERS", "4" if FAST else "16"))
    writes = int(os.environ.get("BENCH_WRITES", "4" if FAST else "16"))

    headline = None

    def section(name: str, fn, *a, **kw):
        """One failing section must not sink the whole bench run."""
        t0 = time.perf_counter()
        try:
            extra[name] = fn(*a, **kw)
            extra[name]["section_s"] = round(time.perf_counter() - t0, 1)
            return extra[name]
        except Exception as e:
            extra[name] = {"error": f"{type(e).__name__}: {e}"}
            return None

    if "kernel" in configs:
        section("verify_kernel", bench_kernel_verify, batches)
    if "rns" in configs:
        section(
            "rns_kernel",
            bench_kernel_rns,
            (1024, 4096) if FAST else (4096, 16384, 65536),
        )
    if "sign" in configs:
        section(
            "sign_kernel",
            bench_kernel_sign,
            (256, 1024) if FAST else (256, 1024, 4096),
        )
    if "modexp" in configs:
        section("modexp_kernel", bench_kernel_modexp, 64 if FAST else 256)
    if "ec" in configs:
        section("ec_kernel", bench_kernel_ec, (64,) if FAST else (64, 256))

    if "c4" in configs:
        headline = section(
            "cluster_4", bench_cluster, 4, 4, writers, writes,
            storage="plain", dispatch_batch=256,
        ) or headline
    if "c4http" in configs:
        section(
            "cluster_4_http", bench_cluster, 4, 4, writers, writes,
            storage="mem", dispatch_batch=256, transport="http",
        )
    if "c16" in configs:
        headline = section(
            "cluster_16", bench_cluster, 16, 4, writers, writes,
            storage="mem", dispatch_batch=256,
        ) or headline
    if "c64" in configs:
        # 8 rw storage nodes: with none, W = U - {Ci} + R is empty and
        # writes have nowhere to land (wotqs.go:72-115).
        headline = section(
            "cluster_64", bench_cluster, 64, 8, writers,
            max(2, writes // 4), storage="mem", dispatch_batch=1024,
        ) or headline
    if "mix64" in configs:
        # BASELINE config 4: 64 replicas, 80/20 read/write mix.
        section(
            "cluster_64_mix", bench_cluster, 64, 8, writers,
            max(2, writes // 4), storage="mem", dispatch_batch=1024,
            read_fraction=0.8,
        )
    batch_headline = None
    batch_size = int(os.environ.get("BENCH_BATCH", "256" if FAST else "1024"))
    if "b16" in configs:
        batch_headline = section(
            "cluster_16_batched", bench_cluster_batch, 16, 4,
            2 if FAST else 4, batch_size, 1 if FAST else 2,
        ) or batch_headline
    if "b64" in configs:
        batch_headline = section(
            "cluster_64_batched", bench_cluster_batch, 64, 8,
            2 if FAST else 4, batch_size, 1 if FAST else 2,
        ) or batch_headline
    if "bmix64" in configs:
        # BASELINE config 4, batched: 64 replicas, 80/20 read/write.
        section(
            "cluster_64_batched_mix", bench_cluster_batch, 64, 8,
            2 if FAST else 4, batch_size, 1, read_fraction=0.8,
        )
    if "thr" in configs:
        # BASELINE config 3/4: threshold (5,9) RSA + ECDSA signing.
        section("threshold_5_9", bench_threshold, 2 if FAST else 4)
    if "tally" in configs:
        section("revoke_tally_256", bench_tally)

    extra["total_s"] = round(time.perf_counter() - t_start, 1)

    if batch_headline is not None:
        value = batch_headline["writes_per_sec"]
        metric = (
            f"signed_writes_per_sec_{batch_headline['replicas']}replica_batched"
        )
    elif headline is not None:
        value = headline["writes_per_sec"]
        metric = f"signed_writes_per_sec_{headline['replicas']}replica"
    elif "rns_kernel" in extra and "best_verifies_per_sec" in extra["rns_kernel"]:
        value = extra["rns_kernel"]["best_verifies_per_sec"]
        metric = "rsa2048_verifies_per_sec"
    elif "verify_kernel" in extra:
        value = extra["verify_kernel"]["best_verifies_per_sec"]
        metric = "rsa2048_verifies_per_sec"
    else:
        value, metric = 0.0, "no_configs_selected"
    is_writes = headline is not None or batch_headline is not None
    print(
        json.dumps(
            {
                "metric": metric,
                "value": value,
                "unit": "writes/s" if is_writes else "verifies/s",
                "vs_baseline": round(value / NORTH_STAR_WRITES_PER_SEC, 5)
                if is_writes
                else None,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
