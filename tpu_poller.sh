#!/bin/bash
# Probe the TPU tunnel every 120s; on success run the full bench so
# every section caches a backend:"tpu" capture in BENCH_partial.json.
# Keeps looping: later windows refresh stale captures and fill sections
# a mid-run tunnel death skipped (bench.py re-probes per section).
cd /root/repo
while true; do
  # -k: the axon register() hang can shrug off SIGTERM; escalate to
  # SIGKILL so a blackholed tunnel can't wedge the probe (observed as
  # multi-minute gaps in this log).
  if timeout -k 10 90 python - <<'PY' 2>/dev/null
import jax
assert jax.default_backend() != "cpu"
PY
  then
    echo "$(date -u +%FT%TZ) TPU LIVE — running full bench" >> tpu_poller.log
    # Above the worst-case sum of per-section TOKEN_TIMEOUT budgets
    # (~16.2 ks) so a fully-budgeted run still writes its record.
    timeout 18000 python bench.py > bench_live_stdout.txt 2> bench_live_stderr.txt
    echo "$(date -u +%FT%TZ) bench rc=$? done" >> tpu_poller.log
    sleep 60
  else
    # Short sleep: observed live windows are ~8 min; a 2-min cadence
    # (plus up-to-90s probe) can miss half a window.
    echo "$(date -u +%FT%TZ) probe: dead" >> tpu_poller.log
    sleep 45
  fi
done
