#!/bin/bash
# Probe the TPU tunnel every 150s; on first success run the full bench so
# every section caches a backend:"tpu" capture in BENCH_partial.json.
cd /root/repo
while true; do
  if timeout 120 python - <<'PY' 2>/dev/null
import jax
ds = jax.devices()
assert any('TPU' in str(d).upper() or d.platform == 'tpu' for d in ds), ds
print('TPU-LIVE', ds)
PY
  then
    echo "$(date -u +%FT%TZ) TPU LIVE — running full bench" >> tpu_poller.log
    timeout 3000 python bench.py > bench_live_stdout.txt 2> bench_live_stderr.txt
    echo "$(date -u +%FT%TZ) bench rc=$? done" >> tpu_poller.log
    exit 0
  else
    echo "$(date -u +%FT%TZ) probe: dead" >> tpu_poller.log
  fi
  sleep 150
done
