"""Shared crypto sidecar (sign+verify+modexp service): protocol round
trips, key-handle policy, backpressure shedding, kill-9 fallback with
zero failed writes, dishonest-sidecar detection (spot-check +
signature self-check), and the fleet-scrape surface (DESIGN.md §17)."""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from bftkv_tpu.admission import AdmissionQueue
from bftkv_tpu.cmd import verify_sidecar as vs
from bftkv_tpu.crypto import rsa
from bftkv_tpu.crypto.remote_verify import (
    RemoteModexpDomain,
    RemoteSignerDomain,
    RemoteVerifierDomain,
    SidecarChannel,
)
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.ops import dispatch

_PORT = [18960]


def _port() -> int:
    _PORT[0] += 1
    return _PORT[0]


@pytest.fixture(scope="module")
def key():
    return rsa.generate(1024)


@pytest.fixture()
def unix_sidecar(tmp_path):
    addr = f"unix:{tmp_path}/crypto.sock"
    srv, _t = vs.serve(addr)
    yield addr, srv
    srv.service.stop()
    srv.shutdown()
    srv.server_close()


def _stop(srv):
    srv.service.stop()
    srv.shutdown()
    srv.server_close()


# -- sign path --------------------------------------------------------------


def test_sign_roundtrip_and_handles(unix_sidecar, key):
    addr, _srv = unix_sidecar
    metrics.reset()
    sd = RemoteSignerDomain(addr)
    key2 = rsa.generate(1024)
    items = [(b"sgn-%d" % i, key if i % 2 else key2) for i in range(6)]
    sigs = sd.sign_batch(items)
    for (msg, k), sig in zip(items, sigs):
        assert rsa.verify_host(msg, sig, k.public)
    snap = metrics.snapshot()
    assert snap.get("sign.remote", 0) == 6
    # Two keys, one registration each — handles are reused after.
    assert snap.get("sign.remote_register", 0) == 2
    sd.sign_batch([(b"again", key)])
    assert metrics.snapshot().get("sign.remote_register", 0) == 2


def test_sign_never_remotes_keys_over_plain_tcp(key):
    # Policy, both ends: a plain TCP channel (squatters after a crash)
    # must never carry private keys.  The client never sends them; a
    # hostile/registration-happy client is ST_REFUSED server-side.
    addr = f"127.0.0.1:{_port()}"
    srv, _t = vs.serve(addr)
    try:
        metrics.reset()
        sd = RemoteSignerDomain(addr)
        assert not sd.channel.carries_keys
        sigs = sd.sign_batch([(b"local-only", key)])
        assert rsa.verify_host(b"local-only", sigs[0], key.public)
        assert metrics.snapshot().get("sign.remote", 0) == 0
        # Server-side enforcement for a client that ignores policy:
        chan = SidecarChannel(addr)
        st, _ = chan.request(
            vs.OP_REGISTER, vs.encode_register_request([key])
        )
        assert st == vs.ST_REFUSED
    finally:
        _stop(srv)


def test_sign_over_hmac_tcp(key):
    secret = b"k" * 32
    addr = f"127.0.0.1:{_port()}"
    srv, _t = vs.serve(addr, secret=secret)
    try:
        metrics.reset()
        sd = RemoteSignerDomain(addr, secret=secret)
        assert sd.channel.carries_keys
        sigs = sd.sign_batch([(b"hmac-sign", key)])
        assert rsa.verify_host(b"hmac-sign", sigs[0], key.public)
        assert metrics.snapshot().get("sign.remote", 0) == 1
    finally:
        _stop(srv)


def test_key_budget_exhaustion_is_terminal_not_a_breaker_flap(
    unix_sidecar, key
):
    # Registering past BFTKV_SIDECAR_MAX_KEYS must NOT trip the shared
    # breaker (ERR would re-trip on every retry — a permanent flap
    # that benches verify too): it is REFUSED, terminal for the
    # connection — signing stays local, verify keeps remoting.
    addr, srv = unix_sidecar
    srv.service.max_keys = 1
    metrics.reset()
    chan = SidecarChannel(addr)
    sd = RemoteSignerDomain(addr, channel=chan)
    key2 = rsa.generate(1024)
    sigs = sd.sign_batch([(b"one", key), (b"two", key2)])
    assert rsa.verify_host(b"one", sigs[0], key.public)
    assert rsa.verify_host(b"two", sigs[1], key2.public)
    snap = metrics.snapshot()
    assert snap.get("sign.remote_refused", 0) == 1
    assert snap.get("verify.remote_breaker_open", 0) == 0
    assert not chan.tripped()
    # Verify still remotes on the same channel; signing stays local
    # without ever asking again.
    vd = RemoteVerifierDomain(addr, channel=chan, spot_rate=0)
    assert list(vd.verify_batch([(b"one", sigs[0], key.public)])) == [True]
    assert metrics.snapshot().get("verify.remote", 0) == 1
    sd.sign_batch([(b"three", key)])
    assert metrics.snapshot().get("sign.remote_refused", 0) == 1  # no retry


def test_register_payload_sealed_on_hmac_channel(key):
    # The HMAC frame tag authenticates but does not HIDE — and the
    # client ships keys before any byte proves the peer knows the
    # secret.  The REGISTER payload must therefore be AEAD-sealed: a
    # squatter capturing the frame must not be able to read d/p/q.
    secret = b"w" * 32
    payload = vs.encode_register_request([key])
    sealed = SidecarChannel(
        "127.0.0.1:1", secret=secret
    ).seal_keys(payload)
    for priv in (key.d, key.p, key.q):
        blob = priv.to_bytes((priv.bit_length() + 7) // 8, "big")
        assert blob in payload  # plaintext encoding does carry them
        assert blob not in sealed  # the wire form must not
    assert vs.unwrap_keys(secret, sealed) == payload
    with pytest.raises(Exception):
        vs.unwrap_keys(secret, sealed[:-1] + bytes([sealed[-1] ^ 1]))
    with pytest.raises(Exception):
        vs.unwrap_keys(b"x" * 32, sealed)
    # No secret (unix socket): seal_keys is the identity — the kernel
    # enforces 0600, and the server expects plaintext there.
    assert SidecarChannel("unix:/tmp/x").seal_keys(payload) == payload


def test_forged_signature_caught_by_self_check(unix_sidecar, key):
    # A dishonest sidecar forges a signature: the e=65537 self-check
    # catches it, the breaker opens, crypto.sidecar.dishonest fires,
    # and the batch re-signs locally — callers still get REAL sigs.
    addr, srv = unix_sidecar
    orig = srv.service.sign.submit
    srv.service.sign.submit = lambda items: [
        b"\x00" * 128 for _ in items
    ]
    try:
        metrics.reset()
        sd = RemoteSignerDomain(addr)
        sigs = sd.sign_batch([(b"forge-%d" % i, key) for i in range(3)])
        for i, sig in enumerate(sigs):
            assert rsa.verify_host(b"forge-%d" % i, sig, key.public)
        snap = metrics.snapshot()
        assert snap.get("crypto.sidecar.dishonest", 0) >= 1
        assert snap.get("sign.remote_fallback", 0) == 3
        assert sd.channel.tripped()
    finally:
        srv.service.sign.submit = orig


# -- verify spot-check ------------------------------------------------------


def test_wrong_verdict_trips_spot_check(unix_sidecar, key):
    # The planted wrong-verdict sidecar double: verdicts inverted.  A
    # spot-checking client must catch it, fall back to LOCAL verdicts
    # (correct ones), open the breaker, and raise the dishonest
    # counter the fleet maps to sidecar_dishonest.
    addr, srv = unix_sidecar
    orig = srv.dispatcher.verify
    srv.dispatcher.verify = lambda items: [
        not v for v in orig(items)
    ]
    try:
        metrics.reset()
        rd = RemoteVerifierDomain(addr, spot_rate=1.0)
        items = [
            (b"sv-%d" % i, rsa.sign(b"sv-%d" % i, key), key.public)
            for i in range(4)
        ]
        assert list(rd.verify_batch(items)) == [True] * 4
        snap = metrics.snapshot()
        assert snap.get("crypto.sidecar.dishonest", 0) >= 1
        assert snap.get("verify.remote_fallback", 0) == 4
        assert rd.channel.tripped()
    finally:
        srv.dispatcher.verify = orig


def test_honest_verdicts_pass_spot_check(unix_sidecar, key):
    addr, _srv = unix_sidecar
    metrics.reset()
    rd = RemoteVerifierDomain(addr, spot_rate=1.0)
    sig = rsa.sign(b"ok", key)
    bad = bytes([sig[0] ^ 1]) + sig[1:]
    items = [(b"ok", sig, key.public), (b"ok", bad, key.public)]
    assert list(rd.verify_batch(items)) == [True, False]
    snap = metrics.snapshot()
    assert snap.get("verify.spot_check", 0) >= 1
    assert snap.get("crypto.sidecar.dishonest", 0) == 0
    assert not rd.channel.tripped()


# -- modexp -----------------------------------------------------------------


def test_modexp_roundtrip_and_spot_check(unix_sidecar):
    addr, _srv = unix_sidecar
    metrics.reset()
    md = RemoteModexpDomain(addr, spot_rate=1.0)
    items = [
        (3, 65537, (1 << 127) - 1),
        (12345, 1 << 20, (1 << 255) - 19),
        (7, 0, 97),
    ]
    assert md.powmod_batch(items) == [pow(*it) for it in items]
    assert metrics.snapshot().get("modexp.remote", 0) == 3
    assert md.powmod(5, 3, 7) == pow(5, 3, 7)


def test_dishonest_modexp_caught(unix_sidecar):
    addr, srv = unix_sidecar
    orig = srv.service.modexp.submit
    srv.service.modexp.submit = lambda items: [
        v + 1 for v in orig(items)
    ]
    try:
        metrics.reset()
        md = RemoteModexpDomain(addr, spot_rate=1.0)
        items = [(3, 65537, (1 << 89) - 1)]
        assert md.powmod_batch(items) == [pow(*items[0])]
        assert metrics.snapshot().get("crypto.sidecar.dishonest", 0) >= 1
        assert md.channel.tripped()
    finally:
        srv.service.modexp.submit = orig


# -- backpressure / shedding ------------------------------------------------


def test_admission_sheds_past_bounds(tmp_path, key):
    # max_inflight=1, no waiters allowed: with one batch stalled in
    # service, a second concurrent batch is shed instantly (ST_SHED →
    # local fallback) WITHOUT opening the breaker — overload is not
    # failure.
    addr = f"unix:{tmp_path}/shed.sock"
    srv, _t = vs.serve(
        addr,
        admission=AdmissionQueue(
            max_inflight=1, max_queue=0, max_wait=0.05,
            metric="sidecar.shed",
        ),
    )
    release = threading.Event()
    orig = srv.dispatcher.verify

    def slow(items):
        release.wait(5)
        return orig(items)

    srv.dispatcher.verify = slow
    try:
        metrics.reset()
        items = [(b"sh", rsa.sign(b"sh", key), key.public)]
        r1 = RemoteVerifierDomain(addr, spot_rate=0.0)
        r2 = RemoteVerifierDomain(addr, spot_rate=0.0)
        out1 = []
        t = threading.Thread(
            target=lambda: out1.append(list(r1.verify_batch(items)))
        )
        t.start()
        time.sleep(0.3)  # let batch 1 occupy the only service slot
        assert list(r2.verify_batch(items)) == [True]  # shed → local
        release.set()
        t.join(10)
        assert out1 == [[True]]
        snap = metrics.snapshot()
        assert snap.get("verify.remote_shed", 0) >= 1
        assert snap.get("sidecar.shed{op=verify}", 0) >= 1
        assert srv.service.admission.shed >= 1
        assert not r2.channel.tripped()
    finally:
        srv.dispatcher.verify = orig
        release.set()
        _stop(srv)


# -- kill -9 mid-traffic ----------------------------------------------------


def test_sidecar_death_mid_traffic_zero_failed_writes(tmp_path, key):
    # The acceptance scenario: a 4-node cluster signs+verifies through
    # the sidecar; the sidecar dies mid-traffic; every write still
    # commits (local crypto fallback), the breaker opens, and after it
    # lapses a restarted sidecar serves again with RE-REGISTERED
    # sign-key handles on a fresh connection.
    from tests.cluster_utils import start_cluster

    addr = f"unix:{tmp_path}/kill.sock"
    srv, _t = vs.serve(addr)
    chan = SidecarChannel(addr, breaker_seconds=0.5)
    dispatch.install(
        dispatch.VerifyDispatcher(
            verifier=RemoteVerifierDomain(channel=chan), calibrate=False
        )
    )
    dispatch.install_signer(
        dispatch.SignDispatcher(
            signer=RemoteSignerDomain(channel=chan),
            calibrate=False,
            max_wait=0.002,
        )
    )
    c = start_cluster(4, 1, 4)
    try:
        cl = c.clients[0]
        metrics.reset()
        assert cl.write(b"sc/pre", b"v0") is None
        snap = metrics.snapshot()
        assert snap.get("sign.remote", 0) > 0  # signing really remoted

        # kill -9: listener gone, socket unlinked, connection severed.
        _stop(srv)
        os.unlink(f"{tmp_path}/kill.sock")
        chan.close()
        for i in range(4):
            assert cl.write(b"sc/during/%d" % i, b"v%d" % i) is None
            assert cl.read(b"sc/during/%d" % i) == b"v%d" % i
        snap = metrics.snapshot()
        assert snap.get("verify.remote_breaker_open", 0) >= 1

        # Restart on the same path; the breaker lapses on its own.
        srv2, _ = vs.serve(addr)
        try:
            time.sleep(0.6)
            reg0 = metrics.snapshot().get("sign.remote_register", 0)
            deadline = time.time() + 10
            while time.time() < deadline:
                assert cl.write(b"sc/after", b"v9") is None
                if metrics.snapshot().get("sign.remote_register", 0) > reg0:
                    break
            snap = metrics.snapshot()
            assert snap.get("sign.remote_register", 0) > reg0
        finally:
            _stop(srv2)
    finally:
        dispatch.uninstall_all()
        c.stop()


def test_cluster_write_commits_despite_dishonest_sidecar(tmp_path, key):
    # Acceptance: a planted dishonest sidecar (forged signatures AND
    # inverted verdicts) is caught by the self-check/spot-check path,
    # the breaker opens, and the write still commits via local crypto.
    from tests.cluster_utils import start_cluster

    addr = f"unix:{tmp_path}/evil.sock"
    srv, _t = vs.serve(addr)
    orig_verify = srv.dispatcher.verify
    orig_sign = srv.service.sign.submit
    srv.dispatcher.verify = lambda items: [not v for v in orig_verify(items)]
    srv.service.sign.submit = lambda items: [b"\x00" * 64 for _ in items]
    chan = SidecarChannel(addr)
    dispatch.install(
        dispatch.VerifyDispatcher(
            verifier=RemoteVerifierDomain(channel=chan, spot_rate=1.0),
            calibrate=False,
        )
    )
    dispatch.install_signer(
        dispatch.SignDispatcher(
            signer=RemoteSignerDomain(channel=chan),
            calibrate=False,
            max_wait=0.002,
        )
    )
    c = start_cluster(4, 1, 4)
    try:
        cl = c.clients[0]
        metrics.reset()
        assert cl.write(b"evil/x", b"payload") is None
        assert cl.read(b"evil/x") == b"payload"
        snap = metrics.snapshot()
        assert snap.get("crypto.sidecar.dishonest", 0) >= 1
        assert chan.tripped()
    finally:
        srv.dispatcher.verify = orig_verify
        srv.service.sign.submit = orig_sign
        dispatch.uninstall_all()
        c.stop()
        _stop(srv)


# -- cross-tenant coalescing ------------------------------------------------


def test_sign_batches_coalesce_across_connections(tmp_path, key):
    # Two tenant channels submit concurrently into one service: the
    # sidecar's sign dispatcher must coalesce them (occupancy > 1 per
    # launch for at least one flush) — the bench criterion's unit
    # form.
    addr = f"unix:{tmp_path}/coal.sock"
    srv, _t = vs.serve(addr, max_wait=0.3)
    # Widen the sign window too: deterministic coalescing on a loaded
    # 1-core box needs a generous collection window.
    srv.service.sign.max_wait = 0.3
    try:
        metrics.reset()
        doms = [RemoteSignerDomain(addr) for _ in range(2)]
        outs = [None, None]

        def run(i):
            outs[i] = doms[i].sign_batch(
                [(b"ct-%d-%d" % (i, j), key) for j in range(8)]
            )

        ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i, sigs in enumerate(outs):
            for j, sig in enumerate(sigs):
                assert rsa.verify_host(b"ct-%d-%d" % (i, j), sig, key.public)
        snap = metrics.snapshot()
        items = snap.get("signdispatch.items", 0)
        flushes = snap.get("signdispatch.flushes", 1)
        assert items >= 16
        assert items / flushes > 1  # cross-tenant coalescing happened
    finally:
        _stop(srv)


# -- stats + fleet scrape ---------------------------------------------------


def test_stats_endpoint_and_fleet_scrape(tmp_path, key):
    from bftkv_tpu.obs import FleetCollector, HTTPSource

    addr = f"unix:{tmp_path}/stats.sock"
    stats = f"127.0.0.1:{_port()}"
    srv, _t = vs.serve(addr, stats=stats, name="sidecar01")
    try:
        rd = RemoteVerifierDomain(addr, spot_rate=0.0)
        items = [(b"st", rsa.sign(b"st", key), key.public)]
        assert list(rd.verify_batch(items)) == [True]

        with urllib.request.urlopen(
            f"http://{stats}/info", timeout=10
        ) as r:
            info = json.loads(r.read())
        assert info["role"] == "sidecar"
        assert info["sidecar"]["queue"]["shed"] == 0
        assert info["sidecar"]["ops"]["verify"] >= 1
        with urllib.request.urlopen(
            f"http://{stats}/metrics?format=json", timeout=10
        ) as r:
            snap = json.loads(r.read())
        assert isinstance(snap, dict)

        # The collector files it as role=sidecar: OUTSIDE every shard
        # f-budget, reported under health()["sidecars"].
        col = FleetCollector([HTTPSource(stats, name="sidecar01")])
        doc = col.scrape_once()
        assert "sidecar01" in doc["sidecars"]
        assert doc["sidecars"]["sidecar01"]["status"] == "up"
        assert all(
            "sidecar01" not in [m["name"] for m in sd["members"]]
            for sd in doc["shards"].values()
        )
        prom = col.prometheus()
        assert "bftkv_fleet_sidecars_up 1" in prom
    finally:
        _stop(srv)


def test_stats_frame_over_socket(unix_sidecar, key):
    addr, _srv = unix_sidecar
    chan = SidecarChannel(addr)
    st = chan.stats()
    assert st is not None and "queue" in st and "batch" in st


# -- codec hostility --------------------------------------------------------


def test_v2_codecs_roundtrip(key):
    pairs = [(7, b"msg-a"), (9, b"")]
    assert vs.decode_sign_request(vs.encode_sign_request(pairs)) == pairs
    keys = vs.decode_register_request(vs.encode_register_request([key]))
    assert (keys[0].n, keys[0].d) == (key.n, key.d)
    items = [(123, 456, 789), (0, 0, 5)]
    assert vs.decode_modexp_request(vs.encode_modexp_request(items)) == items
    with pytest.raises(Exception):
        vs.decode_register_request(b"\xff\xff\xff\xff garbage")


def test_malformed_v2_frame_is_err_not_verdict(unix_sidecar):
    # Hostile payload bytes on an op frame: the tenant sees ST_ERR and
    # falls back to local crypto — never a fabricated "valid" answer.
    addr, _srv = unix_sidecar
    chan = SidecarChannel(addr)
    st, payload = chan.request(vs.OP_SIGN, b"\xff\xff\xff\xff junk")
    assert st == vs.ST_ERR and payload == b""
    st, _ = chan.request(vs.OP_MODEXP, b"\x00")
    assert st == vs.ST_ERR
