"""2-shard loopback cluster smoke (tier-1): one client-visible
keyspace, hash-routed across two disjoint quorum cliques.

Covers the full keyed path end to end: routed writes/reads, storage
placement (a shard's records never land on the other shard's
replicas), the wrong-shard admission gate, batched write/read shard
grouping, and the shard-aware anti-entropy plane.
"""

import pytest

from bftkv_tpu import quorum as qm
from bftkv_tpu.errors import ERR_WRONG_SHARD
from bftkv_tpu.sync import SyncDaemon, admit_records
from tests.cluster_utils import start_cluster


@pytest.fixture(scope="module")
def cluster():
    cl = start_cluster(4, 1, 4, bits=1024, n_shards=2)
    yield cl
    cl.stop()


def keys_per_shard(client, count=1, tag=b"k"):
    """{shard index: [keys]} with ``count`` keys per shard."""
    out: dict = {}
    i = 0
    while (
        min((len(v) for v in out.values()), default=0) < count
        or len(out) < 2
    ) and i < 4096:
        k = b"shard/%s/%d" % (tag, i)
        out.setdefault(client.qs.shard_of(k), []).append(k)
        i += 1
    return out


def shard_servers(cluster, idx):
    return [
        s
        for s in cluster.all_servers
        if s.qs.my_shard() == idx
    ]


def test_write_read_across_shards(cluster):
    c = cluster.clients[0]
    assert c.qs.shard_count() == 2
    ks = keys_per_shard(c, count=2)
    assert set(ks) == {0, 1}
    for idx, keys in ks.items():
        for k in keys:
            c.write(k, b"v-" + k)
    for idx, keys in ks.items():
        for k in keys:
            assert c.read(k) == b"v-" + k


def test_storage_placement(cluster):
    c = cluster.clients[0]
    ks = keys_per_shard(c, tag=b"place")
    for idx, keys in ks.items():
        k = keys[0]
        c.write(k, b"placed")
        other = 1 - idx
        for srv in shard_servers(cluster, other):
            with pytest.raises(Exception):
                srv.storage.read(k, 0)
        # ...and at least one replica of the owner shard has it.
        assert any(
            _has(srv, k) for srv in shard_servers(cluster, idx)
        ), (idx, k)


def _has(srv, k):
    try:
        srv.storage.read(k, 0)
        return True
    except Exception:
        return False


def test_wrong_shard_admission_rejected(cluster):
    c = cluster.clients[0]
    ks = keys_per_shard(c, tag=b"adm")
    for idx, keys in ks.items():
        k = keys[0]
        for srv in shard_servers(cluster, 1 - idx):
            with pytest.raises(ERR_WRONG_SHARD):
                srv._time(k, None, None)


def test_batched_paths_split_by_shard(cluster):
    c = cluster.clients[0]
    ks = keys_per_shard(c, count=3, tag=b"batch")
    items = [(k, b"b-" + k) for keys in ks.values() for k in keys]
    assert len({c.qs.shard_of(k) for k, _v in items}) == 2
    errs = c.write_many(items)
    assert errs == [None] * len(items)
    got = c.read_many([k for k, _v in items])
    assert got == [v for _k, v in items]


def test_keyed_quorum_nodes_stay_in_shard(cluster):
    c = cluster.clients[0]
    ks = keys_per_shard(c, tag=b"quorum")
    for idx, keys in ks.items():
        k = keys[0]
        for rw in (qm.READ | qm.AUTH, qm.AUTH | qm.PEER, qm.WRITE):
            nodes = qm.choose_quorum_for(c.qs, k, rw).nodes()
            assert nodes
            for n in nodes:
                assert c.qs.shard_index_of(n.id) == idx, (
                    k, rw, n.name,
                )


def test_sync_verify_quorum_is_keyed(cluster):
    """A storage node's UNKEYED AUTH quorum holds both cliques as
    separate QCs and ``is_sufficient`` is any-QC — so a foreign
    clique's signature threshold would pass it.  The sync plane (and
    every other admission path) must therefore verify against the
    keyed owner quorum, where the foreign clique counts for nothing."""
    c = cluster.clients[0]
    ks = keys_per_shard(c, tag=b"keyedq")
    rw_a = next(
        s for s in cluster.storage_servers if s.qs.my_shard() == 0
    )
    k = ks[0][0]  # owned by rw_a's shard
    topo = rw_a.qs._topology()
    b_clique = [
        n
        for n in rw_a.self_node.get_peers()
        if topo.member.get(n.id) == 1
    ]
    assert len(b_clique) == 4
    # The laundering hole the keyed quorum closes: unkeyed accepts the
    # foreign clique's threshold...
    assert rw_a.qs.choose_quorum(qm.AUTH).is_sufficient(b_clique)
    # ...the keyed owner quorum does not.
    qa = qm.choose_quorum_for(rw_a.qs, k, qm.AUTH)
    assert not qa.is_sufficient(b_clique)
    assert not qa.is_threshold(b_clique)


def test_sync_plane_is_shard_aware(cluster):
    c = cluster.clients[0]
    ks = keys_per_shard(c, tag=b"sync")
    # Something synced exists in both shards.
    for idx, keys in ks.items():
        c.write(keys[0], b"sync-seed")
    c.drain_tails()  # sync moves CERTIFIED records; settle the tails
    rw_a = next(
        s
        for s in cluster.storage_servers
        if s.qs.my_shard() == 0
    )
    # 1. peer selection: only same-shard replicas are polled.
    daemon = SyncDaemon(rw_a, interval=999)
    for peer in daemon._peers():
        assert rw_a.qs.shard_index_of(peer.id) in (None, 0)
    # 2. a foreign shard's completed record dies in admission.
    rw_b = next(
        s
        for s in cluster.storage_servers
        if s.qs.my_shard() == 1
    )
    k_b = ks[1][0]
    raw = rw_b.storage.read(k_b, 0)
    stats = admit_records(rw_a, [raw])
    assert stats["rejected"] == 1 and stats["admitted"] == 0
    # ...while replaying an owned record is a clean no-op.
    k_a = ks[0][0]
    raw_a = rw_a.storage.read(k_a, 0)
    stats = admit_records(rw_a, [raw_a])
    assert stats["rejected"] == 0
    # 3. a full round against live same-shard peers converges clean.
    got = daemon.run_round()
    assert got["rejected"] == 0


def test_shard_labels_are_a_closed_enum(cluster):
    """Label hygiene for the routing plane (PR 2's cardinality rule
    applied to the new ``shard`` labels): after routed traffic plus a
    wrong-shard rejection, every ``shard=`` label value across the
    whole registry is a shard index — an integer below the shard
    count — so the label space is bounded by topology, never by keys,
    peers, or request volume."""
    from bftkv_tpu.metrics import registry
    from bftkv_tpu.obs.collector import parse_flat_key

    c = cluster.clients[0]
    nsh = c.qs.shard_count()
    # the registry is process-global: flush residue from earlier tests
    # (a wider topology would leave higher shard indices behind)
    registry.reset()
    ks = keys_per_shard(c, tag=b"labels")
    for idx, keys in ks.items():
        c.write(keys[0], b"labeled")
        c.read(keys[0])
    # drive the wrong-shard gate so server.wrong_shard{shard=} exists
    k0 = ks[0][0]
    srv = shard_servers(cluster, 1)[0]
    with pytest.raises(ERR_WRONG_SHARD):
        srv._time(k0, None, None)

    snap = registry.snapshot()
    shard_series = {}
    for key in snap:
        name, labels = parse_flat_key(key)
        if "shard" in labels:
            shard_series.setdefault(name, set()).add(labels["shard"])
    # the three routed hot-path families all carry the label...
    assert any(n.startswith("quorum.route.shard") for n in shard_series)
    assert any(n.startswith("server.wrong_shard") for n in shard_series)
    assert any(
        n.startswith("client.write.latency") for n in shard_series
    )
    # ...and every value anywhere is a bounded shard index
    for name, values in shard_series.items():
        for v in values:
            assert v.isdigit() and int(v) < nsh, (name, v)
