"""Byzantine scenarios: collusion/equivocation with revocation of
double-signers, honest-reader convergence
(reference: protocol/mal_test.go:23-71, malclient_test.go,
malserver_test.go; BASELINE's "zero additional safety violations")."""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import topology
from bftkv_tpu.errors import Error
from bftkv_tpu.transport.loopback import TrLoopback

from cluster_utils import start_cluster
from mal_utils import MalClient, MalServer, MalStorage

BITS = 2048
N_SERVERS = 7
N_RW = 6


@pytest.fixture()
def mal_cluster():
    c = start_cluster(
        n_servers=N_SERVERS,
        n_users=2,
        n_rw=N_RW,
        bits=BITS,
        server_cls=MalServer,
        storage_factory=MalStorage,
    )
    # colluders: the last 3 quorum servers + the last 2 storage nodes
    mal = {i.cert.address for i in c.universe.servers[-3:]}
    mal |= {i.cert.address for i in c.universe.storage_nodes[-2:]}
    MalServer.mal_addresses = mal
    try:
        yield c, mal
    finally:
        MalServer.mal_addresses = set()
        c.stop()


def test_collusion_convergence_and_revocation(mal_cluster):
    """A malicious client + colluding servers equivocate <x,t,v>/<x,t,v'>;
    an honest reader still converges to a single value and revokes the
    double-signers (reference: mal_test.go:23-71)."""
    c, mal = mal_cluster
    uni = c.universe

    # the equivocator drives user 0's identity
    evil_ident = uni.users[0]
    graph, crypt, qs = topology.make_node(evil_ident, uni.view_of(evil_ident))
    evil = MalClient(
        graph, qs, TrLoopback(crypt, c.net), crypt, mal_addresses=mal
    )
    evil.write_mal(b"mal_var", b"value-one", b"value-two")

    # an honest reader converges (one of the two equivocated values)
    honest = c.clients[1]
    value = honest.read(b"mal_var")
    assert value in (b"value-one", b"value-two")

    # … and revokes every signer that signed both values: the colluding
    # quorum servers (their shares are in both collective signatures)
    deadline = time.time() + 5
    mal_server_ids = {i.cert.id for i in uni.servers[-3:]}
    while time.time() < deadline:
        revoked = set(honest.self_node.revoked)
        if mal_server_ids <= revoked:
            break
        time.sleep(0.05)
    assert mal_server_ids <= set(honest.self_node.revoked), (
        "colluding double-signers must be revoked on read"
    )
    # the equivocating writer signed both values too
    assert evil_ident.cert.id in honest.self_node.revoked


def test_honest_write_survives_colluders(mal_cluster):
    """With ≤f colluders misbehaving, honest quorum writes/reads still
    succeed (the b-masking guarantee)."""
    c, mal = mal_cluster
    honest = c.clients[1]
    honest.write(b"sane_var", b"sane value")
    assert honest.read(b"sane_var") == b"sane value"


def test_honest_batch_write_survives_colluders(mal_cluster):
    """The batched pipeline under the same adversary: colluders sign and
    store every item unverified, honest replicas still enforce the full
    checks, and the b-masking quorum carries the batch through."""
    c, mal = mal_cluster
    honest = c.clients[1]
    items = [(b"sane_batch/%d" % i, b"batch value %d" % i) for i in range(12)]
    assert honest.write_many(items) == [None] * 12
    for var, val in items:
        assert honest.read(var) == val
    # A second batch updates the same variables at t+1 — the colluders'
    # stored garbage must not poison the timestamp phase.
    items2 = [(v, b"updated " + val) for v, val in items]
    assert honest.write_many(items2) == [None] * 12
    assert honest.read(b"sane_batch/0") == b"updated batch value 0"


def test_same_uid_may_overwrite(mal_cluster):
    """TOFU allows a different key with the SAME uid to overwrite
    (reference: server.go:329-337 — id *or* uid match; mal_test.go
    TestTOFU 'trusted entity overwrite successful (same UId)')."""
    c, _ = mal_cluster
    uni = c.universe
    owner = c.clients[1]
    owner.write(b"tofu_uid_var", b"original")

    # a fresh identity with the same uid, counter-signed by the quorum
    u2 = uni.users[1]
    alias = topology.new_identity("alias", uid=u2.cert.uid, bits=BITS)
    for s in uni.servers[-3:]:
        topology.sign(s, alias)
    uni.users.append(alias)
    try:
        graph, crypt, qs = topology.make_node(alias, uni.view_of(alias))
        twin = type(owner)(graph, qs, TrLoopback(crypt, c.net), crypt)
        # servers must learn the alias cert (gossip, as a real client would)
        twin.joining()
        twin.write(b"tofu_uid_var", b"overwritten by same uid")
        assert twin.read(b"tofu_uid_var") == b"overwritten by same uid"
    finally:
        uni.users.remove(alias)
