"""Byzantine scenarios: collusion/equivocation with revocation of
double-signers, honest-reader convergence
(reference: protocol/mal_test.go:23-71, malclient_test.go,
malserver_test.go; BASELINE's "zero additional safety violations")."""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import topology
from bftkv_tpu.transport.loopback import TrLoopback

from cluster_utils import start_cluster
from mal_utils import MalClient, MalServer, MalStorage

BITS = 2048
N_SERVERS = 7
N_RW = 6


@pytest.fixture()
def mal_cluster():
    c = start_cluster(
        n_servers=N_SERVERS,
        n_users=2,
        n_rw=N_RW,
        bits=BITS,
        server_cls=MalServer,
        storage_factory=MalStorage,
    )
    # colluders: the last 3 quorum servers + the last 2 storage nodes
    mal = {i.cert.address for i in c.universe.servers[-3:]}
    mal |= {i.cert.address for i in c.universe.storage_nodes[-2:]}
    MalServer.mal_addresses = mal
    try:
        yield c, mal
    finally:
        MalServer.mal_addresses = set()
        c.stop()


def test_collusion_convergence_and_revocation(mal_cluster):
    """A malicious client + colluding servers equivocate <x,t,v>/<x,t,v'>;
    an honest reader still converges to a single value and revokes the
    double-signers (reference: mal_test.go:23-71)."""
    c, mal = mal_cluster
    uni = c.universe

    # the equivocator drives user 0's identity
    evil_ident = uni.users[0]
    graph, crypt, qs = topology.make_node(evil_ident, uni.view_of(evil_ident))
    evil = MalClient(
        graph, qs, TrLoopback(crypt, c.net), crypt, mal_addresses=mal
    )
    evil.write_mal(b"mal_var", b"value-one", b"value-two")

    # an honest reader converges (one of the two equivocated values)
    honest = c.clients[1]
    value = honest.read(b"mal_var")
    assert value in (b"value-one", b"value-two")

    # … and revokes every signer that signed both values: the colluding
    # quorum servers (their shares are in both collective signatures)
    deadline = time.time() + 5
    mal_server_ids = {i.cert.id for i in uni.servers[-3:]}
    while time.time() < deadline:
        revoked = set(honest.self_node.revoked)
        if mal_server_ids <= revoked:
            break
        time.sleep(0.05)
    assert mal_server_ids <= set(honest.self_node.revoked), (
        "colluding double-signers must be revoked on read"
    )
    # the equivocating writer signed both values too
    assert evil_ident.cert.id in honest.self_node.revoked


def test_honest_write_survives_colluders(mal_cluster):
    """With ≤f colluders misbehaving, honest quorum writes/reads still
    succeed (the b-masking guarantee)."""
    c, mal = mal_cluster
    honest = c.clients[1]
    honest.write(b"sane_var", b"sane value")
    assert honest.read(b"sane_var") == b"sane value"


def test_honest_batch_write_survives_colluders(mal_cluster):
    """The batched pipeline under the same adversary: colluders sign and
    store every item unverified, honest replicas still enforce the full
    checks, and the b-masking quorum carries the batch through."""
    c, mal = mal_cluster
    honest = c.clients[1]
    items = [(b"sane_batch/%d" % i, b"batch value %d" % i) for i in range(12)]
    assert honest.write_many(items) == [None] * 12
    for var, val in items:
        assert honest.read(var) == val
    # A second batch updates the same variables at t+1 — the colluders'
    # stored garbage must not poison the timestamp phase.
    items2 = [(v, b"updated " + val) for v, val in items]
    assert honest.write_many(items2) == [None] * 12
    assert honest.read(b"sane_batch/0") == b"updated batch value 0"


def test_high_t_liar_cannot_starve_reads(mal_cluster):
    """A replica answering reads with an unsigned fabricated higher-t
    value must not fail the read once the full fan-out is in: the
    highest *threshold-reaching* timestamp wins (the liar's lone bucket
    never reaches threshold).  The reference only checks the global max
    t, so there this liar starves reads whenever its response arrives
    early — a liveness (not safety) gap this framework closes."""
    from bftkv_tpu import packet as pkt

    c, _ = mal_cluster
    honest = c.clients[1]
    honest.write(b"liar_var", b"the truth")
    honest.write_many([(b"liar_batch/%d" % i, b"t-%d" % i) for i in range(4)])

    victim = c.storage_servers[0]
    orig_read_item = victim._read_item
    orig_batch_read = victim._batch_read

    def lying_read_item(variable, proof):
        return pkt.serialize(variable, b"FORGED", 2**40, None, None)

    def lying_batch_read(req, peer, sender):
        items = pkt.parse_list(req)
        fake = pkt.serialize(b"x", b"FORGED", 2**40, None, None)
        return pkt.serialize_results([(None, fake)] * len(items))

    victim._read_item = lying_read_item
    victim._batch_read = lying_batch_read
    try:
        for _ in range(5):  # deterministic regardless of arrival order
            assert honest.read(b"liar_var") == b"the truth"
            got = honest.read_many([b"liar_batch/%d" % i for i in range(4)])
            assert got == [b"t-%d" % i for i in range(4)]
    finally:
        victim._read_item = orig_read_item
        victim._batch_read = orig_batch_read


def test_lone_signed_newest_value_wins_over_stale_threshold(mal_cluster):
    """One replica holding the newest value with its *completed
    collective signature* beats a stale threshold: the reader accepts
    the cryptographically quorum-endorsed packet and completes the
    in-flight write rather than serving (or failing to) the old value.
    An unsigned fabrication in the same position is rejected (see
    test_high_t_liar_cannot_starve_reads)."""
    from bftkv_tpu import packet as pkt

    c, _ = mal_cluster
    honest = c.clients[1]
    honest.write(b"ur_var", b"old")
    honest.write(b"ur_var", b"newest")
    honest.drain_tails()  # the scenario needs the CERTIFIED newest record

    # Simulate under-replication of the newest write: every READ-quorum
    # replica except one is rolled back to the old committed state.
    keepers = c.storage_servers
    newest_raw = keepers[0].storage.read(b"ur_var", 0)
    np_ = pkt.parse(newest_raw)
    assert np_.value == b"newest" and np_.ss is not None and np_.ss.completed
    for srv in keepers[1:]:
        old_raw = srv.storage.read(b"ur_var", np_.t - 1)
        srv.storage.write(b"ur_var", np_.t, old_raw)  # shadow newest
    # Their latest is now the old value again (at the old timestamp
    # semantics: latest = max t, so rewrite under t with old content).
    got = honest.read(b"ur_var")
    assert got == b"newest", got
    assert honest.read_many([b"ur_var"]) == [b"newest"]


def test_signed_other_variable_cannot_substitute(mal_cluster):
    """A Byzantine replica answering read(x) with a *genuinely signed*
    packet for a different variable y (higher t) must not have y's
    value served for x: responses are bound to the requested variable
    before any bucket — threshold or signature — can accept them."""
    from bftkv_tpu import packet as pkt

    c, _ = mal_cluster
    honest = c.clients[1]
    honest.write(b"sub_x", b"x-value")
    for _ in range(3):  # drive y's timestamp above x's
        honest.write(b"sub_y", b"y-value")

    victim = c.storage_servers[0]
    y_packet = victim.storage.read(b"sub_y", 0)
    assert pkt.parse(y_packet).t > pkt.parse(
        victim.storage.read(b"sub_x", 0)
    ).t
    orig = victim._read_item

    def substituting_read_item(variable, proof):
        if variable == b"sub_x":
            return y_packet  # genuine quorum-signed packet — for y
        return orig(variable, proof)

    victim._read_item = substituting_read_item
    try:
        for _ in range(5):
            assert honest.read(b"sub_x") == b"x-value"
            assert honest.read_many([b"sub_x"]) == [b"x-value"]
    finally:
        victim._read_item = orig


def test_same_uid_may_overwrite(mal_cluster):
    """TOFU allows a different key with the SAME uid to overwrite
    (reference: server.go:329-337 — id *or* uid match; mal_test.go
    TestTOFU 'trusted entity overwrite successful (same UId)')."""
    c, _ = mal_cluster
    uni = c.universe
    owner = c.clients[1]
    owner.write(b"tofu_uid_var", b"original")
    owner.drain_tails()  # certified ownership before the alias overwrite

    # a fresh identity with the same uid, counter-signed by the quorum
    u2 = uni.users[1]
    alias = topology.new_identity("alias", uid=u2.cert.uid, bits=BITS)
    for s in uni.servers[-3:]:
        topology.sign(s, alias)
    uni.users.append(alias)
    try:
        graph, crypt, qs = topology.make_node(alias, uni.view_of(alias))
        twin = type(owner)(graph, qs, TrLoopback(crypt, c.net), crypt)
        # servers must learn the alias cert (gossip, as a real client would)
        twin.joining()
        twin.write(b"tofu_uid_var", b"overwritten by same uid")
        assert twin.read(b"tofu_uid_var") == b"overwritten by same uid"
    finally:
        uni.users.remove(alias)
