"""Flight recorder (bftkv_tpu/obs/recorder): the anomaly→bundle path,
window coalescing, the rate limit and disk caps, and the contract that
a bundle opens with plain stdlib json and no live fleet."""

from __future__ import annotations

import json
import os
import time

from bftkv_tpu.obs import FleetCollector
from bftkv_tpu.obs.recorder import FlightRecorder, read_manifest


def _emit(coll, kind="member_down", source="a01", shard=0,
          detail="probe failed"):
    coll._emit(kind, source, shard, detail)


# -- anomaly -> bundle ------------------------------------------------------


def test_anomaly_mints_bundle_whose_manifest_names_it(tmp_path):
    coll = FleetCollector([])
    rec = FlightRecorder(
        str(tmp_path / "bb"), min_interval_s=3600
    ).add_to(coll)
    assert coll.recorder is rec  # /fleet/bundle's demand seam
    _emit(coll)
    bundles = rec.bundles()
    assert len(bundles) == 1 and rec.bundle_count == 1
    man = read_manifest(bundles[0])
    assert man["reason"] == "member_down"
    assert [a["kind"] for a in man["anomalies"]] == ["member_down"]
    assert man["anomalies"][0]["source"] == "a01"
    # the manifest inventories every file with its true size, and each
    # JSON feed parses with nothing but the stdlib — no live fleet, no
    # bftkv import needed to open a black box
    assert man["files"] and man["bytes"] == sum(man["files"].values())
    for name, size in man["files"].items():
        p = os.path.join(bundles[0], name)
        assert os.path.getsize(p) == size
        if name.endswith(".json"):
            with open(p) as f:
                json.load(f)
    for expected in ("traces.json", "metrics.json", "anomalies.json",
                     "failpoints.json"):
        assert expected in man["files"]


def test_same_window_anomalies_amend_not_mint(tmp_path):
    coll = FleetCollector([])
    rec = FlightRecorder(
        str(tmp_path / "bb"), min_interval_s=3600
    ).add_to(coll)
    _emit(coll, "member_down")
    _emit(coll, "gray_member", detail="a02 straggling")
    assert len(rec.bundles()) == 1
    assert rec.coalesced == 1
    man = read_manifest(rec.bundles()[0])
    assert [a["kind"] for a in man["anomalies"]] == [
        "member_down", "gray_member",
    ]
    assert "amended_ts" in man


def test_rate_limit_window_expiry_mints_fresh_bundle(tmp_path):
    coll = FleetCollector([])
    rec = FlightRecorder(
        str(tmp_path / "bb"), min_interval_s=0.05
    ).add_to(coll)
    _emit(coll)
    time.sleep(0.08)  # outside min_interval: a new event, a new box
    _emit(coll, "slo_burn")
    assert len(rec.bundles()) == 2
    assert rec.coalesced == 0


def test_mark_window_opens_fresh_epoch(tmp_path):
    # The nemesis contract: back-to-back fault windows never share a
    # bundle even when the rate limit would have coalesced them, and
    # within one window every follow-up amends.
    coll = FleetCollector([])
    rec = FlightRecorder(
        str(tmp_path / "bb"), min_interval_s=3600
    ).add_to(coll)
    rec.mark_window()
    _emit(coll, "member_down")
    _emit(coll, "member_down", source="a02")
    rec.mark_window()
    _emit(coll, "gray_member")
    bundles = rec.bundles()
    assert len(bundles) == 2
    assert rec.coalesced == 1
    kinds = [
        [a["kind"] for a in read_manifest(b)["anomalies"]]
        for b in bundles
    ]
    assert kinds == [["member_down", "member_down"], ["gray_member"]]


# -- disk bounds ------------------------------------------------------------


def test_bundle_count_cap_evicts_oldest(tmp_path):
    rec = FlightRecorder(str(tmp_path / "bb"), max_bundles=3)
    for i in range(6):
        rec.snapshot(reason=f"r{i}")
        time.sleep(0.002)  # distinct millisecond stamps
    bundles = rec.bundles()
    assert len(bundles) == 3
    # oldest evicted first; the black box keeps the recent past
    assert [b.rsplit("-", 1)[1] for b in bundles] == ["r3", "r4", "r5"]
    assert rec.bundle_count == 6  # created, not surviving


def test_byte_cap_keeps_at_least_the_newest(tmp_path):
    rec = FlightRecorder(str(tmp_path / "bb"), max_bytes=1)
    a = rec.snapshot(reason="first")
    time.sleep(0.002)
    b = rec.snapshot(reason="second")
    # 1 byte fits nothing, but the just-written bundle must survive —
    # an empty black box is worse than an oversized one
    assert rec.bundles() == [b]
    assert not os.path.isdir(a)


def test_full_disk_suppressed_never_raises(tmp_path):
    blocked = tmp_path / "blocked"
    blocked.write_text("a file where the bundle dir must go")
    coll = FleetCollector([])
    rec = FlightRecorder(str(blocked)).add_to(coll)
    _emit(coll)  # must not raise out of the anomaly feed
    assert rec.suppressed == 1 and rec.bundle_count == 0


# -- demand snapshots with no live fleet ------------------------------------


def test_demand_snapshot_with_nothing_wired(tmp_path):
    # A recorder wired to no collector still writes a valid (sparse)
    # bundle from the process-wide feeds — the cmd.fleet --bundle path
    # against a dead fleet.
    rec = FlightRecorder(str(tmp_path / "bb"))
    bundle = rec.snapshot()
    man = read_manifest(bundle)
    assert man["reason"] == "demand"
    assert man["anomalies"] == []
    assert "traces.json" in man["files"]
    assert "health.json" not in man["files"]  # no collector wired
    with open(os.path.join(bundle, "metrics.json")) as f:
        assert isinstance(json.load(f), dict)


def test_reason_is_sanitized_into_the_dirname(tmp_path):
    rec = FlightRecorder(str(tmp_path / "bb"))
    bundle = rec.snapshot(reason="slo_burn: shard 0 / p99>0.5s!")
    name = os.path.basename(bundle)
    assert name.startswith("bundle-")
    tail = name.split("-", 2)[2]
    assert all(c.isalnum() or c in "-_" for c in tail)
    assert os.path.isdir(bundle)
