"""TPA roaming + threshold-CA through live servers
(reference: protocol/roaming_test.go:15-29, dist_test.go:29-105)."""

from __future__ import annotations

import hashlib

import pytest

from bftkv_tpu.crypto import rsa
from bftkv_tpu.crypto.threshold import ThresholdAlgo
from bftkv_tpu.errors import Error

from cluster_utils import start_cluster

BITS = 2048


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(n_servers=4, n_users=2, n_rw=4, bits=BITS)
    yield c
    c.stop()


def test_tpa_roundtrip(cluster):
    """First authenticate sets up the shared secret; a later one (the
    'roaming' device) recovers the same cipher key
    (reference: roaming_test.go:15-29)."""
    cli = cluster.clients[0]
    proof, key = cli.authenticate(b"tpa_var", b"correct horse")
    assert proof is not None and key
    proof2, key2 = cli.authenticate(b"tpa_var", b"correct horse")
    assert key2 == key


def test_tpa_wrong_password(cluster):
    cli = cluster.clients[0]
    cli.authenticate(b"tpa_wp", b"right password")
    with pytest.raises(Error):
        cli.authenticate(b"tpa_wp", b"wrong password")


def test_tpa_protected_write_read(cluster):
    """The proof gates reads on servers that hold the auth params —
    the quorum servers, which stored them at setAuth/sign time
    (reference: server.go:181-185; full value secrecy additionally
    comes from API-layer symmetric encryption, api.go:149-163)."""
    from bftkv_tpu import packet as pkt
    from bftkv_tpu.errors import ERR_AUTHENTICATION_FAILURE

    cli = cluster.clients[0]
    proof, _key = cli.authenticate(b"tpa_rw", b"pw1")
    cli.write(b"tpa_rw", b"secret-value", proof=proof)
    assert cli.read(b"tpa_rw", proof=proof) == b"secret-value"
    # A quorum server holds the auth params (stored at setAuth/sign
    # time) and refuses any read of the protected variable without the
    # proof; with the proof it answers (with no completed version —
    # W = U − {Ci} + R keeps completed writes off the clique servers,
    # reference: wotqs.go:108-110).
    srv = cluster.servers[0]
    with pytest.raises(ERR_AUTHENTICATION_FAILURE):
        srv._read(pkt.serialize(b"tpa_rw", None, 0, None, None), None, None)
    raw = srv._read(pkt.serialize(b"tpa_rw", None, 0, None, proof), None, None)
    # The clique never holds a COMPLETED version (W = U − {Ci} + R);
    # since the round collapse it may serve its commit-pending copy —
    # uncertified, so a reader accepts it only through the resolve
    # path.  Either way: no certified record here.
    if raw is not None:
        p = pkt.parse(raw)
        assert p.ss is not None and not p.ss.completed


def test_threshold_rsa_ca(cluster):
    """Distribute an RSA CA key, threshold-sign, verify against the
    public key (reference: dist_test.go:29-105)."""
    cli = cluster.clients[0]
    key = rsa.generate(2048)
    cli.distribute("ca-rsa", key)
    tbs = b"an X.509 to-be-signed blob"
    sig = cli.dist_sign("ca-rsa", tbs, ThresholdAlgo.RSA, "sha256")
    assert rsa.verify_host(tbs, sig, key.public)


def test_threshold_dsa_ca(cluster):
    from bftkv_tpu.crypto.threshold import dsa as tdsa

    cli = cluster.clients[0]
    key = tdsa.generate(1024)
    cli.distribute("ca-dsa", key)
    tbs = b"dsa signing payload"
    sig = cli.dist_sign("ca-dsa", tbs, ThresholdAlgo.DSA, "sha256")
    # standard DSA verify: v = (g^u1 · y^u2 mod p) mod q == r
    size = (key.q.bit_length() + 7) // 8
    r = int.from_bytes(sig[:size], "big")
    s = int.from_bytes(sig[size:], "big")
    assert 0 < r < key.q and 0 < s < key.q
    ops = tdsa._DSAGroupOps(key.p, key.q, key.g)
    m = ops.os2i(hashlib.sha256(tbs).digest())
    w = pow(s, -1, key.q)
    v = (
        pow(key.g, m * w % key.q, key.p)
        * pow(key.y, r * w % key.q, key.p)
    ) % key.p % key.q
    assert v == r


def test_threshold_ecdsa_ca(cluster):
    pytest.importorskip("cryptography")  # oracle cross-check needs the host lib
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    from bftkv_tpu.crypto import ec
    from bftkv_tpu.crypto.threshold import ecdsa as tec

    cli = cluster.clients[0]
    key = tec.generate(ec.P256)
    cli.distribute("ca-ec", key)
    tbs = b"ecdsa signing payload"
    sig = cli.dist_sign("ca-ec", tbs, ThresholdAlgo.ECDSA, "sha256")
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    pub = key.curve.scalar_base_mult(key.d)
    pubkey = cec.EllipticCurvePublicNumbers(
        pub[0], pub[1], cec.SECP256R1()
    ).public_key()
    pubkey.verify(encode_dss_signature(r, s), tbs, cec.ECDSA(hashes.SHA256()))


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_threshold_repeated_rounds_5_of_9():
    """Repeated dist_sign rounds at (t,n)=(5,9): regression for the
    session-reordering race — a second signing round's server-to-server
    share envelopes (relayed through the client, no transport retry
    channel) must stay decryptable even when the recipient never saw
    the dealer's earlier session bootstrap."""
    from bftkv_tpu.crypto.threshold import ecdsa as tec
    from bftkv_tpu.crypto import ec

    c = start_cluster(n_servers=9, n_users=1, n_rw=4, bits=1024)
    try:
        cli = c.clients[0]
        key = rsa.generate(1024)
        cli.distribute("rrca-rsa", key)
        eckey = tec.generate(ec.P256)
        cli.distribute("rrca-ec", eckey)
        for i in range(2):
            sig = cli.dist_sign(
                "rrca-rsa", b"round-%d" % i, ThresholdAlgo.RSA, "sha256"
            )
            assert rsa.verify_host(b"round-%d" % i, sig, key.public)
        for i in range(2):
            sig = cli.dist_sign(
                "rrca-ec", b"ec-round-%d" % i, ThresholdAlgo.ECDSA, "sha256"
            )
            assert len(sig) == 64
    finally:
        c.stop()


def test_threshold_x509_issuance(cluster):
    """The threshold CA issues a real X.509 certificate: template TBS
    threshold-signed, certificate reassembled, verifiable with the
    standard library against the CA public key
    (reference: cmd/bftrw/bftrw.go:216-302)."""
    import datetime

    pytest.importorskip("cryptography")  # X.509 interop needs the host lib
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import (
        padding as cpadding,
        rsa as crsa,
    )

    from bftkv_tpu.cmd.bftrw import threshold_sign_x509

    cli = cluster.clients[0]
    ca_key = rsa.generate(2048)
    cli.distribute("x509-ca", ca_key)

    # Build a template: self-signed leaf with a SubjectKeyId.
    leaf = crsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(x509.NameOID.COMMON_NAME, "leaf")])
    now = datetime.datetime(2026, 1, 1)
    template = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(leaf.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(leaf.public_key()),
            critical=False,
        )
        .sign(leaf, hashes.SHA256())
    )

    class _Api:  # the slice of api.API threshold_sign_x509 needs
        def sign(self, caname, tbs, algo, hash_name):
            return cli.dist_sign(caname, tbs, algo, hash_name)

    out_der = threshold_sign_x509(_Api(), "x509-ca", template.public_bytes(
        serialization.Encoding.DER))
    issued = x509.load_der_x509_certificate(out_der)
    assert issued.tbs_certificate_bytes == template.tbs_certificate_bytes
    ca_pub = crsa.RSAPublicNumbers(ca_key.e, ca_key.n).public_key()
    ca_pub.verify(
        issued.signature,
        issued.tbs_certificate_bytes,
        cpadding.PKCS1v15(),
        hashes.SHA256(),
    )
