"""Tier-1 perf smoke: a loopback 4-replica mini-bench with a floor.

Transport/protocol throughput regressions (a dispatcher stall, a
serialized fan-out, a storage path gone quadratic, a cache that stopped
hitting) used to surface only in the next round's BENCH record; this
asserts a CONSERVATIVE writes/sec floor in CI instead.  Loopback, not
HTTP — per the port-block constraint, concurrent HTTP clusters in one
test process collide (tests/cluster_utils port ranges are per-process).

The floor is ~4x below the worst rate observed on the slowest
known-good box (a time-sliced 2-vCPU container measured 8-17 writes/s
at this shape), so it trips on structural regressions, not on CI
noise.
"""

from __future__ import annotations

import os
import threading
import time


from bftkv_tpu.ops import dispatch
from bftkv_tpu.storage.memkv import MemStorage
from tests.cluster_utils import start_cluster

#: Conservative: a structural regression (serialized rounds, stalled
#: dispatcher, quadratic storage) lands well below this; a loaded CI
#: box does not.
FLOOR_WRITES_PER_SEC = 2.0

WRITERS = 4
WRITES_PER_WRITER = 4
KEY_BITS = 1024  # keygen speed; the write path is bits-agnostic


def test_write_path_throughput_floor():
    # Mirror the daemon boot path (cmd/bftkv.py): with BFTKV_PROFILE
    # set, the continuous sampler runs THROUGH the timed region below —
    # CI's armed pass holds the same floors as the disarmed one, which
    # is the profiler's within-5%-overhead contract.  Disarmed: no-op.
    from bftkv_tpu.obs import profiler

    profiler.ensure_started()
    cluster = start_cluster(
        4, WRITERS, 4, bits=KEY_BITS, storage_factory=MemStorage
    )
    clients = cluster.clients
    try:
        dispatch.install(dispatch.VerifyDispatcher(max_batch=256))
        dispatch.install_signer(dispatch.SignDispatcher(max_batch=128))
        value = os.urandom(1024)
        # Session + compile warmup outside the timed region, exactly
        # like bench.py's cluster sections.
        for ci, c in enumerate(clients[:WRITERS]):
            c.write(b"smoke/warm/%d" % ci, value)

        errors: list = []

        def run(ci: int, client) -> None:
            try:
                for i in range(WRITES_PER_WRITER):
                    client.write(b"smoke/%d/%d" % (ci, i), value)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(ci, c), daemon=True)
            for ci, c in enumerate(clients[:WRITERS])
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        assert not errors, errors[0]

        total = WRITERS * WRITES_PER_WRITER
        rate = total / elapsed
        # Correctness before rate: a fast wrong answer is no smoke pass.
        assert clients[0].read(b"smoke/0/%d" % (WRITES_PER_WRITER - 1)) == value
        assert rate >= FLOOR_WRITES_PER_SEC, (
            f"write path regressed: {rate:.2f} writes/s "
            f"< floor {FLOOR_WRITES_PER_SEC} "
            f"({total} writes in {elapsed:.1f}s)"
        )
    finally:
        dispatch.uninstall_all()
        cluster.stop()
