"""Anti-entropy: digest tree, Byzantine-safe pull admission, and
replica convergence without client traffic (bftkv_tpu/sync).

The adversary model mirrors tests/mal_utils.py — malicious behavior by
*subclassing* the real server, never mocking: a Byzantine peer serves
forged, replayed, and cert-stripped records during SYNC_PULL and must
achieve nothing beyond wasted bandwidth."""

from __future__ import annotations

import random
import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import topology
from bftkv_tpu import transport as tp
from bftkv_tpu.crypto import new_crypto
from bftkv_tpu.crypto import signature as sigmod
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.ops import dispatch
from bftkv_tpu.protocol.server import HIDDEN_PREFIX, Server
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.sync import SyncDaemon, admit_records
from bftkv_tpu.sync.digest import DigestTree, bucket_of, latest_completed
from bftkv_tpu.transport.loopback import TrLoopback
from cluster_utils import start_cluster

BITS = 1024  # keygen speed; the sync plane is bits-agnostic


def _completed_record(variable: bytes, t: int, value: bytes) -> bytes:
    """A syntactically completed record (unverifiable signatures —
    digest-tree tests only)."""
    sig = pkt.SignaturePacket(data=b"")
    ss = pkt.SignaturePacket(data=b"", completed=True)
    return pkt.serialize(variable, value, t, sig, ss, None)


# -- digest tree -----------------------------------------------------------


def test_digest_tree_covers_only_completed_records():
    st = MemStorage()
    tree = DigestTree(st)
    assert tree.buckets() == {}

    st.write(b"x", 1, _completed_record(b"x", 1, b"v"))
    # In-progress sign record (no completed ss): invisible.
    st.write(b"y", 1, pkt.serialize(b"y", b"w", 1, pkt.SignaturePacket(data=b""), None))
    # Hidden-prefix share: never in a digest.
    st.write(HIDDEN_PREFIX + b"s", 0, b"share")
    tree.mark(b"x")
    tree.mark(b"y")
    tree.mark(HIDDEN_PREFIX + b"s")

    buckets = tree.buckets()
    assert list(buckets) == [bucket_of(b"x")]

    # Incremental: a new completed version changes exactly its bucket.
    st.write(b"x", 2, _completed_record(b"x", 2, b"v2"))
    tree.mark(b"x")
    assert tree.buckets() != buckets
    assert tree.root() != bytes(32)


def test_protected_records_never_enter_the_sync_plane(cluster):
    """TPA-protected records (stored auth params) are excluded from
    digests AND rejected on pull admission: open Join enrollment makes
    the keyring-peer gate attacker-satisfiable, so the plane must only
    ever carry what an anonymous quorum READ would serve."""
    st = MemStorage()
    sig = pkt.SignaturePacket(data=b"")
    ss = pkt.SignaturePacket(data=b"", completed=True)
    protected = pkt.serialize(b"prot", b"secret!", 3, sig, ss, b"authparams")
    st.write(b"prot", 3, protected)
    st.write(b"open", 3, _completed_record(b"open", 3, b"public"))
    tree = DigestTree(st)
    assert list(tree.buckets()) == [bucket_of(b"open")]
    assert latest_completed(st, b"prot") is None

    # Admission symmetrically refuses a pushed protected record.
    victim = cluster.server_named("rw03")
    stats = admit_records(victim, [protected])
    assert stats == {"admitted": 0, "rejected": 1, "stale": 0}
    with pytest.raises(Exception):
        victim.storage.read(b"prot", 0)


def test_digest_tree_equality_is_content_equality():
    a, b = MemStorage(), MemStorage()
    for st in (a, b):
        for i in range(20):
            var = b"k%d" % i
            st.write(var, 1, _completed_record(var, 1, b"v%d" % i))
    ta, tb = DigestTree(a), DigestTree(b)
    assert ta.buckets() == tb.buckets()
    assert ta.root() == tb.root()
    b.write(b"k3", 2, _completed_record(b"k3", 2, b"divergent"))
    tb.mark(b"k3")
    mine, theirs = ta.buckets(), tb.buckets()
    divergent = [k for k, h in theirs.items() if mine.get(k) != h]
    assert divergent == [bucket_of(b"k3")]


def test_digest_wire_codecs_roundtrip():
    buckets = {0: b"\x11" * 32, 7: b"\x22" * 32, 255: b"\x33" * 32}
    assert pkt.parse_digest(pkt.serialize_digest(buckets)) == buckets
    assert pkt.parse_bucket_ids(pkt.serialize_bucket_ids([0, 9, 255])) == [
        0,
        9,
        255,
    ]
    # Untrusted input: torn entries are protocol errors, not aliases.
    with pytest.raises(Exception):
        pkt.parse_digest(pkt.serialize_list([b"\x00" + b"h" * 31]))
    with pytest.raises(Exception):
        pkt.parse_bucket_ids(pkt.serialize_list([b"ab"]))


# -- full-stack convergence ------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(n_servers=4, n_users=1, n_rw=4, bits=BITS)
    yield c
    c.stop()


def test_convergence_after_missed_writes(cluster):
    """A replica that missed M writes converges to digest equality via
    anti-entropy alone — no client reads — with every pulled collective
    signature verified as ONE batch through the installed device
    dispatcher."""
    c = cluster
    cl = c.clients[0]
    victim = c.server_named("rw01")
    victim.tr.stop()

    M = 5
    for i in range(M):
        cl.write(b"conv%d" % i, b"val%d" % i)
    cl.drain_tails()  # collapsed writes certify on the async tail

    victim.start()
    base = metrics.snapshot()
    # The verify memo (crypto/vcache.py) would satisfy every pulled
    # record from cache in this shared-process cluster; disable it so
    # the device-batch admission path this test observes is exercised
    # (a restarted replica PROCESS starts with an empty memo).
    from bftkv_tpu.crypto import vcache as _vcache
    _was = _vcache._ENABLED
    _vcache._ENABLED = False
    dispatch.install(
        dispatch.VerifyDispatcher(max_wait=0.001, calibrate=False)
    )
    try:
        daemon = SyncDaemon(victim, interval=999, rng=random.Random(1))
        stats = daemon.run_round()
        total = dict(stats)
        if total["admitted"] < M:  # acceptance bound: two rounds
            for k, v in daemon.run_round().items():
                total[k] += v
        assert total["admitted"] == M
        assert total["rejected"] == 0

        snap = metrics.snapshot()
        # One device batch per pull that had anything to verify: all M
        # records rode a single verify_many submission...
        batches = snap["sync.pull.verify_batch.count"] - base.get(
            "sync.pull.verify_batch.count", 0
        )
        assert batches == 1
        assert snap["sync.pull.verify_batch.p99"] >= M
        # ...and that submission went through the batched dispatcher.
        assert snap["dispatch.verifies"] - base.get("dispatch.verifies", 0) > 0
        assert (
            snap["sync.pull.records"] - base.get("sync.pull.records", 0) == M
        )
    finally:
        _vcache._ENABLED = _was
        dispatch.uninstall()

    # Digest equality across every storage replica, reached with zero
    # client reads.
    roots = {
        name: c.server_named(name)._sync_tree().root()
        for name in ("rw01", "rw02", "rw03", "rw04")
    }
    assert len(set(roots.values())) == 1, roots
    for i in range(M):
        raw = victim.storage.read(b"conv%d" % i, 0)
        assert pkt.parse(raw).value == b"val%d" % i


def test_oversized_record_skipped_not_served(cluster):
    """A record bigger than the reply byte budget is skipped on the
    serving side (with a metric), never shipped-and-discarded — the
    ship/discard cycle would re-transfer it every round forever."""
    srv = cluster.server_named("rw02")
    srv.storage.write(b"small-rec", 1, _completed_record(b"small-rec", 1, b"v"))
    srv.storage.write(
        b"big-rec", 1, _completed_record(b"big-rec", 1, b"x" * 4096)
    )
    tree = srv._sync_tree()
    tree.mark(b"small-rec")
    tree.mark(b"big-rec")
    srv.SYNC_PULL_MAX_BYTES = 1024  # instance override, this test only
    try:
        before = metrics.snapshot().get("server.sync_pull.oversized", 0)
        peer_cert = srv.crypt.keyring.get(cluster.universe.servers[0].id)
        req = pkt.serialize_bucket_ids(
            sorted({bucket_of(b"small-rec"), bucket_of(b"big-rec")})
        )
        served = pkt.parse_list(srv._sync_pull(req, peer_cert, peer_cert))
        values = {pkt.parse(r).variable for r in served}
        assert b"small-rec" in values
        assert b"big-rec" not in values
        assert (
            metrics.snapshot()["server.sync_pull.oversized"] - before == 1
        )
    finally:
        del srv.SYNC_PULL_MAX_BYTES  # fall back to the class bound


# -- Byzantine peers -------------------------------------------------------


class MalSyncServer(Server):
    """A Byzantine peer on the sync plane: advertises divergence for
    every bucket and serves tampered records during SYNC_PULL
    (subclass-not-mock, the mal_utils.py discipline)."""

    mal_records: list[bytes] = []

    def _sync_digest(self, req, peer, sender):
        self._require_sync_peer(peer)
        # Claim a bogus hash for every bucket the tampered records
        # touch, so any honest puller sees divergence and pulls.
        buckets = {}
        for raw in self.mal_records:
            try:
                var = pkt.parse(raw).variable or b""
            except Exception:
                continue
            buckets[bucket_of(var)] = b"\xee" * 32
        return pkt.serialize_digest(buckets)

    def _sync_pull(self, req, peer, sender):
        self._require_sync_peer(peer)
        return pkt.serialize_list(list(self.mal_records))


@pytest.fixture()
def mal_cluster():
    c = start_cluster(
        n_servers=4, n_users=1, n_rw=4, bits=BITS, server_cls=MalSyncServer
    )
    MalSyncServer.mal_records = []
    yield c
    MalSyncServer.mal_records = []
    c.stop()


def _tampered_records(cluster, variable: bytes):
    """Forged / replayed / cert-stripped variants of a genuine record."""
    honest = cluster.server_named("rw02")
    genuine = latest_completed(honest.storage, variable)
    assert genuine is not None
    _t, raw, _p = genuine
    p = pkt.parse(raw)

    # 1. Forged: attacker value at a newer timestamp, signatures replayed
    #    from the genuine record (tbss changed -> they cannot verify).
    forged = pkt.serialize(variable, b"poison", p.t + 10, p.sig, p.ss, None)
    # 2. Replay-retarget: genuine signatures moved to another variable.
    replayed = pkt.serialize(b"other-var", p.value, p.t + 1, p.sig, p.ss, None)
    # 3. Cert/signature-stripped: the collective signature cut below
    #    sufficiency (first signer only).
    entries = sigmod.parse_entries(p.ss.data)
    stripped_ss = pkt.SignaturePacket(
        data=sigmod.serialize_entries(entries[:1]),
        completed=True,
        cert=p.ss.cert,
    )
    stripped = pkt.serialize(variable, b"poison2", p.t + 11, p.sig, stripped_ss, None)
    # 4. Hidden-prefix smuggle: a "completed" record for a share slot.
    hidden = pkt.serialize(HIDDEN_PREFIX + b"s", b"x", 1, p.sig, p.ss, None)
    return [forged, replayed, stripped, hidden]


def test_byzantine_pull_rejected_state_unchanged(mal_cluster):
    c = mal_cluster
    cl = c.clients[0]
    cl.write(b"target", b"honest-value")
    cl.drain_tails()  # the forged variants derive from the CERTIFIED record

    victim = c.server_named("rw01")
    MalSyncServer.mal_records = _tampered_records(c, b"target")
    # Only the mal peer advertises divergence to the fully-synced
    # victim, so the pull provably went to the Byzantine peer.
    mal_only = [n for n in victim.self_node.get_peers() if n.name == "a01"]
    assert mal_only

    before_root = victim._sync_tree().root()
    before = metrics.snapshot()
    daemon = SyncDaemon(victim, interval=999, rng=random.Random(3))
    daemon._peers = lambda: mal_only  # point the round at the adversary
    stats = daemon.run_round()

    assert stats["admitted"] == 0
    assert stats["rejected"] >= 4
    snap = metrics.snapshot()
    assert snap["sync.rejected"] - before.get("sync.rejected", 0) >= 4
    # Local state untouched: digest root identical, honest value served.
    assert victim._sync_tree().root() == before_root
    raw = victim.storage.read(b"target", 0)
    assert pkt.parse(raw).value == b"honest-value"
    with pytest.raises(Exception):
        victim.storage.read(HIDDEN_PREFIX + b"s", 0)


def test_direct_admission_rejects_uncertified_records(cluster):
    """admit_records is the trust boundary even without transport: a
    record whose collective signature was minted by a single server
    (below sufficiency) dies in the batched verify."""
    victim = c = cluster.server_named("rw03")
    share = c.crypt.collective.sign(c.crypt.signer, b"whatever")
    bogus = pkt.serialize(
        b"solo", b"v", 5, pkt.SignaturePacket(data=b""), share, None
    )
    bogus_p = pkt.parse(bogus)
    assert bogus_p.ss is not None
    bogus_p.ss.completed = True
    stats = admit_records(victim, [bogus_p.serialize()])
    assert stats == {"admitted": 0, "rejected": 1, "stale": 0}


def test_stale_replay_is_ignored_not_admitted(cluster):
    """A pure replay of an older genuine record neither poisons state
    nor counts as Byzantine — it is skipped as stale."""
    c = cluster
    cl = c.clients[0]
    cl.write(b"stale-key", b"v1")
    victim = c.server_named("rw04")
    # write() returns at the commit threshold; delivery to the full
    # replica set completes asynchronously (the fan-out tail), so wait
    # for rw04's copy instead of assuming synchronous full delivery.
    old = None
    for _ in range(200):
        old = latest_completed(victim.storage, b"stale-key")
        if old is not None:
            break
        time.sleep(0.01)
    assert old is not None
    cl.write(b"stale-key", b"v2")
    # Same asynchrony for v2: the replayed v1 is only STALE once the
    # victim's own copy has moved past it.
    for _ in range(200):
        cur = latest_completed(victim.storage, b"stale-key")
        if cur is not None and pkt.parse(cur[1]).value == b"v2":
            break
        time.sleep(0.01)
    stats = admit_records(victim, [old[1]])
    assert stats["admitted"] == 0
    assert stats["rejected"] == 0
    assert stats["stale"] == 1
    assert pkt.parse(victim.storage.read(b"stale-key", 0)).value == b"v2"


def test_sync_refuses_unknown_peers(cluster):
    """A sender outside the keyring gets ERR_PERMISSION_DENIED: sync
    must not leak TPA-protected values to strangers."""
    c = cluster
    stranger = topology.new_identity("stranger", bits=BITS)
    crypt = new_crypto(stranger.key, stranger.cert)
    target = c.universe.servers[0]
    crypt.keyring.register(
        [next(x for x in c.universe.certs() if x.id == target.id)]
    )
    tr = TrLoopback(crypt, c.net)
    results = []
    tr.multicast(
        tp.SYNC_DIGEST,
        [crypt.keyring.get(target.id)],
        b"",
        lambda res: results.append(res) or True,
    )
    assert results and results[0].err is not None
