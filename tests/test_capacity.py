"""Capacity plane (bftkv_tpu/obs/capacity): USE rows from induced
saturation, the bottleneck verdict, device-occupancy parity, and the
``resource_saturated`` hysteresis — plus the loopback fleet scrape the
CI capacity smoke step asserts against."""

from __future__ import annotations

import threading
import time

import pytest

from bftkv_tpu.admission import AdmissionQueue
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import Metrics, registry as metrics
from bftkv_tpu.obs import FleetCollector
from bftkv_tpu.obs.capacity import CapacityPlane, RESOURCE_PHASES, RESOURCES


@pytest.fixture(autouse=True)
def _clean_registry():
    """Capacity reads the process registry; every test starts and ends
    with a blank one (and a disarmed failpoint registry) so induced
    saturation cannot bleed across tests."""
    metrics.reset()
    fp.disarm()
    yield
    fp.disarm()
    metrics.reset()


def _observe_twice(cp: CapacityPlane, member: str = "m") -> dict:
    """Baseline-then-read: the first scrape seeds the counter-delta
    baseline from an empty snapshot, so the second scrape's deltas
    equal the totals accumulated by the test body."""
    cp.observe(member, {}, now=0.0)
    return cp.observe(member, metrics.snapshot(), now=1.0)


# -- vocabulary closure -----------------------------------------------------


def test_resource_vocabulary_is_closed_and_mapped():
    """Every resource maps to phases (the verdict join) and nothing
    else does — adding a resource without the mapping is the schema
    drift the closed vocabulary exists to prevent."""
    assert set(RESOURCE_PHASES) == set(RESOURCES)
    from bftkv_tpu.trace import PHASES

    for res, phases in RESOURCE_PHASES.items():
        for p in phases:
            assert p in PHASES, f"{res} maps to unknown phase {p}"


# -- seeded induction: admission --------------------------------------------


def test_shrunk_sidecar_admission_names_admission():
    """A sidecar AdmissionQueue shrunk to one slot + one queue slot
    under 4 concurrent holders saturates: waiters queue, one sheds, and
    the verdict names admission."""
    q = AdmissionQueue(
        max_inflight=1, max_queue=1, max_wait=0.05, metric="sidecar.shed"
    )
    assert q.acquire("sign")  # holds the only slot for the duration
    results = []

    def contender():
        results.append(q.acquire("sign"))

    threads = [threading.Thread(target=contender) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not any(results)  # all queued-then-timed-out or shed
    cp = CapacityPlane()
    rows = _observe_twice(cp)
    adm = rows["admission"]
    assert adm["utilization"] == 1.0
    assert adm["saturation"] == 1.0
    assert adm["errors"] >= 1  # instant sheds past the queue limit
    assert adm["tiers"]["sidecar"]["shed"] >= 1
    v = cp.verdict()
    assert v["top"]["resource"] == "admission"
    assert "admission on m limits throughput" in v["summary"]
    q.release()


def test_admission_verdict_survives_phase_join():
    """With a real phase-share join the admission verdict stands when
    the budget says time is spent in the phases admission backs."""
    q = AdmissionQueue(
        max_inflight=1, max_queue=1, max_wait=0.01, metric="sidecar.shed"
    )
    assert q.acquire("sign")
    assert not q.acquire("sign")  # queue empty+held → instant shed path
    cp = CapacityPlane()
    _observe_twice(cp)
    v = cp.verdict({"server": 0.4, "sidecar": 0.3, "rpc": 0.3})
    assert v["top"]["resource"] == "admission"
    assert v["top"]["phase_weight"] == pytest.approx(0.7)
    q.release()


# -- seeded induction: log-commit path --------------------------------------


def test_stalled_fsync_names_log_commit(tmp_path):
    """Stalling the durability barrier via the storage.fsync failpoint
    drives commit-wait p99 past the saturation reference: the verdict
    names the commit path."""
    from bftkv_tpu.storage.logkv import LogStorage

    st = LogStorage(str(tmp_path / "db"), group_commit_s=0.0)
    try:
        fp.registry.arm(0)
        fp.registry.add("storage.fsync", "stall", seconds=0.3)
        st.write(b"k", 1, b"v")
    finally:
        fp.disarm()
        st.close()
    cp = CapacityPlane()
    rows = _observe_twice(cp)
    lc = rows["log_commit"]
    assert lc["saturation"] == 1.0
    assert lc["commit_wait_p99_s"] >= 0.3
    v = cp.verdict()
    assert v["top"]["resource"] == "log_commit"
    assert "log_commit on m limits throughput" in v["summary"]


# -- device-occupancy parity ------------------------------------------------


def test_device_occupancy_matches_items_per_launch(keys64):
    """The occupancy gauge must agree with the dispatcher's own
    items/flushes counters: occupancy == (items per launch) / max_batch
    when every flush fits one launch."""
    from bftkv_tpu.crypto import rsa
    from bftkv_tpu.ops import dispatch

    key = keys64
    d = dispatch.VerifyDispatcher(
        max_batch=8, max_wait=0.01, calibrate=False
    ).start()
    try:
        msgs = [b"m%d" % i for i in range(4)]
        items = [(m, rsa.sign(m, key), key.public) for m in msgs]
        assert d.verify(items).all()
    finally:
        d.stop()
    cp = CapacityPlane()
    rows = _observe_twice(cp)
    disp = rows["dispatch"]["dispatchers"]["dispatch"]
    snap = metrics.snapshot()
    items_n = snap["dispatch.verifies"]
    flushes = snap["dispatch.flushes"]
    assert disp["items_per_launch"] == pytest.approx(items_n / flushes)
    occ = rows["dispatch"]["utilization"]
    assert occ == pytest.approx((items_n / flushes) / 8, abs=0.01)


@pytest.fixture(scope="module")
def keys64():
    from bftkv_tpu.crypto import rsa

    return rsa.generate(2048)


# -- hysteresis -------------------------------------------------------------


def _saturated_snap(n_shed: float) -> dict:
    """A synthetic member snapshot with a saturated sidecar admission
    tier; bumping ``n_shed`` each scrape keeps it traffic-bearing."""
    return {
        "admission.limit{resource=sidecar}": 2.0,
        "admission.inflight{resource=sidecar}": 2.0,
        "admission.waiting{resource=sidecar}": 4.0,
        "admission.queue_limit{resource=sidecar}": 4.0,
        "sidecar.shed": n_shed,
        "admission.wait.count{resource=sidecar}": n_shed,
    }


def _healthy_snap(n: float) -> dict:
    return {
        "admission.limit{resource=sidecar}": 2.0,
        "admission.inflight{resource=sidecar}": 0.0,
        "admission.waiting{resource=sidecar}": 0.0,
        "admission.queue_limit{resource=sidecar}": 4.0,
        "sidecar.shed": 0.0,
        "admission.wait.count{resource=sidecar}": n,
    }


def test_resource_saturated_fires_once_per_episode(monkeypatch):
    """slo_burn's exact contract: k consecutive traffic-bearing
    breaching scrapes fire ONCE; staying saturated does not re-fire;
    a healthy scrape re-arms for the next episode."""
    monkeypatch.setenv("BFTKV_SAT_THRESHOLD", "0.8")
    monkeypatch.setenv("BFTKV_SAT_SCRAPES", "3")
    cp = CapacityPlane()
    shed = 0.0
    fired = []
    for i in range(5):
        shed += 1.0
        cp.observe("m", _saturated_snap(shed), now=float(i))
        fired.append(cp.check())
    # scrape 0 seeds the baseline (shed delta == total, still >0, so it
    # counts); fires exactly at the 3rd consecutive breach, then never
    # again while the episode persists
    assert [len(f) for f in fired] == [0, 0, 1, 0, 0]
    ev = fired[2][0]
    assert ev == {
        "member": "m",
        "resource": "admission",
        "saturation": 1.0,
        "utilization": 1.0,
    }
    # recovery re-arms: healthy scrape, then a fresh 3-breach episode
    cp.observe("m", _healthy_snap(shed + 1), now=5.0)
    assert cp.check() == []
    for i in range(3):
        shed += 1.0
        cp.observe("m", _saturated_snap(shed), now=6.0 + i)
        out = cp.check()
        assert len(out) == (1 if i == 2 else 0)


def test_idle_scrapes_hold_the_count(monkeypatch):
    """An idle scrape (no admission traffic) neither advances nor
    resets the hysteresis — idle can neither saturate nor recover."""
    monkeypatch.setenv("BFTKV_SAT_THRESHOLD", "0.8")
    monkeypatch.setenv("BFTKV_SAT_SCRAPES", "2")
    cp = CapacityPlane()
    cp.observe("m", _saturated_snap(1.0), now=0.0)
    assert cp.check() == []
    # identical snapshot: zero deltas → idle → count held, not reset
    cp.observe("m", _saturated_snap(1.0), now=1.0)
    assert cp.check() == []
    cp.observe("m", _saturated_snap(2.0), now=2.0)
    assert len(cp.check()) == 1


# -- fleet integration (the CI capacity smoke references this) --------------


def test_fleet_scrape_renders_capacity_and_emits_anomaly(monkeypatch):
    """Loopback fleet: the collector folds member metrics into the
    capacity section, health() carries it, render_capacity names the
    saturated resource, and sustained saturation surfaces in the
    anomaly feed as resource_saturated (recorder auto-bundle trigger)."""
    from bftkv_tpu.cmd.fleet import render_capacity
    from tests.test_fleet import _two_shard_fleet

    monkeypatch.setenv("BFTKV_SAT_THRESHOLD", "0.8")
    monkeypatch.setenv("BFTKV_SAT_SCRAPES", "2")
    srcs = _two_shard_fleet()
    hot = next(s for s in srcs if s.name == "a01")
    reg = Metrics()
    coll = FleetCollector(srcs, local_metrics=reg)
    shed = 0.0
    doc = None
    for _ in range(3):
        shed += 2.0
        hot.snap = _saturated_snap(shed)
        doc = coll.scrape_once()
    cap = doc["capacity"]
    assert cap["members"]["a01"]["admission"]["saturation"] == 1.0
    assert cap["fleet"]["admission"]["saturation"] == 1.0
    assert cap["verdict"]["top"]["resource"] == "admission"
    text = render_capacity(doc)
    assert "admission" in text and "verdict:" in text
    assert "a01" in text
    sat = [a for a in doc["anomalies"] if a["kind"] == "resource_saturated"]
    assert len(sat) == 1 and sat[0]["source"] == "a01"
    assert "admission" in sat[0]["detail"]


def test_fleet_prometheus_exports_resource_family():
    srcs_mod = __import__("tests.test_fleet", fromlist=["_two_shard_fleet"])
    srcs = srcs_mod._two_shard_fleet()
    hot = next(s for s in srcs if s.name == "b01")
    hot.snap = _saturated_snap(3.0)
    coll = FleetCollector(srcs)
    coll.scrape_once()
    text = coll.prometheus()
    assert "# TYPE bftkv_fleet_resource_saturation gauge" in text
    assert (
        'bftkv_fleet_resource_saturation{member="b01",resource="admission"}'
        in text
    )
    assert "bftkv_fleet_resource_verdict_score" in text


def test_capacity_forget_drops_member_state():
    cp = CapacityPlane()
    cp.observe("m", _saturated_snap(1.0), now=0.0)
    assert "m" in cp.doc()["members"]
    cp.forget("m")
    assert cp.doc() == {"members": {}, "fleet": {}}


def test_verdict_without_saturation_reports_next_wall():
    """Nothing queued anywhere: the verdict degrades to naming the
    fullest resource instead of inventing a bottleneck."""
    cp = CapacityPlane()
    cp.observe(
        "m",
        {
            "admission.limit{resource=gateway}": 4.0,
            "admission.inflight{resource=gateway}": 2.0,
            "admission.waiting{resource=gateway}": 0.0,
            "admission.queue_limit{resource=gateway}": 8.0,
        },
        now=0.0,
    )
    v = cp.verdict()
    assert v["top"] is None
    assert "no saturated resource" in v["summary"]
    assert "admission" in v["summary"]


def test_compute_member_first_scrape_uses_totals():
    """dt and prev defaults: first scrape (empty prev) reads deltas as
    totals — the honest first reading, not a zero row."""
    from bftkv_tpu.obs.capacity import _index, compute_member

    idx = _index(
        {
            "storage.compact.read_bytes": 2.0 * 1024 * 1024,
            "storage.compact.written_bytes": 1.0 * 1024 * 1024,
            "storage.compact.mbps": 3.0,
        }
    )
    rows = compute_member(idx, {}, 1.0)
    assert rows["compact_io"]["mbps"] == pytest.approx(3.0)
    assert rows["compact_io"]["utilization"] == 1.0  # ungoverned + active


def test_compact_governor_throttles_and_reports(tmp_path, monkeypatch):
    """BFTKV_LOG_COMPACT_MBPS bounds the copy loop: with a tiny budget
    the governor sleeps, the throttle histogram records the debt, and
    the capacity row reads as saturated."""
    monkeypatch.setenv("BFTKV_LOG_COMPACT_MBPS", "0.5")
    from bftkv_tpu.storage.logkv import LogStorage

    st = LogStorage(str(tmp_path / "db"), fsync=False, group_commit_s=0.0)
    try:
        blob = b"x" * 4096
        for i in range(64):
            st.write(b"k%d" % i, 1, blob)
        st.seal_active()
        t0 = time.monotonic()
        st.compact()
        elapsed = time.monotonic() - t0
    finally:
        st.close()
    snap = metrics.snapshot()
    moved = snap.get("storage.compact.read_bytes", 0) + snap.get(
        "storage.compact.written_bytes", 0
    )
    assert moved > 0
    # ~0.5 MB at 0.5 MB/s cannot finish instantly
    throttled = snap.get("storage.compact.throttle.sum", 0.0)
    assert throttled > 0.0
    assert elapsed >= throttled * 0.5
    cp = CapacityPlane()
    rows = _observe_twice(cp)
    io = rows["compact_io"]
    assert io["mbps"] <= 0.75  # governed at 0.5, tolerance for rounding
    assert io["saturation"] > 0.0
