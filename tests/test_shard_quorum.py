"""Keyed quorum routing: disjoint-clique shards, HRW bucket routing,
ownership, caches, and the choose_quorum generation guard.

All graph-level (FakeNode) tests — no crypto, so the whole file runs in
well under a second.  Topology: two 4-cliques of quorum servers
(a01-a04, b01-b04), eight storage-only rw nodes, and a user u01 who
signs every server and rw node.
"""

import threading

import pytest

from bftkv_tpu import quorum as q
from bftkv_tpu.graph import Graph
from bftkv_tpu.quorum.wotqs import ROUTE_BUCKETS, WotQS
from tests.test_graph_quorum import FakeNode


def mk_shard_universe(n_per_clique=4, n_rw=8, cliques=("a", "b")):
    nodes = {}
    nid = iter(range(1, 1000))

    def add(name, address="", uid=""):
        n = FakeNode(next(nid), name, address=address, uid=uid)
        nodes[name] = n
        return n

    for grp in cliques:
        for i in range(1, n_per_clique + 1):
            add(f"{grp}{i:02d}", address=f"http://{grp}{i:02d}")
    for i in range(1, n_rw + 1):
        add(f"rw{i:02d}", address=f"http://rw{i:02d}")
    add("u01", uid="u01@example.test")

    def sign(signer, signee):
        nodes[signee].signer_ids.add(nodes[signer].id)

    for grp in cliques:
        names = [f"{grp}{i:02d}" for i in range(1, n_per_clique + 1)]
        for s1 in names:
            for s2 in names:
                if s1 != s2:
                    sign(s1, s2)
    for grp in cliques:
        for i in range(1, n_per_clique + 1):
            sign("u01", f"{grp}{i:02d}")
    for i in range(1, n_rw + 1):
        sign("u01", f"rw{i:02d}")
        for grp in cliques:
            for j in range(1, n_per_clique + 1):
                sign(f"rw{i:02d}", f"{grp}{j:02d}")
    return nodes


def build(nodes, self_name, order=None):
    g = Graph()
    ordered = (
        [nodes[n] for n in order] if order else list(nodes.values())
    )
    g.add_nodes(ordered)
    g.set_self_nodes([nodes[self_name]])
    return g


@pytest.fixture()
def universe():
    return mk_shard_universe()


def shard_names(qs, universe):
    byid = {n.id: name for name, n in universe.items()}
    topo = qs._topology()
    return [sorted(byid[n.id] for n in c.nodes) for c in topo.shards]


# -- enumeration ----------------------------------------------------------


def test_two_cliques_enumerated(universe):
    qs = WotQS(build(universe, "u01"))
    groups = shard_names(qs, universe)
    assert sorted(map(tuple, groups)) == [
        tuple(f"a{i:02d}" for i in range(1, 5)),
        tuple(f"b{i:02d}" for i in range(1, 5)),
    ]
    assert qs.shard_count() == 2


def test_users_never_form_shards(universe):
    # u01 <-> nothing bidirectionally except... give u01 mutual edges
    # with a whole clique: still no shard membership (no address).
    for i in range(1, 5):
        universe["u01"].signer_ids.add(universe[f"a{i:02d}"].id)
    qs = WotQS(build(universe, "u01"))
    for grp in shard_names(qs, universe):
        assert "u01" not in grp


def test_single_clique_degenerates(universe):
    solo = {
        name: n
        for name, n in universe.items()
        if not name.startswith("b")
    }
    qs = WotQS(build(solo, "u01"))
    assert qs.shard_count() == 1
    assert qs.shard_of(b"x") is None
    assert qs.owns(b"anything")
    assert qs.owned_buckets() is None
    assert qs.shard_buckets() == [ROUTE_BUCKETS]
    # Bit-for-bit: the keyed API returns the SAME memoized object the
    # unkeyed call returns.
    qa = qs.choose_quorum(q.AUTH)
    assert qs.choose_quorum_for(b"x", q.AUTH) is qa


def test_local_trust_edges_do_not_shape_shards(universe):
    """server_trust_rw-style local edges exist in ONE view only; letting
    them into clique enumeration would give that view a different route
    table than the rest of the fleet.  a01's local a01->rw edges +
    rw->a01 certificate edges look bidirectional in a01's graph — the
    enumeration must still produce the pure server cliques."""
    g = build(universe, "a01")
    baseline = [sorted(n.id for n in c.nodes)
                for c in g.get_disjoint_cliques()]
    g.add_local_edges(
        universe["a01"].id,
        [universe[f"rw{i:02d}"].id for i in range(1, 9)],
    )
    got = [sorted(n.id for n in c.nodes) for c in g.get_disjoint_cliques()]
    assert got == baseline
    # An operator redundantly listing a CLIQUE-MATE in localtrust must
    # not demote the certificate-borne edge either: the clique survives.
    g.add_local_edges(universe["a01"].id, [universe["a02"].id])
    got = [sorted(n.id for n in c.nodes) for c in g.get_disjoint_cliques()]
    assert got == baseline


# -- routing --------------------------------------------------------------


def test_route_table_covers_every_bucket(universe):
    qs = WotQS(build(universe, "u01"))
    counts = qs.shard_buckets()
    assert sum(counts) == ROUTE_BUCKETS
    assert len(counts) == 2
    assert all(c > 0 for c in counts)
    # HRW over 256 buckets / 2 cliques: grossly unbalanced would mean a
    # broken hash, not bad luck.
    assert max(counts) / min(counts) < 2.0


def test_routing_agrees_across_views_and_orders(universe):
    names = list(universe)
    qs1 = WotQS(build(universe, "u01", order=names))
    qs2 = WotQS(build(universe, "a01", order=list(reversed(names))))
    qs3 = WotQS(build(universe, "rw01", order=sorted(names)))
    for i in range(64):
        x = b"var/%d" % i
        assert qs1.shard_of(x) == qs2.shard_of(x) == qs3.shard_of(x)


def test_ownership_matches_route(universe):
    qs_a = WotQS(build(universe, "a01"))
    qs_b = WotQS(build(universe, "b01"))
    a_idx = qs_a.my_shard()
    b_idx = qs_b.my_shard()
    assert a_idx is not None and b_idx is not None and a_idx != b_idx
    hits = {True: 0, False: 0}
    for i in range(64):
        x = b"own/%d" % i
        owner = qs_a.shard_of(x)
        assert qs_a.owns(x) == (owner == a_idx)
        assert qs_b.owns(x) == (owner == b_idx)
        hits[qs_a.owns(x)] += 1
    assert hits[True] and hits[False]  # both outcomes actually exercised


def test_complement_partition_balanced(universe):
    qs = WotQS(build(universe, "rw01"))
    topo = qs._topology()
    per_shard = [0, 0]
    for nid, idx in topo.assign.items():
        per_shard[idx] += 1
    assert per_shard == [4, 4]
    # every rw node got an assignment, no clique member did
    assert set(topo.assign) & set(topo.member) == set()
    mine = qs.my_shard()
    owned = qs.owned_buckets()
    assert owned is not None
    assert owned == {
        b for b in range(ROUTE_BUCKETS) if topo.table[b] == mine
    }


def test_keyed_quorum_stays_inside_shard(universe):
    qs = WotQS(build(universe, "u01"))
    topo = qs._topology()
    for i in range(16):
        x = b"q/%d" % i
        idx = qs.shard_of(x)
        allowed = {n.id for n in topo.shards[idx].nodes} | {
            nid for nid, a in topo.assign.items() if a == idx
        }
        for rw in (q.READ | q.AUTH, q.AUTH | q.PEER, q.WRITE, q.READ):
            quorum = qs.choose_quorum_for(x, rw)
            got = {n.id for qc in quorum.qcs for n in qc.nodes}
            assert got, (i, rw)
            assert got <= allowed, (i, rw, got - allowed)


def test_keyed_cache_and_generation(universe):
    g = build(universe, "u01")
    qs = WotQS(g)
    x = b"cache/1"
    q1 = qs.choose_quorum_for(x, q.WRITE)
    assert qs.choose_quorum_for(x, q.WRITE) is q1  # memoized
    g.remove_nodes([universe["rw08"]])  # bumps generation
    q2 = qs.choose_quorum_for(x, q.WRITE)
    assert q2 is not q1
    assert universe["rw08"].id not in {
        n.id for qc in q2.qcs for n in qc.nodes
    }


def test_route_metric_closed_enum(universe):
    from bftkv_tpu.metrics import registry as metrics

    qs = WotQS(build(universe, "u01"))
    for i in range(32):
        qs.choose_quorum_for(b"m/%d" % i, q.READ)
    snap = metrics.snapshot()
    labels = [
        k
        for k in snap
        if k.startswith("quorum.route.shard{")
    ]
    assert labels and len(labels) <= qs.shard_count()


# -- the choose_quorum generation-guard race (wotqs.py:207-235) -----------


def test_choose_quorum_generation_race():
    """A quorum built from the pre-mutation graph must never be served
    under the post-mutation generation: the clique walk completes on
    the old graph, membership mutates before the builder can memoize,
    and the guarded store has to DROP the stale result (wotqs.py's
    choose_quorum store guard — implemented but previously untested)."""
    # 6-node clique: still a valid quorum (f=1) after one node leaves,
    # so the post-mutation rebuild is a real quorum, not a degenerate
    # empty one.
    nodes = mk_shard_universe(n_per_clique=6, n_rw=8, cliques=("a",))
    g = build(nodes, "a01")
    qs = WotQS(g)
    started = threading.Event()
    proceed = threading.Event()
    real = g.get_cliques

    def stale_get_cliques(sid, distance):
        # Snapshot the PRE-mutation cliques, then let the mutation land
        # before returning — the builder finishes its construction from
        # a world that no longer exists.
        res = real(sid, distance)
        started.set()
        assert proceed.wait(5), "mutator never released the builder"
        return res

    g.get_cliques = stale_get_cliques
    box = {}

    def build_quorum():
        box["q"] = qs.choose_quorum(q.AUTH)

    t = threading.Thread(target=build_quorum)
    t.start()
    assert started.wait(5)
    # Membership mutation lands while the builder holds the old clique
    # list: a02 leaves, generation bumps.
    g.remove_nodes([nodes["a02"]])
    proceed.set()
    t.join(5)
    g.get_cliques = real
    stale = box["q"]
    assert nodes["a02"].id in {
        n.id for qc in stale.qcs for n in qc.nodes
    }, "builder should have constructed from the pre-mutation graph"
    # The next call must rebuild from the mutated graph — serving the
    # stale quorum out of the memo would resurrect a02 post-removal.
    fresh = qs.choose_quorum(q.AUTH)
    assert fresh is not stale
    assert fresh.qcs, "5-node clique must still form a quorum"
    assert nodes["a02"].id not in {
        n.id for qc in fresh.qcs for n in qc.nodes
    }


def test_live_generation_churn_under_writers(universe):
    """The autopilot's steady state: graph generations keep bumping
    (spare admission, revocations) WHILE writer threads select keyed
    quorums.  No quorum may ever be served under the wrong generation:
    whenever a writer observes a quiescent generation around its call
    (same before and after), the returned quorum must reflect exactly
    that generation's membership — here, whether rw08 exists."""
    g = build(universe, "u01")
    qs = WotQS(g)
    rw08 = universe["rw08"]
    # The route table derives from the CLIQUES alone, so it is stable
    # under rw (complement) churn; rw08's seat is its round-robin slot,
    # also stable whenever it is present.  Keys routed to that shard
    # must include rw08 in their WRITE complement exactly when the
    # generation they were served under had rw08 in the graph.
    rw_idx = qs.shard_index_of(rw08.id)
    assert rw_idx is not None
    keys = []
    i = 0
    while len(keys) < 8:
        x = b"churn/%d" % i
        i += 1
        if qs.shard_of(x) == rw_idx:
            keys.append(x)
    stop = threading.Event()
    present = {}  # generation -> rw08 in the graph at that generation
    lock = threading.Lock()
    violations: list = []

    def record(gen: int, has: bool) -> None:
        with lock:
            present[gen] = has

    record(g.generation, True)

    def churn():
        for _ in range(60):
            g.remove_nodes([rw08])
            record(g.generation, False)
            g.add_peers([rw08])
            record(g.generation, True)
        stop.set()

    def writer(wi: int):
        i = 0
        while not stop.is_set():
            i += 1
            x = keys[(wi + i) % len(keys)]
            gen_before = g.generation
            quorum = qs.choose_quorum_for(x, q.WRITE)
            topo_n = qs.shard_count()
            gen_after = g.generation
            if gen_before != gen_after:
                continue  # mutation mid-call: nothing to assert
            with lock:
                expect = present.get(gen_before)
            if expect is None:
                continue
            got = any(
                n.id == rw08.id
                for qc in quorum.qcs
                for n in qc.nodes
            )
            if got != expect:
                violations.append(
                    (wi, gen_before, expect, got)
                )
            if topo_n != 2:
                violations.append((wi, gen_before, "shards", topo_n))

    threads = [
        threading.Thread(target=writer, args=(wi,), daemon=True)
        for wi in range(4)
    ]
    churner = threading.Thread(target=churn, daemon=True)
    for t in threads:
        t.start()
    churner.start()
    churner.join(30)
    stop.set()
    for t in threads:
        t.join(10)
    assert not violations, violations[:5]
    # and the memos settled on the FINAL generation's world
    final = qs.choose_quorum_for(keys[0], q.WRITE)
    assert any(
        n.id == rw08.id for qc in final.qcs for n in qc.nodes
    )


def test_keyed_topology_generation_race(universe):
    """Same guard discipline for the shard topology memo: a routing
    table computed from the pre-mutation graph must not survive the
    mutation, or keys would keep routing to a dissolved clique."""
    g = build(universe, "u01")
    qs = WotQS(g)
    started = threading.Event()
    proceed = threading.Event()
    real = g.get_disjoint_cliques

    def stale_disjoint(min_size=4):
        res = real(min_size)
        started.set()
        assert proceed.wait(5)
        return res

    g.get_disjoint_cliques = stale_disjoint
    box = {}
    t = threading.Thread(
        target=lambda: box.setdefault("n", qs.shard_count())
    )
    t.start()
    assert started.wait(5)
    for name in ("b01", "b02", "b03", "b04"):
        g.remove_nodes([universe[name]])  # the b-clique dissolves
    proceed.set()
    t.join(5)
    g.get_disjoint_cliques = real
    assert box["n"] == 2  # the racer built from the old world...
    assert qs.shard_count() == 1  # ...but the memo did not keep it
