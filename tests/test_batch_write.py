"""The batched write pipeline (``Client.write_many`` + the BATCH_*
server handlers).

The batch path must keep exact single-``write`` semantics per item —
timestamp, quorum-certificate, equivocation, TOFU, write-once, and
collective-signature checks all still run on every replica — while the
three phases each cross the network once for the whole batch.  These
tests assert equivalence with the single path, per-item error
independence, and interop in both directions (batch-written values read
back through the normal quorum read; singly-written variables update
through the batch path).
"""

from __future__ import annotations

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu.errors import ERR_INVALID_TIMESTAMP, ERR_PERMISSION_DENIED
from bftkv_tpu.ops import dispatch
from tests.cluster_utils import start_cluster


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(4, 2, 4)
    yield c
    c.stop()


def test_write_many_roundtrip(cluster):
    c = cluster.clients[0]
    items = [(b"batch/x%d" % i, b"value-%d" % i) for i in range(8)]
    errs = c.write_many(items)
    assert errs == [None] * len(items)
    for var, val in items:
        assert c.read(var) == val


def test_write_many_interops_with_single_path(cluster):
    c = cluster.clients[0]
    # Singly-written variable updates through the batch path at t+1...
    c.write(b"batch/mix", b"v1")
    errs = c.write_many([(b"batch/mix", b"v2"), (b"batch/other", b"o1")])
    assert errs == [None, None]
    assert c.read(b"batch/mix") == b"v2"
    # ...and a batch-written variable updates through the single path.
    c.write(b"batch/other", b"o2")
    assert c.read(b"batch/other") == b"o2"


def test_write_many_per_item_errors_are_independent(cluster):
    c = cluster.clients[0]
    # A write-once variable rejects updates but must not sink the batch.
    c.write_once(b"batch/frozen", b"forever")
    errs = c.write_many(
        [(b"batch/frozen", b"mutate?"), (b"batch/live", b"fine")]
    )
    # Same mapping as the single path: an immutable variable surfaces at
    # the Time phase as maxt == 2^64-1 (client.go:90-92 analog).
    assert errs[0] == ERR_INVALID_TIMESTAMP
    assert errs[1] is None
    assert c.read(b"batch/frozen") == b"forever"
    assert c.read(b"batch/live") == b"fine"


def test_write_many_rejects_hidden_prefix_per_item(cluster):
    c = cluster.clients[0]
    errs = c.write_many(
        [(b"!!!secret!!!x", b"nope"), (b"batch/visible", b"yes")]
    )
    assert errs[0] == ERR_PERMISSION_DENIED
    assert errs[1] is None
    assert c.read(b"batch/visible") == b"yes"


def test_write_many_rejects_duplicate_variables(cluster):
    c = cluster.clients[0]
    with pytest.raises(ValueError):
        c.write_many([(b"batch/dup", b"a"), (b"batch/dup", b"b")])


def test_write_many_empty_batch(cluster):
    assert cluster.clients[0].write_many([]) == []


def test_write_many_monotonic_timestamps(cluster):
    """Repeated batches bump t exactly like repeated single writes."""
    c = cluster.clients[0]
    for round_no in range(3):
        errs = c.write_many([(b"batch/t", b"round-%d" % round_no)])
        assert errs == [None]
    assert c.read(b"batch/t") == b"round-2"
    srv = cluster.servers[0]
    stored = pkt.parse(srv.storage.read(b"batch/t", 0))
    assert stored.t == 3


def test_write_many_two_clients_see_each_other(cluster):
    """Client B's batch write at t, then client A single-writes at t+1
    (same-uid TOFU applies across users of the same uid universe)."""
    a, b = cluster.clients[0], cluster.clients[1]
    errs = b.write_many([(b"batch/shared-%d" % i, b"from-b") for i in range(4)])
    assert errs == [None] * 4
    assert a.read(b"batch/shared-0") == b"from-b"


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_write_many_with_dispatchers_installed(cluster):
    """The pipeline's device batches coalesce through the global
    dispatchers exactly like the single path."""
    dispatch.install()
    dispatch.install_signer()
    try:
        c = cluster.clients[0]
        items = [(b"batch/disp%d" % i, bytes([i]) * 64) for i in range(16)]
        assert c.write_many(items) == [None] * 16
        for var, val in items:
            assert c.read(var) == val
    finally:
        dispatch.uninstall_all()


def test_read_many_roundtrip(cluster):
    c = cluster.clients[0]
    items = [(b"rm/%d" % i, b"rv-%d" % i) for i in range(6)]
    assert c.write_many(items) == [None] * 6
    got = c.read_many([v for v, _ in items])
    assert got == [val for _, val in items]


def test_read_many_mixed_missing_and_errors(cluster):
    c = cluster.clients[0]
    c.write(b"rm/present", b"here")
    got = c.read_many([b"rm/present", b"rm/never-written", b"!!!secret!!!x"])
    assert got[0] == b"here"
    assert got[1] is None  # no data: every replica answers "empty"
    assert got[2] == ERR_PERMISSION_DENIED  # hidden prefix, per item


def test_read_many_repairs_stale_replica(cluster):
    """A replica that missed the write phase gets read-repaired by the
    batch.  The victim must be a node the READ quorum actually consults
    (a storage node: W = U − {Ci} + R lands writes there), and healing
    means the *collective signature* is back, not just the value."""
    import time

    c = cluster.clients[0]
    c.write(b"rm/heal", b"healthy")
    c.drain_tails()  # the collective back-fill rides the async tail
    victim = cluster.storage_servers[0]
    stored = victim.storage.read(b"rm/heal", 0)
    p = pkt.parse(stored)
    assert p.ss is not None and p.ss.completed  # precondition: healthy
    # Realistic staleness: the replica saw the sign request (persisted
    # without ss — the in-progress marker) but missed the write phase.
    victim.storage.write(
        b"rm/heal",
        p.t,
        pkt.serialize(b"rm/heal", p.value, p.t, p.sig, None),
    )
    got = c.read_many([b"rm/heal"])
    assert got == [b"healthy"]
    deadline = time.time() + 5
    healed = False
    while time.time() < deadline and not healed:
        rp = pkt.parse(victim.storage.read(b"rm/heal", 0))
        healed = rp.ss is not None and rp.ss.completed
        if not healed:
            time.sleep(0.05)
    assert healed, "stale replica was not repaired by read_many"


def test_concurrent_overlapping_batches_converge(cluster):
    """Two clients batch-writing OVERLAPPING variables concurrently:
    every per-item outcome is success or one of the protocol's conflict
    errors, and afterwards each variable reads back as ONE consistent
    value on a quorum (a written value — or nothing, when the conflict
    sank both writers).  Mirrors the reference's concurrency scenarios
    (rw_test.go) on the batch path."""
    import threading

    from bftkv_tpu.errors import (
        ERR_BAD_TIMESTAMP,
        ERR_EQUIVOCATION,
        ERR_INSUFFICIENT_NUMBER_OF_RESPONSES,
        ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES,
        ERR_INVALID_SIGN_REQUEST,
    )

    a, b = cluster.clients[0], cluster.clients[1]
    shared = [b"conc/%d" % i for i in range(6)]
    outcomes: dict = {}

    def run(tag, client):
        try:
            outcomes[tag] = client.write_many(
                [(v, b"%s-val" % tag) for v in shared]
            )
        except Exception as e:  # keep the real failure, not a KeyError
            outcomes[tag] = e

    ts = [
        threading.Thread(target=run, args=(t, c))
        for t, c in ((b"A", a), (b"B", b))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    conflict_errors = (
        ERR_BAD_TIMESTAMP,
        ERR_EQUIVOCATION,
        ERR_INSUFFICIENT_NUMBER_OF_RESPONSES,
        ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES,
        ERR_INVALID_SIGN_REQUEST,
    )
    for tag in (b"A", b"B"):
        assert isinstance(outcomes[tag], list), outcomes[tag]
        for err in outcomes[tag]:
            assert err is None or err in conflict_errors, err

    for v in shared:
        got = a.read(v)
        # A conflict may sink both writers (neither reaches quorum);
        # what must never happen is a torn or reader-dependent value.
        assert got in (b"A-val", b"B-val", None), (v, got)
        assert b.read(v) == got


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_batch_pipeline_at_64_replicas():
    """BASELINE-scale smoke: the batch pipeline through a 64-replica +
    8-storage-node universe (1024-bit keys keep the host-crypto CPU
    lane tolerable).  Catches scale-only regressions — quorum
    construction, fan-out sizing, per-item accounting — that 4-node
    clusters cannot."""
    c = start_cluster(64, 1, 8, bits=1024)
    try:
        cl = c.clients[0]
        items = [(b"s64/%d" % i, b"v%d" % i) for i in range(8)]
        assert cl.write_many(items) == [None] * 8
        assert cl.read_many([v for v, _ in items]) == [
            val for _, val in items
        ]
    finally:
        c.stop()


def test_write_many_over_http():
    """One batched round over real localhost HTTP sockets."""
    c = start_cluster(4, 1, 4, transport="http")
    try:
        client = c.clients[0]
        items = [(b"hb/%d" % i, b"http-%d" % i) for i in range(6)]
        assert client.write_many(items) == [None] * 6
        for var, val in items:
            assert client.read(var) == val
        assert client.read_many([v for v, _ in items]) == [
            val for _, val in items
        ]
    finally:
        c.stop()


def test_batch_frame_cert_survives_rejected_carrier(cluster):
    """Mid-join writer: replicas lack the writer's cert and the batch
    pipeline embeds it on the FIRST item only.  If that carrier item is
    itself rejected (hidden prefix), the frame-level cert harvest must
    still resolve the remaining items' signer (round-5 review finding:
    the harvest originally ran after the per-item policy checks)."""
    c = cluster.clients[0]
    cid = c.crypt.signer.cert.id
    saved = []
    for s in cluster.all_servers:
        cert = s.crypt.keyring.get(cid)
        if cert is not None:
            saved.append((s, cert))
            s.crypt.keyring.remove([cid])
    try:
        errs = c.write_many(
            [
                (b"!!!secret!!!carrier", b"nope"),
                (b"batch/after-carrier", b"survives"),
            ]
        )
        assert errs[0] == ERR_PERMISSION_DENIED
        assert errs[1] is None, errs[1]
        assert c.read(b"batch/after-carrier") == b"survives"
    finally:
        for s, cert in saved:
            s.crypt.keyring.register([cert])


def test_batch_overwrite_by_midjoin_writer(cluster):
    """Mid-join writer OVERWRITES through the batch path.  TOFU in
    ``_write_storage_checks`` resolves new_issuer for items 2..B from
    the frame-level cert harvest, and prev_issuer from the stored
    record — which ``_batch_sign`` must persist self-contained (the
    carrier's cert restored) or all later overwrites of the variable
    fail until join gossip lands the writer's cert (round-5 review
    finding)."""
    c = cluster.clients[0]
    cid = c.crypt.signer.cert.id
    saved = []
    for s in cluster.all_servers:
        cert = s.crypt.keyring.get(cid)
        if cert is not None:
            saved.append((s, cert))
            s.crypt.keyring.remove([cid])
    variables = [b"batch/midjoin-ow-%d" % i for i in range(3)]
    try:
        errs = c.write_many([(v, b"gen1-" + v) for v in variables])
        assert errs == [None] * 3, errs
        # Overwrite through the batch path: every item, not just the
        # cert-carrying first one, must pass TOFU on every replica.
        errs = c.write_many([(v, b"gen2-" + v) for v in variables])
        assert errs == [None] * 3, errs
        for v in variables:
            assert c.read(v) == b"gen2-" + v
        # And the single path can overwrite a batch-written variable
        # mid-join too (prev_issuer comes from the stored record).
        c.write(variables[1], b"gen3")
        assert c.read(variables[1]) == b"gen3"
    finally:
        for s, cert in saved:
            s.crypt.keyring.register([cert])
