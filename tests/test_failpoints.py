"""Failpoint registry + fault-hardened client path (bftkv_tpu/faults,
transport retry/deadline/circuit-breaker).

The registry tests are pure units; the injection tests run a real
4+4 loopback cluster and assert the BFT masking property under each
fault class: a minority-link fault is absorbed, a majority fault fails
cleanly, and the hardened client path (retries, per-RPC deadlines,
peer circuit breaking) keeps honest traffic fast around dead peers."""

from __future__ import annotations

import time
import types

import pytest

from bftkv_tpu import transport as tp
from bftkv_tpu.errors import Error
from bftkv_tpu.faults import byzantine as byz
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.storage.memkv import MemStorage

from cluster_utils import start_cluster

BITS = 1024  # keygen speed; fault injection is bits-agnostic


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends disarmed — a leaked rule would bleed
    faults into unrelated tests."""
    fp.disarm()
    yield
    fp.disarm()


# -- registry units --------------------------------------------------------


def test_disarmed_fire_is_noop():
    assert fp.ARMED is False
    assert fp.fire("transport.send", dst="x") is None
    assert fp.registry.trace() == []


def test_same_seed_identical_fault_trace():
    """The tentpole determinism contract: one seed, one fault trace."""

    def run(seed: int):
        reg = fp.arm(seed)
        reg.add("transport.send", "drop", prob=0.4, rule_id="d")
        reg.add("storage.write", "io_error", prob=0.2, rule_id="io")
        fired = []
        for i in range(50):
            fired.append(fp.fire("transport.send", dst=f"n{i % 4}") is not None)
            fired.append(fp.fire("storage.write", backend="mem") is not None)
        return fired, [tuple(e) for e in reg.trace()]

    f1, t1 = run(7)
    f2, t2 = run(7)
    f3, t3 = run(8)
    assert f1 == f2 and t1 == t2
    assert t1 and t3 != t1  # a different seed is a different schedule
    assert any(f1) and not all(f1)


def test_rule_match_times_and_params():
    reg = fp.arm(0)
    rule = reg.add(
        "transport.send",
        "delay",
        match={"dst": "rw01", "cmd": lambda c: c in ("time", "read")},
        times=2,
        seconds=0.5,
        rule_id="m",
    )
    assert fp.fire("transport.send", dst="rw02", cmd="time") is None
    assert fp.fire("transport.send", dst="rw01", cmd="write") is None
    act = fp.fire("transport.send", dst="rw01", cmd="time")
    assert act is not None and act.kind == "delay"
    assert fp.delay_seconds(act) == 0.5
    assert fp.fire("transport.send", dst="rw01", cmd="read") is not None
    # times=2 exhausted
    assert fp.fire("transport.send", dst="rw01", cmd="time") is None
    assert rule.fires == 2
    reg.remove(rule)
    assert fp.fire("transport.send", dst="rw01", cmd="time") is None


def test_delay_seconds_draw_is_deterministic_and_bounded():
    reg = fp.arm(5)
    reg.add(
        "dispatch.flush", "stall", seconds=0.01, max_seconds=0.05, rule_id="s"
    )
    a1 = fp.fire("dispatch.flush", name="dispatch")
    d1 = fp.delay_seconds(a1)
    assert 0.01 <= d1 <= 0.05
    reg2 = fp.arm(5)
    reg2.add(
        "dispatch.flush", "stall", seconds=0.01, max_seconds=0.05, rule_id="s"
    )
    assert fp.delay_seconds(fp.fire("dispatch.flush", name="dispatch")) == d1


def test_corrupt_bytes_changes_payload_preserves_length():
    data = bytes(range(64))
    out = fp.corrupt_bytes(data, 0.37)
    assert out != data and len(out) == len(data)
    assert fp.corrupt_bytes(b"", 0.5) == b""


def test_link_of_normalization():
    assert fp.link_of("loop://a01") == "a01"
    assert fp.link_of("http://127.0.0.1:6001/bftkv/v1/read") == "127.0.0.1:6001"
    assert fp.link_of("a01") == "a01"


def test_memstorage_io_error_failpoint():
    st = MemStorage()
    fp.arm(1)
    fp.registry.add(
        "storage.write", "io_error", match={"backend": "mem"}, times=1
    )
    with pytest.raises(OSError):
        st.write(b"x", 1, b"v")
    st.write(b"x", 1, b"v")  # times exhausted: back to normal
    assert st.read(b"x") == b"v"


def test_nemesis_plan_is_pure_function_of_seed():
    from bftkv_tpu.faults.nemesis import STEP_KINDS, Nemesis

    dummy = types.SimpleNamespace(
        names=lambda storage_only=True: ["rw01", "rw02", "rw03", "rw04"]
    )
    p1 = Nemesis(dummy, seed=7).plan(8)
    p2 = Nemesis(dummy, seed=7).plan(8)
    p3 = Nemesis(dummy, seed=9).plan(8)
    assert p1 == p2
    assert p3 != p1
    kinds = {s["kind"] for s in p1}
    assert kinds <= set(STEP_KINDS)
    # route_flap needs the autopilot + a sharded cluster; on anything
    # else the seeded plan degrades it to a partition, so the schedule
    # stays runnable (and replayable) everywhere.
    assert "route_flap" not in kinds


# -- live-cluster injection ------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(n_servers=4, n_users=1, n_rw=4, bits=BITS)
    try:
        yield c
    finally:
        c.stop()


def test_corrupt_on_minority_link_is_masked(cluster):
    """Corrupting every payload to one replica breaks its session
    decrypt, but the 3-of-4 quorum masks it — the write commits."""
    cl = cluster.clients[0]
    fp.arm(21)
    before = metrics.snapshot()
    fp.registry.add(
        "transport.send", "corrupt", match={"dst": "rw01"}, rule_id="c"
    )
    cl.write(b"fp_corrupt", b"survives")
    assert cl.read(b"fp_corrupt") == b"survives"
    snap = metrics.snapshot()
    key = "faults.fired{action=corrupt,point=transport.send}"
    assert snap.get(key, 0) > before.get(key, 0)


def test_drop_beyond_f_fails_write_cleanly(cluster):
    """Dropping the links to THREE of four write replicas must fail the
    write with a protocol error, not hang or corrupt.

    Three, not two: the write-class clauses commit at f+1 = 2 acks, so
    a 2-drop write can legitimately COMMIT on the two surviving
    replicas — the old 2-drop version only failed while the instant
    drop errors outraced the surviving replicas' handler work and
    tripped the eager fail-fast, a race the hot-loop overhaul flipped."""
    cl = cluster.clients[0]
    fp.arm(22)
    fp.registry.add(
        "transport.send",
        "drop",
        # Both write-plane commands: the collapsed round (write_sign)
        # carries the commit, the classic round (write) the fallback
        # and back-fill.
        match={
            "dst": lambda d: d in ("rw02", "rw03", "rw04"),
            "cmd": lambda c: c in ("write", "write_sign"),
        },
        rule_id="d2",
    )
    with pytest.raises(Error):
        cl.write(b"fp_majority", b"nope")
    fp.disarm()
    cl.write(b"fp_majority", b"now ok")
    assert cl.read(b"fp_majority") == b"now ok"


def test_retry_recovers_transient_drop(cluster):
    """A drop that clears after two attempts is absorbed by the bounded
    jittered-backoff retry policy; transport.retries counts it."""
    cl = cluster.clients[0]
    fp.arm(23)
    fp.registry.add(
        "transport.send",
        "drop",
        match={"dst": "rw01", "cmd": "write_sign"},
        times=2,
        rule_id="r",
    )
    before = metrics.snapshot()
    cl.tr.retry_policy = tp.RetryPolicy(retries=3, backoff=0.01)
    try:
        cl.write(b"fp_retry", b"retried")
    finally:
        del cl.tr.retry_policy
    assert cl.read(b"fp_retry") == b"retried"
    # The write commits at the quorum threshold and no longer blocks on
    # the dropped peer's response (hedged staged fan-out, DESIGN.md
    # §13) — its retries complete in a background worker, so poll for
    # the counter instead of snapshotting immediately.
    key = "transport.retries{cmd=write_sign}"
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if metrics.snapshot().get(key, 0) >= before.get(key, 0) + 2:
            break
        time.sleep(0.02)
    snap = metrics.snapshot()
    assert snap.get(key, 0) >= before.get(key, 0) + 2


def test_rpc_deadline_bounds_injected_delay(cluster):
    """A 30 s chaos delay on one link becomes a fast per-RPC timeout
    under a 0.2 s deadline — the quorum absorbs the timed-out peer and
    the op completes in bounded time."""
    cl = cluster.clients[0]
    fp.arm(24)
    fp.registry.add(
        "transport.send", "delay", match={"dst": "rw02"}, seconds=30.0,
        rule_id="slow",
    )
    old = cl.tr.rpc_timeout
    cl.tr.rpc_timeout = 0.2
    t0 = time.monotonic()
    try:
        cl.write(b"fp_deadline", b"fast")
        assert cl.read(b"fp_deadline") == b"fast"
    finally:
        cl.tr.rpc_timeout = old
    # 3 phases × ≤(a few) deadline hits on one peer: far below the 30 s
    # the injected delay would have cost without a deadline.
    assert time.monotonic() - t0 < 10.0


def test_circuit_breaker_skips_dead_peer_and_recovers(cluster):
    """Consecutive failures open the peer's circuit: posts are skipped
    instantly instead of eating the deadline every round.  A half-open
    probe after open_secs closes it again once the peer returns."""
    cl = cluster.clients[0]
    victim = cluster.server_named("rw04")
    health = tp.peer_health
    old = (health.enabled, health.threshold, health.open_secs)
    health.enabled, health.threshold, health.open_secs = True, 2, 0.2
    health.reset()
    before = metrics.snapshot()
    victim.tr.stop()  # the peer goes dark
    try:
        for i in range(3):
            cl.write(b"fp_cb_%d" % i, b"v")  # 3-of-4 carries each write
            # rw04 sits outside wave 1; each back-fill flush is what
            # posts to it — drain per write so the failures are
            # consecutive, not coalesced into one batch.
            cl.drain_tails()
        assert "loop://rw04" in health.open_peers()
        snap = metrics.snapshot()
        skipped = sum(
            v - before.get(k, 0)
            for k, v in snap.items()
            if k.startswith("transport.peer.skipped")
        )
        assert skipped > 0
        assert snap.get("transport.peer.opens", 0) > before.get(
            "transport.peer.opens", 0
        )

        victim.start()  # peer returns; wait past open_secs, then probe
        time.sleep(0.25)
        cl.write(b"fp_cb_back", b"v")
        cl.drain_tails()  # the back-fill flush carries the probe
        deadline = time.monotonic() + 5
        while health.open_peers() and time.monotonic() < deadline:
            cl.write(b"fp_cb_back", b"v")
            cl.drain_tails()
            time.sleep(0.05)
        assert "loop://rw04" not in health.open_peers()
        assert metrics.snapshot().get(
            "transport.peer.recovered", 0
        ) > before.get("transport.peer.recovered", 0)
    finally:
        victim.start()  # idempotent re-register
        health.enabled, health.threshold, health.open_secs = old
        health.reset()


def test_sync_round_abort_failpoint(cluster):
    import random

    from bftkv_tpu.sync import SyncDaemon

    srv = cluster.server_named("rw03")
    fp.arm(25)
    fp.registry.add("sync.round", "abort", match={"node": "rw03"}, rule_id="a")
    d = SyncDaemon(srv, interval=999, rng=random.Random(1))
    stats = d.run_round()
    assert stats.get("aborted") == 1
    assert stats["peers"] == 0


def test_admission_error_failpoint_is_masked_by_quorum(cluster):
    """An injected admission error on one replica (error reply on every
    write) is just another faulty replica to the quorum."""
    cl = cluster.clients[0]
    fp.arm(26)
    fp.registry.add(
        "server.admission",
        "error",
        match={"node": "rw02", "cmd": "write"},
        error="permission denied",
        rule_id="adm",
    )
    cl.write(b"fp_adm", b"ok")
    assert cl.read(b"fp_adm") == b"ok"


def test_colluder_program_equivalent_to_malserver(cluster):
    """The sign-anything colluder expressed as a failpoint program: an
    honest writer still commits and reads correctly (the colluder's
    unverified shares/stores create no authority)."""
    cl = cluster.clients[0]
    fp.arm(27)
    rules = byz.make_colluder(fp.registry, "rw01")
    try:
        cl.write(b"fp_byz", b"honest")
        # rw01 is wave-1 AND a colluder: the honest plane copies ride
        # the back-fill; settle it before reading.
        cl.drain_tails()
        assert cl.read(b"fp_byz") == b"honest"
    finally:
        fp.registry.remove_all(rules)
    assert any(r.fires for r in rules)  # the program actually ran


def test_custom_registry_dispatches_through_fire():
    """A harness-owned FaultRegistry becomes the active one on arm():
    hook sites (module-level fire) must see its rules."""
    reg = fp.FaultRegistry()
    reg.arm(5)
    try:
        reg.add("transport.send", "drop", rule_id="mine")
        act = fp.fire("transport.send", dst="anything")
        assert act is not None and act.rule.rule_id == "mine"
        assert [e.rule_id for e in reg.trace()] == ["mine"]
        assert fp.registry.trace() == []  # the singleton saw nothing
    finally:
        reg.disarm()
    assert fp.fire("transport.send", dst="anything") is None


def test_answered_error_closes_open_circuit():
    """A peer whose circuit opened while it was down must close it the
    moment it ANSWERS — even when the answer is an interned protocol
    error (a reply proves reachability)."""
    from bftkv_tpu.errors import ERR_PERMISSION_DENIED
    from bftkv_tpu.transport import _send

    health = tp.peer_health
    old = (health.enabled, health.threshold, health.open_secs)
    health.enabled, health.threshold, health.open_secs = True, 2, 0.05
    health.reset()

    class _Tr:
        def post(self, url, data):
            raise ERR_PERMISSION_DENIED

    try:
        for _ in range(2):
            health.fail("loop://p1")
        assert "loop://p1" in health.open_peers()
        time.sleep(0.06)  # half-open window
        with pytest.raises(ERR_PERMISSION_DENIED):
            _send(_Tr(), "loop://p1/bftkv/v1/read", b"x", "read", "loop://p1")
        assert "loop://p1" not in health.open_peers()
    finally:
        health.enabled, health.threshold, health.open_secs = old
        health.reset()
