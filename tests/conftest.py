"""Test configuration: run JAX on a virtual multi-device CPU mesh.

Real TPU hardware in CI has a single chip; all sharding tests use
``--xla_force_host_platform_device_count=N`` (default 8, override with
``BFTKV_TEST_DEVICES``) so multi-chip layouts compile and execute
without real chips.

The ambient environment may pre-import jax with an accelerator
platform selected (sitecustomize PJRT plugin registration), so env
vars alone are not enough — :mod:`bftkv_tpu.hostcpu` repairs the
already-imported jax in-process.  The real-TPU lane opts out with
``BFTKV_TPU_LANE=1``.
"""

import os

import pytest

if os.environ.get("BFTKV_TPU_LANE") != "1":
    from bftkv_tpu.hostcpu import force_cpu

    force_cpu(int(os.environ.get("BFTKV_TEST_DEVICES", "8")))


@pytest.fixture(scope="session", autouse=True)
def _lockwatch_gate():
    """The lockwatch pytest gate (DESIGN.md §16): with
    ``BFTKV_LOCKWATCH=1`` the whole tier runs under the runtime lock
    sanitizer, and any lock-order cycle or blocking-call-under-lock
    recorded across the session fails it here.  Disarmed (the default)
    this fixture is inert — ``named_lock`` returned plain stdlib locks
    and nothing was recorded."""
    yield
    from bftkv_tpu.devtools import lockwatch

    if lockwatch.enabled():
        msg = lockwatch.fail_message()
        assert msg is None, msg
