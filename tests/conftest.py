"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI has a single chip; all sharding tests use
``--xla_force_host_platform_device_count=8`` so multi-chip layouts
compile and execute without real chips.

The ambient environment may pre-import jax with an accelerator
platform selected (sitecustomize PJRT plugin registration), so env
vars alone are not enough — :mod:`bftkv_tpu.hostcpu` repairs the
already-imported jax in-process.  An explicit TPU lane can opt out
with ``BFTKV_TPU_LANE=1``.
"""

import os

if os.environ.get("BFTKV_TPU_LANE") != "1":
    from bftkv_tpu.hostcpu import force_cpu

    force_cpu(8)
