"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI has a single chip; all sharding tests use
``--xla_force_host_platform_device_count=8`` so multi-chip layouts
compile and execute without real chips.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
