"""BASELINE config 5: 256 simulated replicas, f = 85 colluders, batched
revoke-on-read tally.

The reference forges the read-response map directly and runs the
revocation logic with no servers (protocol/revoke_test.go:67-159);
this is the same pattern at 256 replicas, asserting (a) the honest
reader still converges on the honestly-quorate value, (b) exactly the
85 equivocators are revoked — zero safety violations — and (c) the
device tally path and the Python scan agree bit-for-bit.
"""

import pytest

from bftkv_tpu import topology
from bftkv_tpu.crypto import new_crypto
from bftkv_tpu.crypto.signature import serialize_entries
from bftkv_tpu.graph import Graph
from bftkv_tpu.packet import SIGNATURE_TYPE_NATIVE, SignaturePacket
from bftkv_tpu.protocol.client import Client, _SignedValue
from bftkv_tpu.quorum.wotqs import WotQS

UNIVERSE = 256
F_BYZ = 85
T = 7  # the forged timestamp

HONEST_A = list(range(0, 128))            # honest signers of value A
HONEST_B = list(range(128, 171))          # honest signers of value B (stale)
COLLUDERS = list(range(171, 256))         # signed both values


class _Ref:
    __slots__ = ("id", "name", "address", "active")

    def __init__(self, i):
        self.id = 1_000_000 + i
        self.name = f"r{i:03d}"
        self.address = ""
        self.active = True


class _RecordingTransport:
    def __init__(self):
        self.notified = []

    def multicast(self, cmd, peers, data, cb):
        self.notified.append((cmd, len(peers)))


class _MajorityQuorum:
    """Threshold = an honest-majority bucket (128 of 256)."""

    def is_threshold(self, nodes):
        return len(nodes) >= 128


def _ss_for(signers):
    return SignaturePacket(
        type=SIGNATURE_TYPE_NATIVE,
        version=1,
        completed=True,
        data=serialize_entries(
            [(1_000_000 + i, b"opaque-sig") for i in signers]
        ),
    )


def _forged_map():
    """m[t][value] = [_SignedValue per responding replica]."""
    replicas = [_Ref(i) for i in range(UNIVERSE)]
    ss_a = _ss_for(HONEST_A + COLLUDERS)
    ss_b = _ss_for(HONEST_B + COLLUDERS)
    m = {T: {}}
    m[T][b"value-A"] = [
        _SignedValue(replicas[i], None, ss_a, b"pktA")
        for i in HONEST_A + COLLUDERS
    ]
    m[T][b"value-B"] = [
        _SignedValue(replicas[i], None, ss_b, b"pktB")
        for i in HONEST_B + COLLUDERS
    ]
    return m


def _reader():
    ident = topology.new_identity("reader", bits=1024)
    graph = Graph()
    graph.set_self_nodes([ident.cert])
    crypt = new_crypto(ident.key, ident.cert)
    tr = _RecordingTransport()
    return Client(graph, WotQS(graph), tr, crypt), graph, tr


@pytest.mark.parametrize("batched", [True, False])
def test_bulk_revoke_identifies_exactly_the_colluders(batched):
    client, graph, tr = _reader()
    client.BATCH_REVOKE_THRESHOLD = 1 if batched else 10**9
    m = _forged_map()

    # (a) the honest reader converges on the honestly-quorate value
    value, maxt = client._max_timestamped_value(m, _MajorityQuorum())
    assert (value, maxt) == (b"value-A", T)

    # (b) revocation: exactly the 85 double-signers, nobody honest
    client._revoke_on_read(m)
    revoked = {1_000_000 + i for i in COLLUDERS}
    got = set(graph.revoked)
    assert got == revoked
    assert len(got) == F_BYZ

    # No NOTIFY broadcast here: none of the forged signer ids resolve
    # to known certificates, and only resolvable certs serialize into
    # the revocation list (reference: client.go:341-346 — same
    # property). The graph still blocks them from future quorums.
    assert not tr.notified


def test_batched_and_scan_paths_agree_on_random_overlaps():
    import random

    rng = random.Random(7)
    for _ in range(5):
        rows = [
            {rng.randrange(300) for _ in range(rng.randrange(1, 120))}
            for _ in range(rng.randrange(2, 6))
        ]
        batched = Client._equivocators_batched(rows)
        seen: dict[int, int] = {}
        scan = set()
        for rno, row in enumerate(rows):
            for sid in row:
                if sid in seen and seen[sid] != rno:
                    scan.add(sid)
                else:
                    seen.setdefault(sid, rno)
        assert batched == scan
