"""Multi-region WAN plane (DESIGN.md §21): the region model, the RTT
matrix grammar + link-delay program, the side-effect-free probe check
(``would_drop``), the WAN-correct gray baseline, and the locality axis
of health-aware staging — including the invariant that ranking can
never change which thresholds a quorum requires.
"""

from __future__ import annotations

import pytest

from bftkv_tpu import quorum as qm
from bftkv_tpu import regions as rg
from bftkv_tpu import topology
from bftkv_tpu import transport as tp
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.regions.topology import NAMED, RttMatrix, install_matrix
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.transport.latency import PeerLatency

from cluster_utils import start_cluster

BITS = 1024


@pytest.fixture(autouse=True)
def _clean_region_plane():
    rg.clear()
    yield
    fp.disarm()
    rg.clear()


# -- region map -------------------------------------------------------------


def test_empty_map_is_the_loopback_world():
    assert not rg.regionmap.installed()
    assert rg.region_of("a01") is None
    # In the loopback world every lookup is None, and None-vs-None is
    # local — region-aware sort keys collapse to a constant.
    assert rg.regionmap.rank(None, None) == 0.0
    assert rg.regionmap.rank("r0", None) == 0.0
    assert rg.regionmap.regions() == []


def test_install_indexes_names_and_link_ids():
    rg.install({"a01": "r0", "http://127.0.0.1:6001": "r1"})
    assert rg.region_of("a01") == "r0"
    # Address resolves in every form: verbatim, bare link id, and a
    # differently-pathed URL collapsing to the same link.
    assert rg.region_of("http://127.0.0.1:6001") == "r1"
    assert rg.region_of("127.0.0.1:6001") == "r1"
    assert rg.region_of("http://127.0.0.1:6001/path") == "r1"
    assert rg.region_of("unknown") is None
    assert rg.region_of(None) is None


def test_members_excludes_link_aliases():
    rg.install({"a01": "r0", "loop://a01": "r0", "a02": "r1"})
    assert rg.regionmap.members("r0") == ["a01"]
    assert rg.regionmap.regions() == ["r0", "r1"]


def test_rank_orders_by_rtt_when_matrix_installed():
    rg.install({"a": "r0", "b": "r1", "c": "r2"})
    assert rg.regionmap.rank("r0", "r0") == 0.0
    assert rg.regionmap.rank("r0", "r1") == 1.0  # no matrix: flat
    m = RttMatrix.parse("20/80/150", ["r0", "r1", "r2"])
    rg.regionmap.set_rtt(m)
    assert rg.regionmap.rank("r0", "r1") == pytest.approx(0.020)
    assert rg.regionmap.rank("r0", "r2") == pytest.approx(0.080)
    assert rg.regionmap.rank("r0", None) == 0.0  # unlabeled: local


# -- rtt matrix grammar -----------------------------------------------------


def test_matrix_pairwise_spec():
    m = RttMatrix.parse("20/80/150", ["r2", "r0", "r1"])  # unsorted in
    assert m.regions == ["r0", "r1", "r2"]
    assert m.intra_s == 0.0
    assert m.rtt("r0", "r1") == pytest.approx(0.020)
    assert m.rtt("r2", "r0") == pytest.approx(0.080)  # symmetric
    assert m.rtt("r1", "r2") == pytest.approx(0.150)
    assert m.min_cross_s() == pytest.approx(0.020)
    assert m.max_cross_s() == pytest.approx(0.150)


def test_matrix_intra_plus_pairwise_spec_and_named():
    m = RttMatrix.parse("wan2", ["r0", "r1"])
    assert NAMED["wan2"] == "20/60"
    assert m.intra_s == pytest.approx(0.020)
    assert m.rtt("r0", "r0") == pytest.approx(0.020)
    assert m.rtt("r0", "r1") == pytest.approx(0.060)
    assert m.name == "wan2"


def test_matrix_rejects_wrong_value_count_and_small_fleets():
    with pytest.raises(ValueError):
        RttMatrix.parse("20/80", ["r0", "r1", "r2"])  # 3 regions: 3 or 4
    with pytest.raises(ValueError):
        RttMatrix.parse("20", ["r0"])  # < 2 regions
    with pytest.raises(ValueError):
        RttMatrix.parse("not/a/spec", ["r0", "r1", "r2"])


# -- link-delay program + failpoint plane -----------------------------------


def test_delay_program_is_quiet_background_and_never_shadows_faults():
    rg.install({"a": "r0", "b": "r1"})
    reg = fp.arm(11)
    # One cross pair at 100 ms RTT → a 50 ms one-way rule each way.
    matrix, program = install_matrix(reg, "100", regions=["r0", "r1"])
    assert all(r.quiet and r.background for r in program.rules)
    assert len(program.rules) == 2
    act = reg._fire("transport.send", {"src": "a", "dst": "b"})
    assert act is not None and act.kind == "delay"
    assert act.params["seconds"] == pytest.approx(0.050)
    # Quiet: the fired delay is an environment, not a fault event.
    assert reg.trace() == []
    # Intra-region and unlabeled traffic never match.
    assert reg._fire("transport.send", {"src": "a", "dst": "a"}) is None
    assert reg._fire("transport.send", {"src": "", "dst": "b"}) is None
    # A foreground drop armed LATER at the same point wins the
    # first-match dispatch over the always-matching topology rule.
    reg.add("transport.send", "drop", match={"dst": "b"}, rule_id="cut")
    act = reg._fire("transport.send", {"src": "a", "dst": "b"})
    assert act is not None and act.kind == "drop"
    # The regionmap learned the matrix for distance ranking.
    assert rg.regionmap.rank("r0", "r1") == pytest.approx(0.100)
    assert matrix.min_cross_s() == pytest.approx(0.100)


def test_would_drop_is_side_effect_free_and_respects_budget():
    reg = fp.arm(7)
    rule = reg.add(
        "transport.send", "drop", match={"dst": "b"}, times=1,
        rule_id="once",
    )
    assert reg.would_drop("transport.send", dst="b")
    assert not reg.would_drop("transport.send", dst="a")
    # No side effects: budgets, draws, and the trace are untouched.
    assert rule._evals == 0 and rule._fires == 0
    assert reg.trace() == []
    # A spent fire budget stops matching — the probe sees the heal.
    assert reg._fire("transport.send", {"dst": "b"}).kind == "drop"
    assert not reg.would_drop("transport.send", dst="b")
    # Delay rules are not drops: geography never reads as a partition.
    reg.add("transport.send", "delay", seconds=0.01, rule_id="slow")
    assert not reg.would_drop("transport.send", dst="c")


# -- WAN-correct gray detection (transport.latency) -------------------------


def test_fleet_baseline_compares_within_region_class_only():
    """A cross-region peer's legitimately higher p50 is geography, not
    grayness — but a peer slow against its OWN region class still
    flags.  This is the WAN regression the fleet-relative baseline
    shipped with: without the class restriction every far peer sits
    3x above the near median and all of geography turns gray."""
    rg.install({"a": "r0", "b": "r0", "c": "r0", "z": "r1", "d": "r0"})
    pl = PeerLatency()
    for _ in range(6):
        for near in ("a", "b", "c"):
            pl.record(near, 0.010)
    # Far peer: steady 1 s p50 — multiples above the near median, but
    # normal for its distance.  No other r1 peer → no class baseline →
    # only the self-relative rule applies, and a steady p50 never
    # trips it.
    for _ in range(6):
        pl.record("z", 1.0)
    assert not pl.is_gray("z")
    # Same-region straggler: judged against its own class's 10 ms
    # median, so its steady 1 s p50 IS persistent grayness.
    for _ in range(6):
        pl.record("d", 1.0)
    assert pl.is_gray("d")


def test_fleet_baseline_unchanged_without_region_map():
    """No region map → one class (None) for everyone: the pre-region
    behavior, bit-for-bit."""
    pl = PeerLatency()
    for _ in range(6):
        for near in ("a", "b", "c"):
            pl.record(near, 0.010)
    for _ in range(6):
        pl.record("z", 1.0)
    assert pl.is_gray("z")


# -- locality-aware staging -------------------------------------------------


@pytest.fixture()
def wan_cluster():
    # The health singletons are process-global: scrub signals earlier
    # tests may have left on the same loop:// addresses.
    tp.peer_latency.reset()
    tp.peer_health.reset()
    c = start_cluster(
        4, 1, 4, bits=BITS, storage_factory=MemStorage, n_regions=3
    )
    yield c
    c.stop()
    tp.peer_latency.reset()
    tp.peer_health.reset()


def test_rank_nodes_puts_same_region_first_and_orders_by_rtt(wan_cluster):
    cl = wan_cluster.clients[0]  # u01 → r0
    qa = qm.choose_quorum_for(cl.qs, b"regions/x", qm.AUTH | qm.PEER)
    nodes = qa.nodes()
    m = RttMatrix.parse("20/80/150", rg.regionmap.regions())
    rg.regionmap.set_rtt(m)
    ranked = cl._rank_nodes(nodes)
    order = [rg.region_of(n.name) for n in ranked]
    # Same-region members form the prefix; the tail orders by matrix
    # distance (r1 at 20 ms before r2 at 80 ms from r0).
    n_same = order.count("r0")
    assert n_same >= 1
    assert all(r == "r0" for r in order[:n_same])
    assert order[n_same:] == ["r1", "r2"]


def test_ranking_is_a_permutation_and_never_changes_thresholds(wan_cluster):
    cl = wan_cluster.clients[0]
    qa = qm.choose_quorum_for(cl.qs, b"regions/y", qm.AUTH | qm.PEER)
    nodes = qa.nodes()
    m = RttMatrix.parse("20/80/150", rg.regionmap.regions())
    rg.regionmap.set_rtt(m)
    ranked = cl._rank_nodes(nodes)
    assert sorted(n.id for n in ranked) == sorted(n.id for n in nodes)
    # Quorum predicates are set functions: any permutation of the same
    # member set answers identically — ordering chooses who is ASKED
    # first, never what the quorum REQUIRES.
    assert qa.is_threshold(ranked) == qa.is_threshold(nodes)
    assert qa.is_sufficient(ranked) == qa.is_sufficient(nodes)
    for k in range(1, len(ranked) + 1):
        prefix = ranked[:k]
        shuffled = sorted(prefix, key=lambda n: n.id)
        assert qa.is_sufficient(prefix) == qa.is_sufficient(shuffled)
        assert qa.is_threshold(prefix) == qa.is_threshold(shuffled)


def test_cross_region_members_ride_the_hedge_wave_not_the_prefix(
    wan_cluster,
):
    """The staged first wave is the minimal sufficient prefix of the
    ranked order: with two of the four clique seats local, it holds
    both local seats plus the NEAREST cross-region one — the farthest
    region is asked only on shortfall (the hedge/expansion path)."""
    from bftkv_tpu.protocol.client import _staged_wave

    cl = wan_cluster.clients[0]
    qa = qm.choose_quorum_for(cl.qs, b"regions/z", qm.AUTH | qm.PEER)
    m = RttMatrix.parse("20/80/150", rg.regionmap.regions())
    rg.regionmap.set_rtt(m)
    ranked = cl._rank_nodes(qa.nodes())
    wave1, rest = _staged_wave(qa, ranked)
    assert qa.is_sufficient(wave1)
    assert not qa.is_sufficient(wave1[:-1])  # minimal, not padded
    assert all(rg.region_of(n.name) != "r2" for n in wave1)
    assert [rg.region_of(n.name) for n in rest] == ["r2"]


def test_rank_nodes_region_axis_gated_by_flag(wan_cluster, monkeypatch):
    monkeypatch.setenv("BFTKV_REGION_RANK", "off")
    cl = wan_cluster.clients[0]
    qa = qm.choose_quorum_for(cl.qs, b"regions/g", qm.AUTH | qm.PEER)
    m = RttMatrix.parse("20/80/150", rg.regionmap.regions())
    rg.regionmap.set_rtt(m)
    nodes = qa.nodes()
    ranked = cl._rank_nodes(nodes)
    # Flag off: the locality axis is inert — with no health signal the
    # quorum's own order is preserved bit-for-bit.
    assert [n.id for n in ranked] == [n.id for n in nodes]


# -- region labels across the topology plane --------------------------------


def test_build_universe_round_robin_and_home_roundtrip(tmp_path):
    uni = topology.build_universe(
        4, 2, 2, bits=BITS, n_gateways=1, n_regions=3
    )
    assert [i.region for i in uni.servers] == ["r0", "r1", "r2", "r0"]
    assert [i.region for i in uni.storage_nodes] == ["r0", "r1"]
    assert [i.region for i in uni.users] == ["r0", "r1"]
    assert [i.region for i in uni.gateways] == ["r0"]
    # Universe.regions maps names AND addresses.
    assert uni.regions["a02"] == "r1"
    assert uni.regions[uni.servers[1].cert.address] == "r1"
    # save_home writes the regions file; load_home merges it into the
    # process-global map (the localtrust pattern).
    ident = uni.users[0]
    home = str(tmp_path / ident.name)
    topology.save_home(
        home, ident, uni.view_of(ident), regions=uni.regions
    )
    rg.clear()
    topology.load_home(home)
    assert rg.region_of("a02") == "r1"
    assert rg.region_of(ident.name) == "r0"
