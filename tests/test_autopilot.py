"""Topology autopilot (DESIGN.md §15): pure decisions, the 3-phase
split executor under live writes (the CI "Split smoke"), clique
retirement with the recorded-history handoff check, and spare
admission through the graph-generation guards."""

import threading

import pytest

from bftkv_tpu import quorum as q
from bftkv_tpu.autopilot import Autopilot, Plan, decide
from bftkv_tpu.autopilot.plan import next_table
from bftkv_tpu.quorum.wotqs import ROUTE_BUCKETS


# -- decisions (pure) -----------------------------------------------------


def test_decide_nothing_on_balance():
    owner = [b % 2 for b in range(ROUTE_BUCKETS)]
    load = [1] * ROUTE_BUCKETS
    assert decide({0: 1, 1: 1}, load, owner, 2) is None


def test_decide_split_hot_shard():
    owner = [b % 2 for b in range(ROUTE_BUCKETS)]
    load = [0] * ROUTE_BUCKETS
    for b in range(0, 40, 2):  # hot buckets all on shard 0
        load[b] = 50
    plan = decide({0: 1, 1: 1}, load, owner, 2)
    assert plan is not None and plan.kind == "split" and plan.shard == 0
    assert plan.assign and set(plan.assign.values()) == {1}
    # only observed-hot buckets move, roughly half the hot load
    assert all(load[b] > 0 for b in plan.assign)


def test_decide_retire_beats_split():
    owner = [b % 2 for b in range(ROUTE_BUCKETS)]
    load = [10] * ROUTE_BUCKETS
    plan = decide({0: 1, 1: -1}, load, owner, 2)
    assert plan is not None and plan.kind == "retire" and plan.shard == 1
    assert set(plan.assign) == {
        b for b in range(ROUTE_BUCKETS) if owner[b] == 1
    }
    assert set(plan.assign.values()) == {0}
    # retire needs a healthy destination
    assert decide({0: -1, 1: -1}, load, owner, 2) is None
    # and at least two shards
    assert decide({0: -1}, load, [0] * ROUTE_BUCKETS, 1) is None


def test_decide_ignores_tiny_load():
    owner = [b % 2 for b in range(ROUTE_BUCKETS)]
    load = [0] * ROUTE_BUCKETS
    load[0] = 5
    assert decide({0: 1, 1: 1}, load, owner, 2) is None


def test_autopilot_hatch(monkeypatch):
    from bftkv_tpu.autopilot import autopilot_enabled

    assert autopilot_enabled()
    monkeypatch.setenv("BFTKV_AUTOPILOT", "off")
    assert not autopilot_enabled()


# -- live clusters --------------------------------------------------------


@pytest.fixture(scope="module")
def split_cluster():
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(4, 2, 4, bits=1024, n_shards=2)
    yield cluster
    cluster.stop()


def hot_keys_for(qs, shard, n, tag=b"hot"):
    out, i = [], 0
    while len(out) < n and i < 65536:
        k = b"%s/%d" % (tag, i)
        i += 1
        if qs.shard_of(k) == shard:
            out.append(k)
    return out


def test_split_smoke(split_cluster):
    """The CI tier-1 "Split smoke": a hot-shard workload on a 2-clique
    loopback fleet triggers an automatic split; writes keep succeeding
    ACROSS the flip; the moved keys' history and new writes are
    readable afterwards; every member lands on the finalize epoch."""
    cluster = split_cluster
    cl = cluster.clients[0]
    qs = cl.qs
    keys = hot_keys_for(qs, 0, 16)
    for k in keys:
        cl.write(k, b"v1-" + k)
    cl.drain_tails()

    ap = Autopilot.for_cluster(cluster)
    plan = ap.decide()
    assert plan is not None and plan.kind == "split" and plan.shard == 0

    stop = threading.Event()
    failures: list = []
    writes_ok = [0]

    def writer():
        i = 0
        while not stop.is_set():
            i += 1
            k = keys[i % len(keys)]
            try:
                cl.write(k, b"w%d-" % i + k)
                writes_ok[0] += 1
            except Exception as e:
                failures.append(e)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        report = ap.execute(plan, pace=0.1)
    finally:
        stop.set()
        t.join(10)
    cl.drain_tails()

    assert report["ok"], report
    assert not failures, failures[:3]
    assert writes_ok[0] > 0  # availability never dropped to zero
    moved = [k for k in keys if qs.shard_of(k) == 1]
    assert moved, "no hot key rerouted by the split"
    # every member + client on the finalize epoch
    epochs = {s.qs.route_epoch() for s in cluster.all_servers}
    epochs |= {c.qs.route_epoch() for c in cluster.clients}
    assert epochs == {report["final_epoch"]}
    # history and fresh writes readable after the flip
    for k in keys:
        assert cl.read(k) is not None
    for k in moved[:4]:
        cl.write(k, b"post-" + k)
    cl.drain_tails()
    for k in moved[:4]:
        assert cl.read(k) == b"post-" + k
    # migrated history re-certified against the NEW owner clique: a
    # new-owner replica verifies its stored record with its own quorum
    from bftkv_tpu import packet as pkt
    from bftkv_tpu.sync.digest import latest_completed

    new_members = [
        s
        for s in cluster.all_servers
        if s.qs.shard_index_of(s.self_node.get_self_id()) == 1
    ]
    checked = 0
    for srv in new_members:
        for k in moved:
            rec = latest_completed(srv.storage, k)
            if rec is None:
                continue
            _t, raw, p = rec
            srv.crypt.collective.verify(
                pkt.tbss(raw),
                p.ss,
                q.choose_quorum_for(srv.qs, k, q.AUTH),
                srv.crypt.keyring,
            )
            checked += 1
    assert checked > 0


def test_status_and_last_decision(split_cluster):
    ap = Autopilot.for_cluster(split_cluster)
    st = ap.status()
    assert "enabled" in st and "epoch" in st and "last" in st


def test_retire_spent_clique():
    """Retiring a clique whose f-budget is exhausted: every bucket's
    certified records must be readable from the new owner BEFORE the
    old clique stops being routed to (the recorded-history check), and
    new writes re-route off the hinted declines."""
    from bftkv_tpu.faults.harness import build_cluster

    cluster = build_cluster(4, 1, 4, bits=1024, n_shards=2)
    try:
        cl = cluster.clients[0]
        qs = cl.qs
        keys = hot_keys_for(qs, 1, 10, tag=b"ret")
        for k in keys:
            cl.write(k, b"v-" + k)
        cl.drain_tails()

        ap = Autopilot.for_cluster(cluster)
        owner = qs.effective_route()
        assign = {
            b: 0 for b in range(ROUTE_BUCKETS) if owner[b] == 1
        }
        report = ap.execute(Plan("retire", 1, assign, reason="test"))
        assert report["ok"], report
        # the recorded-history check ran clean pre-flip
        assert "handoff_misses" not in report
        assert ap.verify_handoff(
            set(assign),
            [
                s
                for s in cluster.all_servers
                if s.qs.shard_index_of(s.self_node.get_self_id()) == 1
            ],
            [
                s
                for s in cluster.all_servers
                if s.qs.shard_index_of(s.self_node.get_self_id()) == 0
            ],
        ) == []
        # every certified record readable via the surviving clique
        for k in keys:
            assert cl.read(k) == b"v-" + k
            assert qs.shard_of(k) == 0
        for k in keys[:4]:
            cl.write(k, b"v2-" + k)
        cl.drain_tails()
        for k in keys[:4]:
            assert cl.read(k) == b"v2-" + k
        assert 1 in ap.status()["retired"]
    finally:
        cluster.stop()


def test_split_snapshot_precopy_log_backed(tmp_path):
    """DESIGN.md §19.5: a split over log-backed replicas bulk-ships
    sealed-segment snapshots through the full admission path before
    the converge loop, with zero failed writes during the migration
    and the moved history readable from the new owners afterwards."""
    import itertools
    import os as _os

    from bftkv_tpu.faults.harness import build_cluster
    from bftkv_tpu.storage.logkv import LogStorage

    counter = itertools.count()
    root = str(tmp_path / "logs")

    def factory():
        return LogStorage(
            _os.path.join(root, "replica-%03d" % next(counter)),
            fsync=False,
            segment_bytes=1 << 16,
        )

    cluster = build_cluster(
        4, 1, 4, bits=1024, n_shards=2, storage_factory=factory
    )
    try:
        cl = cluster.clients[0]
        qs = cl.qs
        keys = hot_keys_for(qs, 0, 16, tag=b"snap")
        for k in keys:
            cl.write(k, b"v-" + k)
        cl.drain_tails()

        ap = Autopilot.for_cluster(cluster)
        owner = qs.effective_route()
        shard0 = [b for b in range(ROUTE_BUCKETS) if owner[b] == 0]
        assign = {b: 1 for b in shard0[: len(shard0) // 2]}

        stop = threading.Event()
        failures: list = []

        def writer():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    cl.write(keys[i % len(keys)], b"w%d" % i)
                except Exception as e:  # pragma: no cover - must not fire
                    failures.append(e)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            report = ap.execute(Plan("split", 0, assign, reason="test"))
        finally:
            stop.set()
            t.join(10)
        cl.drain_tails()

        assert report["ok"], report
        # log-backed old owners expose snapshot_records(); the pre-copy
        # stage must actually ship admitted records, not fall back to
        # the per-variable converge loop alone
        assert report.get("snapshot_shipped", 0) > 0, report
        assert not failures, failures[:3]
        for k in keys:
            assert cl.read(k) is not None
        moved = [k for k in keys if qs.shard_of(k) == 1]
        assert moved, "no key rerouted by the split"
        for k in moved[:4]:
            cl.write(k, b"post-" + k)
        cl.drain_tails()
        for k in moved[:4]:
            assert cl.read(k) == b"post-" + k
    finally:
        cluster.stop()


def test_decide_retire_from_real_f_budget():
    """The full detect→decide loop for retirement: crash enough of one
    clique that the fleet collector's f-budget hits zero, and the
    autopilot's next decision is to retire that clique."""
    from bftkv_tpu import trace as trmod
    from bftkv_tpu.faults.harness import build_cluster
    from bftkv_tpu.metrics import registry as mreg
    from bftkv_tpu.obs import FleetCollector, LocalSource

    cluster = build_cluster(4, 1, 4, bits=1024, n_shards=2)
    try:
        cl = cluster.clients[0]
        keys = hot_keys_for(cl.qs, 1, 6, tag=b"fb")
        for k in keys:
            cl.write(k, b"v-" + k)
        cl.drain_tails()
        collector = FleetCollector(
            [
                LocalSource(
                    name,
                    lambda n=name: cluster.server_named(n),
                )
                for name in sorted(cluster._by_name)
            ],
            local_metrics=mreg,
            local_tracer=trmod.tracer,
        )
        collector.scrape_once()
        ap = Autopilot.for_cluster(cluster, collector=collector)
        # healthy fleet: no retirement decision
        plan = ap.decide()
        assert plan is None or plan.kind != "retire"
        # shard 1's clique loses f+1 members: budget exhausted
        byid = {
            s.qs.shard_index_of(s.self_node.get_self_id()): []
            for s in cluster.servers
        }
        for s in cluster.servers:
            byid[
                s.qs.shard_index_of(s.self_node.get_self_id())
            ].append(s.self_node.name)
        for name in byid[1][:2]:  # f=1 for a 4-clique: 2 down = spent
            cluster.crash(name)
        collector.scrape_once()
        doc = collector.health()
        assert doc["shards"]["1"]["f_budget"]["remaining"] <= 0
        plan = ap.decide()
        assert plan is not None and plan.kind == "retire"
        assert plan.shard == 1
        # the plan drains every bucket the spent clique owns, to the
        # surviving shard
        assert set(plan.assign.values()) == {0}
        # executing it under the crash still completes: the surviving
        # clique members + storage plane hold the certified history
        report = ap.execute(plan)
        assert report["ok"], report
        for k in keys:
            assert cl.read(k) == b"v-" + k
            assert cl.qs.shard_of(k) == 0
    finally:
        cluster.stop()


def test_retire_blocked_without_copy():
    """A retirement whose pre-copy cannot complete must NOT flip: the
    old clique keeps being routed to (abort + rescind), rather than
    stranding certified history."""
    from bftkv_tpu.faults.harness import build_cluster

    cluster = build_cluster(4, 1, 4, bits=1024, n_shards=2)
    try:
        cl = cluster.clients[0]
        qs = cl.qs
        keys = hot_keys_for(qs, 1, 4, tag=b"blocked")
        for k in keys:
            cl.write(k, b"v-" + k)
        cl.drain_tails()
        ap = Autopilot.for_cluster(cluster)
        ap.MAX_SYNC_ROUNDS = 0  # pre-copy can make no progress
        owner = qs.effective_route()
        assign = {
            b: 0 for b in range(ROUTE_BUCKETS) if owner[b] == 1
        }
        report = ap.execute(Plan("retire", 1, assign, reason="test"))
        assert not report["ok"]
        assert report["aborted"] == "precopy_blocked"
        # routing unchanged: the old clique still serves its keys
        for k in keys:
            assert qs.shard_of(k) == 1
            assert cl.read(k) == b"v-" + k
    finally:
        cluster.stop()


def test_admit_spares_bumps_generation():
    from bftkv_tpu import topology
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(4, 1, 2, bits=1024, n_shards=1)
    try:
        ap = Autopilot.for_cluster(cluster)
        spare = topology.new_identity(
            "sp01", address="loop://sp01", uid="sp01@spare", bits=1024
        )
        gens = {
            id(s): s.self_node.generation for s in cluster.all_servers
        }
        accepted = ap.admit_spares([spare.cert])
        assert accepted == len(cluster.all_servers) + len(cluster.clients)
        for s in cluster.all_servers:
            assert s.self_node.generation > gens[id(s)]
            assert s.crypt.keyring.get(spare.cert.id) is not None
    finally:
        cluster.stop()


def test_issue_table_linearizes():
    """Tables issued concurrently (a flap racing a migration) must get
    distinct epochs and CHAIN contents — later tables keep earlier
    moves."""
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(4, 1, 4, bits=1024, n_shards=2)
    try:
        ap = Autopilot.for_cluster(cluster)
        qs = cluster.clients[0].qs
        owner = qs.effective_route()
        b1 = next(b for b in range(ROUTE_BUCKETS) if owner[b] == 0)
        b2 = next(
            b for b in range(ROUTE_BUCKETS) if owner[b] == 0 and b != b1
        )
        rt1 = ap.issue_table({b1: 1}, dual=False)
        rt2 = ap.issue_table({b2: 1}, dual=False)
        assert rt2.epoch > rt1.epoch
        # rt2 keeps rt1's move
        assert rt2.cliques[rt2.table[b1]] == rt1.cliques[rt1.table[b1]]
        # a STAGED table stays out of the chain
        rt_stage = ap.issue_table({b1: 0}, dual=True, stage=True)
        rt3 = ap.issue_table({}, dual=False)
        assert rt3.epoch > rt_stage.epoch
        assert rt3.cliques[rt3.table[b1]] == rt2.cliques[rt2.table[b1]]
    finally:
        cluster.stop()


def test_next_table_shapes():
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(4, 1, 4, bits=1024, n_shards=2)
    try:
        qs = cluster.clients[0].qs
        owner = qs.effective_route()
        b = next(i for i in range(ROUTE_BUCKETS) if owner[i] == 0)
        rt = next_table(qs, {b: 1}, dual=True)
        assert rt.epoch == 1
        assert rt.dual == {b: 0}
        rt2 = next_table(qs, {b: 1}, dual=False, retiring={0})
        assert rt2.dual == {}
        assert rt2.retiring == {0}
    finally:
        cluster.stop()
