"""End-to-end protocol rounds over the real HTTP transport with the
cross-request verify dispatcher installed.

The reference's whole tier-3 suite runs over HTTP loopback
(reference: protocol/test_utils.go:24-82); this is the analog, plus the
in-situ proof that concurrent server handlers share device launches
(dispatch batch occupancy > 1 under concurrent writes).
"""

import threading

import pytest

from bftkv_tpu.errors import Error
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.ops import dispatch
from bftkv_tpu.transport.http import TrHTTP
from tests.cluster_utils import start_cluster

KEY_BITS = 1024  # keygen speed; the session/protocol path is bits-agnostic


@pytest.fixture(scope="module")
def http_cluster():
    # 4 quorum + 4 rw nodes: the READ-complement clique needs >= 4 nodes
    # for f >= 1 (wotqs.go:55-66), else the READ quorum is empty.
    cluster = start_cluster(4, 3, 4, bits=KEY_BITS, transport="http")
    yield cluster
    cluster.stop()


def test_http_write_read_roundtrip(http_cluster):
    c = http_cluster.clients[0]
    c.write(b"http/x", b"over the wire")
    assert c.read(b"http/x") == b"over the wire"
    # A second client sees the committed value through its own ports.
    assert http_cluster.clients[1].read(b"http/x") == b"over the wire"


def test_http_missing_variable_reads_none(http_cluster):
    assert http_cluster.clients[0].read(b"http/never-written") is None


def test_http_error_tunnel(http_cluster):
    """Interned errors survive the x-error header round trip
    (reference: transport/http/http.go:59-66): a hostile body fails
    session-layer decryption server-side and the client re-raises the
    *same interned error object*, not a generic HTTP failure."""
    addr = http_cluster.universe.servers[0].cert.address
    tr = http_cluster.clients[0].tr
    with pytest.raises(Error) as ei:
        tr.post(addr + "/bftkv/v1/sign", b"\xde\xad\xbe\xef" * 8)
    import bftkv_tpu.errors as errors

    assert errors.error_from_string(ei.value.message) is type(ei.value)


def test_http_concurrent_writes_share_device_batches(http_cluster, monkeypatch):
    """N clients writing concurrently through real sockets: all writes
    land, and the dispatcher coalesces verify calls from concurrent
    handler threads into shared launches (mean batch > 1).

    Calibration and the verify memo are disabled for the duration:
    both would (correctly) keep verifies away from the dispatcher on a
    CPU backend, and this test exists to observe the coalescing
    machinery itself."""
    from bftkv_tpu.crypto import vcache

    monkeypatch.setattr(vcache, "_ENABLED", False)
    metrics.reset()
    dispatch.install(
        dispatch.VerifyDispatcher(max_batch=256, max_wait=0.01, calibrate=False)
    )
    try:
        errors: list = []

        def run(ci, client):
            try:
                for i in range(3):
                    client.write(b"http/c%d/%d" % (ci, i), b"v%d-%d" % (ci, i))
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(ci, c))
            for ci, c in enumerate(http_cluster.clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for ci in range(len(http_cluster.clients)):
            assert http_cluster.clients[0].read(b"http/c%d/2" % ci) == b"v%d-2" % ci

        snap = metrics.snapshot()
        assert snap.get("dispatch.flushes", 0) >= 1
        mean = snap["dispatch.verifies"] / snap["dispatch.flushes"]
        assert mean > 1.0, f"no cross-request coalescing observed: {snap}"
    finally:
        dispatch.uninstall()


def test_http_connections_are_reused(http_cluster):
    """The per-peer keep-alive pool carries repeat RPCs on existing
    sockets: after a warm first write, further writes mostly reuse
    (transport.conn.reused grows much faster than .dialed)."""
    c = http_cluster.clients[0]
    c.write(b"http/pool-warm", b"w")  # dials + pools the quorum links
    metrics.reset()
    for i in range(3):
        c.write(b"http/pool/%d" % i, b"v%d" % i)
    snap = metrics.snapshot()
    reused = snap.get("transport.conn.reused", 0)
    dialed = snap.get("transport.conn.dialed", 0)
    assert reused > 0, f"no connection reuse observed: {snap}"
    # A write is ~12 RPCs; with warm pools nearly all should reuse.
    assert reused >= 3 * dialed, (reused, dialed)


def test_http_transport_is_really_used(http_cluster):
    """Guard against the fixture silently falling back to loopback."""
    assert isinstance(http_cluster.clients[0].tr, TrHTTP)
    assert http_cluster.universe.servers[0].cert.address.startswith("http://127.0.0.1:")
