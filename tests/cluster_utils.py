"""In-process cluster fixtures: N quorum servers + M storage nodes +
clients on the loopback transport — the reference's tier-3 pattern of
running every server in one process (reference: protocol/test_utils.go:24-82,
topology from scripts/setup.sh)."""

from __future__ import annotations

from dataclasses import dataclass, field

import itertools

from bftkv_tpu import topology
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import Server
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.transport.http import TrHTTP
from bftkv_tpu.transport.loopback import LoopbackNet, TrLoopback

# Each HTTP cluster gets a disjoint port range so tests never collide.
_port_block = itertools.count(16001, 100)


@dataclass
class Cluster:
    universe: topology.Universe
    net: LoopbackNet | None
    servers: list[Server] = field(default_factory=list)  # quorum (a*)
    storage_servers: list[Server] = field(default_factory=list)  # rw*
    clients: list[Client] = field(default_factory=list)
    gateways: list = field(default_factory=list)  # bftkv_tpu.gateway
    gateway_addrs: dict[str, str] = field(default_factory=dict)

    @property
    def all_servers(self) -> list[Server]:
        return self.servers + self.storage_servers

    def gateway_client(self, i: int = 0, *, verify: bool = True):
        """A front-door client riding user ``i``'s identity against
        every gateway of the cluster: the client's own keyring copies
        of the (unaddressed) gateway certificates, paired with the
        cluster's configured dial addresses."""
        from bftkv_tpu.gateway import GatewayClient, GatewayPeer

        client = self.clients[i]
        peers = [
            GatewayPeer(
                client.crypt.keyring.get(gw.self_node.get_self_id()),
                self.gateway_addrs[gw.self_node.name],
            )
            for gw in self.gateways
        ]
        return GatewayClient(client, peers, verify=verify)

    def stop(self) -> None:
        for gw in self.gateways:
            gw.stop()
        for s in self.all_servers:
            s.tr.stop()
        if self.universe.regions:
            # The region map is process-global: a labeled cluster must
            # not leak its geography into the next test's fleet.
            from bftkv_tpu import regions

            regions.clear()

    def server_named(self, name: str) -> Server:
        idents = self.universe.servers + self.universe.storage_nodes
        for ident, srv in zip(idents, self.all_servers):
            if ident.name == name:
                return srv
        raise KeyError(name)


def start_cluster(
    n_servers: int = 4,
    n_users: int = 1,
    n_rw: int = 4,
    *,
    bits: int = 2048,
    unsigned_users: int = 0,
    storage_factory=MemStorage,
    server_cls=Server,
    client_cls=Client,
    transport_cls=TrLoopback,
    transport: str = "loop",
    alg: str = "rsa",
    n_shards: int = 1,
    n_gateways: int = 0,
    n_regions: int = 0,
) -> Cluster:
    """``transport="loop"`` wires the in-process loopback net;
    ``transport="http"`` starts every server on a real localhost HTTP
    port — the reference's tier-3 shape (protocol/test_utils.go:24-82,
    one process, loopback sockets).  ``n_shards`` builds that many
    disjoint server cliques (``n_servers``/``n_rw`` become per-shard
    counts — see topology.build_universe).  ``n_regions`` labels every
    principal round-robin and installs the process-global region map
    (cleared again by :meth:`Cluster.stop`)."""
    if transport == "http":
        http_cls = TrHTTP if transport_cls is TrLoopback else transport_cls
        if not (isinstance(http_cls, type) and issubclass(http_cls, TrHTTP)):
            raise ValueError(
                f"transport='http' needs a TrHTTP subclass, got {transport_cls}"
            )
        base = next(_port_block)
        uni = topology.build_universe(
            n_servers, n_users, n_rw, scheme="http", bits=bits,
            base_port=base, rw_base_port=base + 50,
            unsigned_users=unsigned_users, alg=alg, n_shards=n_shards,
            n_gateways=n_gateways, gw_base_port=base + 80,
            n_regions=n_regions,
        )
        net = None
        make_tr = lambda crypt: http_cls(crypt)
    else:
        uni = topology.build_universe(
            n_servers, n_users, n_rw, scheme="loop", bits=bits,
            unsigned_users=unsigned_users, alg=alg, n_shards=n_shards,
            n_gateways=n_gateways, n_regions=n_regions,
        )
        net = LoopbackNet()
        make_tr = lambda crypt: transport_cls(crypt, net)
    if uni.regions:
        from bftkv_tpu import regions

        regions.install(uni.regions)
    cluster = Cluster(universe=uni, net=net)
    for ident in uni.servers + uni.storage_nodes:
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        srv = server_cls(graph, qs, make_tr(crypt), crypt, storage_factory())
        srv.start()
        if ident in uni.servers:
            cluster.servers.append(srv)
        else:
            cluster.storage_servers.append(srv)
    for ident in uni.users:
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        tr = make_tr(crypt)
        # Clients are partitionable links too (the chaos-harness
        # idiom): without a link id the failpoint ctx posts src="" and
        # a region-keyed rule (WAN delay, region cut) can never match
        # client-originated traffic.
        tr.link_id = ident.name
        cluster.clients.append(client_cls(graph, qs, tr, crypt))
    for ident in uni.gateways:
        from bftkv_tpu.gateway import Gateway

        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        gw = Gateway(graph, qs, make_tr(crypt), crypt)
        dial = uni.gateway_addrs[ident.name]
        gw.start(dial.split("://", 1)[-1])
        cluster.gateways.append(gw)
        cluster.gateway_addrs[ident.name] = dial
    return cluster
