"""In-process cluster fixtures: N quorum servers + M storage nodes +
clients on the loopback transport — the reference's tier-3 pattern of
running every server in one process (reference: protocol/test_utils.go:24-82,
topology from scripts/setup.sh)."""

from __future__ import annotations

from dataclasses import dataclass, field

from bftkv_tpu import topology
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import Server
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.transport.loopback import LoopbackNet, TrLoopback


@dataclass
class Cluster:
    universe: topology.Universe
    net: LoopbackNet
    servers: list[Server] = field(default_factory=list)  # quorum (a*)
    storage_servers: list[Server] = field(default_factory=list)  # rw*
    clients: list[Client] = field(default_factory=list)

    @property
    def all_servers(self) -> list[Server]:
        return self.servers + self.storage_servers

    def stop(self) -> None:
        for s in self.all_servers:
            s.tr.stop()

    def server_named(self, name: str) -> Server:
        idents = self.universe.servers + self.universe.storage_nodes
        for ident, srv in zip(idents, self.all_servers):
            if ident.name == name:
                return srv
        raise KeyError(name)


def start_cluster(
    n_servers: int = 4,
    n_users: int = 1,
    n_rw: int = 4,
    *,
    bits: int = 2048,
    unsigned_users: int = 0,
    storage_factory=MemStorage,
    server_cls=Server,
    client_cls=Client,
    transport_cls=TrLoopback,
) -> Cluster:
    uni = topology.build_universe(
        n_servers, n_users, n_rw, scheme="loop", bits=bits,
        unsigned_users=unsigned_users,
    )
    net = LoopbackNet()
    cluster = Cluster(universe=uni, net=net)
    for ident in uni.servers + uni.storage_nodes:
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        tr = transport_cls(crypt, net)
        srv = server_cls(graph, qs, tr, crypt, storage_factory())
        srv.start()
        if ident in uni.servers:
            cluster.servers.append(srv)
        else:
            cluster.storage_servers.append(srv)
    for ident in uni.users:
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        tr = transport_cls(crypt, net)
        cluster.clients.append(client_cls(graph, qs, tr, crypt))
    return cluster
