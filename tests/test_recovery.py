"""Self-healing recovery plane (DESIGN.md §13): the pending-residue
repair daemon and the adaptive per-peer deadline tracker.

The headline scenario is the client-killed-mid-tail orphan: a writer
that crashes after the 2f+1 commit but before its async back-fill
leaves commit-pending residue plane-wide.  The repair daemon must
certify it fleet-wide with ZERO reads issued; never-certifiable
planted residue must be demoted with exactly one tail_starved anomaly.
"""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.sync import SyncDaemon
from bftkv_tpu.transport.latency import PeerLatency

from cluster_utils import start_cluster

BITS = 1024


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(4, 1, 4, bits=BITS)
    # Warm the write path so orphan scenarios measure repair, not setup.
    c.clients[0].write(b"recovery/warmup", b"w")
    c.clients[0].drain_tails()
    yield c
    c.stop()


def _orphan_write(monkeypatch, cl, var: bytes, val: bytes) -> None:
    """A write killed between the 2f+1 commit and its back-fill tail:
    the commit round runs in full, the tail (mint + verify + coalesced
    back-fill) never does — exactly a writer crash at that instant."""
    monkeypatch.setattr(cl, "_ws_finish", lambda *a, **k: None)
    cl.write(var, val)
    monkeypatch.undo()


def _read_counters(snap: dict) -> int:
    return sum(
        v
        for k, v in snap.items()
        if k.startswith("server.read.count")
        or k.startswith("server.batch_read.count")
    )


def test_orphan_repair_certifies_fleet_wide_without_reads(
    cluster, monkeypatch
):
    """Checker invariant 3's premise, restored by the daemon alone:
    after the repair pass every replica holds the record with a
    VERIFYING collective signature — and not one READ was issued."""
    cl = cluster.clients[0]
    var, val = b"recovery/orphan", b"orphaned-value"
    _orphan_write(monkeypatch, cl, var, val)
    cl.drain_tails()

    # Every replica that admitted the round holds commit-pending
    # residue; none holds a certified version.
    pending = 0
    for srv in cluster.all_servers:
        try:
            p = pkt.parse(srv.storage.read(var, 0))
        except Exception:
            continue
        assert not (p.ss is not None and p.ss.completed)
        pending += 1
    assert pending >= 3  # at least the committing 2f+1 prefix persisted

    reads_before = _read_counters(metrics.snapshot())
    cert_before = metrics.snapshot().get("sync.repair.certified", 0)

    # One replica's daemon repairs (grace window ignored via
    # repair_once); its SIGN round + plane-wide back-fill must certify
    # EVERYONE — the other daemons find nothing left to do.
    daemon = SyncDaemon(cluster.storage_servers[0], interval=999)
    stats = daemon.repair_once()
    assert stats["certified"] >= 1
    assert stats["demoted"] == 0

    qa = qm.choose_quorum_for(cl.qs, var, qm.AUTH)
    for srv in cluster.all_servers:
        raw = srv.storage.read(var, 0)
        p = pkt.parse(raw)
        assert p.ss is not None and p.ss.completed, (
            f"{srv.self_node.name} still holds uncertified residue"
        )
        srv.crypt.collective.verify(
            pkt.tbss(raw), p.ss, qa, srv.crypt.keyring
        )
    assert cl.read(var) == val

    assert metrics.snapshot().get("sync.repair.certified", 0) >= (
        cert_before + 1
    )
    # Zero reads issued by the repair itself: the read counters moved
    # only by the single verification read() above.
    assert _read_counters(metrics.snapshot()) - reads_before <= len(
        cluster.all_servers
    )

    # The plane is settled for this variable: a second pass on the same
    # daemon has nothing left to repair and nothing to demote.
    again = daemon.repair_once()
    assert again["certified"] == 0 and again["demoted"] == 0


def test_repair_respects_grace_window(cluster, monkeypatch):
    """Residue younger than BFTKV_REPAIR_AFTER is presumed to be a live
    write's tail and left alone; it repairs once the window passes."""
    cl = cluster.clients[0]
    var = b"recovery/grace"
    _orphan_write(monkeypatch, cl, var, b"young")
    cl.drain_tails()

    srv = cluster.storage_servers[1]
    daemon = SyncDaemon(srv, interval=999, repair_after=3600.0)
    stats = daemon.repair_round()
    assert stats["certified"] == 0 and stats["waiting"] >= 1
    p = pkt.parse(srv.storage.read(var, 0))
    assert not p.ss.completed  # untouched inside the grace window

    daemon.repair_after = 0.0
    time.sleep(0.01)
    stats = daemon.repair_round()
    assert stats["certified"] == 1
    p = pkt.parse(srv.storage.read(var, 0))
    assert p.ss is not None and p.ss.completed


def test_uncertifiable_residue_demoted_with_one_anomaly(cluster):
    """A planted record no quorum will ever endorse (its writer
    signature does not verify) is demoted — once — and surfaces as
    exactly one tail_starved anomaly in the fleet feed."""
    from bftkv_tpu.obs import FleetCollector

    cl = cluster.clients[0]
    srv = cluster.storage_servers[2]
    var = b"recovery/poison"
    # Valid signature STRUCTURE over the wrong preimage: every honest
    # replica's writer-signature check refuses to sign this record.
    sig = cl.crypt.signer.issue(pkt.serialize(var, b"other", 1, nfields=3))
    residue = pkt.serialize(
        var,
        b"planted",
        1,
        sig,
        pkt.SignaturePacket(
            type=pkt.SIGNATURE_TYPE_NATIVE, version=1, completed=False,
            data=None,
        ),
    )
    srv.storage.write(var, 1, residue)

    collector = FleetCollector([], local_metrics=metrics)
    collector.scrape_once()  # counter-delta baseline
    seq0 = max((a["seq"] for a in collector.anomalies()), default=0)

    def fresh_starved():
        return [
            a
            for a in collector.anomalies(since_seq=seq0)
            if a["kind"] == "tail_starved"
        ]

    daemon = SyncDaemon(srv, interval=999)
    stats = daemon.repair_once()
    assert stats["demoted"] == 1 and stats["certified"] == 0

    collector.scrape_once()
    starved = fresh_starved()
    assert len(starved) == 1
    assert "sync.repair.demoted" in starved[0]["detail"]

    # Demotion is remembered: no retry loop, no second anomaly.
    stats = daemon.repair_once()
    assert stats["demoted"] == 0 and stats["certified"] == 0
    collector.scrape_once()
    assert len(fresh_starved()) == 1


def test_outage_retries_instead_of_demoting(cluster, monkeypatch):
    """A SIGN round that fails on transport errors alone (partition,
    timeouts) is an OUTAGE, not a verdict: the residue is retried next
    round, never demoted, and no tail_starved anomaly fires."""
    from bftkv_tpu.faults import failpoint as fp

    cl = cluster.clients[0]
    var = b"recovery/outage"
    _orphan_write(monkeypatch, cl, var, b"survives-partition")
    cl.drain_tails()

    # A replica inside the staged commit wave (the interleaved prefix
    # contacts the first storage seats), so it holds the residue.
    srv = cluster.storage_servers[1]
    daemon = SyncDaemon(srv, interval=999)
    demoted_before = metrics.snapshot().get("sync.repair.demoted", 0)
    fp.arm(9)
    fp.registry.add(
        "transport.send", "drop", match={"cmd": "sign"}, rule_id="cut"
    )
    try:
        stats = daemon.repair_once()
    finally:
        fp.disarm()
    assert stats["retrying"] >= 1 and stats["demoted"] == 0
    assert (
        metrics.snapshot().get("sync.repair.demoted", 0) == demoted_before
    )
    # The partition heals: the same daemon certifies on the next pass.
    stats = daemon.repair_once()
    assert stats["certified"] >= 1 and stats["demoted"] == 0
    p = pkt.parse(srv.storage.read(var, 0))
    assert p.ss is not None and p.ss.completed


def test_repair_skips_protected_and_certified(cluster):
    """pending_variables: certified records, hidden-prefix state and
    TPA-protected records never enter the repair scan."""
    srv = cluster.storage_servers[0]
    pending, _cursor = srv.pending_variables()
    for variable, t, _raw, p in pending:
        assert not (p.ss is not None and p.ss.completed)
        assert p.auth is None
        assert not variable.startswith(b"!!!secret!!!")


def test_pending_scan_windowed_cursor(cluster):
    """The repair scan is windowed: a tiny scan_window pages through
    the keyspace with a resumable cursor instead of parsing the whole
    store per call."""
    srv = cluster.storage_servers[0]
    all_keys = sorted(srv.storage.keys())
    seen: list[bytes] = []
    cursor = None
    for _ in range(len(all_keys) + 1):
        _pending, cursor = srv.pending_variables(
            after=cursor, scan_window=2
        )
        if cursor is None:
            break
        seen.append(cursor)
    assert cursor is None  # the cycle terminates
    assert seen == sorted(seen)  # strictly forward progress


# -- adaptive per-peer deadlines (transport/latency.py) ---------------------


def test_adaptive_deadline_tracks_peer_history():
    pl = PeerLatency()
    pl.floor = 0.05
    addr = "loop://fast"
    for _ in range(8):
        pl.record(addr, 0.01)
    # 8 x p99 + slack, far under the fixed 10 s worst case.
    dl = pl.deadline(addr, 10.0)
    assert 0.05 <= dl <= 0.5
    # An unknown peer keeps the configured worst case.
    assert pl.deadline("loop://stranger", 10.0) == 10.0
    # The deadline is exported as a gauge.
    snap = metrics.snapshot()
    assert any(
        k.startswith("transport.peer.deadline_ms") for k in snap
    )


def test_adaptive_deadline_disabled_env(monkeypatch):
    monkeypatch.setenv("BFTKV_ADAPTIVE_TIMEOUT", "off")
    pl = PeerLatency()
    for _ in range(8):
        pl.record("loop://x", 0.01)
    assert pl.deadline("loop://x", 10.0) == 10.0


def test_gray_flag_trips_and_recovers():
    pl = PeerLatency()
    addr = "loop://grayish"
    before = metrics.snapshot().get(
        "transport.peer.slow{peer=grayish}", 0
    )
    for _ in range(6):
        pl.record(addr, 0.02)
    assert not pl.is_gray(addr)
    pl.record(addr, 1.5)  # way past 3 x p50 and the absolute guard
    assert pl.is_gray(addr)
    assert (
        metrics.snapshot().get("transport.peer.slow{peer=grayish}", 0)
        == before + 1
    )
    # A genuinely fast answer clears the flag early.
    pl.record(addr, 0.02)
    assert not pl.is_gray(addr)


def test_hedge_delay_bounded():
    pl = PeerLatency()
    assert pl.hedge_delay(["loop://nobody"]) == pl.hedge_min
    for _ in range(8):
        pl.record("loop://slowish", 5.0)
    assert pl.hedge_delay(["loop://slowish"]) == pl.hedge_cap


def test_timeout_records_as_gray_sample():
    pl = PeerLatency()
    pl.record("loop://dead", 1.0, timeout=True)
    assert pl.is_gray("loop://dead")
