"""RSA sign/verify: host primitives vs the cryptography-library oracle,
and the batched TPU verify kernel vs both."""

import numpy as np
import pytest

from bftkv_tpu.crypto import rsa

KEY_BITS = 1024  # keygen speed; kernel is width-generic (128-limb padded)


@pytest.fixture(scope="module")
def keys():
    return [rsa.generate(KEY_BITS) for _ in range(3)]


def test_sign_verify_host(keys):
    key = keys[0]
    sig = rsa.sign(b"hello bftkv", key)
    assert rsa.verify_host(b"hello bftkv", sig, key.public)
    assert not rsa.verify_host(b"hello bftkV", sig, key.public)
    assert not rsa.verify_host(b"hello bftkv", sig, keys[1].public)


def test_sign_matches_cryptography_oracle(keys):
    pytest.importorskip("cryptography")  # oracle cross-check needs the host lib
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa as crsa

    key = keys[0]
    # Rebuild the same key in the oracle library and cross-check both ways.
    pub = crsa.RSAPublicNumbers(key.e, key.n).public_key()
    sig = rsa.sign(b"cross-check", key)
    pub.verify(sig, b"cross-check", padding.PKCS1v15(), hashes.SHA256())

    priv = crsa.RSAPrivateNumbers(
        p=key.p,
        q=key.q,
        d=key.d,
        dmp1=key.d % (key.p - 1),
        dmq1=key.d % (key.q - 1),
        iqmp=pow(key.q, -1, key.p),
        public_numbers=crsa.RSAPublicNumbers(key.e, key.n),
    ).private_key()
    their_sig = priv.sign(b"cross-check", padding.PKCS1v15(), hashes.SHA256())
    assert their_sig == sig  # PKCS#1 v1.5 is deterministic


def test_verify_batch_tpu(keys):
    # host_threshold=0 forces the device kernel even for a small batch —
    # this test also covers the power-of-two padding path (8 → 256 rows).
    dom = rsa.VerifierDomain(nlimbs=128, host_threshold=0)
    msgs = [f"msg-{i}".encode() for i in range(6)]
    items = []
    for i, m in enumerate(msgs):
        key = keys[i % len(keys)]
        items.append((m, rsa.sign(m, key), key.public))
    # Corrupt two entries: wrong message, wrong key.
    items.append((b"tampered", items[0][1], keys[0].public))
    items.append((msgs[1], items[1][1], keys[2].public))
    ok = dom.verify_batch(items)
    want = np.array([True] * 6 + [False, False])
    assert (ok == want).all()


def test_verify_batch_oversize_sig(keys):
    dom = rsa.VerifierDomain(nlimbs=128, host_threshold=0)
    key = keys[0]
    bad_sig = (key.n + 1).to_bytes(key.size_bytes + 1, "big")
    ok = dom.verify_batch([(b"m", bad_sig, key.public)])
    assert not ok[0]


def test_verify_batch_empty():
    assert rsa.VerifierDomain().verify_batch([]).shape == (0,)


def test_sign_batch_device_matches_host(keys):
    """Batched CRT signing on device is bit-identical to host signing
    (PKCS#1 v1.5 is deterministic), across mixed key sizes."""
    dom = rsa.SignerDomain(host_threshold=0)
    big = rsa.generate(2048)
    items = [(f"m{i}".encode(), keys[i % len(keys)]) for i in range(5)]
    items.append((b"wide", big))
    sigs = dom.sign_batch(items)
    for (m, k), s in zip(items, sigs):
        assert s == rsa.sign(m, k)
        assert rsa.verify_host(m, s, k.public)


def test_sign_batch_host_crossover(keys):
    dom = rsa.SignerDomain(host_threshold=64)
    items = [(b"a", keys[0]), (b"b", keys[1])]
    assert dom.sign_batch(items) == [rsa.sign(b"a", keys[0]), rsa.sign(b"b", keys[1])]


def test_sign_dispatcher_end_to_end(keys):
    from bftkv_tpu.ops import dispatch

    d = dispatch.SignDispatcher(
        rsa.SignerDomain(host_threshold=0), max_batch=64, max_wait=0.005
    ).start()
    try:
        import threading

        out: dict = {}

        def go(i):
            out[i] = d.sign(b"msg-%d" % i, keys[i % len(keys)])

        ts = [threading.Thread(target=go, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i in range(8):
            assert rsa.verify_host(b"msg-%d" % i, out[i], keys[i % len(keys)].public)
    finally:
        d.stop()


def test_verify_batch_host_crossover(keys):
    """Small batches route to the host oracle (device launches only pay
    off past a few hundred items); results are identical either way."""
    dom = rsa.VerifierDomain(nlimbs=128, host_threshold=64)
    key = keys[0]
    sig = rsa.sign(b"m", key)
    ok = dom.verify_batch([(b"m", sig, key.public), (b"x", sig, key.public)])
    assert ok[0] and not ok[1]


# -- native Montgomery modexp (native/montmodexp.c) -------------------------


def test_native_modexp_matches_pow_oracle():
    """The CIOS Montgomery extension is pinned to pow() across widths,
    edge bases, and exponent shapes; the pure path stays the oracle."""
    import random

    if rsa._MM is None:
        pytest.skip("native modexp not built")
    rng = random.Random(1234)
    for bits in (512, 1024, 2048):
        for _ in range(10):
            mod = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
            params = rsa._mont_params(mod)
            for base in (
                0,
                1,
                2,
                mod - 1,
                rng.getrandbits(bits) % mod,
            ):
                for exp in (1, 2, 65537, rng.getrandbits(bits)):
                    assert rsa._native_powmod(base, exp, params) == pow(
                        base, exp, mod
                    ), (bits, base, exp)


def test_native_sign_matches_pure_python(keys, monkeypatch):
    """One signature, both engines, byte-identical — so an engine flip
    (or BFTKV_NATIVE_MODEXP=off) can never change the wire."""
    if rsa._MM is None:
        pytest.skip("native modexp not built")
    key = keys[0]
    native = rsa.sign(b"engine parity", key)
    monkeypatch.setattr(rsa, "_MM", None)
    assert rsa.sign(b"engine parity", key) == native


def test_crt_pow_d_roundtrips_encrypt(keys):
    key = keys[0]
    m = 0x123456789ABCDEF
    c = pow(m, key.e, key.n)
    assert rsa.crt_pow_d(c, key) == m
