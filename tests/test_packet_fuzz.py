"""Codec fuzzing (VERDICT r3 item 9): every parser that touches
attacker-supplied bytes must fail only with interned errors — no
foreign exception types, no hangs, no unbounded allocation — under
random truncation and mutation (reference surface:
packet/packet.go:62-115).
"""

from __future__ import annotations

import random

import pytest

from bftkv_tpu import errors, packet as pkt
from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import ecdsa, rsa, signature as sigmod
from bftkv_tpu.crypto.message import MessageSecurity

_TRIALS = 1500  # per corpus entry class; whole module runs in seconds


def _mutations(rng: random.Random, blob: bytes):
    """Truncations, bit flips, length-prefix inflation, junk."""
    if blob:
        yield blob[: rng.randrange(len(blob))]
        b = bytearray(blob)
        for _ in range(rng.randint(1, 8)):
            b[rng.randrange(len(b))] = rng.randrange(256)
        yield bytes(b)
        # Inflate a plausible length prefix to a huge value.
        b2 = bytearray(blob)
        if len(b2) >= 4:
            i = rng.randrange(len(b2) - 3)
            b2[i : i + 4] = (0x7FFFFFFF).to_bytes(4, "big")
            yield bytes(b2)
    yield rng.randbytes(rng.randrange(0, 64))


def _assert_interned(fn, blob):
    try:
        fn(blob)
    except errors.Error:
        pass
    except (ValueError, EOFError) as e:  # codecs may not leak these either
        pytest.fail(f"non-interned {type(e).__name__}: {e!r} for {blob[:30]!r}")
    except Exception as e:
        pytest.fail(f"{type(e).__name__}: {e!r} escaped for {blob[:30]!r}")


def test_packet_parse_fuzz():
    rng = random.Random(1)
    genuine = pkt.serialize(b"var", b"value" * 10, 7, None, None)
    for _ in range(_TRIALS):
        for blob in _mutations(rng, genuine):
            _assert_interned(pkt.parse, blob)


def test_packet_list_and_results_fuzz():
    rng = random.Random(2)
    lst = pkt.serialize_list([b"a" * 9, b"b" * 30, b""])
    res = pkt.serialize_results([(None, b"x"), ("some error", b"")])
    for _ in range(_TRIALS):
        for blob in _mutations(rng, lst):
            _assert_interned(pkt.parse_list, blob)
        for blob in _mutations(rng, res):
            _assert_interned(pkt.parse_results, blob)


def test_signature_packet_fuzz():
    rng = random.Random(3)
    key = rsa.generate(1024)
    cert = certmod.Certificate(n=key.n, e=key.e, name="f")
    signer = sigmod.Signer(key, cert)
    genuine = pkt.serialize_signature(signer.issue(b"tbs"))
    for _ in range(_TRIALS):
        for blob in _mutations(rng, genuine):
            _assert_interned(pkt.parse_signature, blob)


def test_auth_request_fuzz():
    rng = random.Random(4)
    genuine = pkt.serialize_auth_request(1, b"var", b"\x01" * 40)
    for _ in range(_TRIALS):
        for blob in _mutations(rng, genuine):
            _assert_interned(pkt.parse_auth_request, blob)


def test_certificate_parse_fuzz_both_algs():
    rng = random.Random(5)
    rkey = rsa.generate(1024)
    rcert = certmod.Certificate(n=rkey.n, e=rkey.e, name="r", uid="r@x")
    certmod.sign_certificate(rcert, rkey)
    ekey = ecdsa.generate()
    ecert = certmod.make_ec_certificate(ekey.public, name="e", uid="e@x")
    certmod.sign_certificate(ecert, ekey)
    corpus = [rcert.serialize(), ecert.serialize(),
              rcert.serialize() + ecert.serialize()]
    for _ in range(_TRIALS // 2):
        for genuine in corpus:
            for blob in _mutations(rng, genuine):
                _assert_interned(certmod.parse, blob)


def test_message_envelope_fuzz():
    # decrypt() consumes pre-authentication bytes straight off the
    # socket — the most exposed parser of all.
    rng = random.Random(6)
    key = rsa.generate(1024)
    cert = certmod.Certificate(n=key.n, e=key.e, name="m")
    ms = MessageSecurity(key, cert)
    genuine = ms.encrypt([cert], b"payload", b"nonce-123")
    for _ in range(400):
        for blob in _mutations(rng, genuine):
            _assert_interned(ms.decrypt, blob)


# -- C codec differential fuzz ----------------------------------------------
# The native codec (native/packetcodec.c) must agree with the
# pure-Python oracle on every input: same value or same interned error.

_HAS_C = pkt._C is not None


def _outcome(fn, blob):
    try:
        return ("ok", fn(blob))
    except errors.Error as e:
        return ("err", str(e))
    except Exception as e:  # non-interned: the fuzz above already fails these
        return ("exc", type(e).__name__)


@pytest.mark.skipif(not _HAS_C, reason="C codec unavailable")
def test_c_codec_differential_fuzz():
    rng = random.Random(7)
    sig = pkt.SignaturePacket(
        type=1, version=3, completed=True, data=b"\x05" * 64, cert=b"c" * 33
    )
    corpus = [
        pkt.serialize(b"var", b"value" * 10, 7, sig, sig, b"auth"),
        pkt.serialize(b"var", None, 9, None, None),
        pkt.serialize(b"x", nfields=1),
        pkt.serialize(b"x", b"v", 5, nfields=3),
        pkt.serialize_list([b"a" * 9, b"", b"q" * 120]),
        pkt.serialize_signature(sig),
        b"",
    ]
    pairs = [
        (pkt.parse, pkt._py_parse),
        (pkt.tbs, pkt._py_tbs),
        (pkt.tbss, pkt._py_tbss),
        (pkt.parse_signature, pkt._py_parse_signature),
        (pkt.parse_list, pkt._py_parse_list),
    ]
    for _ in range(300):
        for genuine in corpus:
            for blob in _mutations(rng, genuine):
                for c_fn, py_fn in pairs:
                    got, want = _outcome(c_fn, blob), _outcome(py_fn, blob)
                    assert got == want, (
                        f"{c_fn.__name__}: C={got!r} PY={want!r} "
                        f"for {blob[:40]!r}"
                    )


@pytest.mark.skipif(not _HAS_C, reason="C codec unavailable")
def test_c_codec_serialize_matches_python():
    rng = random.Random(8)
    for _ in range(500):
        var = rng.randbytes(rng.randrange(0, 20))
        val = None if rng.random() < 0.3 else rng.randbytes(rng.randrange(0, 200))
        t = rng.randrange(0, 2**64)
        mk = lambda: (
            None
            if rng.random() < 0.4
            else pkt.SignaturePacket(
                type=rng.choice([0, 1, 2, 255]),
                version=rng.randrange(0, 2**32),
                completed=rng.random() < 0.5,
                data=None if rng.random() < 0.3 else rng.randbytes(10),
                cert=None if rng.random() < 0.5 else rng.randbytes(10),
            )
        )
        sig, ss = mk(), mk()
        auth = None if rng.random() < 0.5 else rng.randbytes(8)
        nfields = rng.choice([None, 1, 2, 3, 4, 5, 6])
        a = pkt.serialize(var, val, t, sig, ss, auth, nfields=nfields)
        b = pkt._py_serialize(var, val, t, sig, ss, auth, nfields=nfields)
        assert a == b
        s = mk()
        assert pkt.serialize_signature(s) == pkt._py_serialize_signature(s)
