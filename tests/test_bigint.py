"""Kernel-vs-oracle property tests for the batched big-integer ops.

The oracle is Python's arbitrary-precision int — the analog of the
reference's Tier-1 math tests (rsa_test.go:31-53, dsa_test.go:47-215).
"""

import random

import numpy as np
import pytest

from bftkv_tpu.ops import bigint, limb

rng = random.Random(1234)


def rand_ints(n, bits):
    return [rng.getrandbits(bits) for _ in range(n)]


def rand_odd(bits):
    n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    return n


@pytest.mark.parametrize("bits", [64, 256, 1024])
def test_carry_resolve(bits):
    nl = limb.nlimbs_for_bits(bits)
    # Random lane values up to 2^26 (the worst case the kernels produce).
    raw = np.array(
        [[rng.getrandbits(26) for _ in range(nl)] for _ in range(8)], dtype=np.uint32
    )
    out = np.asarray(bigint.carry_resolve(raw, nl + 2))
    for row_raw, row_out in zip(raw, out):
        want = sum(int(v) << (16 * i) for i, v in enumerate(row_raw))
        assert limb.limbs_to_int(row_out) == want


@pytest.mark.parametrize("bits", [64, 256, 2048])
def test_mul(bits):
    nl = limb.nlimbs_for_bits(bits)
    xs = rand_ints(6, bits)
    ys = rand_ints(6, bits)
    a = limb.ints_to_limbs(xs, nl)
    b = limb.ints_to_limbs(ys, nl)
    out = np.asarray(bigint.mul(a, b))
    for x, y, row in zip(xs, ys, out):
        assert limb.limbs_to_int(row) == x * y


def test_add_sub_geq():
    nl = 16
    xs = rand_ints(8, 250)
    ys = rand_ints(8, 250)
    a = limb.ints_to_limbs(xs, nl)
    b = limb.ints_to_limbs(ys, nl)
    s = np.asarray(bigint.add(a, b, nl + 1))
    for x, y, row in zip(xs, ys, s):
        assert limb.limbs_to_int(row) == x + y
    d = np.asarray(bigint.sub_mod_r(a, b))
    r = 1 << (16 * nl)
    for x, y, row in zip(xs, ys, d):
        assert limb.limbs_to_int(row) == (x - y) % r
    ge = np.asarray(bigint.geq(a, b))
    for x, y, g in zip(xs, ys, ge):
        assert bool(g) == (x >= y)
    # equality edge
    assert bool(np.asarray(bigint.geq(a, a)).all())


@pytest.mark.parametrize("bits", [256, 2048])
def test_mont_mul(bits):
    n = rand_odd(bits)
    dom = bigint.MontgomeryDomain(n)
    xs = [rng.randrange(n) for _ in range(5)]
    ys = [rng.randrange(n) for _ in range(5)]
    am = dom.encode(xs)
    bm = dom.encode(ys)
    out = np.asarray(bigint.mont_mul(am, bm, dom.n, dom.n_prime))
    got = dom.decode(out)
    for x, y, g in zip(xs, ys, got):
        assert g == (x * y) % n


def test_mont_roundtrip():
    n = rand_odd(256)
    dom = bigint.MontgomeryDomain(n)
    xs = [rng.randrange(n) for _ in range(4)]
    plain = limb.ints_to_limbs(xs, dom.nlimbs)
    m = bigint.to_mont(plain, dom.r2, dom.n, dom.n_prime)
    back = np.asarray(bigint.from_mont(m, dom.n, dom.n_prime))
    assert limb.limbs_to_ints(back) == xs


@pytest.mark.parametrize("e", [3, 17, 65537])
def test_mont_pow_static(e):
    n = rand_odd(512)
    dom = bigint.MontgomeryDomain(n)
    xs = [rng.randrange(n) for _ in range(4)]
    am = dom.encode(xs)
    out = np.asarray(bigint.mont_pow_static(am, e, dom.n, dom.n_prime))
    got = dom.decode(out)
    for x, g in zip(xs, got):
        assert g == pow(x, e, n)


@pytest.mark.parametrize("bits,ebits", [(256, 256), (512, 64)])
def test_mont_exp(bits, ebits):
    n = rand_odd(bits)
    dom = bigint.MontgomeryDomain(n)
    xs = [rng.randrange(n) for _ in range(4)]
    es = [rng.getrandbits(ebits) | 1 for _ in range(4)]
    am = dom.encode(xs)
    e = limb.ints_to_limbs(es, limb.nlimbs_for_bits(ebits))
    one = np.broadcast_to(dom.one_mont, am.shape)
    out = np.asarray(bigint.mont_exp(am, e, dom.n, dom.n_prime, one))
    got = dom.decode(out)
    for x, ei, g in zip(xs, es, got):
        assert g == pow(x, ei, n)


def test_mont_exp_shared_exponent():
    # Exponent broadcast from a single shared vector (e.g. fixed e).
    n = rand_odd(256)
    dom = bigint.MontgomeryDomain(n)
    xs = [rng.randrange(n) for _ in range(3)]
    am = dom.encode(xs)
    e_int = 65537
    e = limb.int_to_limbs(e_int, 2)
    one = np.broadcast_to(dom.one_mont, am.shape)
    out = np.asarray(bigint.mont_exp(am, e, dom.n, dom.n_prime, one))
    assert dom.decode(out) == [pow(x, e_int, n) for x in xs]


def test_per_element_moduli():
    # Batched moduli: each element has its own n (threshold-signing case).
    ns = [rand_odd(256) for _ in range(3)]
    doms = [bigint.MontgomeryDomain(n, 16) for n in ns]
    xs = [rng.randrange(n) for n in ns]
    ys = [rng.randrange(n) for n in ns]
    am = np.stack([d.encode([x])[0] for d, x in zip(doms, xs)])
    bm = np.stack([d.encode([y])[0] for d, y in zip(doms, ys)])
    nn = np.stack([d.n for d in doms])
    npr = np.stack([d.n_prime for d in doms])
    out = np.asarray(bigint.mont_mul(am, bm, nn, npr))
    for d, x, y, row, n in zip(doms, xs, ys, out, ns):
        assert d.decode(row[None])[0] == (x * y) % n


def test_mul_extremes():
    nl = 16
    m = (1 << (16 * nl)) - 1  # all-0xFFFF digits: worst-case carry chains
    a = limb.ints_to_limbs([m, m, 0, 1], nl)
    b = limb.ints_to_limbs([m, 1, m, m], nl)
    out = np.asarray(bigint.mul(a, b))
    want = [m * m, m, 0, m]
    assert limb.limbs_to_ints(out) == want
