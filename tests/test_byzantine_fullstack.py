"""Byzantine scenarios on the FULL stack (VERDICT r3 item 7): the same
collusion/equivocation adversaries as tests/test_byzantine.py, but over
real localhost HTTP with the verify+sign dispatchers and the shared
verify sidecar installed — the configuration the bench claims matter
for — plus the batched read fallback at the 64-replica quorum shape.
Gate: zero additional safety violations with batching active
(reference: protocol/mal_test.go:23-71).
"""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import topology
from bftkv_tpu.cmd import verify_sidecar
from bftkv_tpu.crypto import rsa
from bftkv_tpu.crypto.remote_verify import RemoteVerifierDomain
from bftkv_tpu.ops import dispatch
from bftkv_tpu.transport.http import TrHTTP

from cluster_utils import start_cluster
from mal_utils import MalClient, MalServer, MalStorage

_PORT = [19400]


@pytest.fixture()
def fullstack_mal_cluster(monkeypatch):
    """7+6 mal cluster over HTTP; dispatchers + sidecar installed."""
    from bftkv_tpu.transport import http as trhttp

    # 13 in-process HTTP servers + device dispatchers on a shared CPU
    # box can push honest handlers past the production 10 s timeout;
    # a timeout here reads as a Byzantine fault and voids the gate.
    monkeypatch.setattr(trhttp, "RESPONSE_TIMEOUT", 120.0)
    _PORT[0] += 1
    addr = f"127.0.0.1:{_PORT[0]}"
    srv, _t = verify_sidecar.serve(addr, max_batch=512)
    c = start_cluster(
        n_servers=7,
        n_users=2,
        n_rw=6,
        server_cls=MalServer,
        storage_factory=MalStorage,
        transport="http",
    )
    # 3 colluding quorum servers (beyond f=2, like the base suite: the
    # equivocator needs each half-group plus colluders to reach suff=5)
    # + 2 colluding storage nodes.
    mal = {i.cert.address for i in c.universe.servers[-3:]}
    mal |= {i.cert.address for i in c.universe.storage_nodes[-2:]}
    MalServer.mal_addresses = mal
    dispatch.install(
        dispatch.VerifyDispatcher(
            verifier=RemoteVerifierDomain(
                addr, local=rsa.VerifierDomain(host_threshold=0)
            ),
            max_batch=512,
        )
    )
    dispatch.install_signer(dispatch.SignDispatcher(max_batch=512))
    try:
        yield c, mal
    finally:
        MalServer.mal_addresses = set()
        dispatch.uninstall_all()
        c.stop()
        srv.dispatcher.stop()
        srv.shutdown()


def test_collusion_over_http_with_dispatchers(fullstack_mal_cluster):
    """Equivocation + revocation with every batching layer live: the
    writes verify through the sidecar-backed dispatcher, shares issue
    through the sign dispatcher, and the honest reader still converges
    and revokes the double-signers."""
    c, mal = fullstack_mal_cluster
    uni = c.universe

    evil_ident = uni.users[0]
    graph, crypt, qs = topology.make_node(evil_ident, uni.view_of(evil_ident))
    evil = MalClient(graph, qs, TrHTTP(crypt), crypt, mal_addresses=mal)
    try:
        evil.write_mal(b"fs_mal", b"value-one", b"value-two")
    finally:
        evil.tr.stop()

    honest = c.clients[1]
    value = honest.read(b"fs_mal")
    assert value in (b"value-one", b"value-two")

    deadline = time.time() + 10
    mal_server_ids = {i.cert.id for i in uni.servers[-3:]}
    while time.time() < deadline:
        if mal_server_ids <= set(honest.self_node.revoked):
            break
        time.sleep(0.05)
    assert mal_server_ids <= set(honest.self_node.revoked)


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_batch_pipeline_safe_over_http_with_dispatchers(
    fullstack_mal_cluster,
):
    """write_many/read_many with colluders active and every device
    batching layer installed: all items land, round-trip, and update."""
    c, _ = fullstack_mal_cluster
    honest = c.clients[1]
    items = [(b"fs_batch/%d" % i, b"v%d" % i) for i in range(16)]
    assert honest.write_many(items) == [None] * 16
    assert honest.read_many([v for v, _ in items]) == [v for _, v in items]
    items2 = [(v, b"u" + val) for v, val in items]
    assert honest.write_many(items2) == [None] * 16
    assert honest.read_many([v for v, _ in items]) == [
        b"u" + val for _, val in items
    ]


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_batched_read_fallback_at_64_replicas():
    """The signed-candidate read fallback (protocol/client.py
    _resolve_complete_fanout_many) at the 64-replica shape: after an
    under-replicated newest write, a lone replica holding the newest
    value WITH its completed collective signature beats the stale
    threshold — through read_many, at the size the bench claims."""
    c = start_cluster(n_servers=64, n_users=1, n_rw=8, bits=1024)
    try:
        cl = c.clients[0]
        vars_ = [b"c64/%d" % i for i in range(4)]
        assert cl.write_many([(v, b"old-" + v) for v in vars_]) == [None] * 4
        assert cl.write_many([(v, b"new-" + v) for v in vars_]) == [None] * 4

        keepers = c.storage_servers
        # write_many returns at ack-threshold; the storage nodes'
        # posts may still be in flight (quorum semantics — the
        # reference's goroutine fan-out behaves identically).  Wait for
        # replication before manufacturing the under-replication.
        deadline = time.time() + 30
        def replicated(v):
            try:
                return all(
                    pkt.parse(s.storage.read(v, 0)).value == b"new-" + v
                    for s in keepers
                )
            except Exception:
                return False
        while time.time() < deadline and not all(
            replicated(v) for v in vars_
        ):
            time.sleep(0.1)
        for v in vars_:
            newest_raw = keepers[0].storage.read(v, 0)
            np_ = pkt.parse(newest_raw)
            assert np_.value == b"new-" + v and np_.ss is not None
            # Roll every other storage replica back to the old state at
            # the same timestamp (under-replication of the newest).
            for srv in keepers[1:]:
                old_raw = srv.storage.read(v, np_.t - 1)
                srv.storage.write(v, np_.t, old_raw)

        got = cl.read_many(vars_)
        assert got == [b"new-" + v for v in vars_], got

        # High-t liars at scale: 5 storage replicas fabricate unsigned
        # higher-t values; the batch read must still serve the truth.
        def lying_batch_read(req, peer, sender):
            items = pkt.parse_list(req)
            fake = pkt.serialize(b"x", b"FORGED", 2**40, None, None)
            return pkt.serialize_results([(None, fake)] * len(items))

        originals = []
        for srv in keepers[1:6]:
            originals.append((srv, srv._batch_read))
            srv._batch_read = lying_batch_read
        try:
            got = cl.read_many(vars_)
            assert got == [b"new-" + v for v in vars_], got
        finally:
            for srv, orig in originals:
                srv._batch_read = orig
    finally:
        c.stop()
