"""devtools/lockwatch: cycle detection, blocking-call-under-lock,
waivers, Condition compatibility, and the zero-overhead-disarmed
contract.

Tests that arm the sanitizer snapshot and restore its global state, so
they compose with a fully-armed tier (``BFTKV_LOCKWATCH=1``) without
planting their synthetic findings into the session gate.
"""

import threading
import time

import pytest

from bftkv_tpu.devtools import lockwatch


@pytest.fixture()
def armed():
    """Arm (if not already), snapshot findings state, restore after."""
    was_armed = lockwatch.ARMED
    saved_edges = dict(lockwatch._edges)
    saved_blocking = dict(lockwatch._blocking)
    saved_waived = dict(lockwatch._waived_orders)
    if not was_armed:
        lockwatch.arm()
    else:
        lockwatch.reset()
    try:
        yield
    finally:
        if not was_armed:
            lockwatch.disarm()
        with lockwatch._state_lock:
            lockwatch._edges.clear()
            lockwatch._edges.update(saved_edges)
            lockwatch._blocking.clear()
            lockwatch._blocking.update(saved_blocking)
            lockwatch._waived_orders.clear()
            lockwatch._waived_orders.update(saved_waived)


# -- disarmed: zero overhead ------------------------------------------------


def test_disarmed_returns_plain_stdlib_locks():
    if lockwatch.ARMED:
        pytest.skip("session runs armed (BFTKV_LOCKWATCH=1)")
    lk = lockwatch.named_lock("test.plain")
    # The contract is structural: no wrapper AT ALL — the exact class a
    # direct threading.Lock() call returns, so the disarmed build is
    # bit-for-bit the pre-lockwatch build on the lock hot path.
    assert type(lk) is type(threading.Lock())
    rlk = lockwatch.named_lock("test.plain.r", rlock=True)
    assert type(rlk) is type(threading.RLock())


def test_disarmed_perf_parity_smoke():
    if lockwatch.ARMED:
        pytest.skip("session runs armed (BFTKV_LOCKWATCH=1)")

    def cycle(lock, n=20000):
        t0 = time.perf_counter()
        for _ in range(n):
            with lock:
                pass
        return time.perf_counter() - t0

    plain = threading.Lock()
    named = lockwatch.named_lock("test.parity")
    # Identical classes, so any delta is box noise; median-of-5 with a
    # generous bound keeps this meaningful without being flaky.
    ratios = []
    for _ in range(5):
        p = cycle(plain)
        m = cycle(named)
        ratios.append(m / max(p, 1e-9))
    ratios.sort()
    assert ratios[2] < 2.0, ratios


def test_disarmed_nothing_patched():
    if lockwatch.ARMED:
        pytest.skip("session runs armed (BFTKV_LOCKWATCH=1)")
    import builtins

    assert not hasattr(builtins.open, "__lockwatch_orig__")


# -- armed: cycles ----------------------------------------------------------


def test_ab_ba_cycle_detected(armed):
    a = lockwatch.named_lock("test.cycle.a")
    b = lockwatch.named_lock("test.cycle.b")
    with a:
        with b:
            pass

    def reverse():
        with b:
            with a:
                pass

    t = threading.Thread(target=reverse)
    t.start()
    t.join()
    rep = lockwatch.report()
    assert ["test.cycle.a", "test.cycle.b", "test.cycle.a"] in rep[
        "cycles"
    ] or ["test.cycle.b", "test.cycle.a", "test.cycle.b"] in rep["cycles"]
    assert lockwatch.fail_message() is not None


def test_consistent_order_is_clean(armed):
    a = lockwatch.named_lock("test.order.a")
    b = lockwatch.named_lock("test.order.b")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = lockwatch.report()
    assert rep["cycles"] == []
    assert "test.order.a->test.order.b" in rep["edges"]


def test_three_party_cycle_detected(armed):
    locks = {
        n: lockwatch.named_lock(f"test.tri.{n}") for n in ("a", "b", "c")
    }

    def nest(first, second):
        with locks[first]:
            with locks[second]:
                pass

    for pair in (("a", "b"), ("b", "c")):
        t = threading.Thread(target=nest, args=pair)
        t.start()
        t.join()
    t = threading.Thread(target=nest, args=("c", "a"))
    t.start()
    t.join()
    cycles = lockwatch.report()["cycles"]
    assert any(len(c) == 4 for c in cycles), cycles


def test_waive_order_excludes_edge(armed):
    a = lockwatch.named_lock("test.waive.a")
    b = lockwatch.named_lock("test.waive.b")
    lockwatch.waive_order(
        "test.waive.b", "test.waive.a", "test fixture: benign reverse"
    )
    with a:
        with b:
            pass

    def reverse():
        with b:
            with a:
                pass

    t = threading.Thread(target=reverse)
    t.start()
    t.join()
    rep = lockwatch.report()
    assert rep["cycles"] == []
    assert any(
        w["order"] == ["test.waive.b", "test.waive.a"]
        for w in rep["waived"]
    )


def test_reentrant_rlock_not_an_edge(armed):
    r = lockwatch.named_lock("test.reentrant", rlock=True)
    with r:
        with r:
            pass
    assert lockwatch.report()["cycles"] == []


# -- armed: blocking calls under watched locks ------------------------------


def test_blocking_open_under_storage_lock_flagged(armed, tmp_path):
    lk = lockwatch.named_lock("storage.test")
    target = tmp_path / "x"
    with lk:
        with open(target, "w") as f:
            f.write("hi")
    blocking = lockwatch.report()["blocking"]
    assert any(
        b["lock"] == "storage.test" and b["func"] == "open"
        for b in blocking
    )
    assert "blocking call under lock" in lockwatch.fail_message()


def test_blocking_listdir_under_metrics_lock_flagged(armed, tmp_path):
    import os

    lk = lockwatch.named_lock("metrics")
    with lk:
        os.listdir(tmp_path)
    blocking = lockwatch.report()["blocking"]
    assert any(b["func"] == "os.listdir" for b in blocking)


def test_blocking_outside_watched_classes_clean(armed, tmp_path):
    lk = lockwatch.named_lock("transport.test.pool")
    with lk:
        (tmp_path / "y").write_text("ok")
    assert lockwatch.report()["blocking"] == []


def test_waiver_region_suppresses_blocking(armed, tmp_path):
    lk = lockwatch.named_lock("storage.test2")
    with lk:
        with lockwatch.waiver("test fixture: known-benign one-time I/O"):
            (tmp_path / "z").write_text("ok")
    assert lockwatch.report()["blocking"] == []


# -- armed: stdlib interop --------------------------------------------------


def test_condition_wait_notify_over_named_lock(armed):
    lk = lockwatch.named_lock("test.cv")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=5)
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        hits.append("set")
        cv.notify()
    t.join(timeout=5)
    assert "woke" in hits
    assert lockwatch.report()["cycles"] == []


def test_acquire_timeout_and_locked(armed):
    lk = lockwatch.named_lock("test.api")
    assert lk.acquire() is True
    assert lk.locked()
    assert lk.acquire(False) is False
    lk.release()
    assert not lk.locked()
