"""Multi-device sharding of the production verify/sign kernels.

The conftest forces an 8-virtual-device CPU mesh; these tests assert
the dispatcher-facing RNS entry points (a) actually take the sharded
path on a multi-device pool, and (b) return bit-identical results to
the single-device kernels — the VERDICT r3 "make multi-device real"
gate.  Collectives stay inside one replica's trust domain (SURVEY §5).
"""

from __future__ import annotations

import secrets

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from bftkv_tpu.crypto import rsa  # noqa: E402
from bftkv_tpu.ops import limb, rns  # noqa: E402


def test_mesh_exists():
    # conftest's 8-device CPU mesh is what the whole module rides on.
    assert len(jax.devices()) >= 8
    assert rns._mesh() is not None
    assert rns._shardable(64)
    assert not rns._shardable(7)  # indivisible batches stay single-dev


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_sharded_verify_matches_single_device():
    key1, key2 = rsa.generate(2048), rsa.generate(2048)
    ctx = rns.context()
    msgs = [b"ms-%d" % i for i in range(16)]
    keys = [key1 if i % 2 else key2 for i in range(16)]
    sigs = [int.from_bytes(rsa.sign(m, k), "big") for m, k in zip(msgs, keys)]
    ems = [rsa.emsa_pkcs1v15_sha256(m, k.size_bytes) for m, k in zip(msgs, keys)]
    sigs[3] ^= 1 << 9
    sigs[11] ^= 1 << 30
    sig_d = np.stack([limb.int_to_limbs(s, 128) for s in sigs])
    em_d = np.stack([limb.int_to_limbs(e, 128) for e in ems])
    idx = np.array([i % 2 for i in range(16)], dtype=np.int32)
    ukey = tuple(
        jnp.asarray(a)
        for a in rns.stack_key_rows(
            [ctx.key_rows(key2.n), ctx.key_rows(key1.n)]
        )
    )
    sig_h = rns.digits_to_halves_u8(sig_d)
    em_h = rns.digits_to_halves_u8(em_d)

    sharded = np.asarray(
        rns._jitted_verify_gather_sharded()(sig_h, em_h, idx, ukey)
    )
    single = np.asarray(rns._jitted_verify_gather()(sig_h, em_h, idx, ukey))
    want = [i not in (3, 11) for i in range(16)]
    assert sharded.tolist() == want
    assert sharded.tolist() == single.tolist()

    # The public entry point routes through the sharded path here.
    assert rns._shardable(16)
    public = np.asarray(
        rns.verify_e65537_rns_indexed(sig_d, em_d, idx, ukey)
    )
    assert public.tolist() == want


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_sharded_pow_matches_single_device_and_host():
    ctx = rns.context(32, 512)
    mods, bases, exps = [], [], []
    while len(mods) < 3:
        m = secrets.randbits(500) | 1
        if ctx.key_rows(m) is not None:
            mods.append(m)
            bases.append(secrets.randbits(490))
            exps.append(secrets.randbits(470))
    # power_mod_rns pads to 64 — divisible by the 8-device mesh, so the
    # public sign path auto-shards; parity against host pow is the gate.
    got = rns.power_mod_rns(bases, exps, mods, n_bits=512)
    assert got == [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]


def test_dispatcher_flush_on_mesh():
    # End-to-end: a dispatcher flush large enough to shard returns the
    # right verdicts through the installed-sidecar call path.
    from bftkv_tpu.ops import dispatch

    key = rsa.generate(2048)
    items = []
    for i in range(16):
        m = b"df-%d" % i
        s = rsa.sign(m, key)
        if i == 7:
            s = bytes([s[0] ^ 1]) + s[1:]
        items.append((m, s, key.public))
    d = dispatch.VerifyDispatcher(
        verifier=rsa.VerifierDomain(host_threshold=0)
    )
    got = np.asarray(d.verify(items))
    assert got.tolist() == [i != 7 for i in range(16)]
