"""Hedged staged fan-out + health-aware staging (DESIGN.md §13).

The tier-1 acceptance smoke lives here: a 4-node loopback cluster with
one clique member delayed ~5-10x the fault-free p99 must keep write
p50 under 2x the fault-free floor — hedging caps the first gray
encounter at one hedge delay, and health-aware staging keeps the gray
member out of the minimal commit prefix afterwards.
"""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import transport as tp
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import registry as metrics

from cluster_utils import start_cluster

BITS = 1024


@pytest.fixture()
def cluster():
    tp.peer_latency.reset()
    c = start_cluster(4, 1, 4, bits=BITS)
    cl = c.clients[0]
    # Warm sessions + the latency tracker outside the measured region.
    for i in range(4):
        cl.write(b"hedge/warm/%d" % i, b"w")
    cl.drain_tails()
    yield c
    c.stop()
    fp.disarm()
    tp.peer_latency.reset()


def _p50(samples: list[float]) -> float:
    s = sorted(samples)
    return s[len(s) // 2]


def _gray_target(cluster) -> str:
    """The first clique member: guaranteed to sit in the minimal
    staged prefix of an interleaved WRITE_SIGN wave."""
    return cluster.universe.servers[0].name


def test_gray_member_does_not_drag_write_p50(cluster):
    """One of four clique members delayed far past p99: with hedging +
    health-aware staging on (the defaults), write p50 stays under
    2x the fault-free floor instead of timeout-bound."""
    cl = cluster.clients[0]

    free = []
    for i in range(8):
        t0 = time.perf_counter()
        cl.write(b"hedge/free/%d" % i, b"v")
        free.append(time.perf_counter() - t0)
    p50_free = _p50(free)

    target = _gray_target(cluster)
    delay = max(10.0 * p50_free, 0.5)
    fp.arm(3)
    fp.registry.add(
        "transport.send",
        "delay",
        match={"dst": target},
        seconds=delay,
        rule_id=f"slow_node:{target}",
    )
    hedged = []
    try:
        for i in range(10):
            t0 = time.perf_counter()
            cl.write(b"hedge/gray/%d" % i, b"v")
            hedged.append(time.perf_counter() - t0)
    finally:
        fp.disarm()
    cl.drain_tails()
    p50_gray = _p50(hedged)

    # The acceptance gate: <= 2x the fault-free floor (plus timer
    # noise headroom when the floor is sub-10 ms), and decisively
    # below the injected delay — the straggler never anchored p50.
    assert p50_gray <= max(2.0 * p50_free, 2.0 * p50_free + 0.05), (
        f"gray p50 {p50_gray:.3f}s vs fault-free {p50_free:.3f}s"
    )
    assert p50_gray < delay / 2

    snap = metrics.snapshot()
    # The first gray write hedged (fp armed -> threaded driver), and
    # the latency tracker flagged the member gray.
    assert (
        sum(
            v
            for k, v in snap.items()
            if k.startswith("transport.hedge.sent")
        )
        >= 1
    )
    from bftkv_tpu import quorum as qm

    qa = qm.choose_quorum_for(cl.qs, b"hedge/gray/0", qm.AUTH | qm.PEER)
    addr = next(n.address for n in qa.nodes() if n.name == target)
    # The straggler's delayed response — the sample that trips the
    # gray flag — lands up to `delay` after its write committed.
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not tp.peer_latency.is_gray(addr):
        time.sleep(0.05)
    assert tp.peer_latency.is_gray(addr)
    # Every gray write still committed through the collapsed path.
    assert cl.read(b"hedge/gray/9") == b"v"


def test_gray_member_surfaces_in_fleet_feed(cluster):
    """The latency tracker's gray transition reaches the anomaly feed
    as gray_member — detection without any injected-fault echo."""
    from bftkv_tpu.obs import FleetCollector

    cl = cluster.clients[0]
    collector = FleetCollector([], local_metrics=metrics)
    collector.scrape_once()
    seq0 = max((a["seq"] for a in collector.anomalies()), default=0)

    target = _gray_target(cluster)
    fp.arm(4)
    fp.registry.add(
        "transport.send",
        "delay",
        match={"dst": target},
        seconds=0.6,
        rule_id=f"slow_node:{target}",
    )
    try:
        cl.write(b"hedge/feed", b"v")
    finally:
        fp.disarm()
    cl.drain_tails()

    # The delayed response (and with it the gray sample) lands ~0.6 s
    # after the hedged write committed — poll the scrape for it.
    gray: list = []
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not gray:
        collector.scrape_once()
        gray = [
            a
            for a in collector.anomalies(since_seq=seq0)
            if a["kind"] == "gray_member"
        ]
        if not gray:
            time.sleep(0.1)
    assert gray, "gray transition never reached the anomaly feed"
    assert any(target in a["detail"] for a in gray)


def test_ranking_pushes_flagged_peers_back(cluster):
    """Health-aware staging: a gray member sorts behind healthy ones,
    an open-breaker member behind gray; healthy order is preserved
    bit-for-bit (stable sort on flags only)."""
    from bftkv_tpu import quorum as qm

    cl = cluster.clients[0]
    qa = qm.choose_quorum_for(cl.qs, b"hedge/rank", qm.AUTH | qm.PEER)
    nodes = qa.nodes()
    assert cl._rank_nodes(nodes) == list(nodes)  # no signal: unchanged

    gray = nodes[0]
    tp.peer_latency.record(gray.address, 0.01)
    tp.peer_latency.record(gray.address, 9.0, timeout=True)
    assert tp.peer_latency.is_gray(gray.address)
    ranked = cl._rank_nodes(nodes)
    assert ranked[-1] is gray
    assert ranked[:-1] == [n for n in nodes if n is not gray]

    was_enabled = tp.peer_health.enabled
    tp.peer_health.enabled = True
    try:
        down = nodes[1]
        for _ in range(tp.peer_health.threshold):
            tp.peer_health.fail(down.address)
        ranked = cl._rank_nodes(nodes)
        assert ranked[-1] is down  # open breaker ranks even behind gray
        assert ranked[-2] is gray
    finally:
        tp.peer_health.enabled = was_enabled
        tp.peer_health.reset()
    tp.peer_latency.reset()


def test_fleet_snapshot_feeds_ranking(cluster):
    """apply_fleet_snapshot: members the /fleet document reports down
    go to the back of the staged wave."""
    from bftkv_tpu import quorum as qm

    cl = cluster.clients[0]
    qa = qm.choose_quorum_for(cl.qs, b"hedge/fleet", qm.AUTH | qm.PEER)
    nodes = qa.nodes()
    victim = nodes[0]
    cl.apply_fleet_snapshot(
        {
            "shards": {
                "0": {
                    "members": [
                        {"name": victim.name, "status": "down"},
                    ]
                }
            }
        }
    )
    try:
        ranked = cl._rank_nodes(nodes)
        assert ranked[-1] is victim
    finally:
        cl.apply_fleet_snapshot({"shards": {}})


def test_hedge_disabled_env(cluster, monkeypatch):
    """BFTKV_HEDGE=off: no hedged waves, no health ranking — the
    pre-hedging staged fan-out, bit for bit."""
    from bftkv_tpu import quorum as qm

    monkeypatch.setenv("BFTKV_HEDGE", "off")
    cl = cluster.clients[0]
    qa = qm.choose_quorum_for(cl.qs, b"hedge/off", qm.AUTH | qm.PEER)
    nodes = qa.nodes()
    tp.peer_latency.record(nodes[0].address, 9.0, timeout=True)
    assert cl._rank_nodes(nodes) == list(nodes)  # ranking off too

    before = sum(
        v
        for k, v in metrics.snapshot().items()
        if k.startswith("transport.hedge.sent")
    )
    fp.arm(5)
    fp.registry.add(
        "transport.send",
        "delay",
        match={"dst": _gray_target(cluster)},
        seconds=0.3,
        rule_id="slow_node:off",
    )
    try:
        cl.write(b"hedge/off", b"v")
    finally:
        fp.disarm()
    cl.drain_tails()
    after = sum(
        v
        for k, v in metrics.snapshot().items()
        if k.startswith("transport.hedge.sent")
    )
    assert after == before
    assert cl.read(b"hedge/off") == b"v"
    tp.peer_latency.reset()
