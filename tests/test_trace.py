"""End-to-end request tracing (bftkv_tpu/trace.py): span primitives,
packet-envelope propagation, and the full client-write span tree over a
loopback cluster — the observability layer's acceptance gate."""

from __future__ import annotations

import json
import logging
import threading
import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import trace
from cluster_utils import start_cluster


def wait_trace(root_name: str, pred, timeout: float = 10.0) -> dict:
    """Newest trace with the given root once ``pred(trace)`` holds.

    The multicast early-exit leaves straggler fan-out workers finishing
    their rpc/server spans AFTER the client call returned, so a trace
    assembled immediately can be mid-flight; poll until it settles."""
    deadline = time.monotonic() + timeout
    last = None
    while True:
        roots = [
            t
            for t in trace.tracer.traces(limit=50)
            if t["root"] == root_name
        ]
        if roots:
            last = roots[-1]
            if pred(last):
                return last
        if time.monotonic() > deadline:
            assert last is not None, f"no {root_name} trace collected"
            return last
        time.sleep(0.05)


def dangling_parents(t: dict) -> list:
    ids = {s["span"] for s in t["spans"]}
    return [s for s in t["spans"] if "parent" in s and s["parent"] not in ids]


# -- primitives -------------------------------------------------------------


def test_span_nesting_parents_on_one_thread():
    trace.tracer.reset()
    with trace.span("outer") as outer:
        with trace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert outer.parent_id is None
    spans = trace.tracer.trace(outer.trace_id)
    assert [s["name"] for s in spans] == ["inner", "outer"]


def test_capture_attach_crosses_threads():
    trace.tracer.reset()
    seen = {}

    def worker(ctx):
        with trace.attach(ctx), trace.span("remote.child") as sp:
            seen["trace_id"] = sp.trace_id
            seen["parent_id"] = sp.parent_id

    with trace.span("root") as root:
        ctx = trace.capture()
        t = threading.Thread(target=worker, args=(ctx,))
        t.start()
        t.join()
    assert seen["trace_id"] == root.trace_id
    assert seen["parent_id"] == root.span_id


def test_attach_none_shields_leaked_context():
    trace.tracer.reset()
    with trace.span("root"):
        with trace.attach(None):
            # Stack still wins over remote; but a fresh thread-style
            # context (empty stack) must see no remote either.
            assert trace.capture() is not None  # stack top
    # outside any span: no context
    assert trace.capture() is None


def test_trace_envelope_roundtrip_and_passthrough():
    tid, sid = trace.new_id(), trace.new_id()
    payload = pkt.serialize(b"x", b"v", 7)
    wrapped = pkt.wrap_trace(tid, sid, payload)
    ctx, out = pkt.unwrap_trace(wrapped)
    assert ctx == (tid, sid)
    assert out == payload
    # the inner payload parses identically after the round trip
    p = pkt.parse(out)
    assert (p.variable, p.value, p.t) == (b"x", b"v", 7)
    # a bare packet passes through untouched: its first envelope byte
    # is a length-prefix 0x00, never the 0xff magic
    ctx2, out2 = pkt.unwrap_trace(payload)
    assert ctx2 is None and out2 == payload


def test_slow_trace_capture_and_json_log(caplog):
    t = trace.Tracer(slow_threshold=0.0)  # everything is "slow"
    old, trace.tracer = trace.tracer, t
    try:
        with caplog.at_level(logging.WARNING, logger="bftkv_tpu.trace.slow"):
            with trace.span("slow.root"):
                with trace.span("slow.child", attrs={"batch_size": 3}):
                    pass
        slow = t.slow()
        assert len(slow) == 1
        assert slow[0]["root"] == "slow.root"
        names = [s["name"] for s in slow[0]["spans"]]
        assert names == ["slow.child", "slow.root"]
        # exactly one structured JSON line, machine-parseable
        lines = [r.message for r in caplog.records]
        assert len(lines) == 1
        doc = json.loads(lines[0])
        assert doc["event"] == "slow_request"
        assert doc["root"] == "slow.root"
        assert any(
            s.get("attrs", {}).get("batch_size") == 3 for s in doc["spans"]
        )
    finally:
        trace.tracer = old


def test_error_lands_in_span_attrs():
    trace.tracer.reset()
    with pytest.raises(ValueError):
        with trace.span("boom") as sp:
            raise ValueError("nope")
    assert "error" in sp.attrs


# -- the acceptance gate: one write, one trace, the full span tree ----------


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(4, 1, 4, bits=1024)
    yield c
    c.stop()


def test_write_trace_spans_loopback_cluster(cluster):
    trace.tracer.reset()
    cluster.clients[0].write(b"traced/x", b"value-1")

    def settled(t):
        names = [s["name"] for s in t["spans"]]
        return (
            sum(1 for n in names if n.startswith("rpc.")) >= 3
            and "server.verify_batch" in names
            and "storage.write" in names
            # the combined round + its async tail have both closed
            and "phase.write_sign" in names
            and "phase.ack" in names
        )

    t = wait_trace("client.write", settled)
    spans = t["spans"]
    names = [s["name"] for s in spans]

    # one trace id covers everything
    assert {s["trace"] for s in spans} == {t["trace_id"]}
    # the collapsed write's phases: ONE combined fan-out, then the
    # async tail (share mint + collective back-fill).  The classic
    # phase.time/phase.sign/phase.write spans belong to the fallback
    # path only (BFTKV_PIGGYBACK=off).
    assert "phase.write_sign" in names
    assert "phase.ack" in names
    # >= 3 per-peer fan-out RPCs (4 quorum servers)
    assert sum(1 for n in names if n.startswith("rpc.")) >= 3
    # server-side admission joined the SAME trace across the envelope
    assert any(n.startswith("server.") for n in names)
    # verify-batch spans carry the batch-size attribute
    vb = [s for s in spans if s["name"] == "server.verify_batch"]
    assert vb
    assert all("batch_size" in s.get("attrs", {}) for s in vb)
    # the storage op made it in
    assert "storage.write" in names


def test_write_trace_parent_edges_resolve(cluster):
    """Every non-root span's parent is another span of the same trace —
    the tree reassembles without dangling edges (single-process
    loopback: all nodes share the collector)."""
    trace.tracer.reset()
    cluster.clients[0].write(b"traced/y", b"value-2")
    t = wait_trace("client.write", lambda t: not dangling_parents(t))
    assert not dangling_parents(t), dangling_parents(t)


def test_read_trace_spans(cluster):
    cluster.clients[0].write(b"traced/r", b"value-r")  # self-contained
    trace.tracer.reset()
    assert cluster.clients[0].read(b"traced/r") == b"value-r"

    def settled(t):
        names = [s["name"] for s in t["spans"]]
        return (
            sum(1 for n in names if n == "rpc.read") >= 3
            and "server.read" in names
        )

    t = wait_trace("client.read", settled)
    names = [s["name"] for s in t["spans"]]
    assert "quorum.select" in names
    assert sum(1 for n in names if n == "rpc.read") >= 3
    assert "server.read" in names


def test_trace_disabled_sends_no_envelope(cluster, monkeypatch):
    """BFTKV_TRACE=off: spans are no-ops, no context rides the wire,
    and the protocol still works."""
    monkeypatch.setattr(trace.tracer, "enabled", False)
    trace.tracer.reset()
    cluster.clients[0].write(b"traced/off", b"v")
    assert cluster.clients[0].read(b"traced/off") == b"v"
    # No client roots collected for the disabled operations (straggler
    # worker spans from the PREVIOUS enabled test may still trickle in
    # after reset(), so assert on the roots, not on emptiness).
    assert not any(
        s["name"] in ("client.write", "client.read")
        for t in trace.tracer.traces(limit=50)
        for s in t["spans"]
    )
