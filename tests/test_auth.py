"""TPA end-to-end, in-process (reference: crypto/auth/auth_test.go:14-114)."""

import pytest

from bftkv_tpu.crypto import auth
from bftkv_tpu.errors import (
    ERR_AUTHENTICATION_FAILURE,
    ERR_TOO_MANY_ATTEMPTS,
)


def run_protocol(password: bytes, servers: dict[int, auth.AuthServer], n: int, k: int):
    """Drive all three phases by direct calls — no transport."""
    client = auth.AuthClient(password, n, k)
    reqs = client.initiate(list(servers))
    phase = 0
    while not client.done(phase):
        nxt = None
        for nid, req in reqs.items():
            res, _done = servers[nid].make_response(phase, req)
            out = client.process_response(phase, res, nid)
            if out is not None:
                nxt = out
                break  # callback early-exit, like the multicast cb
        assert nxt is not None, f"phase {phase} never completed"
        reqs = nxt
        phase += 1
    return client, reqs


def make_servers(password: bytes, n: int, k: int, proofs=None):
    params = auth.generate_partial_auth_params(password, n, k)
    return {
        i: auth.AuthServer(
            params[i],
            (proofs[i] if proofs else b"proof-%d" % i),
            sleep=lambda _t: None,
        )
        for i in range(n)
    }


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_full_roundtrip_n10_k7():
    password = b"correct horse battery staple"
    n, k = 10, 7
    servers = make_servers(password, n, k)
    client, proofs = run_protocol(password, servers, n, k)
    # every participating server's proof decrypts intact
    for nid, proof in proofs.items():
        assert proof == b"proof-%d" % nid
    key1 = client.get_cipher_key()
    # a fresh session derives the same cipher key (it's hash(g_pi^S, pw))
    client2, _ = run_protocol(password, make_refreshed(servers), n, k)
    assert client2.get_cipher_key() == key1


def make_refreshed(servers):
    # re-wrap the same params in fresh server sessions
    return {
        nid: auth.AuthServer(s.params.serialize(), s.proof, sleep=lambda _t: None)
        for nid, s in servers.items()
    }


def test_wrong_password_fails_mac():
    password = b"right"
    n, k = 4, 3
    servers = make_servers(password, n, k)
    client = auth.AuthClient(b"wrong", n, k)
    reqs = client.initiate(list(servers))
    # phase 0 succeeds (servers just exponentiate)
    nxt = None
    for nid, req in reqs.items():
        res, _ = servers[nid].make_response(0, req)
        out = client.process_response(0, res, nid)
        if out is not None:
            nxt = out
            break
    assert nxt is not None
    # phase 1 runs, phase 2 must fail the MAC on every server
    n_map = None
    for nid, req in nxt.items():
        res, _ = servers[nid].make_response(1, req)
        out = client.process_response(1, res, nid)
        if out is not None:
            n_map = out
    assert n_map is not None
    for nid, ni in n_map.items():
        with pytest.raises(ERR_AUTHENTICATION_FAILURE):
            servers[nid].make_response(2, ni)


def test_retry_limit():
    servers = make_servers(b"pw", 1, 1)
    s = servers[0]
    client = auth.AuthClient(b"pw", 1, 1)
    x = client.initiate([0])[0]
    for _ in range(auth.AUTH_RETRY_LIMIT - 1):
        s.make_response(0, x)
    with pytest.raises(ERR_TOO_MANY_ATTEMPTS):
        s.make_response(0, x)


def test_k_minus_one_is_insufficient():
    password = b"pw"
    n, k = 5, 3
    servers = make_servers(password, n, k)
    client = auth.AuthClient(password, n, k)
    reqs = client.initiate(list(servers))
    fed = 0
    for nid, req in reqs.items():
        if fed == k - 1:
            break
        res, _ = servers[nid].make_response(0, req)
        assert client.process_response(0, res, nid) is None or fed == k - 1
        fed += 1
    assert client.gs is None


def test_params_roundtrip():
    p = auth.AuthParams(x=3, y=12345, v=67890, salt=b"salty")
    assert auth.AuthParams.parse(p.serialize()) == p


def test_stragglers_and_duplicates_ignored():
    """Late phase-0 responses and replayed phase-1/2 responses must not
    corrupt the combined state (all n respond; k < n)."""
    password = b"pw"
    n, k = 5, 3
    servers = make_servers(password, n, k)
    client = auth.AuthClient(password, n, k)
    reqs = client.initiate(list(servers))
    # feed ALL n phase-0 responses (no early exit)
    nxt = None
    for nid, req in reqs.items():
        res, _ = servers[nid].make_response(0, req)
        out = client.process_response(0, res, nid)
        if out is not None:
            nxt = out  # keep the FIRST map; stragglers keep arriving
    assert nxt is not None and len(nxt) == k
    # phase 1 with a duplicate of every response
    n_map = None
    for nid, req in nxt.items():
        res, _ = servers[nid].make_response(1, req)
        out = client.process_response(1, res, nid)
        dup = client.process_response(1, res, nid)  # replay
        assert dup is None or out is not None
        n_map = out or n_map
    assert n_map is not None
    assert all(v is not None for v in n_map.values())
    # phase 2 completes with intact MACs
    proofs = None
    for nid, ni in n_map.items():
        res, _ = servers[nid].make_response(2, ni)
        out = client.process_response(2, res, nid)
        proofs = out or proofs
    assert proofs is not None
    for nid, proof in proofs.items():
        assert proof == b"proof-%d" % nid


def test_concurrent_sessions_do_not_clobber():
    """Two clients interleaved against the same AuthServer state."""
    password = b"pw"
    servers = make_servers(password, 1, 1)
    s = servers[0]
    c1 = auth.AuthClient(password, 1, 1)
    c2 = auth.AuthClient(password, 1, 1)
    x1 = c1.initiate([0])[0]
    x2 = c2.initiate([0])[0]
    m1 = c1.process_response(0, s.make_response(0, x1, session=1)[0], 0)
    m2 = c2.process_response(0, s.make_response(0, x2, session=2)[0], 0)
    # interleave phase 1: session 2 runs between session 1's phases
    n1 = c1.process_response(1, s.make_response(1, m1[0], session=1)[0], 0)
    n2 = c2.process_response(1, s.make_response(1, m2[0], session=2)[0], 0)
    p1 = c1.process_response(2, s.make_response(2, n1[0], session=1)[0], 0)
    p2 = c2.process_response(2, s.make_response(2, n2[0], session=2)[0], 0)
    assert p1[0] == b"proof-0" and p2[0] == b"proof-0"


def test_attempt_counter_spans_sessions():
    """attempts accrues per stored variable, not per client session."""
    servers = make_servers(b"pw", 1, 1)
    s = servers[0]
    for i in range(auth.AUTH_RETRY_LIMIT - 1):
        c = auth.AuthClient(b"pw", 1, 1)
        s.make_response(0, c.initiate([0])[0], session=i)
    c = auth.AuthClient(b"pw", 1, 1)
    with pytest.raises(ERR_TOO_MANY_ATTEMPTS):
        s.make_response(0, c.initiate([0])[0], session=99)
    s.reset_attempts()
    res, _ = s.make_response(0, c.initiate([0])[0], session=100)
    assert res
