"""Critical-path attribution (bftkv_tpu/obs/critpath): hand-built
trace trees with known exclusive times, overlap/straggler semantics,
child clipping, p99-exemplar selection, histogram merge across
members — plus the collector's one-scrape-deferred attribution pass,
the SLO burn-rate anomaly hysteresis, and the loopback acceptance bar
(per-phase exclusive times sum to the root span's duration)."""

from __future__ import annotations

import pytest

from bftkv_tpu import trace
from bftkv_tpu.metrics import BUCKETS
from bftkv_tpu.obs import FleetCollector
from bftkv_tpu.obs.critpath import PhaseBudget, attribute
from bftkv_tpu.trace import PHASES, phase_of

from cluster_utils import start_cluster


def sp(name, start, dur, *, span, parent=None, trace_id="t1",
       phase=None, attrs=None):
    d = {"trace": trace_id, "span": span, "name": name,
         "start": float(start), "duration": float(dur)}
    if parent is not None:
        d["parent"] = parent
    if phase is not None:
        d["phase"] = phase
    if attrs:
        d["attrs"] = attrs
    return d


def _bd(op="write", shard=0, root_s=1.0, phases=None, tid="t"):
    phases = phases or {"rpc": root_s}
    return {"op": op, "shard": shard, "trace_id": tid, "root_s": root_s,
            "phases": phases, "attributed_s": sum(phases.values())}


# -- the phase registry -----------------------------------------------------


def test_phase_registry_closed_enum():
    assert set(PHASES) == {
        "lease", "fanout", "rpc", "server", "dispatch", "sidecar",
        "combine", "backfill", "other",
    }
    assert phase_of("presession.lease") == "lease"
    assert phase_of("rpc.write_sign") == "rpc"  # prefix rule
    assert phase_of("sidecar.call") == "sidecar"
    # longest prefix wins: sync.repair.backfill is the back-fill tail,
    # not generic sync work
    assert phase_of("sync.repair.backfill") == "backfill"
    assert phase_of("sync.pull") == "other"
    # outside the registry: lands in "other" at runtime (bftlint keeps
    # that set empty in-tree)
    assert phase_of("totally.unknown") == "other"


# -- one-trace attribution --------------------------------------------------


def test_exclusive_times_known_tree():
    spans = [
        sp("client.write", 0.0, 1.0, span="r", attrs={"shard": 2}),
        sp("presession.lease", 0.0, 0.2, span="a", parent="r"),
        sp("phase.write_sign", 0.2, 0.7, span="b", parent="r"),
        sp("rpc.write_sign", 0.3, 0.5, span="c", parent="b"),
    ]
    bd = attribute(spans)
    assert bd["op"] == "write" and bd["shard"] == 2
    assert bd["root_s"] == pytest.approx(1.0)
    ph = bd["phases"]
    assert ph["lease"] == pytest.approx(0.2)
    assert ph["rpc"] == pytest.approx(0.5)
    # fan-out self time = round span minus its rpc child
    assert ph["fanout"] == pytest.approx(0.2)
    # root self time (0.9..1.0) is "other"
    assert ph["other"] == pytest.approx(0.1)
    assert sum(ph.values()) == pytest.approx(bd["root_s"])
    assert bd["attributed_s"] == pytest.approx(bd["root_s"])


def test_overlapping_siblings_straggler_owns_overlap():
    # sidecar [0.0, 0.6] and rpc [0.2, 0.8] overlap on [0.2, 0.6]; the
    # LAST-ENDING sibling (the straggler the caller waited on) claims
    # it — rpc gets 0.6, sidecar only its un-overlapped 0.2.
    spans = [
        sp("client.write", 0.0, 1.0, span="r"),
        sp("sidecar.call", 0.0, 0.6, span="a", parent="r"),
        sp("rpc.write_sign", 0.2, 0.6, span="b", parent="r"),
    ]
    ph = attribute(spans)["phases"]
    assert ph["rpc"] == pytest.approx(0.6)
    assert ph["sidecar"] == pytest.approx(0.2)
    assert ph["other"] == pytest.approx(0.2)
    assert sum(ph.values()) == pytest.approx(1.0)


def test_overlapping_same_phase_counted_once():
    # Two parallel RPCs [0, 0.6] + [0.2, 0.8]: union is 0.8 seconds of
    # wall clock, never the 1.2 a naive per-span sum would claim.
    spans = [
        sp("client.write", 0.0, 1.0, span="r"),
        sp("rpc.write_sign", 0.0, 0.6, span="a", parent="r"),
        sp("rpc.write_sign", 0.2, 0.6, span="b", parent="r"),
    ]
    ph = attribute(spans)["phases"]
    assert ph["rpc"] == pytest.approx(0.8)
    assert sum(ph.values()) == pytest.approx(1.0)


def test_child_outliving_root_is_clipped():
    # An async back-fill tail outlives the root (early commit): only
    # its in-window slice [0.9, 1.0] enters the budget, so the phase
    # sum still equals the root duration exactly.
    spans = [
        sp("client.write", 0.0, 1.0, span="r"),
        sp("backfill.record", 0.9, 1.6, span="a", parent="r"),
    ]
    ph = attribute(spans)["phases"]
    assert ph["backfill"] == pytest.approx(0.1)
    assert sum(ph.values()) == pytest.approx(1.0)


def test_clock_skewed_child_outside_window_drops_to_parent():
    # Cross-process skew pushed the stitched child entirely outside the
    # root's window: it attributes nothing (coarser, never double).
    spans = [
        sp("client.write", 0.0, 1.0, span="r"),
        sp("server.write_sign", 5.0, 0.3, span="a", parent="r"),
    ]
    ph = attribute(spans)["phases"]
    assert ph["server"] == 0.0
    assert ph["other"] == pytest.approx(1.0)


def test_explicit_phase_attr_wins_over_registry():
    spans = [
        sp("client.write", 0.0, 1.0, span="r"),
        sp("verify:flush", 0.0, 0.3, span="a", parent="r",
           phase="dispatch"),
    ]
    ph = attribute(spans)["phases"]
    assert ph["dispatch"] == pytest.approx(0.3)


def test_non_root_traces_return_none():
    assert attribute([]) is None
    # a server-only fragment (root never stitched in) has no budget
    assert attribute(
        [sp("server.write_sign", 0.0, 0.5, span="a", parent="gone")]
    ) is None
    # batch roots are deliberately outside ROOT_OPS
    assert attribute(
        [sp("client.write_many", 0.0, 0.5, span="r")]
    ) is None


def test_read_root_reports_as_read():
    bd = attribute([sp("client.read_certified", 0.0, 0.2, span="r")])
    assert bd["op"] == "read"
    assert bd["phases"]["other"] == pytest.approx(0.2)


# -- aggregation: histograms + exemplars ------------------------------------


def test_budget_doc_counts_and_shares():
    pb = PhaseBudget()
    for _ in range(4):
        pb.observe(_bd(shard=1, root_s=0.4,
                       phases={"rpc": 0.3, "other": 0.1}))
    d = pb.doc()["write"][1]
    assert d["count"] == 4
    assert d["root_sum_s"] == pytest.approx(1.6)
    assert d["phases"]["rpc"]["share"] == pytest.approx(0.75)
    assert d["phases"]["other"]["share"] == pytest.approx(0.25)
    assert sum(d["phases"]["rpc"]["buckets"]) == 4


def test_p99_exemplar_is_a_straggler_not_the_mean():
    pb = PhaseBudget(max_exemplars=4)
    for i in range(100):
        pb.observe(_bd(root_s=0.01, phases={"rpc": 0.01},
                       tid=f"fast{i}"))
    for i in range(5):
        pb.observe(_bd(root_s=2.0, phases={"server": 2.0},
                       tid=f"slow{i}"))
    d = pb.doc()["write"][0]
    ex = d["p99_exemplar"]
    # the exemplar's breakdown is a slow trace's — all server time —
    # even though 100/105 observations were fast rpc-bound writes
    assert ex["root_s"] == pytest.approx(2.0)
    assert set(ex["phases"]) == {"server"}
    assert ex["trace_id"].startswith("slow")
    assert d["root_p99_le_s"] >= 2.0


def test_histogram_merge_across_members():
    a, b = PhaseBudget(), PhaseBudget()
    a.observe(_bd(root_s=0.1, phases={"rpc": 0.1}, tid="m1"))
    b.observe(_bd(root_s=1.0, phases={"server": 1.0}, tid="m2"))
    b.observe(_bd(op="read", shard=1, root_s=0.2,
                  phases={"rpc": 0.2}, tid="m3"))
    a.merge(b)
    doc = a.doc()
    d = doc["write"][0]
    assert d["count"] == 2
    assert d["root_sum_s"] == pytest.approx(1.1)
    # bucket vectors summed, both phases present
    assert sum(d["phases"]["rpc"]["buckets"]) == 1
    assert sum(d["phases"]["server"]["buckets"]) == 1
    # exemplars re-ranked across members: the merged p99 exemplar is
    # the other member's slow trace
    assert d["p99_exemplar"]["trace_id"] == "m2"
    assert doc["read"][1]["count"] == 1
    # merge is summation on the fixed ladder: merging into a fresh
    # budget reproduces the same doc
    c = PhaseBudget()
    c.merge(a)
    assert c.doc()["write"][0]["root_sum_s"] == pytest.approx(1.1)


# -- the collector's deferred attribution pass ------------------------------


_CLIQUE = {"n": 4, "f": 1, "threshold": 3, "suff": 3,
           "members": ["a01", "a02", "a03", "a04"]}


class _Src:
    """A scriptable member whose /trace feed drains per scrape."""

    def __init__(self, name, spans_by_scrape, ring_dropped=0):
        self.name = name
        self._spans = list(spans_by_scrape)
        self._cursor = 0
        self.ring_dropped = ring_dropped
        self._info = {"name": name, "shard": 0, "shard_count": 1,
                      "role": "clique", "clique": _CLIQUE,
                      "owned_buckets": 128}

    def info(self):
        return self._info

    def metrics(self):
        return {}

    def probe(self):
        return True

    def trace_export(self, cursor):
        spans = self._spans.pop(0) if self._spans else []
        self._cursor += len(spans)
        return {"cursor": self._cursor, "dropped": 0, "spans": spans,
                "slow": [], "ring_dropped": self.ring_dropped,
                "slow_dropped": 0}


def test_collector_attributes_one_scrape_after_root():
    # Scrape 1 carries the client-side tree; the server's stitched
    # fragment only lands on scrape 2 — attribution must wait for it.
    client_spans = [
        sp("client.write", 0.0, 1.0, span="r", attrs={"shard": 0}),
        sp("rpc.write_sign", 0.1, 0.8, span="c", parent="r"),
    ]
    server_spans = [
        sp("server.write_sign", 0.2, 0.5, span="s", parent="c"),
    ]
    srcs = [
        _Src("a01", [client_spans, []], ring_dropped=3),
        _Src("a02", [[], server_spans]),
    ]
    coll = FleetCollector(srcs)
    doc1 = coll.scrape_once()
    assert doc1["write_budget_by_phase"] == {}
    doc2 = coll.scrape_once()
    budget = doc2["write_budget_by_phase"][0]
    assert budget["count"] == 1
    ph = {p: d["sum_s"] for p, d in budget["phases"].items()}
    # the late-arriving server fragment made it into the budget
    assert ph["server"] == pytest.approx(0.5, abs=1e-6)
    assert ph["rpc"] == pytest.approx(0.3, abs=1e-6)
    assert sum(ph.values()) == pytest.approx(1.0, abs=1e-6)
    # the per-shard view is the same budget
    assert doc2["shards"]["0"]["budget"]["write"]["count"] == 1
    # ring-drop satellites: members' self-reported overwrite counts
    # aggregate fleet-wide instead of dying in per-daemon counters
    assert doc2["fleet"]["trace_drops"]["ring"] == 3
    prom = coll.prometheus()
    assert "bftkv_fleet_phase_seconds_bucket" in prom
    assert 'phase="rpc"' in prom
    assert "bftkv_fleet_trace_ring_dropped 3" in prom


# -- SLO burn rate ----------------------------------------------------------


def _vec(fast=0, slow=0):
    v = [0] * (len(BUCKETS) + 1)
    v[0] = fast                       # ≤ 1 ms bucket
    v[BUCKETS.index(2.5)] = slow      # ≤ 2.5 s bucket, over any sane SLO
    return v


def test_slo_burn_needs_k_consecutive_breaches(monkeypatch):
    monkeypatch.setenv("BFTKV_SLO_WRITE_P99", "0.5")
    monkeypatch.setenv("BFTKV_SLO_BURN_SCRAPES", "3")
    coll = FleetCollector([])
    seen: list = []
    coll.add_anomaly_listener(seen.append)
    fast, slow = 0, 0

    def scrape(d_fast=0, d_slow=0):
        nonlocal fast, slow
        fast += d_fast
        slow += d_slow
        coll._slo_burn_check({(0, "write"): _vec(fast, slow)})

    def burns():
        return [a for a in seen if a["kind"] == "slo_burn"]

    scrape(d_slow=1)          # breach 1
    scrape(d_slow=1)          # breach 2
    assert burns() == []      # one (or two) slow scrapes never page
    scrape()                  # idle: no traffic, burn count HOLDS
    assert burns() == []
    scrape(d_slow=1)          # breach 3 -> fires
    assert len(burns()) == 1
    assert burns()[0]["shard"] == 0
    scrape(d_slow=1)          # still burning: fires once per episode
    assert len(burns()) == 1
    scrape(d_fast=50)         # healthy scrape re-arms the hysteresis
    scrape(d_slow=1)
    scrape(d_slow=1)
    assert len(burns()) == 1  # re-armed: two breaches are not three
    scrape(d_slow=1)
    assert len(burns()) == 2  # a second full episode fires again


def test_slo_burn_disabled_without_flag(monkeypatch):
    monkeypatch.delenv("BFTKV_SLO_WRITE_P99", raising=False)
    coll = FleetCollector([])
    seen: list = []
    coll.add_anomaly_listener(seen.append)
    for _ in range(5):
        coll._slo_burn_check({(0, "write"): _vec(slow=100)})
    assert seen == []


# -- loopback acceptance: budgets sum to the root ---------------------------


def test_loopback_write_budget_sums_to_root():
    """ISSUE 15 acceptance: on a real loopback cluster_4 write, the
    per-phase exclusive times sum to within 10% of the root span's
    duration (by construction they match exactly), and the budget
    actually attributes time to real phases."""
    t = trace.Tracer()
    old, trace.tracer = trace.tracer, t
    cluster = start_cluster(4, 1, 4, bits=1024)
    try:
        cl = cluster.clients[0]
        cl.write(b"critpath/warm", b"v0")
        cl.drain_tails()
        cur = t.export(0)["cursor"]
        for i in range(3):
            cl.write(b"critpath/%d" % i, b"payload-%d" % i)
        cl.drain_tails()
        spans = t.export(cur)["spans"]
    finally:
        cluster.stop()
        trace.tracer = old
    traces: dict[str, list] = {}
    for s in spans:
        traces.setdefault(s["trace"], []).append(s)
    budgets = [
        bd for bd in (attribute(v) for v in traces.values())
        if bd is not None and bd["op"] == "write"
    ]
    assert len(budgets) == 3
    for bd in budgets:
        assert bd["root_s"] > 0
        gap = abs(sum(bd["phases"].values()) - bd["root_s"])
        assert gap <= 0.10 * bd["root_s"] + 1e-9
        assert set(bd["phases"]) == set(PHASES)
    # the decomposition is non-degenerate: real fan-out/rpc time was
    # attributed, not everything lumped into "other"
    total = sum(bd["root_s"] for bd in budgets)
    named = sum(
        v for bd in budgets for p, v in bd["phases"].items()
        if p != "other"
    )
    assert named > 0.25 * total
