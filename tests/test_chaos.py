"""Chaos nemesis + safety checker over a live 4-node loopback cluster
(bftkv_tpu/faults: nemesis schedules, crash-restart onto the same
storage, link-matrix partitions, Byzantine failpoint programs, and the
BFT invariants the checker enforces over every run).

Tier-1 keeps the short deterministic runs; the long seeded soak is
``slow``-marked for the nightly lane."""

from __future__ import annotations

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu.faults import byzantine as byz
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.faults.checker import SafetyChecker
from bftkv_tpu.faults.harness import build_cluster
from bftkv_tpu.faults.nemesis import Nemesis

BITS = 1024


@pytest.fixture(autouse=True)
def _disarmed():
    fp.disarm()
    yield
    fp.disarm()


@pytest.fixture()
def cluster():
    c = build_cluster(4, 1, 4, bits=BITS)
    try:
        yield c
    finally:
        c.stop()


def _roots(cluster):
    return {s._sync_tree().root() for s in cluster.storage_servers}


def test_partition_crash_restart_checker_clean(cluster):
    """The tier-1 short chaos run: partition one replica, crash-restart
    another onto the same storage, keep writing throughout, converge
    via anti-entropy, and demand ZERO safety violations."""
    nem = Nemesis(cluster, seed=11)
    fp.registry.arm(11)
    cl = cluster.clients[0]

    cl.write_once(b"chaos/once", b"immutable")
    cluster.recorder.write_once_ok("u01", b"chaos/once", b"immutable")
    nem.traffic("baseline")

    # Partition: rw01 cut from everyone (servers AND the client).
    rules = nem.partition("rw01")
    try:
        nem.traffic("partitioned")
    finally:
        nem.heal(rules)

    # Crash-restart: rw02 dies, traffic continues on 3/4, then a FRESH
    # server restarts on the same storage and must be converged back.
    cluster.crash("rw02")
    nem.traffic("crashed")
    cluster.restart("rw02")

    nem.traffic("healed")
    cluster.recorder.read_ok("u01", b"chaos/once", cl.read(b"chaos/once"))

    assert nem.converge(), "anti-entropy must reconverge all replicas"
    assert len(_roots(cluster)) == 1
    trace = fp.registry.trace()
    assert trace, "the partition must actually have dropped packets"
    fp.disarm()

    checker = SafetyChecker(cluster.recorder, f=cluster.f)
    violations = checker.check(cluster.storage_servers)
    assert violations == [], violations
    # No write was lost despite the chaos windows (1 fault at a time
    # stays inside the f budget, so liveness held too).
    assert nem.failures == {"write": 0, "read": 0}
    # Every converged replica serves the latest committed values.
    for var, val in sorted(nem._written.items())[:3]:
        for srv in cluster.storage_servers:
            assert pkt.parse(srv.storage.read(var, 0)).value == val


def test_byzantine_programs_checker_clean(cluster):
    """Byzantine modes as failpoint programs: a colluder and a stale
    replayer (both genuinely signed behaviors) achieve nothing an
    honest reader can observe — and the checker proves it."""
    nem = Nemesis(cluster, seed=12)
    fp.registry.arm(12)
    cl = cluster.clients[0]
    nem.traffic("pre")

    colluder = byz.make_colluder(fp.registry, "rw01")
    stale = byz.make_stale_replayer(fp.registry, "rw02")
    try:
        nem.traffic("byz")
        # Overwrite a variable while rw02 replays stale reads: the
        # reader's deterministic resolution must still pick the newest
        # committed value.
        cl.write(b"chaos/fresh", b"old")
        cl.write(b"chaos/fresh", b"new")
        # Both wave-1 write-plane members are the two faulty nodes here
        # (beyond the f=1 budget reads are promised under) — settle the
        # back-fill so the honest plane holds the certified record.
        cl.drain_tails()
        cluster.recorder.write_ok("u01", b"chaos/fresh", b"new")
        got = cl.read(b"chaos/fresh")
        cluster.recorder.read_ok("u01", b"chaos/fresh", got)
        assert got == b"new"
    finally:
        fp.registry.remove_all(colluder + stale)
    assert any(r.fires for r in stale), "stale replayer must have answered"

    assert nem.converge()
    fp.disarm()
    violations = SafetyChecker(cluster.recorder, f=cluster.f).check(
        cluster.storage_servers
    )
    assert violations == [], violations


def test_checker_catches_planted_violations(cluster):
    """The checker itself must not be vacuous: plant a fabricated read
    and a conflicting commit in the history and see both flagged."""
    rec = cluster.recorder
    cl = cluster.clients[0]
    cl.write(b"chk/x", b"real")
    rec.read_ok("u01", b"chk/x", b"FABRICATED")  # nothing signed this
    for node in ("rw01", "rw02", "rw03"):
        rec.record(
            "persist", node=node, honest=True, variable=b"chk/y", t=9,
            value=b"A", completed=True,
        )
        rec.record(
            "persist", node=node, honest=True, variable=b"chk/y", t=9,
            value=b"B", completed=True,
        )
    violations = SafetyChecker(rec, f=cluster.f).check(
        cluster.storage_servers
    )
    assert any("no verifiable collective signature" in v for v in violations)
    assert any("conflicting commits" in v for v in violations)


def test_seeded_nemesis_run_end_to_end(cluster):
    """``Nemesis.run`` — the programmatic form of
    ``python -m bftkv_tpu.faults.nemesis --seed N``: seeded plan,
    traffic, repair, convergence, checker."""
    report = Nemesis(cluster, seed=3).run(steps=3)
    assert report["violations"] == []
    assert report["converged"] is True
    assert report["faults_fired"] >= 0
    assert len(report["plan"]) == 3
    # The plan replays identically for the same seed and cluster shape.
    assert Nemesis(cluster, seed=3).plan(3) == report["plan"]


@pytest.mark.slow
def test_long_nemesis_soak():
    """Nightly soak: a 12-step seeded schedule with dwell, fresh
    cluster, zero violations and full convergence demanded."""
    c = build_cluster(4, 1, 4, bits=BITS)
    try:
        report = Nemesis(c, seed=42).run(steps=12, dwell=0.2)
        assert report["violations"] == [], report["violations"]
        assert report["converged"] is True
        assert report["faults_fired"] > 0
    finally:
        c.stop()
