"""WAN smoke (DESIGN.md §21, the CI tier-1 step): a 2-region loopback
fleet under the ``wan2`` matrix (20 ms intra / 60 ms cross).  Proves
the two §21 claims cheaply: a same-region gateway read is served at
cache latency (never paying the cross-region quorum fan-out), and the
fleet collector's health document grows per-region rows that
``cmd.fleet`` renders.
"""

from __future__ import annotations

import time
from collections import Counter

import pytest

from bftkv_tpu import regions as rg
from bftkv_tpu import transport as tp
from bftkv_tpu.cmd.fleet import render as fleet_render
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.obs import FleetCollector, LocalSource
from bftkv_tpu.regions.topology import install_matrix
from bftkv_tpu.storage.memkv import MemStorage

from cluster_utils import start_cluster

BITS = 1024


@pytest.fixture(scope="module")
def wan_fleet():
    tp.peer_latency.reset()
    tp.peer_health.reset()
    cluster = start_cluster(
        4, 2, 4, bits=BITS, storage_factory=MemStorage,
        n_gateways=1, n_regions=2,
    )
    reg = fp.arm(5)
    matrix, _program = install_matrix(reg, "wan2")
    yield cluster, reg, matrix
    fp.disarm()
    cluster.stop()
    tp.peer_latency.reset()
    tp.peer_health.reset()


def _p50(lats: list[float]) -> float:
    s = sorted(lats)
    return s[len(s) // 2]


def test_same_region_gateway_read_at_cache_latency(wan_fleet):
    cluster, _reg, matrix = wan_fleet
    uni = cluster.universe
    # Round-robin labels put reader 0 in the gateway's region and
    # reader 1 across the 60 ms link.
    assert uni.users[0].region == uni.gateways[0].region
    assert uni.users[1].region != uni.gateways[0].region
    gw_same = cluster.gateway_client(0)
    gw_cross = cluster.gateway_client(1)

    gw_same.write(b"wan/smoke", b"v1")
    assert gw_same.read(b"wan/smoke") == b"v1"  # warm the edge cache

    same, cross = [], []
    for _ in range(9):
        t0 = time.perf_counter()
        assert gw_same.read(b"wan/smoke") == b"v1"
        same.append(time.perf_counter() - t0)
    for _ in range(5):
        t0 = time.perf_counter()
        assert gw_cross.read(b"wan/smoke") == b"v1"
        cross.append(time.perf_counter() - t0)

    # A cached same-region read pays one intra-region hop (~10 ms
    # one-way under wan2); an uncached read would add the gateway's
    # cross-region quorum fan-out (>= 30 ms more).  Cache latency is
    # therefore anything comfortably below that fan-out floor.
    assert _p50(same) < 0.035, f"same-region read p50 {_p50(same):.4f}s"
    # The cross-region reader pays the 60 ms link by construction —
    # same-region locality is what the region plane buys.
    assert _p50(cross) > _p50(same)


def test_region_rows_in_health_and_fleet_render(wan_fleet):
    cluster, _reg, _matrix = wan_fleet
    uni = cluster.universe
    idents = uni.servers + uni.storage_nodes
    sources = [
        LocalSource(ident.name, lambda s=srv: s)
        for ident, srv in zip(idents, cluster.all_servers)
    ]
    for gw in cluster.gateways:
        sources.append(LocalSource(gw.self_node.name, lambda g=gw: g))
    coll = FleetCollector(sources)
    coll.scrape_once()
    doc = coll.health()

    regs = doc["regions"]
    assert regs["n"] == 2
    expected = Counter(
        i.region for i in uni.servers + uni.storage_nodes + uni.gateways
    )
    assert set(regs["rows"]) == set(expected)
    for r, row in regs["rows"].items():
        assert row["members"] == expected[r]
        assert row["up"] == row["members"]
        assert row["down"] == [] and not row["dark"]
    gw_region = uni.gateways[0].region
    assert regs["rows"][gw_region]["gateways"] == [
        uni.gateways[0].name
    ]
    # Healthy fleet: the region-level f-budget is intact and nothing
    # in the anomaly feed names a region outage.
    assert regs["f_budget"]["f"] == 0  # (2-1)//3 — any outage reads -1
    assert regs["f_budget"]["remaining"] == 0
    assert regs["f_budget"]["dark"] == []
    assert not [
        a for a in coll.anomalies() if a["kind"] == "region_down"
    ]

    out = fleet_render(doc)
    assert "regions: 2" in out
    for r, row in regs["rows"].items():
        assert f"{r}: {row['up']}/{row['members']} up" in out
    # The process-global map and the health rollup agree on the world.
    assert sorted(regs["rows"]) == rg.regionmap.regions()
