"""Visual transport: WebSocket handshake, graph snapshot, request feed
(reference: transport/http-visual/http-visual.go:43-173)."""

import base64
import hashlib
import json
import os
import socket
import struct
import time


from bftkv_tpu import topology
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import Server
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.transport.http import TrHTTP
from bftkv_tpu.transport.visual import TrVisual, WsHub

WS_PORT = 17801
BASE = 17821


def _ws_connect(port: int) -> tuple[socket.socket, bytes]:
    """Returns (socket, leftover): frames pushed right after the 101
    can land in the same recv as the handshake response."""
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    key = base64.b64encode(os.urandom(16)).decode()
    s.sendall(
        (
            "GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
            f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n\r\n"
        ).encode()
    )
    resp = b""
    while b"\r\n\r\n" not in resp:
        resp += s.recv(4096)
    head, _, leftover = resp.partition(b"\r\n\r\n")
    assert b"101" in head.split(b"\r\n")[0]
    want = base64.b64encode(
        hashlib.sha1(
            (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
        ).digest()
    )
    assert want in head
    return s, leftover


def _read_frames(s: socket.socket, timeout: float = 10.0, initial: bytes = b""):
    s.settimeout(timeout)
    buf = initial
    while True:
        try:
            while True:
                # parse as many complete frames as buffered
                if len(buf) >= 2:
                    ln = buf[1] & 0x7F
                    off = 2
                    if ln == 126:
                        if len(buf) < 4:
                            pass
                        ln = struct.unpack(">H", buf[2:4])[0]
                        off = 4
                    if len(buf) >= off + ln:
                        yield json.loads(buf[off : off + ln])
                        buf = buf[off + ln :]
                        continue
                break
            chunk = s.recv(65536)
            if not chunk:
                return
            buf += chunk
        except socket.timeout:
            return


def test_visual_feed_end_to_end():
    uni = topology.build_universe(
        4, 1, 4, scheme="http", base_port=BASE, rw_base_port=BASE + 20,
        bits=1024,
    )
    hub = WsHub(("127.0.0.1", WS_PORT))
    servers = []
    try:
        for i, ident in enumerate(uni.servers + uni.storage_nodes):
            graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
            # first server narrates to the hub; the rest are plain HTTP
            tr = TrVisual(crypt, hub, graph) if i == 0 else TrHTTP(crypt)
            srv = Server(graph, qs, tr, crypt, MemStorage())
            srv.start()
            servers.append(srv)

        ws, leftover = _ws_connect(WS_PORT)
        time.sleep(0.2)

        g, cr, q = topology.make_node(uni.users[0], uni.view_of(uni.users[0]))
        client = Client(g, q, TrHTTP(cr), cr)
        client.write(b"vis/x", b"hello")
        assert client.read(b"vis/x") == b"hello"

        events = list(_read_frames(ws, timeout=3.0, initial=leftover))
        types = {e["type"] for e in events}
        assert "graph" in types, events
        cmds = {e.get("command") for e in events if e["type"] == "request"}
        # the narrated node served at least one write-path command
        # (write_sign = the collapsed round; time/sign/write = the
        # classic rounds and the certify/back-fill deliveries)
        assert {"time", "sign", "write", "write_sign", "batch_write"} & cmds, events
        graph_evt = next(e for e in events if e["type"] == "graph")
        assert any(n["self"] for n in graph_evt["nodes"])
        assert graph_evt["edges"]
        ws.close()
    finally:
        for srv in servers:
            srv.tr.stop()
        hub.stop()


def test_visual_page_exists():
    page = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "visual", "index.html",
    )
    with open(page) as f:
        body = f.read()
    assert "WebSocket" in body and "drawGraph" in body
