"""Storage backends: versioned read/write, t=0=latest, persistence.

Mirrors the semantics of the reference storage layer
(reference: storage/storage.go, storage/plain/plain.go,
storage/leveldb/leveldb.go).
"""

import pytest

from bftkv_tpu.errors import ERR_NOT_FOUND
from bftkv_tpu.storage.logkv import LogStorage
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.storage.native import NativeStorage
from bftkv_tpu.storage.plain import PlainStorage


@pytest.fixture(params=["mem", "plain", "native", "log"])
def store(request, tmp_path):
    if request.param == "mem":
        yield MemStorage()
    elif request.param == "plain":
        yield PlainStorage(str(tmp_path / "db"))
    elif request.param == "log":
        s = LogStorage(str(tmp_path / "db-log"), fsync=False)
        yield s
        s.close()
    else:
        s = NativeStorage(str(tmp_path / "db.log"))
        yield s
        s.close()


def test_not_found(store):
    with pytest.raises(ERR_NOT_FOUND):
        store.read(b"missing")
    with pytest.raises(ERR_NOT_FOUND):
        store.read(b"missing", 3)


def test_versions_and_latest(store):
    store.write(b"x", 1, b"v1")
    store.write(b"x", 3, b"v3")
    store.write(b"x", 2, b"v2")
    assert store.read(b"x", 1) == b"v1"
    assert store.read(b"x", 2) == b"v2"
    assert store.read(b"x") == b"v3"  # t=0 -> latest
    with pytest.raises(ERR_NOT_FOUND):
        store.read(b"x", 4)


def test_versions_listing(store):
    # versions() is part of the storage contract (the server read path's
    # scan past in-progress sign records; reference: leveldb.go:30-46).
    assert store.versions(b"x") == []
    store.write(b"x", 1, b"v1")
    store.write(b"x", 3, b"v3")
    store.write(b"x", 2**64 - 1, b"once")
    assert sorted(store.versions(b"x")) == [1, 3, 2**64 - 1]
    assert store.versions(b"other") == []


def test_native_versions_survive_reopen(tmp_path):
    path = str(tmp_path / "db.log")
    s = NativeStorage(path)
    for t in range(1, 100):
        s.write(b"x", t, b"v%d" % t)
    s.close()
    s = NativeStorage(path)
    assert sorted(s.versions(b"x")) == list(range(1, 100))
    s.close()


def test_overwrite_same_t(store):
    store.write(b"x", 5, b"a")
    store.write(b"x", 5, b"b")
    assert store.read(b"x", 5) == b"b"
    assert store.read(b"x") == b"b"


def test_empty_value_and_binary_keys(store):
    var = bytes(range(256))
    store.write(var, 1, b"")
    assert store.read(var) == b""


def test_writeonce_timestamp(store):
    t = 2**64 - 1
    store.write(b"once", t, b"final")
    assert store.read(b"once") == b"final"
    assert store.read(b"once", t) == b"final"


@pytest.mark.parametrize("cls", ["plain", "native", "log"])
def test_persistence_across_reopen(cls, tmp_path):
    if cls == "plain":
        path = str(tmp_path / "db")
        s = PlainStorage(path)
    elif cls == "log":
        path = str(tmp_path / "db-log")
        s = LogStorage(path, fsync=False)
    else:
        path = str(tmp_path / "db.log")
        s = NativeStorage(path)
    s.write(b"x", 1, b"v1")
    s.write(b"x", 2, b"v2")
    s.write(b"y", 7, b"w")
    if cls == "native":
        s.close()
        s = NativeStorage(path)
    elif cls == "log":
        s.close()
        s = LogStorage(path, fsync=False)
    else:
        s = PlainStorage(path)
    assert s.read(b"x") == b"v2"
    assert s.read(b"x", 1) == b"v1"
    assert s.read(b"y") == b"w"
    if cls in ("native", "log"):
        s.close()


def test_keys_and_scan(store):
    # keys()/scan() are part of the storage contract (the anti-entropy
    # digest tree enumerates the keyspace with them; bftkv_tpu/sync).
    assert store.keys() == []
    assert store.scan() == []
    long_var = b"\xff" * 200  # hash-stemmed in the plain backend
    store.write(b"x", 1, b"a")
    store.write(b"x", 3, b"c")
    store.write(b"y", 2, b"b")
    store.write(long_var, 7, b"z")
    assert sorted(store.keys()) == sorted([b"x", b"y", long_var])
    assert sorted(store.scan()) == sorted(
        [(b"x", 1), (b"x", 3), (b"y", 2), (long_var, 7)]
    )
    # Overwriting an existing version must not duplicate inventory rows.
    store.write(b"x", 3, b"c2")
    assert sorted(store.keys()) == sorted([b"x", b"y", long_var])
    assert len(store.scan()) == 4


def test_backend_differential_parity(tmp_path):
    """Drive the identical write/read/versions/keys/scan sequence
    through all four backends and assert identical observable results
    — the contract is one, the engines are four.  The log engine
    additionally crash-restarts mid-trace (index dropped, rebuilt from
    the segment scan) and again before observation: replay must land
    on the exact same view the backends that never died present."""
    import random

    backends = {
        "mem": MemStorage(),
        "plain": PlainStorage(str(tmp_path / "p")),
        "native": NativeStorage(str(tmp_path / "n.log")),
        # Tiny segments so the trace spans several sealed files — the
        # replay exercises multi-segment rebuild, not just one tail.
        "log": LogStorage(
            str(tmp_path / "l"), fsync=False, segment_bytes=512
        ),
    }
    rng = random.Random(42)
    variables = [b"a", b"b" * 40, b"\x00\x01", b"h" * 120, b""]
    ops = []
    for _ in range(120):
        var = rng.choice(variables)
        t = rng.randint(1, 12)
        ops.append((var, t, b"v%d-%d" % (t, rng.randint(0, 3))))

    for i, (var, t, val) in enumerate(ops):
        for s in backends.values():
            s.write(var, t, val)
        if i == 60:
            backends["log"].reopen()  # crash-restart mid-trace

    backends["log"].reopen()  # and once more before observing

    def observe(s):
        out = {
            "keys": sorted(s.keys()),
            "scan": sorted(s.scan()),
        }
        for var in variables:
            out[("versions", var)] = sorted(s.versions(var))
            for t in [0] + sorted({t for v, t, _ in ops if v == var}):
                try:
                    out[("read", var, t)] = s.read(var, t)
                except ERR_NOT_FOUND:
                    out[("read", var, t)] = None
        return out

    views = {name: observe(s) for name, s in backends.items()}
    assert views["mem"] == views["plain"]
    assert views["mem"] == views["native"]
    assert views["mem"] == views["log"]
    backends["native"].close()
    backends["log"].close()


def test_native_large_values(tmp_path):
    s = NativeStorage(str(tmp_path / "db.log"))
    big = bytes(1024 * 1024)
    s.write(b"big", 1, big)
    s.write(b"big", 2, b"tiny")
    assert s.read(b"big", 1) == big
    assert s.read(b"big") == b"tiny"
    s.close()


def test_plain_torn_write_recovery(tmp_path):
    """A write torn mid-flight (storage failpoint: partial bytes land in
    the .tmp, the process dies before rename) must leave the store
    readable at the previous version after "restart", keep the torn
    remnant out of versions()/keys()/scan(), and let a subsequent write
    of the same version succeed."""
    from bftkv_tpu.faults import failpoint as fp

    path = str(tmp_path / "db")
    s = PlainStorage(path)
    s.write(b"x", 1, b"v1")

    fp.arm(3)
    try:
        fp.registry.add(
            "storage.write", "torn", match={"backend": "plain"}, times=1
        )
        with pytest.raises(OSError):
            s.write(b"x", 2, b"v2-that-tears")
    finally:
        fp.disarm()

    # The torn remnant is on disk but invisible to every read surface.
    import os

    assert any(n.endswith(".tmp") for n in os.listdir(path))
    s2 = PlainStorage(path)  # crash-restart onto the same dir
    assert s2.read(b"x") == b"v1"
    assert s2.versions(b"x") == [1]
    assert s2.keys() == [b"x"]
    assert s2.scan() == [(b"x", 1)]

    # Recovery: the same version writes cleanly over the stale .tmp.
    s2.write(b"x", 2, b"v2")
    assert s2.read(b"x") == b"v2"
    assert s2.versions(b"x") == [1, 2]


def test_log_crash_replay_torn_tail(tmp_path):
    """Crash-point replay, case 1: the process dies MID-append — half a
    record lands on disk.  Reopen truncates the tail at the first bad
    checksum and recovers the exact pre-crash ``scan()``; the same
    version then writes cleanly over the reclaimed space."""
    from bftkv_tpu.faults import failpoint as fp

    s = LogStorage(str(tmp_path / "db"), fsync=False)
    s.write(b"x", 1, b"v1")
    s.write(b"y", 2, b"v2")
    before = sorted(s.scan())

    fp.arm(3)
    try:
        fp.registry.add(
            "storage.write", "torn", match={"backend": "log"}, times=1
        )
        with pytest.raises(OSError):
            s.write(b"x", 3, b"v3-that-tears")
    finally:
        fp.disarm()

    s.reopen()  # crash-restart onto the same segment directory
    assert sorted(s.scan()) == before
    assert s.read(b"x") == b"v1"
    assert s.versions(b"x") == [1]

    s.write(b"x", 3, b"v3")
    assert s.read(b"x") == b"v3"
    s.close()


def test_log_crash_replay_append_before_index(tmp_path):
    """Crash-point replay, case 2: the record hit the log in full but
    the process died BEFORE any index update.  Replay recovers it — the
    log is the truth, the in-RAM index is a cache."""
    from bftkv_tpu.storage import segment as seg

    s = LogStorage(str(tmp_path / "db"), fsync=False)
    s.write(b"x", 1, b"v1")
    # The crash point: a complete, checksummed record the dying process
    # never indexed (appended behind the store's back).
    with open(s._active_path, "ab") as f:
        f.write(seg.encode_record(b"y", 7, b"w"))
    s.reopen()
    assert sorted(s.scan()) == [(b"x", 1), (b"y", 7)]
    assert s.read(b"y") == b"w"
    assert s.versions(b"y") == [7]
    s.close()


def test_plain_fsync_policy(tmp_path, monkeypatch):
    """Durability policy: the library default is no per-write fsync
    (the reference's leveldb stance); the daemon opts in explicitly,
    and BFTKV_PLAIN_FSYNC overrides either way.  The crash-safe write
    ORDERING (temp + rename) is unconditional."""
    monkeypatch.delenv("BFTKV_PLAIN_FSYNC", raising=False)
    assert PlainStorage(str(tmp_path / "a")).fsync is False
    monkeypatch.setenv("BFTKV_PLAIN_FSYNC", "1")
    assert PlainStorage(str(tmp_path / "b")).fsync is True
    s = PlainStorage(str(tmp_path / "c"), fsync=True)
    assert s.fsync is True
    s.write(b"x", 1, b"v1")  # exercises the fsync(file)+fsync(dir) path
    assert s.read(b"x") == b"v1"
    assert s.versions(b"x") == [1]
