"""Server session-state bounds (VERDICT r3 weak #5 / item 8).

A long-lived daemon must not accumulate unbounded per-variable TPA
state or per-peer transport sessions from hostile traffic.  These
tests flood the seams and assert the maps stay bounded — while the
anti-brute-force attempt counter survives eviction (the property that
justified keeping sessions alive in the first place).
"""

from __future__ import annotations

import pytest

from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import rsa
from bftkv_tpu.crypto.message import MessageSecurity


def _cluster():
    from tests.cluster_utils import start_cluster

    return start_cluster(4, 1, 4)


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_auth_session_map_bounded():
    c = _cluster()
    try:
        srv = c.servers[0]
        srv.AUTH_SESSIONS_MAX = 16
        cl = c.clients[0]
        # Flood distinct protected variables: each authenticate builds
        # an AuthServer per replica (reference: server.go:405-434).
        for i in range(28):
            var = b"flood/%d" % i
            cl.authenticate(var, b"pw-%d" % i)  # seeds params + auths
        assert len(srv._auth) <= 16, len(srv._auth)
        for s in c.all_servers:
            assert len(s._auth) <= 4096
        # The hottest entry still authenticates after the flood.  One
        # bounded retry: the TPA handshake needs k-of-n live phases,
        # and on a heavily loaded machine a replica can miss its slot
        # in the first attempt (observed ~1 in 3 full-suite runs under
        # contention); what this test pins is that eviction never
        # *locks out* the variable, not single-shot scheduling luck.
        proof, _ = cl.authenticate(b"flood/27", b"pw-27")
        if proof is None:
            import time

            time.sleep(0.5)
            proof, _ = cl.authenticate(b"flood/27", b"pw-27")
        assert proof is not None
    finally:
        c.stop()


def test_auth_attempts_survive_eviction():
    # Eviction must not reset the brute-force penalty: retire a hot
    # AuthServer with attempts, then re-create it — counter carries.
    c = _cluster()
    try:
        cl = c.clients[0]
        var = b"bf/x"
        cl.authenticate(var, b"right")  # creates the auth data + sessions
        srv = c.servers[0]
        assert var in srv._auth
        srv._auth[var].attempts = 3
        # Force eviction via the TTL path.
        with srv._auth_lock:
            srv._auth_evict_locked(now=1e12)
        assert var not in srv._auth
        assert srv._auth_attempts.get(var) == 3
        # Next authenticate rebuilds the AuthServer WITH the carried
        # count (consumed from _auth_attempts at rebuild).  The client
        # needs only k of n for the final phase, so this server may not
        # observe "done": its counter is either reset (0) or the seeded
        # 3 plus this run's attempt — never restarted from scratch.
        cl.authenticate(var, b"right")
        assert var in srv._auth
        assert var not in srv._auth_attempts
        assert srv._auth[var].attempts in (0, 3, 4), srv._auth[var].attempts
        # At least one replica completed the handshake and cleared it.
        assert any(
            s._auth.get(var) is not None and s._auth[var].attempts == 0
            for s in c.servers
        )
    finally:
        c.stop()


def test_message_security_tables_bounded():
    key = rsa.generate(1024)
    cert = certmod.Certificate(n=key.n, e=key.e, name="m")
    ms = MessageSecurity(key, cert)
    ms._CACHE_MAX = 64
    # 200 distinct "peers" bootstrap sessions at us.
    for i in range(200):
        pk = rsa.generate(1024)
        pc = certmod.Certificate(n=pk.n, e=pk.e, name="p%d" % i)
        peer = MessageSecurity(pk, pc)
        blob = peer.encrypt([cert], b"hi", b"n%d" % i)
        ms.decrypt(blob)
    assert len(ms._by_id) <= 64
    assert len(ms._by_peer) <= 64


def test_auth_attempts_fold_after_midflight_eviction():
    """ADVICE r4 #1: an AuthServer fetched under _auth_lock is used
    outside it; if eviction retires it mid-handshake, wrong-password
    increments made on the retired object must still land in the
    durable counter (or in the replacement instance) when the handler
    finishes."""
    c = _cluster()
    try:
        cl = c.clients[0]
        var = b"bf/race"
        cl.authenticate(var, b"right")
        srv = c.servers[0]
        a = srv._auth[var]

        # Handler holds `a`; TTL eviction retires it concurrently with
        # attempts=2 recorded at retirement time.
        a.attempts = 2
        with srv._auth_lock:
            srv._auth_evict_locked(now=1e12)
        assert srv._auth_attempts.get(var) == 2

        # The in-flight handler then increments the retired object
        # (wrong password inside make_response) and finishes.
        a.attempts = 3
        srv._auth_fold_attempts(var, a)
        assert srv._auth_attempts.get(var) == 3

        # Replacement case: a new instance owns the variable while the
        # evicted one is still live; fold carries max() into it.
        cl.authenticate(var, b"right")  # rebuilds the map entry
        cur = srv._auth[var]
        assert cur is not a
        base = cur.attempts
        a.attempts = base + 5
        srv._auth_fold_attempts(var, a)
        assert srv._auth[var].attempts == base + 5

        # And folding a stale lower count never regresses the counter.
        a.attempts = 1
        srv._auth_fold_attempts(var, a)
        assert srv._auth[var].attempts == base + 5
    finally:
        c.stop()
