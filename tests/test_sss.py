"""SSS share/reconstruct properties (reference: crypto/sss/sss_test.go:54-75)."""

import random
import secrets

import pytest

from bftkv_tpu.crypto import sss

P = (1 << 127) - 1  # Mersenne prime, plenty for tests


def test_roundtrip_random_subsets():
    rng = random.Random(7)
    for _ in range(10):
        secret = secrets.randbelow(P)
        n, k = 10, 7
        shares = sss.distribute(secret, n, k, P)
        subset = rng.sample(shares, k)
        proc = sss.SSSProcess(n, k, P, subset)
        assert proc.secret == secret


def test_incremental_and_duplicate_shares():
    secret = 0xDEADBEEF
    shares = sss.distribute(secret, 5, 3, P)
    proc = sss.SSSProcess(5, 3, P)
    assert proc.process_response(shares[0]) is None
    # duplicate x must not count toward k
    assert proc.process_response(shares[0]) is None
    assert proc.process_response(shares[1]) is None
    assert proc.process_response(shares[3]) == secret
    # further shares are no-ops
    assert proc.process_response(shares[4]) == secret


def test_k_minus_one_insufficient():
    secret = 12345
    shares = sss.distribute(secret, 6, 4, P)
    proc = sss.SSSProcess(6, 4, P, shares[:3])
    assert proc.secret is None


def test_lagrange_tiny():
    # f(x) = 3 + 2x over Z_97: shares at x=1,2 are 5,7; λ weights recombine.
    m = 97
    xs = [1, 2]
    s = (sss.lagrange(1, xs, m) * 5 + sss.lagrange(2, xs, m) * 7) % m
    assert s == 3


def test_bad_params():
    with pytest.raises(ValueError):
        sss.distribute(1, 3, 4, P)
