"""Session-keyed message security: bootstrap → fast path → self-healing.

The first envelope between a pair carries an RSA-signed session grant;
everything after is pure AEAD. These tests pin the security properties
the design note in crypto/message.py claims: authenticity of the grant,
co-recipient isolation, reflection rejection, tamper detection, and the
ERR_UNKNOWN_SESSION recovery loop at the transport layer.
"""

import pytest

from bftkv_tpu import topology
from bftkv_tpu.errors import (
    ERR_DECRYPTION_FAILURE,
    ERR_INVALID_TRANSPORT_SECURITY_DATA,
    ERR_UNKNOWN_SESSION,
)
from bftkv_tpu.crypto.message import MessageSecurity

BITS = 1024


@pytest.fixture(scope="module")
def idents():
    return [topology.new_identity(f"n{i}", bits=BITS) for i in range(3)]


def mk(ident):
    return MessageSecurity(ident.key, ident.cert)


def test_bootstrap_then_session_roundtrip(idents):
    a, b = mk(idents[0]), mk(idents[1])
    blob1 = a.encrypt([idents[1].cert], b"first", b"n1")
    assert blob1[0] == 0x01  # bootstrap
    plain, sender, nonce = b.decrypt(blob1)
    assert (plain, nonce) == (b"first", b"n1")
    assert sender.id == idents[0].cert.id

    blob2 = a.encrypt([idents[1].cert], b"second", b"n2")
    assert blob2[0] == 0x02  # session fast path — no RSA involved
    plain, sender, nonce = b.decrypt(blob2)
    assert (plain, nonce) == (b"second", b"n2")
    assert sender.id == idents[0].cert.id

    # And the responder direction reuses the same session.
    resp = b.encrypt([idents[0].cert], b"reply", b"n2")
    assert resp[0] == 0x02
    plain, sender, _ = a.decrypt(resp)
    assert plain == b"reply" and sender.id == idents[1].cert.id


def test_multirecipient_bootstrap_isolates_grants(idents):
    a, b, c = (mk(i) for i in idents)
    blob = a.encrypt([idents[1].cert, idents[2].cert], b"fanout", b"n")
    pb, _, _ = b.decrypt(blob)
    pc, _, _ = c.decrypt(blob)
    assert pb == pc == b"fanout"
    # Fast-path envelope to both; each decrypts only its own record.
    blob2 = a.encrypt([idents[1].cert, idents[2].cert], b"fast", b"n")
    assert blob2[0] == 0x02
    assert b.decrypt(blob2)[0] == b"fast"
    assert c.decrypt(blob2)[0] == b"fast"
    # c cannot decrypt an envelope addressed to b alone.
    only_b = a.encrypt([idents[1].cert], b"private", b"n")
    with pytest.raises((ERR_DECRYPTION_FAILURE, ERR_UNKNOWN_SESSION)):
        c.decrypt(only_b)


def test_unknown_session_raises_interned_error(idents):
    a, b = mk(idents[0]), mk(idents[1])
    b.decrypt(a.encrypt([idents[1].cert], b"x", b"n"))
    fast = a.encrypt([idents[1].cert], b"y", b"n")
    fresh_b = mk(idents[1])  # simulates peer restart: empty session cache
    with pytest.raises(ERR_UNKNOWN_SESSION):
        fresh_b.decrypt(fast)


def test_reflection_rejected(idents):
    """A→B fast-path envelope bounced back at A must not decrypt as a
    message 'from B' (role byte in the key-wrap AAD)."""
    a, b = mk(idents[0]), mk(idents[1])
    b.decrypt(a.encrypt([idents[1].cert], b"x", b"n"))
    fast = a.encrypt([idents[1].cert], b"y", b"n")
    with pytest.raises((ERR_DECRYPTION_FAILURE, ERR_UNKNOWN_SESSION)):
        a.decrypt(fast)


def test_hostile_grant_cannot_hijack_session(idents):
    """A Byzantine peer that learned an honest pair's sid (it travels in
    cleartext on fast-path envelopes) must not be able to overwrite the
    honest inbound session with a grant of its own."""
    a, v, m = (mk(i) for i in idents)
    v.decrypt(a.encrypt([idents[1].cert], b"x", b"n"))  # honest A->V session
    sid = next(iter(a._by_peer.values())).sid
    # M forges a bootstrap to V whose grant reuses A's sid.  Envelope
    # secrets come from the crypto.rng DRBG seam now, so that is what
    # gets forced.
    from unittest import mock

    from bftkv_tpu.crypto import rng as _rng

    real = _rng.generate_random  # bind the real function before patching

    with mock.patch(
        "bftkv_tpu.crypto.message.rng.generate_random",
        side_effect=lambda n: sid if n == 16 else real(n),
    ):
        # Force M's grant sid to collide with A's.
        hostile = m.encrypt([idents[1].cert], b"evil", b"n")
    v.decrypt(hostile)  # the payload itself is authenticated, fine
    # A's fast path must still decrypt at V.
    fast = a.encrypt([idents[1].cert], b"still-works", b"n")
    plain, sender, _ = v.decrypt(fast)
    assert plain == b"still-works" and sender.id == idents[0].cert.id


def test_tampered_session_payload_fails_closed(idents):
    a, b = mk(idents[0]), mk(idents[1])
    b.decrypt(a.encrypt([idents[1].cert], b"x", b"n"))
    fast = bytearray(a.encrypt([idents[1].cert], b"y", b"n"))
    fast[-1] ^= 0x01
    with pytest.raises(
        (ERR_DECRYPTION_FAILURE, ERR_INVALID_TRANSPORT_SECURITY_DATA)
    ):
        b.decrypt(bytes(fast))


def test_garbage_and_empty_fail_closed(idents):
    b = mk(idents[1])
    for blob in (b"", b"\x00", b"\x03junk", b"\x02\x00", b"\x01" + b"\xff" * 40):
        with pytest.raises(
            (ERR_DECRYPTION_FAILURE, ERR_INVALID_TRANSPORT_SECURITY_DATA)
        ):
            b.decrypt(blob)


def test_transport_rebootstraps_after_peer_restart(idents):
    """The multicast fan-out recovers transparently when the peer lost
    its session cache: ERR_UNKNOWN_SESSION → invalidate → bootstrap."""
    from bftkv_tpu import transport as tp
    from bftkv_tpu.protocol.server import Server
    from bftkv_tpu.storage.memkv import MemStorage
    from bftkv_tpu.transport.loopback import LoopbackNet, TrLoopback

    uni = topology.build_universe(4, 1, 0, scheme="loop", bits=BITS)
    net = LoopbackNet()
    servers = []
    for ident in uni.servers:
        graph, crypt, qs = topology.make_node(ident, uni.view_of(ident))
        srv = Server(graph, qs, TrLoopback(crypt, net), crypt, MemStorage())
        srv.start()
        servers.append(srv)
    ugraph, ucrypt, uqs = topology.make_node(
        uni.users[0], uni.view_of(uni.users[0])
    )
    tr = TrLoopback(ucrypt, net)

    def times(expect_ok: int) -> int:
        oks = []
        tr.multicast(
            tp.TIME,
            [s.cert for s in uni.servers],
            b"x",
            lambda res: (oks.append(res) if res.err is None else None) and False,
        )
        return len(oks)

    assert times(4) == 4  # bootstraps everywhere
    # "Restart" one server: fresh crypto state, same identity/storage.
    victim = servers[0]
    victim.tr.stop()
    graph, crypt, qs = topology.make_node(
        uni.servers[0], uni.view_of(uni.servers[0])
    )
    srv2 = Server(graph, qs, TrLoopback(crypt, net), crypt, victim.storage)
    srv2.start()
    # The client still holds a session for the old incarnation; the
    # fan-out must self-heal and still get 4 responses.
    assert times(4) == 4
    for s in servers[1:] + [srv2]:
        s.tr.stop()
