"""Round-collapsed writes (PR 8): piggybacked shares, presession
leases, 2f+1 early commit with the async certify tail.

The acceptance smoke lives here too: a steady-state write crosses the
network in at most TWO quorum round trips — the combined WRITE_SIGN
fan-out the caller waits on, plus the async collective back-fill —
counted from the client-side ``transport.rpcs`` deltas."""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import transport as tp
from bftkv_tpu.errors import Error
from bftkv_tpu.faults import failpoint as fp
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.protocol.server import Server

from cluster_utils import start_cluster

BITS = 1024


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(4, 1, 4, bits=BITS)
    yield c
    c.stop()


def _client_rpcs(snap: dict) -> dict[str, int]:
    """client-side transport.rpcs by command name."""
    out: dict[str, int] = {}
    for k, v in snap.items():
        if k.startswith("transport.rpcs{") and "side=client" in k:
            cmd = k.split("cmd=")[1].split(",")[0].rstrip("}")
            out[cmd] = out.get(cmd, 0) + v
    return out


def _delta(after: dict, before: dict) -> dict[str, int]:
    return {
        k: v - before.get(k, 0)
        for k, v in after.items()
        if v - before.get(k, 0) > 0
    }


# -- the acceptance smoke: <= 2 quorum round trips per steady write ---------


def test_steady_state_write_is_two_round_trips(cluster):
    """After warmup, one write = one WRITE_SIGN fan-out (the round the
    caller waits on) + one batched BATCH_WRITE back-fill round on the
    async tail — and NOTHING else: no TIME round, no SIGN round."""
    cl = cluster.clients[0]
    cl.write(b"rt/warm", b"v")  # sessions + quorum caches + pump
    cl.drain_tails()

    before = _client_rpcs(metrics.snapshot())
    cl.write(b"rt/steady", b"value")
    cl.drain_tails()
    after = _client_rpcs(metrics.snapshot())
    d = _delta(after, before)

    # Only the two write-path rounds crossed the network.
    assert set(d) <= {"write_sign", "batch_write"}, d
    assert d.get("write_sign", 0) >= 1

    # Round-trip bound: the combined round fans to (at most) the
    # sign q ∪ write q union, the back-fill to the write quorum — two
    # rounds' worth of RPCs.
    qa = qm.choose_quorum_for(cl.qs, b"rt/steady", qm.AUTH | qm.PEER)
    qw = qm.choose_quorum_for(cl.qs, b"rt/steady", qm.WRITE)
    union = {n.id for n in qa.nodes()} | {n.id for n in qw.nodes()}
    assert d.get("write_sign", 0) <= len(union)
    assert d.get("batch_write", 0) <= len(qw.nodes())
    assert sum(d.values()) <= len(union) + len(qw.nodes())


def test_repeat_writer_uses_lease_no_declines(cluster):
    """Overwriting a variable this client already wrote costs zero
    timestamp declines: the presession lease supplies the guess."""
    cl = cluster.clients[0]
    cl.write(b"lease/x", b"v1")
    before = metrics.snapshot().get("client.piggyback.retry_t", 0)
    cl.write(b"lease/x", b"v2")
    cl.write(b"lease/x", b"v3")
    assert metrics.snapshot().get("client.piggyback.retry_t", 0) == before
    assert cl.read(b"lease/x") == b"v3"


def test_stale_lease_declines_and_retries_in_round(cluster):
    """A cold lease guesses t=1 against a variable that moved on; the
    quorum answers with stored-timestamp hints and the SAME round
    structure retries — no TIME round, no revocation of the honest
    writer."""
    cl = cluster.clients[0]
    cl.write(b"stale/x", b"v1")
    cl.write(b"stale/x", b"v2")
    cl._presession.lease_drop(b"stale/x")  # simulate a restarted client
    before = metrics.snapshot()
    cl.write(b"stale/x", b"v3")
    snap = metrics.snapshot()
    assert snap.get("client.piggyback.retry_t", 0) > before.get(
        "client.piggyback.retry_t", 0
    )
    # the decline path must not have touched the TIME round
    assert _delta(_client_rpcs(snap), _client_rpcs(before)).get(
        "time", 0
    ) == 0
    assert cl.read(b"stale/x") == b"v3"
    # an optimistic decline is not equivocation: nobody got revoked
    assert not cl.self_node.revoked


def test_tail_certifies_the_record(cluster):
    """After the tail drains, the write plane holds the record with a
    completed, sufficient collective signature (the wotqs math is
    untouched: suff signers, verified)."""
    cl = cluster.clients[0]
    cl.write(b"cert/x", b"certified")
    cl.drain_tails()
    qa = qm.choose_quorum_for(cl.qs, b"cert/x", qm.AUTH)
    certified = 0
    for srv in cluster.storage_servers:
        raw = srv.storage.read(b"cert/x", 0)
        p = pkt.parse(raw)
        if p.ss is not None and p.ss.completed:
            srv.crypt.collective.verify(
                pkt.tbss(raw), p.ss, qa, srv.crypt.keyring
            )
            certified += 1
    assert certified == len(cluster.storage_servers)


def test_read_before_backfill_resolves_committed_value(cluster):
    """The race the early commit opens: a read lands after the 2f+1
    commit but before the collective back-fill.  The pending record is
    served, wins by responder threshold, and the READER completes the
    certification — the committed value comes back, never a bare
    unbacked one."""
    cl = cluster.clients[0]
    fp.arm(81)
    try:
        # Cut the back-fill entirely: both delivery shapes drop (the
        # coalescer's BATCH_WRITE and the certify-repair WRITE).
        fp.registry.add(
            "transport.send",
            "drop",
            match={"cmd": lambda c: c in ("write", "batch_write")},
            rule_id="bf",
        )
        before = metrics.snapshot().get("client.read.certified", 0)
        cl.write(b"race/x", b"committed")
        cl.drain_tails()
        # Every write-plane copy that exists is still commit-pending,
        # and at least the commit threshold (f+1) of them exist — the
        # wave-1 fan-out wrote those; the rest would have come from the
        # (cut) back-fill.
        pending = 0
        for srv in cluster.storage_servers:
            try:
                raw = srv.storage.read(b"race/x", 0)
            except Exception:
                continue
            p = pkt.parse(raw)
            assert p.ss is not None and not p.ss.completed
            pending += 1
        assert pending >= 2  # f+1 for the 4-node write plane
        assert cl.read(b"race/x") == b"committed"
        assert metrics.snapshot().get("client.read.certified", 0) > before
    finally:
        fp.disarm()
    # With the drop healed, the next read re-certifies and its repair
    # tail upgrades the pending copies to the certified record.
    assert cl.read(b"race/x") == b"committed"
    cl.drain_tails()
    deadline = time.time() + 5
    done = 0
    while time.time() < deadline:
        done = sum(
            1
            for srv in cluster.storage_servers
            if (p := pkt.parse(srv.storage.read(b"race/x", 0))).ss
            is not None
            and p.ss.completed
        )
        if done:
            break
        time.sleep(0.05)
    assert done >= 1


def test_batched_read_resolves_pending_too(cluster):
    """read_many hits the same pending-resolution path."""
    cl = cluster.clients[0]
    fp.arm(82)
    try:
        fp.registry.add(
            "transport.send",
            "drop",
            match={"cmd": lambda c: c in ("write", "batch_write")},
            rule_id="bf2",
        )
        cl.write(b"race/m1", b"mv1")
        cl.write(b"race/m2", b"mv2")
        cl.drain_tails()
        assert cl.read_many([b"race/m1", b"race/m2"]) == [b"mv1", b"mv2"]
    finally:
        fp.disarm()


# -- starved tails surface in the health plane ------------------------------


def test_starved_tail_raises_anomaly():
    """n=5 clique: commit lands at 2f+1 = 3 acks but suff = 4.  Two
    share-withholding clique members (Byzantine-lite: honest persist,
    shareless ack — clean drops beyond f would fail the round outright,
    so starvation is inherently a misbehavior phenomenon) starve the
    tail.  The write still succeeds (that is the point of early
    commit), the counter fires, the fleet collector turns it into an
    anomaly, and a later read certifies the record anyway (helping)."""
    from bftkv_tpu.obs import FleetCollector, LocalSource

    c = start_cluster(5, 1, 4, bits=BITS)
    cl = c.clients[0]

    def shareless(server, cmd, req, peer, sender):
        server._write_sign(req, peer, sender)  # honest admission+persist
        return pkt.serialize_ws_ack(share=b"")  # ... but no share

    try:
        cl.write(b"starve/warm", b"v")
        cl.drain_tails()
        collector = FleetCollector(
            [
                LocalSource("a01", lambda: c.servers[0]),
            ],
            local_metrics=metrics,
        )
        collector.scrape_once()  # baseline for counter deltas
        fp.arm(83)
        try:
            fp.registry.add(
                "server.admission",
                "handle",
                match={
                    "node": lambda n: n in ("a04", "a05"),
                    "cmd": "write_sign",
                },
                fn=shareless,
                rule_id="withhold2",
            )
            before = metrics.snapshot().get("client.tail.starved", 0)
            cl.write(b"starve/x", b"survives")
            cl.drain_tails()
            assert (
                metrics.snapshot().get("client.tail.starved", 0)
                == before + 1
            )
        finally:
            fp.disarm()
        collector.scrape_once()
        kinds = {a["kind"] for a in collector.anomalies()}
        assert "tail_starved" in kinds
        # the read certifies the starved record (misbehavior healed)
        assert cl.read(b"starve/x") == b"survives"
    finally:
        c.stop()


# -- negotiation: old servers keep working ----------------------------------


class LegacyServer(Server):
    """A pre-piggyback server: WRITE_SIGN is an unknown command."""

    _handlers = {
        k: v for k, v in Server._handlers.items() if k != tp.WRITE_SIGN
    }


def test_legacy_quorum_falls_back_to_classic_rounds():
    c = start_cluster(4, 1, 4, bits=BITS, server_cls=LegacyServer)
    cl = c.clients[0]
    try:
        before = metrics.snapshot().get("client.piggyback.fallback", 0)
        cl.write(b"legacy/x", b"old school")
        assert cl.read(b"legacy/x") == b"old school"
        snap = metrics.snapshot()
        assert snap.get("client.piggyback.fallback", 0) > before
        assert cl._legacy_peers  # the quorum is remembered as legacy
        # subsequent writes skip the probe entirely
        rpcs_before = _client_rpcs(metrics.snapshot())
        cl.write(b"legacy/y", b"still old school")
        d = _delta(_client_rpcs(metrics.snapshot()), rpcs_before)
        assert d.get("write_sign", 0) == 0
        assert cl.read(b"legacy/y") == b"still old school"
    finally:
        c.stop()


def test_piggyback_off_env_uses_classic_rounds(monkeypatch):
    from bftkv_tpu.protocol import client as client_mod

    monkeypatch.setattr(client_mod, "_PIGGYBACK", False)
    c = start_cluster(4, 1, 4, bits=BITS)
    cl = c.clients[0]
    try:
        before = _client_rpcs(metrics.snapshot())
        cl.write(b"off/x", b"classic")
        d = _delta(_client_rpcs(metrics.snapshot()), before)
        assert d.get("write_sign", 0) == 0
        assert d.get("time", 0) >= 1 and d.get("sign", 0) >= 1
        assert cl.read(b"off/x") == b"classic"
    finally:
        c.stop()


def test_ws_ack_codec_roundtrip():
    s, share, t = pkt.parse_ws_ack(pkt.serialize_ws_ack(share=b"abc"))
    assert (s, share, t) == (pkt.WS_ACCEPT, b"abc", 0)
    s, share, t = pkt.parse_ws_ack(pkt.serialize_ws_ack(decline_t=42))
    assert (s, share, t) == (pkt.WS_DECLINE_T, b"", 42)
    s, share, t = pkt.parse_ws_ack(pkt.serialize_ws_ack())
    assert (s, share, t) == (pkt.WS_ACCEPT, b"", 0)
    for bad in (b"", b"\x01", b"\x01short", b"\x02xxxxxxxxx"):
        with pytest.raises(Error):
            pkt.parse_ws_ack(bad)
