"""Sampling profiler (bftkv_tpu/obs/profiler): stack folding into
collapsed-flamegraph lines, the memory bounds (stack count + depth),
the disarmed on-demand window, and the off-is-free arming contract."""

from __future__ import annotations

import sys
import threading
import time

import pytest

from bftkv_tpu.obs import profiler


def _parked(evt):
    evt.wait(10)


def _parked_too(evt):
    evt.wait(10)


def _deep(n, evt):
    if n:
        return _deep(n - 1, evt)
    evt.wait(10)


def _spawn(target, *args):
    evt = threading.Event()
    t = threading.Thread(target=target, args=args + (evt,), daemon=True)
    t.start()
    # the helper must be parked inside its wait before we sample
    for _ in range(200):
        frame = sys._current_frames().get(t.ident)
        if frame is not None and "wait" in frame.f_code.co_name:
            break
        time.sleep(0.005)
    return t, evt


def test_sample_once_folds_parked_threads_root_to_leaf():
    t, evt = _spawn(_parked)
    try:
        p = profiler.Profiler()
        assert p.sample_once() >= 1
        out = p.collapsed()
        assert out.startswith("# bftkv profile:")
        line = next(
            l for l in out.splitlines()[1:] if "_parked;" in l
        )
        stack, count = line.rsplit(" ", 1)
        assert int(count) >= 1
        # collapsed format runs root -> leaf: the parked helper's
        # frame precedes the Event.wait frames it called into
        assert stack.index("_parked") < stack.index("wait")
        assert "test_profiler.py:_parked" in stack
    finally:
        evt.set()
        t.join()


def test_max_stacks_bound_folds_overflow():
    t1, e1 = _spawn(_parked)
    t2, e2 = _spawn(_parked_too)
    try:
        p = profiler.Profiler(max_stacks=1)
        p.sample_once()
        with p._lock:
            assert len(p._counts) == 1
            assert p._overflow >= 1  # >= 2 distinct stacks were live
        assert "<overflow>" in p.collapsed()
    finally:
        e1.set()
        e2.set()
        t1.join()
        t2.join()


def test_max_depth_keeps_the_leaf_side():
    t, evt = _spawn(_deep, 60)
    try:
        p = profiler.Profiler(max_depth=5)
        frame = sys._current_frames()[t.ident]
        stack = p._fold(frame)
        # the root side folds into <deep>; the hot leaf survives
        assert stack.startswith("<deep>;")
        assert stack.count(";") == 5
        assert "wait" in stack.rsplit(";", 2)[-1] or "_deep" in stack
    finally:
        evt.set()
        t.join()


def test_disarmed_is_off_and_profile_for_still_works(monkeypatch):
    monkeypatch.delenv("BFTKV_PROFILE", raising=False)
    assert profiler.enabled() is False
    # off = no thread, no global sampler at all
    assert profiler.ensure_started() is None
    # ...but a demand window still answers, via a TEMPORARY sampler
    out = profiler.profile_for(0.05)
    assert out.startswith("# bftkv profile:")
    # the window is what the flight recorder snapshots into bundles
    assert profiler.last() == out


def test_armed_starts_one_continuous_sampler(monkeypatch):
    monkeypatch.setenv("BFTKV_PROFILE", "1")
    saved = profiler._global
    profiler._global = None
    try:
        p = profiler.ensure_started()
        assert p is not None and p.running()
        assert profiler.ensure_started() is p  # started once
        t, evt = _spawn(_parked)
        try:
            deadline = time.time() + 5
            while time.time() < deadline:
                with p._lock:
                    if any("_parked" in s for s in p._counts):
                        break
                time.sleep(0.02)
            else:
                pytest.fail("continuous sampler never saw the "
                            "parked thread")
        finally:
            evt.set()
            t.join()
    finally:
        if profiler._global is not None:
            profiler._global.stop()
        profiler._global = saved


def test_armed_window_overhead_parity_smoke(monkeypatch):
    """The 67 Hz comb must be invisible to foreground work: a tight
    CPU loop with the sampler running stays near parity with the same
    loop alone.  Median-of-5 with a generous bound (the CI perf smoke
    holds the real 5% bar on the full write path, where the loop body
    dwarfs the sampler's per-tick cost)."""
    def cycle(n=200_000):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n):
            acc += i
        return time.perf_counter() - t0

    cycle()  # warm
    p = profiler.Profiler(hz=67)
    ratios = []
    for _ in range(5):
        off = cycle()
        p.start()
        try:
            on = cycle()
        finally:
            p.stop()
        ratios.append(on / max(off, 1e-9))
    ratios.sort()
    assert ratios[2] < 1.5, ratios
