"""Batching dispatcher: cross-thread coalescing, correctness, metrics."""

from __future__ import annotations

import threading

import pytest

from bftkv_tpu.crypto import rsa
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.ops import dispatch


@pytest.fixture(scope="module")
def keypair():
    key = rsa.generate(2048)
    return key, key.public


def _items(key, pub, n, good=True):
    out = []
    for i in range(n):
        msg = b"msg-%d" % i
        sig = rsa.sign(msg, key)
        if not good:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        out.append((msg, sig, pub))
    return out


def test_dispatcher_verifies_correctly(keypair):
    key, pub = keypair
    d = dispatch.VerifyDispatcher(max_batch=64, max_wait=0.01).start()
    try:
        ok = d.verify(_items(key, pub, 5))
        assert ok.all()
        bad = d.verify(_items(key, pub, 3, good=False))
        assert not bad.any()
    finally:
        d.stop()


def test_dispatcher_coalesces_across_threads(keypair):
    key, pub = keypair
    metrics.reset()
    d = dispatch.VerifyDispatcher(
        max_batch=4096, max_wait=0.05, calibrate=False
    ).start()
    results = {}
    try:
        def worker(i):
            results[i] = d.verify(_items(key, pub, 4))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r.all() for r in results.values())
        snap = metrics.snapshot()
        # 8 threads × 4 items coalesced into far fewer flushes
        assert snap["dispatch.verifies"] == 32
        assert snap["dispatch.flushes"] < 8
        assert snap["dispatch.batch.sum"] / snap["dispatch.batch.count"] > 4
    finally:
        d.stop()
        metrics.reset()


def test_install_routes_collective_verify(keypair):
    """CollectiveSignature.verify goes through the installed dispatcher."""
    from bftkv_tpu.crypto import cert as certmod
    from bftkv_tpu.crypto.signature import CollectiveSignature, Signer

    key, pub = keypair
    cert = certmod.Certificate(n=key.n, e=key.e, name="d1", uid="d1")
    signer = Signer(key, cert)

    class _Q:
        def is_sufficient(self, nodes):
            return len(nodes) >= 1

    cs = CollectiveSignature()
    share = cs.sign(signer, b"payload")
    metrics.reset()
    dispatch.install(
        dispatch.VerifyDispatcher(max_batch=8, max_wait=0.005, calibrate=False)
    )
    try:
        # use_cache=False: the share was seeded into the verify memo
        # at issue time, and a memo hit would (correctly) skip the
        # dispatcher this test exists to observe.
        cs.verify(b"payload", share, _Q(), None, use_cache=False)
        assert metrics.snapshot().get("dispatch.verifies", 0) >= 1
    finally:
        dispatch.uninstall()
        metrics.reset()


def test_stopped_dispatcher_falls_back(keypair):
    key, pub = keypair
    d = dispatch.VerifyDispatcher()
    # not started: verify() still works synchronously
    assert d.verify(_items(key, pub, 2)).all()
    assert d.verify([]).shape == (0,)


def test_sign_dispatcher_mixed_rsa_ec_batch(keypair):
    """One flush may carry RSA and EC items interleaved (ADVICE r4 #3);
    every signature must come back in submission order, each verified
    by its own algorithm."""
    from bftkv_tpu.crypto import ecdsa

    key, pub = keypair
    ec_key = ecdsa.generate()
    d = dispatch.SignDispatcher(max_batch=64, max_wait=0.01).start()
    try:
        items = [
            (b"rsa-0", key),
            (b"ec-0", ec_key),
            (b"rsa-1", key),
            (b"ec-1", ec_key),
        ]
        sigs = d.submit(items)
        assert len(sigs) == 4
        assert rsa.verify_host(b"rsa-0", sigs[0], pub)
        assert ecdsa.verify_host(b"ec-0", sigs[1], ec_key.public)
        assert rsa.verify_host(b"rsa-1", sigs[2], pub)
        assert ecdsa.verify_host(b"ec-1", sigs[3], ec_key.public)
    finally:
        d.stop()


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_ec_signers_coalesce_across_threads():
    """Concurrent EC writers' batches merge into shared flushes, the
    same coalescing the RSA path has always had (ADVICE r4 #3)."""
    from bftkv_tpu.crypto import ecdsa

    ec_key = ecdsa.generate()
    metrics.reset()
    d = dispatch.SignDispatcher(max_batch=4096, max_wait=0.05).start()
    results = {}
    try:
        def worker(i):
            msgs = [b"t%d-m%d" % (i, j) for j in range(4)]
            results[i] = (msgs, d.submit([(m, ec_key) for m in msgs]))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for msgs, sigs in results.values():
            for m, s in zip(msgs, sigs):
                assert ecdsa.verify_host(m, s, ec_key.public)
        snap = metrics.snapshot()
        assert snap["signdispatch.items"] == 32
        assert snap["signdispatch.flushes"] < 8
    finally:
        d.stop()
        metrics.reset()


def test_signer_issue_many_routes_ec_through_dispatcher():
    """Signer.issue_many submits EC work to the installed dispatcher
    instead of signing inline in the caller's thread."""
    from bftkv_tpu.crypto import cert as certmod
    from bftkv_tpu.crypto import ecdsa
    from bftkv_tpu.crypto.signature import Signer, verify_with_certificate

    ec_key = ecdsa.generate()
    cert = certmod.make_ec_certificate(ec_key.public, name="ec-d", uid="ec-d")
    metrics.reset()
    dispatch.install_signer(
        dispatch.SignDispatcher(max_batch=8, max_wait=0.005, calibrate=False)
    )
    try:
        pkts = Signer(ec_key, cert).issue_many([b"a", b"b"])
        for tbs, pkt in zip([b"a", b"b"], pkts):
            verify_with_certificate(tbs, pkt, cert)
        assert metrics.snapshot().get("signdispatch.items", 0) >= 2
    finally:
        dispatch.uninstall_signer()
        metrics.reset()


def test_pipelined_flushes_interleave_and_stay_correct(keypair):
    """With pipeline=2 a flush waiting on the device must not block the
    next flush from launching (r5: overlap hides the ~100 ms tunneled
    launch RTT behind host assembly).  Deterministic: flush 1 BLOCKS
    until flush 2 has entered _run_batch — if flushes were serial this
    would deadlock (and the waits would time out and fail)."""
    key, pub = keypair
    d = dispatch.VerifyDispatcher(
        max_batch=8, max_wait=0.5, pipeline=2, calibrate=False
    )
    inner = d._run_batch
    first_in = threading.Event()
    second_in = threading.Event()
    n_calls = []
    lock = threading.Lock()

    def run(items):
        with lock:
            n_calls.append(len(items))
            rank = len(n_calls)
        if rank == 1:
            first_in.set()
            assert second_in.wait(timeout=20), (
                "second flush never launched while the first was "
                "in flight: flushes are serial"
            )
        else:
            second_in.set()
        return inner(items)

    d._run_batch = run
    d.start()
    try:
        results = {}
        # 8 items == max_batch: each submit drains as its own immediate
        # flush (no timer involved, no cross-submit coalescing race).
        t1 = threading.Thread(
            target=lambda: results.setdefault(1, d.verify(_items(key, pub, 8)))
        )
        t1.start()
        assert first_in.wait(timeout=10)
        t2 = threading.Thread(
            target=lambda: results.setdefault(2, d.verify(_items(key, pub, 8)))
        )
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not t1.is_alive() and not t2.is_alive()
        assert results[1].all() and results[2].all()
        assert len(n_calls) == 2 and all(n == 8 for n in n_calls)
    finally:
        d.stop()


def test_stop_drains_inflight_flushes(keypair):
    """stop() must not return while a pipelined flush worker still owes
    a caller its result."""
    key, pub = keypair
    d = dispatch.VerifyDispatcher(max_batch=4, max_wait=0.001, pipeline=2)
    inner = d._run_batch
    started = threading.Event()

    def slow_run(items):
        started.set()
        import time

        time.sleep(0.2)
        return inner(items)

    d._run_batch = slow_run
    d.start()
    try:
        got = {}
        t = threading.Thread(
            target=lambda: got.setdefault("ok", d.verify(_items(key, pub, 4)))
        )
        t.start()
        started.wait(timeout=5)
    finally:
        d.stop()
    t.join(timeout=5)
    assert not t.is_alive()
    assert got["ok"].all()


def test_pipeline_one_restores_serial_flushing(keypair):
    key, pub = keypair
    d = dispatch.VerifyDispatcher(
        max_batch=4, max_wait=0.001, pipeline=1, calibrate=False
    )
    peak, inflight = [], []
    gate = threading.Lock()
    inner = d._run_batch

    def counting_run(items):
        with gate:
            inflight.append(1)
            peak.append(len(inflight))
        try:
            return inner(items)
        finally:
            with gate:
                inflight.pop()

    d._run_batch = counting_run
    d.start()
    try:
        threads = [
            threading.Thread(target=lambda: d.verify(_items(key, pub, 4)))
            for _ in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert max(peak) == 1, peak
    finally:
        d.stop()
