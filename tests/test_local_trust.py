"""Local-only trust edges (the server_trust_rw fix, round 4).

The operator extension "servers trust rw nodes so the daemon's own
client-API reads have a read quorum" used to be implemented as real
certificate signatures — which leaked to every peer through join
responses, formed bidirectional a↔rw edges in client graphs, and
silently broke post-join writes (found by the round-4 verification
drive).  The edges are now in-memory graph state that never
serializes; these tests pin both halves: the capability works, and a
client that joins afterward still has working quorums.
"""

from __future__ import annotations

from bftkv_tpu import topology
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import Server
from bftkv_tpu.storage.memkv import MemStorage
from bftkv_tpu.transport.loopback import LoopbackNet, TrLoopback


def _build(server_trust_rw: bool):
    uni = topology.build_universe(
        4, 1, 4, bits=1024, server_trust_rw=server_trust_rw
    )
    net = LoopbackNet()
    servers = []
    for ident in uni.servers + uni.storage_nodes:
        graph, crypt, qs = topology.make_node(
            ident, uni.view_of(ident), local_trust=uni.local_trust_of(ident)
        )
        srv = Server(graph, qs, TrLoopback(crypt, net), crypt, MemStorage())
        srv.start()
        servers.append(srv)
    u = uni.users[0]
    graph, crypt, qs = topology.make_node(u, uni.view_of(u))
    client = Client(graph, qs, TrLoopback(crypt, net), crypt)
    return uni, servers, client


def test_local_edges_never_serialize():
    uni, servers, client = _build(server_trust_rw=True)
    try:
        rw_ids = {s.id for s in uni.storage_nodes}
        for srv in servers[:4]:  # the a* quorum servers
            # The local edges exist in the server's own graph…
            sv = srv.self_node.vertices[srv.self_node.get_self_id()]
            assert rw_ids <= set(sv.edges), "local trust edges missing"
            # …but never in the certificates it would serialize to a
            # joining peer: no rw id appears in any a-cert's signers.
            from bftkv_tpu.crypto import cert as certmod

            for c in certmod.parse(srv.self_node.serialize_nodes()):
                assert not (set(c.signers()) & rw_ids) or c.id in rw_ids
    finally:
        for s in servers:
            s.tr.stop()


def test_write_survives_join_with_server_trust_rw():
    # The regression: joining used to import the leaked a→rw edges and
    # break the client's quorums ("insufficient number of responses").
    uni, servers, client = _build(server_trust_rw=True)
    try:
        client.write(b"lt/pre", b"before-join")
        assert client.read(b"lt/pre") == b"before-join"
        client.joining()
        client.write(b"lt/post", b"after-join")
        assert client.read(b"lt/post") == b"after-join"
        assert client.write_many(
            [(b"lt/b/%d" % i, b"v%d" % i) for i in range(4)]
        ) == [None] * 4
    finally:
        for s in servers:
            s.tr.stop()


def test_daemon_reads_have_quorum_with_local_trust(tmp_path):
    # The capability the flag exists for: a server's own client can
    # READ (rw nodes complete its read quorum) — via the load_home
    # localtrust file path the daemon uses.
    uni = topology.build_universe(4, 1, 4, bits=1024, server_trust_rw=True)
    for ident in uni.all:
        topology.save_home(
            str(tmp_path / ident.name), ident, uni.view_of(ident),
            local_trust=uni.local_trust_of(ident),
        )
    net = LoopbackNet()
    servers = []
    triples = {}
    for ident in uni.servers + uni.storage_nodes:
        graph, crypt, qs = topology.load_home(str(tmp_path / ident.name))
        triples[ident.name] = (graph, crypt, qs)
        srv = Server(graph, qs, TrLoopback(crypt, net), crypt, MemStorage())
        srv.start()
        servers.append(srv)
    try:
        # A user writes a value…
        u = uni.users[0]
        g, crypt, qs = topology.load_home(str(tmp_path / u.name))
        cl = Client(g, qs, TrLoopback(crypt, net), crypt)
        cl.write(b"lt/d", b"daemon-visible")
        cl.drain_tails()  # certified copies before the daemon-side read
        # …and the a01 daemon's own client (its graph carries the
        # localtrust edges) can read it back.
        g1, c1, q1 = triples["a01"]
        own = Client(g1, q1, servers[0].tr, c1)
        assert own.read(b"lt/d") == b"daemon-visible"
    finally:
        for s in servers:
            s.tr.stop()
