"""Epoched route table (DESIGN.md §15): RouteTable wire format +
signing, WotQS install/ownership/dual-window semantics at the graph
level, and the end-to-end stale-route decline → in-round reroute loop
on a 2-shard loopback cluster."""

import pytest

from bftkv_tpu import quorum as q
from bftkv_tpu.errors import (
    ERR_WRONG_SHARD,
    parse_wrong_shard,
    wrong_shard_error,
)
from bftkv_tpu.quorum.wotqs import (
    ROUTE_BUCKETS,
    RouteTable,
    WotQS,
    route_bucket,
)
from tests.test_shard_quorum import build, mk_shard_universe


def mk_qs(universe, who="u01"):
    return WotQS(build(universe, who))


def flip_table(qs, moves: dict, *, dual=True, epoch=None, retiring=()):
    owner = qs.effective_route()
    table = list(owner)
    dual_map = {}
    for b, dest in moves.items():
        if dual:
            dual_map[b] = table[b]
        table[b] = dest
    return RouteTable(
        epoch=(qs.route_epoch() + 1) if epoch is None else epoch,
        cliques=qs.route_cliques(),
        table=table,
        dual=dual_map,
        retiring=retiring,
    )


# -- wire format ----------------------------------------------------------


def test_route_table_roundtrip(universe):
    qs = mk_qs(universe)
    rt = flip_table(qs, {3: 1, 7: 0}, retiring={1})
    rt2 = RouteTable.parse(rt.serialize())
    assert rt2.epoch == rt.epoch
    assert rt2.cliques == rt.cliques
    assert rt2.table == rt.table
    assert rt2.dual == rt.dual
    assert rt2.retiring == rt.retiring


def test_route_table_sign_verify():
    from bftkv_tpu import topology
    from bftkv_tpu.crypto.keyring import Keyring

    ident = topology.new_identity("ap01", bits=1024)
    ring = Keyring()
    ring.register([ident.cert])
    rt = RouteTable(
        epoch=2,
        cliques=(1, 2),
        table=[0] * ROUTE_BUCKETS,
        dual={5: 1},
    )
    rt.sign(ident.key, ident.cert)
    assert rt.verify(ring)
    rt2 = RouteTable.parse(rt.serialize())
    assert rt2.verify(ring)
    rt2.epoch = 3  # tamper
    assert not rt2.verify(ring)
    # unknown issuer
    assert not RouteTable.parse(rt.serialize()).verify(Keyring())


@pytest.fixture()
def universe():
    return mk_shard_universe()


# -- install semantics ----------------------------------------------------


def test_install_monotonic(universe):
    qs = mk_qs(universe)
    assert qs.route_epoch() == 0
    rt1 = flip_table(qs, {})
    assert qs.install_route_table(rt1)
    assert qs.route_epoch() == 1
    # re-install of the current epoch is an idempotent True
    assert qs.install_route_table(rt1)
    # a stale epoch can never roll routing back
    stale = flip_table(qs, {}, epoch=0)
    assert not qs.install_route_table(stale)
    assert qs.route_epoch() == 1
    rt2 = flip_table(qs, {})
    assert qs.install_route_table(rt2)
    assert qs.route_epoch() == 2


def test_signed_install_requires_valid_signature(universe):
    from bftkv_tpu import topology
    from bftkv_tpu.crypto.keyring import Keyring

    ident = topology.new_identity("ap01", bits=1024)
    ring = Keyring()
    ring.register([ident.cert])
    qs = mk_qs(universe)
    rt = flip_table(qs, {})
    assert not qs.install_route_table(rt, ring)  # unsigned
    rt.sign(ident.key, ident.cert)
    assert qs.install_route_table(rt, ring)


# -- ownership + dual window ----------------------------------------------


def moving_bucket(qs, owner_idx):
    for b in range(ROUTE_BUCKETS):
        if qs.effective_route()[b] == owner_idx:
            return b
    raise AssertionError("no bucket owned by shard")


def var_in_bucket(b):
    i = 0
    while True:
        x = b"ep/%d" % i
        if route_bucket(x) == b:
            return x
        i += 1


def test_dual_window_roles(universe):
    qs_a = mk_qs(universe, "a01")
    qs_b = mk_qs(universe, "b01")
    a_idx, b_idx = qs_a.my_shard(), qs_b.my_shard()
    mb = moving_bucket(qs_a, a_idx)
    x = var_in_bucket(mb)
    assert qs_a.route_role(x) == "owner"
    assert qs_b.route_role(x) == "foreign"
    # flip mb from a's shard to b's with the dual window open
    for qs in (qs_a, qs_b):
        assert qs.install_route_table(
            flip_table(qs, {mb: b_idx}, dual=True)
        )
    assert qs_a.route_role(x) == "dual"
    assert qs_b.route_role(x) == "owner"
    assert qs_a.owns(x) and qs_b.owns(x)  # both inside the window
    assert qs_a.signs_for(x) and qs_b.signs_for(x)
    assert mb in qs_a.owned_buckets() and mb in qs_b.owned_buckets()
    assert qs_b.dual_pull_shards() == {a_idx}
    assert qs_a.dual_pull_shards() == {b_idx}
    assert len(qs_b.alt_quorums_for(x, q.AUTH)) == 1
    # finalize: window closes, old owner goes inert
    for qs in (qs_a, qs_b):
        assert qs.install_route_table(
            flip_table(qs, {mb: b_idx}, dual=False)
        )
    assert qs_a.route_role(x) == "foreign"
    assert not qs_a.owns(x) and qs_b.owns(x)
    assert not qs_a.signs_for(x)
    assert qs_a.alt_quorums_for(x, q.AUTH) == []
    assert mb not in qs_a.owned_buckets()


def test_stale_routed_and_hint(universe):
    qs_a = mk_qs(universe, "a01")
    a_idx = qs_a.my_shard()
    b_idx = 1 - a_idx
    mb = moving_bucket(qs_a, a_idx)
    x = var_in_bucket(mb)
    assert not qs_a.stale_routed(x)
    assert qs_a.install_route_table(
        flip_table(qs_a, {mb: b_idx}, dual=False)
    )
    # an epoch-0 client would still send x here: that is a stale route
    assert qs_a.stale_routed(x)
    epoch, owner = qs_a.route_hint(x)
    assert epoch == 1 and owner == b_idx


def test_note_route_hint_only_newer(universe):
    qs = mk_qs(universe)
    x = b"hint/x"
    b = route_bucket(x)
    owner = qs.effective_route()[b]
    other = 1 - owner
    assert not qs.note_route_hint(x, 0, other)  # not newer than epoch 0
    assert qs.note_route_hint(x, 3, other)
    assert qs.shard_of(x) == other  # hint steers ROUTING...
    assert qs.effective_route()[b] == owner  # ...but not admission
    # a newer installed table supersedes the hint
    assert qs.install_route_table(flip_table(qs, {}, epoch=3))
    assert qs.shard_of(x) == owner


def test_verify_view_quorum_suff(universe):
    """A clique server's weight into a FOREIGN clique is zero, so the
    low-weight rule zeroes suff — unless the verify view is requested
    (migration admission judges the old owner's signatures there)."""
    qs_a = mk_qs(universe, "a01")
    b_idx = 1 - qs_a.my_shard()
    collect = qs_a.quorum_for_shard(b_idx, q.AUTH)
    judge = qs_a.quorum_for_shard(b_idx, q.AUTH, verify_view=True)
    assert all(s == 0 for s in collect.bounds()["suff"])
    assert any(s > 0 for s in judge.bounds()["suff"])


def test_seat_info_reports_epoch(universe):
    qs = mk_qs(universe, "a01")
    assert qs.seat_info()["epoch"] == 0
    mb = moving_bucket(qs, qs.my_shard())
    assert qs.install_route_table(
        flip_table(qs, {mb: 1 - qs.my_shard()}, dual=True)
    )
    info = qs.seat_info()
    assert info["epoch"] == 1
    assert info["dual_buckets"] == 1


# -- wrong-shard decline format -------------------------------------------


def test_wrong_shard_error_forms():
    bare = wrong_shard_error()
    assert bare is ERR_WRONG_SHARD
    assert parse_wrong_shard(bare) == (None, None)
    hinted = wrong_shard_error(4, 2)
    assert parse_wrong_shard(hinted) == (4, 2)
    assert parse_wrong_shard(hinted()) == (4, 2)  # instance too
    assert parse_wrong_shard("wrong shard epoch=9 owner=0") == (9, 0)
    assert parse_wrong_shard("bad timestamp") is None
    # interned round trip through the wire form
    from bftkv_tpu.errors import error_from_string

    assert parse_wrong_shard(error_from_string(hinted.message)) == (4, 2)


# -- end to end: decline → reroute on a loopback cluster -------------------


def test_stale_client_reroutes_in_round():
    from bftkv_tpu.metrics import registry as metrics
    from tests.cluster_utils import start_cluster

    cluster = start_cluster(4, 2, 4, bits=1024, n_shards=2)
    try:
        fresh, stale = cluster.clients
        qs = fresh.qs
        x = None
        i = 0
        while x is None:
            c = b"flap/%d" % i
            i += 1
            if qs.shard_of(c) == 0:
                x = c
        stale.write(x, b"v0")
        stale.drain_tails()
        # abrupt flip to shard 1 delivered to everyone EXCEPT `stale`
        rt = None
        for principal in cluster.all_servers + [fresh]:
            pq = principal.qs
            if rt is None:
                owner = pq.effective_route()
                table = list(owner)
                table[route_bucket(x)] = 1
                rt = RouteTable(
                    1, pq.route_cliques(), table, {}, set()
                )
            assert pq.install_route_table(rt)
        metrics.reset()
        stale.write(x, b"v1")  # declines at the old owner, re-routes
        stale.drain_tails()
        snap = metrics.snapshot()
        assert (
            sum(
                v
                for k, v in snap.items()
                if k.startswith("server.epoch_stale")
            )
            > 0
        )
        assert snap.get("client.route.rerouted", 0) > 0
        assert stale.qs.shard_of(x) == 1  # hint adopted
        assert fresh.read(x) == b"v1"
    finally:
        cluster.stop()
