"""Verified-signature memo (crypto/vcache.py): safety properties.

The memo may only ever change WHERE a successful verification is
computed, never WHAT verifies: the full key triple must byte-match
(flipping any of signer key / tbs / sig misses), revocation evicts,
negative results are never cached, TPA paths bypass it, and a warm
cache must not let a tampered signature through.
"""

from __future__ import annotations

import pytest

from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import rsa, vcache
from bftkv_tpu.crypto.signature import (
    CollectiveSignature,
    Signer,
    verify_with_certificate,
)
from bftkv_tpu.errors import ERR_INVALID_SIGNATURE
from bftkv_tpu.metrics import registry as metrics

KEY_BITS = 1024  # keygen speed; cache keys are digest-based either way


@pytest.fixture(scope="module")
def identity():
    key = rsa.generate(KEY_BITS)
    cert = certmod.Certificate(n=key.n, e=key.e, name="vc", uid="vc")
    return key, cert


@pytest.fixture(autouse=True)
def fresh_cache():
    vcache.reset()
    metrics.reset()
    yield
    vcache.reset()
    metrics.reset()


class _Q:
    def is_sufficient(self, nodes):
        return len(nodes) >= 1


def _share(key, cert, tbs: bytes):
    return CollectiveSignature().sign(Signer(key, cert), tbs)


def test_hit_requires_exact_triple(identity):
    key, cert = identity
    tbs = b"triple-match"
    sig = rsa.sign(tbs, key)
    vcache.put(cert, tbs, sig)
    assert vcache.get(cert, tbs, sig)

    # Flip one byte of the tbs -> miss.
    assert not vcache.get(cert, b"Triple-match", sig)
    # Flip one byte of the sig -> miss.
    tampered = sig[:-1] + bytes([sig[-1] ^ 1])
    assert not vcache.get(cert, tbs, tampered)
    # Different signer key material (same everything else) -> miss.
    other = rsa.generate(KEY_BITS)
    other_cert = certmod.Certificate(n=other.n, e=other.e, name="o", uid="o")
    assert not vcache.get(other_cert, tbs, sig)


def test_same_id_different_key_material_misses(identity):
    """The fingerprint binds the public key bytes, not just the id: a
    forged cert claiming an honest id but different key material must
    not share the honest signer's entries."""
    key, cert = identity
    tbs = b"id-collision"
    sig = rsa.sign(tbs, key)
    vcache.put(cert, tbs, sig)

    other = rsa.generate(KEY_BITS)
    forged = certmod.Certificate(n=other.n, e=other.e, name="f", uid="f")
    forged.__dict__["_id"] = cert.id  # forced id collision
    assert forged.id == cert.id
    assert not vcache.get(forged, tbs, sig)


def test_revocation_evicts(identity):
    key, cert = identity
    for i in range(3):
        tbs = b"rev-%d" % i
        vcache.put(cert, tbs, rsa.sign(tbs, key))
    assert vcache.get(cert, b"rev-0", rsa.sign(b"rev-0", key))
    vcache.invalidate_signer(cert.id)
    assert len(vcache.cache) == 0
    assert not vcache.get(cert, b"rev-1", rsa.sign(b"rev-1", key))


def test_negative_results_never_cached(identity):
    key, cert = identity
    tbs = b"negative"
    share = _share(key, cert, tbs)
    good = share.data
    # Tamper the signature bytes inside the entry encoding.
    share.data = good[:-1] + bytes([good[-1] ^ 1])
    before = len(vcache.cache)
    with pytest.raises(ERR_INVALID_SIGNATURE):
        verify_with_certificate(tbs, share, cert, use_cache=True)
    assert len(vcache.cache) == before, "a failed verify was memoized"
    # The honest bytes still verify (and only THEY get memoized).
    share.data = good
    verify_with_certificate(tbs, share, cert)


def test_warm_cache_cannot_mask_tampering(identity):
    """After a successful (memoized) verify, flipping any byte must
    still be rejected — the memo key covers the full triple."""
    key, cert = identity
    tbs = b"no-masking"
    share = _share(key, cert, tbs)
    verify_with_certificate(tbs, share, cert)  # memoizes
    good = share.data
    share.data = good[:-1] + bytes([good[-1] ^ 1])
    with pytest.raises(ERR_INVALID_SIGNATURE):
        verify_with_certificate(tbs, share, cert)
    share.data = good
    with pytest.raises(ERR_INVALID_SIGNATURE):
        verify_with_certificate(b"other-tbs", share, cert)


def test_use_cache_false_bypasses_entirely(identity):
    """The TPA paths pass use_cache=False: no consultation, no
    insertion — the hit/miss series must stay silent."""
    key, cert = identity
    tbs = b"tpa-bypass"
    share = _share(key, cert, tbs)
    share_data_certless = share
    vcache.reset()
    metrics.reset()
    cs = CollectiveSignature()

    class Ring:
        def get(self, sid):
            return cert if sid == cert.id else None

    cs.verify(tbs, share_data_certless, _Q(), Ring(), use_cache=False)
    snap = metrics.snapshot()
    assert snap.get("verify.cache.hits", 0) == 0
    assert snap.get("verify.cache.misses", 0) == 0
    assert len(vcache.cache) == 0


def test_seeding_from_own_signature(identity):
    """A signature issued by this process verifies from the memo
    without recomputing the math (sign-then-verify correctness)."""
    key, cert = identity
    signer = Signer(key, cert)
    pkt = signer.issue(b"seeded")
    snap = metrics.snapshot()
    assert snap.get("verify.cache.seeded", 0) >= 1

    calls = []
    orig = certmod.verify_detached

    def counting(tbs, sig, c):
        calls.append(tbs)
        return orig(tbs, sig, c)

    certmod.verify_detached = counting
    try:
        verify_with_certificate(b"seeded", pkt, cert)
    finally:
        certmod.verify_detached = orig
    assert calls == [], "seeded verify recomputed the math"


def test_collective_verify_memoizes_and_rechecks_quorum(identity):
    """verify_many caches the math but recomputes sufficiency: the same
    ss must fail against a stricter quorum even with a warm cache."""
    key, cert = identity
    tbs = b"quorum-recheck"
    share = _share(key, cert, tbs)
    cs = CollectiveSignature()

    class Ring:
        def get(self, sid):
            return cert if sid == cert.id else None

    cs.verify(tbs, share, _Q(), Ring())  # memoizes the entry

    class Stricter:
        def is_sufficient(self, nodes):
            return len(nodes) >= 2

    with pytest.raises(Exception):
        cs.verify(tbs, share, Stricter(), Ring())
