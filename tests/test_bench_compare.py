"""tools/bench_compare.py: the perf-regression gate over bench records."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)
from bench_compare import compare, extract_sections, main  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def driver_record(sections):
    return {"n": 5, "cmd": "python bench.py", "rc": 0,
            "parsed": {"metric": "x", "value": 1, "unit": "u",
                       "extra": {"sections": sections}}}


def detail_record(sections):
    return {"metric": "x", "value": 1, "unit": "u",
            "extra": {"sections": sections}}


def test_extracts_both_formats():
    d = extract_sections(driver_record({"cluster_4": ["cpu", 7.5],
                                        "rns_kernel": "skip"}))
    assert d["cluster_4"] == ("cpu", 7.5, None, None, None, None)
    assert d["rns_kernel"] == ("skip", None, None, None, None, None)
    d = extract_sections(detail_record({
        "cluster_4": {"backend": "cpu", "writes_per_sec": 18.6,
                      "write_p50_s": 0.42},
        "cluster_shards": {"backend": "cpu", "writes_per_sec": 55.0},
        "kernel": {"backend": "tpu", "rsa2048_verifies_per_sec": 5e5},
        "bad": {"error": "boom"},
    }))
    assert d["cluster_4"] == ("cpu", 18.6, 0.42, None, None, None)
    assert d["cluster_shards"] == ("cpu", 55.0, None, None, None, None)
    assert d["kernel"][1] == 5e5
    assert d["bad"] == ("err", None, None, None, None, None)
    # three-element compact form (driver records after the round collapse)
    d = extract_sections(driver_record({"cluster_4": ["cpu", 7.5, 0.3]}))
    assert d["cluster_4"] == ("cpu", 7.5, 0.3, None, None, None)
    # four-element compact form: the gray section's slowdown ratio
    d = extract_sections(
        driver_record({"cluster_4_gray": ["cpu", 20.0, 0.1, 1.8]})
    )
    assert d["cluster_4_gray"] == ("cpu", 20.0, 0.1, 1.8, None, None)
    d = extract_sections(detail_record({
        "cluster_4_gray": {"backend": "cpu", "writes_per_sec": 20.0,
                           "write_p50_s": 0.1,
                           "gray_slowdown_hedged": 1.7},
    }))
    assert d["cluster_4_gray"] == ("cpu", 20.0, 0.1, 1.7, None, None)
    # five-element compact form: phase_budget shares ride 5th (gray
    # slot null when the section has no gray axis)
    d = extract_sections(driver_record({
        "cluster_4": ["cpu", 60.0, 0.2, None, {"rpc": 0.6, "server": 0.3}],
    }))
    assert d["cluster_4"] == (
        "cpu", 60.0, 0.2, None, {"rpc": 0.6, "server": 0.3}, None
    )
    d = extract_sections(detail_record({
        "cluster_4": {"backend": "cpu", "writes_per_sec": 60.0,
                      "write_p50_s": 0.2,
                      "phase_budget": {"rpc": 0.6}},
    }))
    assert d["cluster_4"][4] == {"rpc": 0.6}
    # six-element compact form: the r11 device-plane occupancy axis
    d = extract_sections(driver_record({
        "cluster_sidecar": ["cpu/1", 590.0, None, None, None, 1024.0],
    }))
    assert d["cluster_sidecar"] == ("cpu/1", 590.0, None, None, None, 1024.0)
    d = extract_sections(detail_record({
        "cluster_sidecar": {
            "backend": "cpu/1",
            "sidecar_ops_per_sec": 590.0,
            "megabatch_occupancy_items_per_launch": 1024.0,
        },
    }))
    assert d["cluster_sidecar"][5] == 1024.0


def test_occupancy_axis_reported_not_gated():
    """The r11 occupancy axis informs the trajectory but never gates:
    a collapse from 1024 to 2 items/launch is printed, not failed."""
    old = driver_record(
        {"cluster_sidecar": ["cpu/1", 590.0, None, None, None, 1024.0]}
    )
    new = driver_record(
        {"cluster_sidecar": ["cpu/1", 580.0, None, None, None, 2.0]}
    )
    lines, regressions, compared = compare(old, new)
    assert regressions == [] and compared == 1
    assert any(
        "occupancy" in ln and "report-only" in ln for ln in lines
    )
    # one-sided (old record predates the axis) still reports
    old2 = driver_record({"cluster_sidecar": ["cpu/1", 590.0]})
    lines, regressions, _ = compare(old2, new)
    assert regressions == []
    assert any("occupancy" in ln for ln in lines)


def test_gray_slowdown_gated():
    """cluster_4_gray left REPORT_ONLY: throughput gates like any
    section, and the hedged slowdown is held under the absolute 2x
    acceptance bound on the NEW record."""
    old = driver_record({"cluster_4_gray": ["cpu", 20.0, 0.1, 1.5]})
    ok = driver_record({"cluster_4_gray": ["cpu", 21.0, 0.1, 1.9]})
    bad = driver_record({"cluster_4_gray": ["cpu", 21.0, 0.1, 2.4]})
    _lines, regressions, compared = compare(old, ok)
    assert regressions == [] and compared == 1
    _lines, regressions, _ = compare(old, bad)
    assert regressions == ["cluster_4_gray (gray_slowdown)"]
    # an old record without the ratio still gates the new one
    old2 = driver_record({"cluster_4_gray": ["cpu", 20.0, 0.1]})
    _lines, regressions, _ = compare(old2, bad)
    assert regressions == ["cluster_4_gray (gray_slowdown)"]


def test_gray_p50_ratio_reported_not_gated():
    """The gray section's p50 round-ratio is weather on 1-core boxes
    (hedge-delay scheduling: same-code spread 0.119-0.203 s); its
    latency contract is the ABSOLUTE 2x hedge bound, which still
    gates.  A 1.7x p50 move alone must not fail the round."""
    old = driver_record({"cluster_4_gray": ["cpu/1", 20.0, 0.118, 1.5]})
    new = driver_record({"cluster_4_gray": ["cpu/1", 21.0, 0.203, 1.6]})
    lines, regressions, compared = compare(old, new)
    assert regressions == [] and compared == 1
    assert any("p50" in ln and "report-only" in ln for ln in lines)
    # the absolute bound still fires regardless
    bad = driver_record({"cluster_4_gray": ["cpu/1", 21.0, 0.3, 2.4]})
    _lines, regressions, _ = compare(old, bad)
    assert regressions == ["cluster_4_gray (gray_slowdown)"]


def test_baseline_reset_skips_one_boundary_only():
    """cluster_shards' metric changed semantics at r12 (closed-loop
    burst -> fixed-offered-load achieved rate): the r11->r12 diff is
    reported, not gated, and diffs entirely on either side of the
    reset gate as usual."""
    def rec(n, rate):
        d = driver_record({"cluster_shards": ["cpu/1", rate]})
        d["n"] = n
        return d

    # straddling the reset: a 2.5x "drop" is the semantics flip
    lines, regressions, compared = compare(rec(11, 102.2), rec(12, 40.2))
    assert regressions == [] and compared == 1
    assert any("reset" in ln for ln in lines)
    # entirely on the new side: the gate is live again
    _lines, regressions, _ = compare(rec(12, 40.2), rec(13, 20.0))
    assert regressions == ["cluster_shards"]
    # entirely on the old side: historical diffs still gate
    _lines, regressions, _ = compare(rec(10, 100.0), rec(11, 50.0))
    assert regressions == ["cluster_shards"]


def test_p50_latency_regression_gated():
    old = driver_record({"cluster_4": ["cpu", 10.0, 0.40]})
    new = driver_record({"cluster_4": ["cpu", 10.5, 0.60]})  # p50 +50%
    lines, regressions, compared = compare(old, new)
    assert regressions == ["cluster_4 (write p50)"]
    assert any("p50" in ln for ln in lines)


def test_p50_improvement_and_missing_side_pass():
    # faster p50 is never a regression
    old = driver_record({"cluster_4": ["cpu", 10.0, 0.85]})
    new = driver_record({"cluster_4": ["cpu", 10.0, 0.30]})
    _lines, regressions, _ = compare(old, new)
    assert regressions == []
    # a record from before the metric existed must not fail every diff
    old2 = driver_record({"cluster_4": ["cpu", 10.0]})
    _lines, regressions, _ = compare(old2, new)
    assert regressions == []


def test_improvement_and_within_threshold_pass():
    old = driver_record({"cluster_4": ["cpu", 10.0],
                         "cluster_16": ["cpu", 10.0]})
    new = driver_record({"cluster_4": ["cpu", 20.0],
                         "cluster_16": ["cpu", 7.5]})  # -25% < 30%
    lines, regressions, compared = compare(old, new)
    assert regressions == []
    assert compared == 2


def test_regression_detected_and_gated():
    old = driver_record({"cluster_4": ["cpu", 10.0]})
    new = driver_record({"cluster_4": ["cpu", 6.0]})  # -40%
    _lines, regressions, _compared = compare(old, new)
    assert regressions == ["cluster_4"]


def test_backend_change_not_compared():
    old = driver_record({"cluster_4": ["tpu", 100.0]})
    new = driver_record({"cluster_4": ["cpu-fallback", 6.0]})
    lines, regressions, compared = compare(old, new)
    assert regressions == []
    assert compared == 1  # engaged (visible), just not numeric
    assert any("backend changed" in ln for ln in lines)


def test_non_cluster_sections_ignored_by_default():
    old = driver_record({"rns_kernel": ["tpu", 100.0]})
    new = driver_record({"rns_kernel": ["tpu", 1.0]})
    _lines, regressions, compared = compare(old, new)
    assert regressions == []
    assert compared == 0


def test_cli_on_committed_trajectory(tmp_path):
    """The CI invocation: the previous committed round vs the current
    one must load, compare, and pass."""
    old = os.path.join(REPO, "BENCH_r05.json")
    new = os.path.join(REPO, "BENCH_r06.json")
    assert os.path.exists(old)
    assert os.path.exists(new), "BENCH_r06.json must be committed"
    assert main([old, new]) == 0


def test_cli_exit_codes(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(driver_record({"cluster_4": ["cpu", 10.0]})))
    b.write_text(json.dumps(driver_record({"cluster_4": ["cpu", 5.0]})))
    assert main([str(a), str(b)]) == 1
    assert main([str(a), str(a)]) == 0


def test_cli_fails_loudly_when_gate_gated_nothing(tmp_path):
    """Format drift / section renames must not silently disable the
    gate: zero engaged sections is its own failure (exit 2)."""
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(driver_record({"cluster_4": ["cpu", 10.0]})))
    b.write_text(json.dumps(driver_record({"cluster_four": ["cpu", 10.0]})))
    assert main([str(a), str(b)]) == 2
