"""Log-structured engine (DESIGN.md §19): group commit, residue-
preserving compaction, the O(changed) digest tree, storage-served
repair cursors, snapshot shipping, and the fill-scaling p50 bound the
issue's acceptance gate names (1M-key p50 within 1.3x of 10k)."""

import threading
import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu.storage.logkv import LogStorage


def _record(variable: bytes, t: int, *, completed: bool, value: bytes = b"v"):
    """A minimal protocol record: parsable, carries a collective
    signature whose ``completed`` bit drives the §12/§19.3 keep rules."""
    sig = pkt.SignaturePacket(
        type=1, version=0, completed=True, data=b"s", cert=b"c"
    )
    ss = pkt.SignaturePacket(
        type=1, version=0, completed=completed, data=b"ss", cert=None
    )
    return pkt.serialize(variable, value, t, sig, ss)


# -- group commit ------------------------------------------------------------


def test_write_batch_one_fsync(tmp_path, monkeypatch):
    """The group-commit contract: a coalesced batch shares ONE
    durability barrier, however many records it carries."""
    import os as os_mod

    calls = []
    real = os_mod.fsync
    monkeypatch.setattr(
        os_mod, "fsync", lambda fd: (calls.append(fd), real(fd))[1]
    )
    s = LogStorage(str(tmp_path / "db"), fsync=True, group_commit_s=0)
    calls.clear()
    s.write_batch([(b"k%03d" % i, 1, b"v%d" % i) for i in range(50)])
    assert len(calls) == 1
    for i in range(50):
        assert s.read(b"k%03d" % i) == b"v%d" % i
    s.close()


def test_single_writes_durable_and_concurrent(tmp_path, monkeypatch):
    """Single writes stay durable-by-default (fsync unless opted out),
    and concurrent writers never fsync MORE than once per write —
    losers of the leader race piggyback on the leader's barrier."""
    import os as os_mod

    count = [0]
    real = os_mod.fsync

    def counting(fd):
        count[0] += 1
        return real(fd)

    monkeypatch.setattr(os_mod, "fsync", counting)
    s = LogStorage(str(tmp_path / "db"), group_commit_s=0)
    assert s.fsync is True  # durable by default, unlike PlainStorage
    count[0] = 0
    errs = []

    def worker(w):
        try:
            for i in range(10):
                s.write(b"w%d-%d" % (w, i), 1, b"x")
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert 1 <= count[0] <= 40
    for w in range(4):
        for i in range(10):
            assert s.read(b"w%d-%d" % (w, i)) == b"x"
    s.close()


# -- compaction --------------------------------------------------------------


def test_compaction_residue_semantics(tmp_path):
    """§19.3 keep rules on real records: a pending version below a
    newer certified one compacts away; certified history, uncertified
    LATEST residue, and unparsable bytes all survive — before and
    after a crash-restart replay of the compacted segment."""
    s = LogStorage(str(tmp_path / "db"), fsync=False, compact_trigger=0)
    # a: pending@1 (reclaimable), certified@2, pending@3 (latest residue)
    s.write(b"a", 1, _record(b"a", 1, completed=False, value=b"a1"))
    s.write(b"a", 2, _record(b"a", 2, completed=True, value=b"a2"))
    s.write(b"a", 3, _record(b"a", 3, completed=False, value=b"a3"))
    # b: certified history — every version stays readable
    s.write(b"b", 1, _record(b"b", 1, completed=True, value=b"b1"))
    s.write(b"b", 2, _record(b"b", 2, completed=True, value=b"b2"))
    # c: unparsable bytes below a certified latest — never dropped
    s.write(b"c", 1, b"\x00not-a-record")
    s.write(b"c", 2, _record(b"c", 2, completed=True, value=b"c2"))

    s.seal_active()
    stats = s.compact()
    assert stats["dropped"] == 1  # exactly a@1
    assert stats["kept"] == 6

    def check(store):
        assert store.versions(b"a") == [2, 3]
        assert pkt.parse(store.read(b"a", 2)).value == b"a2"
        assert pkt.parse(store.read(b"a", 3)).value == b"a3"
        assert store.versions(b"b") == [1, 2]
        assert store.versions(b"c") == [1, 2]
        assert store.read(b"c", 1) == b"\x00not-a-record"

    check(s)
    s.reopen()  # replay the compacted segment from disk
    check(s)
    s.close()


def test_compaction_trigger_reclaims_dead_bytes(tmp_path):
    """Overwriting the same (variable, t) accumulates dead bytes in
    sealed segments; the background trigger compacts them away and the
    store keeps serving the live copies."""
    s = LogStorage(
        str(tmp_path / "db"),
        fsync=False,
        segment_bytes=2048,
        compact_trigger=0.3,
    )
    payload = bytes(128)
    for round_ in range(6):
        for i in range(20):
            s.write(b"k%02d" % i, 1, payload + b"%d" % round_)
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if s.compactions and s.dead_ratio() < 0.3:
            break
        time.sleep(0.02)
    assert s.compactions >= 1
    for i in range(20):
        assert s.read(b"k%02d" % i) == payload + b"5"
    s.close()


# -- O(changed) digests ------------------------------------------------------


class _CountingStorage:
    """Storage proxy counting read()/versions() calls — the probe the
    O(changed) assertions use."""

    def __init__(self, inner):
        self.inner = inner
        self.reads = 0
        self.version_calls = 0

    def read(self, variable, t=0):
        self.reads += 1
        return self.inner.read(variable, t)

    def versions(self, variable):
        self.version_calls += 1
        return self.inner.versions(variable)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_digest_tree_reads_o_changed(tmp_path):
    """After the initial build, a digest round re-reads ONLY dirty
    variables: 100 changed records out of 3000 cost ~100 reads, not a
    keyspace sweep."""
    from bftkv_tpu.sync.digest import DigestTree

    s = LogStorage(str(tmp_path / "db"), fsync=False)
    n = 3000
    for i in range(n):
        var = b"key-%05d" % i
        s.write(var, 1, _record(var, 1, completed=True))
    probe = _CountingStorage(s)
    tree = DigestTree(probe)
    tree.buckets()  # full build: O(keyspace), once
    base = tree.root()

    probe.reads = 0
    probe.version_calls = 0
    changed = [b"key-%05d" % i for i in range(0, 1000, 10)]  # 100 vars
    for var in changed:
        s.write(var, 2, _record(var, 2, completed=True))
        tree.mark(var)
    tree.buckets()
    assert tree.root() != base
    # Bounded by the CHANGED set (small constant per variable), far
    # under the 3000-key keyspace.
    assert probe.reads <= 4 * len(changed)
    assert probe.version_calls <= 4 * len(changed)

    probe.reads = 0
    probe.version_calls = 0
    tree.buckets()  # nothing dirty: free
    assert probe.reads == 0 and probe.version_calls == 0
    s.close()


# -- repair-scan cursor ------------------------------------------------------


def test_pending_variables_storage_served_cursor(tmp_path):
    """``pending_variables`` on a §19 store pages through the keyspace
    via the storage-served sorted_keys cursor: each window reads only
    window-many records, finds exactly the pending residue, and the
    cursor walk terminates."""
    from bftkv_tpu.protocol.server import Server

    s = LogStorage(str(tmp_path / "db"), fsync=False)
    pending_vars = set()
    for i in range(40):
        var = b"key-%03d" % i
        completed = i % 8 != 0
        if not completed:
            pending_vars.add(var)
        s.write(var, 1, _record(var, 1, completed=completed))

    class _Stub:
        storage = s

    stub = _Stub()
    probe = _CountingStorage(s)
    stub.storage = probe

    found = set()
    cursor = None
    rounds = 0
    while True:
        probe.reads = 0
        got, cursor = Server.pending_variables(
            stub, after=cursor, scan_window=7
        )
        rounds += 1
        assert probe.reads <= 7  # the window bounds the record reads
        found.update(v for v, _t, _raw, _p in got)
        if cursor is None:
            break
        assert rounds <= 40
    assert found == pending_vars
    assert rounds == 6  # ceil(40 / 7) windows, not a full-store parse
    s.close()


def test_sorted_keys_window(tmp_path):
    s = LogStorage(str(tmp_path / "db"), fsync=False)
    import random

    keys = [b"k%03d" % i for i in range(50)]
    for k in random.Random(7).sample(keys, len(keys)):
        s.write(k, 1, b"v")
    assert s.sorted_keys() == keys
    assert s.sorted_keys(after=b"k010", limit=5) == keys[11:16]
    assert s.sorted_keys(after=keys[-1]) == []
    # The cached sort survives same-key updates and extends on new keys.
    s.write(b"k000", 2, b"v2")
    s.write(b"zzz", 1, b"v")
    assert s.sorted_keys() == keys + [b"zzz"]
    s.close()


# -- snapshot shipping -------------------------------------------------------


def test_snapshot_records_live_only(tmp_path):
    """snapshot_records seals the active segment and streams exactly
    the LIVE records (superseded same-(variable, t) copies stay dead),
    honoring the predicate."""
    s = LogStorage(str(tmp_path / "db"), fsync=False)
    s.write(b"x", 1, b"old")
    s.write(b"x", 1, b"new")  # supersedes the first copy
    s.write(b"x", 2, b"x2")
    s.write(b"y", 1, b"y1")
    got = sorted(s.snapshot_records())
    assert got == [(b"x", 1, b"new"), (b"x", 2, b"x2"), (b"y", 1, b"y1")]
    only_y = list(s.snapshot_records(lambda v: v == b"y"))
    assert only_y == [(b"y", 1, b"y1")]
    assert s.sealed_segment_paths()  # the active segment was sealed
    s.close()


# -- fill-scaling p50 --------------------------------------------------------


def _fill_p50(path: str, n: int, samples: int = 2000) -> float:
    """Median append latency measured AFTER ``n`` resident keys."""
    s = LogStorage(path, fsync=False)
    payload = b"p" * 64
    for i in range(n):
        s.write(b"fill-%07d" % i, 1, payload)
    lat = []
    for i in range(samples):
        t0 = time.perf_counter()
        s.write(b"probe-%07d" % i, 1, payload)
        lat.append(time.perf_counter() - t0)
    s.close()
    lat.sort()
    return lat[len(lat) // 2]


def test_fill_p50_flat_10k_vs_100k(tmp_path):
    """Append cost must not scale with resident keyspace: p50 at 100k
    keys within 1.3x of p50 at 10k (plus a scheduler-noise epsilon)."""
    p10k = _fill_p50(str(tmp_path / "s10k"), 10_000)
    p100k = _fill_p50(str(tmp_path / "s100k"), 100_000)
    assert p100k <= 1.3 * p10k + 5e-6, (p10k, p100k)


@pytest.mark.slow
def test_fill_p50_flat_10k_vs_1m(tmp_path):
    """The acceptance-gate form: 1M resident keys, p50 within 1.3x of
    the 10k-key fill."""
    p10k = _fill_p50(str(tmp_path / "s10k"), 10_000)
    p1m = _fill_p50(str(tmp_path / "s1m"), 1_000_000)
    assert p1m <= 1.3 * p10k + 5e-6, (p10k, p1m)
