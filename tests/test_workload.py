"""Workload engine tests (DESIGN.md §23): seeded spec determinism
across runs AND worker counts, the bucket-vector merge law, key-model
sanity bounds, the open-loop overload regression (latency from the
SCHEDULED start, backlog reported not absorbed), a loopback cluster
smoke, and the universe profiler's O(universe)-per-op oracle."""

import time

import pytest

from bftkv_tpu.metrics import BUCKETS
from bftkv_tpu.workload.driver import (
    LatencyHist,
    OpenLoop,
    merge_reports,
    run_in_process,
)
from bftkv_tpu.workload.spec import WorkloadSpec, parse_spec
from bftkv_tpu.workload.universe import (
    apply_churn,
    build_synthetic_graph,
    churn_schedule,
    profile_universe,
)
from tests.cluster_utils import start_cluster

BITS = 1024


# -- spec determinism ---------------------------------------------------


def test_stream_identical_across_runs_and_canonical_roundtrip():
    spec = WorkloadSpec.preset("storm", rate=40.0, duration_s=1.0, seed=5)
    total = spec.total_ops()
    ops1 = [spec.op_at(g) for g in range(total)]
    ops2 = [spec.op_at(g) for g in range(total)]
    assert ops1 == ops2
    again = parse_spec(spec.canonical())
    assert again == spec
    assert [again.op_at(g) for g in range(total)] == ops1


def test_stream_identical_across_worker_counts():
    """Worker slices partition the SAME global stream: op g is op g
    no matter how many workers the spec is split over."""
    spec = WorkloadSpec.preset("write_heavy", rate=50.0, duration_s=1.0,
                               seed=9)
    full = list(spec.iter_ops(0, 1))
    for w in (2, 4, 8):
        sliced = []
        for ci in range(w):
            sliced.extend(spec.iter_ops(ci, w))
        assert sorted(sliced, key=lambda o: o.index) == full


def test_owner_slots_respect_worker_divisibility():
    """g % owners ≡ g % W composes: every owner slot maps to exactly
    one worker when W divides owners — the TOFU safety arithmetic."""
    spec = WorkloadSpec(owners=8, rate=100.0, duration_s=0.5, seed=3)
    for w in (2, 4, 8):
        owner_to_worker: dict = {}
        for ci in range(w):
            for op in spec.iter_ops(ci, w):
                assert owner_to_worker.setdefault(op.owner, ci) == ci


def test_arrival_programs_monotone_and_sized():
    for name in ("read_heavy", "write_heavy", "storm", "ramp"):
        spec = WorkloadSpec.preset(name, rate=40.0, duration_s=2.0, seed=1)
        total = spec.total_ops()
        assert total >= int(40.0 * 2.0)  # ramp/storm only add rate
        dues = [spec.due(g) for g in range(total)]
        assert all(b >= a for a, b in zip(dues, dues[1:]))
        assert dues[-1] <= spec.duration_s + 1e-6


# -- key models ---------------------------------------------------------


def test_zipf_rank_zero_is_hottest():
    spec = WorkloadSpec(keys="zipf", zipf_s=1.2, keyspace=64,
                        rate=1000.0, duration_s=1.0, seed=4)
    ranks = [spec.op_at(g).rank for g in range(1000)]
    counts = [ranks.count(r) for r in range(64)]
    assert counts[0] == max(counts)
    assert counts[0] > 3 * max(counts[32:], default=0)


def test_hotset_bounds_and_churn():
    spec = WorkloadSpec(keys="hotset", hot_keys=4, hot_frac=0.9,
                        churn_every=100, keyspace=256,
                        rate=1000.0, duration_s=1.0, seed=7)
    epoch0, epoch1 = spec.hot_set(0), spec.hot_set(1)
    assert len(epoch0) == len(epoch1) == 4
    assert epoch0 != epoch1  # churn rotates the set
    hot_hits = sum(
        1 for g in range(100) if spec.op_at(g).rank in epoch0
    )
    # 90% of draws land in the 4-key hot set (binomial, wide bound).
    assert hot_hits >= 75


def test_storm_window_concentrates_on_hot_set():
    spec = WorkloadSpec.preset("storm", rate=100.0, duration_s=2.0,
                               seed=2, churn_every=0)
    in_storm = [
        op for op in spec.iter_ops() if spec.in_storm(op.due_s)
    ]
    assert in_storm, "storm window produced no ops"
    hot = spec.hot_set(0)
    assert all(op.rank in hot for op in in_storm)


# -- histogram merge law ------------------------------------------------


def test_bucket_merge_equals_single_stream():
    import hashlib

    lats = [
        int.from_bytes(hashlib.sha256(b"lat%d" % i).digest()[:4], "big")
        / 2**32 * 0.4
        for i in range(600)
    ]
    whole = LatencyHist()
    parts = [LatencyHist() for _ in range(3)]
    for i, v in enumerate(lats):
        whole.observe(v)
        parts[i % 3].observe(v)
    merged = LatencyHist()
    for p in parts:
        merged.merge(p)
    assert merged.counts == whole.counts
    assert merged.n == whole.n
    assert merged.total == pytest.approx(whole.total)
    for q in (0.5, 0.9, 0.99):
        assert merged.quantile(q) == whole.quantile(q)


def test_merge_reports_sums_bucket_vectors():
    spec = WorkloadSpec(rate=100.0, duration_s=1.0, seed=1)
    reports = []
    ref = LatencyHist()
    for w in range(2):
        h = LatencyHist()
        for i in range(50):
            v = 0.001 * (i + 1) * (w + 1)
            h.observe(v)
            ref.observe(v)
        reports.append({
            "lat_buckets": h.counts, "lat_total_s": h.total,
            "ops": {"write": 50}, "offered_ops": 50, "elapsed_s": 1.0,
            "backlog": {"ops_behind": w, "max_sched_lag_s": 0.1 * w},
        })
    merged = merge_reports(reports, spec, workers=2)
    assert merged["lat_buckets"] == ref.counts
    assert merged["offered_ops"] == 100
    assert merged["ops"] == {"write": 100}
    assert merged["p99_offered_s"] == ref.quantile(0.99)
    assert merged["backlog"] == {"ops_behind": 1, "max_sched_lag_s": 0.1}
    assert merged["mode"] == "multi_process"


def test_hist_rejects_wrong_ladder():
    with pytest.raises(ValueError):
        LatencyHist(counts=[0] * len(BUCKETS))


# -- open-loop overload regression --------------------------------------


def test_openloop_reports_backlog_and_charges_from_due():
    """The PR 20 overload fix: when the scheduler falls behind, an
    op's latency still runs from its SCHEDULED start and the backlog
    is reported — never silently absorbed into a slower offered
    load."""
    ol = OpenLoop(rate=1000.0, workers=1)
    lag_seen = []
    for k in range(6):
        due = ol.wait(0, k)
        time.sleep(0.01)  # deliberately slower than the 1ms schedule
        lag_seen.append(time.perf_counter() - due)
    backlog = ol.backlog()
    assert backlog["ops_behind"] >= 4
    assert backlog["max_sched_lag_s"] > 0
    # Latency measured from the due time grows with the queue: the
    # coordinated-omission correction is visible in the samples.
    assert lag_seen[-1] > lag_seen[0]
    assert lag_seen[-1] >= 0.04


def test_openloop_on_time_has_no_backlog():
    # 50ms spacing: trivially keepable even on a loaded 1-core box.
    ol = OpenLoop(rate=20.0, workers=1)
    for k in range(3):
        ol.wait(0, k)
    assert ol.backlog() == {"ops_behind": 0, "max_sched_lag_s": 0.0}


# -- loopback cluster smoke --------------------------------------------


@pytest.fixture(scope="module")
def wl_cluster():
    c = start_cluster(4, 2, 4, bits=BITS)
    yield c
    c.stop()


def test_run_in_process_smoke(wl_cluster):
    spec = WorkloadSpec.preset(
        "write_heavy", rate=30.0, duration_s=1.0, seed=6, owners=2,
        keyspace=32,
    )
    rep = run_in_process(spec, wl_cluster.clients, workers=2)
    assert rep["errors"] == 0, rep["error_samples"]
    assert rep["offered_ops"] == spec.total_ops()
    assert rep["achieved_rate_per_sec"] > 0
    assert rep["p50_offered_s"] is not None
    assert sum(rep["ops"].values()) == rep["offered_ops"]
    assert rep["mode"] == "in_process"
    # Written values are readable back through the cluster.
    wrote = [
        op for op in spec.iter_ops() if op.kind == "write"
    ]
    assert wrote
    got = wl_cluster.clients[0].read(
        spec.key_bytes(wrote[-1].owner, wrote[-1].rank)
    )
    assert got is not None


def test_run_in_process_rejects_nondivisible_workers(wl_cluster):
    spec = WorkloadSpec(owners=3, rate=10.0, duration_s=0.2)
    with pytest.raises(ValueError):
        run_in_process(spec, wl_cluster.clients, workers=2)


# -- universe scaling ---------------------------------------------------


def test_universe_profile_oracle_zero_o_universe_calls():
    """The §23 acceptance bar at test scale: once memos are warm,
    steady-state choose_quorum_for does NO O(universe) graph
    traversal — counted, not timed."""
    res = profile_universe(200, shard_size=4, ops=64, churn_events=2,
                           seed=1)
    assert res["n_cliques"] == 50
    assert res["o_universe_calls_steady"] == 0
    assert res["steady_per_op_us"] < 10_000


def test_synthetic_graph_shapes_and_churn():
    g, certs = build_synthetic_graph(48, shard_size=4, seed=2)
    cliques = g.get_disjoint_cliques(min_size=4)
    assert len(cliques) == 12
    assert all(len(c.nodes) == 4 for c in cliques)
    sched = churn_schedule(6, n_nodes=48, duration_s=1.0, seed=2,
                           storm_start_frac=0.5, storm_revokes=3)
    assert sched == churn_schedule(6, n_nodes=48, duration_s=1.0, seed=2,
                                   storm_start_frac=0.5, storm_revokes=3)
    assert sum(1 for e in sched if e.kind == "revoke") >= 3
    gen0 = g.generation
    for ev in sched:
        apply_churn(g, certs, ev, shard_size=4, seed=2)
    assert g.generation > gen0
    assert g.get_disjoint_cliques(min_size=4)


def test_flag_overrides_splice_env_knobs(monkeypatch):
    """BFTKV_WORKLOAD_{SEED,RATE,DURATION} resolve through one read
    path (spec.flag_overrides): unset flags leave caller defaults
    untouched, set flags override the matching spec fields."""
    from bftkv_tpu.workload.spec import flag_overrides

    for name in ("BFTKV_WORKLOAD_SEED", "BFTKV_WORKLOAD_RATE",
                 "BFTKV_WORKLOAD_DURATION"):
        monkeypatch.delenv(name, raising=False)
    assert flag_overrides() == {}
    monkeypatch.setenv("BFTKV_WORKLOAD_SEED", "7")
    monkeypatch.setenv("BFTKV_WORKLOAD_RATE", "33.5")
    monkeypatch.setenv("BFTKV_WORKLOAD_DURATION", "2.5")
    over = flag_overrides()
    assert over == {"seed": 7, "rate": 33.5, "duration_s": 2.5}
    spec = WorkloadSpec.preset("storm", **over)
    assert (spec.seed, spec.rate, spec.duration_s) == (7, 33.5, 2.5)
