"""Byzantine + threshold-CA scenarios on ECDSA P-256 identity
universes: the "zero additional safety violations" gate must hold
regardless of the identity-key algorithm (the adversary machinery in
mal_utils is algorithm-agnostic by construction, like the reference's
PGP layer — crypto_pgp.go:310-405).
"""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import topology
from bftkv_tpu.transport.loopback import TrLoopback

from cluster_utils import start_cluster
from mal_utils import MalClient, MalServer, MalStorage


@pytest.fixture()
def ec_mal_cluster():
    c = start_cluster(
        n_servers=7,
        n_users=2,
        n_rw=6,
        server_cls=MalServer,
        storage_factory=MalStorage,
        alg="p256",
    )
    mal = {i.cert.address for i in c.universe.servers[-3:]}
    mal |= {i.cert.address for i in c.universe.storage_nodes[-2:]}
    MalServer.mal_addresses = mal
    try:
        yield c, mal
    finally:
        MalServer.mal_addresses = set()
        c.stop()


def test_ec_collusion_convergence_and_revocation(ec_mal_cluster):
    """Equivocation with EC-signed packets: the honest reader converges
    and the EC double-signers are revoked (mal_test.go:23-71, on
    P-256 identities)."""
    c, mal = ec_mal_cluster
    uni = c.universe

    evil_ident = uni.users[0]
    graph, crypt, qs = topology.make_node(evil_ident, uni.view_of(evil_ident))
    evil = MalClient(
        graph, qs, TrLoopback(crypt, c.net), crypt, mal_addresses=mal
    )
    evil.write_mal(b"ec_mal", b"value-one", b"value-two")

    honest = c.clients[1]
    value = honest.read(b"ec_mal")
    assert value in (b"value-one", b"value-two")

    deadline = time.time() + 10
    mal_server_ids = {i.cert.id for i in uni.servers[-3:]}
    while time.time() < deadline:
        if mal_server_ids <= set(honest.self_node.revoked):
            break
        time.sleep(0.05)
    assert mal_server_ids <= set(honest.self_node.revoked)
    assert evil_ident.cert.id in honest.self_node.revoked


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_ec_batch_pipeline_survives_colluders(ec_mal_cluster):
    c, _ = ec_mal_cluster
    honest = c.clients[1]
    items = [(b"ec_sane/%d" % i, b"v%d" % i) for i in range(8)]
    assert honest.write_many(items) == [None] * 8
    assert honest.read_many([v for v, _ in items]) == [v for _, v in items]


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_threshold_ca_on_ec_identity_cluster():
    """The decentralized CA over a pure-EC identity cluster: RSA and
    ECDSA CA keys distribute (shares ECIES-encrypted per recipient via
    the message layer) and threshold-sign with verifiable output
    (reference: protocol/dist_test.go:29-105)."""
    from bftkv_tpu.crypto import rsa as rsamod
    from bftkv_tpu.crypto.ec import P256
    from bftkv_tpu.crypto.threshold import ThresholdAlgo
    from bftkv_tpu.crypto.threshold.ecdsa import generate as ec_generate

    c = start_cluster(9, 1, 4, alg="p256")
    try:
        cl = c.clients[0]
        ca_rsa = rsamod.generate(2048)
        cl.distribute("ecu-rsa", ca_rsa)
        sig = cl.dist_sign("ecu-rsa", b"tbs-1", ThresholdAlgo.RSA, "sha256")
        em = rsamod.emsa_pkcs1v15_sha256(b"tbs-1", ca_rsa.size_bytes)
        assert pow(int.from_bytes(sig, "big"), ca_rsa.e, ca_rsa.n) == em

        ca_ec = ec_generate()
        cl.distribute("ecu-ec", ca_ec)
        sig2 = cl.dist_sign("ecu-ec", b"tbs-2", ThresholdAlgo.ECDSA, "sha256")
        # Threshold ECDSA emits raw r(32)‖s(32) — the same wire form as
        # identity ECDSA; verify against the CA public key directly.
        from bftkv_tpu.crypto import ecdsa as id_ecdsa

        pub_pt = P256.scalar_base_mult(ca_ec.d)
        pub = id_ecdsa.ECPublicKey(x=pub_pt[0], y=pub_pt[1])
        assert len(sig2) == 64
        assert id_ecdsa.verify_host(b"tbs-2", sig2, pub)
    finally:
        c.stop()
