"""ECDSA P-256 identity certificates end-to-end (BASELINE config 4).

The reference's PGP layer verifies whatever algorithm a key carries
(crypto/pgp/crypto_pgp.go:310-405); these tests prove the same
algorithm agility here: EC certs parse/sign/verify, the keyring
persists EC keys, the message layer bootstraps sessions via ECIES, the
verify dispatcher handles mixed batches, and full clusters run on
pure-EC and mixed universes over loopback and HTTP.
"""

from __future__ import annotations

import numpy as np
import pytest

from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import ecdsa, rsa
from bftkv_tpu.crypto.keyring import (
    Keyring,
    parse_private_key,
    serialize_private_key,
)


def test_ecdsa_sign_verify_roundtrip():
    key = ecdsa.generate()
    sig = ecdsa.sign(b"hello", key)
    assert len(sig) == ecdsa.SIG_BYTES
    assert ecdsa.verify_host(b"hello", sig, key.public)
    assert not ecdsa.verify_host(b"hellO", sig, key.public)
    assert not ecdsa.verify_host(b"hello", sig[:-1] + b"\x00", key.public)
    # Deterministic (RFC 6979): same message, same signature.
    assert ecdsa.sign(b"hello", key) == sig


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_ecdsa_batch_sign_and_verify(monkeypatch):
    # Force the device path (crossover would keep these tiny batches on
    # host and skip the kernels under test).
    monkeypatch.setenv("BFTKV_EC_SIGN_THRESHOLD", "0")
    monkeypatch.setenv("BFTKV_EC_VERIFY_THRESHOLD", "0")
    key = ecdsa.generate()
    msgs = [b"m-%d" % i for i in range(5)]
    sigs = ecdsa.sign_batch(msgs, key)
    # Device-batch nonces are HEDGED (RFC 6979 §3.6) so a faulted
    # device R can never pair with a same-k signature: batch sigs are
    # valid but deliberately differ from the deterministic single path.
    assert all(
        ecdsa.verify_host(m, s, key.public) for m, s in zip(msgs, sigs)
    )
    assert sigs != [ecdsa.sign(m, key) for m in msgs]
    items = [(m, s, key.public) for m, s in zip(msgs, sigs)]
    items[2] = (msgs[2], sigs[3], key.public)  # wrong sig for msg
    items.append((b"junk", b"short", key.public))  # malformed
    got = ecdsa.verify_batch(items)
    assert got == [True, True, False, True, True, False]


def test_ec_certificate_roundtrip_and_edges():
    ec_key = ecdsa.generate()
    rsa_key = rsa.generate(1024)
    cert = certmod.make_ec_certificate(
        ec_key.public, name="e01", address="loop://e01", uid="e01@x"
    )
    certmod.sign_certificate(cert, ec_key)  # self-edge (EC)
    certmod.sign_certificate(cert, rsa_key)  # cross-alg edge (RSA)
    rsa_cert = certmod.Certificate(n=rsa_key.n, e=rsa_key.e, name="r01")

    parsed = certmod.parse(cert.serialize())[0]
    assert parsed.id == cert.id and parsed.alg == certmod.ALG_P256
    assert parsed.name == "e01" and parsed.address == "loop://e01"
    assert parsed.verify_signature(parsed)  # EC self-edge
    assert parsed.verify_signature(rsa_cert)  # RSA edge onto EC cert
    # And the reverse direction: an EC signer onto an RSA cert.
    certmod.sign_certificate(rsa_cert, ec_key)
    assert rsa_cert.verify_signature(parsed)


def test_ec_cert_bad_point_rejected():
    ec_key = ecdsa.generate()
    cert = certmod.make_ec_certificate(ec_key.public)
    blob = bytearray(cert.serialize())
    # Corrupt a point byte (inside the SEC1 chunk after magic+alg).
    blob[20] ^= 0xFF
    with pytest.raises(Exception):
        certmod.parse(bytes(blob))


def test_keyring_persists_ec_keys(tmp_path):
    ec_key = ecdsa.generate()
    rsa_key = rsa.generate(1024)
    assert parse_private_key(serialize_private_key(ec_key)) == ec_key

    ring = Keyring()
    ec_cert = certmod.make_ec_certificate(ec_key.public, name="e")
    rsa_cert = certmod.Certificate(n=rsa_key.n, e=rsa_key.e, name="r")
    ring.register([ec_cert], priv=ec_key)
    ring.register([rsa_cert], priv=rsa_key)
    ring.save_secring(str(tmp_path / "sec"))
    ring.save_pubring(str(tmp_path / "pub"))

    ring2 = Keyring()
    ring2.load_pubring(str(tmp_path / "pub"))
    ring2.load_secring(str(tmp_path / "sec"))
    assert ring2.private_key(ec_cert.id) == ec_key
    assert ring2.lookup(ec_cert.id).alg == certmod.ALG_P256


def test_message_security_ec_pairs():
    from bftkv_tpu.crypto.message import MessageSecurity

    ids = {}
    for name, alg in (("e1", "p256"), ("e2", "p256"), ("r1", "rsa")):
        if alg == "p256":
            k = ecdsa.generate()
            c = certmod.make_ec_certificate(k.public, name=name)
        else:
            k = rsa.generate(1024)
            c = certmod.Certificate(n=k.n, e=k.e, name=name)
        certmod.sign_certificate(c, k)
        ids[name] = (k, c, MessageSecurity(k, c))

    for a, b in (("e1", "e2"), ("e1", "r1"), ("r1", "e1")):
        ka, ca, ma = ids[a]
        kb, cb, mb = ids[b]
        # Bootstrap then session fast path, both directions of alg mix.
        for i in range(2):
            blob = ma.encrypt([cb], b"payload-%d" % i, b"nonce-%d" % i)
            pt, sender, nonce = mb.decrypt(blob)
            assert pt == b"payload-%d" % i and nonce == b"nonce-%d" % i
            assert sender.id == ca.id


def test_verifier_domain_mixed_batch():
    ec_key = ecdsa.generate()
    rsa_key = rsa.generate(1024)
    items = []
    for i in range(4):
        m = b"mix-%d" % i
        if i % 2:
            items.append((m, ecdsa.sign(m, ec_key), ec_key.public))
        else:
            items.append((m, rsa.sign(m, rsa_key), rsa_key.public))
    items[3] = (items[3][0] + b"!", items[3][1], items[3][2])
    dom = rsa.VerifierDomain(host_threshold=0)
    got = np.asarray(dom.verify_batch(items))
    assert got.tolist() == [True, True, True, False]


@pytest.mark.parametrize(
    "alg",
    [
        # The all-EC variant pays the cold scalar-mult jits; tier-2.
        pytest.param("p256", marks=pytest.mark.slow),
        "mixed",
    ],
)
def test_cluster_on_ec_keys(alg):
    from tests.cluster_utils import start_cluster

    c = start_cluster(4, 1, 4, alg=alg)
    try:
        cl = c.clients[0]
        cl.write(b"ec/x", b"v1")
        assert cl.read(b"ec/x") == b"v1"
        cl.write(b"ec/x", b"v2")
        assert cl.read(b"ec/x") == b"v2"
        errs = cl.write_many([(b"ec/b/%d" % i, b"bv%d" % i) for i in range(8)])
        assert errs == [None] * 8
        vals = cl.read_many([b"ec/b/%d" % i for i in range(8)])
        assert vals == [b"bv%d" % i for i in range(8)]
    finally:
        c.stop()


def test_http_cluster_on_ec_keys():
    # The reference tier-3 shape (real localhost HTTP) on a pure-EC
    # universe: sessions bootstrap via ECIES, writes verify via the
    # batched EC path.
    from tests.cluster_utils import start_cluster

    c = start_cluster(4, 1, 4, transport="http", alg="p256")
    try:
        cl = c.clients[0]
        cl.write(b"echttp/x", b"h1")
        assert cl.read(b"echttp/x") == b"h1"
    finally:
        c.stop()
