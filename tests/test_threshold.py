"""Threshold RSA/DSA/ECDSA, simulated multi-node without transport
(reference test strategy: crypto/threshold/rsa/rsa_test.go,
dsa/dsa_test.go + test_utils, ecdsa/ecdsa_test.go — SURVEY.md §4 tier 2)."""

import random
import secrets

import pytest

from bftkv_tpu import errors
from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import ec
from bftkv_tpu.crypto import rsa as rsakeys
from bftkv_tpu.crypto import new_crypto
from bftkv_tpu.crypto.threshold import (
    ThresholdAlgo,
    ThresholdInstance,
    parse_params,
    serialize_params,
)
from bftkv_tpu.crypto.threshold import dsa as tdsa
from bftkv_tpu.crypto.threshold import ecdsa as tecdsa
from bftkv_tpu.crypto.threshold import rsa as trsa

RNG = random.Random(42)


def _rng(bound):
    return RNG.randrange(bound)


# -- RSA tree unit tests (reference: rsa_test.go:31-102) -------------------


def test_split_key_sums_back():
    d = secrets.randbits(512)
    frags = trsa._split_key(d, 7, _rng)
    assert sum(frags) == d
    assert len(frags) == 7


def test_key_tree_covers_k_subsets():
    """Any k-subset of servers holds fragments that recombine to d along
    the exclusion tree (the property behind rsa.go:75-127): the value at
    a tree node is recoverable from a holder set S iff for every child i
    of the node, either i ∈ S holds the fragment directly or S recovers
    child i's subtree."""
    n, k = 5, 3
    d = secrets.randbits(64)
    tree = trsa.make_key_tree(d, 0, n, k, _rng)
    per_server = []
    for i in range(n):
        keys = {}
        trsa.collect_keys(tree, i, keys)
        per_server.append(keys)
        assert keys, f"server {i} holds no fragments"

    def recover(node, holders):
        if node.children is None:
            return None  # leaf value is only reachable via its holder
        total = 0
        for i, child in node.children.items():
            if i in holders:
                assert per_server[i][node.idx] == child.di
                total += child.di
            else:
                sub = recover(child, holders)
                if sub is None:
                    return None
                total += sub
        return total

    import itertools

    for subset in itertools.combinations(range(n), k):
        assert recover(tree, set(subset)) == d, subset
    # k-1 servers must NOT recover
    for subset in itertools.combinations(range(n), k - 1):
        assert recover(tree, set(subset)) is None, subset


def sim_rsa_sign(key, n, k, subset, tbs=b"threshold me"):
    """Drive dealer → per-server sign → client combine with direct calls."""
    ctx = trsa.RSAThreshold(rng=_rng)

    class FakeNode:
        def __init__(self, i):
            self.id = i

    nodes = [FakeNode(i) for i in range(n)]
    shares, algo = ctx.distribute(key, nodes, k)
    assert algo == ThresholdAlgo.RSA
    proc = ctx.new_process(tbs, algo, "sha256")
    for _round in range(10):
        target, req = proc.make_request()
        if req is None:
            break
        sig = None
        for node in target:
            if node.id not in subset:
                continue
            res = ctx.sign(shares[node.id], req, 0xC11E47, node.id)
            if res is None:
                continue
            sig = proc.process_response(res, node)
            if sig is not None:
                break
        if sig is not None:
            return sig, tbs
    return proc.sig, tbs


def test_rsa_threshold_full_quorum():
    key = rsakeys.generate(1024)
    sig, tbs = sim_rsa_sign(key, 5, 3, set(range(5)))
    assert sig is not None
    assert rsakeys.verify_host(tbs, sig, key.public)
    # matches the host signer exactly (deterministic PKCS#1 v1.5)
    assert sig == rsakeys.sign(tbs, key)


def test_rsa_threshold_k_subsets():
    key = rsakeys.generate(1024)
    n, k = 5, 3
    subsets = [set(s) for s in [(0, 1, 2), (2, 3, 4), (0, 2, 4), (1, 3, 4)]]
    for subset in subsets:
        sig, tbs = sim_rsa_sign(key, n, k, subset)
        assert sig is not None, f"subset {subset} failed"
        assert rsakeys.verify_host(tbs, sig, key.public), subset


def test_rsa_threshold_k_minus_one_insufficient():
    key = rsakeys.generate(1024)
    sig, _ = sim_rsa_sign(key, 5, 3, {0, 1})
    assert sig is None


def test_emsa_matches_host_encoding():
    key = rsakeys.generate(1024)
    prefix = trsa._HASH_PREFIXES["sha256"]
    import hashlib

    tbs = b"encode me"
    m = trsa.emsa_encode(prefix, hashlib.sha256(tbs).digest(), key.size_bytes)
    assert m == rsakeys.emsa_pkcs1v15_sha256(tbs, key.size_bytes)


# -- DSA/ECDSA 3-phase simulation (reference: dsa_test.go:221-463) ---------


def make_universe(n):
    """n server identities with full cross-knowledge (tier-2 fake
    backend: direct calls, no transport)."""
    idents = []
    for i in range(n):
        key = rsakeys.generate(1024)
        c = certmod.Certificate(n=key.n, name=f"s{i}", address=f"addr{i}", uid=f"u{i}")
        certmod.sign_certificate(c, key)
        idents.append((key, c))
    bundles = []
    for key, c in idents:
        crypt = new_crypto(key, c)
        for _, other in idents:
            crypt.keyring.register([other])
        bundles.append(crypt)
    return idents, bundles


def sim_dsa_sign(make_ctx, key, n, kthresh, tbs=b"dsa sign me", subset=None):
    idents, bundles = make_universe(n)
    nodes = [c for _, c in idents]
    servers = {c.id: make_ctx(bundles[i]) for i, (_, c) in enumerate(idents)}
    shares = {}
    client_ctx = make_ctx(bundles[0])  # client reuses server-0 identity
    out, algo = client_ctx.distribute(key, nodes, kthresh)
    for node, share in zip(nodes, out):
        shares[node.id] = share
    client_id = 0xBEEF
    proc = client_ctx.new_process(tbs, algo, "sha256")
    for _round in range(10):
        target, req = proc.make_request()
        if not target:
            break
        result = None
        advance = False
        for node in target:
            if subset is not None and node.id not in subset:
                continue
            res = servers[node.id].sign(shares[node.id], req, client_id, node.id)
            if res is None:
                continue
            try:
                result = proc.process_response(res, node)
            except errors.ERR_CONTINUE:
                advance = True
                break
            if result is not None:
                return result
        if result is not None:
            return result
        if not advance:
            break
    return None


def test_dsa_threshold_roundtrip():
    key = tdsa.generate(1024)
    n = 6
    sig = sim_dsa_sign(lambda crypt: tdsa.new(crypt), key, n, 3)
    assert sig is not None
    # standard DSA verify: v = (g^u1 · y^u2 mod p) mod q == r
    size = (key.q.bit_length() + 7) // 8
    r = int.from_bytes(sig[:size], "big")
    s = int.from_bytes(sig[size:], "big")
    assert 0 < r < key.q and 0 < s < key.q
    import hashlib

    ops = tdsa._DSAGroupOps(key.p, key.q, key.g)
    m = ops.os2i(hashlib.sha256(b"dsa sign me").digest())
    w = pow(s, -1, key.q)
    u1 = (m * w) % key.q
    u2 = (r * w) % key.q
    v = (pow(key.g, u1, key.p) * pow(key.y, u2, key.p)) % key.p % key.q
    assert v == r


def test_ecdsa_threshold_roundtrip():
    key = tecdsa.generate(ec.P256)
    n = 6
    tbs = b"ecdsa sign me"
    sig = sim_dsa_sign(lambda crypt: tecdsa.new(crypt), key, n, 3, tbs=tbs)
    assert sig is not None
    size = 32
    r = int.from_bytes(sig[:size], "big")
    s = int.from_bytes(sig[size:], "big")
    # cross-check against the host crypto library
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    pub = key.curve.scalar_base_mult(key.d)
    pubkey = cec.EllipticCurvePublicNumbers(
        pub[0], pub[1], cec.SECP256R1()
    ).public_key()
    pubkey.verify(encode_dss_signature(r, s), tbs, cec.ECDSA(hashes.SHA256()))


def test_dispatcher_routes_by_key_and_algo():
    idents, bundles = make_universe(3)
    nodes = [c for _, c in idents]
    inst = ThresholdInstance(bundles[0])
    key = rsakeys.generate(1024)
    shares, algo = inst.distribute(key, nodes, 2)
    assert algo == ThresholdAlgo.RSA
    aux = serialize_params(algo, shares[0])
    back_algo, data = parse_params(aux)
    assert back_algo == ThresholdAlgo.RSA and data == shares[0]
    with pytest.raises(errors.ERR_UNSUPPORTED_ALGORITHM):
        inst.distribute(object(), nodes, 2)
    with pytest.raises(errors.ERR_UNSUPPORTED_ALGORITHM):
        parse_params(b"")


def test_partial_param_hostile_bytes():
    for data in [b"", b"\x00", b"\xff" * 7, secrets.token_bytes(40)]:
        with pytest.raises(errors.Error):
            trsa._parse_partial_param(data)
        with pytest.raises(errors.Error):
            trsa._parse_sign_request(data)
        with pytest.raises(errors.Error):
            trsa._parse_partial_signature(data)
