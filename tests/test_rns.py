"""RNS (residue number system) RSA verifier vs the host oracle.

Covers the Bajard/Shenoy base-extension math on real signatures, mixed
key sizes, adversarial inputs (bit flips, wrong keys, sig >= n, hostile
moduli sharing a factor with a channel prime), and backend equivalence
through VerifierDomain.
"""

import numpy as np
import pytest

from bftkv_tpu.crypto import rsa
from bftkv_tpu.ops import limb, rns


@pytest.fixture(scope="module")
def keys():
    return [rsa.generate(1024), rsa.generate(2048)]


def _verify_rns_direct(items):
    ctx = rns.context()
    rows, sig_d, em_d = [], [], []
    for message, sig_bytes, key in items:
        rows.append(ctx.key_rows(key.n))
        sig_d.append(limb.int_to_limbs(int.from_bytes(sig_bytes, "big"), 128))
        em_d.append(
            limb.int_to_limbs(
                rsa.emsa_pkcs1v15_sha256(message, key.size_bytes), 128
            )
        )
    key_rows = rns.stack_key_rows(rows)
    return np.asarray(
        rns.verify_e65537_rns(np.stack(sig_d), np.stack(em_d), key_rows)
    )


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_rns_matches_oracle_mixed_keys(keys):
    items = []
    want = []
    for i in range(6):
        key = keys[i % 2]
        m = b"rns-oracle-%d" % i
        sig = rsa.sign(m, key)
        if i == 2:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])  # flipped bit
        if i == 4:
            m = b"tampered"
            # signature stays for the original message
            sig = rsa.sign(b"rns-oracle-4", key)
        items.append((m, sig, key.public))
        want.append(rsa.verify_host(m, sig, key.public))
    got = _verify_rns_direct(items)
    assert list(got) == want
    assert want == [True, True, False, True, False, True]


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_rns_wrong_key_rejected(keys):
    m = b"cross"
    sig = rsa.sign(m, keys[0])
    got = _verify_rns_direct([(m, sig, keys[1].public)] * 2)
    assert not got.any()


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_verifier_domain_backends_agree(keys):
    """All three device backends (rns / limb / pallas) return identical
    verdicts on the same adversarial batch."""
    key = keys[0]
    sig = rsa.sign(b"m", key)
    items = [
        (b"m", sig, key.public),
        (b"x", sig, key.public),
        (b"m", sig, keys[1].public),
        (b"m", (key.n + 5).to_bytes(key.size_bytes + 1, "big"), key.public),
    ]
    results = {}
    for backend in ("rns", "limb", "pallas"):
        dom = rsa.VerifierDomain(host_threshold=0, backend=backend)
        results[backend] = list(dom.verify_batch(items))
    assert (
        results["rns"] == results["limb"] == results["pallas"]
        == [True, False, False, False]
    )


def test_backend_name_validated():
    with pytest.raises(ValueError):
        rsa.VerifierDomain(backend="rsn")


def test_hostile_modulus_falls_back(keys):
    """A modulus sharing a factor with a channel prime cannot ride the
    RNS path; the verifier must fall back per item, not crash."""
    ctx = rns.context()
    p0 = ctx.pb[0]
    hostile_n = p0 * ((1 << 2000) // p0 + 1)  # divisible by a channel prime
    if hostile_n % 2 == 0:
        hostile_n += p0
    assert ctx.key_rows(hostile_n) is None
    dom = rsa.VerifierDomain(host_threshold=0, backend="rns")
    key = keys[0]
    sig = rsa.sign(b"m", key)
    items = [
        (b"m", sig, key.public),
        (b"m", sig, rsa.PublicKey(n=hostile_n)),
    ]
    ok = dom.verify_batch(items)
    assert ok[0] and not ok[1]


def test_rns_padding_rows_never_verify(keys):
    """Bucket padding uses sig=0 rows; a batch of 1 real item padded to
    256 must return exactly one True."""
    dom = rsa.VerifierDomain(host_threshold=0, backend="rns")
    key = keys[1]
    sig = rsa.sign(b"solo", key)
    ok = dom.verify_batch([(b"solo", sig, key.public)])
    assert ok.shape == (1,) and ok[0]


def test_pallas_auto_gated_on_per_chain_proof(monkeypatch, tmp_path):
    """Auto mode routes through a fused Pallas chain only on a single
    real TPU chip AND after that chain has a proven-completion marker;
    a verify-only proof must not arm the pow chain (r5 code review)."""
    monkeypatch.setattr(rns.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(rns.jax, "devices", lambda: ["chip0"])
    monkeypatch.setattr(
        rns, "_pallas_proven_path",
        lambda which: str(tmp_path / f"marker_{which}"),
    )
    rns._pallas_proven.cache_clear()
    try:
        # No marker: auto never selects pallas, even on "tpu".
        assert rns._use_pallas("BFTKV_RNS_POW_BACKEND") is False
        assert rns._use_pallas("BFTKV_RNS_VERIFY_BACKEND") is False
        # A verify proof arms verify only.
        (tmp_path / "marker_verify").touch()
        rns._pallas_proven.cache_clear()
        assert rns._use_pallas("BFTKV_RNS_VERIFY_BACKEND") is True
        assert rns._use_pallas("BFTKV_RNS_POW_BACKEND") is False
        # Forced modes ignore the marker in both directions.
        monkeypatch.setenv("BFTKV_RNS_VERIFY_BACKEND", "xla")
        assert rns._use_pallas("BFTKV_RNS_VERIFY_BACKEND") is False
        monkeypatch.setenv("BFTKV_RNS_POW_BACKEND", "pallas")
        assert rns._use_pallas("BFTKV_RNS_POW_BACKEND") is True
        # Multi-chip pools stay on the sharded XLA path in auto.
        monkeypatch.delenv("BFTKV_RNS_VERIFY_BACKEND")
        monkeypatch.setattr(rns.jax, "devices", lambda: ["c0", "c1"])
        (tmp_path / "marker_pow").touch()
        rns._pallas_proven.cache_clear()
        assert rns._use_pallas("BFTKV_RNS_VERIFY_BACKEND") is False
    finally:
        rns._pallas_proven.cache_clear()


def test_pallas_mark_proven_no_marker_off_tpu(monkeypatch, tmp_path):
    """Status flips to ok everywhere, but the cross-process marker is
    only written where it was actually proven: on a real TPU backend."""
    monkeypatch.setattr(
        rns, "_pallas_proven_path",
        lambda which: str(tmp_path / f"marker_{which}"),
    )
    monkeypatch.setattr(rns, "_PALLAS_STATUS", {"pow": "unused", "verify": "unused"})
    rns._pallas_mark_proven("pow")  # backend is cpu under the test env
    assert rns.pallas_status()["pow"] == "ok"
    assert not (tmp_path / "marker_pow").exists()
    try:
        monkeypatch.setattr(rns.jax, "default_backend", lambda: "tpu")
        rns._pallas_mark_proven("verify")
        assert (tmp_path / "marker_verify").exists()
        # Early return: a second call must not touch the path again.
        (tmp_path / "marker_verify").unlink()
        rns._pallas_mark_proven("verify")
        assert not (tmp_path / "marker_verify").exists()
    finally:
        rns._pallas_proven.cache_clear()
