"""Shared verify sidecar: protocol round-trip, cross-client coalescing,
fallback on sidecar death, and a live cluster routed through it."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from bftkv_tpu.cmd import verify_sidecar
from bftkv_tpu.crypto import rsa
from bftkv_tpu.crypto.remote_verify import RemoteVerifierDomain
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.ops import dispatch

_PORT = [18900]


def _port() -> int:
    _PORT[0] += 1
    return _PORT[0]


@pytest.fixture()
def sidecar():
    addr = f"127.0.0.1:{_port()}"
    srv, t = verify_sidecar.serve(addr, max_batch=512)
    yield addr, srv
    srv.dispatcher.stop()
    srv.shutdown()


def _items(n: int, key=None, tamper: set | None = None):
    key = key or rsa.generate(1024)
    out = []
    for i in range(n):
        msg = b"sc-%d" % i
        sig = rsa.sign(msg, key)
        if tamper and i in tamper:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        out.append((msg, sig, key.public))
    return out, key


def test_request_codec_roundtrip():
    items, _ = _items(3)
    decoded = verify_sidecar.decode_request(
        verify_sidecar.encode_request(items)
    )
    for (m1, s1, k1), (m2, s2, k2) in zip(items, decoded):
        assert (m1, s1, k1.n, k1.e) == (m2, s2, k2.n, k2.e)


def test_remote_verify_matches_local(sidecar):
    addr, _srv = sidecar
    items, _ = _items(8, tamper={2, 5})
    rd = RemoteVerifierDomain(addr)
    got = rd.verify_batch(items)
    want = [i not in (2, 5) for i in range(8)]
    assert list(got) == want
    assert metrics.snapshot().get("verify.remote", 0) >= 8


def test_sidecar_coalesces_across_clients():
    # A long collection window makes the cross-client coalescing
    # deterministic on loaded machines (the default 2 ms window would
    # race thread start skew).
    addr = f"127.0.0.1:{_port()}"
    srv, _t = verify_sidecar.serve(addr, max_batch=512, max_wait=0.5)
    items, key = _items(16)
    metrics.reset()
    domains = [RemoteVerifierDomain(addr) for _ in range(4)]
    results = [None] * 4

    def run(i):
        results[i] = domains[i].verify_batch(items)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    try:
        assert all(np.asarray(r).all() for r in results)
        snap = metrics.snapshot()
        # 4 clients x 16 items landed in fewer flushes than clients:
        # the sidecar's dispatcher coalesced across connections.
        assert snap.get("dispatch.items", 0) >= 64
        assert snap.get("dispatch.flushes", 64) < 4
    finally:
        srv.dispatcher.stop()
        srv.shutdown()


def test_fallback_when_sidecar_dies(sidecar):
    addr, srv = sidecar
    items, _ = _items(4)
    rd = RemoteVerifierDomain(addr)
    assert list(rd.verify_batch(items)) == [True] * 4
    srv.dispatcher.stop()
    srv.shutdown()
    srv.server_close()
    # The established connection keeps serving (threading server with
    # live handler threads) — graceful, but death means severing it too.
    rd._close()
    metrics.reset()
    assert list(rd.verify_batch(items)) == [True] * 4  # local fallback
    assert metrics.snapshot().get("verify.remote_fallback", 0) == 4


def test_cluster_verifies_through_sidecar(sidecar):
    from tests.cluster_utils import start_cluster

    addr, srv = sidecar
    c = start_cluster(4, 1, 4)
    metrics.reset()
    dispatch.install(
        dispatch.VerifyDispatcher(verifier=RemoteVerifierDomain(addr))
    )
    try:
        cl = c.clients[0]
        items = [(b"sc/%d" % i, b"v%d" % i) for i in range(8)]
        assert cl.write_many(items) == [None] * 8
        for v, val in items:
            assert cl.read(v) == val
        snap = metrics.snapshot()
        # The protocol's collective verifies actually crossed the wire
        # (RemoteVerifierDomain only engages above host_threshold, so
        # force it by checking either remote or local-fallback-free).
        assert snap.get("verify.remote", 0) + snap.get("verify.host", 0) > 0
        assert snap.get("verify.remote_fallback", 0) == 0
    finally:
        dispatch.uninstall_all()
        c.stop()
