"""Shared verify sidecar: protocol round-trip, cross-client coalescing,
fallback on sidecar death, and a live cluster routed through it."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from bftkv_tpu.cmd import verify_sidecar
from bftkv_tpu.crypto import rsa
from bftkv_tpu.crypto.remote_verify import RemoteVerifierDomain
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu.ops import dispatch

_PORT = [18900]


def _port() -> int:
    _PORT[0] += 1
    return _PORT[0]


@pytest.fixture()
def sidecar():
    addr = f"127.0.0.1:{_port()}"
    srv, t = verify_sidecar.serve(addr, max_batch=512)
    yield addr, srv
    srv.dispatcher.stop()
    srv.shutdown()


def _items(n: int, key=None, tamper: set | None = None):
    key = key or rsa.generate(1024)
    out = []
    for i in range(n):
        msg = b"sc-%d" % i
        sig = rsa.sign(msg, key)
        if tamper and i in tamper:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        out.append((msg, sig, key.public))
    return out, key


def test_request_codec_roundtrip():
    items, _ = _items(3)
    decoded = verify_sidecar.decode_request(
        verify_sidecar.encode_request(items)
    )
    for (m1, s1, k1), (m2, s2, k2) in zip(items, decoded):
        assert (m1, s1, k1.n, k1.e) == (m2, s2, k2.n, k2.e)


def test_remote_verify_matches_local(sidecar):
    addr, _srv = sidecar
    items, _ = _items(8, tamper={2, 5})
    rd = RemoteVerifierDomain(addr)
    got = rd.verify_batch(items)
    want = [i not in (2, 5) for i in range(8)]
    assert list(got) == want
    assert metrics.snapshot().get("verify.remote", 0) >= 8


def test_sidecar_coalesces_across_clients():
    # A long collection window makes the cross-client coalescing
    # deterministic on loaded machines (the default 2 ms window would
    # race thread start skew).
    addr = f"127.0.0.1:{_port()}"
    srv, _t = verify_sidecar.serve(addr, max_batch=512, max_wait=0.5)
    items, key = _items(16)
    metrics.reset()
    domains = [RemoteVerifierDomain(addr) for _ in range(4)]
    results = [None] * 4

    def run(i):
        results[i] = domains[i].verify_batch(items)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    try:
        assert all(np.asarray(r).all() for r in results)
        snap = metrics.snapshot()
        # 4 clients x 16 items landed in fewer flushes than clients:
        # the sidecar's dispatcher coalesced across connections.
        assert snap.get("dispatch.items", 0) >= 64
        assert snap.get("dispatch.flushes", 64) < 4
    finally:
        srv.dispatcher.stop()
        srv.shutdown()


def test_fallback_when_sidecar_dies(sidecar):
    addr, srv = sidecar
    items, _ = _items(4)
    rd = RemoteVerifierDomain(addr)
    assert list(rd.verify_batch(items)) == [True] * 4
    srv.dispatcher.stop()
    srv.shutdown()
    srv.server_close()
    # The established connection keeps serving (threading server with
    # live handler threads) — graceful, but death means severing it too.
    rd._close()
    metrics.reset()
    assert list(rd.verify_batch(items)) == [True] * 4  # local fallback
    assert metrics.snapshot().get("verify.remote_fallback", 0) == 4


def test_internal_error_falls_back_locally(sidecar):
    # A dispatcher failure (dead/hung accelerator) must NOT surface as
    # "all signatures invalid" — that would be a cluster-wide liveness
    # outage.  The sidecar replies zero-length (count mismatch) and the
    # client verifies locally.
    addr, srv = sidecar

    def boom(items):
        raise RuntimeError("accelerator gone")

    srv.dispatcher.verify, orig = boom, srv.dispatcher.verify
    try:
        items, _ = _items(4, tamper={1})
        rd = RemoteVerifierDomain(addr)
        metrics.reset()
        assert list(rd.verify_batch(items)) == [True, False, True, True]
        assert metrics.snapshot().get("verify.remote_fallback", 0) == 4
    finally:
        srv.dispatcher.verify = orig


def test_malformed_frame_still_fails_closed(sidecar):
    # Hostile bytes (not an internal error) keep the all-fail reply:
    # attacker-controlled input never produces a "valid" verdict and
    # never pushes work onto the local fallback.
    import socket as socketmod
    import struct

    addr, _srv = sidecar
    host, _, port = addr.rpartition(":")
    s = socketmod.create_connection((host, int(port)), timeout=10)
    body = struct.pack(">I", 3) + b"\xff garbage"
    s.sendall(struct.pack(">I", len(body)) + body)
    (ln,) = struct.unpack(">I", s.recv(4))
    assert ln == 3 and s.recv(3) == b"\x00\x00\x00"
    s.close()


def test_unix_socket_sidecar(tmp_path):
    import os
    import stat

    addr = f"unix:{tmp_path}/verify.sock"
    srv, _t = verify_sidecar.serve(addr, max_batch=512)
    try:
        mode = os.stat(f"{tmp_path}/verify.sock").st_mode
        assert stat.S_IMODE(mode) == 0o600
        items, _ = _items(6, tamper={0})
        rd = RemoteVerifierDomain(addr)
        assert list(rd.verify_batch(items)) == [False] + [True] * 5
        assert metrics.snapshot().get("verify.remote", 0) >= 6
    finally:
        srv.dispatcher.stop()
        srv.shutdown()


def test_hmac_roundtrip_and_fail_closed():
    secret = b"s" * 32
    addr = f"127.0.0.1:{_port()}"
    srv, _t = verify_sidecar.serve(addr, max_batch=512, secret=secret)
    try:
        items, _ = _items(4, tamper={3})
        rd = RemoteVerifierDomain(addr, secret=secret)
        assert list(rd.verify_batch(items)) == [True, True, True, False]
        assert metrics.snapshot().get("verify.remote", 0) >= 4

        # Client without the secret: the sidecar drops the connection;
        # verification degrades to local, never to trusting the wire.
        rd2 = RemoteVerifierDomain(addr)
        metrics.reset()
        assert list(rd2.verify_batch(items)) == [True, True, True, False]
        assert metrics.snapshot().get("verify.remote_fallback", 0) == 4
    finally:
        srv.dispatcher.stop()
        srv.shutdown()


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_port_squatter_verdicts_rejected():
    # An impostor on the sidecar port returns all-true without knowing
    # the secret; a keyed client must fail closed (local verify), not
    # accept forged verdicts.  This is ADVICE r3 finding 2's scenario.
    import socket as socketmod
    import struct
    import threading as th

    secret = b"k" * 32
    port = _port()
    lsock = socketmod.socket()
    lsock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", port))
    lsock.listen(1)

    def impostor():
        # Serve every reconnect attempt: the client retries once on a
        # fresh socket, and only the MAC check may reject the forgery —
        # a one-shot impostor would leave the retry stalling on the
        # listen backlog and the test would pass via timeout instead.
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            try:
                hdr = conn.recv(4)
                if len(hdr) < 4:
                    continue
                (ln,) = struct.unpack(">I", hdr)
                got = b""
                while len(got) < ln:
                    part = conn.recv(ln - len(got))
                    if not part:
                        break
                    got += part
                # forged v2 "all valid" reply — ST_OK + one verdict
                # byte per item — with a garbage tag of the right
                # length; only the response MAC can reject this shape
                out = (
                    bytes([verify_sidecar.ST_OK])
                    + b"\x01" * 3
                    + b"\x00" * verify_sidecar.TAG_LEN
                )
                conn.sendall(struct.pack(">I", len(out)) + out)
            finally:
                conn.close()

    t = th.Thread(target=impostor, daemon=True)
    t.start()
    try:
        items, _ = _items(3, tamper={0})
        rd = RemoteVerifierDomain(f"127.0.0.1:{port}", secret=secret)
        metrics.reset()
        # Forged verdict says [T,T,T]; fail-closed local verify says no.
        assert list(rd.verify_batch(items)) == [False, True, True]
        assert metrics.snapshot().get("verify.remote_bad_mac", 0) >= 1
    finally:
        lsock.close()


def test_cluster_verifies_through_sidecar(sidecar, monkeypatch):
    from tests.cluster_utils import start_cluster

    from bftkv_tpu.crypto import vcache

    # The verify memo would satisfy this in-process cluster's repeat
    # verifies from cache; disable it so protocol verifies actually
    # reach the remote sidecar this test observes.
    monkeypatch.setattr(vcache, "_ENABLED", False)
    addr, srv = sidecar
    c = start_cluster(4, 1, 4)
    metrics.reset()
    dispatch.install(
        dispatch.VerifyDispatcher(
            verifier=RemoteVerifierDomain(addr), calibrate=False
        )
    )
    try:
        cl = c.clients[0]
        items = [(b"sc/%d" % i, b"v%d" % i) for i in range(8)]
        assert cl.write_many(items) == [None] * 8
        for v, val in items:
            assert cl.read(v) == val
        snap = metrics.snapshot()
        # The protocol's collective verifies actually crossed the wire
        # (RemoteVerifierDomain only engages above host_threshold, so
        # force it by checking either remote or local-fallback-free).
        assert snap.get("verify.remote", 0) + snap.get("verify.host", 0) > 0
        assert snap.get("verify.remote_fallback", 0) == 0
    finally:
        dispatch.uninstall_all()
        c.stop()
