"""Edge gateway tier (tier-1): certified cache soundness, coalescing,
shedding, invalidation, and horizontal stacking.

The load-bearing assertions are the soundness ones: a poisoned fill —
bytes whose collective signature does not verify against the OWNER
quorum, whether tampered or minted by the wrong shard's clique — is
never cached, never served, and counted; and the GatewayClient refuses
served bytes it cannot verify itself, so even a compromised gateway
cannot forge a read (DESIGN.md §14.2).
"""

import threading
import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu.errors import (
    ERR_GATEWAY_OVERLOADED,
    ERR_UNCERTIFIED_RECORD,
)
from bftkv_tpu.gateway import CertifiedCache, GatewayClient
from bftkv_tpu.metrics import registry as metrics
from tests.cluster_utils import start_cluster


@pytest.fixture(scope="module")
def cluster():
    cl = start_cluster(4, 1, 4, bits=1024, n_gateways=2)
    yield cl
    cl.stop()


@pytest.fixture()
def gwc(cluster):
    return cluster.gateway_client(0)


def snap(name: str) -> float:
    return metrics.snapshot().get(name, 0)


# -- cache unit behavior ----------------------------------------------------


def test_cache_newer_t_wins_and_ttl():
    c = CertifiedCache(max_entries=8, ttl=0.05)
    assert c.put(b"x", 3, b"rec3")
    assert not c.put(b"x", 2, b"rec2")  # stale fill loses
    assert c.get(b"x").record == b"rec3"
    assert c.put(b"x", 4, b"rec4")
    time.sleep(0.06)
    assert c.get(b"x") is None  # expired
    assert c.get(b"x", allow_stale=True).record == b"rec4"


def test_cache_lru_bound_and_bucket_invalidation():
    c = CertifiedCache(max_entries=2, ttl=60)
    c.put(b"a", 1, b"ra")
    c.put(b"b", 1, b"rb")
    c.put(b"c", 1, b"rc")  # evicts a (LRU)
    assert c.get(b"a") is None
    assert len(c) == 2
    from bftkv_tpu.sync.digest import bucket_of

    assert c.invalidate_bucket(bucket_of(b"b")) >= 1
    assert c.get(b"b") is None


# -- read-through + write path ---------------------------------------------


def test_certified_read_through_and_hit(cluster, gwc):
    c = cluster.clients[0]
    c.write(b"gwt/direct", b"v1")
    c.drain_tails()
    h0, m0 = snap("gateway.cache.hits"), snap("gateway.cache.misses")
    assert gwc.read(b"gwt/direct") == b"v1"  # fill (miss)
    assert gwc.read(b"gwt/direct") == b"v1"  # cache hit
    assert snap("gateway.cache.misses") == m0 + 1
    assert snap("gateway.cache.hits") == h0 + 1


def test_absent_key_not_cached(gwc):
    assert gwc.read(b"gwt/never-written") is None
    assert gwc.read(b"gwt/never-written") is None


def test_write_through_and_invalidation_on_backfill(cluster, gwc):
    """A gateway write invalidates the stale entry and the certified
    back-fill re-fills the cache — the subsequent read is a HIT on the
    new value, no quorum fill."""
    gwc.write(b"gwt/w", b"old")
    assert gwc.read(b"gwt/w") == b"old"
    f0 = snap("gateway.cache.fills")
    b0 = snap("gateway.cache.backfill_puts")
    gwc.write(b"gwt/w", b"new")
    assert snap("gateway.cache.backfill_puts") > b0
    assert gwc.read(b"gwt/w") == b"new"
    assert snap("gateway.cache.fills") == f0  # served from write-through


def test_same_variable_burst_coalesces(cluster, gwc):
    c0 = snap("gateway.write.coalesced")
    ws0 = snap("server.write_sign.count")
    errs: list = []

    def w(i):
        try:
            gwc.write(b"gwt/burst", b"b%d" % i)
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=w, args=(i,)) for i in range(10)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    coalesced = snap("gateway.write.coalesced") - c0
    assert coalesced >= 1
    # The burst cost fewer WRITE_SIGN fan-outs than callers: at most
    # (callers - coalesced) rounds × quorum size posts crossed servers.
    got = gwc.read(b"gwt/burst")
    assert got is not None and got.startswith(b"b")
    assert snap("server.write_sign.count") - ws0 <= (10 - coalesced) * 8


def test_cross_variable_burst_batches(cluster, gwc):
    r0 = snap("gateway.write.batched_rounds")
    errs: list = []

    def w(i):
        try:
            gwc.write(b"gwt/multi/%d" % i, b"m%d" % i)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=w, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    for i in range(6):
        assert gwc.read(b"gwt/multi/%d" % i) == b"m%d" % i
    assert snap("gateway.write.batched_rounds") > r0


# -- admission / shedding ---------------------------------------------------


def test_shed_path(cluster, gwc):
    gw = cluster.gateways[0]
    old = (gw.admission.max_inflight, gw.admission.max_queue)
    s0 = snap("gateway.shed{op=read}")
    gw.admission.max_inflight = 0
    gw.admission.max_queue = 0
    try:
        # Both gateways must shed or the HRW failover masks the test.
        for g in cluster.gateways:
            g.admission.max_inflight = 0
            g.admission.max_queue = 0
        with pytest.raises(ERR_GATEWAY_OVERLOADED):
            gwc.read(b"gwt/shed-me")
    finally:
        for g in cluster.gateways:
            g.admission.max_inflight, g.admission.max_queue = old
    assert snap("gateway.shed{op=read}") > s0
    # Cache hits bypass admission entirely.
    gwc.write(b"gwt/shed-hit", b"v")
    assert gwc.read(b"gwt/shed-hit") == b"v"
    for g in cluster.gateways:
        g.admission.max_inflight = 0
        g.admission.max_queue = 0
    try:
        assert gwc.read(b"gwt/shed-hit") == b"v"
    finally:
        for g in cluster.gateways:
            g.admission.max_inflight, g.admission.max_queue = old


# -- cache soundness: poisoned fills ---------------------------------------


def test_poisoned_fill_never_served(cluster, gwc, monkeypatch):
    """A fill whose bytes were tampered with (value flipped, signature
    kept) must fail the gateway's owner-quorum verification: counted,
    never cached, never served."""
    gw = cluster.gateways[0]
    c = cluster.clients[0]
    c.write(b"gwt/poison", b"honest")
    c.drain_tails()
    value, t, record = gw.client.read_certified(b"gwt/poison")
    assert value == b"honest" and record is not None
    p = pkt.parse(record)
    forged = pkt.serialize(
        p.variable, b"FORGED!", p.t, p.sig, p.ss, p.auth
    )

    for g in cluster.gateways:
        monkeypatch.setattr(
            g.client,
            "read_certified",
            lambda variable, proof=None: (b"FORGED!", t, forged),
        )
    v0 = snap("gateway.cache.verify_fail")
    with pytest.raises(ERR_UNCERTIFIED_RECORD):
        gwc.read(b"gwt/poison")
    assert snap("gateway.cache.verify_fail") > v0
    assert gw.cache.get(b"gwt/poison") is None
    monkeypatch.undo()
    assert gwc.read(b"gwt/poison") == b"honest"


def test_poisoned_backfill_never_cached(cluster):
    """The write-through (on_certified) plane crosses the same gate."""
    gw = cluster.gateways[0]
    v0 = snap("gateway.cache.verify_fail")
    gw._on_certified(b"gwt/bogus", b"\x00garbage-not-a-record")
    assert snap("gateway.cache.verify_fail") > v0
    assert gw.cache.get(b"gwt/bogus") is None


def test_client_side_verification(cluster, gwc):
    """Even a compromised gateway cannot forge a read: the
    GatewayClient re-verifies the served record itself."""
    c = cluster.clients[0]
    c.write(b"gwt/cliver", b"real")
    c.drain_tails()
    _v, _t, raw = gwc.read_record(b"gwt/cliver")
    p = pkt.parse(raw)
    forged = pkt.serialize(p.variable, b"evil", p.t, p.sig, p.ss)
    with pytest.raises(ERR_UNCERTIFIED_RECORD):
        gwc._check_served(b"gwt/cliver", forged)
    # and a record for ANOTHER variable is rejected by name binding
    with pytest.raises(ERR_UNCERTIFIED_RECORD):
        gwc._check_served(b"gwt/other", raw)


def test_wrong_quorum_signature_rejected():
    """A collective signature minted by a clique that does NOT own the
    variable is unusable: the certified-fill rule verifies against the
    owner quorum, where foreign signers can never reach sufficiency."""
    cl = start_cluster(4, 1, 4, bits=1024, n_shards=2, n_gateways=1)
    try:
        gw = cl.gateways[0]
        c = cl.clients[0]
        shard_of = c.qs.shard_of
        # a variable owned by shard 0, and shard 1's servers
        var = next(
            b"gwt/wq/%d" % i
            for i in range(4096)
            if shard_of(b"gwt/wq/%d" % i) == 0
        )
        foreign = [
            s for s in cl.servers if s.qs.my_shard() == 1
        ]
        assert foreign
        # Forge: writer-sign <x,v,t> as the user, then collect a
        # "collective" signature from the WRONG clique's signers.
        tbs = pkt.serialize(var, b"squat", 1, nfields=3)
        sig = c.crypt.signer.issue(tbs)
        tbss = pkt.serialize(var, b"squat", 1, sig, nfields=4)
        from bftkv_tpu.crypto import signature as sigmod

        entries = []
        for s in foreign:
            share = s.crypt.collective.sign(s.crypt.signer, tbss)
            entries.extend(sigmod.parse_entries(share.data))
        ss = pkt.SignaturePacket(
            type=pkt.SIGNATURE_TYPE_NATIVE,
            version=1,
            completed=True,
            data=sigmod.serialize_entries(entries),
        )
        forged = pkt.serialize(var, b"squat", 1, sig, ss)
        v0 = snap("gateway.cache.verify_fail")
        with pytest.raises(ERR_UNCERTIFIED_RECORD):
            gw._verify_certified(var, forged)
        assert snap("gateway.cache.verify_fail") > v0
        # Sanity — the same shares DO satisfy the minting clique's own
        # sufficiency, so the rejection above is quorum BINDING (the
        # owner clique's threshold), not malformedness.
        foreign_var = next(
            b"gwt/wq/%d" % i
            for i in range(4096)
            if shard_of(b"gwt/wq/%d" % i) == 1
        )
        qa1 = qm.choose_quorum_for(gw.qs, foreign_var, qm.AUTH)
        signers = [
            gw.crypt.keyring.get(sid)
            for sid, _sb in sigmod.parse_entries(ss.data)
        ]
        assert qa1.is_sufficient([s for s in signers if s is not None])
    finally:
        cl.stop()


# -- anti-entropy invalidation ---------------------------------------------


def test_sync_invalidation(cluster, gwc):
    # The entry lives on the HRW-primary gateway for this variable.
    primary_id = gwc._route(b"gwt/sync")[0].id
    gw = next(
        g
        for g in cluster.gateways
        if g.self_node.get_self_id() == primary_id
    )
    c = cluster.clients[0]
    c.write(b"gwt/sync", b"old")
    c.drain_tails()
    assert gwc.read(b"gwt/sync") == b"old"
    gw.sync_invalidate_round()  # baseline digests
    c.write(b"gwt/sync", b"new")
    c.drain_tails()
    # TTL has not lapsed: without the sync plane this read is stale.
    assert gwc.read(b"gwt/sync") == b"old"
    i0 = snap("gateway.cache.sync_invalidated")
    assert gw.sync_invalidate_round() >= 1
    assert snap("gateway.cache.sync_invalidated") > i0
    assert gwc.read(b"gwt/sync") == b"new"


# -- horizontal stacking ----------------------------------------------------


def test_hrw_routing_is_sticky(cluster, gwc):
    order1 = [g.id for g in gwc._route(b"gwt/route-x")]
    order2 = [g.id for g in gwc._route(b"gwt/route-x")]
    assert order1 == order2
    assert len(set(order1)) == 2
    # different variables spread across gateways
    firsts = {gwc._route(b"gwt/route-%d" % i)[0].id for i in range(32)}
    assert len(firsts) == 2


def test_gateway_failover(cluster):
    """A dead gateway is routed around — the tier is stateless."""
    gwc = cluster.gateway_client(0)
    gwc.write(b"gwt/fo", b"v")
    primary_id = gwc._route(b"gwt/fo")[0].id
    primary = next(
        g
        for g in cluster.gateways
        if g.self_node.get_self_id() == primary_id
    )
    primary.tr.stop()
    try:
        f0 = snap("gateway.client.failover")
        assert gwc.read(b"gwt/fo") == b"v"
        assert snap("gateway.client.failover") > f0
    finally:
        primary.start(primary.address)


# -- fleet integration ------------------------------------------------------


def test_fleet_scrapes_gateways(cluster, gwc):
    from bftkv_tpu import trace as trmod
    from bftkv_tpu.obs import FleetCollector, LocalSource

    sources = [
        LocalSource(s.self_node.name, lambda s=s: s)
        for s in cluster.all_servers
    ]
    for gw in cluster.gateways:
        sources.append(
            LocalSource(gw.self_node.name, lambda gw=gw: gw)
        )
    col = FleetCollector(
        sources, local_metrics=metrics, local_tracer=trmod.tracer
    )
    col.scrape_once()
    # shed once so the delta fires an anomaly
    for g in cluster.gateways:
        g.admission.max_inflight = 0
        g.admission.max_queue = 0
    try:
        with pytest.raises(ERR_GATEWAY_OVERLOADED):
            gwc.read(b"gwt/fleet-shed")
    finally:
        for g in cluster.gateways:
            g.admission.max_inflight = 64
            g.admission.max_queue = 128
    doc = col.scrape_once()
    assert set(doc["gateways"]) == {"gw01", "gw02"}
    assert all(
        g["status"] == "up" for g in doc["gateways"].values()
    )
    # gateways never enter the clique f-budget
    for sd in doc["shards"].values():
        names = {m["name"] for m in sd["members"]}
        assert not names & {"gw01", "gw02"}
        assert sd["f_budget"]["remaining"] == sd["f_budget"]["f"]
    kinds = [a["kind"] for a in doc["anomalies"]]
    assert "gateway_shed" in kinds
    assert "bftkv_fleet_gateways_up" in col.prometheus()
