"""Byzantine fault-injection harness: malicious server / storage /
client, by subclassing — never mocking — exactly as the reference does
(reference: protocol/malserver_test.go:23-194, malstorage_test.go:19-115,
malclient_test.go:83-189).

The *behaviors* now live in :mod:`bftkv_tpu.faults.byzantine` as
failpoint handler programs, shared with the chaos nemesis; this module
keeps the reference-shaped subclass API as a shim over them so the
existing Byzantine suite and chaos runs exercise one mechanism."""

from __future__ import annotations

from bftkv_tpu import packet as pkt
from bftkv_tpu import quorum as qm
from bftkv_tpu import transport as tp
from bftkv_tpu.errors import ERR_INSUFFICIENT_NUMBER_OF_QUORUM
from bftkv_tpu.faults import byzantine as byz
from bftkv_tpu.protocol import majority_error
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.protocol.server import Server
from bftkv_tpu.storage.memkv import MemStorage


class MalStorage(MemStorage):
    """Keeps *conflicting* values in a side area instead of refusing
    them (reference: malstorage_test.go:19-115)."""

    def __init__(self):
        super().__init__()
        self.mal: dict[tuple[bytes, int], list[bytes]] = {}

    def mal_write(self, variable: bytes, t: int, value: bytes) -> None:
        self.mal.setdefault((variable, t), []).append(value)
        # the latest conflicting write shadows the honest record
        super().write(variable, t, value)


class MalServer(Server):
    """A colluding server: for addresses in ``mal_addresses`` it signs
    anything (no writer-sig verify, no quorum certificate, no
    equivocation check) and stores unverified double-writes
    (reference: malserver_test.go:55-116)."""

    mal_addresses: set[str] = set()

    @property
    def _is_mal(self) -> bool:
        return self.self_node.address in self.mal_addresses

    # Behaviors delegate to the shared failpoint programs
    # (bftkv_tpu/faults/byzantine.py) — one implementation serves both
    # this subclass harness and the chaos nemesis.

    def _sign(self, req: bytes, peer, sender):
        if not self._is_mal:
            return super()._sign(req, peer, sender)
        # sign whatever arrives (reference: malSign, :64-89)
        return byz.sign_anything(self, tp.SIGN, req, peer, sender)

    def _write(self, req: bytes, peer, sender):
        if not self._is_mal:
            return super()._write(req, peer, sender)
        # store without any verification (reference: malWrite, :91-112)
        return byz.store_unverified(self, tp.WRITE, req, peer, sender)

    def _write_sign(self, req: bytes, peer, sender):
        if not self._is_mal:
            return super()._write_sign(req, peer, sender)
        # the collapsed round faces the same adversary: sign + store
        # anything, ack with a genuine share
        return byz.write_sign_anything(self, tp.WRITE_SIGN, req, peer, sender)

    # The batch pipeline must face the same adversary: a colluder signs
    # and stores every item of a batch without any verification.

    def _batch_sign(self, req: bytes, peer, sender):
        if not self._is_mal:
            return super()._batch_sign(req, peer, sender)
        return byz.batch_sign_anything(self, tp.BATCH_SIGN, req, peer, sender)

    def _batch_time(self, req: bytes, peer, sender):
        if not self._is_mal:
            return super()._batch_time(req, peer, sender)
        return byz.batch_time_skew(self, tp.BATCH_TIME, req, peer, sender)

    def _batch_write(self, req: bytes, peer, sender):
        if not self._is_mal:
            return super()._batch_write(req, peer, sender)
        return byz.batch_store_unverified(
            self, tp.BATCH_WRITE, req, peer, sender
        )


class MalClient(Client):
    """The textbook equivocator: writes <x,t,v> to one half of each
    quorum plus the colluders, and <x,t,v'> to the other half plus the
    colluders (reference: malclient_test.go:83-189)."""

    def __init__(self, *args, mal_addresses: set[str] = frozenset(), **kw):
        super().__init__(*args, **kw)
        self.mal_addresses = set(mal_addresses)

    def _split(self, nodes: list) -> tuple[list, list, list]:
        """(honest-half-1, honest-half-2, colluders) — honest nodes
        interleaved (reference: getGroup, malclient_test.go:61-81)."""
        h1: list = []
        h2: list = []
        colluders: list = []
        flip = True
        for n in nodes:
            if n.address in self.mal_addresses:
                colluders.append(n)
            elif flip:
                h1.append(n)
                flip = False
            else:
                h2.append(n)
                flip = True
        return h1, h2, colluders

    def write_mal(self, variable: bytes, v1: bytes, v2: bytes) -> None:
        """Equivocate: both values at the same timestamp
        (reference: WriteMal, malclient_test.go:83-127)."""
        q = self.qs.choose_quorum(qm.AUTH)
        maxt = 0
        actives: list = []
        failure: list = []

        def cb(res: tp.MulticastResponse) -> bool:
            nonlocal maxt
            if res.err is None and res.data and len(res.data) <= 8:
                t = int.from_bytes(res.data, "big")
                maxt = max(maxt, t)
                actives.append(res.peer)
                return q.is_threshold(actives)
            failure.append(res.peer)
            return q.reject(failure)

        self.tr.multicast(tp.TIME, q.nodes(), variable, cb)
        if not q.is_threshold(actives):
            raise ERR_INSUFFICIENT_NUMBER_OF_QUORUM
        t = maxt + 1

        s1, s2, smal = self._split(q.nodes())
        rq = self.qs.choose_quorum(qm.WRITE)
        r1, r2, rmal = self._split(rq.nodes())

        self._sign_and_write(s1 + smal, r1 + rmal, variable, v1, t, q)
        self._sign_and_write(s2 + smal, r2 + rmal, variable, v2, t, q)

    def _sign_and_write(
        self, sign_group, write_group, variable, value, t, q
    ) -> None:
        """(reference: signAndWrite, malclient_test.go:129-189)."""
        tbs = pkt.serialize(variable, value, t, nfields=3)
        sig = self.crypt.signer.issue(tbs)
        tbss = pkt.serialize(variable, value, t, sig, nfields=4)
        ss = self.crypt.collective.sign(self.crypt.signer, tbss)
        req = pkt.serialize(variable, value, t, sig, None)
        failure: list = []
        errs: list = []

        def cb(res: tp.MulticastResponse) -> bool:
            nonlocal ss
            if res.err is None and res.data is not None:
                try:
                    share = pkt.parse_signature(res.data)
                    ss, done = self.crypt.collective.combine(
                        ss, share, q, self.crypt.keyring
                    )
                    return done
                except Exception as e:
                    errs.append(e)
            else:
                errs.append(res.err)
            failure.append(res.peer)
            return q.reject(failure)

        self.tr.multicast(tp.SIGN, sign_group, req, cb)
        try:
            self.crypt.collective.verify(tbss, ss, q, self.crypt.keyring)
        except Exception as e:
            raise majority_error(errs, e)

        wreq = pkt.serialize(variable, value, t, sig, ss)
        self.tr.multicast(tp.WRITE, write_group, wreq, lambda res: False)
