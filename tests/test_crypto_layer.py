"""Crypto layer: certs, keyring, signatures, collective sigs, messages.

Mirrors the reference's crypto behavior (crypto/pgp/crypto_pgp.go):
cert parse/sign/merge, detached sign/verify, collective combine until
sufficient, sign-then-encrypt with nonce echo, symmetric data encryption.
"""


import pytest

from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import dataenc, keyring, message, new_crypto, rsa, signature
from bftkv_tpu.errors import (
    ERR_DECRYPTION_FAILURE,
    ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES,
    ERR_INVALID_SIGNATURE,
)

KEY_BITS = 1024  # small keys keep the suite fast; kernels are width-generic


@pytest.fixture(scope="module")
def identities():
    out = []
    for i in range(5):
        key = rsa.generate(KEY_BITS)
        c = certmod.Certificate(
            n=key.n,
            e=key.e,
            name=f"node{i}",
            address=f"http://127.0.0.1:{6000 + i}",
            uid=f"node{i}@example.test",
        )
        out.append((key, c))
    return out


class FixedQuorum:
    """Duck-typed quorum: sufficient once >= k distinct nodes."""

    def __init__(self, k):
        self.k = k

    def is_sufficient(self, nodes):
        return len({n.id for n in nodes}) >= self.k


def test_cert_roundtrip_and_id(identities):
    key, c = identities[0]
    blob = c.serialize()
    [parsed] = certmod.parse(blob)
    assert parsed.id == c.id
    assert parsed.name == "node0"
    assert parsed.address.endswith(":6000")
    assert parsed.uid == "node0@example.test"
    assert parsed.n == key.n


def test_cert_sign_merge_signers(identities):
    _, c = identities[0]
    c = certmod.parse(c.serialize())[0]  # fresh copy
    for key, signer_cert in identities[1:3]:
        certmod.sign_certificate(c, key)
    assert set(c.signers()) == {identities[1][1].id, identities[2][1].id}
    assert c.verify_signature(identities[1][1])
    assert not c.verify_signature(identities[3][1])
    # merge unions signature sets
    c2 = certmod.parse(c.serialize())[0]
    certmod.sign_certificate(c2, identities[3][0])
    c.merge(c2)
    assert set(c.signers()) == {
        identities[1][1].id,
        identities[2][1].id,
        identities[3][1].id,
    }


def test_parse_many(identities):
    blob = certmod.serialize_many([c for _, c in identities])
    parsed = certmod.parse(blob)
    assert [p.id for p in parsed] == [c.id for _, c in identities]


def test_keyring_register_merge_persist(identities, tmp_path):
    ring = keyring.Keyring()
    key0, c0 = identities[0]
    ring.register([c0], priv=key0)
    assert ring.lookup(c0.id) is c0
    assert ring.private_key(c0.id).d == key0.d
    # merging via re-register
    copy = certmod.parse(c0.serialize())[0]
    certmod.sign_certificate(copy, identities[1][0])
    ring.register([copy])
    assert identities[1][1].id in ring.lookup(c0.id).signers()
    # persistence
    ring.save_pubring(str(tmp_path / "pubring"))
    ring.save_secring(str(tmp_path / "secring"))
    ring2 = keyring.Keyring()
    ring2.load_pubring(str(tmp_path / "pubring"))
    ring2.load_secring(str(tmp_path / "secring"))
    assert ring2.lookup(c0.id).id == c0.id
    assert ring2.private_key(c0.id).d == key0.d


def test_detached_signature(identities):
    key, c = identities[0]
    s = signature.Signer(key, c)
    pkt = s.issue(b"hello world")
    assert signature.signers(pkt) == [c.id]
    signature.verify_with_certificate(b"hello world", pkt, c)
    with pytest.raises(ERR_INVALID_SIGNATURE):
        signature.verify_with_certificate(b"tampered", pkt, c)
    # issuer resolution from the embedded cert, no keyring
    got = signature.issuer(pkt, None)
    assert got.id == c.id


def test_collective_combine_and_verify(identities):
    tbss = b"<x,v,t,sig>"
    ring = keyring.Keyring()
    for _, c in identities:
        ring.register([c])
    cs = signature.CollectiveSignature(rsa.VerifierDomain(nlimbs=64))
    q = FixedQuorum(3)
    ss = None
    done = False
    for i, (key, c) in enumerate(identities[:3]):
        share = cs.sign(signature.Signer(key, c), tbss)
        ss, done = cs.combine(ss, share, q, ring)
        assert done == (i == 2)
    assert ss.completed
    cs.verify(tbss, ss, q, ring)
    # not sufficient for a larger quorum
    with pytest.raises(ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES):
        cs.verify(tbss, ss, FixedQuorum(4), ring)
    # tampered message fails
    with pytest.raises(ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES):
        cs.verify(b"other", ss, q, ring)


def test_collective_verify_without_keyring_uses_embedded_certs(identities):
    tbss = b"payload"
    cs = signature.CollectiveSignature(rsa.VerifierDomain(nlimbs=64))
    q = FixedQuorum(2)
    ss = None
    for key, c in identities[:2]:
        share = cs.sign(signature.Signer(key, c), tbss)
        ss, _ = cs.combine(ss, share, q, None)
    empty = keyring.Keyring()
    cs.verify(tbss, ss, q, empty)


def test_duplicate_signer_counted_once(identities):
    tbss = b"dup"
    cs = signature.CollectiveSignature(rsa.VerifierDomain(nlimbs=64))
    key, c = identities[0]
    q = FixedQuorum(2)
    ss = None
    for _ in range(3):
        share = cs.sign(signature.Signer(key, c), tbss)
        ss, done = cs.combine(ss, share, q, None)
    assert not done
    with pytest.raises(ERR_INSUFFICIENT_NUMBER_OF_SIGNATURES):
        cs.verify(tbss, ss, q, keyring.Keyring())


def test_message_security_roundtrip(identities):
    skey, scert = identities[0]
    rkey, rcert = identities[1]
    sender = message.MessageSecurity(skey, scert)
    recipient = message.MessageSecurity(rkey, rcert)
    blob = sender.encrypt([rcert, identities[2][1]], b"secret payload", b"nonce42")
    pt, peer, nonce = recipient.decrypt(blob)
    assert pt == b"secret payload"
    assert peer.id == scert.id
    assert nonce == b"nonce42"
    # third recipient can also decrypt
    third = message.MessageSecurity(identities[2][0], identities[2][1])
    pt2, _, _ = third.decrypt(blob)
    assert pt2 == b"secret payload"
    # non-recipient cannot
    outsider = message.MessageSecurity(identities[3][0], identities[3][1])
    with pytest.raises(ERR_DECRYPTION_FAILURE):
        outsider.decrypt(blob)


def test_message_tamper_detected(identities):
    skey, scert = identities[0]
    rkey, rcert = identities[1]
    sender = message.MessageSecurity(skey, scert)
    recipient = message.MessageSecurity(rkey, rcert)
    blob = bytearray(sender.encrypt([rcert], b"payload", b"n"))
    blob[-1] ^= 0xFF
    with pytest.raises(ERR_DECRYPTION_FAILURE):
        recipient.decrypt(bytes(blob))


def test_dataenc_roundtrip():
    key = b"some derived key material"
    ct = dataenc.encrypt(b"hello", key)
    assert dataenc.decrypt(ct, key) == b"hello"
    with pytest.raises(ERR_DECRYPTION_FAILURE):
        dataenc.decrypt(ct, b"wrong key")


def test_crypto_bundle(identities):
    key, c = identities[0]
    cr = new_crypto(key, c)
    assert cr.signer.cert.id == c.id
    assert cr.keyring.lookup(c.id) is c
    pkt = cr.signer.issue(b"m")
    signature.verify_with_certificate(b"m", pkt, c)
