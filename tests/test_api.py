"""API-layer tests: open/register/password RW/update_cert
(reference: api/api_test.go:48-162)."""

from __future__ import annotations

import pytest

from bftkv_tpu import api as apimod
from bftkv_tpu import topology
from bftkv_tpu.errors import Error
from bftkv_tpu.transport.loopback import TrLoopback

from cluster_utils import start_cluster

BITS = 2048


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(n_servers=4, n_users=1, n_rw=4, bits=BITS)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def homes(cluster, tmp_path_factory):
    """Home dirs for every server + a virgin user (reference: test1)."""
    tmp_path = tmp_path_factory.mktemp("homes")
    uni = cluster.universe
    paths = {}
    for ident in uni.servers + uni.storage_nodes:
        p = str(tmp_path / ident.name)
        topology.save_home(p, ident, uni.view_of(ident))
        paths[ident.name] = p
    virgin = topology.new_identity(
        "test1", uid="test1@example.com", bits=BITS
    )
    p = str(tmp_path / "test1")
    topology.save_home(p, virgin, [virgin.cert])
    paths["test1"] = p
    return paths


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_register_enrolls_a_virgin_user(cluster, homes):
    """A fresh identity with zero counter-signatures registers, gains a
    quorum certificate, and can then write (reference: api_test.go:48-140)."""
    factory = lambda crypt: TrLoopback(crypt, cluster.net)
    api = apimod.open_client(homes["test1"], factory, join=False)

    # before registering, a write must be rejected (no quorum cert)
    api._sign_peers([homes[s.name] for s in cluster.universe.servers])
    with pytest.raises(Error):
        api.client.write(b"api_prereg", b"x")

    # the reference registers against a* AND rw* (api_test.go:24-41)
    certlist = [
        homes[i.name]
        for i in cluster.universe.servers + cluster.universe.storage_nodes
    ]
    api.register(certlist, "s3cret")

    self_cert = api.crypt.keyring.lookup(api.graph.id)
    assert len(self_cert.signers()) >= 3  # self + >= f+1 servers

    # now the quorum certificate check passes
    api.write(b"api_postreg", b"registered!")
    assert api.read(b"api_postreg") == b"registered!"


def test_password_protected_write_read(cluster, tmp_path):
    uni = cluster.universe
    user = uni.users[0]
    # build the signed user's home on the fly
    d = str(tmp_path / "u01-home")
    topology.save_home(d, user, uni.view_of(user))
    factory = lambda crypt: TrLoopback(crypt, cluster.net)
    api = apimod.open_client(d, factory, join=False)

    api.write(b"api_pw_var", b"top secret", password="hunter2")
    assert api.read(b"api_pw_var", password="hunter2") == b"top secret"
    # the stored value is ciphertext, not the plaintext
    raw = api.client.read(
        b"api_pw_var",
        api.client.authenticate(b"api_pw_var", b"hunter2")[0],
    )
    assert raw != b"top secret"
    # wrong password fails
    with pytest.raises(Error):
        api.read(b"api_pw_var", password="wrong")


def test_update_cert_rewrites_pubring(cluster, tmp_path):
    uni = cluster.universe
    user = uni.users[0]
    d = str(tmp_path / "u01-home")
    topology.save_home(d, user, uni.view_of(user))
    factory = lambda crypt: TrLoopback(crypt, cluster.net)
    api = apimod.open_client(d, factory, join=False)
    api.update_cert()
    # reload: the pubring must still parse and contain the whole view
    graph, crypt, qs = topology.load_home(d)
    assert graph.id == user.id
    assert len(graph.get_peers()) >= len(uni.servers)
