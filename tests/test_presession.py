"""Presession pump: timestamp leases, warm-session resealing, and the
stale-session edge in grouped envelope sealing (a restarted peer costs
ONE per-peer reseal, never a whole-group OAEP bootstrap)."""

from __future__ import annotations


from bftkv_tpu.crypto.presession import MAX_UINT64, Presession
from bftkv_tpu.faults.harness import build_cluster
from bftkv_tpu.metrics import registry as metrics

from cluster_utils import start_cluster

BITS = 1024


class _FakeClient:
    tr = None


# -- leases -----------------------------------------------------------------


def test_lease_lifecycle():
    p = Presession(_FakeClient())
    assert p.next_t(b"x") == 1  # never seen: optimistic first write
    p.lease_update(b"x", 4)
    assert p.next_t(b"x") == 5
    p.lease_update(b"x", 2)  # leases only move forward
    assert p.next_t(b"x") == 5
    p.lease_drop(b"x")
    assert p.next_t(b"x") == 1


def test_lease_never_aliases_write_once_marker():
    p = Presession(_FakeClient())
    p.lease_update(b"sealed", MAX_UINT64)
    # guessing MAX_UINT64 would BE a write-once; the quorum answers
    # ERR_NO_MORE_WRITE to t=1, which is the correct outcome
    assert p.next_t(b"sealed") == 1


def test_lease_lru_bound():
    p = Presession(_FakeClient())
    p.LEASE_MAX = 4
    for i in range(8):
        p.lease_update(b"k%d" % i, i + 1)
    assert len(p._leases) == 4
    assert p.next_t(b"k7") == 9  # newest kept
    assert p.next_t(b"k0") == 1  # oldest evicted


def test_presession_off_disables_leases(monkeypatch):
    monkeypatch.setenv("BFTKV_PRESESSION", "off")
    p = Presession(_FakeClient())
    p.lease_update(b"x", 9)
    assert p.next_t(b"x") == 1


# -- signer maps (share-combination state) ----------------------------------


def test_signer_map_memoized_per_quorum_object():
    class _N:
        def __init__(self, i):
            self.id = i

    class _Q:
        def __init__(self):
            self.calls = 0
            self._nodes = [_N(1), _N(2)]

        def nodes(self):
            self.calls += 1
            return self._nodes

    q = _Q()
    p = Presession(_FakeClient())
    m1 = p.signer_map(q)
    m2 = p.signer_map(q)
    assert m1 is m2 and set(m1) == {1, 2}
    assert q.calls == 1


# -- session warming --------------------------------------------------------


def test_pump_reseals_cold_peer():
    c = start_cluster(4, 1, 4, bits=BITS)
    cl = c.clients[0]
    try:
        cl.write(b"warm/x", b"v")  # establishes sessions + warm set
        cl.drain_tails()
        msg = cl.tr.security.message
        victim = next(iter(cl._presession._warm_peers.values()))
        msg.invalidate(victim.id)
        assert not msg.has_session(victim.id)
        before = metrics.snapshot().get(
            "crypto.session.reseal{cmd=presession}", 0
        )
        resealed = cl._presession.warm_once()
        # The invalidated victim, plus any quorum member the staged
        # wave never had to contact — warming those is the pump's job.
        assert resealed >= 1
        assert msg.has_session(victim.id)
        assert (
            metrics.snapshot().get(
                "crypto.session.reseal{cmd=presession}", 0
            )
            == before + resealed
        )
        # nothing cold: the next round is a no-op
        assert cl._presession.warm_once() == 0
    finally:
        c.stop()


def test_pump_skips_open_breaker_peer_until_half_open():
    """A downed peer whose circuit breaker is OPEN stops consuming pump
    work (crypto.session.reseal_skipped); once the breaker's open
    window lapses (half-open), the pump re-seals it again — without
    ever consuming the breaker's one half-open probe slot itself."""
    import time as _time

    from bftkv_tpu import transport as tp

    c = start_cluster(4, 1, 4, bits=BITS)
    cl = c.clients[0]
    was_enabled = tp.peer_health.enabled
    try:
        cl.write(b"skip/x", b"v")  # establishes sessions + warm set
        cl.drain_tails()
        cl._presession.warm_once()  # seal every staged-wave leftover
        msg = cl.tr.security.message
        victim = next(iter(cl._presession._warm_peers.values()))
        msg.invalidate(victim.id)
        assert not msg.has_session(victim.id)

        tp.peer_health.enabled = True
        tp.peer_health.reset()
        for _ in range(tp.peer_health.threshold):
            tp.peer_health.fail(victim.address)
        assert tp.peer_health.is_open(victim.address)

        before = metrics.snapshot().get("crypto.session.reseal_skipped", 0)
        assert cl._presession.warm_once() == 0
        assert not msg.has_session(victim.id)  # no pump work burned
        assert (
            metrics.snapshot().get("crypto.session.reseal_skipped", 0)
            == before + 1
        )
        # is_open never consumed the half-open probe: force the open
        # window to lapse and the pump immediately re-seals.
        with tp.peer_health._lock:
            tp.peer_health._states[victim.address][1] = (
                _time.monotonic() - 1.0
            )
        assert not tp.peer_health.is_open(victim.address)
        assert cl._presession.warm_once() >= 1
        assert msg.has_session(victim.id)
    finally:
        tp.peer_health.enabled = was_enabled
        tp.peer_health.reset()
        c.stop()


def test_restarted_peer_costs_one_reseal_not_group_bootstrap():
    """The stale-session edge: a replica restart invalidates only ITS
    pairwise session.  The next write's grouped sealing keeps every
    other peer on the session envelope — the per-recipient OAEP
    bootstrap wrap count grows by ~the single resealed peer, not by the
    whole group — and the transport's unknown-session retry heals the
    one stale link (crypto.session.reseal)."""
    c = build_cluster(4, 1, 4, bits=BITS)
    cl = c.clients[0]
    try:
        cl.write(b"reseal/x", b"v1")
        cl.drain_tails()
        cl.write(b"reseal/y", b"v2")  # steady state: all sessions warm
        cl.drain_tails()

        snap0 = metrics.snapshot()
        c.restart("rw01")  # fresh Server + MessageSecurity on the same data
        cl.write(b"reseal/z", b"v3")
        cl.drain_tails()
        snap1 = metrics.snapshot()

        reseals = sum(
            snap1.get(k, 0) - snap0.get(k, 0)
            for k in snap1
            if k.startswith("crypto.session.reseal")
        )
        assert reseals >= 1
        # The client's own sealing stayed warm for everyone else: its
        # share of fresh bootstrap wraps is the restarted peer's reseal
        # (the restarted SERVER also bootstraps its response sessions —
        # one per peer it answers — so bound the total instead of
        # demanding zero).
        wraps = snap1.get(
            "crypto.session.bootstrap_wraps", 0
        ) - snap0.get("crypto.session.bootstrap_wraps", 0)
        group = len(c.all_servers)
        assert wraps < 2 * group, (
            f"{wraps} bootstrap wraps after one peer restart — "
            "the whole group degraded to bootstrap sealing"
        )
        assert cl.read(b"reseal/z") == b"v3"
    finally:
        c.stop()


def test_pump_thread_lifecycle():
    p = Presession(_FakeClient(), interval=0.01)
    p.ensure_pump()
    assert p._pump is not None and p._pump.is_alive()
    p.ensure_pump()  # idempotent
    p.stop()
    p._pump.join(timeout=2)
    assert not p._pump.is_alive()


def test_pump_not_started_when_disabled(monkeypatch):
    monkeypatch.setenv("BFTKV_PRESESSION", "off")
    p = Presession(_FakeClient(), interval=0.01)
    p.ensure_pump()
    assert p._pump is None
