"""Quorum-certificate soundness (round-5 /verify findings).

Two properties, found driving a GnuPG-migrated universe end-to-end:

1. The signature-count check must VERIFY each counted signature — the
   server accepts quorum certificates from certs PRESENTED by writers,
   so an id-only count would let anyone mint one (forged entries).
2. A writer may hold a RICHER copy of its own cert (quorum certificate
   accumulated across replicas / imported from GnuPG rings) than a
   replica's keyring copy — the presented copy must satisfy the check
   WITHOUT being persisted into the keyring, because the trust graph
   derives edges from keyring signature sets and valid third-party
   certifications must not become edges just by being shown.
"""

from __future__ import annotations

import pytest

from bftkv_tpu.errors import ERR_INVALID_QUORUM_CERTIFICATE
from tests.cluster_utils import start_cluster


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(4, 1, 4)
    yield c
    c.stop()


def _strip_keyring_qcert(cluster, cert_id):
    """Make every replica's keyring copy of the cert signature-sparse,
    as after a partial migration; returns the removed sets."""
    saved = []
    for s in cluster.all_servers:
        have = s.crypt.keyring.get(cert_id)
        saved.append((have, dict(have.signatures)))
        have.signatures.clear()
        have.__dict__.pop("_qcert_ok", None)
    return saved


def test_rich_presented_cert_satisfies_sparse_keyring(cluster):
    c = cluster.clients[0]
    cid = c.crypt.signer.cert.id
    saved = _strip_keyring_qcert(cluster, cid)
    try:
        # Single path and batch path both carry the client's own rich
        # cert; the replicas' sparse copies must not shadow it.
        c.write(b"qcert/single", b"v1")
        assert c.read(b"qcert/single") == b"v1"
        errs = c.write_many([(b"qcert/b1", b"x"), (b"qcert/b2", b"y")])
        assert errs == [None, None]
        # The keyring copies were NOT enriched by the presented cert.
        for srv, (have, _) in zip(cluster.all_servers, saved):
            assert have.signatures == {}, (
                "presented cert leaked into the keyring"
            )
    finally:
        for have, sigs in saved:
            have.signatures.update(sigs)


def test_forged_qcert_entries_not_counted(cluster):
    c = cluster.clients[0]
    cert = c.crypt.signer.cert
    cid = cert.id
    saved = _strip_keyring_qcert(cluster, cid)
    real = dict(c.crypt.signer.cert.signatures)
    try:
        # Forge: claim every server's id with garbage signature bytes.
        cert.signatures.clear()
        for s in cluster.all_servers:
            cert.signatures[s.self_node.id] = b"\x01" * 256
        with pytest.raises(ERR_INVALID_QUORUM_CERTIFICATE):
            c.write(b"qcert/forged", b"evil")
    finally:
        cert.signatures.clear()
        cert.signatures.update(real)
        for have, sigs in saved:
            have.signatures.update(sigs)
