"""Fused RNS Pallas chains (ops/pallas_rns) vs host oracles and the XLA
RNS kernels — interpret mode on the CPU lane (the kernel body lowers to
ordinary XLA ops; Mosaic compilation is exercised on real TPU runs).
"""

from __future__ import annotations

import secrets

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from bftkv_tpu.crypto import rsa
from bftkv_tpu.ops import limb, pallas_rns, rns


def _pow_operands(ctx, digits, T, n_top_bits):
    mods = []
    while len(mods) < 3:
        m = secrets.randbits(n_top_bits) | 1
        if ctx.key_rows(m) is not None:
            mods.append(m)
    mods = [mods[i % 3] for i in range(T)]
    bases = [secrets.randbits(n_top_bits - 8) for _ in range(T)]
    exps = [secrets.randbits(n_top_bits - 40) for _ in range(T)]
    unique, urows, idxs = {}, [], []
    for m in mods:
        if m not in unique:
            unique[m] = len(urows)
            urows.append(ctx.key_rows(m))
        idxs.append(unique[m])
    urows += [urows[0]] * (64 - len(urows))
    ukey = tuple(jnp.asarray(a) for a in rns.stack_key_rows(urows))
    base_digits = np.stack(
        [limb.int_to_limbs(b % m, digits) for b, m in zip(bases, mods)]
    )
    ed = np.stack([limb.int_to_limbs(e, digits) for e in exps])
    nib = np.empty((T, digits * 4), dtype=np.uint8)
    nib[:, 0::4] = ed & 0xF
    nib[:, 1::4] = (ed >> 4) & 0xF
    nib[:, 2::4] = (ed >> 8) & 0xF
    nib[:, 3::4] = (ed >> 12) & 0xF
    nib = nib[:, ::-1]
    return mods, bases, exps, ukey, base_digits, nib, idxs


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_pow_pallas_matches_host_pow():
    digits, n_bits = 16, 256
    ctx = rns.context(digits, n_bits)
    T = 8
    mods, bases, exps, ukey, base_digits, nib, idxs = _pow_operands(
        ctx, digits, T, 250
    )
    sigma = np.asarray(
        pallas_rns.pow_pallas(
            rns.digits_to_halves_u8(base_digits),
            np.ascontiguousarray(nib.T),
            np.asarray(idxs, dtype=np.int32),
            ukey,
            digits=digits,
            n_bits=n_bits,
            interpret=True,
        )
    )
    vals = rns._sigma_to_ints(ctx, sigma)
    for v, b, e, m in zip(vals, bases, exps, mods):
        assert v % m == pow(b, e, m)


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_power_mod_rns_pallas_backend(monkeypatch):
    # The integrated seam: power_mod_rns routes through the fused
    # kernel when forced, and the result matches the host oracle.
    monkeypatch.setenv("BFTKV_RNS_POW_BACKEND", "pallas")
    mods, bases, exps = [], [], []
    ctx = rns.context(32, 512)
    while len(mods) < 5:
        m = secrets.randbits(500) | 1
        if ctx.key_rows(m) is not None:
            mods.append(m)
            bases.append(secrets.randbits(490))
            exps.append(secrets.randbits(480))
    got = rns.power_mod_rns(bases, exps, mods, n_bits=512)
    assert got == [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_verify_pallas_matches_reference():
    key1, key2 = rsa.generate(2048), rsa.generate(2048)
    ctx = rns.context()
    items = []
    for i, k in enumerate([key1, key2] * 4):
        msg = b"pv-%d" % i
        s = int.from_bytes(rsa.sign(msg, k), "big")
        em = rsa.emsa_pkcs1v15_sha256(msg, k.size_bytes)
        items.append((s, em, k))
    s3, em3, k3 = items[3]
    items[3] = (s3 ^ (1 << 17), em3, k3)  # bit-flipped signature
    sig_d = np.stack([limb.int_to_limbs(s, 128) for s, _, _ in items])
    em_d = np.stack([limb.int_to_limbs(e, 128) for _, e, _ in items])
    idx = np.array([i % 2 for i in range(8)], dtype=np.int32)
    urows = [ctx.key_rows(key1.n), ctx.key_rows(key2.n)]
    ukey = tuple(jnp.asarray(a) for a in rns.stack_key_rows(urows))
    ok = np.asarray(
        pallas_rns.verify_pallas(
            rns.digits_to_halves_u8(sig_d),
            rns.digits_to_halves_u8(em_d),
            idx,
            ukey,
            interpret=True,
        )
    )
    assert ok.tolist() == [True, True, True, False] + [True] * 4

    # Same inputs through the XLA RNS kernel must agree exactly.
    xla = np.asarray(
        rns.verify_e65537_rns_indexed(sig_d, em_d, idx, ukey)
    )
    assert ok.tolist() == xla.tolist()


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_verify_rns_indexed_pallas_backend(monkeypatch):
    # Env-forced fused backend through the public indexed entry point
    # (what the dispatcher and sidecar call).
    monkeypatch.setenv("BFTKV_RNS_VERIFY_BACKEND", "pallas")
    key = rsa.generate(2048)
    ctx = rns.context()
    msgs = [b"ix-%d" % i for i in range(4)]
    sigs = [int.from_bytes(rsa.sign(m, key), "big") for m in msgs]
    ems = [rsa.emsa_pkcs1v15_sha256(m, key.size_bytes) for m in msgs]
    sigs[2] ^= 2
    sig_d = np.stack([limb.int_to_limbs(s, 128) for s in sigs])
    em_d = np.stack([limb.int_to_limbs(e, 128) for e in ems])
    ukey = tuple(
        jnp.asarray(a) for a in rns.stack_key_rows([ctx.key_rows(key.n)])
    )
    ok = np.asarray(
        rns.verify_e65537_rns_indexed(
            sig_d, em_d, np.zeros(4, dtype=np.int32), ukey
        )
    )
    assert ok.tolist() == [True, True, False, True]


def test_mosaic_lowering_for_tpu_target():
    """The fused chains LOWER to Mosaic for a TPU target (jax.export
    runs the pallas→Mosaic MLIR lowering on the host, no device
    needed).  Interpret-mode tests cannot catch unsupported-op or
    layout errors in that lowering; this pins the class of failure
    that would otherwise only surface as the loud XLA fallback during
    a live bench window (VERDICT r4 item 3)."""
    # ``jax.export`` attribute access is gated by an accelerated
    # deprecation shim in some jax builds (0.4.37); the module import
    # is the stable spelling.
    from jax import export as jax_export

    # Verify chain at the production tile (2048-bit context).
    tv = pallas_rns.TILE_VERIFY
    pc = pallas_rns._pad_consts(128, 2048)
    run = pallas_rns._verify_call(128, 2048, tv, False)
    z = lambda w: jnp.zeros((tv, w), jnp.float32)
    exp = jax_export.export(run, platforms=("tpu",))(
        z(256), z(256),
        z(pc.kpad), z(pc.kpad), z(1), z(pc.kpad),
        z(pc.kpad), z(pc.kpad), z(pc.kpad), z(pc.kpad), z(1),
    )
    assert len(exp.mlir_module_serialized) > 0

    # Sign (pow) chain at the production tile (1024-bit CRT context).
    tp = pallas_rns.TILE_POW
    pc2 = pallas_rns._pad_consts(64, 1024)
    run2 = pallas_rns._pow_call(64, 1024, tp, False)
    zp = lambda w: jnp.zeros((tp, w), jnp.float32)
    exp2 = jax_export.export(run2, platforms=("tpu",))(
        zp(128),                               # base halves
        jnp.zeros((256, tp), jnp.float32),     # nibbles (W, T)
        zp(pc2.kpad), zp(pc2.kpad), zp(1), zp(pc2.kpad),
        zp(pc2.kpad), zp(pc2.kpad), zp(1),
    )
    assert len(exp2.mlir_module_serialized) > 0
