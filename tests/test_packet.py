"""Wire-format tests (codec parity with reference packet/packet.go)."""

import io

import pytest

from bftkv_tpu import errors, packet


def test_error_interning():
    e1 = errors.new_error("some failure")
    e2 = errors.error_from_string("some failure")
    assert e1 is e2
    assert errors.error_from_string("permission denied") is errors.ERR_PERMISSION_DENIED


def test_error_roundtrip_equality():
    assert errors.Error("x") == errors.Error("x")
    assert errors.Error("x") != errors.Error("y")
    d = {errors.ERR_EXIST: 1}
    assert d[errors.Error("already exist")] == 1


def test_error_raise_and_catch():
    # Interned errors are classes: raising creates a fresh instance,
    # and both specific and generic except clauses work.
    with pytest.raises(errors.ERR_BAD_TIMESTAMP):
        raise errors.ERR_BAD_TIMESTAMP
    try:
        raise errors.ERR_BAD_TIMESTAMP
    except errors.Error as e:
        assert e == errors.ERR_BAD_TIMESTAMP
        assert e == errors.error_from_string("bad timestamp")
    # Fresh instance per raise: no shared traceback state.
    seen = []
    for _ in range(2):
        try:
            raise errors.ERR_EXIST
        except errors.Error as e:
            seen.append(e)
    assert seen[0] is not seen[1]


def test_roundtrip_full():
    sig = packet.SignaturePacket(
        type=1, version=3, completed=True, data=b"sigdata", cert=b"certdata"
    )
    ss = packet.SignaturePacket(
        type=1, version=0, completed=False, data=b"ss", cert=None
    )
    pkt = packet.serialize(b"var", b"value", 42, sig, ss, b"auth")
    p = packet.parse(pkt)
    assert p.variable == b"var"
    assert p.value == b"value"
    assert p.t == 42
    assert p.sig.data == b"sigdata"
    assert p.sig.cert == b"certdata"
    assert p.sig.version == 3
    assert p.sig.completed
    assert not p.ss.completed
    assert p.ss.cert is None
    assert p.auth == b"auth"


def test_roundtrip_partial():
    # Short packets: <x>, <x,v>, <x,v,t> — parser defaults the tail.
    p = packet.parse(packet.serialize(b"x", nfields=1))
    assert p.variable == b"x" and p.value is None and p.t == 0 and p.sig is None

    p = packet.parse(packet.serialize(b"x", b"v", nfields=2))
    assert p.value == b"v" and p.t == 0

    p = packet.parse(packet.serialize(b"x", b"v", 7, nfields=3))
    assert p.t == 7 and p.sig is None and p.ss is None and p.auth is None


def test_nil_signature_roundtrip():
    pkt = packet.serialize(b"x", b"v", 1, None, None, None)
    p = packet.parse(pkt)
    assert p.sig is None and p.ss is None and p.auth is None


def test_empty_chunk_parses_as_none():
    pkt = packet.serialize(b"x", b"", 1)
    assert packet.parse(pkt).value is None


def test_tbs_tbss():
    sig = packet.SignaturePacket(data=b"S" * 16)
    ss = packet.SignaturePacket(data=b"T" * 16)
    pkt = packet.serialize(b"var", b"val", 9, sig, ss, b"a")
    t = packet.tbs(pkt)
    # tbs covers x, v, t only; re-serializing the prefix fields must match.
    assert t == packet.serialize(b"var", b"val", 9, nfields=3)
    tt = packet.tbss(pkt)
    assert tt == packet.serialize(b"var", b"val", 9, sig, nfields=4)
    assert tt.startswith(t)
    # tbs is invariant to the signatures attached.
    pkt2 = packet.serialize(b"var", b"val", 9, None, ss, b"a")
    assert packet.tbs(pkt2) == t


def test_write_once_t():
    pkt = packet.serialize(b"x", b"v", packet.WRITE_ONCE_T)
    assert packet.parse(pkt).t == packet.WRITE_ONCE_T


def test_signature_packet_roundtrip():
    sig = packet.SignaturePacket(
        type=5, version=9, completed=True, data=b"d", cert=b"c"
    )
    assert packet.parse_signature(packet.serialize_signature(sig)) == sig
    assert packet.parse_signature(packet.serialize_signature(None)) is None


def test_auth_request_roundtrip():
    pkt = packet.serialize_auth_request(2, b"var", b"adata")
    phase, var, adata = packet.parse_auth_request(pkt)
    assert (phase, var, adata) == (2, b"var", b"adata")


def test_bigint_roundtrip():
    buf = io.BytesIO()
    for n in [0, 1, 255, 256, 2**64, 2**2047 + 12345]:
        packet.write_bigint(buf, n)
    buf.seek(0)
    for n in [0, 1, 255, 256, 2**64, 2**2047 + 12345]:
        assert packet.read_bigint(buf) == n


def test_malformed():
    with pytest.raises(errors.Error):
        packet.parse(b"\x00\x00\x00\x00\x00\x00\x00\x09short")
    # EOF before the first field is malformed, matching the reference's
    # strictness on `variable` (packet/packet.go:64-67).
    with pytest.raises(errors.ERR_MALFORMED_REQUEST):
        packet.parse(b"")
    # Hostile 2^63-scale length prefixes are clean protocol errors.
    import struct

    with pytest.raises(errors.Error):
        packet.parse(struct.pack(">Q", 2**63) + b"xx")
    with pytest.raises(errors.Error):
        packet.tbs(struct.pack(">Q", 2**63) + b"xx")
    # EOFError never escapes public entry points.
    with pytest.raises(errors.Error):
        packet.tbss(packet.serialize(b"x", b"v", 1, nfields=3))
    with pytest.raises(errors.Error):
        packet.parse_signature(b"")


def test_signature_type_must_fit_byte():
    with pytest.raises(ValueError):
        packet.serialize_signature(packet.SignaturePacket(type=256))
    assert packet.SIGNATURE_TYPE_PASSWORD_AUTH_PROOF <= 0xFF
