"""Metrics registry: labels, gauges, Prometheus exposition, and
concurrency (observe/snapshot under threads with the sort moved
outside the lock)."""

from __future__ import annotations

import re
import threading

from bftkv_tpu.metrics import Metrics


def test_counters_gauges_labels_flatten_in_snapshot():
    m = Metrics()
    m.incr("plain")
    m.incr("rpc", 2, labels={"cmd": "write", "side": "client"})
    m.gauge("depth", 7.5)
    m.gauge("occ", 0.25, labels={"name": "dispatch"})
    snap = m.snapshot()
    assert snap["plain"] == 1
    # labels flatten sorted by key
    assert snap["rpc{cmd=write,side=client}"] == 2
    assert snap["depth"] == 7.5
    assert snap["occ{name=dispatch}"] == 0.25


def test_gauge_last_write_wins():
    m = Metrics()
    m.gauge("g", 1.0)
    m.gauge("g", 3.0)
    assert m.snapshot()["g"] == 3.0
    assert "bftkv_g 3.0" in m.prometheus()


def test_observe_series_snapshot_keys_unchanged():
    """The historical flat keys (.count/.sum/.p50/.p99) survive the
    label-aware restructure — existing consumers read them."""
    m = Metrics()
    for v in (1.0, 2.0, 3.0, 4.0):
        m.observe("lat", v)
    snap = m.snapshot()
    assert snap["lat.count"] == 4
    assert snap["lat.sum"] == 10.0
    assert "lat.p50" in snap and "lat.p99" in snap
    assert m.percentile("lat", 0.5) == 3.0


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"  # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" -?[0-9.eE+-]+(e[+-]?[0-9]+)?$"
)


def test_prometheus_exposition_is_scrapable():
    m = Metrics()
    m.incr("server.write.ok", 3)
    m.incr("transport.rpcs", 5, labels={"cmd": "sign", "transport": "loop"})
    m.gauge("dispatch.occupancy", 0.5)
    m.observe("client.write.latency", 0.01)
    m.observe("client.write.latency", 0.02)
    text = m.prometheus()
    assert text.endswith("\n")
    sample_lines = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram)$", line), line
            continue
        assert _PROM_LINE.match(line), f"unscrapable line: {line!r}"
        sample_lines.append(line)
    # counters end in _total
    assert any(
        ln.startswith("bftkv_server_write_ok_total 3") for ln in sample_lines
    )
    assert any(
        ln.startswith("bftkv_transport_rpcs_total{") and ' 5' in ln
        for ln in sample_lines
    )
    # every TYPE counter name ends _total
    for line in text.splitlines():
        mobj = re.match(r"^# TYPE (\S+) counter$", line)
        if mobj:
            assert mobj.group(1).endswith("_total"), line
    # observe() series expose fixed-bucket histograms + _sum/_count
    assert "# TYPE bftkv_client_write_latency histogram" in text
    assert 'bftkv_client_write_latency_bucket{le="0.01"} 1' in text
    assert 'bftkv_client_write_latency_bucket{le="0.025"} 2' in text
    assert 'bftkv_client_write_latency_bucket{le="+Inf"} 2' in text
    assert "bftkv_client_write_latency_sum" in text
    assert "bftkv_client_write_latency_count 2" in text
    # gauges typed as gauge
    assert "# TYPE bftkv_dispatch_occupancy gauge" in text


def test_prometheus_label_escaping():
    m = Metrics()
    m.incr("weird", labels={"v": 'a"b\\c\nd'})
    text = m.prometheus()
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    # still one line per sample
    for line in text.splitlines():
        assert "\n" not in line


def test_concurrent_observe_snapshot_percentile():
    """observe() from many threads while snapshot()/percentile() run
    concurrently: totals must come out exact and nothing deadlocks
    (the sort happens outside the lock)."""
    m = Metrics()
    n_threads, per_thread = 4, 3000
    stop = threading.Event()

    def writer(k: int):
        for i in range(per_thread):
            m.observe("lat", float(i))
            m.incr("ops", labels={"t": str(k % 2)})

    def reader():
        while not stop.is_set():
            m.snapshot()
            m.percentile("lat", 0.99)

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    snap = m.snapshot()
    assert snap["lat.count"] == n_threads * per_thread
    assert (
        snap["ops{t=0}"] + snap["ops{t=1}"] == n_threads * per_thread
    )


def test_reset_clears_everything():
    m = Metrics()
    m.incr("a")
    m.gauge("b", 1)
    m.observe("c", 1.0)
    m.reset()
    assert m.snapshot() == {}
    assert m.prometheus() == "\n"


def test_histograms_merge_across_instances():
    """The fixed-ladder contract the fleet collector leans on: two
    registries' bucket vectors sum element-wise and the merged quantile
    estimate is computable from the sum alone (per-daemon summary
    quantiles can't do this — DESIGN.md §11)."""
    from bftkv_tpu.metrics import BUCKETS, histogram_quantile

    a, b = Metrics(), Metrics()
    for v in (0.002, 0.002, 0.02):
        a.observe("lat", v, labels={"shard": 0})
    for v in (0.2, 0.2, 0.2, 7.0):
        b.observe("lat", v, labels={"shard": 0})
    ha = a.histograms()["lat{shard=0}"]
    hb = b.histograms()["lat{shard=0}"]
    assert len(ha["buckets"]) == len(BUCKETS) + 1
    merged = [x + y for x, y in zip(ha["buckets"], hb["buckets"])]
    assert sum(merged) == 7
    assert ha["count"] + hb["count"] == 7
    # 4 of 7 samples are <= 0.25 -> the p50 bucket is le=0.25
    assert histogram_quantile(0.5, merged) == 0.25
    assert histogram_quantile(0.99, merged) == 10.0
    assert histogram_quantile(0.5, [0] * (len(BUCKETS) + 1)) is None
    # snapshot carries the same counts as flat .bucket{le=} keys
    snap = a.snapshot()
    assert snap["lat.bucket{shard=0,le=0.0025}"] == 2
    assert snap["lat.bucket{shard=0,le=0.025}"] == 1
