"""Operator-surface smoke test: genkeys → run_cluster (real OS
processes) → bftrw write/read → daemon client API.

This is the deployment shape of the reference — one process per replica
on localhost HTTP (scripts/run.sh + cmd/bftkv + cmd/bftrw) — which the
in-process cluster tests cannot cover.
"""

import os
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE = 17001
RW_BASE = 17101
API_BASE = 17501

ENV = dict(
    os.environ,
    PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    JAX_PLATFORMS="cpu",  # daemons must not fight over the single TPU chip
)


def run_cmd(args, **kw):
    return subprocess.run(
        [sys.executable, "-m"] + args,
        env=ENV, cwd=REPO, capture_output=True, timeout=180, **kw
    )


def wait_port(port: int, timeout: float = 180.0) -> None:
    # Generous: a co-scheduled test suite or bench run can stretch 9
    # daemons' jax imports well past a minute on a shared CPU box.
    import socket

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with socket.socket() as s:
            s.settimeout(1.0)
            try:
                s.connect(("127.0.0.1", port))
                return
            except OSError:
                time.sleep(0.3)
    raise TimeoutError(f"port {port} never came up")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from bftkv_tpu.cmd import run_cluster

    tmp = tmp_path_factory.mktemp("cmd")
    keys = str(tmp / "keys")
    dbs = str(tmp / "dbs")
    gen = run_cmd([
        "bftkv_tpu.cmd.genkeys", "--out", keys,
        "--servers", "4", "--rw", "4", "--users", "1", "--bits", "1024",
        "--base-port", str(BASE), "--rw-base-port", str(RW_BASE),
    ])
    assert gen.returncode == 0, gen.stderr.decode()

    homes = run_cluster.server_homes(keys)
    assert len(homes) == 8
    # The client APIs act as the user identity: server identities
    # under-collect collective signatures (their AUTH|PEER quorum
    # excludes self) and cannot reach the rw nodes in trust distance —
    # same property as the reference topology.
    procs = run_cluster.spawn(
        homes, dbs, storage="native", api_base=API_BASE,
        client_home=os.path.join(keys, "u01"), extra_env=ENV,
        # The whole fleet verifies through one shared sidecar process —
        # every cmd test below then exercises the sidecar path too.
        verify_sidecar=f"auto:127.0.0.1:{API_BASE + 99}",
    )
    try:
        for port in (*range(BASE, BASE + 4), *range(RW_BASE, RW_BASE + 4)):
            wait_port(port)
        wait_port(API_BASE)
        yield {"keys": keys, "dbs": dbs, "procs": procs}
    finally:
        run_cluster.shutdown(procs)


def test_bftrw_write_read_across_processes(cluster):
    home = os.path.join(cluster["keys"], "u01")
    w = run_cmd(["bftkv_tpu.cmd.bftrw", "--home", home, "write", "smoke/x",
                 "hello from bftrw"])
    assert w.returncode == 0, w.stderr.decode()
    r = run_cmd(["bftkv_tpu.cmd.bftrw", "--home", home, "read", "smoke/x"])
    assert r.returncode == 0, r.stderr.decode()
    assert r.stdout == b"hello from bftrw"


def test_bftrw_writemany_readmany(cluster):
    home = os.path.join(cluster["keys"], "u01")
    lines = b"\n".join(b"bulk/%d=value-%d" % (i, i) for i in range(5))
    w = run_cmd(
        ["bftkv_tpu.cmd.bftrw", "--home", home, "writemany"], input=lines
    )
    assert w.returncode == 0, w.stderr.decode()
    assert b"5/5 written" in w.stderr
    r = run_cmd(
        ["bftkv_tpu.cmd.bftrw", "--home", home, "readmany"]
        + ["bulk/%d" % i for i in range(5)]
    )
    assert r.returncode == 0, r.stderr.decode()
    for i in range(5):
        assert b"bulk/%d=value-%d" % (i, i) in r.stdout


def test_daemon_client_api(cluster):
    # The daemon's own client writes through the quorum...
    req = urllib.request.Request(
        f"http://127.0.0.1:{API_BASE}/write/smoke/api", data=b"via api",
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as res:
        assert res.status == 200
    # ...and any other replica's API reads it back.
    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE + 1}/read/smoke/api", timeout=60
    ) as res:
        assert res.read() == b"via api"


def test_daemon_show_and_metrics(cluster):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/show", timeout=30
    ) as res:
        body = res.read().decode()
    assert "self: a01" in body and "peer:" in body
    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/metrics", timeout=30
    ) as res:
        import json

        assert res.headers.get("content-type", "").startswith(
            "application/json"
        )
        snap = json.loads(res.read())
    assert isinstance(snap, dict)
    # Content negotiation: a Prometheus scraper's Accept header gets
    # text exposition from the same endpoint.
    req = urllib.request.Request(
        f"http://127.0.0.1:{API_BASE}/metrics",
        headers={"accept": "text/plain"},
    )
    with urllib.request.urlopen(req, timeout=30) as res:
        assert res.headers.get("content-type", "").startswith("text/plain")
        prom = res.read().decode()
    assert "# TYPE" in prom
    assert "_total" in prom  # counters end in _total
    # ?format=prometheus works without the header (curl-friendly)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/metrics?format=prometheus", timeout=30
    ) as res:
        assert res.read().decode().startswith("# TYPE")


def test_daemon_info_endpoint(cluster):
    import json

    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/info", timeout=30
    ) as res:
        info = json.loads(res.read())
    assert info["name"] == "a01"
    # the clique thresholds the fleet collector aggregates against,
    # straight from the wotqs b-masking math (n=4 -> f=1, 2f+1=3)
    assert info["clique"]["n"] == 4
    assert info["clique"]["f"] == 1
    assert info["clique"]["threshold"] == 3
    assert info["role"] == "clique"
    assert set(info["clique"]["members"]) == {"a01", "a02", "a03", "a04"}


def test_daemon_trace_export_cursor(cluster):
    import json

    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/trace?since=0", timeout=30
    ) as res:
        doc = json.loads(res.read())
    assert {"cursor", "dropped", "spans", "slow"} <= set(doc)
    cur = doc["cursor"]
    # draining again from the returned cursor yields nothing new
    # (no traffic between the two calls except other tests' residue;
    # allow spans but require the cursor to be monotonic)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/trace?since={cur}", timeout=30
    ) as res:
        doc2 = json.loads(res.read())
    assert doc2["cursor"] >= cur
    assert isinstance(doc2["spans"], list)


def test_daemon_trace_endpoint(cluster):
    import json

    # Drive one write through the daemon's client so a trace exists.
    req = urllib.request.Request(
        f"http://127.0.0.1:{API_BASE}/write/smoke/traced", data=b"t",
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as res:
        assert res.status == 200
    # Straggler fan-out workers may still be recording rpc spans right
    # after the write returns; poll until the trace settles.
    deadline = time.monotonic() + 30
    names: list = []
    while time.monotonic() < deadline:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{API_BASE}/trace?limit=50", timeout=30
        ) as res:
            doc = json.loads(res.read())
        assert set(doc) == {"slow_threshold_s", "slow", "recent"}
        roots = [t for t in doc["recent"] if t["root"] == "client.write"]
        if roots:
            names = [s["name"] for s in roots[-1]["spans"]]
            if (
                "quorum.select" in names
                and sum(1 for n in names if n.startswith("rpc.")) >= 3
            ):
                break
        time.sleep(0.5)
    assert "quorum.select" in names, names
    assert sum(1 for n in names if n.startswith("rpc.")) >= 3, names


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_daemon_profile_endpoint(cluster):
    """The jax-profiler trace endpoint (pprof analog,
    reference: cmd/bftkv/main.go:20,253) captures a trace directory
    confined under the fixed profile root."""
    import tempfile

    outdir = os.path.join(tempfile.gettempdir(), "bftkv-profile", "smoke")
    with urllib.request.urlopen(
        f"http://127.0.0.1:{API_BASE}/debug/profile?seconds=0.2&name=smoke",
        timeout=90,
    ) as res:
        assert b"trace captured" in res.read()
    found = []
    for root, _dirs, files in os.walk(outdir):
        found += [f for f in files if f.endswith(".trace.json.gz")
                  or "xplane" in f or f.endswith(".pb")]
    assert found, f"no trace artifacts under {outdir}"


def test_daemon_api_missing_variable(cluster):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{API_BASE}/read/smoke/none", timeout=60
        )
    assert ei.value.code == 404
