"""Trust graph + WoT quorum system semantics.

Topology mirrors the reference's canonical test universe
(reference: scripts/setup.sh:17-48): servers a01–a10 and b01–b10 as two
10-cliques, storage-only nodes rw01–rw06, users u01–u04 who sign the
a-servers and rw nodes, with a07–a10 counter-signing the users' certs
(u04 deliberately unsigned for TOFU tests).
"""

from dataclasses import dataclass, field

import numpy as np
import pytest

from bftkv_tpu import quorum as q
from bftkv_tpu.graph import Graph
from bftkv_tpu.ops import tally
from bftkv_tpu.quorum.wotqs import WotQS


@dataclass
class FakeNode:
    """Duck-typed node: the graph/quorum layers only need the
    certificate fields (reference: crypto/cert/cert.go:6-16)."""

    _id: int
    name: str
    address: str = ""
    uid: str = ""
    active: bool = True
    signer_ids: set = field(default_factory=set)

    @property
    def id(self):
        return self._id

    def signers(self):
        return list(self.signer_ids)

    def serialize(self):
        return self.name.encode()


def mkuniverse():
    nodes = {}
    nid = iter(range(1, 1000))

    def add(name, address="", uid=""):
        n = FakeNode(next(nid), name, address=address, uid=uid)
        nodes[name] = n
        return n

    for i in range(1, 11):
        add(f"a{i:02d}", address=f"http://a{i:02d}")
    for i in range(1, 11):
        add(f"b{i:02d}", address=f"http://b{i:02d}")
    for i in range(1, 7):
        add(f"rw{i:02d}", address=f"http://rw{i:02d}")
    for i in (1, 2, 3, 4):
        add(f"u{i:02d}", uid="foo@example.test")

    def sign(signer, signee):
        nodes[signee].signer_ids.add(nodes[signer].id)

    # two 10-cliques: pairwise cross-signed
    for grp in ("a", "b"):
        names = [f"{grp}{i:02d}" for i in range(1, 11)]
        for s1 in names:
            for s2 in names:
                if s1 != s2:
                    sign(s1, s2)
    # users sign the a-servers and rw nodes
    for u in ("u01", "u02", "u03", "u04"):
        for i in range(1, 11):
            sign(u, f"a{i:02d}")
        for i in range(1, 7):
            sign(u, f"rw{i:02d}")
    # a07-a10 sign the users' certs (u04 deliberately unsigned)
    for u in ("u01", "u02", "u03"):
        for i in (7, 8, 9, 10):
            sign(f"a{i:02d}", u)
    return nodes


@pytest.fixture()
def universe():
    return mkuniverse()


def build_graph(nodes, self_name):
    g = Graph()
    g.add_nodes(list(nodes.values()))
    g.set_self_nodes([nodes[self_name]])
    return g


def names_of(nodeset, nodes):
    byid = {n.id: name for name, n in nodes.items()}
    return sorted(byid[n.id] for n in nodeset)


def test_bfs_reachable(universe):
    g = build_graph(universe, "u01")
    # distance 0: just self
    r0 = g.get_reachable_nodes(universe["u01"].id, 0)
    assert names_of(r0, universe) == ["u01"]
    # distance 1: everything u01 signed
    r1 = g.get_reachable_nodes(universe["u01"].id, 1)
    expected = ["u01"] + [f"a{i:02d}" for i in range(1, 11)] + [
        f"rw{i:02d}" for i in range(1, 7)
    ]
    assert names_of(r1, universe) == sorted(expected)
    # distance 2: + users signed by a07-a10 (u02, u03), b-clique unreachable
    r2 = g.get_reachable_nodes(universe["u01"].id, 2)
    assert "u02" in names_of(r2, universe)
    assert "b01" not in names_of(r2, universe)
    # BFS visits each node once
    ids = [n.id for n in r2]
    assert len(ids) == len(set(ids))


def test_user_seed_clique(universe):
    g = build_graph(universe, "u01")
    cliques = g.get_cliques(universe["u01"].id, 0)
    assert len(cliques) == 1
    # u01 <-> a07..a10 are mutually signed: that's the seed clique
    assert names_of(cliques[0].nodes, universe) == [
        "a07",
        "a08",
        "a09",
        "a10",
        "u01",
    ]


def test_server_clique_and_weight(universe):
    g = build_graph(universe, "u01")
    cliques = g.get_cliques(universe["u01"].id, 2)
    byset = {tuple(names_of(c.nodes, universe)): c for c in cliques}
    a_clique = byset.get(tuple(f"a{i:02d}" for i in range(1, 11)))
    assert a_clique is not None
    # weight = #edges from the seed (u01) into the clique: u01 signed all 10
    assert a_clique.weight == 10


def test_nonunique_maximal_clique_bails(universe):
    # x is mutually signed with members of two disjoint cliques -> the
    # unique-maximal-clique assumption breaks and the seed yields nothing
    # (reference: graph.go:332-362)
    nodes = universe
    x = FakeNode(999, "x", address="http://x")
    nodes["x"] = x
    for peer in ("a01", "b01"):
        x.signer_ids.add(nodes[peer].id)
        nodes[peer].signer_ids.add(x.id)
    g = build_graph(nodes, "x")
    cliques = g.get_cliques(x.id, 0)
    assert cliques == []


def test_revoke_removes_and_blocks_readd(universe):
    g = build_graph(universe, "u01")
    a01 = universe["a01"]
    g.revoke(a01)
    assert not g.in_graph(a01)
    assert a01.id in g.revoked
    # re-adding is blocked
    g.add_nodes([a01])
    assert not g.in_graph(a01)
    # the a-clique shrinks to 9
    cliques = g.get_cliques(universe["u01"].id, 2)
    sizes = sorted(len(c.nodes) for c in cliques)
    assert 9 in sizes


def test_in_reachable(universe):
    g = build_graph(universe, "a01")
    # who signed u01 (besides destinations themselves)?
    res = g.get_in_reachable([universe["u01"]])
    got = names_of(res, universe)
    assert got == ["a07", "a08", "a09", "a10"]


def test_wotqs_cert_quorum_params(universe):
    g = build_graph(universe, "a01")
    qs = WotQS(g)
    qr = qs.choose_quorum(q.CERT | q.AUTH)
    # distance 0 from a01: the 10-clique; CERT -> threshold = f+1
    assert len(qr.qcs) == 1
    qc = qr.qcs[0]
    assert (qc.f, qc.min, qc.threshold, qc.suff) == (3, 10, 4, 7)
    a_nodes = [universe[f"a{i:02d}"] for i in range(1, 11)]
    assert qr.is_quorum(a_nodes)
    assert qr.is_threshold(a_nodes[:4])
    assert not qr.is_threshold(a_nodes[:3])
    assert qr.is_sufficient(a_nodes[:7])
    assert not qr.is_sufficient(a_nodes[:6])
    assert not qr.reject(a_nodes[:3])
    assert qr.reject(a_nodes[:4])


def test_wotqs_auth_quorum_threshold(universe):
    g = build_graph(universe, "a01")
    qs = WotQS(g)
    qa = qs.choose_quorum(q.AUTH)
    qc = qa.qcs[0]
    assert qc.threshold == 7  # 2f+1 for AUTH
    assert qa.get_threshold() == sum(c.threshold for c in qa.qcs)


def test_wotqs_peer_excludes_self(universe):
    g = build_graph(universe, "a01")
    qs = WotQS(g)
    qp = qs.choose_quorum(q.AUTH | q.PEER)
    all_nodes = {n.id for qc in qp.qcs for n in qc.nodes}
    assert universe["a01"].id not in all_nodes
    # 9-node clique: f = 2
    assert qp.qcs[0].f == 2


def test_wotqs_write_quorum_covers_peers(universe):
    g = build_graph(universe, "a01")
    qs = WotQS(g)
    qw = qs.choose_quorum(q.WRITE)
    # Pure WRITE drops the clique qcs and keeps only the complements:
    # "W = U - {Ci} + R" (wotqs.go:103-113). From a01 that is every peer
    # outside the a-clique, with f == 0 (any node may store).
    covered = {n.id for qc in qw.qcs for n in qc.nodes}
    for name, n in universe.items():
        if name.startswith(("b", "rw")):
            assert n.id in covered, name
        if name.startswith("a"):
            assert n.id not in covered, name
    assert all(qc.f == 0 for qc in qw.qcs)
    # time phase uses READ|AUTH which *keeps* the cliques (client.go:64)
    qt = qs.choose_quorum(q.READ | q.AUTH)
    t_covered = {n.id for qc in qt.qcs for n in qc.nodes}
    assert universe["a02"].id in t_covered


def test_wotqs_inactive_nodes_filtered(universe):
    g = build_graph(universe, "a01")
    qs = WotQS(g)
    universe["a02"].active = False
    qr = qs.choose_quorum(q.CERT | q.AUTH)
    assert universe["a02"].id not in {n.id for n in qr.nodes()}
    universe["a02"].active = True


def test_tally_matches_host_predicates(universe):
    g = build_graph(universe, "a01")
    qs = WotQS(g)
    qr = qs.choose_quorum(q.AUTH)
    membership, index = qr.membership_matrix()
    bounds = qr.bounds()
    rng = np.random.default_rng(0)
    universe_nodes = {n.id: n for n in universe.values()}
    ids = list(index.keys())
    batch = []
    masks = []
    for _ in range(64):
        k = rng.integers(0, len(ids) + 1)
        chosen = rng.choice(ids, size=k, replace=False) if k else []
        nodes = [universe_nodes[i] for i in chosen]
        batch.append(nodes)
        masks.append(qr.mask_of(nodes))
    cand = np.stack(masks) if masks else np.zeros((0, len(ids)), bool)
    th = np.asarray(
        tally.is_threshold_batch(membership, cand, bounds["threshold"])
    )
    su = np.asarray(tally.is_sufficient_batch(membership, cand, bounds["suff"]))
    rj = np.asarray(tally.reject_batch(membership, cand, bounds["f"]))
    iq = np.asarray(
        tally.is_quorum_batch(membership, cand, bounds["f"], bounds["min"])
    )
    for i, nodes in enumerate(batch):
        assert th[i] == qr.is_threshold(nodes)
        assert su[i] == qr.is_sufficient(nodes)
        assert rj[i] == qr.reject(nodes)
        assert iq[i] == qr.is_quorum(nodes)


def test_equivocation_pairs():
    # 3 values at one timestamp; node 2 signed two of them
    sets = np.zeros((3, 5), dtype=bool)
    sets[0, [0, 2]] = True
    sets[1, [1, 2]] = True
    sets[2, [3]] = True
    eq = np.asarray(tally.equivocation_pairs(sets))
    assert list(np.nonzero(eq)[0]) == [2]
