"""RNS P-256 field core (ops/ec_rns) vs the host Jacobian oracle.

Property tests over random scalars/points, identity/doubling edge
lanes, and the ECDSA verify equation — the VERDICT r3 "rebuild the
P-256 kernel with the RNS playbook" gate.
"""

from __future__ import annotations

import secrets

import pytest

pytest.importorskip("jax")

from bftkv_tpu.crypto.ec import P256  # noqa: E402
from bftkv_tpu.ops import ec_rns  # noqa: E402


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_scalar_mult_matches_host_oracle():
    pts, ks, want = [], [], []
    for i in range(8):
        d = 1 + secrets.randbelow(P256.n - 1)
        pt = P256.scalar_base_mult(d)
        k = secrets.randbelow(P256.n)
        pts.append(pt)
        ks.append(k)
        want.append(P256.scalar_mult(pt, k))
    got = ec_rns.scalar_mult_hosts(pts, ks)
    assert got == want


def test_identity_and_edge_scalars():
    g = (P256.gx, P256.gy)
    pts = [None, g, g, g, g]
    ks = [5, 0, 1, P256.n, P256.n - 1]
    got = ec_rns.scalar_mult_hosts(pts, ks)
    assert got[0] is None  # k·O = O
    assert got[1] is None  # 0·G = O
    assert got[2] == g  # 1·G = G
    assert got[3] is None  # n·G = O
    assert got[4] == P256.scalar_mult(g, P256.n - 1)


def test_small_scalars_exercise_doubling_lanes():
    # 2·G hits the H≡0 doubling lane inside the window adds.
    g = (P256.gx, P256.gy)
    ks = list(range(1, 9))
    got = ec_rns.scalar_base_mult_hosts(ks)
    for k, pt in zip(ks, got):
        assert pt == P256.scalar_mult(g, k)


def test_ecdsa_equation_on_rns_backend(monkeypatch):
    # Full ECDSA verify through ops.ec with the RNS backend forced:
    # u1·G + u2·Q must reconstruct R for genuine signatures only.
    monkeypatch.setenv("BFTKV_EC_BACKEND", "rns")
    monkeypatch.setenv("BFTKV_EC_VERIFY_THRESHOLD", "0")
    monkeypatch.setenv("BFTKV_EC_SIGN_THRESHOLD", "0")
    from bftkv_tpu.crypto import ecdsa

    key = ecdsa.generate()
    msgs = [b"rns-%d" % i for i in range(4)]
    sigs = ecdsa.sign_batch(msgs, key)
    for m, s in zip(msgs, sigs):
        assert ecdsa.verify_host(m, s, key.public)
    items = [(m, s, key.public) for m, s in zip(msgs, sigs)]
    items[1] = (msgs[1], sigs[2], key.public)
    assert ecdsa.verify_batch(items) == [True, False, True, True]
