"""Repo hygiene: no orphaned bytecode, and the BFTKV_* flag seam.

The gateway prototype left six ``.pyc`` files in
``bftkv_tpu/gateway/__pycache__/`` whose source was never committed
(ROADMAP item 1) — bytecode that outlives its module is at best dead
weight and at worst something importable that no review ever saw.
Every compiled module under the package must have its matching ``.py``
next to the ``__pycache__`` directory.
"""

from pathlib import Path

import bftkv_tpu


def test_no_orphaned_bytecode():
    pkg = Path(bftkv_tpu.__file__).resolve().parent
    orphans = []
    for pyc in pkg.rglob("__pycache__/*.pyc"):
        # cpython bytecode names look like "module.cpython-310.pyc".
        stem = pyc.name.split(".", 1)[0]
        src = pyc.parent.parent / f"{stem}.py"
        if not src.exists():
            orphans.append(str(pyc.relative_to(pkg)))
    assert not orphans, (
        "bytecode without committed source (delete it or commit the "
        f"module): {orphans}"
    )


def test_no_bftkv_flag_read_outside_flags_seam():
    """Every ``BFTKV_*`` environment read in the package goes through
    ``bftkv_tpu/flags.py`` (the registry seam): a raw ``os.environ`` /
    ``getenv`` read of a ``BFTKV_*`` name anywhere else would ship an
    undeclared, undocumented flag — the 48-vs-16 README drift this PR
    closed.  tools/bftlint enforces the same rule with AST precision;
    this source-level sweep keeps it self-enforcing even for code that
    never crosses the lint step (and double-checks the linter)."""
    import re

    pkg = Path(bftkv_tpu.__file__).resolve().parent
    pat = re.compile(
        r"(?:environ(?:\.get)?\s*[\(\[]|getenv\s*\()\s*f?['\"]BFTKV_"
    )
    offenders = []
    for py in pkg.rglob("*.py"):
        if py.name == "flags.py" and py.parent == pkg:
            continue
        for i, line in enumerate(py.read_text().split("\n"), 1):
            if pat.search(line):
                offenders.append(f"{py.relative_to(pkg)}:{i}: {line.strip()}")
    assert not offenders, (
        "BFTKV_* flags must be read through the bftkv_tpu.flags seam "
        "(declare in the registry, read via flags.raw/get/enabled):\n"
        + "\n".join(offenders)
    )


def test_every_declared_flag_is_read_somewhere():
    """The registry stays honest in the other direction too: a flag
    declared in flags.py but referenced nowhere in the package is dead
    documentation (either wire it up or delete the declaration)."""
    from bftkv_tpu import flags

    pkg = Path(bftkv_tpu.__file__).resolve().parent
    blob = "\n".join(
        py.read_text()
        for py in pkg.rglob("*.py")
        if not (py.name == "flags.py" and py.parent == pkg)
    )
    dead = [name for name in flags.declared() if name not in blob]
    assert not dead, f"declared but never read anywhere: {dead}"
