"""Repo hygiene: no orphaned bytecode in the package tree.

The gateway prototype left six ``.pyc`` files in
``bftkv_tpu/gateway/__pycache__/`` whose source was never committed
(ROADMAP item 1) — bytecode that outlives its module is at best dead
weight and at worst something importable that no review ever saw.
Every compiled module under the package must have its matching ``.py``
next to the ``__pycache__`` directory.
"""

from pathlib import Path

import bftkv_tpu


def test_no_orphaned_bytecode():
    pkg = Path(bftkv_tpu.__file__).resolve().parent
    orphans = []
    for pyc in pkg.rglob("__pycache__/*.pyc"):
        # cpython bytecode names look like "module.cpython-310.pyc".
        stem = pyc.name.split(".", 1)[0]
        src = pyc.parent.parent / f"{stem}.py"
        if not src.exists():
            orphans.append(str(pyc.relative_to(pkg)))
    assert not orphans, (
        "bytecode without committed source (delete it or commit the "
        f"module): {orphans}"
    )
