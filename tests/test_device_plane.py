"""Device-resident crypto plane (ISSUE 19): persistent staging rings
(ops/devbuf), async mega-batch dispatch, and online recalibration.

All tier-1 tests here run on stub kernels — the real CPU-XLA RNS pow
compile costs ~23 s per shape and belongs to the slow tier.  The stub
DECODES the staged device operands (base halves, exponent nibbles,
CRT-reconstructed moduli) and answers from host ``pow``, so a staging
bug — wrong live rows, wrong pad broadcast, a slot reused while a
flush is in flight — shows up as a bit-for-bit mismatch against the
independently computed expected values.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

pytest.importorskip("jax")

from bftkv_tpu.metrics import registry as metrics  # noqa: E402
from bftkv_tpu.ops import devbuf, dispatch  # noqa: E402
from bftkv_tpu.ops import rns  # noqa: E402

M512 = (1 << 511) + 187  # odd pseudo-moduli, two limb-width classes
M768 = (1 << 767) + 183


# -- buffer ring ownership --------------------------------------------------


def test_ring_never_hands_out_inflight_slot():
    ring = devbuf.BufferRing(
        "t:ring", lambda: {"a": np.zeros(4)}, slots=2, width="t"
    )
    s1 = ring.acquire()
    s2 = ring.acquire()
    assert s1 is not None and s2 is not None and s1 is not s2
    assert s1.in_flight and s2.in_flight
    # Saturated: acquire must NOT block liveness — None tells the
    # caller to allocate fresh, and the overflow is counted.
    assert ring.acquire() is None
    assert ring.overflows == 1
    f = ring.fresh()
    assert f.in_flight and f is not s1 and f is not s2
    ring.release(f)  # unpooled: no-op, never re-enters the ring
    assert ring.acquire() is None
    ring.release(s1)
    s3 = ring.acquire()
    assert s3 is s1 and s3.seq == 2  # recycled only after release
    with pytest.raises(AssertionError):
        ring.release(s2)
        ring.release(s2)  # double release is a detected bug, not silent


def test_ring_acquire_waits_for_release():
    ring = devbuf.BufferRing(
        "t:wait", lambda: {"a": np.zeros(1)}, slots=1, width="t"
    )
    s = ring.acquire()
    t = threading.Timer(0.05, ring.release, args=(s,))
    t.start()
    try:
        got = ring.acquire(timeout=2.0)
        assert got is s  # the release woke the waiter within timeout
    finally:
        t.cancel()
        ring.release(got)


# -- stub device kernel -----------------------------------------------------


def _crt_int(ctx, residues) -> int:
    """Rebuild the modulus from its staged base-prime residues."""
    m = 0
    for r, p in zip(residues, ctx.pb):
        mi = ctx.M // p
        m += ((int(r) * pow(mi % p, -1, p)) % p) * mi
    return m % ctx.M


def _stub_jitted_pow(seen: list, crash_bases: frozenset = frozenset()):
    """A drop-in for ``rns._jitted_pow`` that decodes the STAGED
    buffers (not the caller's lists) and answers from host ``pow`` —
    staging corruption cannot cancel out."""

    def fake(digits, n_bits, donate=False):
        ctx = rns.context(digits, n_bits)
        k = ctx.k

        def g(bh, nt, ix, ukey):
            seen.append(
                {
                    "digits": digits,
                    "rings": devbuf.stats(),
                }
            )
            mods_u = [_crt_int(ctx, row[:k]) for row in np.asarray(ukey[0])]
            out = np.empty((bh.shape[0], k), dtype=np.float32)
            for j in range(bh.shape[0]):
                b = int.from_bytes(bh[j].tobytes(), "little")
                if b in crash_bases:
                    raise RuntimeError("injected kernel crash")
                e = 0
                for nib in nt[:, j]:
                    e = (e << 4) | int(nib)
                m = mods_u[int(ix[j])]
                v = pow(b, e, m)
                for i, p in enumerate(ctx.pb):
                    mi = ctx.M // p
                    out[j, i] = (v % p) * pow(mi % p, -1, p) % p
            return out

        return g

    return fake


@pytest.fixture()
def stub_kernel(monkeypatch):
    seen: list = []
    monkeypatch.setattr(rns, "_jitted_pow", _stub_jitted_pow(seen))
    monkeypatch.setattr(rns, "_shardable", lambda _batch: False)
    devbuf.reset()
    metrics.reset()
    yield seen
    devbuf.reset()
    metrics.reset()


# -- staged parity: two width classes, interleaved tenants ------------------


def test_interleaved_widths_scatter_back_bit_for_bit(stub_kernel):
    """Two tenants interleave RSA-512- and RSA-768-class items through
    the async dispatcher; every scattered result must equal host
    ``pow`` exactly, and no staging slot may be reused while its
    launch is in flight."""
    d = dispatch.ModexpDispatcher(
        max_batch=256, max_wait=0.02, calibrate=False, device_threshold=2
    ).start()
    results: dict[int, list[int]] = {}
    try:

        def tenant(tid: int) -> None:
            items = [
                (3 + tid * 100 + i, 65537, M512 if i % 2 else M768)
                for i in range(8)
            ]
            results[tid] = (d.submit(items), items)

        threads = [
            threading.Thread(target=tenant, args=(t,)) for t in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        d.stop()
    for got, items in results.values():
        assert list(got) == [pow(b, e, m) for b, e, m in items]
    # Both width classes launched through the device tier...
    assert {s["digits"] for s in stub_kernel} == {32, 48}
    # ...each with its staging slot held in flight DURING the kernel
    # call (the stub snapshots ring state from inside the launch).
    for s in stub_kernel:
        busy = [r for r in s["rings"].values() if r["in_flight"] > 0]
        assert busy, "kernel ran without an in-flight staging slot"
    # All slots returned to their rings once the flushes completed.
    for r in devbuf.stats().values():
        assert r["in_flight"] == 0 and r["acquires"] >= 1
    snap = metrics.snapshot()
    assert snap.get("modexp.device", 0) == 16
    assert "dispatch.launch_rtt" in snap  # the EWMA observed the RTT


def test_kernel_crash_mid_flush_releases_slot_and_falls_back(monkeypatch):
    """A launch that dies mid-flush (device fault, tenant-poisoned
    batch) must release its staging slot — not leak it in flight — and
    the flush still answers every caller via the host tier."""
    seen: list = []
    sentinel = 424243  # base staged for the doomed 512-class launch
    monkeypatch.setattr(
        rns, "_jitted_pow", _stub_jitted_pow(seen, frozenset({sentinel}))
    )
    monkeypatch.setattr(rns, "_shardable", lambda _batch: False)
    devbuf.reset()
    metrics.reset()
    d = dispatch.ModexpDispatcher(
        max_batch=256, max_wait=0.01, calibrate=False, device_threshold=2
    ).start()
    try:
        items = [(sentinel, 65537, M512), (5, 65537, M512), (7, 3, M768)]
        got = d.submit(items)
        assert list(got) == [pow(b, e, m) for b, e, m in items]
        # The crashed width group fell back to host; the healthy one
        # (768-class) still answered from the stub device tier.
        snap = metrics.snapshot()
        assert snap.get("modexp.host", 0) >= 2
        assert snap.get("modexp.device", 0) == 1
        for r in devbuf.stats().values():
            assert r["in_flight"] == 0  # the crash released the slot
        # The ring is healthy: the next flush reuses it and succeeds.
        ok = d.submit([(11, 65537, M512), (13, 65537, M512)])
        assert list(ok) == [pow(11, 65537, M512), pow(13, 65537, M512)]
    finally:
        d.stop()
        devbuf.reset()
        metrics.reset()


def test_power_mod_rns_devbuf_off_matches_on(stub_kernel, monkeypatch):
    """BFTKV_DISPATCH_DEVBUF=off: throwaway staging arrays, identical
    results — the ring is an optimization, never a semantic."""
    bases, exps, mods = [9, 10, 11], [65537, 3, 17], [M512] * 3
    want = [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    assert rns.power_mod_rns(bases, exps, mods, n_bits=512) == want
    assert devbuf.stats()  # ring path engaged
    devbuf.reset()
    monkeypatch.setenv("BFTKV_DISPATCH_DEVBUF", "off")
    assert rns.power_mod_rns(bases, exps, mods, n_bits=512) == want
    assert devbuf.stats() == {}  # no ring was minted


# -- async dispatch layer ---------------------------------------------------


class _FakeAsyncDispatcher(dispatch._BatchDispatcher):
    """Deterministic async subclass: launches record order, block on
    per-launch events, and can be told to raise at completion."""

    name = "modexpdispatch"  # registered metric prefix

    def __init__(self, **kw):
        super().__init__(**kw)
        self.launched: list = []
        self.finalized: list = []
        self.gates: dict = {}
        self.fail = set()

    def _run_batch(self, items):
        return [("sync", it) for it in items]

    def _launch_batch(self, items):
        tag = items[0]
        self.launched.append(tag)
        gate = self.gates.get(tag)

        def complete():
            if gate is not None:
                assert gate.wait(10)
            if tag in self.fail:
                raise RuntimeError(f"completion failed: {tag}")
            self.finalized.append(tag)
            return [("async", it) for it in items]

        return complete


def test_async_flushes_finalize_fifo_and_overlap():
    """Flush N+1 must launch while flush N's completion is still
    pending (the overlap the async plane exists for), and completions
    scatter FIFO so callers observe synchronous-path ordering."""
    d = _FakeAsyncDispatcher(
        max_batch=8, max_wait=0.005, calibrate=False, pipeline=1
    )
    assert d._async  # BFTKV_DISPATCH_ASYNC defaults on
    d.start()
    assert d._drain is not None
    g1, g2 = threading.Event(), threading.Event()
    d.gates.update({"a1": g1, "b1": g2})
    out: dict = {}
    try:
        t1 = threading.Thread(
            target=lambda: out.update(r1=d.submit(["a1", "a2"]))
        )
        t1.start()
        # Wait for launch 1 to be dispatched (completion gated open).
        deadline = threading.Event()
        for _ in range(200):
            if d.launched:
                break
            deadline.wait(0.01)
        assert d.launched == ["a1"]
        t2 = threading.Thread(
            target=lambda: out.update(r2=d.submit(["b1"]))
        )
        t2.start()
        # The second flush launches while the first is still gated:
        # host assembly of N+1 overlapped device execution of N.
        for _ in range(200):
            if len(d.launched) == 2:
                break
            deadline.wait(0.01)
        assert d.launched == ["a1", "b1"]
        assert not d.finalized
        g2.set()  # completion 2 ready FIRST...
        deadline.wait(0.05)
        assert d.finalized == []  # ...but FIFO holds it behind 1
        g1.set()
        t1.join(10)
        t2.join(10)
        assert d.finalized == ["a1", "b1"]
        assert out["r1"] == [("async", "a1"), ("async", "a2")]
        assert out["r2"] == [("async", "b1")]
    finally:
        g1.set()
        g2.set()
        d.stop()
    assert d._drain is None  # stop() drained the completion thread


def test_async_completion_error_reaches_callers_only_of_that_flush():
    d = _FakeAsyncDispatcher(
        max_batch=4, max_wait=0.002, calibrate=False, pipeline=1
    ).start()
    d.fail.add("bad")
    try:
        with pytest.raises(RuntimeError, match="completion failed"):
            d.submit(["bad"])
        assert d.submit(["fine"]) == [("async", "fine")]
    finally:
        d.stop()


def test_async_off_restores_synchronous_flush(monkeypatch):
    """BFTKV_DISPATCH_ASYNC=off: no drain thread, _launch_batch never
    consulted — the pre-r11 synchronous flush, byte for byte."""
    monkeypatch.setenv("BFTKV_DISPATCH_ASYNC", "off")

    class _NeverAsync(_FakeAsyncDispatcher):
        def _launch_batch(self, items):
            pytest.fail("_launch_batch called with ASYNC=off")

    d = _NeverAsync(max_batch=4, max_wait=0.002, calibrate=False).start()
    try:
        assert not d._async and d._drain is None
        assert d.submit(["x", "y"]) == [("sync", "x"), ("sync", "y")]
    finally:
        d.stop()


# -- calibration lifecycle --------------------------------------------------


def test_crossover_override_and_recalibrate(monkeypatch):
    try:
        monkeypatch.setenv("BFTKV_DISPATCH_CROSSOVER", "48")
        cal = dispatch.calibration(force=True)
        assert cal["source"] == "override"
        assert cal["verify_crossover"] == 48
        assert cal["prefer_host"] is False
        # <= 0 pins always-host regardless of backend.
        monkeypatch.setenv("BFTKV_DISPATCH_CROSSOVER", "0")
        cal = dispatch.calibration(force=True)
        assert cal["prefer_host"] is True
        assert cal["verify_crossover"] == dispatch.ALWAYS_HOST
        # recalibrate() re-applies the fresh verdict to installed
        # dispatchers without restarting them.
        monkeypatch.setenv("BFTKV_DISPATCH_CROSSOVER", "33")
        d = dispatch.install(
            dispatch.VerifyDispatcher(max_batch=8, max_wait=0.001)
        )
        try:
            cal = dispatch.recalibrate()
            assert cal["verify_crossover"] == 33
            assert d.verifier.host_threshold == 33
        finally:
            dispatch.uninstall()
    finally:
        # Un-cache the override so later tests see a real probe.
        monkeypatch.delenv("BFTKV_DISPATCH_CROSSOVER", raising=False)
        dispatch.calibration(force=True)


def test_launch_rtt_ewma_feeds_observed_calibration(monkeypatch):
    monkeypatch.setattr(dispatch, "_LAUNCH_RTT_EWMA", None)
    dispatch.note_launch_rtt(0.100)
    dispatch.note_launch_rtt(0.200)
    rtt = dispatch.observed_launch_rtt()
    assert rtt == pytest.approx(0.8 * 0.100 + 0.2 * 0.200)
    # CPU backends stay pinned no matter what the EWMA says — the
    # CPU-XLA kernels lose at every batch size (the r05 regression).
    cal = dispatch.calibration(force=True)
    assert cal["backend"] != "cpu" or cal["prefer_host"] is True


# -- sidecar: /recalibrate hook + device_plane stats ------------------------


def test_sidecar_recalibrate_hook_and_device_plane_stats(tmp_path):
    from bftkv_tpu.cmd import verify_sidecar as vs

    addr = f"unix:{tmp_path}/devplane.sock"
    stats = "127.0.0.1:19731"
    srv, _t = vs.serve(addr, stats=stats)
    try:
        metrics.reset()
        with urllib.request.urlopen(
            f"http://{stats}/recalibrate", timeout=10
        ) as r:
            cal = json.loads(r.read())
        assert cal["source"] in ("probe", "observed", "override")
        assert "verify_crossover" in cal
        with urllib.request.urlopen(
            f"http://{stats}/info", timeout=10
        ) as r:
            info = json.loads(r.read())
        plane = info["sidecar"]["device_plane"]
        assert plane["calibration"]["backend"] == cal["backend"]
        assert plane["recalibrations"] >= 1
        assert isinstance(plane["buffer_rings"], dict)
        # POST works too (the devtools-hook convention).
        req = urllib.request.Request(
            f"http://{stats}/recalibrate", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["backend"] == cal["backend"]
    finally:
        srv.service.stop()
        srv.shutdown()
        srv.server_close()
        metrics.reset()


def test_sidecar_first_launch_triggers_recalibration(tmp_path, monkeypatch):
    """The first accelerator-backed launch (observed_launch_rtt turns
    non-None) re-prices the crossover within the short wake interval,
    not after the full BFTKV_DISPATCH_RECAL_S period."""
    from bftkv_tpu.cmd import verify_sidecar as vs

    monkeypatch.setenv("BFTKV_DISPATCH_RECAL_S", "3600")
    monkeypatch.setattr(
        vs.SidecarService, "_RECAL_TICK", 0.05, raising=False
    )
    addr = f"unix:{tmp_path}/firstlaunch.sock"
    srv, _t = vs.serve(addr)
    try:
        metrics.reset()
        dispatch.note_launch_rtt(0.010)  # "a launch completed"
        deadline = threading.Event()
        # Wait on THIS service's first-launch latch, not the bare
        # counter: a predecessor test's recal thread can outlive its
        # stop() join timeout and bump the global counter after our
        # metrics.reset(), satisfying a counter-only wait early.
        for _ in range(200):
            if (srv.service._recal_seen_rtt
                    and metrics.snapshot().get(
                        "sidecar.recalibrations", 0) >= 1):
                break
            deadline.wait(0.05)
        assert metrics.snapshot().get("sidecar.recalibrations", 0) >= 1
        assert srv.service._recal_seen_rtt is True
    finally:
        srv.service.stop()
        srv.shutdown()
        srv.server_close()
        metrics.reset()


# -- capacity plane wiring --------------------------------------------------


def test_capacity_rows_carry_launch_rtt_and_ring_saturation():
    from bftkv_tpu.obs import capacity

    metrics.reset()
    try:
        metrics.incr("modexpdispatch.flushes", 4)
        metrics.incr("modexpdispatch.items", 64)
        metrics.observe("modexpdispatch.batch", 16)
        metrics.gauge("dispatch.launch_rtt", 0.042)
        metrics.gauge(
            "devbuf.saturation", 0.75, labels={"width": "32"}
        )
        metrics.gauge(
            "devbuf.saturation", 0.25, labels={"width": "ec"}
        )
        idx = capacity._index(metrics.snapshot())
        row = capacity.compute_member(idx, {}, 1.0)["dispatch"]
        assert row["launch_rtt_s"] == pytest.approx(0.042)
        assert row["buffer_rings"] == {"32": 0.75, "ec": 0.25}
        assert row["saturation"] >= 0.75  # ring pressure surfaces
    finally:
        metrics.reset()


# -- real-kernel parity (slow tier) -----------------------------------------


@pytest.mark.slow  # ~23 s/shape CPU-XLA compile: tier-2 only
def test_staged_parity_real_kernel():
    devbuf.reset()
    bases, exps = [3, 5, 7], [65537, 65537, 3]
    mods = [M512, M512, M512]
    want = [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    assert rns.power_mod_rns(bases, exps, mods, n_bits=512) == want
    deferred = rns.power_mod_rns(bases, exps, mods, n_bits=512, defer=True)
    assert deferred.wait() == want
    for r in devbuf.stats().values():
        assert r["in_flight"] == 0
