"""Acceptance path for the fleet health plane: a live 2-shard loopback
cluster, scraped → aggregated → f-budget.  Kill one clique replica and
exactly that shard's budget decrements while the other stays full
(ISSUE 7 acceptance criterion), with the outage in the anomaly feed.
"""

from __future__ import annotations

import pytest

from bftkv_tpu import trace
from bftkv_tpu.metrics import registry
from bftkv_tpu.obs import FleetCollector, LocalSource
from tests.cluster_utils import start_cluster


@pytest.fixture(scope="module")
def fleet():
    cl = start_cluster(4, 1, 4, bits=1024, n_shards=2)
    idents = cl.universe.servers + cl.universe.storage_nodes
    sources = [
        LocalSource(ident.name, (lambda s=srv: s))
        for ident, srv in zip(idents, cl.all_servers)
    ]
    coll = FleetCollector(
        sources, local_metrics=registry, local_tracer=trace.tracer
    )
    yield cl, coll
    cl.stop()


def shard_key(client, shard, tag=b"fleet"):
    i = 0
    while i < 4096:
        k = b"%s/%d" % (tag, i)
        if client.qs.shard_of(k) == shard:
            return k
        i += 1
    raise AssertionError("no key for shard")


def test_scrape_aggregate_f_budget(fleet):
    cl, coll = fleet
    c = cl.clients[0]
    for sh in (0, 1):
        c.write(shard_key(c, sh), b"v")
    doc = coll.scrape_once()
    assert set(doc["shards"]) == {"0", "1"}
    for sh, sd in doc["shards"].items():
        # thresholds straight from the wotqs b-masking math for n=4
        assert (sd["n"], sd["f"], sd["threshold"]) == (4, 1, 3)
        assert sd["f_budget"] == {
            "f": 1, "used": 0, "remaining": 1, "down": [],
            "storage_down": [],
        }
        # the routed writes produced a per-shard merged write SLO
        assert sd["slo"]["write"]["count"] >= 1
    assert doc["traces"]["traces"] >= 2
    assert doc["fleet"]["up"] == 16


def test_kill_one_replica_decrements_exactly_that_shard(fleet):
    cl, coll = fleet
    # a clique member of shard 1 (not shard 0, to prove attribution)
    victim_name = None
    for srv in cl.servers:
        if srv.qs.my_shard() == 1:
            victim_name = srv.self_node.name
            srv.tr.stop()
            break
    assert victim_name
    doc = coll.scrape_once()
    assert doc["shards"]["1"]["f_budget"]["used"] == 1
    assert doc["shards"]["1"]["f_budget"]["remaining"] == 0
    assert doc["shards"]["1"]["f_budget"]["down"] == [victim_name]
    assert doc["shards"]["0"]["f_budget"] == {
        "f": 1, "used": 0, "remaining": 1, "down": [], "storage_down": [],
    }
    assert any(
        a["kind"] == "member_down"
        and a["source"] == victim_name
        and a["shard"] == 1
        for a in doc["anomalies"]
    )
    # the shard is AT its fault bound but still live: a routed write to
    # the degraded shard must still commit (2f+1 of the remaining 3)
    c = cl.clients[0]
    k = shard_key(c, 1, tag=b"fleet/degraded")
    c.write(k, b"still-live")
    assert c.read(k) == b"still-live"


def test_fleet_endpoint_serves_the_same_budget(fleet):
    """The /fleet HTTP surface over the live collector reports the
    degraded shard exactly as the in-process document does."""
    import json
    import urllib.request

    from bftkv_tpu.obs.http import serve_fleet

    _cl, coll = fleet
    httpd = serve_fleet(coll, "127.0.0.1:0")
    try:
        port = httpd.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet", timeout=10
        ) as r:
            doc = json.loads(r.read())
        assert doc["shards"]["1"]["f_budget"]["remaining"] == 0
        assert doc["shards"]["0"]["f_budget"]["remaining"] == 1
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fleet",
            headers={"accept": "text/plain"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            text = r.read().decode()
        assert 'bftkv_fleet_f_budget_remaining{shard="1"} 0' in text
    finally:
        httpd.shutdown()
