"""tools/bftlint: every rule catches its planted violation, waivers
suppress, the clean fixture passes, and HEAD itself lints clean.

Fixtures are synthesized into a tmp tree shaped like the repo
(``bftkv_tpu/protocol/...``) so the layer-scoped rules engage; the tmp
tree gets the REAL registry modules (flags.py, metrics.py) copied in,
so declared-flag and label-key extraction run against the genuine
source of truth.
"""

import shutil
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools import bftlint

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def tree(tmp_path):
    pkg = tmp_path / "bftkv_tpu"
    (pkg / "protocol").mkdir(parents=True)
    shutil.copy(REPO / "bftkv_tpu" / "flags.py", pkg / "flags.py")
    shutil.copy(REPO / "bftkv_tpu" / "metrics.py", pkg / "metrics.py")
    shutil.copy(REPO / "bftkv_tpu" / "trace.py", pkg / "trace.py")
    return tmp_path


def lint(tree, source, rel="bftkv_tpu/protocol/fixture.py"):
    p = tree / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return bftlint.lint_paths([str(p)], root=str(tree))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# -- env-flag ---------------------------------------------------------------


def test_env_flag_direct_read_caught(tree):
    fs = lint(tree, """\
        import os
        v = os.environ.get("BFTKV_PIGGYBACK", "on")
    """)
    assert rules_of(fs) == ["env-flag"]


def test_env_flag_subscript_and_getenv_caught(tree):
    fs = lint(tree, """\
        import os
        a = os.environ["BFTKV_REPAIR"]
        b = os.getenv("BFTKV_HEDGE")
    """)
    assert len(fs) == 2 and rules_of(fs) == ["env-flag"]


def test_env_flag_undeclared_name_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu import flags
        v = flags.raw("BFTKV_TOTALLY_NOT_DECLARED")
    """)
    assert rules_of(fs) == ["env-flag"]
    assert "not declared" in fs[0].message


def test_env_flag_declared_seam_read_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu import flags
        v = flags.raw("BFTKV_PIGGYBACK", "on")
        w = flags.enabled("BFTKV_REPAIR")
    """)
    assert fs == []


# -- label-enum -------------------------------------------------------------


def test_label_enum_bad_key_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu.metrics import registry as metrics
        metrics.incr("server.thing", labels={"variable": "x"})
    """)
    assert rules_of(fs) == ["label-enum"]
    assert "variable" in fs[0].message


def test_label_enum_unresolvable_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu.metrics import registry as metrics
        def f(labels):
            metrics.incr("server.thing", labels=labels)
    """)
    assert rules_of(fs) == ["label-enum"]


def test_label_enum_local_assignment_and_ifexp_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu.metrics import registry as metrics
        def f(shard):
            labels = {"shard": shard} if shard is not None else None
            metrics.incr("server.thing", labels=labels)
            metrics.observe("server.lat", 0.1, labels={"cmd": "write"})
    """)
    assert fs == []


# -- failpoint-guard --------------------------------------------------------


def test_failpoint_unguarded_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu.faults import failpoint as fp
        def hook():
            act = fp.fire("storage.write", backend="x")
            return act
    """)
    assert rules_of(fs) == ["failpoint-guard"]


def test_failpoint_guard_is_branch_sensitive(tree):
    """A fire() on the DISARMED side of a guard must still flag: the
    else branch of `if fp.ARMED:`, and code below an inverted
    `if fp.ARMED: return` early return, both run exactly when
    disarmed."""
    fs = lint(tree, """\
        from bftkv_tpu.faults import failpoint as fp
        def hook_else():
            if fp.ARMED:
                pass
            else:
                fp.fire("storage.write", backend="x")
        def hook_inverted_return(data):
            if fp.ARMED:
                return data
            return fp.fire("transport.send", cmd="x")
    """)
    assert [f.rule for f in fs] == ["failpoint-guard", "failpoint-guard"]


def test_failpoint_guarded_variants_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu.faults import failpoint as fp
        def hook_if():
            if fp.ARMED:
                return fp.fire("storage.write", backend="x")
        def hook_early_return(data):
            if not fp.ARMED:
                return data
            act = fp.fire("transport.send", cmd="x")
            return act or data
    """)
    assert fs == []


# -- interned-error ---------------------------------------------------------


def test_interned_error_runtime_error_caught(tree):
    fs = lint(tree, """\
        def handler():
            raise RuntimeError("catastrophic wire failure")
    """)
    assert rules_of(fs) == ["interned-error"]


def test_interned_error_dynamic_new_error_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu.errors import new_error
        def decline(peer):
            raise new_error(f"go away {peer}")
    """)
    assert rules_of(fs) == ["interned-error"]
    assert "dynamic" in fs[0].message


def test_interned_error_constant_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu.errors import new_error
        ERR_X = new_error("transport: fixture error")
        def decline():
            raise ERR_X
    """)
    assert fs == []


# -- swallowed-exception ----------------------------------------------------


def test_bare_except_caught(tree):
    fs = lint(tree, """\
        def f():
            try:
                g()
            except:
                pass
    """)
    assert "swallowed-exception" in rules_of(fs)


def test_broad_swallow_without_comment_caught(tree):
    fs = lint(tree, """\
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    assert rules_of(fs) == ["swallowed-exception"]


def test_swallow_with_comment_or_narrow_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu.errors import ERR_NOT_FOUND
        def f():
            try:
                g()
            except Exception:
                pass  # best-effort cleanup: peer already gone
            try:
                g()
            except ERR_NOT_FOUND:
                pass
    """)
    assert fs == []


# -- named-lock -------------------------------------------------------------


def test_named_lock_direct_construction_caught(tree):
    fs = lint(tree, """\
        import threading
        _lock = threading.Lock()
    """)
    assert rules_of(fs) == ["named-lock"]


def test_named_lock_seam_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu.devtools.lockwatch import named_lock
        _lock = named_lock("protocol.fixture")
    """)
    assert fs == []


# -- span-phase -------------------------------------------------------------


def test_span_phase_undeclared_name_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu import trace

        def f():
            with trace.span("totally.new.span"):
                pass
    """)
    assert rules_of(fs) == ["span-phase"]


def test_span_phase_declared_forms_clean(tree):
    fs = lint(tree, """\
        from bftkv_tpu import trace

        def f(name):
            with trace.span("phase.write_sign"):      # exact
                pass
            with trace.span("rpc.anything_new"):      # prefix rule
                pass
            with trace.span(f"server.{name}"):        # f-string prefix
                pass
            with trace.span(name, phase="dispatch"):  # explicit phase
                pass
    """)
    assert fs == []


def test_span_phase_dynamic_without_phase_caught(tree):
    fs = lint(tree, """\
        from bftkv_tpu import trace

        def f(self, name):
            with trace.span(f"{self.name}.flush"):  # no leading literal
                pass
            with trace.span(name):                  # unresolvable
                pass
            with trace.span(name, phase="not-a-phase"):
                pass
    """)
    assert [f.rule for f in fs] == ["span-phase"] * 3


# -- waivers ----------------------------------------------------------------


def test_waiver_suppresses_only_named_rule(tree):
    fs = lint(tree, """\
        import os
        a = os.environ.get("BFTKV_PIGGYBACK")  # bftlint: ignore[env-flag] fixture
        b = os.environ.get("BFTKV_REPAIR")
    """)
    assert len(fs) == 1 and fs[0].line == 3


def test_waiver_on_preceding_line(tree):
    fs = lint(tree, """\
        import os
        # bftlint: ignore[env-flag] fixture reason
        a = os.environ.get("BFTKV_PIGGYBACK")
    """)
    assert fs == []


# -- clean fixture + the real tree ------------------------------------------


def test_clean_fixture_passes(tree):
    fs = lint(tree, """\
        from bftkv_tpu import flags
        from bftkv_tpu.devtools.lockwatch import named_lock
        from bftkv_tpu.errors import ERR_NOT_FOUND
        from bftkv_tpu.faults import failpoint as fp
        from bftkv_tpu.metrics import registry as metrics

        _lock = named_lock("protocol.fixture")
        _ON = flags.raw("BFTKV_PIGGYBACK", "on") != "off"

        def handler(storage, variable):
            if fp.ARMED:
                fp.fire("storage.write", backend="fixture")
            try:
                raw = storage.read(variable, 0)
            except ERR_NOT_FOUND:
                return None
            metrics.incr("server.reads", labels={"cmd": "read"})
            return raw
    """)
    assert fs == []


def test_head_lints_clean():
    """The merged tree must stay bftlint-clean (the CI "Invariant
    lint" step asserts the same from a named job)."""
    findings = bftlint.lint_repo(str(REPO))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tree, tmp_path):
    bad = tree / "bftkv_tpu" / "protocol" / "bad.py"
    bad.write_text('import os\nv = os.environ.get("BFTKV_PIGGYBACK")\n')
    assert (
        bftlint.main([str(bad), "--root", str(tree), "--json"]) == 1
    )
    good = tree / "bftkv_tpu" / "protocol" / "good.py"
    good.write_text("x = 1\n")
    assert bftlint.main([str(good), "--root", str(tree)]) == 0


def test_cli_module_runs_clean_on_repo():
    """`python -m tools.bftlint` — the exact CI invocation — exits 0
    on HEAD and prints the clean banner."""
    res = subprocess.run(
        [sys.executable, "-m", "tools.bftlint"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "clean" in res.stdout


def test_readme_freshness_check_detects_staleness(tmp_path):
    (tmp_path / "bftkv_tpu").mkdir()
    shutil.copy(
        REPO / "bftkv_tpu" / "flags.py",
        tmp_path / "bftkv_tpu" / "flags.py",
    )
    (tmp_path / "bftkv_tpu" / "__init__.py").write_text("")
    from bftkv_tpu import flags as real_flags

    stale = (
        real_flags.README_BEGIN
        + "\n| old table |\n"
        + real_flags.README_END
    )
    (tmp_path / "README.md").write_text(stale)
    fs = bftlint.check_readme(str(tmp_path))
    assert len(fs) == 1 and fs[0].rule == "readme-flags"
    (tmp_path / "README.md").write_text(
        "# x\n\n" + real_flags.readme_table() + "\n"
    )
    assert bftlint.check_readme(str(tmp_path)) == []
