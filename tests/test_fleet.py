"""Fleet health plane (bftkv_tpu/obs): trace export/drain semantics,
cross-process stitching, f-budget aggregation, the anomaly feed, and
the /fleet HTTP surface — all against fake or in-process sources (the
live-cluster path is tests/test_fleet_cluster.py)."""

from __future__ import annotations

import json
import threading
import urllib.request

from bftkv_tpu import trace
from bftkv_tpu.metrics import BUCKETS, Metrics
from bftkv_tpu.obs import FleetCollector, Stitcher
from bftkv_tpu.obs.collector import parse_flat_key


# -- trace export / drain ---------------------------------------------------


def test_export_cursor_drains_incrementally():
    t = trace.Tracer(max_spans=64)
    old, trace.tracer = trace.tracer, t
    try:
        with trace.span("a"):
            pass
        out = t.export(0)
        assert [s["name"] for s in out["spans"]] == ["a"]
        assert out["dropped"] == 0
        cur = out["cursor"]
        with trace.span("b"):
            pass
        out2 = t.export(cur)
        assert [s["name"] for s in out2["spans"]] == ["b"]
        # nothing new: empty drain, cursor stable
        out3 = t.export(out2["cursor"])
        assert out3["spans"] == [] and out3["dropped"] == 0
    finally:
        trace.tracer = old


def test_export_reports_ring_overflow_as_dropped():
    t = trace.Tracer(max_spans=4)
    old, trace.tracer = trace.tracer, t
    try:
        cur = t.export(0)["cursor"]
        for i in range(10):
            with trace.span(f"s{i}"):
                pass
        out = t.export(cur)
        # ring holds the newest 4; the 6 older ones are honestly lost
        assert len(out["spans"]) == 4
        assert out["dropped"] == 6
        # a cursor AHEAD of the sequence (process restarted) resyncs
        t.reset()
        with trace.span("fresh"):
            pass
        out2 = t.export(cur + 1000)
        assert [s["name"] for s in out2["spans"]] == ["fresh"]
    finally:
        trace.tracer = old


def test_ring_dropped_is_reader_relative():
    """The cumulative overwrite gauge counts only spans evicted before
    ANY reader drained them: a full ring whose tail every scrape keeps
    up with loses nothing — otherwise the TRACE DROPS warning would
    fire forever on any busy long-lived daemon."""
    t = trace.Tracer(max_spans=4)
    old, trace.tracer = trace.tracer, t
    try:
        for i in range(10):
            with trace.span(f"s{i}"):
                pass
        out = t.export(0)
        # spans 1-6 were overwritten before this first drain: real loss
        assert out["ring_dropped"] == 6
        cur = out["cursor"]
        # the ring stays full, but these evictions overwrite spans the
        # drain above was already offered — not loss
        for i in range(4):
            with trace.span(f"t{i}"):
                pass
        out2 = t.export(cur)
        assert out2["ring_dropped"] == 6
        assert [s["name"] for s in out2["spans"]] == [
            "t0", "t1", "t2", "t3",
        ]
    finally:
        trace.tracer = old


def test_slow_dropped_is_reader_relative():
    t = trace.Tracer(max_spans=64, max_slow=2, slow_threshold=0.0)
    old, trace.tracer = trace.tracer, t
    try:
        for i in range(4):
            with trace.span(f"s{i}"):
                pass
        # 4 slow roots through a 2-deep ring, never read: 2 lost
        assert t.export(0)["slow_dropped"] == 2
        t.slow()  # a reader drained the ring
        for i in range(2):
            with trace.span(f"u{i}"):
                pass
        # the 2 evictions overwrote already-read entries: not loss
        assert t.export(0)["slow_dropped"] == 2
    finally:
        trace.tracer = old


def test_export_vs_record_race_loses_nothing():
    """Concurrent drain-vs-record: every recorded span shows up in
    exactly one drain (no loss, no duplication) as long as the ring
    does not overflow."""
    t = trace.Tracer(max_spans=65536)
    old, trace.tracer = trace.tracer, t
    try:
        n_threads, per_thread = 4, 500
        seen: list = []
        stop = threading.Event()

        def drain():
            cur = 0
            while True:
                out = t.export(cur)
                assert out["dropped"] == 0
                cur = out["cursor"]
                seen.extend(s["name"] for s in out["spans"])
                if stop.is_set() and not out["spans"]:
                    return

        def record(k: int):
            for i in range(per_thread):
                with trace.span(f"w{k}.{i}"):
                    pass

        drainer = threading.Thread(target=drain)
        writers = [
            threading.Thread(target=record, args=(k,))
            for k in range(n_threads)
        ]
        drainer.start()
        for w in writers:
            w.start()
        for w in writers:
            w.join()
        stop.set()
        drainer.join()
        # Filter to this test's own spans: an async tail from an
        # earlier test's fan-out pool may legitimately record into the
        # swapped-in tracer (pool threads outlive their test).
        mine = [n for n in seen if n.startswith("w") and "." in n]
        assert len(mine) == n_threads * per_thread
        assert len(set(mine)) == len(mine)
    finally:
        trace.tracer = old


def test_slow_trace_carries_shard_and_peer():
    t = trace.Tracer(slow_threshold=0.0)
    old, trace.tracer = trace.tracer, t
    try:
        with trace.span("client.write", attrs={"shard": 1}):
            with trace.span("rpc.write", attrs={"peer": "b02"}):
                pass
        entry = t.slow()[0]
        assert entry["shard"] == 1
        assert entry["peer"] == "b02"
    finally:
        trace.tracer = old


# -- stitching --------------------------------------------------------------


def _span(tid, sid, name, parent=None, duration=1.0, attrs=None):
    d = {
        "trace": tid,
        "span": sid,
        "name": name,
        "start": 0.0,
        "duration": duration,
    }
    if parent:
        d["parent"] = parent
    if attrs:
        d["attrs"] = attrs
    return d


def test_stitcher_joins_sources_and_dedups():
    st = Stitcher()
    assert st.add("a01", [_span("t1", "s1", "client.write", duration=2.0)]) == 1
    # re-scrape overlap: same span again is not double counted
    assert st.add("a01", [_span("t1", "s1", "client.write")]) == 0
    st.add("rw01", [_span("t1", "s2", "server.write", parent="s1")])
    assert st.summary() == {"traces": 1, "stitched": 1}
    [tr] = st.traces()
    assert tr["root"] == "client.write" and tr["stitched"]
    assert tr["sources"] == ["a01", "rw01"]
    tree = st.tree("t1")
    assert tree["children"][0]["name"] == "client.write"
    assert tree["children"][0]["children"][0]["src"] == "rw01"
    assert st.tree("nope") is None


def test_stitcher_bounded():
    st = Stitcher(max_traces=4)
    for i in range(10):
        st.add("x", [_span(f"t{i}", f"s{i}", "root")])
    assert st.summary()["traces"] == 4


# -- flat-key parsing -------------------------------------------------------


def test_parse_flat_key():
    assert parse_flat_key("plain") == ("plain", {})
    assert parse_flat_key("a.b{shard=1,le=0.5}") == (
        "a.b", {"shard": "1", "le": "0.5"}
    )


# -- collector over fake sources --------------------------------------------


class FakeSource:
    """A scriptable fleet member."""

    def __init__(self, name, shard, clique, up=True):
        self.name = name
        self.up = up
        self._info = {
            "name": name,
            "shard": shard,
            "shard_count": 2,
            "role": "clique" if name in clique["members"] else "storage",
            "clique": clique,
            "owned_buckets": 128,
        }
        self.snap: dict = {}
        self.spans: list = []
        self.slow: list = []

    def info(self):
        return self._info

    def metrics(self):
        if not self.up:
            raise OSError("down")
        return self.snap

    def trace_export(self, cursor):
        return {
            "cursor": cursor + len(self.spans),
            "dropped": 0,
            "spans": self.spans,
            "slow": self.slow,
        }

    def probe(self):
        return self.up


def _clique(names):
    n = len(names)
    f = (n - 1) // 3
    return {
        "n": n,
        "f": f,
        "threshold": 2 * f + 1,
        "suff": f + (n - f) // 2 + 1,
        "members": sorted(names),
    }


def _two_shard_fleet():
    ca = _clique(["a01", "a02", "a03", "a04"])
    cb = _clique(["b01", "b02", "b03", "b04"])
    srcs = [FakeSource(n, 0, ca) for n in ca["members"]]
    srcs += [FakeSource(n, 1, cb) for n in cb["members"]]
    srcs.append(FakeSource("rw01", 0, ca))  # storage member of shard 0
    return srcs


def test_f_budget_decrements_only_the_dark_members_shard():
    srcs = _two_shard_fleet()
    coll = FleetCollector(srcs)
    doc = coll.scrape_once()
    assert set(doc["shards"]) == {"0", "1"}
    for sd in doc["shards"].values():
        assert sd["f_budget"] == {
            "f": 1, "used": 0, "remaining": 1, "down": [],
            "storage_down": [],
        }
    next(s for s in srcs if s.name == "b02").up = False
    doc = coll.scrape_once()
    assert doc["shards"]["1"]["f_budget"]["remaining"] == 0
    assert doc["shards"]["1"]["f_budget"]["down"] == ["b02"]
    assert doc["shards"]["0"]["f_budget"]["remaining"] == 1
    kinds = [(a["kind"], a["source"], a["shard"]) for a in doc["anomalies"]]
    assert ("member_down", "b02", 1) in kinds
    # a dark STORAGE node alarms but does not consume the clique budget
    next(s for s in srcs if s.name == "rw01").up = False
    doc = coll.scrape_once()
    assert doc["shards"]["0"]["f_budget"]["remaining"] == 1
    assert doc["shards"]["0"]["f_budget"]["storage_down"] == ["rw01"]
    # recovery emits member_up and restores the budget
    next(s for s in srcs if s.name == "b02").up = True
    doc = coll.scrape_once()
    assert doc["shards"]["1"]["f_budget"]["remaining"] == 1
    assert any(a["kind"] == "member_up" for a in doc["anomalies"])


def test_counter_deltas_become_anomalies_once():
    srcs = _two_shard_fleet()
    coll = FleetCollector(srcs)
    coll.scrape_once()
    a01 = srcs[0]
    a01.snap = {"server.wrong_shard{shard=0}": 3, "server.equivocation": 1}
    doc = coll.scrape_once()
    got = {
        (a["kind"], a["source"], a["shard"], a["count"])
        for a in doc["anomalies"]
    }
    assert ("wrong_shard", "a01", 0, 3) in got
    assert ("equivocation", "a01", 0, 1) in got
    # unchanged counters do not re-fire
    n = len(coll.anomalies())
    coll.scrape_once()
    assert len(coll.anomalies()) == n


def test_slo_histograms_merge_across_members_per_shard():
    srcs = _two_shard_fleet()
    bucket_of = lambda le: (
        f"client.write.latency.bucket{{shard=1,le={le}}}"
    )
    # two daemons each observed one write into the 0.25 bucket
    for s in srcs[4:6]:
        s.snap = {bucket_of(0.25): 1}
    coll = FleetCollector(srcs)
    doc = coll.scrape_once()
    slo = doc["shards"]["1"]["slo"]["write"]
    assert slo["count"] == 2
    assert slo["p50_le_s"] == 0.25
    assert slo["buckets"][BUCKETS.index(0.25)] == 2
    assert "write" not in doc["shards"]["0"]["slo"]


def test_slow_entries_become_shard_exemplars():
    srcs = _two_shard_fleet()
    srcs[0].slow = [
        {"trace_id": "abc", "root": "client.write", "duration": 2.0,
         "shard": 0, "peer": "a03"}
    ]
    coll = FleetCollector(srcs)
    doc = coll.scrape_once()
    [ex] = doc["shards"]["0"]["exemplars"]
    assert ex["trace_id"] == "abc" and ex["peer"] == "a03"
    assert doc["shards"]["1"]["exemplars"] == []


def test_fleet_http_endpoint_json_and_prometheus():
    from bftkv_tpu.obs.http import serve_fleet

    coll = FleetCollector(_two_shard_fleet())
    coll.scrape_once()
    httpd = serve_fleet(coll, "127.0.0.1:0")
    port = httpd.server_address[1]
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet", timeout=10
        ) as r:
            assert r.headers["content-type"].startswith("application/json")
            doc = json.loads(r.read())
        assert doc["fleet"]["daemons"] == 9
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fleet",
            headers={"accept": "text/plain"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            text = r.read().decode()
        assert 'bftkv_fleet_f_budget_remaining{shard="0"} 1' in text
        assert 'bftkv_fleet_f_budget_remaining{shard="1"} 1' in text
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as r:
            assert r.read() == b"ok\n"
    finally:
        httpd.shutdown()


def test_local_metrics_feed_and_render():
    """The in-process feed path (nemesis mode): a process-wide registry
    backs counter-delta anomalies, and the CLI renderer accepts the
    document."""
    from bftkv_tpu.cmd.fleet import render

    reg = Metrics()
    coll = FleetCollector(_two_shard_fleet(), local_metrics=reg)
    coll.scrape_once()
    reg.incr("transport.peer.opens", 2)
    doc = coll.scrape_once()
    assert any(
        a["kind"] == "peer_circuit_open" and a["count"] == 2
        for a in doc["anomalies"]
    )
    text = render(doc)
    assert "shard 0" in text and "budget 1/1" in text


def test_fleet_prometheus_one_type_line_per_family():
    """A second '# TYPE' line for one metric name is a parse error in
    a real Prometheus server — multi-shard fleets must group samples
    per family (and histograms need a _sum for rate(sum)/rate(count))."""
    srcs = _two_shard_fleet()
    for s in srcs[:2]:
        s.snap = {
            "client.write.latency.bucket{shard=0,le=0.25}": 1,
            "client.write.latency.sum{shard=0}": 0.2,
        }
    for s in srcs[4:6]:
        s.snap = {
            "client.write.latency.bucket{shard=1,le=0.5}": 1,
            "client.write.latency.sum{shard=1}": 0.4,
        }
    coll = FleetCollector(srcs)
    coll.scrape_once()
    text = coll.prometheus()
    seen = set()
    for line in text.splitlines():
        if line.startswith("# TYPE"):
            _, _, name, _typ = line.split()
            assert name not in seen, f"duplicate TYPE for {name}"
            seen.add(name)
    assert 'bftkv_fleet_shard_n{shard="0"} 4' in text
    assert 'bftkv_fleet_shard_n{shard="1"} 4' in text
    assert 'bftkv_fleet_write_latency_sum{shard="0"} 0.4' in text
    assert 'bftkv_fleet_write_latency_sum{shard="1"} 0.8' in text
    assert 'bftkv_fleet_write_latency_count{shard="0"} 2' in text
    doc = coll.health()
    assert doc["shards"]["0"]["slo"]["write"]["sum_s"] == 0.4


def test_info_refreshes_on_cadence_and_recovery():
    """Topology is not static: the collector re-fetches /info on a
    scrape cadence (and after a down→up transition) so membership
    churn reseats the health document instead of going stale."""
    srcs = _two_shard_fleet()
    coll = FleetCollector(srcs)
    coll.INFO_REFRESH_SCRAPES = 10**9  # cadence off for this test
    coll.scrape_once()
    mover = next(s for s in srcs if s.name == "a04")
    mover._info = dict(mover._info, shard=1)
    coll.scrape_once()
    # no refresh yet: still seated in shard 0
    assert any(
        m["name"] == "a04"
        for m in coll.health()["shards"]["0"]["members"]
    )
    coll.INFO_REFRESH_SCRAPES = 1  # every scrape is a refresh tick
    coll.scrape_once()
    doc = coll.health()
    assert any(
        m["name"] == "a04" for m in doc["shards"]["1"]["members"]
    )
    assert not any(
        m["name"] == "a04" for m in doc["shards"]["0"]["members"]
    )
    # recovery refresh: a member that went down and came back re-reads
    # its seat even with the cadence off
    coll.INFO_REFRESH_SCRAPES = 10**9
    mover.up = False
    coll.scrape_once()
    mover._info = dict(mover._info, shard=0)
    mover.up = True
    coll.scrape_once()  # up-transition marks stale...
    coll.scrape_once()  # ...next scrape re-fetches
    assert any(
        m["name"] == "a04"
        for m in coll.health()["shards"]["0"]["members"]
    )


def test_down_from_boot_member_is_unseated_not_misbinned():
    """A member that never answered /info has an UNKNOWN seat: binning
    it into shard 0 would let its real shard report a full f-budget
    with a clique member dark.  It must surface as fleet.unseated (and
    the CLI must refuse to call the fleet healthy)."""
    from bftkv_tpu.cmd.fleet import _exit_code

    class DeadSource:
        name = "127.0.0.1:9"

        def info(self):
            raise OSError("connection refused")

        def metrics(self):
            raise OSError("connection refused")

        def trace_export(self, cursor):
            raise OSError("connection refused")

        def probe(self):
            return False

    srcs = _two_shard_fleet() + [DeadSource()]
    coll = FleetCollector(srcs)
    doc = coll.scrape_once()
    assert doc["fleet"]["unseated"] == ["127.0.0.1:9"]
    assert "127.0.0.1:9" in doc["fleet"]["down"]
    # no shard claims it, and no budget silently absorbs it
    for sd in doc["shards"].values():
        assert all(m["name"] != "127.0.0.1:9" for m in sd["members"])
        assert sd["f_budget"]["remaining"] == sd["f_budget"]["f"]
    assert _exit_code(doc) == 1
