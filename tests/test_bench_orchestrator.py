"""The bench orchestrator's evidence policy (VERDICT r3 item 1).

bench.py is the round's measurement record; its cache/fallback state
machine decides what the driver's end-of-round run reports when the
accelerator tunnel flaps.  These tests fake the probe and the section
subprocesses and pin the policy:

- live TPU results persist per section and win;
- a dead tunnel reuses cached TPU captures, labeled with capture time;
- a FAST-mode capture never stands in for a full-matrix record;
- a genuine section error is reported, never masked by a stale cache;
- a hung child (tunnel died mid-run) falls back to cache and marks
  health unknown so the next section re-probes.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "PARTIAL_PATH", str(tmp_path / "partial.json"))
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("BENCH_CONFIGS", "tally")
    return mod


def _run_main(mod, capsys) -> dict:
    mod.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(line)


def test_live_tpu_result_persists_and_wins(bench, monkeypatch, capsys):
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "tpu",
            "devices": ["TPU_0"],
            "jax": "x",
            "result": {"tallies_per_sec": 123.0},
        },
    )
    out = _run_main(bench, capsys)
    assert out["extra"]["backend"] == "tpu"
    assert out["extra"]["revoke_tally_256"]["tallies_per_sec"] == 123.0
    saved = bench._load_partial()
    assert saved["sections"]["revoke_tally_256"]["backend"] == "tpu"


def test_dead_tunnel_reuses_cached_capture_labeled(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    monkeypatch.setattr(
        bench, "_run_child",
        lambda *a, **k: pytest.fail("no child may run on a dead tunnel "
                                    "when a cache exists"),
    )
    out = _run_main(bench, capsys)
    sec = out["extra"]["revoke_tally_256"]
    assert sec["tallies_per_sec"] == 999.0
    assert sec["cached_from"] == "2026-07-30T12:00:00Z"
    assert out["extra"]["backend"] == "tpu"
    assert out["extra"]["cached_sections"] == ["revoke_tally_256"]


def test_fast_mode_capture_rejected_for_full_run(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": True,  # smoke capture
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    # tally is CPU_OK, so the orchestrator measures on CPU instead of
    # splicing in the incomparable FAST capture.
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "cpu",
            "devices": ["CPU_0"],
            "jax": "x",
            "result": {"tallies_per_sec": 7.0},
        },
    )
    out = _run_main(bench, capsys)
    sec = out["extra"]["revoke_tally_256"]
    assert sec["tallies_per_sec"] == 7.0
    assert "cached_from" not in sec
    assert "cpu" in out["extra"]["backend"]


def test_section_error_not_masked_by_cache(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "tpu",
            "devices": ["TPU_0"],
            "jax": "x",
            "result": {"error": "AssertionError: kernel wrong"},
        },
    )
    out = _run_main(bench, capsys)
    assert "error" in out["extra"]["revoke_tally_256"]


def test_hung_child_falls_back_to_cache(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench, "_run_child", lambda token, t, force_cpu: None  # hang/kill
    )
    out = _run_main(bench, capsys)
    sec = out["extra"]["revoke_tally_256"]
    assert sec["tallies_per_sec"] == 999.0
    assert sec["cached_from"] == "2026-07-30T12:00:00Z"
