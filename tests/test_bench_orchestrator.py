"""The bench orchestrator's evidence policy (VERDICT r3 item 1, r4 item 2).

bench.py is the round's measurement record; its cache/fallback state
machine decides what the driver's end-of-round run reports when the
accelerator tunnel flaps.  These tests fake the probe and the section
subprocesses and pin the policy:

- live TPU results persist per section and win;
- a dead tunnel reuses cached TPU captures, labeled with capture time;
- a FAST-mode capture never stands in for a full-matrix record;
- a genuine section error is reported, never masked by a stale cache;
- a hung child (tunnel died mid-run) falls back to cache and marks
  health unknown so the next section re-probes;
- the final stdout line is COMPACT (<1 KB) so the driver's bounded
  stdout tail can never truncate away the headline (r04's failure),
  with the full record in BENCH_detail.json and on stderr;
- cached captures carry a code fingerprint; reuse after a source change
  is flagged `cached_stale_code` (ADVICE r4 #2);
- a probe failure before one section does NOT doom the rest of the run:
  the orchestrator re-probes (bounded) and resumes live on a revived
  tunnel (r05: a mid-run flap skipped 13 sections permanently);
- the headline prefers TPU-backed sections over CPU fallbacks (r04's
  headline was CPU cluster_4 while a TPU kernel capture sat cached);
- each section gets its own timeout budget so a hang costs minutes.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "PARTIAL_PATH", str(tmp_path / "partial.json"))
    monkeypatch.setattr(mod, "DETAIL_PATH", str(tmp_path / "detail.json"))
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_SECTION_TIMEOUT", raising=False)
    monkeypatch.setenv("BENCH_CONFIGS", "tally")
    return mod


def _run_main(mod, capsys):
    """Run main(); return (compact stdout record, full detail record)."""
    mod.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    compact = json.loads(line)
    with open(mod.DETAIL_PATH) as f:
        detail = json.load(f)
    return compact, detail


def test_live_tpu_result_persists_and_wins(bench, monkeypatch, capsys):
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "tpu",
            "devices": ["TPU_0"],
            "jax": "x",
            "result": {"tallies_per_sec": 123.0},
        },
    )
    compact, detail = _run_main(bench, capsys)
    assert compact["extra"]["backend"] == "tpu"
    assert compact["extra"]["sections"]["revoke_tally_256"] == ["tpu", 123.0]
    assert detail["extra"]["revoke_tally_256"]["tallies_per_sec"] == 123.0
    saved = bench._load_partial()
    assert saved["sections"]["revoke_tally_256"]["backend"] == "tpu"
    # Captures are stamped with the code fingerprint for staleness checks.
    assert saved["sections"]["revoke_tally_256"]["code"] == bench._code_fingerprint()


def test_dead_tunnel_reuses_cached_capture_labeled(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "code": bench._code_fingerprint(),
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    monkeypatch.setattr(
        bench, "_run_child",
        lambda *a, **k: pytest.fail("no child may run on a dead tunnel "
                                    "when a cache exists"),
    )
    compact, detail = _run_main(bench, capsys)
    sec = detail["extra"]["revoke_tally_256"]
    assert sec["tallies_per_sec"] == 999.0
    assert sec["cached_from"] == "2026-07-30T12:00:00Z"
    assert "cached_stale_code" not in sec  # fingerprint matches HEAD
    assert detail["extra"]["backend"] == "tpu"
    assert detail["extra"]["cached_sections"] == ["revoke_tally_256"]
    assert compact["extra"]["sections"]["revoke_tally_256"] == ["cached", 999.0]


def test_cached_capture_from_older_code_is_flagged(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "code": "deadbeef0000",  # pre-change fingerprint
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "cpu",
            "devices": ["CPU_0"],
            "jax": "x",
            "result": {"tallies_per_sec": 7.0},
        },
    )
    compact, detail = _run_main(bench, capsys)
    sec = detail["extra"]["revoke_tally_256"]
    # Still the best evidence available — reused, but honestly labeled.
    assert sec["tallies_per_sec"] == 999.0
    assert sec["cached_stale_code"] is True
    assert compact["extra"]["sections"]["revoke_tally_256"] == [
        "cached-stale", 999.0,
    ]


def test_fast_mode_capture_rejected_for_full_run(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": True,  # smoke capture
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    # tally is CPU_OK, so the orchestrator measures on CPU instead of
    # splicing in the incomparable FAST capture.
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "cpu",
            "devices": ["CPU_0"],
            "jax": "x",
            "result": {"tallies_per_sec": 7.0},
        },
    )
    compact, detail = _run_main(bench, capsys)
    sec = detail["extra"]["revoke_tally_256"]
    assert sec["tallies_per_sec"] == 7.0
    assert "cached_from" not in sec
    assert "cpu" in detail["extra"]["backend"]
    # Fallback statuses carry the core count since r10 (cpu/8-fallback)
    # so bench_compare can refuse cross-box comparisons.
    assert compact["extra"]["sections"]["revoke_tally_256"] == [
        f"cpu/{os.cpu_count()}-fallback", 7.0,
    ]


def test_section_error_not_masked_by_cache(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": "revoke_tally_256",
            "backend": "tpu",
            "devices": ["TPU_0"],
            "jax": "x",
            "result": {"error": "AssertionError: kernel wrong"},
        },
    )
    compact, detail = _run_main(bench, capsys)
    assert "error" in detail["extra"]["revoke_tally_256"]
    assert compact["extra"]["sections"]["revoke_tally_256"] == "err"


def test_hung_child_falls_back_to_cache(bench, monkeypatch, capsys):
    bench._save_partial(
        {
            "sections": {
                "revoke_tally_256": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-30T12:00:00Z",
                    "fast_mode": False,
                    "result": {"tallies_per_sec": 999.0},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench, "_run_child", lambda token, t, force_cpu: None  # hang/kill
    )
    compact, detail = _run_main(bench, capsys)
    sec = detail["extra"]["revoke_tally_256"]
    assert sec["tallies_per_sec"] == 999.0
    assert sec["cached_from"] == "2026-07-30T12:00:00Z"
    assert compact["extra"]["sections"]["revoke_tally_256"] == ["cached", 999.0]


def test_probe_recovers_mid_run(bench, monkeypatch, capsys):
    """A tunnel that dies before one section and revives before the
    next resumes live capture (the r05 flap skipped everything after
    one failed probe)."""
    monkeypatch.setenv("BENCH_CONFIGS", "modexp,tally")
    probes = iter([False, True])
    monkeypatch.setattr(bench, "_probe_backend", lambda t: next(probes))
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": bench.SECTION_NAMES[token],
            "backend": "cpu" if force_cpu else "tpu",
            "devices": ["TPU_0"],
            "jax": "x",
            "result": {"tallies_per_sec": 5.0},
        },
    )
    compact, detail = _run_main(bench, capsys)
    assert detail["extra"]["modexp_kernel"].get("skipped")
    assert compact["extra"]["sections"]["revoke_tally_256"] == ["tpu", 5.0]


def test_probe_failures_bounded(bench, monkeypatch, capsys):
    """A dead-all-day tunnel costs at most 3 probe timeouts, not one
    per section (driver-time budget)."""
    monkeypatch.setenv("BENCH_CONFIGS", "rns,sign,kernel,ec,modexp,thr")
    calls = []
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: calls.append(t) or False
    )
    monkeypatch.setattr(
        bench, "_run_child",
        lambda *a, **k: pytest.fail("no child on a dead tunnel"),
    )
    _run_main(bench, capsys)
    assert len(calls) == 3


def test_headline_prefers_tpu_backed_section(bench, monkeypatch, capsys):
    """A cached TPU kernel rate outranks a live CPU-fallback cluster
    number in headline selection (r04 regression)."""
    monkeypatch.setenv("BENCH_CONFIGS", "rns,c4")
    bench._save_partial(
        {
            "sections": {
                "rns_kernel": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-31T03:49:29Z",
                    "fast_mode": False,
                    "code": bench._code_fingerprint(),
                    "result": {"best_verifies_per_sec": 550684.8},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": bench.SECTION_NAMES[token],
            "backend": "cpu",
            "devices": ["CPU_0"],
            "jax": "x",
            "result": {"writes_per_sec": 6.72},
        },
    )
    compact, detail = _run_main(bench, capsys)
    assert compact["metric"] == "rsa2048_verifies_per_sec"
    assert compact["value"] == 550684.8
    # Verify-rate headlines ratio against the per-replica verify
    # requirement (2.2M/s) instead of reporting null.
    assert compact["vs_baseline"] == round(
        550684.8 / bench.NORTH_STAR_VERIFIES_PER_SEC, 5
    )
    assert compact["extra"]["headline_from"] == "rns_kernel"
    # The CPU cluster number still rides along in the record.
    assert detail["extra"]["cluster_4"]["writes_per_sec"] == 6.72


def test_stale_cache_never_beats_fresh_measurement(bench, monkeypatch, capsys):
    """A cached capture of OLDER code is never promoted over a freshly
    measured section — even a CPU-fallback one (r05 regression: the
    headline was a cached-stale rns_kernel while a live cluster_4
    measurement sat in the same record)."""
    monkeypatch.setenv("BENCH_CONFIGS", "rns,c4")
    bench._save_partial(
        {
            "sections": {
                "rns_kernel": {
                    "backend": "tpu",
                    "jax": "x",
                    "devices": ["TPU_0"],
                    "captured": "2026-07-31T03:49:29Z",
                    "fast_mode": False,
                    "code": "stale-fingerprint",  # predates HEAD
                    "result": {"best_verifies_per_sec": 550684.8},
                }
            }
        }
    )
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": bench.SECTION_NAMES[token],
            "backend": "cpu",
            "devices": ["CPU_0"],
            "jax": "x",
            "result": {"writes_per_sec": 6.72},
        },
    )
    compact, detail = _run_main(bench, capsys)
    assert detail["extra"]["rns_kernel"]["cached_stale_code"] is True
    assert compact["extra"]["headline_from"] == "cluster_4"
    assert compact["metric"] == "signed_writes_per_sec_4replica"
    assert compact["value"] == 6.72


def test_per_section_timeout_budgets(bench, monkeypatch, capsys):
    """Sections get sized timeouts (a hung kernel section must not burn
    a cluster-sized budget); BENCH_SECTION_TIMEOUT overrides."""
    monkeypatch.setenv("BENCH_CONFIGS", "modexp,b64")
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    seen = {}

    def child(token, timeout, force_cpu):
        seen[token] = timeout
        return {
            "section": bench.SECTION_NAMES[token],
            "backend": "tpu",
            "devices": ["TPU_0"],
            "jax": "x",
            "result": {"x_per_sec": 1.0},
        }

    monkeypatch.setattr(bench, "_run_child", child)
    _run_main(bench, capsys)
    assert seen == {
        "modexp": bench.TOKEN_TIMEOUT["modexp"],
        "b64": bench.TOKEN_TIMEOUT["b64"],
    }
    assert seen["modexp"] < seen["b64"]

    monkeypatch.setenv("BENCH_SECTION_TIMEOUT", "123")
    seen.clear()
    _run_main(bench, capsys)
    assert seen == {"modexp": 123.0, "b64": 123.0}


def test_final_stdout_line_stays_small(bench, monkeypatch, capsys):
    """The driver keeps a bounded stdout tail; the headline line must
    never outgrow it.  Worst realistic cases: the full 16-section matrix
    with every section skipped (r04's shape), and the full matrix with
    every section reporting a number.
    """
    all_tokens = ",".join(bench.SECTION_NAMES)
    monkeypatch.setenv("BENCH_CONFIGS", all_tokens)

    # Case 1: dead tunnel, empty cache, nothing CPU_OK → all skip/cpu.
    monkeypatch.setattr(bench, "_probe_backend", lambda t: False)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": bench.SECTION_NAMES[token],
            "backend": "cpu",
            "devices": ["CPU_0"],
            "jax": "0.9.0",
            "result": {"writes_per_sec": 7.28, "write_p50_s": 2.03},
        },
    )
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert len(line.encode()) < 1024, f"{len(line)}B: {line[:200]}"
    parsed = json.loads(line)
    assert parsed["metric"]  # headline survived
    assert parsed["extra"]["detail"] == "BENCH_detail.json"

    # Case 2: live TPU, every section reports.
    monkeypatch.setattr(bench, "_probe_backend", lambda t: True)
    monkeypatch.setattr(
        bench,
        "_run_child",
        lambda token, t, force_cpu: {
            "section": bench.SECTION_NAMES[token],
            "backend": "tpu",
            "devices": ["TPU_0"],
            "jax": "0.9.0",
            "result": {
                "writes_per_sec": 123456.78,
                "write_p50_s": 0.001,
                "verifies_device": 10**9,
            },
        },
    )
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert len(line.encode()) < 1536, f"{len(line)}B"
    parsed = json.loads(line)
    assert parsed["extra"]["backend"] == "tpu"
    # Full record retrievable from the detail file.
    with open(bench.DETAIL_PATH) as f:
        detail = json.load(f)
    assert detail["extra"]["cluster_64_batched"]["verifies_device"] == 10**9
