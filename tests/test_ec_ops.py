"""Batched P-256 kernels vs the host oracle (crypto/ec.py).

The reference's EC math comes from Go crypto/elliptic and is exercised
by its threshold-ECDSA tests (crypto/threshold/ecdsa/ecdsa_test.go);
here the device kernels are property-tested against the same scalar
identities on random and adversarial inputs.
"""

import secrets

import pytest

from bftkv_tpu.crypto.ec import P256
from bftkv_tpu.ops import ec as ec_ops

G = (P256.gx, P256.gy)


def host_mul(pt, k):
    return P256.scalar_mult(pt, k)


def test_scalar_base_mult_matches_oracle():
    ks = [1, 2, 3, 7, P256.n - 1, secrets.randbelow(P256.n), secrets.randbelow(P256.n)]
    got = ec_ops.scalar_base_mult_hosts(ks)
    want = [P256.scalar_base_mult(k) for k in ks]
    assert got == want


def test_scalar_mult_arbitrary_points():
    pts, ks = [], []
    for _ in range(6):
        p = P256.scalar_base_mult(secrets.randbelow(P256.n) or 1)
        pts.append(p)
        ks.append(secrets.randbelow(P256.n))
    got = ec_ops.scalar_mult_hosts(pts, ks)
    want = [host_mul(p, k) for p, k in zip(pts, ks)]
    assert got == want


def test_edge_cases():
    p1 = P256.scalar_base_mult(12345)
    pts = [None, p1, p1, G, p1]
    ks = [5, 0, P256.n, 2, P256.n - 1]
    got = ec_ops.scalar_mult_hosts(pts, ks)
    want = [None, None, None, P256.double(G), host_mul(p1, P256.n - 1)]
    assert got == want
    # n-1 · P = -P
    assert got[4] == (p1[0], (-p1[1]) % P256.p)


@pytest.mark.slow  # tier-2: heavy on a small-CPU tier-1 box (see pytest.ini)
def test_add_batch_including_cancellation():
    d = ec_ops.p256()
    a = P256.scalar_base_mult(111)
    b = P256.scalar_base_mult(222)
    neg_a = (a[0], (-a[1]) % P256.p)
    X1, Y1, Z1 = d.encode_points([a, a, a, None, b])
    X2, Y2, Z2 = d.encode_points([b, a, neg_a, b, None])
    out = d.decode_points(*ec_ops.to_affine(*ec_ops.add_batch(X1, Y1, Z1, X2, Y2, Z2)))
    assert out == [P256.add(a, b), P256.double(a), None, b, b]


def test_linear_combine():
    pts = [P256.scalar_base_mult(i + 1) for i in range(5)]
    ks = [3, 1, 4, 1, 5]
    got = ec_ops.linear_combine_hosts(pts, ks)
    want = None
    for p, k in zip(pts, ks):
        want = P256.add(want, host_mul(p, k))
    assert got == want


def test_distributivity_property():
    """(k1 + k2)·G == k1·G + k2·G through the batched kernels alone."""
    k1 = secrets.randbelow(P256.n)
    k2 = secrets.randbelow(P256.n)
    lhs = ec_ops.scalar_base_mult_hosts([(k1 + k2) % P256.n])[0]
    rhs = ec_ops.linear_combine_hosts([G, G], [k1, k2])
    assert lhs == rhs
