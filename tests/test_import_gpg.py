"""GnuPG keyring importer: migrate a reference-shaped universe.

Builds a miniature version of the reference's key universe with real
GnuPG (scripts/setup.sh:17-48 shape: per-node homedirs, cross-signed
via export/sign/import like scripts/trust.sh), then imports it and
checks that identities, secret keys, and VERIFIED trust edges all
arrive natively — and that a tampered certification is rejected.
"""

from __future__ import annotations

import os
import shutil
import subprocess

import pytest

from bftkv_tpu.cmd import import_gpg

GPG = shutil.which("gpg")
pytestmark = pytest.mark.skipif(GPG is None, reason="gpg not installed")


def _gpg(home, *args, stdin: bytes | None = None) -> bytes:
    os.makedirs(home, mode=0o700, exist_ok=True)
    out = subprocess.run(
        [GPG, "--homedir", home, "--batch", "--no-tty", "--yes",
         "--pinentry-mode", "loopback", "--passphrase", "", *args],
        input=stdin, capture_output=True, check=True,
    )
    return out.stdout


def _fpr(home: str) -> str:
    out = _gpg(home, "--list-keys", "--with-colons").decode()
    for line in out.splitlines():
        if line.startswith("fpr:"):
            return line.split(":")[9]
    raise AssertionError("no fingerprint")


@pytest.fixture(scope="module")
def universe(tmp_path_factory):
    """Three nodes; a01 signs rw01's key, rw01 signs a01 and u01
    (trust.sh semantics: signer imports the signed key into its own
    ring).  Every node dir gets pubring.gpg + secring.gpg like
    gen.sh."""
    root = tmp_path_factory.mktemp("gpgu")
    uids = {
        "a01": "a01 (localhost:5701) <svc@example.com>",
        "rw01": "rw01 (localhost:5601) <svc@example.com>",
        "u01": "u01 <foo@example.com>",
    }
    homes = {}
    for name, uid in uids.items():
        home = str(root / f".{name}")
        _gpg(home, "--quick-gen-key", uid, "rsa2048", "sign", "never")
        homes[name] = home

    def cross_sign(signer: str, signee: str) -> None:
        # trust.sh "both" mode: the signed key lands in the signer's
        # ring AND is re-imported into the signee's ring.
        pub = _gpg(homes[signee], "--export")
        _gpg(homes[signer], "--import", stdin=pub)
        _gpg(homes[signer], "--quick-sign-key", _fpr(homes[signee]))
        signed = _gpg(homes[signer], "--export", _fpr(homes[signee]))
        _gpg(homes[signee], "--import", stdin=signed)

    cross_sign("a01", "rw01")
    cross_sign("rw01", "a01")
    cross_sign("rw01", "u01")

    dirs = {}
    for name, home in homes.items():
        d = root / name
        d.mkdir()
        (d / "pubring.gpg").write_bytes(_gpg(home, "--export"))
        (d / "secring.gpg").write_bytes(_gpg(home, "--export-secret-key"))
        dirs[name] = str(d)
    return dirs


def test_full_universe_import(universe, tmp_path):
    res = import_gpg.import_homedirs(list(universe.values()))
    assert len(res.certs) == 3
    assert len(res.secrets) == 3  # every homedir contributed its key
    by_name = {c.name: c for c in res.certs.values()}
    assert set(by_name) == {"a01", "rw01", "u01"}
    assert by_name["a01"].address == "localhost:5701"
    assert by_name["a01"].uid == "svc@example.com"
    assert by_name["u01"].address == ""

    # All three certifications became NATIVE, verifiable signatures.
    got = {
        (s, t) for s, t in res.edges
    }
    want = {
        (by_name["a01"].id, by_name["rw01"].id),
        (by_name["rw01"].id, by_name["a01"].id),
        (by_name["rw01"].id, by_name["u01"].id),
    }
    assert got == want
    assert res.unconverted == []
    for signer_id, signee_id in got:
        signer = res.certs[signer_id]
        assert res.certs[signee_id].verify_signature(signer)

    # The written homes round-trip through the daemon loader.
    out = tmp_path / "native"
    written = import_gpg.write_native_homes(res, str(out))
    assert len(written) == 3
    from bftkv_tpu.topology import load_home

    graph, crypt, qs = load_home(str(out / "rw01"))
    assert crypt.signer.cert.name == "rw01"
    # rw01's graph sees its edge onto a01 (a real cert signature edge).
    reachable = {
        c.id for c in graph.get_reachable_nodes(by_name["rw01"].id, 1)
    }
    assert by_name["a01"].id in reachable


def test_single_homedir_unknown_issuer_dropped(universe):
    # Importing ONLY u01's homedir: rw01's certification rides u01's
    # ring but rw01's PUBLIC key does not — the edge is unverifiable
    # and must be dropped with a note, never converted on faith.
    res = import_gpg.import_homedirs([universe["u01"]])
    by_name = {c.name: c for c in res.certs.values()}
    assert "u01" in by_name
    assert len(res.secrets) == 1
    assert res.edges == []
    assert res.unconverted == []
    assert any("unverifiable" in n for n in res.notes)


def test_verified_edge_without_signer_secret_unconverted(universe, tmp_path):
    # u01's homedir plus a PUBLIC-only copy of rw01's: the rw01->u01
    # certification now verifies, but rw01's secret key is absent —
    # the edge must be reported as unconverted, never forged.
    rw_pub = tmp_path / "rw01-pubonly"
    rw_pub.mkdir()
    with open(os.path.join(universe["rw01"], "pubring.gpg"), "rb") as f:
        (rw_pub / "pubring.gpg").write_bytes(f.read())
    res = import_gpg.import_homedirs([universe["u01"], str(rw_pub)])
    by_name = {c.name: c for c in res.certs.values()}
    assert len(res.secrets) == 1  # only u01's
    # rw01's ring carries rw01->a01 and rw01->u01; neither can be
    # re-signed without rw01's secret.
    assert (by_name["rw01"].id, by_name["u01"].id) not in set(res.edges)
    assert any(t == by_name["u01"].id for _, t in res.unconverted)
    # The unforged edge is NOT embedded in the cert.
    assert by_name["rw01"].id not in by_name["u01"].signatures


def test_tampered_certification_rejected(universe):
    # rw01's pubring carries verifiable certifications (it holds the
    # issuer keys).  Flip a byte near the end of the ring — inside the
    # last signature's MPI — and confirm the importer rejects rather
    # than converts the damaged certification.
    with open(os.path.join(universe["rw01"], "pubring.gpg"), "rb") as f:
        intact_bytes = f.read()
    intact = import_gpg.parse_keyring(intact_bytes)
    intact_edges = sum(
        len(k.certified_by) for k in intact.keys.values()
    )
    assert intact_edges >= 2  # rw01->a01, rw01->u01 at least

    data = bytearray(intact_bytes)
    data[-10] ^= 0x40
    ring = import_gpg.parse_keyring(bytes(data))
    tampered_edges = sum(
        len(k.certified_by) for k in ring.keys.values()
    )
    # The damaged certification must be lost or loudly rejected —
    # never silently kept.
    assert tampered_edges < intact_edges or any(
        "BAD certification" in n or "parse error" in n for n in ring.notes
    )


def test_protected_secret_key_skipped(tmp_path):
    home = str(tmp_path / ".prot")
    os.makedirs(home, mode=0o700)
    subprocess.run(
        [GPG, "--homedir", home, "--batch", "--no-tty", "--yes",
         "--pinentry-mode", "loopback", "--passphrase", "hunter2",
         "--quick-gen-key", "prot <p@x>", "rsa2048", "sign", "never"],
        capture_output=True, check=True,
    )
    d = tmp_path / "prot"
    d.mkdir()
    out = subprocess.run(
        [GPG, "--homedir", home, "--batch", "--no-tty", "--yes",
         "--pinentry-mode", "loopback", "--passphrase", "hunter2",
         "--export-secret-key"],
        capture_output=True, check=True,
    ).stdout
    (d / "secring.gpg").write_bytes(out)
    res = import_gpg.import_homedirs([str(d)])
    # Identity imports; the protected secret is skipped, not decrypted.
    assert len(res.certs) == 1
    assert res.secrets == {}


def test_written_homes_keep_ring_locality(universe, tmp_path):
    """Per-home views (round-5 /verify finding): a home's pubring holds
    its OWN ring's view; the owner's outbound certifications become
    localtrust (local-only graph edges), never cert signatures — a
    union view would pull users into server cliques (DESIGN.md §1.2)."""
    import os as _os

    res = import_gpg.import_homedirs(list(universe.values()))
    out = tmp_path / "homes"
    import_gpg.write_native_homes(res, str(out))
    by_name = {c.name: c for c in res.certs.values()}

    from bftkv_tpu.crypto.keyring import Keyring

    ring = Keyring()
    view = ring.load_pubring(str(out / "rw01" / "pubring"))
    certs = {c.name: c for c in view}
    # rw01's own outbound edge (rw01 signed a01 and u01) is NOT a cert
    # signature in its home...
    assert by_name["rw01"].id not in certs["a01"].signatures
    assert by_name["rw01"].id not in certs["u01"].signatures
    # ...it is localtrust instead.
    with open(_os.path.join(str(out / "rw01"), "localtrust")) as f:
        lt = {int(line, 16) for line in f if line.strip()}
    assert by_name["a01"].id in lt and by_name["u01"].id in lt
    # Inbound edges stay as real signatures (a01 -> rw01).
    assert by_name["a01"].id in certs["rw01"].signatures
