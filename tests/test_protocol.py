"""Protocol-layer integration tests: the reference's tier-3 behavioral
spec, un-skipped (reference: protocol/server_test.go:34-59,
rw_test.go, mal_test.go TOFU scenario, protocol.go Joining)."""

from __future__ import annotations

import time

import pytest

from bftkv_tpu import packet as pkt
from bftkv_tpu import topology
from bftkv_tpu.errors import (
    ERR_INVALID_QUORUM_CERTIFICATE,
    ERR_INVALID_TIMESTAMP,
    Error,
)
from bftkv_tpu.protocol.client import Client
from bftkv_tpu.transport.loopback import TrLoopback

from cluster_utils import start_cluster

BITS = 2048


@pytest.fixture(scope="module")
def cluster():
    c = start_cluster(n_servers=4, n_users=2, bits=BITS, unsigned_users=1)
    yield c
    c.stop()


def test_basic_write_read(cluster):
    """reference: protocol/server_test.go:34-59."""
    cli = cluster.clients[0]
    cli.write(b"test_basic", b"hello world")
    assert cli.read(b"test_basic") == b"hello world"


def test_overwrite_bumps_timestamp(cluster):
    cli = cluster.clients[0]
    cli.write(b"test_over", b"v1")
    cli.write(b"test_over", b"v2")
    assert cli.read(b"test_over") == b"v2"
    # storage holds both versions; latest has t=2
    srv = cluster.storage_servers[0]
    stored = pkt.parse(srv.storage.read(b"test_over", 0))
    assert stored.t == 2
    assert stored.value == b"v2"


def test_write_once_is_final(cluster):
    cli = cluster.clients[0]
    cli.write_once(b"test_once", b"forever")
    assert cli.read(b"test_once") == b"forever"
    # t is pinned at 2^64-1; the next Write's time phase must refuse
    # (reference: client.go:85-87 ErrInvalidTimestamp)
    with pytest.raises(ERR_INVALID_TIMESTAMP):
        cli.write(b"test_once", b"again")


def test_tofu_rejects_foreign_writer(cluster):
    """A different user (different id AND uid) cannot overwrite
    (reference: server.go:329-337, mal_test.go TOFU scenario)."""
    owner, intruder = cluster.clients[0], cluster.clients[1]
    owner.write(b"test_tofu", b"mine")
    # TOFU ownership is established by the CERTIFIED record (pending
    # residue never owns — DESIGN.md §12); settle the async tail first.
    owner.drain_tails()
    with pytest.raises(Error):
        intruder.write(b"test_tofu", b"stolen")
    assert owner.read(b"test_tofu") == b"mine"


def test_unsigned_user_has_no_quorum_certificate(cluster):
    """The unsigned user's cert fails the CERT-quorum threshold at sign
    time (reference: server.go:211-214; setup.sh leaves u04 unsigned)."""
    unsigned = cluster.clients[1]  # last user is the unsigned one
    with pytest.raises(ERR_INVALID_QUORUM_CERTIFICATE):
        unsigned.write(b"test_unsigned_var", b"x")


def test_read_missing_variable(cluster):
    cli = cluster.clients[0]
    assert cli.read(b"test_never_written") is None


def test_read_repair(cluster):
    """A server that missed the write gets healed by the next read
    (reference: client.go:281-302)."""
    cli = cluster.clients[0]
    cli.write(b"test_repair", b"healme")
    cli.drain_tails()  # back-fill delivers the full-quorum copies
    victim = cluster.storage_servers[0]
    # wipe the victim's copy
    victim.storage._data.pop(b"test_repair", None)  # type: ignore[attr-defined]
    assert cli.read(b"test_repair") == b"healme"
    # the read worker finishes write-back asynchronously
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            raw = victim.storage.read(b"test_repair", 0)
            assert pkt.parse(raw).value == b"healme"
            return
        except Exception:
            time.sleep(0.05)
    raise AssertionError("read repair never reached the stale server")


def test_joining_discovers_the_graph():
    """A client knowing one server crawls the whole membership
    (reference: protocol/protocol.go:21-52)."""
    c = start_cluster(n_servers=4, n_users=1, bits=BITS)
    try:
        uni = c.universe
        user = uni.users[0]
        # the newcomer's initial view: itself + one server only
        keep = {user.id, uni.servers[0].id}
        seed = [cc for cc in uni.view_of(user) if cc.id in keep]
        graph, crypt, qs = topology.make_node(user, seed)
        tr = TrLoopback(crypt, c.net)
        newcomer = Client(graph, qs, tr, crypt)
        assert len(graph.get_peers()) == 1
        newcomer.joining()
        ids = {n.id for n in graph.get_peers()}
        for s in uni.servers:
            assert s.cert.id in ids
    finally:
        c.stop()


def test_cluster_on_native_storage(tmp_path_factory):
    """A full protocol round on the C++ log-structured backend —
    incl. the read scan-back which needs ``versions()``
    (reference: server.go:166-180 over leveldb.go:30-46)."""
    from bftkv_tpu.storage.native import NativeStorage

    base = tmp_path_factory.mktemp("nativedb")
    counter = [0]

    def factory():
        counter[0] += 1
        return NativeStorage(str(base / f"db{counter[0]}.log"))

    c = start_cluster(n_servers=4, n_users=1, bits=BITS, storage_factory=factory)
    try:
        cli = c.clients[0]
        cli.write(b"native_rt", b"v1")
        cli.write(b"native_rt", b"v2")
        assert cli.read(b"native_rt") == b"v2"

        # In-progress sign record (no completed ss) far above the last
        # completed version: the read must scan back via versions().
        srv = c.storage_servers[0]
        completed = srv.storage.read(b"native_rt", 0)
        p = pkt.parse(completed)
        stale = pkt.serialize(b"native_rt", b"ghost", p.t + 5000, p.sig, None)
        srv.storage.write(b"native_rt", p.t + 5000, stale)
        raw = srv._read(pkt.serialize(b"native_rt", None, 0), None, None)
        assert pkt.parse(raw).value == b"v2"
    finally:
        c.stop()
        for s in c.all_servers:
            s.storage.close()


def test_concurrent_writers_same_variable():
    """Two *distinct* signed clients race writes to one variable
    (reference: protocol/rw_test.go TestConflict /
    TestManyClientsConcurrentWrite — distinct keys per writer: one key
    racing itself would equivocate and get revoked): individual rounds
    may fail with interned protocol errors (equivocation / bad
    timestamp), but the system stays consistent — readers converge on a
    value some writer actually wrote."""
    import threading

    from bftkv_tpu.errors import Error

    # Dedicated cluster: the storm legitimately triggers server-side
    # conflict handling, which must not leak into other tests' state.
    c = start_cluster(n_servers=4, n_users=2, n_rw=4, bits=BITS)
    try:
        attempted: list[bytes] = []
        written: list[bytes] = []
        unexpected: list = []

        def storm(client, tag):
            for i in range(6):
                val = b"%s-%d" % (tag, i)
                # A write that errors after collecting its collective
                # signature can still land on some servers and win the
                # read — converged values come from *attempted*, not
                # only acknowledged, writes.
                attempted.append(val)
                try:
                    client.write(b"conflict/x", val)
                    written.append(val)
                except Error:
                    pass  # protocol-level rejection is legitimate here
                except Exception as e:  # pragma: no cover
                    unexpected.append(e)

        threads = [
            threading.Thread(target=storm, args=(c.clients[0], b"a")),
            threading.Thread(target=storm, args=(c.clients[1], b"b")),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not unexpected, unexpected
        assert written, "at least one write must succeed"
        r1 = c.clients[0].read(b"conflict/x")
        r2 = c.clients[1].read(b"conflict/x")
        assert r1 in attempted
        assert r2 == r1  # convergence across readers
    finally:
        c.stop()
