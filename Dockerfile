# Containerized cluster (reference: Dockerfile + scripts/run.sh — one
# process per key dir with sequential ports).
#
#   docker build -t bftkv-tpu .
#   docker run -p 7001-7008:7001-7008 bftkv-tpu
#
# The image generates a fresh 4+4 universe at build time and runs one
# daemon per home dir; override CMD to mount real keys instead. JAX
# runs on CPU inside the container — the verify/sign dispatchers are
# opt-in (--dispatch) and belong on accelerator-backed replicas.

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*
RUN pip install --no-cache-dir "jax[cpu]" cryptography numpy

WORKDIR /app
COPY bftkv_tpu ./bftkv_tpu
COPY native ./native
COPY visual ./visual
RUN make -C native

ENV JAX_PLATFORMS=cpu PYTHONPATH=/app
RUN python -m bftkv_tpu.cmd.genkeys --out /keys --servers 4 --rw 4 \
        --users 1 --base-port 7001 --rw-base-port 7101

# Certificates carry 127.0.0.1 dial addresses (valid inside the
# container); --bind-host/--api-host open the listen sockets on all
# interfaces so published ports are reachable from the host.
EXPOSE 7001-7008 7101-7108 7501-7508
CMD ["python", "-m", "bftkv_tpu.cmd.run_cluster", \
     "--keys", "/keys", "--db-root", "/data", "--storage", "native", \
     "--api-base", "7501", "--client-home", "/keys/u01", \
     "--bind-host", "0.0.0.0", "--api-host", "0.0.0.0"]
