#!/usr/bin/env python3
"""Diff two bench records; fail on cluster-section regressions.

The bench trajectory (BENCH_r01.json, BENCH_r02.json, ...) only means
something if someone reads it — this is the reader.  It compares the
*cluster* sections (the end-to-end numbers a protocol/transport/storage
regression actually moves; kernel sections swing with the accelerator
tunnel and are excluded by default) of two bench records and exits
non-zero when any shared section regressed more than ``--threshold``
(default 30%) on EITHER axis:

- **throughput** (the headline ``writes_per_sec``-style number; lower
  is worse), or
- **write p50 latency** (``write_p50_s``; HIGHER is worse) — after the
  round-collapse work, latency is a first-class deliverable and a
  throughput-neutral latency regression must fail CI on its own.

Accepted inputs, auto-detected per file:

- a driver round record (``BENCH_rNN.json``): sections under
  ``parsed.extra.sections``, each a compact ``[status, number]`` pair —
  or ``[status, number, write_p50_s]`` once the round records carry the
  latency axis (older two-element records simply skip the p50 gate);
- a full bench record (``BENCH_detail.json`` / bench.py stderr line):
  sections under ``extra.sections`` as dicts;
- a bare ``{"sections": {...}}`` dict.

Sections measured on different backend classes (tpu vs cpu, or CPU
boxes with different core counts — ``cpu/8`` vs ``cpu/1``) are
reported but never compared — a tunnel flap or a driver-box reschedule
is not a regression.  Use from CI::

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = [
    "compare",
    "extract_sections",
    "main",
    "BASELINE_RESET",
    "GRAY_SLOWDOWN_MAX",
    "P50_REPORT_ONLY",
    "REPORT_ONLY",
]

#: Sections printed but never gated.  Was empty since r10: cluster_4_log
#: rode here for its FIRST landing round (r9, the cluster_4_gray /
#: cluster_sidecar precedent) and gates now that r10 shares it — the
#: promotion the one-round grace period promised.
#:
#: cluster_shards sat here r11 for measured box noise (sub-second
#: closed-loop burst, 45–126 w/s across same-code runs on the 1-core
#: driver box) and promoted back out at r12 via BASELINE_RESET below.
#:
#: cluster_workload rides here for its FIRST landing round (r12), the
#: cluster_4_gray / cluster_sidecar / cluster_4_log precedent; it
#: gates once the next round shares it.
REPORT_ONLY: set = {"cluster_workload"}

#: Sections whose headline METRIC changed semantics at a given round:
#: comparisons that straddle the reset round are reported, never gated
#: (the numbers measure different things), and comparisons entirely on
#: one side gate as usual.  cluster_shards at r12: the measured region
#: moved from a closed-loop burst (how fast CAN the box write — the
#: noise that demoted it in r11) to a FIXED OFFERED LOAD through the
#: workload engine, so the recorded rate is the achieved rate against
#: a deterministic schedule — stable by construction, with queueing in
#: the CO-corrected p99_offered_s — and r12→r13 gates on it.  Keyed by
#: the driver records' round number ``n``; detail records carry no
#: round number, so ad-hoc detail diffs compare as before.
BASELINE_RESET: dict = {"cluster_shards": 12}

#: Sections whose write-p50 ROUND-RATIO is reported, never gated.
#: cluster_4_gray's p50 is dominated by hedge-delay scheduling against
#: crypto contention: back-to-back same-code runs on the 1-core driver
#: box drew 0.119–0.203 s (1.7x spread), so the 30% ratio gate fails
#: on weather about every other round.  The section's latency CONTRACT
#: is the absolute §13 bound — hedged p50 ≤ 2x the fault-free floor —
#: which rides the 4th slot and still gates on every round, weather or
#: not.  Throughput still gates normally.
P50_REPORT_ONLY: set = {"cluster_4_gray"}

#: Absolute bound on the NEW record's hedged gray slowdown (write p50
#: with one delayed clique member ÷ fault-free floor) — the DESIGN.md
#: §13 acceptance bar, enforced on every committed round, not only in
#: tests: ≤ f gray members may make writes slower, never >2× slower.
GRAY_SLOWDOWN_MAX = 2.0


def _backend_class(status: str) -> str:
    """Comparability class of a section status.  CPU statuses carry
    the core count since r10 (``cpu/8``, ``cpu/8-fallback``): the
    cluster sections saturate threads, so numbers from boxes with
    different core counts are incomparable — reported, never gated,
    exactly like tpu-vs-cpu.  Legacy bare ``cpu`` statuses (unknown
    core count) form their own class for the same reason.  A ``+wan:``
    marker (the cluster_wan section's RTT matrix, DESIGN.md §21) is
    part of the class: geography dominates the physics, so a round
    under a different matrix — or none — is never compared against."""
    s = (status or "").lower()
    base, _, wan = s.partition("+wan:")
    if not base.startswith("cpu"):
        cls = "tpu"
    else:
        cls = base.split()[0].split("-")[0]  # "cpu/8[-fallback]" → "cpu/8"
    if wan:
        cls += "+wan:" + wan.split()[0].split("-")[0]
    return cls


def extract_sections(doc: dict) -> dict:
    """``{section name: (status, headline number | None, p50 | None,
    gray_slowdown | None, phase_budget | None, occupancy | None)}`` —
    the fourth element only the gray section carries (compact records:
    a 4th list element; detail records: ``gray_slowdown_hedged``); the
    fifth is the per-phase share dict the attribution plane emits
    (compact: 5th element, null gray slot when the section has no gray
    axis; detail: ``phase_budget``) — reported, never gated: shares
    shift with the workload, the latency axes above are the gates.
    The sixth (r11) is the device-plane occupancy axis — items per
    launch under the mega-batch dry run (compact: 6th element; detail:
    ``megabatch_occupancy_items_per_launch``) — landed REPORT_ONLY:
    occupancy moves with tenant count and window sizing, so it informs
    the trajectory without gating it."""
    sections = None
    for path in (("parsed", "extra", "sections"), ("extra", "sections"),
                 ("sections",)):
        node = doc
        for k in path:
            node = node.get(k) if isinstance(node, dict) else None
            if node is None:
                break
        if isinstance(node, dict):
            sections = node
            break
    out: dict = {}
    if sections is None:
        return out

    def num(v):
        return v if isinstance(v, (int, float)) else None

    for name, sec in sections.items():
        if isinstance(sec, (list, tuple)) and len(sec) in (2, 3, 4, 5, 6):
            status = sec[0]
            p50 = num(sec[2]) if len(sec) >= 3 else None
            gray = num(sec[3]) if len(sec) >= 4 else None
            pb = sec[4] if len(sec) >= 5 and isinstance(sec[4], dict) \
                else None
            occ = num(sec[5]) if len(sec) >= 6 else None
            out[name] = (str(status), num(sec[1]), p50, gray, pb, occ)
        elif isinstance(sec, dict):
            if "skipped" in sec:
                out[name] = ("skip", None, None, None, None, None)
                continue
            if "error" in sec:
                out[name] = ("err", None, None, None, None, None)
                continue
            n = sec.get("writes_per_sec")
            if not isinstance(n, (int, float)):
                n = next(
                    (
                        v
                        for k, v in sec.items()
                        if k.endswith("_per_sec")
                        and isinstance(v, (int, float))
                    ),
                    None,
                )
            pb = sec.get("phase_budget")
            out[name] = (
                str(sec.get("backend", "?")),
                n,
                num(sec.get("write_p50_s")),
                num(sec.get("gray_slowdown_hedged")),
                pb if isinstance(pb, dict) else None,
                num(sec.get("megabatch_occupancy_items_per_launch")),
            )
        elif isinstance(sec, str):
            out[name] = (sec, None, None, None, None, None)
    return out


def compare(
    old: dict, new: dict, threshold: float = 0.30, prefix: str = "cluster"
) -> tuple[list[str], list[str], int]:
    """Returns ``(report lines, regression lines, sections engaged)``.
    Engaged counts sections the gate actually looked at — numerically
    compared, or explicitly reported as backend-incomparable.  Zero
    means the gate gated NOTHING (format drift, section renames);
    callers must treat that as its own failure, or the regression gate
    silently stops gating."""
    a = extract_sections(old)
    b = extract_sections(new)
    n_old = old.get("n") if isinstance(old, dict) else None
    n_new = new.get("n") if isinstance(new, dict) else None
    lines: list[str] = []
    regressions: list[str] = []
    compared = 0
    shared = sorted(set(a) & set(b))
    for name in shared:
        if prefix and not name.startswith(prefix):
            continue
        (sa, va, pa, _ga, _ba, oa), (sb, vb, pb, gb, bb, ob) = (
            a[name], b[name]
        )
        if name in REPORT_ONLY:
            lines.append(
                f"  {name}: {va} -> {vb}  (report-only, not gated)"
            )
            continue
        reset = BASELINE_RESET.get(name)
        if (
            reset is not None
            and isinstance(n_old, int)
            and isinstance(n_new, int)
            and n_old < reset <= n_new
        ):
            lines.append(
                f"  {name}: {va} -> {vb}  (metric semantics reset at "
                f"r{reset:02d}, baselines incommensurable — not "
                f"compared; gates again next round)"
            )
            compared += 1  # the gate engaged; the reset is visible
            continue
        if va is None or vb is None:
            lines.append(f"  {name}: no shared number "
                         f"({sa}:{va} -> {sb}:{vb}), skipped")
            continue
        if _backend_class(sa) != _backend_class(sb):
            lines.append(
                f"  {name}: backend changed ({sa} -> {sb}), not compared"
            )
            compared += 1  # the gate engaged; incomparability is visible
            continue
        ratio = vb / va if va else float("inf")
        verdict = "ok"
        if ratio < 1.0 - threshold:
            verdict = f"REGRESSION (>{threshold:.0%} drop)"
            regressions.append(name)
        compared += 1
        lines.append(
            f"  {name}: {va:g} -> {vb:g}  ({ratio:.2f}x)  {verdict}"
        )
        # Latency axis: p50 compares only when BOTH records carry it —
        # the metric appeared with the round-collapse work, and a
        # missing side must not fail every historical comparison.
        if pa is not None and pb is not None and pa > 0:
            lratio = pb / pa
            if name in P50_REPORT_ONLY:
                lines.append(
                    f"  {name} write p50: {pa:g}s -> {pb:g}s  "
                    f"({lratio:.2f}x)  (report-only: gated by the "
                    f"absolute {GRAY_SLOWDOWN_MAX:g}x hedge bound)"
                )
            else:
                lverdict = "ok"
                if lratio > 1.0 + threshold:
                    lverdict = (
                        f"REGRESSION (p50 >{threshold:.0%} slower)"
                    )
                    regressions.append(f"{name} (write p50)")
                lines.append(
                    f"  {name} write p50: {pa:g}s -> {pb:g}s  "
                    f"({lratio:.2f}x)  {lverdict}"
                )
        # Phase budget: the attribution plane's per-phase wall-clock
        # shares — reported so the committed trajectory shows WHERE
        # each round's latency went, never gated (shares shift with
        # the workload; the latency axes above are the gates).
        if isinstance(bb, dict) and bb:
            shares = ", ".join(
                f"{p}={v:.0%}"
                for p, v in sorted(
                    bb.items(), key=lambda kv: -kv[1]
                )
                if isinstance(v, (int, float)) and v >= 0.005
            )
            lines.append(f"  {name} phase budget: {shares}")
        # Occupancy axis (r11, REPORT_ONLY): items per launch under the
        # mega-batch dry run — the device plane's coalescing health.
        # Never gated: occupancy moves with tenant count and window
        # sizing, and a host-tier box reports it too (the dry run is
        # backend-independent), so it informs the trajectory only.
        if ob is not None:
            prev = f"{oa:g} -> " if oa is not None else ""
            lines.append(
                f"  {name} occupancy: {prev}{ob:g} items/launch  "
                "(report-only, not gated)"
            )
        # Gray axis: an ABSOLUTE bound on the new record, not a
        # round-over-round ratio — 2.1× vs 2.0× is a tiny relative
        # move but a broken acceptance bar (only the new side needs
        # the value; older records never carried it).
        if gb is not None:
            gverdict = "ok"
            if gb > GRAY_SLOWDOWN_MAX:
                gverdict = (
                    f"REGRESSION (> {GRAY_SLOWDOWN_MAX:g}x bound)"
                )
                regressions.append(f"{name} (gray_slowdown)")
            lines.append(
                f"  {name} gray slowdown (hedged): {gb:g}x  "
                f"(bound {GRAY_SLOWDOWN_MAX:g}x)  {gverdict}"
            )
    # The gray bound is ABSOLUTE, so a section new in this round (no
    # old side to diff) is still held to it.
    for name in sorted(set(b) - set(a)):
        if prefix and not name.startswith(prefix):
            continue
        gb = b[name][3]
        if gb is None:
            continue
        gverdict = "ok"
        if gb > GRAY_SLOWDOWN_MAX:
            gverdict = f"REGRESSION (> {GRAY_SLOWDOWN_MAX:g}x bound)"
            regressions.append(f"{name} (gray_slowdown)")
        compared += 1
        lines.append(
            f"  {name} gray slowdown (hedged): {gb:g}x  "
            f"(bound {GRAY_SLOWDOWN_MAX:g}x, new section)  {gverdict}"
        )
    if not any(name.startswith(prefix) for name in shared):
        lines.append(f"  (no shared '{prefix}*' sections)")
    return lines, regressions, compared


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench JSON records; non-zero exit on "
                    "cluster-section regression (throughput or write p50)"
    )
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=0.30,
                    help="maximum tolerated fractional regression on "
                         "either axis (default 0.30)")
    ap.add_argument("--prefix", default="cluster",
                    help="only compare sections with this name prefix "
                         "(default: cluster; '' = all)")
    args = ap.parse_args(argv)

    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    lines, regressions, compared = compare(
        old, new, threshold=args.threshold, prefix=args.prefix
    )
    print(f"bench_compare: {args.old} -> {args.new} "
          f"(threshold {args.threshold:.0%})")
    for ln in lines:
        print(ln)
    if regressions:
        print(f"bench_compare: {len(regressions)} regression(s): "
              + ", ".join(regressions))
        return 1
    if compared == 0:
        print("bench_compare: NOTHING COMPARED — no shared "
              f"'{args.prefix}*' section with commensurable numbers; "
              "the regression gate did not run (format drift? section "
              "rename?)")
        return 2
    print(f"bench_compare: ok ({compared} section(s) compared)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
