"""bftlint — the project's AST invariant linter (zero dependencies).

Ten PRs of DESIGN.md prose turned safety rules into reviewer memory:
every ``BFTKV_*`` flag documented, metric labels from closed enums,
failpoint hooks behind the module-bool guard, protocol errors interned,
no silently swallowed exceptions, every lock through the ``named_lock``
seam.  bftlint machine-checks each of them over the real source tree
(``python -m tools.bftlint``), emits machine-readable findings
(``--json``), and exits non-zero on any violation — CI runs it as the
tier-1 "Invariant lint" step.  DESIGN.md §16 maps each rule to the PR
whose prose it replaces.

Waiver syntax, on the finding line or the line above::

    something_flagged()  # bftlint: ignore[rule-name] why it is safe

Rules (scoped in repo-walk mode; explicit file arguments get ALL rules,
which is how the planted-violation fixtures in tests/ are checked):

- ``env-flag`` — ``os.environ``/``os.getenv`` reads of a ``BFTKV_*``
  literal outside ``bftkv_tpu/flags.py``, and ``flags.*`` reads of an
  undeclared name, are rejected; every flag is declared once in the
  registry with default + doc.
- ``readme-flags`` — the README flags table must equal the one
  generated from the registry (``python -m bftkv_tpu.flags --readme``).
- ``label-enum`` — ``incr/observe/gauge(..., labels=)`` call sites may
  only pass dict literals (directly or via a local single-hop
  assignment) whose keys are members of ``metrics.LABEL_KEYS``.
- ``failpoint-guard`` — every ``fire()`` eval site outside the faults
  package sits behind the ``ARMED`` module-bool guard (the PR 3
  disarmed-parity contract).
- ``interned-error`` — protocol/transport/gateway/sync layers must not
  raise bare ``Exception``/``RuntimeError`` (wire errors intern via
  ``errors.new_error``), and ``new_error`` outside ``errors.py`` must
  take a constant message (a dynamic message grows the intern registry
  without bound).
- ``swallowed-exception`` — bare ``except:`` anywhere; and on the
  protocol/transport layers an ``except`` whose body is only
  ``pass``/``continue`` must carry a comment saying WHY the swallow is
  safe.
- ``named-lock`` — ``threading.Lock()``/``RLock()`` construction in
  the package goes through ``devtools.lockwatch.named_lock`` so the
  lock sanitizer sees every lock.
- ``span-phase`` — every ``trace.span(...)`` name must resolve to a
  phase of the closed ``trace.PHASES`` enum via the ``SPAN_PHASES``
  registry (exact name, declared ``name.`` prefix, or an explicit
  ``phase=`` literal): an undeclared span silently lands in the
  ``other`` budget bucket, which is exactly the unattributed latency
  the critical-path plane exists to kill (DESIGN.md §18).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass

__all__ = ["Finding", "lint_paths", "lint_repo", "main", "RULES"]

RULES = (
    "env-flag",
    "readme-flags",
    "label-enum",
    "failpoint-guard",
    "interned-error",
    "swallowed-exception",
    "named-lock",
    "span-phase",
)

#: Layers whose error/exception discipline is wire-facing.
_PROTOCOL_LAYERS = (
    "bftkv_tpu/protocol/",
    "bftkv_tpu/transport/",
    "bftkv_tpu/gateway/",
    "bftkv_tpu/sync/",
)

_WAIVER_RE = re.compile(r"#\s*bftlint:\s*ignore\[([a-z\-,\s]+)\]")


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _waived(lines: list[str], lineno: int, rule: str) -> bool:
    """True when the finding line carries ``# bftlint: ignore[rule]``
    (comma lists allowed), or the line above is a standalone waiver
    comment (a trailing waiver on the previous line waives only that
    line, not its neighbors)."""
    for ln in (lineno, lineno - 1):
        if not (1 <= ln <= len(lines)):
            continue
        text = lines[ln - 1]
        if ln != lineno and not text.lstrip().startswith("#"):
            continue
        m = _WAIVER_RE.search(text)
        if m and rule in [r.strip() for r in m.group(1).split(",")]:
            return True
    return False


# ---------------------------------------------------------------------------
# Registry extraction (AST-parsed, never imported: bftlint must run on
# a box with nothing but the stdlib).
# ---------------------------------------------------------------------------


def declared_flags(root: str) -> set[str]:
    """Flag names declared in bftkv_tpu/flags.py (``_flag("NAME", ...)``
    calls)."""
    path = os.path.join(root, "bftkv_tpu", "flags.py")
    tree = ast.parse(open(path).read(), filename=path)
    out: set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_flag"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.add(node.args[0].value)
    return out


def declared_span_phases(root: str) -> tuple[set[str], dict[str, str]]:
    """``(PHASES, SPAN_PHASES)`` from bftkv_tpu/trace.py — the closed
    phase enum and the span-name registry (keys ending in ``.`` are
    prefix rules), AST-parsed like every other registry here."""
    path = os.path.join(root, "bftkv_tpu", "trace.py")
    tree = ast.parse(open(path).read(), filename=path)
    phases: set[str] = set()
    span_phases: dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        value = node.value
        if "PHASES" in names and isinstance(value, ast.Tuple):
            phases = {
                e.value for e in value.elts if isinstance(e, ast.Constant)
            }
        elif "SPAN_PHASES" in names and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    span_phases[k.value] = v.value
    if not phases or not span_phases:
        raise RuntimeError("trace.PHASES / trace.SPAN_PHASES not found")
    return phases, span_phases


def declared_label_keys(root: str) -> set[str]:
    """The closed label-key enum from metrics.LABEL_KEYS."""
    path = os.path.join(root, "bftkv_tpu", "metrics.py")
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "LABEL_KEYS":
                    return {
                        e.value
                        for e in node.value.elts
                        if isinstance(e, ast.Constant)
                    }
    raise RuntimeError("metrics.LABEL_KEYS not found")


# ---------------------------------------------------------------------------
# Per-file analysis.
# ---------------------------------------------------------------------------


class _Parents(ast.NodeVisitor):
    def __init__(self):
        self.parents: dict[ast.AST, ast.AST] = {}

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            self.parents[child] = node
        super().generic_visit(node)


def _mentions_armed(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "ARMED":
            return True
        if isinstance(sub, ast.Name) and sub.id == "ARMED":
            return True
    return False


def _armed_polarity(test: ast.AST, neg: bool = False) -> str | None:
    """Which branch of a test mentioning ARMED is the armed one:
    ``"true"`` (e.g. ``fp.ARMED``, ``fp.ARMED and x``) means the
    body runs armed, ``"false"`` (e.g. ``not fp.ARMED``) means the
    body runs DISARMED, ``None`` when ARMED is not mentioned."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _armed_polarity(test.operand, not neg)
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            pol = _armed_polarity(v, neg)
            if pol is not None:
                return pol
        return None
    if _mentions_armed(test):
        return "false" if neg else "true"
    return None


def _dict_keys_ok(d: ast.Dict, allowed: set[str]) -> str | None:
    """None if every key is a constant in ``allowed``; else a message."""
    for k in d.keys:
        if not isinstance(k, ast.Constant) or not isinstance(k.value, str):
            return "label key is not a string literal"
        if k.value not in allowed:
            return (
                f"label key {k.value!r} is not in metrics.LABEL_KEYS "
                "(closed enum; extend it deliberately if this is a new "
                "dimension)"
            )
    return None


def _is_env_read(node: ast.Call) -> ast.expr | None:
    """The name argument when ``node`` reads the environment
    (os.environ.get / os.getenv), else None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        # os.environ.get(...) / _os.environ.get(...)
        if (
            f.attr == "get"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "environ"
        ):
            return node.args[0] if node.args else None
        # os.getenv(...)
        if f.attr == "getenv" and isinstance(f.value, ast.Name):
            return node.args[0] if node.args else None
    return None


class _FileLinter:
    def __init__(
        self,
        path: str,
        rel: str,
        rules: set[str],
        flags_declared: set[str],
        label_keys: set[str],
        span_registry: tuple[set, dict] = (set(), {}),
    ):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.rules = rules
        self.flags_declared = flags_declared
        self.label_keys = label_keys
        self.phases, self.span_phases = span_registry
        self.src = open(path).read()
        self.lines = self.src.split("\n")
        self.tree = ast.parse(self.src, filename=path)
        p = _Parents()
        p.visit(self.tree)
        self.parents = p.parents
        self.findings: list[Finding] = []

    def emit(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if _waived(self.lines, line, rule):
            return
        self.findings.append(Finding(self.rel, line, rule, message))

    # -- rule: env-flag ----------------------------------------------------

    def check_env_flag(self) -> None:
        if self.rel.endswith("bftkv_tpu/flags.py"):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                arg = self._env_read_of_bftkv(node)
                if arg is not None:
                    self.emit(
                        node, "env-flag",
                        f"direct environment read of {arg!r}: go through "
                        "the bftkv_tpu.flags seam (raw/get/enabled/...) "
                        "and declare the flag in the registry",
                    )
                self._check_flags_call(node)
            elif isinstance(node, ast.Subscript):
                # os.environ["BFTKV_..."]
                v = node.value
                if (
                    isinstance(v, ast.Attribute)
                    and v.attr == "environ"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value.startswith("BFTKV_")
                ):
                    self.emit(
                        node, "env-flag",
                        "direct environ subscript of "
                        f"{node.slice.value!r}: go through bftkv_tpu.flags",
                    )

    def _env_read_of_bftkv(self, node: ast.Call) -> str | None:
        arg = _is_env_read(node)
        if (
            arg is not None
            and isinstance(arg, ast.Constant)
            and isinstance(arg.value, str)
            and arg.value.startswith("BFTKV_")
        ):
            return arg.value
        return None

    def _check_flags_call(self, node: ast.Call) -> None:
        f = node.func
        if not (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "flags"
            and f.attr in ("raw", "get", "enabled", "get_int", "get_float")
        ):
            return
        if not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in self.flags_declared:
                self.emit(
                    node, "env-flag",
                    f"flag {arg.value!r} is not declared in "
                    "bftkv_tpu/flags.py (add it with default + doc line)",
                )

    # -- rule: label-enum --------------------------------------------------

    def check_label_enum(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and f.attr in ("incr", "observe", "gauge")
            ):
                continue
            for kw in node.keywords:
                if kw.arg == "labels":
                    self._check_labels_value(node, kw.value)

    def _check_labels_value(self, call: ast.Call, value: ast.expr) -> None:
        for d in self._resolve_label_dicts(call, value):
            if d is None:
                self.emit(
                    call, "label-enum",
                    "labels= is not resolvable to a dict literal (pass a "
                    "literal, or assign one to a local immediately before "
                    "the call) — closed-enum keys cannot be checked",
                )
                return
            msg = _dict_keys_ok(d, self.label_keys)
            if msg:
                self.emit(call, "label-enum", msg)

    def _resolve_label_dicts(self, call, value):
        """Yield the dict literal(s) ``value`` can denote, or None when
        unresolvable.  Handles literals, None, IfExp branches, and a
        single-hop local name assigned from those in the enclosing
        function."""
        if isinstance(value, ast.Dict):
            yield value
            return
        if isinstance(value, ast.Constant) and value.value is None:
            return
        if isinstance(value, ast.IfExp):
            yield from self._resolve_label_dicts(call, value.body)
            yield from self._resolve_label_dicts(call, value.orelse)
            return
        if isinstance(value, ast.Name):
            fn = self._enclosing_function(call)
            assigns = [
                n.value
                for n in ast.walk(fn if fn is not None else self.tree)
                if isinstance(n, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == value.id
                    for t in n.targets
                )
            ]
            if assigns:
                for a in assigns:
                    yield from self._resolve_label_dicts(call, a)
                return
        yield None

    def _enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    # -- rule: failpoint-guard ---------------------------------------------

    def check_failpoint_guard(self) -> None:
        if "bftkv_tpu/faults/" in self.rel:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_fire = (
                isinstance(f, ast.Attribute) and f.attr == "fire"
            ) or (isinstance(f, ast.Name) and f.id == "fire")
            if not is_fire:
                continue
            if self._guarded_by_armed(node):
                continue
            self.emit(
                node, "failpoint-guard",
                "failpoint fire() outside the `if ARMED:` module-bool "
                "guard — hook sites must not pay context construction "
                "when disarmed (PR 3 parity contract)",
            )

    def _guarded_by_armed(self, node: ast.AST) -> bool:
        # Branch-SENSITIVE: `if fp.ARMED:` guards only its body, and
        # an early return guards only when its test is the negated
        # form (`if not fp.ARMED: return`).  A fire() in the else
        # branch, or below `if fp.ARMED: return`, runs exactly when
        # disarmed — the opposite of the contract — and must flag.
        # (a) ancestor If / IfExp with the call on the armed branch
        cur: ast.AST | None = node
        while cur is not None:
            parent = self.parents.get(cur)
            if isinstance(parent, (ast.If, ast.IfExp)):
                pol = _armed_polarity(parent.test)
                in_body = (
                    cur in parent.body
                    if isinstance(parent, ast.If)
                    else cur is parent.body
                )
                in_orelse = (
                    cur in parent.orelse
                    if isinstance(parent, ast.If)
                    else cur is parent.orelse
                )
                if pol == "true" and in_body:
                    return True
                if pol == "false" and in_orelse:
                    return True
            if (
                isinstance(parent, ast.BoolOp)
                and isinstance(parent.op, ast.And)
            ):
                # `fp.ARMED and fp.fire(...)`: guarded when a positive
                # ARMED mention precedes the value holding the call.
                idx = (
                    parent.values.index(cur)
                    if cur in parent.values
                    else len(parent.values)
                )
                if any(
                    _armed_polarity(v) == "true"
                    for v in parent.values[:idx]
                ):
                    return True
            cur = parent
        # (b) early-return guard at the top of the enclosing function:
        #     if not fp.ARMED: return ...   (negated form ONLY)
        fn = self._enclosing_function(node)
        if fn is not None:
            for stmt in fn.body:
                if (
                    isinstance(stmt, ast.If)
                    and _armed_polarity(stmt.test) == "false"
                    and any(isinstance(s, ast.Return) for s in stmt.body)
                ):
                    return True
        return False

    # -- rule: interned-error ----------------------------------------------

    def check_interned_error(self) -> None:
        on_layer = any(layer in self.rel for layer in _PROTOCOL_LAYERS)
        for node in ast.walk(self.tree):
            if (
                on_layer
                and isinstance(node, ast.Raise)
                and isinstance(node.exc, ast.Call)
                and isinstance(node.exc.func, ast.Name)
                and node.exc.func.id in ("Exception", "RuntimeError")
            ):
                self.emit(
                    node, "interned-error",
                    f"raise {node.exc.func.id} on a wire-facing layer: "
                    "protocol errors must intern via errors.new_error / "
                    "ERR_* so both sides compare equal",
                )
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "new_error"
                and not self.rel.endswith("bftkv_tpu/errors.py")
                and node.args
                and not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                )
            ):
                self.emit(
                    node, "interned-error",
                    "new_error() with a dynamic message outside errors.py "
                    "grows the intern registry without bound — intern a "
                    "constant or add a parser like wrong_shard_error",
                )

    # -- rule: swallowed-exception -----------------------------------------

    def check_swallowed_exception(self) -> None:
        on_layer = any(layer in self.rel for layer in _PROTOCOL_LAYERS)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                self.emit(
                    node, "swallowed-exception",
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "— name the exception class",
                )
                continue
            if not on_layer:
                continue
            only_noop = all(
                isinstance(s, (ast.Pass, ast.Continue)) for s in node.body
            )
            if not only_noop or not self._broad_catch(node.type):
                continue
            end = max(
                getattr(s, "end_lineno", s.lineno) for s in node.body
            )
            span = self.lines[node.lineno - 1 : end]
            if not any("#" in ln for ln in span):
                self.emit(
                    node, "swallowed-exception",
                    "exception swallowed with no comment saying why that "
                    "is safe (wire-facing layer) — explain or handle",
                )

    @staticmethod
    def _broad_catch(t: ast.expr) -> bool:
        """True for ``except Exception``/``BaseException`` (alone or in
        a tuple).  Narrow catches (ERR_NOT_FOUND, OSError, ValueError)
        with a no-op body are idiomatic not-found/cleanup control flow
        and stay unflagged — the hazard the rule encodes is the BROAD
        silent swallow that can eat real protocol bugs."""
        if isinstance(t, ast.Tuple):
            return any(_FileLinter._broad_catch(e) for e in t.elts)
        return isinstance(t, ast.Name) and t.id in (
            "Exception", "BaseException",
        )

    # -- rule: named-lock --------------------------------------------------

    def check_named_lock(self) -> None:
        if not self.rel.startswith("bftkv_tpu/") or self.rel.endswith(
            "devtools/lockwatch.py"
        ):
            return
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("Lock", "RLock")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "threading"
            ):
                self.emit(
                    node, "named-lock",
                    f"direct threading.{node.func.attr}() — create locks "
                    "through devtools.lockwatch.named_lock(name) so the "
                    "lock sanitizer sees them",
                )

    # -- rule: span-phase --------------------------------------------------

    def _span_name_declared(self, name: str) -> bool:
        if name in self.span_phases:
            return True
        return any(
            name.startswith(p)
            for p in self.span_phases
            if p.endswith(".")
        )

    def check_span_phase(self) -> None:
        if not self.phases or self.rel.endswith("bftkv_tpu/trace.py"):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_span = (
                isinstance(f, ast.Attribute) and f.attr == "span"
            ) or (isinstance(f, ast.Name) and f.id == "span")
            if not is_span or not node.args:
                continue
            phase_kw = next(
                (kw.value for kw in node.keywords if kw.arg == "phase"),
                None,
            )
            if phase_kw is not None:
                if not (
                    isinstance(phase_kw, ast.Constant)
                    and phase_kw.value in self.phases
                ):
                    self.emit(
                        node, "span-phase",
                        "phase= must be a string literal from "
                        "trace.PHASES (closed enum)",
                    )
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                if not self._span_name_declared(arg.value):
                    self.emit(
                        node, "span-phase",
                        f"span name {arg.value!r} resolves to no "
                        "declared phase: add it (or a `prefix.` rule) "
                        "to trace.SPAN_PHASES, or pass an explicit "
                        "phase= — undeclared spans land in the 'other' "
                        "budget bucket invisibly (DESIGN.md §18)",
                    )
            elif isinstance(arg, ast.JoinedStr):
                lead = (
                    arg.values[0].value
                    if arg.values
                    and isinstance(arg.values[0], ast.Constant)
                    and isinstance(arg.values[0].value, str)
                    else ""
                )
                if not lead or not self._span_name_declared(lead):
                    self.emit(
                        node, "span-phase",
                        "dynamic span name with no declared-prefix "
                        "leading literal: pass an explicit phase= from "
                        "trace.PHASES",
                    )
            else:
                self.emit(
                    node, "span-phase",
                    "span name is not statically resolvable: pass an "
                    "explicit phase= from trace.PHASES",
                )

    def run(self) -> list[Finding]:
        if "env-flag" in self.rules:
            self.check_env_flag()
        if "label-enum" in self.rules:
            self.check_label_enum()
        if "failpoint-guard" in self.rules:
            self.check_failpoint_guard()
        if "interned-error" in self.rules:
            self.check_interned_error()
        if "swallowed-exception" in self.rules:
            self.check_swallowed_exception()
        if "named-lock" in self.rules:
            self.check_named_lock()
        if "span-phase" in self.rules:
            self.check_span_phase()
        return self.findings


# ---------------------------------------------------------------------------
# README flags-table freshness.
# ---------------------------------------------------------------------------


def check_readme(root: str) -> list[Finding]:
    """The README section between the flags-table markers must equal
    the registry-generated one (``python -m bftkv_tpu.flags --readme``).

    The registry is loaded from ``root``'s own ``flags.py`` via an
    isolated spec-load (the module is stdlib-only by design, so it
    executes standalone): a plain ``import bftkv_tpu.flags`` would
    resolve through ``sys.modules``/``sys.path`` and could silently
    validate the target tree's README against a DIFFERENT checkout's
    registry."""
    import importlib.util

    flags_path = os.path.join(root, "bftkv_tpu", "flags.py")
    spec = importlib.util.spec_from_file_location(
        "_bftlint_flags_under_check", flags_path
    )
    _flags = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(_flags)
    expected = _flags.readme_table()
    readme_path = os.path.join(root, "README.md")
    text = open(readme_path).read()
    begin, end = _flags.README_BEGIN, _flags.README_END
    i, j = text.find(begin), text.find(end)
    if i < 0 or j < 0:
        return [
            Finding(
                "README.md", 1, "readme-flags",
                "flags-table markers missing: paste the output of "
                "`python -m bftkv_tpu.flags --readme` into README.md",
            )
        ]
    actual = text[i : j + len(end)]
    if actual.strip() != expected.strip():
        line = text[:i].count("\n") + 1
        return [
            Finding(
                "README.md", line, "readme-flags",
                "flags table is stale: regenerate with "
                "`python -m bftkv_tpu.flags --readme` (the registry in "
                "bftkv_tpu/flags.py is the source of truth)",
            )
        ]
    return []


# ---------------------------------------------------------------------------
# Drivers.
# ---------------------------------------------------------------------------


def _walk_py(root: str, sub: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, sub)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def _span_registry(root: str) -> tuple[set, dict]:
    """The span-phase registry, or empty when the target tree has no
    trace.py (fixture trees): the rule then no-ops rather than failing
    every unrelated lint."""
    try:
        return declared_span_phases(root)
    except (OSError, RuntimeError):
        return set(), {}


def _lint_file(
    p: str, rel: str, rules: set, flags_declared: set, label_keys: set,
    span_registry: tuple = (set(), {}),
) -> list[Finding]:
    """One file's findings; an unreadable or unparsable file is itself
    a finding (``parse-error``), never a traceback — the linter must
    survive hostile input like everything else in this tree."""
    try:
        return _FileLinter(
            p, rel, rules, flags_declared, label_keys, span_registry
        ).run()
    except SyntaxError as e:
        return [
            Finding(
                rel, e.lineno or 1, "parse-error",
                f"file does not parse: {e.msg}",
            )
        ]
    except OSError as e:
        return [
            Finding(rel, 1, "parse-error", f"cannot read file: {e}")
        ]


def lint_paths(
    paths: list[str],
    root: str,
    rules: set[str] | None = None,
) -> list[Finding]:
    """Lint explicit files with every AST rule (fixture mode)."""
    rules = rules or set(RULES)
    flags_declared = declared_flags(root)
    label_keys = declared_label_keys(root)
    span_registry = _span_registry(root)
    findings: list[Finding] = []
    for p in paths:
        rel = os.path.relpath(p, root) if os.path.isabs(p) else p
        findings.extend(
            _lint_file(
                p, rel, rules, flags_declared, label_keys, span_registry
            )
        )
    return findings


def lint_repo(root: str) -> list[Finding]:
    """The full repo walk: bftkv_tpu/ + tools/ with layer-scoped rules,
    plus the README freshness check."""
    flags_declared = declared_flags(root)
    label_keys = declared_label_keys(root)
    span_registry = _span_registry(root)
    findings: list[Finding] = []
    rules = set(RULES)
    for p in _walk_py(root, "bftkv_tpu") + _walk_py(root, "tools"):
        rel = os.path.relpath(p, root)
        findings.extend(
            _lint_file(
                p, rel, rules, flags_declared, label_keys, span_registry
            )
        )
    findings.extend(check_readme(root))
    return findings


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tools.bftlint",
        description="project invariant linter (DESIGN.md §16)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="explicit files to lint with ALL rules (default: repo "
        "walk over bftkv_tpu/ + tools/ plus README freshness)",
    )
    ap.add_argument("--root", default=".", help="repo root")
    ap.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma list restricting which rules run",
    )
    args = ap.parse_args(argv)
    rules = set(args.rules.split(",")) if args.rules else None
    if args.paths:
        findings = lint_paths(args.paths, args.root, rules)
    else:
        findings = lint_repo(args.root)
        if rules:
            findings = [f for f in findings if f.rule in rules]
    if args.json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(
            f"bftlint: {len(findings)} finding(s)"
            if findings
            else "bftlint: clean"
        )
    return 1 if findings else 0
