"""Repo tooling (``tools.bftlint`` runs as ``python -m tools.bftlint``;
``bench_compare.py`` stays a plain script)."""
