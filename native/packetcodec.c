/* C packet codec for the bftkv_tpu wire format.
 *
 * The hot server handlers (batch sign/write: protocol/server.py) parse
 * and re-serialize thousands of <x,v,t,sig,ss,auth> packets per call;
 * the Python codec costs 6-12 us per operation, which caps a replica
 * process at ~12k handler items/s (docs/PERFORMANCE.md "Handler Python
 * ceiling").  This module implements the same grammar (byte-compatible
 * with the reference codec, packet/packet.go:35-115) in C, loaded
 * on demand by bftkv_tpu/packet.py with the pure-Python implementation
 * kept as fallback and as the fuzz-tested semantics oracle.
 *
 * Grammar (all multi-byte integers big-endian):
 *   chunk      = u64 length | length bytes      (length 0 -> None)
 *   signature  = u8 type | u32 version | u8 completed |
 *                chunk(data) | chunk(cert)      (type 0 -> None)
 *   packet     = chunk(x) [chunk(v) [u64 t [sig [ss [chunk(auth)]]]]]
 *   list       = u32 count | count * chunk
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *Malformed = NULL; /* ERR_MALFORMED_REQUEST class */

static uint64_t
rd_u64(const unsigned char *p)
{
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v = (v << 8) | p[i];
    return v;
}

static uint32_t
rd_u32(const unsigned char *p)
{
    uint32_t v = 0;
    for (int i = 0; i < 4; i++)
        v = (v << 8) | p[i];
    return v;
}

static int
raise_malformed(void)
{
    PyErr_SetNone(Malformed ? Malformed : PyExc_ValueError);
    return -1;
}

/* -1 error (exception set), -2 clean EOF (no exception), 0 ok. */
static int
chunk_at(const unsigned char *b, Py_ssize_t n, Py_ssize_t *off,
         PyObject **out)
{
    if (*off == n)
        return -2;
    if (*off + 8 > n)
        return raise_malformed();
    uint64_t ln = rd_u64(b + *off);
    *off += 8;
    if (ln == 0) {
        Py_INCREF(Py_None);
        *out = Py_None;
        return 0;
    }
    if (ln > (uint64_t)(n - *off))
        return raise_malformed();
    *out = PyBytes_FromStringAndSize((const char *)b + *off,
                                     (Py_ssize_t)ln);
    if (*out == NULL)
        return -1;
    *off += (Py_ssize_t)ln;
    return 0;
}

/* Signature record -> (type, version, completed, data, cert) tuple or
 * None for the nil type.  Same return codes as chunk_at. */
static int
signature_at(const unsigned char *b, Py_ssize_t n, Py_ssize_t *off,
             PyObject **out)
{
    if (*off == n)
        return -2;
    if (*off + 6 > n)
        return raise_malformed();
    unsigned typ = b[*off];
    uint32_t version = rd_u32(b + *off + 1);
    unsigned completed = b[*off + 5];
    *off += 6;
    PyObject *data = NULL, *cert = NULL;
    /* A record that ends cleanly mid-signature propagates as EOF, not
     * malformed — the Python reader's EOFError tolerance in parse(). */
    int rc = chunk_at(b, n, off, &data);
    if (rc != 0)
        return rc;
    rc = chunk_at(b, n, off, &cert);
    if (rc != 0) {
        Py_DECREF(data);
        return rc;
    }
    if (typ == 0) { /* SIGNATURE_TYPE_NIL */
        Py_DECREF(data);
        Py_DECREF(cert);
        Py_INCREF(Py_None);
        *out = Py_None;
        return 0;
    }
    *out = Py_BuildValue("(IIONN)", typ, (unsigned)version,
                         completed ? Py_True : Py_False, data, cert);
    return *out == NULL ? -1 : 0;
}

/* parse(b) -> (variable, value, t, sig, ss, auth); omitted trailing
 * fields come back as the dataclass defaults (None / 0). */
static PyObject *
codec_parse(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    const unsigned char *b = (const unsigned char *)view.buf;
    Py_ssize_t n = view.len, off = 0;
    PyObject *variable = NULL, *value = NULL, *sig = NULL, *ss = NULL,
             *auth = NULL;
    uint64_t t = 0;
    int rc = chunk_at(b, n, &off, &variable);
    if (rc == -2)
        raise_malformed();
    if (rc != 0)
        goto fail;
    rc = chunk_at(b, n, &off, &value);
    if (rc < -1)
        goto done; /* clean EOF: defaults */
    if (rc < 0)
        goto fail;
    if (off == n)
        goto done;
    if (off + 8 > n) {
        raise_malformed();
        goto fail;
    }
    t = rd_u64(b + off);
    off += 8;
    rc = signature_at(b, n, &off, &sig);
    if (rc == -2)
        goto done;
    if (rc < 0)
        goto fail;
    rc = signature_at(b, n, &off, &ss);
    if (rc == -2)
        goto done;
    if (rc < 0)
        goto fail;
    rc = chunk_at(b, n, &off, &auth);
    if (rc == -2)
        goto done;
    if (rc < 0)
        goto fail;
done:
    PyBuffer_Release(&view);
    {
        PyObject *out = Py_BuildValue(
            "(OOKOOO)", variable ? variable : Py_None,
            value ? value : Py_None, (unsigned long long)t,
            sig ? sig : Py_None, ss ? ss : Py_None,
            auth ? auth : Py_None);
        Py_XDECREF(variable);
        Py_XDECREF(value);
        Py_XDECREF(sig);
        Py_XDECREF(ss);
        Py_XDECREF(auth);
        return out;
    }
fail:
    PyBuffer_Release(&view);
    Py_XDECREF(variable);
    Py_XDECREF(value);
    Py_XDECREF(sig);
    Py_XDECREF(ss);
    Py_XDECREF(auth);
    return NULL;
}

/* tbs_offset(b) -> offset just past t (malformed if truncated). */
static Py_ssize_t
tbs_offset(const unsigned char *b, Py_ssize_t n)
{
    Py_ssize_t off = 0;
    for (int i = 0; i < 2; i++) {
        if (off + 8 > n) {
            raise_malformed();
            return -1;
        }
        uint64_t ln = rd_u64(b + off);
        off += 8;
        if (ln > (uint64_t)(n - off)) {
            raise_malformed();
            return -1;
        }
        off += (Py_ssize_t)ln;
    }
    off += 8;
    if (off > n) {
        raise_malformed();
        return -1;
    }
    return off;
}

static PyObject *
codec_tbs_offset(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Py_ssize_t off =
        tbs_offset((const unsigned char *)view.buf, view.len);
    PyBuffer_Release(&view);
    if (off < 0)
        return NULL;
    return PyLong_FromSsize_t(off);
}

/* tbss_end(b) -> offset just past sig (for pkt[:end]). */
static PyObject *
codec_tbss_end(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    const unsigned char *b = (const unsigned char *)view.buf;
    Py_ssize_t n = view.len;
    Py_ssize_t off = tbs_offset(b, n);
    if (off < 0) {
        PyBuffer_Release(&view);
        return NULL;
    }
    PyObject *sig = NULL;
    int rc = signature_at(b, n, &off, &sig);
    PyBuffer_Release(&view);
    if (rc == -2) {
        raise_malformed();
        return NULL;
    }
    if (rc < 0)
        return NULL;
    Py_XDECREF(sig);
    return PyLong_FromSsize_t(off);
}

/* parse_signature(b) -> tuple | None */
static PyObject *
codec_parse_signature(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Py_ssize_t off = 0;
    PyObject *sig = NULL;
    int rc = signature_at((const unsigned char *)view.buf, view.len,
                          &off, &sig);
    PyBuffer_Release(&view);
    if (rc == -2) {
        raise_malformed();
        return NULL;
    }
    if (rc < 0)
        return NULL;
    return sig;
}

/* parse_list(b) -> list[bytes] (empty chunks -> b"") */
static PyObject *
codec_parse_list(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    const unsigned char *b = (const unsigned char *)view.buf;
    Py_ssize_t n = view.len;
    if (n < 4) {
        PyBuffer_Release(&view);
        raise_malformed();
        return NULL;
    }
    uint32_t count = rd_u32(b);
    if ((uint64_t)count > (uint64_t)((n - 4) / 8)) {
        PyBuffer_Release(&view);
        raise_malformed();
        return NULL;
    }
    PyObject *out = PyList_New(count);
    if (out == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    Py_ssize_t off = 4;
    for (uint32_t i = 0; i < count; i++) {
        PyObject *c = NULL;
        int rc = chunk_at(b, n, &off, &c);
        if (rc == -2)
            raise_malformed();
        if (rc != 0) {
            Py_DECREF(out);
            PyBuffer_Release(&view);
            return NULL;
        }
        if (c == Py_None) {
            Py_DECREF(c);
            c = PyBytes_FromStringAndSize(NULL, 0);
            if (c == NULL) {
                Py_DECREF(out);
                PyBuffer_Release(&view);
                return NULL;
            }
        }
        PyList_SET_ITEM(out, i, c); /* steals */
    }
    PyBuffer_Release(&view);
    return out;
}

/* -- serialization ------------------------------------------------------ */

typedef struct {
    unsigned char *buf;
    Py_ssize_t len, cap;
} wbuf;

static int
wb_grow(wbuf *w, Py_ssize_t need)
{
    if (w->len + need <= w->cap)
        return 0;
    Py_ssize_t cap = w->cap ? w->cap : 256;
    while (cap < w->len + need)
        cap *= 2;
    unsigned char *nb = PyMem_Realloc(w->buf, cap);
    if (nb == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    w->buf = nb;
    w->cap = cap;
    return 0;
}

static int
wb_u64(wbuf *w, uint64_t v)
{
    if (wb_grow(w, 8) < 0)
        return -1;
    for (int i = 7; i >= 0; i--)
        w->buf[w->len++] = (unsigned char)(v >> (8 * i));
    return 0;
}

/* obj: bytes-like or None */
static int
wb_chunk(wbuf *w, PyObject *obj)
{
    if (obj == NULL || obj == Py_None)
        return wb_u64(w, 0);
    Py_buffer view;
    if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0)
        return -1;
    int rc = wb_u64(w, (uint64_t)view.len);
    if (rc == 0 && view.len) {
        rc = wb_grow(w, view.len);
        if (rc == 0) {
            memcpy(w->buf + w->len, view.buf, view.len);
            w->len += view.len;
        }
    }
    PyBuffer_Release(&view);
    return rc;
}

/* sig: None (nil record) or (type, version, completed, data, cert) */
static int
wb_signature(wbuf *w, PyObject *sig)
{
    unsigned long typ = 0, version = 0;
    int completed = 0;
    PyObject *data = Py_None, *cert = Py_None;
    if (sig != NULL && sig != Py_None) {
        if (!PyTuple_Check(sig) || PyTuple_GET_SIZE(sig) != 5) {
            PyErr_SetString(PyExc_TypeError,
                            "signature must be a 5-tuple or None");
            return -1;
        }
        typ = PyLong_AsUnsignedLong(PyTuple_GET_ITEM(sig, 0));
        version = PyLong_AsUnsignedLong(PyTuple_GET_ITEM(sig, 1));
        if (PyErr_Occurred())
            return -1;
        completed = PyObject_IsTrue(PyTuple_GET_ITEM(sig, 2));
        if (completed < 0)
            return -1;
        data = PyTuple_GET_ITEM(sig, 3);
        cert = PyTuple_GET_ITEM(sig, 4);
        if (typ > 0xFF) {
            PyErr_SetString(PyExc_ValueError,
                            "signature type does not fit one byte");
            return -1;
        }
        if (version > 0xFFFFFFFFUL) {
            /* The Python oracle's struct.pack(">I") rejects this. */
            PyErr_SetString(PyExc_ValueError,
                            "signature version does not fit four bytes");
            return -1;
        }
    }
    if (wb_grow(w, 6) < 0)
        return -1;
    w->buf[w->len++] = (unsigned char)typ;
    for (int i = 3; i >= 0; i--)
        w->buf[w->len++] = (unsigned char)(version >> (8 * i));
    w->buf[w->len++] = (unsigned char)(completed ? 1 : 0);
    if (wb_chunk(w, data) < 0)
        return -1;
    return wb_chunk(w, cert);
}

/* serialize(variable, value, t, sig, ss, auth, nfields) -> bytes */
static PyObject *
codec_serialize(PyObject *self, PyObject *args)
{
    PyObject *variable, *value, *sig, *ss, *auth;
    unsigned long long t;
    int nfields;
    if (!PyArg_ParseTuple(args, "OOKOOOi", &variable, &value, &t, &sig,
                          &ss, &auth, &nfields))
        return NULL;
    wbuf w = {NULL, 0, 0};
    int rc = 0;
    if (nfields >= 1)
        rc = wb_chunk(&w, variable);
    if (rc == 0 && nfields >= 2)
        rc = wb_chunk(&w, value);
    if (rc == 0 && nfields >= 3)
        rc = wb_u64(&w, t);
    if (rc == 0 && nfields >= 4)
        rc = wb_signature(&w, sig);
    if (rc == 0 && nfields >= 5)
        rc = wb_signature(&w, ss);
    if (rc == 0 && nfields >= 6)
        rc = wb_chunk(&w, auth);
    PyObject *out = NULL;
    if (rc == 0)
        out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

/* serialize_signature(sig_tuple_or_None) -> bytes */
static PyObject *
codec_serialize_signature(PyObject *self, PyObject *arg)
{
    wbuf w = {NULL, 0, 0};
    if (wb_signature(&w, arg) < 0) {
        PyMem_Free(w.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

static PyObject *
codec_set_malformed(PyObject *self, PyObject *arg)
{
    Py_XDECREF(Malformed);
    Py_INCREF(arg);
    Malformed = arg;
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"parse", codec_parse, METH_O,
     "parse(b) -> (variable, value, t, sig, ss, auth)"},
    {"tbs_offset", codec_tbs_offset, METH_O, "offset just past t"},
    {"tbss_end", codec_tbss_end, METH_O, "offset just past sig"},
    {"parse_signature", codec_parse_signature, METH_O,
     "parse one signature record"},
    {"parse_list", codec_parse_list, METH_O, "parse count-prefixed list"},
    {"serialize", codec_serialize, METH_VARARGS,
     "serialize(variable, value, t, sig, ss, auth, nfields)"},
    {"serialize_signature", codec_serialize_signature, METH_O,
     "serialize one signature record"},
    {"set_malformed", codec_set_malformed, METH_O,
     "install the interned malformed-request error class"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_packetcodec",
    "C codec for the bftkv_tpu wire format", -1, methods,
};

PyMODINIT_FUNC
PyInit__packetcodec(void)
{
    return PyModule_Create(&moduledef);
}
