// Log-structured versioned KV engine — the native storage backend.
//
// Capability parity with the reference's leveldb backend
// (reference: storage/leveldb/leveldb.go:22-53): key space is
// variable || bigendian(t), "latest" is the maximal t for a variable,
// writes are synced. Design is TPU-framework-native rather than a port:
// a single append-only log with an in-memory version index, rebuilt by
// replay on open — recovery therefore composes with the protocol layer's
// rejoin + read-repair story (SURVEY.md §5 "Checkpoint / resume").
//
// C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Slot {
  uint64_t offset;  // offset of the value bytes in the log
  uint64_t length;
};

// Record layout: magic(1) | varlen(u32 LE) | t(u64 LE) | vallen(u64 LE)
// | var | val
constexpr uint8_t kMagic = 0xB7;
constexpr size_t kHeader = 1 + 4 + 8 + 8;

struct Store {
  FILE* log = nullptr;
  std::string path;
  std::mutex mu;
  std::map<std::string, std::map<uint64_t, Slot>> index;
  uint64_t tail = 0;

  bool Replay() {
    std::vector<char> hdr(kHeader);
    uint64_t off = 0;
    if (fseek(log, 0, SEEK_SET) != 0) return false;
    for (;;) {
      size_t got = fread(hdr.data(), 1, kHeader, log);
      if (got == 0) break;           // clean end
      if (got < kHeader) break;      // torn tail: truncate logically
      if ((uint8_t)hdr[0] != kMagic) break;
      uint32_t varlen;
      uint64_t t, vallen;
      memcpy(&varlen, hdr.data() + 1, 4);
      memcpy(&t, hdr.data() + 5, 8);
      memcpy(&vallen, hdr.data() + 13, 8);
      std::string var(varlen, '\0');
      if (fread(var.data(), 1, varlen, log) < varlen) break;
      uint64_t val_off = off + kHeader + varlen;
      if (fseek(log, (long)vallen, SEEK_CUR) != 0) break;
      index[var][t] = Slot{val_off, vallen};
      off = val_off + vallen;
      if (fseek(log, (long)off, SEEK_SET) != 0) break;
    }
    tail = off;
    return fseek(log, (long)tail, SEEK_SET) == 0;
  }
};

}  // namespace

extern "C" {

Store* kv_open(const char* path) {
  FILE* f = fopen(path, "a+b");
  if (!f) return nullptr;
  Store* s = new Store;
  s->log = f;
  s->path = path;
  if (!s->Replay()) {
    fclose(f);
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(Store* s) {
  if (!s) return;
  fclose(s->log);
  delete s;
}

// Returns 0 on success.
int kv_write(Store* s, const uint8_t* var, uint32_t varlen, uint64_t t,
             const uint8_t* val, uint64_t vallen) {
  if (!s) return -1;  // defense against use-after-close via the ctypes seam
  std::lock_guard<std::mutex> lock(s->mu);
  if (fseek(s->log, (long)s->tail, SEEK_SET) != 0) return -1;
  uint8_t hdr[kHeader];
  hdr[0] = kMagic;
  memcpy(hdr + 1, &varlen, 4);
  memcpy(hdr + 5, &t, 8);
  memcpy(hdr + 13, &vallen, 8);
  if (fwrite(hdr, 1, kHeader, s->log) < kHeader) return -1;
  if (varlen && fwrite(var, 1, varlen, s->log) < varlen) return -1;
  if (vallen && fwrite(val, 1, vallen, s->log) < vallen) return -1;
  if (fflush(s->log) != 0) return -1;  // synced writes, leveldb.go:48-53
  uint64_t val_off = s->tail + kHeader + varlen;
  s->index[std::string((const char*)var, varlen)][t] = Slot{val_off, vallen};
  s->tail = val_off + vallen;
  return 0;
}

// Writes up to cap version timestamps (descending) into out; returns the
// total number of stored versions, or -1 if the variable is unknown.
// Call with cap == 0 to size, then again with a large-enough buffer
// (mirrors the leveldb key-range walk, leveldb.go:30-46).
int64_t kv_versions(Store* s, const uint8_t* var, uint32_t varlen,
                    uint64_t* out, uint64_t cap) {
  if (!s) return -1;
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(std::string((const char*)var, varlen));
  if (it == s->index.end()) return -1;
  const std::map<uint64_t, Slot>& versions = it->second;
  uint64_t i = 0;
  for (auto vit = versions.rbegin(); vit != versions.rend() && i < cap;
       ++vit, ++i) {
    out[i] = vit->first;
  }
  return (int64_t)versions.size();
}

// Writes length-prefixed (u32 LE) variable names into out (cap bytes
// of room); returns the total byte length needed for ALL names, or -1
// on error. Call with out == nullptr / cap == 0 to size, then again
// with a large-enough buffer (same two-call shape as kv_versions).
// Keyspace enumeration backs the anti-entropy digest tree
// (bftkv_tpu/sync); the reference's leveldb backend would use a
// whole-range iterator the same way.
int64_t kv_keys(Store* s, uint8_t* out, uint64_t cap) {
  if (!s) return -1;
  std::lock_guard<std::mutex> lock(s->mu);
  uint64_t need = 0, off = 0;
  for (const auto& kv : s->index) {
    uint64_t rec = 4 + kv.first.size();
    if (out && off + rec <= cap) {
      uint32_t len = (uint32_t)kv.first.size();
      memcpy(out + off, &len, 4);
      memcpy(out + off + 4, kv.first.data(), kv.first.size());
      off += rec;
    }
    need += rec;
  }
  return (int64_t)need;
}

// t == 0 means latest. Returns value length, or -1 if not found, or -2 on
// I/O error. If out is non-null it must have room for the value (call once
// with out == nullptr to size, then again to fetch; *t_out gets the
// resolved timestamp so the pair of calls is consistent).
int64_t kv_read(Store* s, const uint8_t* var, uint32_t varlen, uint64_t t,
                uint8_t* out, uint64_t* t_out) {
  if (!s) return -2;
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(std::string((const char*)var, varlen));
  if (it == s->index.end() || it->second.empty()) return -1;
  const std::map<uint64_t, Slot>& versions = it->second;
  std::map<uint64_t, Slot>::const_iterator vit;
  if (t == 0) {
    vit = std::prev(versions.end());
  } else {
    vit = versions.find(t);
    if (vit == versions.end()) return -1;
  }
  if (t_out) *t_out = vit->first;
  const Slot& slot = vit->second;
  if (out) {
    if (fseek(s->log, (long)slot.offset, SEEK_SET) != 0) return -2;
    if (fread(out, 1, slot.length, s->log) < slot.length) return -2;
    if (fseek(s->log, (long)s->tail, SEEK_SET) != 0) return -2;
  }
  return (int64_t)slot.length;
}

}  // extern "C"
