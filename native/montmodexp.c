/* Montgomery modular exponentiation for the RSA hot path.
 *
 * CPython's big-int pow() is the write path's floor: one RSA-2048
 * CRT sign is two 1024-bit modexps at ~4 ms each, it holds the GIL
 * for the duration, and a 4-signs-per-write protocol tops out around
 * 25 writes/s/core no matter how few round trips the transport pays
 * (docs/PERFORMANCE.md "RSA floor").  This extension implements the
 * same modexp as fixed-width CIOS Montgomery multiplication with a
 * 4-bit window, releases the GIL while computing, and is loaded
 * opportunistically by bftkv_tpu/crypto/rsa.py (BFTKV_NATIVE_MODEXP=off
 * disables; the pure pow() path remains the semantics oracle, pinned
 * by differential tests in tests/test_rsa.py).
 *
 * API:  powmod(base, exp, mod, r2, n0inv) -> bytes
 *   base, mod, r2: big-endian byte strings, len(mod) a multiple of 8;
 *   base < mod;  r2 = 2^(2*64*nlimbs) mod mod (caller precomputes,
 *   cached per key);  n0inv = -mod^-1 mod 2^64.
 *   exp: big-endian byte string, any length > 0.
 * Returns the big-endian result, len(mod) bytes.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;
typedef uint64_t u64;

#define MAX_LIMBS 64 /* up to 4096-bit moduli */

/* little-endian limb arrays throughout */

static void be_to_limbs(const unsigned char *be, Py_ssize_t len, u64 *out,
                        int nlimbs) {
    memset(out, 0, (size_t)nlimbs * 8);
    for (Py_ssize_t i = 0; i < len; i++) {
        Py_ssize_t bit = (len - 1 - i);
        out[bit / 8] |= (u64)be[i] << (8 * (bit % 8));
    }
}

static void limbs_to_be(const u64 *in, int nlimbs, unsigned char *be) {
    for (int i = 0; i < nlimbs; i++) {
        u64 w = in[nlimbs - 1 - i];
        for (int b = 0; b < 8; b++)
            be[i * 8 + b] = (unsigned char)(w >> (8 * (7 - b)));
    }
}

static int geq(const u64 *a, const u64 *n, int L) {
    for (int i = L - 1; i >= 0; i--) {
        if (a[i] > n[i]) return 1;
        if (a[i] < n[i]) return 0;
    }
    return 1; /* equal */
}

static void sub_n(u64 *a, const u64 *n, int L) {
    u64 borrow = 0;
    for (int i = 0; i < L; i++) {
        u64 ni = n[i] + borrow;
        borrow = (ni < borrow) | (a[i] < ni);
        a[i] -= ni;
    }
}

/* CIOS Montgomery multiplication: t = a*b*R^-1 mod n (R = 2^(64L)).
 * Accumulator has L+2 limbs; result reduced to < n. */
static void mont_mul(const u64 *a, const u64 *b, const u64 *n, u64 n0inv,
                     int L, u64 *t /* L+2 scratch, output in t[0..L-1] */) {
    memset(t, 0, (size_t)(L + 2) * 8);
    for (int i = 0; i < L; i++) {
        u64 carry = 0;
        u64 ai = a[i];
        for (int j = 0; j < L; j++) {
            u128 s = (u128)ai * b[j] + t[j] + carry;
            t[j] = (u64)s;
            carry = (u64)(s >> 64);
        }
        u128 s = (u128)t[L] + carry;
        t[L] = (u64)s;
        t[L + 1] = (u64)(s >> 64);

        u64 m = t[0] * n0inv;
        s = (u128)m * n[0] + t[0];
        carry = (u64)(s >> 64);
        for (int j = 1; j < L; j++) {
            s = (u128)m * n[j] + t[j] + carry;
            t[j - 1] = (u64)s;
            carry = (u64)(s >> 64);
        }
        s = (u128)t[L] + carry;
        t[L - 1] = (u64)s;
        t[L] = t[L + 1] + (u64)(s >> 64);
        t[L + 1] = 0;
    }
    if (t[L] || geq(t, n, L)) sub_n(t, n, L);
}

static PyObject *py_powmod(PyObject *self, PyObject *args) {
    Py_buffer base_b, exp_b, mod_b, r2_b;
    unsigned long long n0inv;
    if (!PyArg_ParseTuple(args, "y*y*y*y*K", &base_b, &exp_b, &mod_b,
                          &r2_b, &n0inv))
        return NULL;

    PyObject *ret = NULL;
    int L = (int)(mod_b.len / 8);
    if (mod_b.len % 8 != 0 || L <= 0 || L > MAX_LIMBS ||
        base_b.len > mod_b.len || r2_b.len > mod_b.len || exp_b.len == 0) {
        PyErr_SetString(PyExc_ValueError, "montmodexp: bad operand shape");
        goto done;
    }

    {
        u64 n[MAX_LIMBS], x[MAX_LIMBS], r2[MAX_LIMBS];
        u64 table[16][MAX_LIMBS];
        u64 acc[MAX_LIMBS], t[MAX_LIMBS + 2];
        unsigned char out[MAX_LIMBS * 8];
        const unsigned char *e = (const unsigned char *)exp_b.buf;
        Py_ssize_t elen = exp_b.len;

        be_to_limbs((const unsigned char *)mod_b.buf, mod_b.len, n, L);
        be_to_limbs((const unsigned char *)base_b.buf, base_b.len, x, L);
        be_to_limbs((const unsigned char *)r2_b.buf, r2_b.len, r2, L);
        if (!(n[0] & 1)) {
            PyErr_SetString(PyExc_ValueError, "montmodexp: even modulus");
            goto done;
        }

        Py_BEGIN_ALLOW_THREADS;

        /* table[1] = x in Montgomery form; table[0] = 1 in Mont form */
        mont_mul(x, r2, n, (u64)n0inv, L, t);
        memcpy(table[1], t, (size_t)L * 8);
        {
            u64 one[MAX_LIMBS];
            memset(one, 0, (size_t)L * 8);
            one[0] = 1;
            mont_mul(one, r2, n, (u64)n0inv, L, t);
            memcpy(table[0], t, (size_t)L * 8);
        }
        for (int i = 2; i < 16; i++) {
            mont_mul(table[i - 1], table[1], n, (u64)n0inv, L, t);
            memcpy(table[i], t, (size_t)L * 8);
        }

        /* 4-bit windowed scan over the big-endian exponent bytes */
        memcpy(acc, table[0], (size_t)L * 8);
        for (Py_ssize_t i = 0; i < elen; i++) {
            unsigned char byte = e[i];
            for (int half = 0; half < 2; half++) {
                int w = half == 0 ? (byte >> 4) : (byte & 0xF);
                for (int s = 0; s < 4; s++) {
                    mont_mul(acc, acc, n, (u64)n0inv, L, t);
                    memcpy(acc, t, (size_t)L * 8);
                }
                if (w) {
                    mont_mul(acc, table[w], n, (u64)n0inv, L, t);
                    memcpy(acc, t, (size_t)L * 8);
                }
            }
        }

        /* out of Montgomery form */
        {
            u64 one[MAX_LIMBS];
            memset(one, 0, (size_t)L * 8);
            one[0] = 1;
            mont_mul(acc, one, n, (u64)n0inv, L, t);
            memcpy(acc, t, (size_t)L * 8);
        }

        limbs_to_be(acc, L, out);

        Py_END_ALLOW_THREADS;

        ret = PyBytes_FromStringAndSize((const char *)out, (Py_ssize_t)L * 8);
    }

done:
    PyBuffer_Release(&base_b);
    PyBuffer_Release(&exp_b);
    PyBuffer_Release(&mod_b);
    PyBuffer_Release(&r2_b);
    return ret;
}

static PyMethodDef Methods[] = {
    {"powmod", py_powmod, METH_VARARGS,
     "powmod(base, exp, mod, r2, n0inv) -> bytes (all big-endian; "
     "len(mod) %% 8 == 0; r2 = 2^(2*64*L) mod mod; n0inv = -mod^-1 mod 2^64)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_montmodexp",
    "fixed-width Montgomery modexp (GIL-releasing)", -1, Methods,
};

PyMODINIT_FUNC PyInit__montmodexp(void) { return PyModule_Create(&moduledef); }
