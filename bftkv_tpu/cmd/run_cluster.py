"""Cluster runner — one daemon process per home directory.

The reference spawns one ``bftkv`` per key dir with sequential ports
(scripts/run.sh:27-41); here the address already lives in each home's
certificate, so the runner just enumerates server homes (names not
starting with ``u``) and execs the daemon for each:

    python -m bftkv_tpu.cmd.genkeys --out /tmp/keys --servers 4 --rw 4
    python -m bftkv_tpu.cmd.run_cluster --keys /tmp/keys --db-root /tmp/dbs

The runner lives until SIGINT/SIGTERM and then tears the fleet down.
``--api-base`` exposes the client API on sequential ports (reference
run.sh uses 6001+ for its debug API).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

from bftkv_tpu import flags


def server_homes(keys_dir: str) -> list[str]:
    out = []
    if not os.path.isdir(keys_dir):
        return out  # --shards generates into a fresh dir
    for name in sorted(os.listdir(keys_dir)):
        home = os.path.join(keys_dir, name)
        # u* are client homes, gw* are edge gateway homes (run by
        # bftkv_tpu.cmd.run_gateway, not the replica daemon).
        if not os.path.isdir(home) or name.startswith(("u", "gw")):
            continue
        out.append(home)
    return out


def gateway_homes(keys_dir: str) -> list[str]:
    if not os.path.isdir(keys_dir):
        return []
    return sorted(
        os.path.join(keys_dir, name)
        for name in os.listdir(keys_dir)
        if name.startswith("gw")
        and os.path.isdir(os.path.join(keys_dir, name))
    )


def spawn(
    homes: list[str],
    db_root: str,
    *,
    storage: str = "plain",
    api_base: int = 0,
    api_host: str = "127.0.0.1",
    bind_host: str = "",
    join: bool = False,
    client_home: str = "",
    verify_sidecar: str = "",
    sidecar: str = "",
    anti_entropy: float = 0.0,
    slow_trace: float | None = None,
    rpc_timeout: float | None = None,
    chaos_seed: int | None = None,
    fleet: int = 0,
    fleet_interval: float = 2.0,
    recorder: str = "",
    autopilot: bool = False,
    gw_homes: list[str] | None = None,
    gw_sync_invalidate: float = 5.0,
    extra_env: dict | None = None,
) -> list[subprocess.Popen]:
    """``verify_sidecar``: "auto" spawns one shared sidecar process and
    routes every daemon's verification through it (public data only —
    signing stays per-replica); "host:port" uses an existing one.

    ``sidecar``: the full shared crypto service — "auto" spawns ONE
    sidecar (mode-0600 unix socket under db_root) that every replica
    AND gateway signs+verifies through, with a stats endpoint the
    ``--fleet`` collector scrapes as a ``role=sidecar`` member (it
    takes the port after the gateways', outside all f-budget math)."""
    if sidecar and verify_sidecar:
        raise ValueError("--sidecar supersedes --verify-sidecar; "
                         "pass one")
    if fleet and not api_base:
        # Argument-only precondition: checked BEFORE any daemon spawns
        # (raising mid-spawn would orphan the just-launched fleet).
        raise ValueError("--fleet needs --api-base (it scrapes the "
                         "daemon APIs)")
    if autopilot and not fleet:
        raise ValueError("--autopilot needs --fleet (it watches the "
                         "collector's /fleet document)")
    os.makedirs(db_root, exist_ok=True)
    procs = []
    env = dict(os.environ, **(extra_env or {}))
    if verify_sidecar == "auto" or verify_sidecar.startswith("auto:"):
        # "auto" → a mode-0600 Unix socket under db_root (a TCP port
        # could be squatted by another local user after a sidecar
        # crash); "auto:HOST:PORT" / "auto:unix:/path" → explicit
        # address.  (Exact prefix match: a real host named
        # auto*.example resolves as an existing sidecar, not a spawn
        # request.)
        _, _, rest = verify_sidecar.partition(":")
        verify_sidecar = rest or "unix:" + os.path.join(
            os.path.abspath(db_root), "verify.sock"
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "bftkv_tpu.cmd.verify_sidecar",
                    "--listen", verify_sidecar,
                ],
                env=env,
            )
        )
    sidecar_stats = ""
    if sidecar == "auto" or sidecar.startswith("auto:"):
        _, _, rest = sidecar.partition(":")
        sidecar = rest or "unix:" + os.path.join(
            os.path.abspath(db_root), "sidecar.sock"
        )
        cmd = [
            sys.executable, "-m", "bftkv_tpu.cmd.verify_sidecar",
            "--listen", sidecar,
        ]
        if api_base:
            # Stats ride the port after the gateways' APIs so the
            # fleet collector's sequential scrape covers the sidecar
            # (role=sidecar — excluded from every f-budget).
            sidecar_stats = (
                f"{api_host}:"
                f"{api_base + len(homes) + len(gw_homes or [])}"
            )
            cmd += ["--stats", sidecar_stats]
        procs.append(subprocess.Popen(cmd, env=env))
    for i, home in enumerate(homes):
        name = os.path.basename(home)
        cmd = [
            sys.executable, "-m", "bftkv_tpu.cmd.bftkv",
            "--home", home,
            "--db", os.path.join(db_root, name),
            "--storage", storage,
            "--revlist", os.path.join(db_root, name + ".rev"),
        ]
        if api_base:
            cmd += ["--api", f"{api_host}:{api_base + i}"]
            if client_home:
                cmd += ["--client-home", client_home]
        if bind_host:
            cmd += ["--bind-host", bind_host]
        if join:
            cmd += ["--join"]
        if sidecar:
            cmd += ["--sidecar", sidecar]
        elif verify_sidecar:
            cmd += ["--verify-sidecar", verify_sidecar]
        if anti_entropy > 0:
            cmd += ["--anti-entropy", str(anti_entropy)]
        if slow_trace is not None:
            cmd += ["--slow-trace", str(slow_trace)]
        if rpc_timeout is not None:
            cmd += ["--rpc-timeout", str(rpc_timeout)]
        if chaos_seed is not None:
            # seed + index: each daemon's schedule is reproducible run
            # to run but the fleet does not fire faults in lockstep.
            cmd += ["--chaos-seed", str(chaos_seed + i)]
        procs.append(subprocess.Popen(cmd, env=env))
    # Edge gateways ride after the replicas: their operator APIs take
    # the next sequential ports, so the fleet collector scrapes the
    # whole tier with one --count.
    for j, home in enumerate(gw_homes or []):
        cmd = [
            sys.executable, "-m", "bftkv_tpu.cmd.run_gateway",
            "--home", home,
            "--sync-invalidate", str(gw_sync_invalidate),
        ]
        if api_base:
            cmd += ["--api", f"{api_host}:{api_base + len(homes) + j}"]
        if bind_host:
            cmd += ["--bind-host", bind_host]
        if rpc_timeout is not None:
            cmd += ["--rpc-timeout", str(rpc_timeout)]
        if sidecar:
            cmd += ["--sidecar", sidecar]
        if fleet:
            cmd += ["--fleet", f"http://127.0.0.1:{fleet}/fleet"]
        procs.append(subprocess.Popen(cmd, env=env))
    if fleet:
        # The health plane rides alongside the fleet: one collector
        # process scraping every daemon's (and gateway's) /info +
        # /metrics + /trace, serving the aggregate on /fleet
        # (bftkv_tpu.obs).  --recorder attaches the flight recorder to
        # it: anomalies snapshot black-box bundles under that dir.
        cmd = [
            sys.executable, "-m", "bftkv_tpu.cmd.fleet",
            "--api-base", str(api_base),
            "--count", str(
                len(homes)
                + len(gw_homes or [])
                + (1 if sidecar_stats else 0)
            ),
            "--api-host", api_host,
            "--listen", f"127.0.0.1:{fleet}",
            "--interval", str(fleet_interval),
        ]
        if recorder:
            cmd += ["--recorder", recorder]
        procs.append(subprocess.Popen(cmd, env=env))
    if autopilot:
        # Advisory watcher over the collector's /fleet document: prints
        # retire/split decisions as JSON lines (BFTKV_AUTOPILOT=off
        # silences it).  In-process fleets (nemesis, benches, tests)
        # run the executing Autopilot directly.
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "bftkv_tpu.autopilot",
                    "--fleet-url", f"http://127.0.0.1:{fleet}/fleet",
                    "--interval", str(max(fleet_interval * 2, 2.0)),
                ],
                env=env,
            )
        )
    return procs


def shutdown(procs: list[subprocess.Popen], timeout: float = 10.0) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="bftkv cluster runner")
    ap.add_argument("--keys", required=True, help="directory of home dirs")
    ap.add_argument("--db-root", required=True)
    # The log engine is the cluster default since PR 17 (group commit
    # beats per-write fsync pairs under any concurrency; bench r9/r10
    # cluster_4_log vs cluster_4) — plain stays selectable, and the
    # single-daemon CLI (cmd/bftkv.py) keeps its plain default.
    ap.add_argument("--storage", choices=["plain", "log", "native", "mem"],
                    default=flags.get("BFTKV_STORAGE") or "log")
    ap.add_argument("--api-base", type=int, default=0,
                    help="client API port for the first server, +1 each")
    ap.add_argument("--client-home", default="",
                    help="user home the client APIs act as (see bftkv --help)")
    ap.add_argument("--api-host", default="127.0.0.1",
                    help="interface the client APIs listen on")
    ap.add_argument("--bind-host", default="",
                    help="protocol listen interface override (containers: "
                         "0.0.0.0)")
    ap.add_argument("--verify-sidecar", default="",
                    help='"auto" spawns one shared verification sidecar '
                         "for the fleet; or host:port of an existing one")
    ap.add_argument("--sidecar", default="",
                    help='"auto" spawns ONE shared crypto sidecar (sign+'
                         "verify+modexp, unix socket under --db-root) "
                         "that every replica and gateway batches "
                         "through; with --fleet its stats endpoint "
                         "joins the scrape as a role=sidecar member.  "
                         "Or host:port/unix:path of an existing one")
    ap.add_argument("--anti-entropy", type=float, default=0.0,
                    metavar="SECONDS",
                    help="per-daemon background state-sync interval "
                         "(jittered; 0 disables — see bftkv --help)")
    ap.add_argument("--slow-trace", type=float, default=None,
                    metavar="SECONDS",
                    help="per-daemon slow-request trace threshold "
                         "(see bftkv --help)")
    ap.add_argument("--rpc-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="per-daemon per-RPC response deadline "
                         "(see bftkv --help)")
    ap.add_argument("--chaos-seed", type=int, default=None, metavar="N",
                    help="TESTING: arm every daemon's deterministic "
                         "failpoint registry (daemon i gets seed N+i); "
                         "same N replays the same fleet-wide fault "
                         "schedule (see bftkv --help)")
    ap.add_argument("--fleet", type=int, default=0, metavar="PORT",
                    help="boot the fleet health collector alongside the "
                         "cluster, serving /fleet (JSON + Prometheus) on "
                         "127.0.0.1:PORT — per-shard f-budget, stitched "
                         "cross-process traces, anomaly feed "
                         "(bftkv_tpu.obs; needs --api-base)")
    ap.add_argument("--fleet-interval", type=float, default=2.0,
                    metavar="SECONDS",
                    help="collector scrape interval")
    ap.add_argument("--recorder", default="", metavar="DIR",
                    help="attach the flight recorder to the --fleet "
                         "collector: every anomaly snapshots a rate-"
                         "limited black-box bundle (traces, metrics, "
                         "anomaly ring, failpoint log, last profile) "
                         "under DIR; POST /fleet/bundle takes one on "
                         "demand (needs --fleet)")
    ap.add_argument("--autopilot", action="store_true",
                    help="boot the topology autopilot watcher beside "
                         "the fleet collector (needs --fleet): it "
                         "consumes /fleet and prints split/retire "
                         "decisions as JSON lines "
                         "(BFTKV_AUTOPILOT=off disables)")
    ap.add_argument("--shards", type=int, default=0, metavar="N",
                    help="one-box sharded quickstart: when --keys holds "
                         "no server homes yet, generate an N-clique "
                         "topology there first (4 servers + 4 rw per "
                         "shard, 1 user; the keyspace hash-routes "
                         "across the cliques) and then run it")
    ap.add_argument("--gateways", type=int, default=0, metavar="N",
                    help="run N edge gateways (cmd.run_gateway) from "
                         "the gw* homes under --keys; their operator "
                         "APIs take the ports after the daemons' and "
                         "join the --fleet scrape.  The --shards "
                         "quickstart generates the gw homes too")
    ap.add_argument("--regions", type=int, default=0, metavar="N",
                    help="quickstart only: generate the topology with "
                         "N region labels (genkeys --regions).  Each "
                         "daemon picks its region up from its home's "
                         "`regions` file automatically, so an already-"
                         "generated labeled keyset needs no flag here")
    args = ap.parse_args(argv)

    if args.shards and not server_homes(args.keys):
        from bftkv_tpu.cmd import genkeys

        print(
            f"run_cluster: generating {args.shards}-shard topology "
            f"under {args.keys}", flush=True,
        )
        genkeys.main([
            "--out", args.keys, "--shards", str(args.shards),
            "--servers", "4", "--rw", "4", "--users", "1",
            "--gateways", str(args.gateways),
            "--regions", str(args.regions),
        ])

    homes = server_homes(args.keys)
    if not homes:
        print(f"no server homes under {args.keys}", file=sys.stderr)
        return 1
    if args.fleet and not args.api_base:
        print("--fleet needs --api-base (the collector scrapes the "
              "daemon APIs)", file=sys.stderr)
        return 1
    if args.autopilot and not args.fleet:
        print("--autopilot needs --fleet (it watches the collector's "
              "/fleet document)", file=sys.stderr)
        return 1
    if args.recorder and not args.fleet:
        print("--recorder needs --fleet (the recorder hangs off the "
              "collector's anomaly feed)", file=sys.stderr)
        return 1
    gw_homes = gateway_homes(args.keys)[: args.gateways]
    if args.gateways and len(gw_homes) < args.gateways:
        print(f"--gateways {args.gateways} but only {len(gw_homes)} gw* "
              f"homes under {args.keys} (genkeys --gateways)",
              file=sys.stderr)
        return 1
    procs = spawn(homes, args.db_root, storage=args.storage,
                  api_base=args.api_base, api_host=args.api_host,
                  bind_host=args.bind_host, client_home=args.client_home,
                  verify_sidecar=args.verify_sidecar,
                  sidecar=args.sidecar,
                  anti_entropy=args.anti_entropy,
                  slow_trace=args.slow_trace,
                  rpc_timeout=args.rpc_timeout,
                  chaos_seed=args.chaos_seed,
                  fleet=args.fleet, fleet_interval=args.fleet_interval,
                  recorder=args.recorder,
                  autopilot=args.autopilot, gw_homes=gw_homes)
    if args.fleet:
        print(f"run_cluster: fleet health @ http://127.0.0.1:{args.fleet}"
              "/fleet", flush=True)
    # The sidecar (if spawned, always first) is an optional optimizer
    # whose clients fall back to local verification: its death must not
    # tear down the replica fleet, and it is not a "server".
    servers = [p for p in procs if "bftkv_tpu.cmd.bftkv" in p.args]
    print(f"run_cluster: {len(servers)} servers up"
          + (f", {len(gw_homes)} gateways" if gw_homes else ""),
          flush=True)

    stopping = False

    def handler(signum, frame):
        nonlocal stopping
        stopping = True

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    while not stopping and all(p.poll() is None for p in servers):
        time.sleep(0.5)
    shutdown(procs)
    print("run_cluster: stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
