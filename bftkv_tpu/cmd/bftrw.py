"""``bftrw`` user CLI — register / read / write / ca / sign / kms / getkey.

Capability parity with the reference user tool
(cmd/bftrw/bftrw.go:60-165,188-316):

    bftrw --home /tmp/keys/u01 register --peers /tmp/keys/a01 ... --password pw
    bftrw --home /tmp/keys/u01 write  x [value | -]   [--password pw]
    bftrw --home /tmp/keys/u01 writeonce x [value | -]
    bftrw --home /tmp/keys/u01 read   x               [--password pw]
    bftrw --home /tmp/keys/u01 ca     <caname> --key ca.pkcs8
    bftrw --home /tmp/keys/u01 sign   <caname> --in tbs.bin --algo rsa --hash sha256
    bftrw --home /tmp/keys/u01 kms    <caname> --password pw   # random key,
                                                               # stored wrapped
    bftrw --home /tmp/keys/u01 getkey <caname> <name> --password pw

``ca`` deals a private key to the quorum as threshold shares;
``sign`` threshold-signs arbitrary TBS bytes with it (the reference's
X.509-specific plumbing is left to the caller — the signature bytes are
standard PKCS#1 v1.5 / DSA / ECDSA).  ``kms`` generates a random
256-bit key, stores it under a random name password-protected, and
prints the name (reference: bftrw.go:272-316).
"""

from __future__ import annotations

import argparse
import os
import sys


def _algo(name: str):
    from bftkv_tpu.crypto.threshold import ThresholdAlgo

    return {
        "rsa": ThresholdAlgo.RSA,
        "dsa": ThresholdAlgo.DSA,
        "ecdsa": ThresholdAlgo.ECDSA,
    }[name]


def _load_ca_key(path: str):
    """PKCS#8 (or traditional PEM) private key → framework key object
    (reference: bftrw.go:217-243 readPKCS8)."""
    from cryptography.hazmat.primitives import serialization

    with open(path, "rb") as f:
        data = f.read()
    load = (
        serialization.load_pem_private_key
        if b"-----BEGIN" in data
        else serialization.load_der_private_key
    )
    key = load(data, password=None)
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives.asymmetric import rsa as crsa

    if isinstance(key, crsa.RSAPrivateKey):
        from bftkv_tpu.crypto import rsa

        pn = key.private_numbers()
        return rsa.PrivateKey(
            n=pn.public_numbers.n, e=pn.public_numbers.e, d=pn.d, p=pn.p, q=pn.q
        )
    if isinstance(key, cec.EllipticCurvePrivateKey):
        from bftkv_tpu.crypto import ec as ecmod
        from bftkv_tpu.crypto.threshold.ecdsa import ECDSAPrivateKey

        if key.curve.name != "secp256r1":
            raise SystemExit(f"unsupported curve {key.curve.name}")
        return ECDSAPrivateKey(ecmod.P256, key.private_numbers().private_value)
    raise SystemExit(f"unsupported CA key type for {path}")


def _value_arg(v: str | None) -> bytes:
    if v is None or v == "-":
        return sys.stdin.buffer.read()
    return v.encode()


# -- X.509 threshold signing (reference: bftrw.go:211-302) ----------------


def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _der_children(data: bytes) -> list[bytes]:
    """Top-level TLV elements of a DER SEQUENCE body (full encodings)."""
    out, off = [], 0
    while off < len(data):
        start = off
        off += 1  # tag (all tags we meet are single-byte)
        ln = data[off]
        off += 1
        if ln & 0x80:
            nbytes = ln & 0x7F
            ln = int.from_bytes(data[off : off + nbytes], "big")
            off += nbytes
        off += ln
        out.append(data[start:off])
    return out


def threshold_sign_x509(a, caname: str, der: bytes) -> bytes:
    """Re-sign an X.509 template certificate with the threshold CA and
    return the assembled DER (reference: bftrw.go:216-302 — the
    template's TBS is threshold-signed and the certificate rebuilt as
    SEQUENCE{tbs, signatureAlgorithm, BIT STRING}).
    """
    from cryptography import x509
    from cryptography.x509.oid import SignatureAlgorithmOID as OID

    crt = x509.load_der_x509_certificate(der)
    oid = crt.signature_algorithm_oid
    algos = {
        OID.RSA_WITH_SHA256: ("rsa", "sha256"),
        OID.RSA_WITH_SHA384: ("rsa", "sha384"),
        OID.RSA_WITH_SHA512: ("rsa", "sha512"),
        OID.ECDSA_WITH_SHA256: ("ecdsa", "sha256"),
        OID.ECDSA_WITH_SHA384: ("ecdsa", "sha384"),
        OID.ECDSA_WITH_SHA512: ("ecdsa", "sha512"),
    }
    if oid not in algos:
        raise SystemExit(f"unsupported signature algorithm {oid}")
    algo_name, hash_name = algos[oid]

    sig = a.sign(caname, crt.tbs_certificate_bytes, _algo(algo_name), hash_name)
    if algo_name == "ecdsa":
        # Our threshold ECDSA yields raw r||s; X.509 carries DER
        # ECDSA-Sig-Value.
        from cryptography.hazmat.primitives.asymmetric.utils import (
            encode_dss_signature,
        )

        half = len(sig) // 2
        sig = encode_dss_signature(
            int.from_bytes(sig[:half], "big"),
            int.from_bytes(sig[half:], "big"),
        )

    outer = _der_children(der)[0]  # the Certificate SEQUENCE
    hdr = 2 if outer[1] < 0x80 else 2 + (outer[1] & 0x7F)
    tbs_b, sigalg_b, _old_sig = _der_children(outer[hdr:])
    bitstring = b"\x03" + _der_len(len(sig) + 1) + b"\x00" + sig
    body = tbs_b + sigalg_b + bitstring
    return b"\x30" + _der_len(len(body)) + body


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="bftkv user tool")
    ap.add_argument("--home", required=True)
    ap.add_argument("--no-join", action="store_true",
                    help="skip the joining crawl (offline commands)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("register")
    p.add_argument("--peers", nargs="+", required=True,
                   help="server home dirs to trust")
    p.add_argument("--password", required=True)

    for name in ("read", "write", "writeonce"):
        p = sub.add_parser(name)
        p.add_argument("variable")
        if name != "read":
            p.add_argument("value", nargs="?")
        p.add_argument("--password", default="")

    p = sub.add_parser("writemany")
    p.add_argument("--file", default="-",
                   help="lines of variable=value (default stdin); batched "
                        "through the write_many pipeline")

    p = sub.add_parser("readmany")
    p.add_argument("variables", nargs="+")

    p = sub.add_parser("ca")
    p.add_argument("caname")
    p.add_argument("--key", required=True, help="PKCS#8 private key file")

    p = sub.add_parser("sign")
    p.add_argument("caname")
    p.add_argument("--in", dest="infile", required=True)
    p.add_argument("--algo", choices=["rsa", "dsa", "ecdsa"], default="rsa")
    p.add_argument("--hash", dest="hash_name", default="sha256")
    p.add_argument("--out", default="", help="signature output (default stdout)")

    p = sub.add_parser("signx509")
    p.add_argument("caname")
    p.add_argument("--cert", required=True,
                   help="template certificate (PEM or DER); its TBS is "
                        "threshold-signed by the CA")
    p.add_argument("--out", default="", help="output file (default stdout PEM)")
    p.add_argument("--no-store", action="store_true",
                   help="skip storing the cert under its SubjectKeyId")

    p = sub.add_parser("kms")
    p.add_argument("caname")
    p.add_argument("--password", required=True)

    p = sub.add_parser("getkey")
    p.add_argument("caname")
    p.add_argument("name")
    p.add_argument("--password", required=True)

    args = ap.parse_args(argv)

    from bftkv_tpu import api as apimod

    a = apimod.open_client(args.home, join=not args.no_join)

    if args.cmd == "register":
        a.register(args.peers, args.password)
        print(f"registered uid={a.uid}")
    elif args.cmd == "read":
        value = a.read(args.variable.encode(), args.password)
        if value is None:
            print("not found", file=sys.stderr)
            return 1
        sys.stdout.buffer.write(value)
    elif args.cmd in ("write", "writeonce"):
        value = _value_arg(args.value)
        if args.cmd == "write":
            a.write(args.variable.encode(), value, args.password)
        else:
            a.write_once(args.variable.encode(), value, args.password)
        print("ok", file=sys.stderr)
    elif args.cmd == "writemany":
        src = (
            sys.stdin.buffer
            if args.file == "-"
            else open(args.file, "rb")
        )
        items = []
        seen: set[bytes] = set()
        dup_errs: list[str] = []
        with src:
            for line in src.read().splitlines():
                if not line.strip():
                    continue
                var, sep, value = line.partition(b"=")
                if not sep or not var:
                    # A typoed line must not silently write b"" (or an
                    # empty variable name) into the store.
                    dup_errs.append(
                        f"{line.decode(errors='replace')!r}: "
                        "expected variable=value"
                    )
                    continue
                if var in seen:
                    # write_many forbids duplicate variables (they
                    # would equivocate at the same timestamp); report
                    # per line instead of crashing on the ValueError.
                    dup_errs.append(
                        f"{var.decode(errors='replace')}: duplicate in batch"
                    )
                    continue
                seen.add(var)
                items.append((var, value))
        errs = a.write_many(items)
        rc = 1 if dup_errs else 0
        for msg in dup_errs:
            print(msg, file=sys.stderr)
        for (var, _v), err in zip(items, errs):
            if err is not None:
                print(f"{var.decode(errors='replace')}: {err}", file=sys.stderr)
                rc = 1
        print(f"{sum(e is None for e in errs)}/{len(items)} written",
              file=sys.stderr)
        return rc
    elif args.cmd == "readmany":
        got = a.read_many([v.encode() for v in args.variables])
        rc = 0
        for var, res in zip(args.variables, got):
            if isinstance(res, bytes):
                sys.stdout.buffer.write(var.encode() + b"=" + res + b"\n")
            elif res is None:
                # Match the single `read` command: missing is an error,
                # distinct from a stored-but-empty value.
                print(f"{var}: not found", file=sys.stderr)
                rc = 1
            else:
                print(f"{var}: {res}", file=sys.stderr)
                rc = 1
        return rc
    elif args.cmd == "ca":
        key = _load_ca_key(args.key)
        a.distribute(args.caname, key)
        print(f"ca {args.caname}: key distributed")
    elif args.cmd == "sign":
        with open(args.infile, "rb") as f:
            tbs = f.read()
        sig = a.sign(args.caname, tbs, _algo(args.algo), args.hash_name)
        if args.out:
            with open(args.out, "wb") as f:
                f.write(sig)
        else:
            sys.stdout.buffer.write(sig)
    elif args.cmd == "signx509":
        from cryptography import x509 as _x509
        from cryptography.hazmat.primitives import serialization as _ser

        with open(args.cert, "rb") as f:
            data = f.read()
        if b"-----BEGIN" in data:
            data = _x509.load_pem_x509_certificate(data).public_bytes(
                _ser.Encoding.DER
            )
        out_der = threshold_sign_x509(a, args.caname, data)
        crt = _x509.load_der_x509_certificate(out_der)
        if not args.no_store:
            # Register under the SubjectKeyId (reference: bftrw.go:293).
            try:
                ski = crt.extensions.get_extension_for_class(
                    _x509.SubjectKeyIdentifier
                ).value.digest
                a.write(ski, out_der)
            except _x509.ExtensionNotFound:
                print("no SubjectKeyId extension; not stored", file=sys.stderr)
        pem = crt.public_bytes(_ser.Encoding.PEM)
        if args.out:
            with open(args.out, "wb") as f:
                f.write(pem)
        else:
            sys.stdout.buffer.write(pem)
    elif args.cmd == "kms":
        # Random name + random key, stored password-protected
        # (reference: bftrw.go:272-316).
        name = os.urandom(8).hex()
        key = os.urandom(32)
        a.write((args.caname + "/" + name).encode(), key, args.password)
        print(name)
    elif args.cmd == "getkey":
        value = a.read((args.caname + "/" + args.name).encode(), args.password)
        if value is None:
            print("not found", file=sys.stderr)
            return 1
        sys.stdout.buffer.write(value)
    return 0


if __name__ == "__main__":
    sys.exit(main())
