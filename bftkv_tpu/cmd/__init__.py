"""Operator-facing commands.

- ``python -m bftkv_tpu.cmd.genkeys`` — key/topology generator
  (replaces the reference's GnuPG scripts, scripts/setup.sh).
- ``python -m bftkv_tpu.cmd.bftkv`` — server daemon with a client-facing
  HTTP API (reference: cmd/bftkv/main.go).
- ``python -m bftkv_tpu.cmd.bftrw`` — user CLI: register / read / write
  / ca / sign / kms / getkey (reference: cmd/bftrw/bftrw.go).
- ``python -m bftkv_tpu.cmd.run_cluster`` — spawn one daemon process per
  home directory (reference: scripts/run.sh).
"""
