"""``bftkv`` server daemon.

Capability parity with the reference daemon (cmd/bftkv/main.go:36-267):
load a home directory (pubring/secring), build
graph/quorum/transport/storage, start the protocol server on the
certificate's address, and optionally expose a client-facing HTTP API:

    GET/POST /read/<var>      value bytes (404 when absent)
    POST     /write/<var>     body = value
    POST     /writeonce/<var> body = value (t = 2^64-1, immutable)
    POST     /joining         re-crawl the trust graph
    POST     /leaving
    GET      /show            trust-graph dump (text)
    GET      /metrics         JSON metrics snapshot (no reference
                              analog; stands in for the visualizer feed)

The revocation list is loaded at startup and persisted on shutdown —
the reference parses it but leaves persistence disabled
(main.go:119-121,170-183); here it round-trips.

    python -m bftkv_tpu.cmd.bftkv --home /tmp/keys/a01 --db /tmp/db/a01 \
        --api 127.0.0.1:7001 [--storage native] [--dispatch]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bftkv_tpu.errors import ERR_NOT_FOUND, Error
from bftkv_tpu.metrics import registry as metrics
from bftkv_tpu import flags

MAX_UINT64 = (1 << 64) - 1


def build_server(args):
    from bftkv_tpu import topology
    from bftkv_tpu.protocol.server import Server
    from bftkv_tpu.transport.http import TrHTTP

    graph, crypt, qs = topology.load_home(args.home)

    if args.storage == "plain":
        from bftkv_tpu.storage.plain import PlainStorage

        # The daemon is durable by default (fsync file + dir per
        # write); BFTKV_PLAIN_FSYNC=0 opts a deployment out.
        storage = PlainStorage(
            args.db,
            fsync=flags.raw("BFTKV_PLAIN_FSYNC", "1") != "0",
        )
    elif args.storage == "log":
        from bftkv_tpu.storage.logkv import LogStorage

        # Durable by default — the §19 engine's whole point is that
        # the fsync is amortized across the group-commit batch, so
        # there is no daemon/library durability split to opt into.
        storage = LogStorage(args.db)
    elif args.storage == "native":
        from bftkv_tpu.storage.native import NativeStorage

        storage = NativeStorage(args.db)
    else:
        from bftkv_tpu.storage.memkv import MemStorage

        storage = MemStorage()

    # Revocation list (reference: main.go:119-121 parses; persistence
    # re-enabled here).
    try:
        with open(args.revlist, "rb") as f:
            from bftkv_tpu.crypto import cert as certmod

            revoked = certmod.parse(f.read())
            # revoke() (not revoke_nodes) so the peers also leave the
            # vertex set quorum selection reads — matching every other
            # revocation site (client.py / server.py).
            for n in revoked:
                graph.revoke(n)
            if revoked:
                print(f"revoked {len(revoked)} node(s) from {args.revlist}")
    except OSError:
        pass
    except Exception as e:
        # A torn .rev (crash mid-persist) must not brick the daemon.
        print(f"warning: ignoring unreadable revocation list: {e}")

    if args.ws:
        from bftkv_tpu.transport.visual import TrVisual, WsHub

        host, _, port = args.ws.rpartition(":")
        hub = WsHub((host or "127.0.0.1", int(port)))
        tr = TrVisual(crypt, hub, graph)
        print(f"bftkv: visualizer feed @ ws://{host or '127.0.0.1'}:{port}")
    else:
        tr = TrHTTP(crypt, rpc_timeout=args.rpc_timeout)
    server = Server(graph, qs, tr, crypt, storage)
    return server, graph, crypt, qs, tr


class _ApiHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *a):
        pass

    def _reply(self, code: int, body: bytes, ctype="application/octet-stream"):
        self.send_response(code)
        self.send_header("content-type", ctype)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _var(self, prefix: str) -> bytes:
        rest = self.path[len(prefix):]
        return urllib.parse.unquote(rest).encode()

    _MUTATING = ("/write/", "/writeonce/", "/joining", "/leaving")

    #: Fixed endpoint names for the api.requests label — anything else
    #: (including variable-bearing paths' tails) collapses to "other"
    #: so hostile URLs cannot blow up label cardinality.
    _ENDPOINTS = frozenset(
        ("read", "write", "writeonce", "joining", "leaving", "show",
         "visual", "debug", "metrics", "trace", "info", "profile")
    )

    def _handle(self):
        svc = self.server.svc
        path = self.path
        ep = path.split("?", 1)[0].split("/", 2)[1] if "/" in path else ""
        metrics.incr(
            "api.requests",
            labels={"endpoint": ep if ep in self._ENDPOINTS else "other"},
        )
        # Always drain the body: HTTP/1.1 keep-alive reuses the
        # connection, and unread bytes would be parsed as the next
        # request line.
        try:
            length = int(self.headers.get("content-length", "0") or 0)
            body = self.rfile.read(length) if length > 0 else b""
        except (ValueError, OSError):
            self._reply(400, b"bad request\n", "text/plain")
            return
        if self.command == "GET" and path.startswith(self._MUTATING):
            # Idempotent GETs (prefetchers, probes) must not mutate
            # quorum state.
            self._reply(405, b"method not allowed\n", "text/plain")
            return
        try:
            if path.startswith("/read/"):
                value = svc.client.read(self._var("/read/"))
                if value is None:
                    self._reply(404, b"not found\n", "text/plain")
                else:
                    self._reply(200, value)
            elif path.startswith("/write/") or path.startswith("/writeonce/"):
                if path.startswith("/write/"):
                    svc.client.write(self._var("/write/"), body)
                else:
                    svc.client.write_once(self._var("/writeonce/"), body)
                self._reply(200, b"ok\n", "text/plain")
            elif path == "/joining":
                svc.client.joining()
                self._reply(200, b"joined\n", "text/plain")
            elif path == "/leaving":
                svc.client.leaving()
                self._reply(200, b"left\n", "text/plain")
            elif path == "/show":
                self._reply(200, svc.show().encode(), "text/plain")
            elif path == "/visual":
                import os as _os

                page = _os.path.join(
                    _os.path.dirname(_os.path.dirname(
                        _os.path.dirname(_os.path.abspath(__file__)))),
                    "visual", "index.html",
                )
                with open(page, "rb") as f:
                    self._reply(200, f.read(), "text/html")
            elif path.startswith("/debug/profile"):
                # TPU/XLA trace capture (stands in for the reference's
                # pprof endpoint, cmd/bftkv/main.go:20,253): collects a
                # jax profiler trace viewable in TensorBoard/Perfetto.
                # The output location is confined to a fixed root — the
                # API may be exposed beyond localhost.
                import re as _re
                import tempfile as _tf
                import time as _time
                import urllib.parse as _up

                q = _up.parse_qs(_up.urlparse(path).query)
                try:
                    seconds = float(q.get("seconds", ["2"])[0])
                except ValueError:
                    seconds = 2.0
                if not (seconds >= 0.0):  # also catches NaN
                    seconds = 0.0
                seconds = min(seconds, 30.0)
                name = _re.sub(
                    r"[^A-Za-z0-9_.-]", "_", q.get("name", ["trace"])[0]
                )[:64]
                # "", "." and ".." survive the character filter but
                # escape (or collapse into) the confinement root.
                if name in ("", ".", ".."):
                    name = "trace"
                outdir = os.path.join(
                    _tf.gettempdir(), "bftkv-profile", name
                )
                import jax

                jax.profiler.start_trace(outdir)
                try:
                    _time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()
                self._reply(
                    200,
                    f"trace captured to {outdir}\n".encode(),
                    "text/plain",
                )
            elif path == "/metrics" or path.startswith("/metrics?"):
                # Content negotiation: Prometheus scrapers ask for text
                # (or pass ?format=prometheus); everyone else keeps the
                # original JSON snapshot.
                q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
                accept = self.headers.get("accept") or ""
                want_prom = q.get("format", [""])[0] == "prometheus" or (
                    "application/json" not in accept
                    and ("text/plain" in accept or "openmetrics" in accept)
                )
                if want_prom:
                    self._reply(
                        200,
                        metrics.prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    body = json.dumps(
                        metrics.snapshot(), sort_keys=True
                    ).encode()
                    self._reply(200, body, "application/json")
            elif path == "/trace" or path.startswith("/trace?"):
                from bftkv_tpu import trace as trmod

                q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
                if "since" in q:
                    # Incremental drain for the fleet collector: spans
                    # after the caller's cursor + the slow ring (its
                    # entries carry shard/peer attribution the /fleet
                    # exemplars surface).
                    try:
                        since = int(q["since"][0])
                    except ValueError:
                        since = 0
                    doc = trmod.tracer.export(max(0, since))
                    doc["slow"] = trmod.tracer.slow()
                    body = json.dumps(
                        doc, sort_keys=True, default=str
                    ).encode()
                    self._reply(200, body, "application/json")
                    return
                try:
                    limit = int(q.get("limit", ["20"])[0])
                except ValueError:
                    limit = 20
                limit = max(1, min(limit, 200))
                body = json.dumps(
                    {
                        "slow_threshold_s": trmod.tracer.slow_threshold,
                        "slow": trmod.tracer.slow(),
                        "recent": trmod.tracer.traces(limit),
                    },
                    sort_keys=True,
                    default=str,
                ).encode()
                self._reply(200, body, "application/json")
            elif path == "/profile" or path.startswith("/profile?"):
                # Wall-clock sampling profile (collapsed flamegraph
                # stacks, obs/profiler.py): the window snapshots the
                # continuous sampler when BFTKV_PROFILE is armed, or
                # runs a temporary one — either way bounded, text/plain,
                # pipe straight into flamegraph.pl / speedscope.
                from bftkv_tpu.obs import profiler

                q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
                try:
                    seconds = float(q.get("seconds", ["2"])[0])
                except ValueError:
                    seconds = 2.0
                if not (seconds >= 0.05):  # also catches NaN
                    seconds = 0.05
                body = profiler.profile_for(min(seconds, 30.0)).encode()
                self._reply(200, body, "text/plain; charset=utf-8")
            elif path == "/info":
                body = json.dumps(
                    self.server.svc.info(), sort_keys=True
                ).encode()
                self._reply(200, body, "application/json")
            else:
                self._reply(404, b"unknown endpoint\n", "text/plain")
        except Error as e:
            code = 404 if type(e) is ERR_NOT_FOUND else 500
            self._reply(code, (e.message + "\n").encode(), "text/plain")
        except Exception as e:  # operator surface: never kill the daemon
            self._reply(500, (str(e) + "\n").encode(), "text/plain")

    do_GET = _handle
    do_POST = _handle


class _ApiService:
    """The daemon's own protocol client + graph introspection
    (reference: apiService, main.go:209-267)."""

    def __init__(self, client, graph, qs=None):
        self.client = client
        self.graph = graph
        self.qs = qs  # the DAEMON's quorum system (not the client's)

    def info(self) -> dict:
        """Machine-readable identity + shard seat for the fleet
        collector (``bftkv_tpu.obs``): who am I, which shard do I
        serve, and the b-masking thresholds of that shard's clique —
        computed HERE from the same ``quorum/wotqs.py`` state the
        protocol uses, so the health plane can never drift from the
        quorum math."""
        from bftkv_tpu.obs.source import seat_document

        g = self.graph
        out: dict = {
            "name": g.name,
            "id": f"{g.id:016x}",
            "addr": g.address,
            "uid": g.uid,
        }
        qs = self.qs if self.qs is not None else getattr(
            self.client, "qs", None
        )
        out.update(seat_document(qs, g.id))
        return out

    def show(self) -> str:
        g = self.graph
        lines = [f"self: {g.name} id={g.id:016x} addr={g.address} uid={g.uid}"]
        qs = self.qs if self.qs is not None else getattr(
            self.client, "qs", None
        )
        if qs is not None and hasattr(qs, "shard_count"):
            try:
                nsh = qs.shard_count()
                if nsh > 1:
                    owned = qs.owned_buckets()
                    mine = qs.my_shard()
                    lines.append(
                        f"shards: {nsh} (mine={mine}, "
                        "owned_buckets="
                        f"{'all' if owned is None else len(owned)}/256)"
                    )
            except Exception:
                pass
        for peer in g.get_peers():
            lines.append(
                f"peer: {peer.name} id={peer.id:016x} addr={peer.address} "
                f"active={peer.active} "
                f"signers={[f'{s:016x}' for s in peer.signers()]}"
            )
        revoked = g.serialize_revoked()
        lines.append(f"revoked: {len(revoked)} bytes")
        return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="bftkv server daemon")
    ap.add_argument("--home", required=True, help="home dir (pubring/secring)")
    ap.add_argument("--db", default="", help="storage path (dir or log file)")
    ap.add_argument("--storage", choices=["plain", "log", "native", "mem"],
                    default=flags.get("BFTKV_STORAGE") or "plain")
    ap.add_argument("--api", default="", help="client API listen addr host:port")
    ap.add_argument("--client-home", default="",
                    help="home dir whose identity performs client-API "
                         "reads/writes (a *user* identity: a server's own "
                         "identity under-collects collective signatures — "
                         "its AUTH|PEER quorum excludes itself, so its "
                         "sufficiency target is below what verifying "
                         "replicas require on the full clique; the "
                         "reference has the same property)")
    ap.add_argument("--revlist", default="", help="revocation list file")
    ap.add_argument("--ws", default="",
                    help="WebSocket visualizer feed addr host:port "
                         "(view at /visual on the client API)")
    ap.add_argument("--bind-host", default="",
                    help="listen on this host instead of the certificate "
                         "address's host (containers: 0.0.0.0 so published "
                         "ports are reachable while peers still dial the "
                         "certificate address)")
    ap.add_argument("--join", action="store_true",
                    help="crawl the trust graph at startup")
    ap.add_argument("--anti-entropy", type=float, default=0.0,
                    metavar="SECONDS",
                    help="background replica state-sync interval "
                         "(jittered; 0 disables). Each round pulls "
                         "digests from f+1 distinct peers and admits "
                         "divergent records only through the full "
                         "local admission path — a restarted or "
                         "lagging replica converges without client "
                         "traffic (bftkv_tpu/sync)")
    ap.add_argument("--slow-trace", type=float, default=None,
                    metavar="SECONDS",
                    help="slow-request threshold: a request trace whose "
                         "root span exceeds it is kept on /trace and "
                         "logged as one JSON line (default from "
                         "BFTKV_SLOW_TRACE_SECONDS, else 1.0)")
    ap.add_argument("--rpc-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="per-RPC response deadline for inter-replica "
                         "calls (default from BFTKV_RPC_TIMEOUT / "
                         "BFTKV_HTTP_TIMEOUT, else 10)")
    ap.add_argument("--chaos-seed", type=int, default=None, metavar="N",
                    help="TESTING: arm the deterministic failpoint "
                         "registry with this seed and install the "
                         "default chaos program (seeded transport "
                         "delays/drops + sync-round aborts; "
                         "bftkv_tpu.faults). Same seed => same fault "
                         "schedule every run")
    ap.add_argument("--dispatch", action="store_true",
                    help="install the TPU verify/sign dispatchers "
                         "(one replica process per accelerator)")
    ap.add_argument("--sidecar", default="",
                    help="host:port or unix:/path of a shared CRYPTO "
                         "sidecar (cmd.verify_sidecar): verification AND "
                         "RSA signing batch across every co-located "
                         "tenant process.  Results are never trusted — "
                         "signatures are self-checked with the public "
                         "exponent and verdicts spot-checked locally "
                         "(BFTKV_SIDECAR_SPOT_RATE); sign keys only "
                         "cross a unix: socket or an HMAC channel "
                         "(--sidecar-secret), else signing stays local")
    ap.add_argument("--sidecar-secret", default="",
                    help="file with a shared secret: HMAC-authenticate "
                         "sidecar frames both ways (enables remote "
                         "signing over TCP; always fail-closed)")
    ap.add_argument("--verify-sidecar", default="",
                    help="host:port or unix:/path of a shared verify "
                         "sidecar (cmd.verify_sidecar); co-located "
                         "replicas consolidate their verification "
                         "batches into one accelerator-owning process — "
                         "verification is public data, signing stays "
                         "in-process. Prefer unix: (mode-0600 socket); "
                         "a TCP port can be squatted after a crash")
    ap.add_argument("--verify-sidecar-secret", default="",
                    help="file with a shared secret: HMAC-authenticate "
                         "sidecar frames both ways and fail closed "
                         "(local verify) on mismatch — use with TCP")
    args = ap.parse_args(argv)
    # Honor JAX_PLATFORMS=cpu *robustly*: ambient sitecustomize may
    # register an accelerator PJRT plugin at interpreter start, and the
    # profiler/trace endpoint initializes every registered backend — a
    # dead accelerator tunnel would hang the API thread.  force_cpu
    # repairs the already-imported jax in-process (same mechanism as
    # the test suite's conftest).
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        from bftkv_tpu.hostcpu import force_cpu

        force_cpu(1)
    if not args.db and args.storage != "mem":
        args.db = args.home.rstrip("/") + ".db"
    if not args.revlist:
        args.revlist = args.home.rstrip("/") + ".rev"
    if args.slow_trace is not None:
        from bftkv_tpu import trace as trmod

        trmod.tracer.slow_threshold = args.slow_trace
    if args.chaos_seed is not None:
        from bftkv_tpu import faults

        faults.default_chaos_program(faults.arm(args.chaos_seed))
        print(
            f"bftkv: CHAOS armed, seed={args.chaos_seed} "
            "(deterministic failpoint program)", flush=True,
        )

    server, graph, crypt, qs, tr = build_server(args)

    if args.sidecar:
        from bftkv_tpu.ops import dispatch

        from bftkv_tpu.crypto.remote_verify import (
            RemoteSignerDomain,
            RemoteVerifierDomain,
            SidecarChannel,
        )

        secret = None
        if args.sidecar_secret:
            from bftkv_tpu.cmd.verify_sidecar import load_secret

            secret = load_secret(args.sidecar_secret)
        # ONE channel for both domains: a dishonest verdict on either
        # op benches the service for both.  calibrate=False on the
        # sign dispatcher: the CPU prefer_host bypass would keep
        # Signer.issue_many from ever reaching the remote domain (the
        # sidecar's own dispatchers re-apply the measured crossover
        # server-side), and the per-process window stays short — the
        # cross-process coalescing happens in the sidecar.
        chan = SidecarChannel(args.sidecar, secret=secret)
        dispatch.install(
            dispatch.VerifyDispatcher(
                verifier=RemoteVerifierDomain(channel=chan)
            )
        )
        dispatch.install_signer(
            dispatch.SignDispatcher(
                signer=RemoteSignerDomain(channel=chan),
                calibrate=False,
                max_wait=0.002,
            )
        )
        if not chan.carries_keys:
            print(
                "bftkv: sidecar channel cannot carry sign keys "
                "(plain TCP without --sidecar-secret); signing stays "
                "local, verification remotes", flush=True,
            )
    elif args.verify_sidecar:
        from bftkv_tpu.ops import dispatch

        from bftkv_tpu.crypto.remote_verify import RemoteVerifierDomain

        # Verification goes to the sidecar (which owns the accelerator);
        # this process must NOT also install device crypto — signing
        # stays host-side unless --dispatch explicitly claims a chip.
        secret = None
        if args.verify_sidecar_secret:
            from bftkv_tpu.cmd.verify_sidecar import load_secret

            secret = load_secret(args.verify_sidecar_secret)
        dispatch.install(
            dispatch.VerifyDispatcher(
                verifier=RemoteVerifierDomain(
                    args.verify_sidecar, secret=secret
                )
            )
        )
        if args.dispatch:
            dispatch.install_signer()
    elif args.dispatch:
        from bftkv_tpu.ops import dispatch

        dispatch.install()
        dispatch.install_signer()

    from bftkv_tpu.obs import profiler

    if profiler.enabled():
        # Continuous sampler (BFTKV_PROFILE=1): /profile windows then
        # snapshot an always-running comb instead of arming on demand.
        profiler.ensure_started()
        print(
            f"bftkv: profiler armed @ {profiler.ensure_started().hz:g} Hz "
            "(/profile?seconds=N)", flush=True,
        )

    server.start(bind_host=args.bind_host)
    where = (
        f"{args.bind_host} (cert addr {graph.address})"
        if args.bind_host
        else graph.address
    )
    print(f"bftkv: serving {graph.name} @ {where}", flush=True)

    sync_daemon = None
    if args.anti_entropy > 0:
        from bftkv_tpu.sync import SyncDaemon

        sync_daemon = SyncDaemon(server, interval=args.anti_entropy).start()
        print(
            f"bftkv: anti-entropy every ~{args.anti_entropy:g}s", flush=True
        )

    from bftkv_tpu.protocol.client import Client

    if args.client_home:
        from bftkv_tpu import topology
        from bftkv_tpu.transport.http import TrHTTP

        cgraph, ccrypt, cqs = topology.load_home(args.client_home)
        client = Client(
            cgraph, cqs, TrHTTP(ccrypt, rpc_timeout=args.rpc_timeout), ccrypt
        )
    else:
        client = Client(graph, qs, tr, crypt)
    if args.join:
        client.joining()

    api_httpd = None
    if args.api:
        host, _, port = args.api.rpartition(":")
        api_httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)),
                                        _ApiHandler)
        api_httpd.daemon_threads = True
        api_httpd.svc = _ApiService(client, graph, qs)
        threading.Thread(target=api_httpd.serve_forever, daemon=True).start()
        print(f"bftkv: client API @ {args.api}", flush=True)

    stop = threading.Event()

    def shutdown(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    stop.wait()

    # Persist the revocation list atomically (re-enabling
    # main.go:170-183; a torn write must not poison the next boot).
    rl = graph.serialize_revoked()
    if rl:
        tmp = args.revlist + "~"
        with open(tmp, "wb") as f:
            f.write(rl)
        os.replace(tmp, args.revlist)
    if api_httpd is not None:
        api_httpd.shutdown()
    if sync_daemon is not None:
        sync_daemon.stop()
    server.stop()
    if hasattr(server.storage, "close"):
        server.storage.close()
    print("bftkv: stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
