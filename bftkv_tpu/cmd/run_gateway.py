"""``run_gateway`` — one edge gateway process (bftkv_tpu/gateway).

Loads a gateway home (``genkeys --gateways N`` emits ``gw01..``),
starts the front-door protocol listener on the certificate's address
(clients reach it with GW_READ/GW_WRITE over the same encrypted
transport every other command uses), and optionally exposes an
operator HTTP API:

    GET/POST /read/<var>    value bytes through the certified cache
    POST     /write/<var>   body = value, coalesced upstream
    GET      /metrics       JSON snapshot or Prometheus text
    GET      /info          identity + role=gateway + cache stats
                            (the fleet collector scrapes this)
    GET      /trace         recent + slow traces (?since= drains)

    python -m bftkv_tpu.cmd.run_gateway --home /tmp/keys/gw01 \
        --api 127.0.0.1:7801 [--sync-invalidate 5] [--fleet URL]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bftkv_tpu.errors import Error
from bftkv_tpu.metrics import registry as metrics


class _GwApiHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *a):
        pass

    def _reply(self, code: int, body: bytes, ctype="application/octet-stream"):
        self.send_response(code)
        self.send_header("content-type", ctype)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _var(self, prefix: str) -> bytes:
        return urllib.parse.unquote(self.path[len(prefix):]).encode()

    def _handle(self):
        gw = self.server.gateway
        path = self.path
        try:
            length = int(self.headers.get("content-length", "0") or 0)
            body = self.rfile.read(length) if length > 0 else b""
        except (ValueError, OSError):
            self._reply(400, b"bad request\n", "text/plain")
            return
        if self.command == "GET" and path.startswith("/write/"):
            self._reply(405, b"method not allowed\n", "text/plain")
            return
        try:
            if path.startswith("/read/"):
                value = gw.read_value(self._var("/read/"))
                if value is None:
                    self._reply(404, b"not found\n", "text/plain")
                else:
                    self._reply(200, value)
            elif path.startswith("/write/"):
                gw.write_value(self._var("/write/"), body)
                self._reply(200, b"ok\n", "text/plain")
            elif path == "/metrics" or path.startswith("/metrics?"):
                q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
                accept = self.headers.get("accept") or ""
                want_prom = q.get("format", [""])[0] == "prometheus" or (
                    "application/json" not in accept
                    and ("text/plain" in accept or "openmetrics" in accept)
                )
                if want_prom:
                    self._reply(
                        200,
                        metrics.prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    self._reply(
                        200,
                        json.dumps(
                            metrics.snapshot(), sort_keys=True
                        ).encode(),
                        "application/json",
                    )
            elif path == "/info":
                self._reply(
                    200,
                    json.dumps(gw.info(), sort_keys=True).encode(),
                    "application/json",
                )
            elif path == "/trace" or path.startswith("/trace?"):
                from bftkv_tpu import trace as trmod

                q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
                if "since" in q:
                    try:
                        since = int(q["since"][0])
                    except ValueError:
                        since = 0
                    doc = trmod.tracer.export(max(0, since))
                    doc["slow"] = trmod.tracer.slow()
                else:
                    doc = {
                        "slow": trmod.tracer.slow(),
                        "recent": trmod.tracer.traces(20),
                    }
                self._reply(
                    200,
                    json.dumps(doc, sort_keys=True, default=str).encode(),
                    "application/json",
                )
            else:
                self._reply(404, b"unknown endpoint\n", "text/plain")
        except Error as e:
            self._reply(500, (e.message + "\n").encode(), "text/plain")
        except Exception as e:  # operator surface: never kill the daemon
            self._reply(500, (str(e) + "\n").encode(), "text/plain")

    do_GET = _handle
    do_POST = _handle


def _fleet_poll(gw, url: str, interval: float, stop: threading.Event):
    """Feed the collector's /fleet JSON into the gateway's routing
    (down members to the back of upstream waves; exhausted-budget
    shards onto the stale-cache fallback)."""
    while not stop.wait(interval):
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                gw.apply_fleet_snapshot(json.loads(r.read()))
            metrics.incr("gateway.fleet.polls")
        except Exception:
            metrics.incr("gateway.fleet.poll_errors")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="bftkv edge gateway daemon")
    ap.add_argument("--home", required=True,
                    help="gateway home dir (genkeys --gateways)")
    ap.add_argument("--listen", default="",
                    help="front-door listen addr host:port (default: "
                         "the certificate address)")
    ap.add_argument("--api", default="",
                    help="operator HTTP API listen addr host:port")
    ap.add_argument("--bind-host", default="",
                    help="listen interface override (containers)")
    ap.add_argument("--cache-max", type=int, default=65536)
    ap.add_argument("--cache-ttl", type=float, default=30.0,
                    help="certified-cache TTL seconds (the invalidation "
                         "backstop)")
    ap.add_argument("--max-inflight", type=int, default=64,
                    help="concurrent upstream quorum operations")
    ap.add_argument("--max-queue", type=int, default=128,
                    help="admission waiters beyond which requests shed")
    ap.add_argument("--sync-invalidate", type=float, default=5.0,
                    metavar="SECONDS",
                    help="anti-entropy invalidation poll interval "
                         "(SYNC_DIGEST diff per shard; 0 disables)")
    ap.add_argument("--fleet", default="", metavar="URL",
                    help="poll this /fleet endpoint and route around "
                         "down members / degraded shards")
    ap.add_argument("--fleet-interval", type=float, default=5.0)
    ap.add_argument("--rpc-timeout", type=float, default=None)
    ap.add_argument("--sidecar", default="",
                    help="host:port or unix:/path of the shared crypto "
                         "sidecar: the gateway's certified-fill verifies "
                         "and coalesced-write signing batch across the "
                         "whole box (results self-/spot-checked; see "
                         "bftkv --sidecar)")
    ap.add_argument("--sidecar-secret", default="",
                    help="shared-secret file for HMAC sidecar frames")
    args = ap.parse_args(argv)

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        from bftkv_tpu.hostcpu import force_cpu

        force_cpu(1)

    if args.sidecar:
        from bftkv_tpu.ops import dispatch

        from bftkv_tpu.crypto.remote_verify import (
            RemoteSignerDomain,
            RemoteVerifierDomain,
            SidecarChannel,
        )

        secret = None
        if args.sidecar_secret:
            from bftkv_tpu.cmd.verify_sidecar import load_secret

            secret = load_secret(args.sidecar_secret)
        chan = SidecarChannel(args.sidecar, secret=secret)
        dispatch.install(
            dispatch.VerifyDispatcher(
                verifier=RemoteVerifierDomain(channel=chan)
            )
        )
        dispatch.install_signer(
            dispatch.SignDispatcher(
                signer=RemoteSignerDomain(channel=chan),
                calibrate=False,
                max_wait=0.002,
            )
        )

    from bftkv_tpu import topology
    from bftkv_tpu.gateway import Gateway
    from bftkv_tpu.transport.http import TrHTTP

    graph, crypt, qs = topology.load_home(args.home)
    tr = TrHTTP(crypt, rpc_timeout=args.rpc_timeout)
    gw = Gateway(
        graph, qs, tr, crypt,
        cache_max=args.cache_max,
        cache_ttl=args.cache_ttl,
        max_inflight=args.max_inflight,
        max_queue=args.max_queue,
    )
    listen = args.listen
    if not listen:
        # genkeys drops the configured dial address beside the keys
        # (gateway certs carry none — they stay out of quorum planes).
        try:
            with open(os.path.join(args.home, "address")) as f:
                listen = f.read().strip().split("://", 1)[-1]
        except OSError:
            pass
    if not listen:
        print("run_gateway: no --listen and no address file in home",
              file=sys.stderr)
        return 1
    if args.bind_host:
        listen = f"{args.bind_host}:{listen.rsplit(':', 1)[-1]}"
    gw.start(listen)
    print(f"run_gateway: serving {graph.name} @ {listen}", flush=True)
    if args.sync_invalidate > 0:
        gw.start_sync_invalidation(args.sync_invalidate)

    stop = threading.Event()
    if args.fleet:
        threading.Thread(
            target=_fleet_poll,
            args=(gw, args.fleet, args.fleet_interval, stop),
            daemon=True,
        ).start()
        print(f"run_gateway: routing off {args.fleet}", flush=True)

    api_httpd = None
    if args.api:
        host, _, port = args.api.rpartition(":")
        api_httpd = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)), _GwApiHandler
        )
        api_httpd.daemon_threads = True
        api_httpd.gateway = gw
        threading.Thread(target=api_httpd.serve_forever, daemon=True).start()
        print(f"run_gateway: operator API @ {args.api}", flush=True)

    def shutdown(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    stop.wait()
    if api_httpd is not None:
        api_httpd.shutdown()
    gw.stop()
    print("run_gateway: stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
