"""``fleet`` — the fleet health CLI (collector front end).

One-shot report against a running fleet's daemon APIs::

    python -m bftkv_tpu.cmd.fleet --api-base 7001 --count 8

or watch continuously, or serve the collector's ``/fleet`` endpoint
(JSON + Prometheus) for dashboards::

    python -m bftkv_tpu.cmd.fleet --api-base 7001 --count 8 \
        --watch --interval 2 --listen 127.0.0.1:7999

``run_cluster --fleet PORT`` boots exactly this alongside the fleet.

Exit codes (one-shot): 0 healthy, 1 some shard's f-budget is exhausted
(``remaining < 0`` — more clique members dark than the b-masking bound
tolerates), 2 nothing scrapeable.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from bftkv_tpu.obs import FleetCollector, HTTPSource

__all__ = ["main", "render", "render_budget", "render_capacity"]


def render_capacity(doc: dict) -> str:
    """The ``--capacity`` table: per member, each exposed resource's
    USE row (utilization / saturation / errors), then the ranked
    bottleneck verdict — the "what do we fix next to go faster"
    answer (DESIGN.md §20)."""
    cap = doc.get("capacity") or {}
    members = cap.get("members") or {}
    lines: list[str] = []
    for member, rows in sorted(members.items()):
        if not rows:
            continue
        lines.append(f"capacity · {member}:")
        for res, row in sorted(
            rows.items(), key=lambda kv: -kv[1]["saturation"]
        ):
            bar = "#" * max(int(row["saturation"] * 24), 0)
            extras = []
            for k in ("items_per_launch", "mbps", "runnable", "backlog",
                      "batch_fill", "fsync_per_s"):
                v = row.get(k)
                if v not in (None, 0, 0.0):
                    extras.append(f"{k}={v:g}")
            disps = row.get("dispatchers") or {}
            for dname, d in sorted(disps.items()):
                occ = d.get("device_occupancy") or {}
                for w, o in sorted(occ.items()):
                    extras.append(f"{dname}[{w}]={o:.2f}")
                if d.get("items_per_launch"):
                    extras.append(
                        f"{dname}/launch={d['items_per_launch']:g}"
                    )
            lines.append(
                f"  {res:<12} util {row['utilization']:>5.0%}  "
                f"sat {row['saturation']:>5.0%}  "
                f"err {row['errors']:g}  {bar}"
                + ("  (" + ", ".join(extras) + ")" if extras else "")
            )
    verdict = cap.get("verdict") or {}
    lines.append(f"verdict: {verdict.get('summary', 'no capacity data')}")
    for r in (verdict.get("ranked") or [])[:5]:
        lines.append(
            f"  {r['score']:.3f}  {r['resource']:<12} on {r['member']} "
            f"(sat {r['saturation']:.2f} x weight {r['phase_weight']:.2f})"
        )
    return "\n".join(lines)


def render_budget(doc: dict) -> str:
    """The ``--budget`` table: per (op, shard), each phase's exclusive
    share of the wall clock plus the p99 exemplar's breakdown — the
    "where did the p99 go" answer (DESIGN.md §18)."""
    lines: list[str] = []
    for op in ("write", "read"):
        budget = doc.get(f"{op}_budget_by_phase") or {}
        for sh, b in sorted(budget.items(), key=lambda kv: str(kv[0])):
            lines.append(
                f"{op} budget · shard {sh}: {b['count']} traces, "
                f"total {b['root_sum_s']:g}s, "
                f"root p99≤{b['root_p99_le_s']:g}s"
            )
            phases = sorted(
                b.get("phases", {}).items(),
                key=lambda kv: -kv[1]["sum_s"],
            )
            for phase, pd in phases:
                if pd["sum_s"] <= 0:
                    continue
                bar = "#" * max(int(pd["share"] * 40), 1)
                lines.append(
                    f"  {phase:<9} {pd['share']:>6.1%}  "
                    f"{pd['sum_s']:>10.4f}s  {bar}"
                )
            ex = b.get("p99_exemplar")
            if ex:
                parts = ", ".join(
                    f"{p}={v:g}s"
                    for p, v in sorted(
                        ex["phases"].items(), key=lambda kv: -kv[1]
                    )
                )
                lines.append(
                    f"  p99 exemplar: trace={ex['trace_id']} "
                    f"{ex['root_s']:g}s → {parts}"
                )
    if not lines:
        lines.append(
            "no attributed traces yet (budgets need two scrapes: "
            "roots attribute one scrape after they appear)"
        )
    return "\n".join(lines)


def render(doc: dict) -> str:
    """The one-shot human report for one health document."""
    fl = doc["fleet"]
    tr = doc["traces"]
    lines = [
        f"fleet: {fl['up']}/{fl['daemons']} daemons up · "
        f"{len(doc['shards'])} shard(s) · "
        f"{tr['traces']} traces ({tr['stitched']} stitched) · "
        f"{len(doc['anomalies'])} anomalies"
    ]
    if fl.get("unseated"):
        lines.append(
            "UNSEATED (never answered /info — shard budgets "
            f"indeterminate): {', '.join(fl['unseated'])}"
        )
    repochs = fl.get("route_epochs") or {}
    if isinstance(repochs.get("max"), int) and repochs["max"] > 0:
        lines.append(
            f"route table: epoch {repochs['max']}"
            + (
                f" (SKEWED — some members still at {repochs['min']})"
                if repochs.get("skewed")
                else ""
            )
        )
    ap = doc.get("autopilot")
    if ap:
        last = ap.get("last") or {}
        lines.append(
            "autopilot: "
            + ("on" if ap.get("enabled") else "OFF (BFTKV_AUTOPILOT)")
            + f" · epoch {ap.get('epoch')}"
            + f" · migrations {ap.get('migrations', 0)}"
            + (
                f" · last {last['kind']}: shard {last.get('shard')} → "
                f"{last.get('targets')} ({last.get('buckets')} buckets, "
                f"{'ok' if last.get('ok') else 'in flight/blocked'})"
                if last.get("kind")
                else ""
            )
        )
        if ap.get("retired"):
            lines.append(f"  retired cliques: {ap['retired']}")
    drops = fl.get("trace_drops") or {}
    if drops.get("ring") or drops.get("slow"):
        lines.append(
            f"TRACE DROPS: ring={drops.get('ring', 0)} "
            f"slow={drops.get('slow', 0)} — attribution under-samples; "
            "scrape more often or raise the rings"
        )
    for sh, sd in sorted(doc["shards"].items()):
        fb = sd["f_budget"]
        slo = sd.get("slo", {})
        w = slo.get("write")
        slo_txt = (
            f" · write p50≤{w['p50_le_s']:g}s p99≤{w['p99_le_s']:g}s "
            f"(n={w['count']})"
            if w
            else ""
        )
        lines.append(
            f"shard {sh}: n={sd['n']} f={sd['f']} "
            f"2f+1={sd['threshold']} · "
            f"budget {fb['remaining']}/{fb['f']}"
            + (f" DOWN={','.join(fb['down'])}" if fb["down"] else "")
            + (
                f" storage-down={','.join(fb['storage_down'])}"
                if fb["storage_down"]
                else ""
            )
            + slo_txt
        )
        for mem in sd["members"]:
            mark = "·" if mem["status"] == "up" else "✗"
            ep = mem.get("epoch")
            lines.append(
                f"  {mark} {mem['name']} [{mem['role'] or '?'}] "
                f"{mem['status']}"
                + (f" e{ep}" if isinstance(ep, int) and ep > 0 else "")
            )
        for ex in sd.get("exemplars", [])[-3:]:
            lines.append(
                f"  slow: {ex['root']} {ex['duration']}s "
                f"trace={ex['trace_id']}"
                + (f" peer={ex['peer']}" if "peer" in ex else "")
            )
    regs = doc.get("regions") or {}
    if regs:
        rb = regs["f_budget"]
        lines.append(
            f"regions: {regs['n']} · region budget "
            f"{rb['remaining']}/{rb['f']}"
            + (f" DARK={','.join(rb['dark'])}" if rb["dark"] else "")
        )
        for rname, row in sorted(regs["rows"].items()):
            mark = "✗" if row["dark"] else "·"
            lines.append(
                f"  {mark} {rname}: {row['up']}/{row['members']} up"
                + (f" down={','.join(row['down'])}" if row["down"] else "")
                + (
                    f" gw={','.join(row['gateways'])}"
                    if row["gateways"]
                    else ""
                )
            )
    for name, g in sorted((doc.get("gateways") or {}).items()):
        mark = "·" if g["status"] == "up" else "✗"
        hits, misses = g.get("hits", 0), g.get("misses", 0)
        rate = hits / (hits + misses) if hits + misses else 0.0
        lines.append(
            f"  {mark} {name} [gateway] {g['status']} · "
            f"cache {g.get('entries', 0)} entries, "
            f"hit rate {rate:.0%} · shed {g.get('shed', 0)} · "
            f"verify_fail {g.get('verify_fail', 0)}"
            + (
                f" · lease serves {g['lease_served']}"
                f"{' (live)' if g.get('lease_live') else ''}"
                if g.get("lease_served")
                else ""
            )
        )
    for name, s in sorted((doc.get("sidecars") or {}).items()):
        mark = "·" if s["status"] == "up" else "✗"
        q = s.get("queue") or {}
        batch = (s.get("batch") or {}).get("sign") or {}
        occ = batch.get("occupancy_per_launch")
        lines.append(
            f"  {mark} {name} [sidecar] {s['status']} · "
            f"queue {q.get('inflight', 0)}+{q.get('waiting', 0)} "
            f"shed {q.get('shed', 0)}"
            + (f" · sign occupancy {occ:g}/launch" if occ else "")
        )
    for a in doc["anomalies"][-8:]:
        lines.append(
            f"anomaly #{a['seq']} {a['kind']} src={a['source']} "
            f"shard={a['shard']} {a['detail']} x{a['count']}"
        )
    return "\n".join(lines)


def _watch_line(doc: dict) -> str:
    budgets = " ".join(
        f"s{sh}:{sd['f_budget']['remaining']}/{sd['f_budget']['f']}"
        for sh, sd in sorted(doc["shards"].items())
    )
    return (
        f"[{time.strftime('%H:%M:%S')}] up={doc['fleet']['up']}"
        f"/{doc['fleet']['daemons']} budget {budgets} "
        f"traces={doc['traces']['traces']}"
        f"({doc['traces']['stitched']} stitched) "
        f"anomalies={len(doc['anomalies'])}"
    )


def _exit_code(doc: dict) -> int:
    if doc["fleet"]["up"] == 0:
        return 2
    if any(
        sd["f_budget"]["remaining"] < 0 for sd in doc["shards"].values()
    ):
        return 1
    if doc["fleet"].get("unseated"):
        # A member whose seat was never learned: the per-shard budgets
        # cannot be trusted while it is unaccounted for.
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet health collector (bftkv_tpu.obs)"
    )
    ap.add_argument("--targets", default="",
                    help="comma-separated daemon API addresses "
                         "(host:port,host:port,...)")
    ap.add_argument("--api-base", type=int, default=0,
                    help="first daemon API port (run_cluster --api-base); "
                         "use with --count")
    ap.add_argument("--count", type=int, default=0,
                    help="how many sequential API ports from --api-base")
    ap.add_argument("--api-host", default="127.0.0.1")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="scrape interval seconds (watch/listen modes)")
    ap.add_argument("--watch", action="store_true",
                    help="keep scraping, one status line per interval")
    ap.add_argument("--listen", default="",
                    help="serve /fleet (JSON + Prometheus) on host:port; "
                         "implies background scraping")
    ap.add_argument("--json", action="store_true",
                    help="one-shot: print the full health document as JSON")
    ap.add_argument("--scrapes", type=int, default=1,
                    help="one-shot: scrape this many times (interval apart) "
                         "before reporting — 2+ arms counter-delta anomalies")
    ap.add_argument("--budget", action="store_true",
                    help="one-shot: per-shard critical-path budget table "
                         "(phase shares + p99 exemplar; implies 2 scrapes "
                         "— attribution defers one scrape for stitching)")
    ap.add_argument("--capacity", action="store_true",
                    help="one-shot: USE-method capacity table + bottleneck "
                         "verdict (implies 2 scrapes — saturation judges "
                         "per-scrape deltas)")
    ap.add_argument("--bundle", default=None, metavar="DIR", nargs="?",
                    const="",
                    help="one-shot: write a flight-recorder bundle of "
                         "everything just scraped into DIR (default "
                         "BFTKV_RECORDER_DIR / <tmp>/bftkv-blackbox) and "
                         "print its path")
    ap.add_argument("--recorder", default="", metavar="DIR",
                    help="watch/listen: attach the flight recorder — every "
                         "anomaly snapshots a rate-limited, size-capped "
                         "black-box bundle under DIR, and POST "
                         "/fleet/bundle serves demand snapshots")
    ap.add_argument("--profile", type=float, default=0.0, metavar="SECONDS",
                    help="one-shot: also pull an N-second collapsed-stack "
                         "profile from every HTTP target (/profile)")
    args = ap.parse_args(argv)

    targets = [t for t in args.targets.split(",") if t.strip()]
    if args.api_base and args.count:
        targets += [
            f"{args.api_host}:{args.api_base + i}" for i in range(args.count)
        ]
    if not targets:
        print("fleet: no targets (--targets or --api-base/--count)",
              file=sys.stderr)
        return 2

    sources = [HTTPSource(t) for t in targets]
    collector = FleetCollector(sources, interval=args.interval)

    if args.listen or args.watch:
        if args.recorder:
            from bftkv_tpu.obs.recorder import FlightRecorder

            rec = FlightRecorder(args.recorder).add_to(collector)
            print(f"fleet: flight recorder @ {rec.dir}", flush=True)
        collector.start(args.interval)
        httpd = None
        if args.listen:
            from bftkv_tpu.obs.http import serve_fleet

            httpd = serve_fleet(collector, args.listen)
            print(f"fleet: /fleet @ {args.listen}", flush=True)
        try:
            while True:
                time.sleep(args.interval)
                if args.watch:
                    print(_watch_line(collector.health()), flush=True)
        except KeyboardInterrupt:
            pass
        finally:
            collector.stop()
            if httpd is not None:
                httpd.shutdown()
        return 0

    doc = None
    scrapes = max(args.scrapes, 2 if args.budget or args.capacity else 1)
    for i in range(scrapes):
        if i:
            time.sleep(args.interval)
        doc = collector.scrape_once()
    profiles = None
    if args.profile > 0:
        # Each /profile request BLOCKS for the window; the windows are
        # independent daemons' — capture them concurrently so the
        # one-shot costs ~one window, not members x window.
        import threading

        results = [""] * len(sources)

        def pull(i: int, src) -> None:
            try:
                results[i] = src.profile(args.profile)
            except Exception as e:
                results[i] = f"# profile failed: {e}\n"

        threads = [
            threading.Thread(target=pull, args=(i, s), daemon=True)
            for i, s in enumerate(sources)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        profiles = {
            src.name or src.base: text
            for src, text in zip(sources, results)
        }
    bundle_path = None
    if args.bundle is not None:
        from bftkv_tpu.obs.recorder import FlightRecorder

        rec = FlightRecorder(args.bundle or None, collector=collector)
        bundle_path = rec.snapshot(reason="demand")
    if args.json:
        # One parseable document on stdout, always: --profile/--bundle
        # results ride INSIDE it rather than trailing it (which would
        # break every `--json | jq .` consumer with Extra data).
        doc = dict(doc)
        if profiles is not None:
            doc["profiles"] = profiles
        if bundle_path is not None:
            doc["bundle"] = bundle_path
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
    else:
        print(render(doc))
        if args.budget:
            print(render_budget(doc))
        if args.capacity:
            print(render_capacity(doc))
        for name, text in (profiles or {}).items():
            print(f"--- profile {name} ---")
            print(text, end="")
        if bundle_path is not None:
            print(f"bundle: {bundle_path}")
    return _exit_code(doc)


if __name__ == "__main__":
    sys.exit(main())
