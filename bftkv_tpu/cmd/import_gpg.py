"""One-way GnuPG keyring importer — the reference operator's migration
path (VERDICT r4 missing #1).

The reference's entire identity universe is GnuPG homedirs generated
and cross-signed by ``scripts/setup.sh`` (reference:
scripts/setup.sh:17-48, scripts/gen.sh, scripts/trust.sh; keyring load
at crypto/pgp/crypto_pgp.go:115-223).  Each node directory holds

    <name>/pubring.gpg   — every key this node knows + the PGP
                           certifications (trust edges) it has imported
    <name>/secring.gpg   — this node's own secret key

This tool converts those into this framework's native home layout
(``bftkv_tpu.topology.save_home``: compact-cert ``pubring``, ``BSK1``
``secring``), re-issuing trust edges as **native compact-cert
signatures**:

- every PGP certification is first **verified against the PGP v4
  signature hash** (RFC 4880 §5.2.4) — a tampered pubring cannot mint
  native trust;
- an edge is re-signed natively when the *signer's* secret key is
  among the imported homedirs.  Migrating a whole cluster
  (``import_gpg --out native run/keys/a01 run/keys/a02 ...``) therefore
  reconstructs the complete trust graph with real signatures;
- verified edges whose signer key is *not* available (single-homedir
  import of third-party certifications) cannot be forged — they are
  reported as ``unconverted`` so the operator can re-sign from the
  signer's node, and (when importing that one homedir's own view) the
  self node's outbound edges are still covered by its secring.

PGP packet grammar support is deliberately read-only and minimal: v4
RSA (algo 1/3) and ECDSA P-256 (algo 19) primary keys, UserID packets,
certification signatures 0x10-0x13, unprotected v4 secret keys (the
reference's keys are passphrase-less, scripts/gen.sh).  Everything else
(subkeys, v3/v5 packets, protected keys) is skipped with a note —
this is an importer, not a PGP implementation (SURVEY §7 scoped PGP
grammar out as a capability; see docs/DESIGN.md §1.1).
"""

from __future__ import annotations

import hashlib
import io
import os
import re
import sys
from dataclasses import dataclass, field

from bftkv_tpu.crypto import cert as certmod
from bftkv_tpu.crypto import ec, rsa
from bftkv_tpu.crypto.ecdsa import ECPrivateKey, ECPublicKey

__all__ = ["parse_keyring", "import_homedirs", "main"]

# -- OpenPGP packet layer ---------------------------------------------------

TAG_SIGNATURE = 2
TAG_SECRET_KEY = 5
TAG_PUBLIC_KEY = 6
TAG_SECRET_SUBKEY = 7
TAG_USER_ID = 13
TAG_PUBLIC_SUBKEY = 14

ALGO_RSA = (1, 3)  # RSA encrypt-or-sign, RSA sign-only
ALGO_ECDSA = 19

_OID_P256 = bytes.fromhex("2a8648ce3d030107")

_HASHES = {
    1: "md5", 2: "sha1", 3: "ripemd160",
    8: "sha256", 9: "sha384", 10: "sha512", 11: "sha224",
}

# DigestInfo prefixes for EMSA-PKCS1-v1_5 (RFC 8017 §9.2 notes).
_DIGESTINFO = {
    "sha1": bytes.fromhex("3021300906052b0e03021a05000414"),
    "sha224": bytes.fromhex("302d300d06096086480165030402040500041c"),
    "sha256": bytes.fromhex("3031300d060960864801650304020105000420"),
    "sha384": bytes.fromhex("3041300d060960864801650304020205000430"),
    "sha512": bytes.fromhex("3051300d060960864801650304020305000440"),
}


class ImportError_(Exception):
    pass


def _iter_packets(data: bytes):
    """Yield ``(tag, body)`` for each OpenPGP packet (RFC 4880 §4)."""
    i, n = 0, len(data)
    while i < n:
        hdr = data[i]
        if not hdr & 0x80:
            raise ImportError_(f"bad packet header byte {hdr:#x} at {i}")
        if hdr & 0x40:  # new format
            tag = hdr & 0x3F
            i += 1
            body = bytearray()
            while True:
                if i >= n:
                    raise ImportError_("truncated packet length")
                o1 = data[i]
                if o1 < 192:
                    ln, i = o1, i + 1
                    partial = False
                elif o1 < 224:
                    ln = ((o1 - 192) << 8) + data[i + 1] + 192
                    i += 2
                    partial = False
                elif o1 == 255:
                    ln = int.from_bytes(data[i + 1 : i + 5], "big")
                    i += 5
                    partial = False
                else:  # 224..254: partial body length
                    ln = 1 << (o1 & 0x1F)
                    i += 1
                    partial = True
                body += data[i : i + ln]
                i += ln
                if not partial:
                    break
            yield tag, bytes(body)
        else:  # old format
            tag = (hdr >> 2) & 0x0F
            lentype = hdr & 0x03
            i += 1
            if lentype == 0:
                ln, i = data[i], i + 1
            elif lentype == 1:
                ln = int.from_bytes(data[i : i + 2], "big")
                i += 2
            elif lentype == 2:
                ln = int.from_bytes(data[i : i + 4], "big")
                i += 4
            else:  # indeterminate: rest of input
                ln = n - i
            yield tag, data[i : i + ln]
            i += ln


def _read_mpi(r: io.BytesIO) -> int:
    hdr = r.read(2)
    if len(hdr) < 2:
        raise ImportError_("truncated MPI")
    bits = int.from_bytes(hdr, "big")
    nbytes = (bits + 7) // 8
    raw = r.read(nbytes)
    if len(raw) < nbytes:
        raise ImportError_("truncated MPI body")
    return int.from_bytes(raw, "big")


# -- parsed structures ------------------------------------------------------


@dataclass
class PGPKey:
    keyid: bytes  # 8-byte PGP v4 key id
    fingerprint: bytes
    algo: int
    body: bytes  # raw public-key packet body (for sig hashing)
    n: int = 0
    e: int = 0
    point: bytes = b""  # SEC1 point for ECDSA
    uid: str = ""  # first user id string
    # verified certifications: set of issuer 8-byte keyids (self excluded)
    certified_by: set = field(default_factory=set)
    secret: object = None  # rsa.PrivateKey | ECPrivateKey when available


@dataclass
class Sig:
    sigtype: int
    pkalgo: int
    hashalgo: int
    hashed_raw: bytes  # version..hashed subpackets, for the v4 trailer
    issuer: bytes | None
    left16: bytes
    mpis: list


def _parse_pubkey_body(body: bytes) -> PGPKey | None:
    r = io.BytesIO(body)
    ver = r.read(1)[0]
    if ver != 4:
        return None
    r.read(4)  # creation time
    algo = r.read(1)[0]
    fpr = hashlib.sha1(
        b"\x99" + len(body).to_bytes(2, "big") + body
    ).digest()
    key = PGPKey(keyid=fpr[-8:], fingerprint=fpr, algo=algo, body=body)
    if algo in ALGO_RSA:
        key.n = _read_mpi(r)
        key.e = _read_mpi(r)
    elif algo == ALGO_ECDSA:
        oid_len = r.read(1)[0]
        oid = r.read(oid_len)
        if oid != _OID_P256:
            return None
        bits = int.from_bytes(r.read(2), "big")
        key.point = r.read((bits + 7) // 8)
    else:
        return None
    return key


def _parse_secret_body(body: bytes):
    """(pubkey, private) for an unprotected v4 secret key, else None."""
    pub = _parse_pubkey_body(body)
    if pub is None:
        return None
    # Re-walk to find where the public material ends; the packet body
    # for sig hashing (and the fingerprint/keyid) must be the *public*
    # form, not the secret packet body.
    r = io.BytesIO(body)
    r.read(6)
    if pub.algo in ALGO_RSA:
        _read_mpi(r), _read_mpi(r)
    else:
        oid_len = r.read(1)[0]
        r.read(oid_len)
        bits = int.from_bytes(r.read(2), "big")
        r.read((bits + 7) // 8)
    pub.body = body[: r.tell()]
    fpr = hashlib.sha1(
        b"\x99" + len(pub.body).to_bytes(2, "big") + pub.body
    ).digest()
    pub.fingerprint, pub.keyid = fpr, fpr[-8:]
    s2k_usage = r.read(1)
    if not s2k_usage or s2k_usage[0] != 0:
        return pub, None  # passphrase-protected: not supported
    try:
        if pub.algo in ALGO_RSA:
            d, p, q, _u = (_read_mpi(r) for _ in range(4))
            priv = rsa.PrivateKey(n=pub.n, e=pub.e, d=d, p=p, q=q)
        else:
            d = _read_mpi(r)
            pt = ec.P256.scalar_base_mult(d)
            priv = ECPrivateKey(
                d=d, public=ECPublicKey(x=pt[0], y=pt[1])
            )
    except ImportError_:
        return pub, None
    return pub, priv


def _parse_sig_body(body: bytes) -> Sig | None:
    r = io.BytesIO(body)
    ver = r.read(1)[0]
    if ver != 4:
        return None
    sigtype = r.read(1)[0]
    pkalgo = r.read(1)[0]
    hashalgo = r.read(1)[0]
    hashed_len = int.from_bytes(r.read(2), "big")
    hashed = r.read(hashed_len)
    unhashed_len = int.from_bytes(r.read(2), "big")
    unhashed = r.read(unhashed_len)
    left16 = r.read(2)
    mpis = []
    try:
        while True:
            mpis.append(_read_mpi(r))
    except ImportError_:
        pass
    issuer = None
    for area in (hashed, unhashed):
        for sp_type, sp_data in _iter_subpackets(area):
            if sp_type == 16 and len(sp_data) == 8:
                issuer = sp_data
            elif sp_type == 33 and len(sp_data) >= 21:
                issuer = sp_data[-8:]  # issuer fingerprint → key id
    return Sig(
        sigtype=sigtype,
        pkalgo=pkalgo,
        hashalgo=hashalgo,
        hashed_raw=body[: 6 + hashed_len],
        issuer=issuer,
        left16=left16,
        mpis=mpis,
    )


def _iter_subpackets(area: bytes):
    i, n = 0, len(area)
    while i < n:
        o1 = area[i]
        if o1 < 192:
            ln, i = o1, i + 1
        elif o1 < 255:
            ln = ((o1 - 192) << 8) + area[i + 1] + 192
            i += 2
        else:
            ln = int.from_bytes(area[i + 1 : i + 5], "big")
            i += 5
        if ln == 0 or i + ln > n:
            return
        yield area[i] & 0x7F, area[i + 1 : i + ln]
        i += ln


# -- certification verification (RFC 4880 §5.2.4) ---------------------------


def _cert_digest(key_body: bytes, uid: bytes, sig: Sig):
    name = _HASHES.get(sig.hashalgo)
    if name is None:
        return None
    h = hashlib.new(name)
    h.update(b"\x99" + len(key_body).to_bytes(2, "big") + key_body)
    h.update(b"\xb4" + len(uid).to_bytes(4, "big") + uid)
    h.update(sig.hashed_raw)
    h.update(b"\x04\xff" + len(sig.hashed_raw).to_bytes(4, "big"))
    return h.digest(), name


def _verify_certification(
    signee: PGPKey, uid: bytes, sig: Sig, signer: PGPKey
) -> bool:
    out = _cert_digest(signee.body, uid, sig)
    if out is None:
        return False
    digest, name = out
    if sig.left16 != digest[:2]:
        return False
    if signer.algo in ALGO_RSA and sig.pkalgo in ALGO_RSA:
        if len(sig.mpis) != 1:
            return False
        prefix = _DIGESTINFO.get(name)
        if prefix is None:
            return False
        k = (signer.n.bit_length() + 7) // 8
        em = b"\x00\x01" + b"\xff" * (k - len(prefix) - len(digest) - 3)
        em += b"\x00" + prefix + digest
        return pow(sig.mpis[0], signer.e, signer.n) == int.from_bytes(
            em, "big"
        )
    if signer.algo == ALGO_ECDSA and sig.pkalgo == ALGO_ECDSA:
        if len(sig.mpis) != 2:
            return False
        return _ecdsa_raw_verify(digest, sig.mpis[0], sig.mpis[1], signer)
    return False


def _ecdsa_raw_verify(digest: bytes, r_: int, s: int, signer: PGPKey) -> bool:
    cv = ec.P256
    n = cv.n
    if not (0 < r_ < n and 0 < s < n):
        return False
    pt = ec.unmarshal(cv, signer.point)
    if pt is None:
        return False
    z = int.from_bytes(digest, "big")
    shift = max(0, 8 * len(digest) - n.bit_length())
    z >>= shift
    w = pow(s, -1, n)
    u1, u2 = (z * w) % n, (r_ * w) % n
    R = cv.add(cv.scalar_base_mult(u1), cv.scalar_mult(pt, u2))
    if R is None:
        return False
    return R[0] % n == r_ % n


# -- keyring walk -----------------------------------------------------------


@dataclass
class Keyring:
    keys: dict  # 8-byte keyid -> PGPKey (primary keys only)
    notes: list  # skipped/unsupported items, human-readable


def parse_keyring(data: bytes) -> Keyring:
    """Parse an exported public (or secret) keyring into primary keys,
    their first user id, and the set of **cryptographically verified**
    certifications on them."""
    keys: dict[bytes, PGPKey] = {}
    notes: list[str] = []
    pending: list[tuple[PGPKey, bytes, Sig]] = []  # unresolved issuers
    cur: PGPKey | None = None
    cur_uid: bytes | None = None
    in_subkey = False
    for tag, body in _iter_packets(data):
        try:
            if tag in (TAG_PUBLIC_KEY, TAG_SECRET_KEY):
                in_subkey = False
                cur_uid = None
                if tag == TAG_PUBLIC_KEY:
                    parsed = _parse_pubkey_body(body)
                    priv = None
                else:
                    out = _parse_secret_body(body)
                    parsed, priv = out if out else (None, None)
                if parsed is None:
                    cur = None
                    notes.append(f"skipped unsupported primary key (tag {tag})")
                    continue
                cur = keys.setdefault(parsed.keyid, parsed)
                if priv is not None:
                    cur.secret = priv
            elif tag in (TAG_PUBLIC_SUBKEY, TAG_SECRET_SUBKEY):
                in_subkey = True  # subkeys carry no trust edges
            elif tag == TAG_USER_ID and cur is not None and not in_subkey:
                uid = body.decode("utf-8", "replace")
                cur_uid = body
                if not cur.uid:
                    cur.uid = uid
            elif tag == TAG_SIGNATURE and cur is not None and not in_subkey:
                sig = _parse_sig_body(body)
                if sig is None or cur_uid is None:
                    continue
                if not 0x10 <= sig.sigtype <= 0x13:
                    continue  # not a certification
                if sig.issuer is None or sig.issuer == cur.keyid:
                    continue  # self-sig binds the uid; not a trust edge
                signer = keys.get(sig.issuer)
                if signer is None:
                    pending.append((cur, cur_uid, sig))
                elif _verify_certification(cur, cur_uid, sig, signer):
                    cur.certified_by.add(sig.issuer)
                else:
                    notes.append(
                        f"BAD certification on {cur.uid!r} by issuer "
                        f"{sig.issuer.hex()} — rejected"
                    )
        except ImportError_ as e:
            notes.append(f"packet parse error (tag {tag}): {e}")
    # Issuers that appeared later in the ring.
    for signee, uid, sig in pending:
        signer = keys.get(sig.issuer)
        if signer is None:
            notes.append(
                f"certification on {signee.uid!r} by unknown issuer "
                f"{sig.issuer.hex()} — unverifiable, dropped"
            )
        elif _verify_certification(signee, uid, sig, signer):
            signee.certified_by.add(sig.issuer)
        else:
            notes.append(
                f"BAD certification on {signee.uid!r} by issuer "
                f"{sig.issuer.hex()} — rejected"
            )
    return Keyring(keys=keys, notes=notes)


# -- native conversion ------------------------------------------------------

_UID_RE = re.compile(
    r"^\s*(?P<name>[^(<]*?)\s*(?:\((?P<addr>[^)]*)\))?\s*"
    r"(?:<(?P<mail>[^>]*)>)?\s*$"
)


def _to_cert(key: PGPKey) -> certmod.Certificate:
    m = _UID_RE.match(key.uid or "")
    name = (m.group("name") if m else "") or key.keyid.hex()
    addr = (m.group("addr") if m else "") or ""
    mail = (m.group("mail") if m else "") or ""
    if key.algo in ALGO_RSA:
        return certmod.Certificate(
            n=key.n, e=key.e, name=name, address=addr, uid=mail
        )
    return certmod.Certificate(
        n=0, e=0, name=name, address=addr, uid=mail,
        alg=certmod.ALG_P256, point=key.point,
    )


@dataclass
class HomeRing:
    """One homedir's parsed view: its keys and its own verified edges."""

    path: str
    keys: dict  # 8-byte keyid -> PGPKey, THIS ring's view only
    owner_kid: bytes | None  # key whose secret rides this homedir


@dataclass
class ImportResult:
    certs: dict  # our 64-bit id -> Certificate (union view, all edges)
    secrets: dict  # our 64-bit id -> private key
    edges: list  # (signer our-id, signee our-id) natively re-signed
    unconverted: list  # (signer keyid hex, signee our-id): no signer key
    notes: list
    homes: list = field(default_factory=list)  # HomeRing per input dir


def import_homedirs(homedirs: list[str]) -> ImportResult:
    """Parse every homedir's pubring.gpg/secring.gpg and rebuild the
    universe natively.  Edge policy per module docstring: verified-PGP
    certification + available signer secret → native signature."""
    keys: dict[bytes, PGPKey] = {}
    notes: list[str] = []
    homes: list[HomeRing] = []
    for hd in homedirs:
        home_keys: dict[bytes, PGPKey] = {}
        owner_kid: bytes | None = None
        for fname in ("pubring.gpg", "secring.gpg"):
            path = os.path.join(hd, fname)
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                ring = parse_keyring(f.read())
            notes += [f"{path}: {n}" for n in ring.notes]
            for kid, key in ring.keys.items():
                have = keys.setdefault(kid, key)
                if have is not key:
                    have.certified_by |= key.certified_by
                    if have.secret is None and key.secret is not None:
                        have.secret = key.secret
                    if not have.uid:
                        have.uid = key.uid
                # Per-home COPY (own certified_by set): the home view
                # must stay this ring's view, not the growing union.
                hk = home_keys.get(kid)
                if hk is None:
                    home_keys[kid] = PGPKey(
                        keyid=key.keyid, fingerprint=key.fingerprint,
                        algo=key.algo, body=key.body, n=key.n, e=key.e,
                        point=key.point, uid=key.uid,
                        certified_by=set(key.certified_by),
                        secret=key.secret,
                    )
                else:
                    hk.certified_by |= key.certified_by
                    if hk.secret is None and key.secret is not None:
                        hk.secret = key.secret
                    if not hk.uid:
                        hk.uid = key.uid
        for kid, key in home_keys.items():
            if key.secret is not None and owner_kid is None:
                owner_kid = kid
        homes.append(HomeRing(path=hd, keys=home_keys, owner_kid=owner_kid))
    certs: dict[int, certmod.Certificate] = {}
    secrets: dict[int, object] = {}
    by_kid: dict[bytes, certmod.Certificate] = {}
    for kid, key in keys.items():
        c = _to_cert(key)
        certs[c.id] = c
        by_kid[kid] = c
        if key.secret is not None:
            secrets[c.id] = key.secret
    edges: list[tuple[int, int]] = []
    unconverted: list[tuple[str, int]] = []
    for kid, key in keys.items():
        signee = by_kid[kid]
        for issuer_kid in sorted(key.certified_by):
            issuer = keys.get(issuer_kid)
            if issuer is not None and issuer.secret is not None:
                certmod.sign_certificate(signee, issuer.secret)
                edges.append((by_kid[issuer_kid].id, signee.id))
            else:
                unconverted.append((issuer_kid.hex(), signee.id))
    return ImportResult(
        certs=certs, secrets=secrets, edges=edges,
        unconverted=unconverted, notes=notes, homes=homes,
    )


def write_native_homes(res: ImportResult, out: str) -> list[str]:
    """One ``save_home`` directory per homedir that contributed a
    secret key.

    Views are PER-HOME, mirroring the reference's keyring locality
    (each node's trust graph comes from its own GnuPG ring): a home's
    pubring holds only the keys its ring held, carrying only the edges
    its ring verified.  A global union view would be unsound — e.g. a
    user's outbound certifications written into *server* homes combine
    with the servers' quorum-certificate signatures on the user into
    bidirectional user↔server edges in every graph, pulling the user
    into the servers' maximal clique and silently reshaping quorums
    (the round-4 ``server_trust_rw`` incident, docs/DESIGN.md §1.2).

    For the same reason the OWNER's own outbound certifications become
    ``localtrust`` entries (local-only graph edges, never serialized
    into certificates) — this framework's canonical form for a node's
    own trust decisions."""
    from bftkv_tpu.topology import Identity, save_home

    # Secret pool spans every imported homedir (an edge in home A may
    # be signed by B's key when B's secring was also imported).
    union_secrets: dict[bytes, object] = {}
    for h in res.homes:
        for kid, key in h.keys.items():
            if key.secret is not None and kid not in union_secrets:
                union_secrets[kid] = key.secret

    written = []
    for home in res.homes:
        if home.owner_kid is None:
            continue
        owner_key = home.keys[home.owner_kid]
        view: list[certmod.Certificate] = []
        local_trust: list[int] = []
        owner_cert = None
        for kid, key in home.keys.items():
            c = _to_cert(key)
            for issuer_kid in sorted(key.certified_by):
                if issuer_kid == home.owner_kid:
                    local_trust.append(c.id)
                    continue
                secret = union_secrets.get(issuer_kid)
                if secret is not None:
                    certmod.sign_certificate(c, secret)
            view.append(c)
            if kid == home.owner_kid:
                owner_cert = c
        name = (owner_cert.name if owner_cert else "") or home.owner_kid.hex()
        path = os.path.join(out, name)
        save_home(
            path,
            Identity(name=name, key=owner_key.secret, cert=owner_cert),
            view,
            local_trust=sorted(set(local_trust) - {owner_cert.id}),
        )
        written.append(path)
    return written


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="import_gpg",
        description="Convert reference GnuPG homedirs (pubring.gpg + "
        "secring.gpg per node) into native bftkv_tpu home directories.",
    )
    ap.add_argument("homedirs", nargs="+", help="reference key dirs "
                    "(e.g. run/keys/a01 run/keys/a02 ...)")
    ap.add_argument("--out", required=True, help="output directory")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    res = import_homedirs(args.homedirs)
    written = write_native_homes(res, args.out)
    if not args.quiet:
        for n in res.notes:
            print(f"note: {n}", file=sys.stderr)
        print(
            f"imported {len(res.certs)} identities "
            f"({len(res.secrets)} with secret keys), "
            f"{len(res.edges)} trust edges re-signed natively, "
            f"{len(res.unconverted)} edges unconverted "
            "(signer secret key not among the imported homedirs)"
        )
        for path in written:
            print(f"  wrote {path}")
        if res.unconverted and not written:
            print(
                "hint: pass every node's homedir in one run so each "
                "edge's signer key is available",
                file=sys.stderr,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
