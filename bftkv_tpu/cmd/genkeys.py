"""Key/topology generator — the GnuPG-scripts replacement.

Builds the canonical universe (reference: scripts/setup.sh:17-48 —
server clique, storage-only rw nodes, users with quorum certificates)
and writes one home directory (pubring + secring) per principal, the
layout :func:`bftkv_tpu.topology.load_home` and the daemon consume.

    python -m bftkv_tpu.cmd.genkeys --out /tmp/keys \
        --servers 4 --rw 4 --users 2 --base-port 6001
"""

from __future__ import annotations

import argparse
import os


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True, help="directory for the home dirs")
    ap.add_argument("--servers", type=int, default=4,
                    help="quorum servers per shard")
    ap.add_argument("--rw", type=int, default=4,
                    help="storage-only rw nodes per shard")
    ap.add_argument("--shards", type=int, default=1,
                    help="number of disjoint server cliques: the "
                         "keyspace hash-routes across them "
                         "(--servers/--rw are per-shard counts)")
    ap.add_argument("--users", type=int, default=1)
    ap.add_argument("--unsigned-users", type=int, default=0,
                    help="trailing users without quorum certificates (TOFU)")
    ap.add_argument("--gateways", type=int, default=0,
                    help="edge gateway identities (gw01..): quorum-"
                         "certified front-door principals sharing one "
                         "TOFU uid, each with a dialable address "
                         "(bftkv_tpu.cmd.run_gateway serves one)")
    ap.add_argument("--gw-base-port", type=int, default=6201)
    ap.add_argument("--regions", type=int, default=0,
                    help="label every principal round-robin into N "
                         "regions (r0..rN-1) and write a `regions` "
                         "file into each home dir: deployment-plane "
                         "geography for locality-aware staging, "
                         "per-region latency classes, and the fleet "
                         "collector's region rollup (DESIGN.md §21); "
                         "certificates are untouched")
    ap.add_argument("--bits", type=int, default=2048)
    ap.add_argument("--alg", default="rsa", choices=["rsa", "p256", "mixed"],
                    help="identity-key algorithm: RSA-2048, ECDSA P-256, "
                         "or alternating (BASELINE config 4)")
    ap.add_argument("--base-port", type=int, default=6001)
    ap.add_argument("--rw-base-port", type=int, default=6101)
    ap.add_argument("--server-trust-rw", action="store_true",
                    help="servers trust rw nodes in their own views, so "
                         "daemon client-API reads have a read quorum "
                         "(extension; not in the reference topology)")
    args = ap.parse_args(argv)

    from bftkv_tpu import topology

    uni = topology.build_universe(
        args.servers,
        args.users,
        args.rw,
        scheme="http",
        base_port=args.base_port,
        rw_base_port=args.rw_base_port,
        bits=args.bits,
        unsigned_users=args.unsigned_users,
        server_trust_rw=args.server_trust_rw,
        alg=args.alg,
        n_shards=args.shards,
        n_gateways=args.gateways,
        gw_base_port=args.gw_base_port,
        n_regions=args.regions,
    )
    if args.regions > 1:
        by_region: dict[str, list[str]] = {}
        for ident in uni.all:
            if ident.region:
                by_region.setdefault(ident.region, []).append(ident.name)
        print(
            "regions: "
            + "; ".join(
                f"{r}: {','.join(names)}"
                for r, names in sorted(by_region.items())
            )
        )
    if args.shards > 1:
        groups = ", ".join(
            f"shard {i}: {g[0].name}..{g[-1].name}"
            for i, g in enumerate(uni.shards)
        )
        print(f"{args.shards} quorum cliques ({groups})")
    os.makedirs(args.out, exist_ok=True)
    for ident in uni.all:
        home = os.path.join(args.out, ident.name)
        topology.save_home(
            home, ident, uni.view_of(ident),
            local_trust=uni.local_trust_of(ident),
            regions=uni.regions or None,
        )
        dial = uni.gateway_addrs.get(ident.name, "")
        if dial:
            # Gateway certs carry no address (they must stay out of
            # the quorum planes); the dial address is deployment
            # config, dropped beside the keys for run_gateway and for
            # clients assembling their gateway list.
            with open(os.path.join(home, "address"), "w") as f:
                f.write(dial + "\n")
        print(
            f"{ident.name}: {home} "
            f"({ident.cert.address or dial or 'client'})"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
