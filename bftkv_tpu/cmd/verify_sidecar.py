"""Shared signature-verification sidecar — one process owns the chip.

SURVEY §5's deployment note: with several replica daemons co-located on
one accelerator host, per-process dispatchers each pay their own device
launches, XLA compilations, and transfer overhead.  *Verification* uses
only public data (message, signature, public key), so — unlike signing,
which must stay inside each replica's trust domain — all co-located
daemons can safely forward their verify batches to one sidecar: batches
from different replicas coalesce in the sidecar's dispatcher into
shared launches, and only one process compiles/holds the kernels.

Wire protocol (length-prefixed, one request per frame):

    request:  u32 count, then per item chunk(msg) chunk(sig) chunk(n) u32 e
    response: count bytes of 0/1

Failure semantics (deliberate, load-bearing):

- *Malformed frame* (attacker-controlled bytes): all-fail response of
  the claimed count — the client's accounting stays aligned and hostile
  input can never manufacture a "valid" verdict.
- *Internal error* (dispatcher/device failure): **zero-length
  response** — a count mismatch on the client side, which makes
  ``RemoteVerifierDomain`` fall back to local verification.  A broken
  accelerator must degrade to local verify, not masquerade as
  "all signatures invalid" (a cluster-wide liveness outage).

Trust boundary: verdicts are only as trustworthy as the transport, so
the recommended deployment is a **Unix domain socket** (``--listen
unix:/path/sock``, created mode 0600) — a TCP port can be squatted by
any local user after a sidecar crash, and the client would happily
reconnect to the impostor.  For TCP, configure a shared secret
(``--secret-file``): every request and response carries an HMAC-SHA256
tag and the client fails closed (local verify) on tag mismatch.

Run: ``python -m bftkv_tpu.cmd.verify_sidecar --listen unix:/run/bftkv/verify.sock``
Daemons opt in with ``bftkv --verify-sidecar unix:/run/bftkv/verify.sock``.
"""

from __future__ import annotations

import argparse
import hashlib
import hmac
import io
import os
import socket
import socketserver
import struct
import sys
import threading

from bftkv_tpu.packet import read_chunk, write_chunk

__all__ = [
    "serve",
    "main",
    "encode_request",
    "decode_request",
    "request_tag",
    "response_tag",
    "TAG_LEN",
]

TAG_LEN = 32  # HMAC-SHA256


def request_tag(secret: bytes, body: bytes) -> bytes:
    return hmac.new(secret, b"bftkv-sidecar-req" + body, hashlib.sha256).digest()


def response_tag(secret: bytes, req_body: bytes, out: bytes) -> bytes:
    """Tag binds the verdicts to the exact request they answer, so a
    recorded response for one batch cannot be replayed for another."""
    h = hashlib.sha256(req_body).digest()
    return hmac.new(secret, b"bftkv-sidecar-res" + h + out, hashlib.sha256).digest()


def encode_request(items: list) -> bytes:
    """[(message, sig_bytes, PublicKey)] → one request frame body."""
    buf = io.BytesIO()
    buf.write(struct.pack(">I", len(items)))
    for message, sig, key in items:
        write_chunk(buf, message)
        write_chunk(buf, sig)
        n = key.n
        write_chunk(buf, n.to_bytes((n.bit_length() + 7) // 8 or 1, "big"))
        buf.write(struct.pack(">I", key.e))
    return buf.getvalue()


def decode_request(body: bytes) -> list:
    from bftkv_tpu.crypto.rsa import PublicKey

    r = io.BytesIO(body)
    (count,) = struct.unpack(">I", r.read(4))
    if count > len(body):  # each item needs headers at minimum
        raise ValueError("bad count")
    items = []
    for _ in range(count):
        msg = read_chunk(r) or b""
        sig = read_chunk(r) or b""
        n = int.from_bytes(read_chunk(r) or b"", "big")
        (e,) = struct.unpack(">I", r.read(4))
        items.append((msg, sig, PublicKey(n=n, e=e)))
    return items


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        sock = self.request
        secret = self.server.secret
        try:
            while True:
                hdr = _recvall(sock, 4)
                if hdr is None:
                    return
                (ln,) = struct.unpack(">I", hdr)
                if ln > self.server.max_frame:
                    return  # oversized frame: drop the connection
                body = _recvall(sock, ln)
                if body is None:
                    return
                if secret is not None:
                    # Unauthenticated peer: drop the connection. No
                    # all-fail reply — an attacker must not be able to
                    # steer verdicts at all without the secret.
                    if len(body) < TAG_LEN or not hmac.compare_digest(
                        body[-TAG_LEN:], request_tag(secret, body[:-TAG_LEN])
                    ):
                        return
                    body = body[:-TAG_LEN]
                claimed = (
                    struct.unpack(">I", body[:4])[0] if len(body) >= 4 else 0
                )
                try:
                    items = decode_request(body)
                except Exception:
                    # Malformed frame: all-fail response of the claimed
                    # count keeps the client's accounting aligned (a
                    # hostile count is already bounded by the frame).
                    out = bytes(min(claimed, len(body)))
                else:
                    try:
                        ok = self.server.dispatcher.verify(items)
                        out = bytes(bool(b) for b in ok)
                    except Exception:
                        # Internal failure (dead/hung accelerator, bug):
                        # zero-length reply = count mismatch = client
                        # falls back to LOCAL verification.  Never
                        # fabricate "all invalid" for well-formed input.
                        out = b""
                tag = b"" if secret is None or not out else response_tag(
                    secret, body, out
                )
                sock.sendall(struct.pack(">I", len(out) + len(tag)) + out + tag)
        except (ConnectionError, OSError):
            return


def _recvall(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _UnixServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True


def serve(
    listen: str,
    *,
    max_batch: int = 4096,
    max_wait: float | None = None,
    max_frame: int = 1 << 26,
    secret: bytes | None = None,
):
    """Start the sidecar; returns (server, thread) for embedding.

    ``listen`` is ``host:port`` or ``unix:/path/to.sock`` (socket file
    created mode 0600 — only this uid's processes can obtain verdicts).
    """
    from bftkv_tpu.ops import dispatch

    if listen.startswith("unix:"):
        path = listen[len("unix:"):]
        try:
            os.unlink(path)
        except OSError:
            pass
        # umask, not post-bind chmod: the socket must never be
        # world-connectable, even for the bind→chmod window (a peer
        # that connects in that window keeps its connection).
        old_umask = os.umask(0o177)
        try:
            srv = _UnixServer(path, _Handler)
        finally:
            os.umask(old_umask)
        os.chmod(path, 0o600)
    else:
        host, _, port = listen.rpartition(":")
        srv = _Server((host or "127.0.0.1", int(port)), _Handler)
    kw = {} if max_wait is None else {"max_wait": max_wait}
    # calibrate=False: a sidecar exists BECAUSE it owns a crypto
    # device; the install-time host/device calibration is for
    # in-process dispatchers sharing a general-purpose host.  The
    # verifier's own host_threshold still routes tiny batches to host.
    srv.dispatcher = dispatch.VerifyDispatcher(
        max_batch=max_batch, calibrate=False, **kw
    ).start()
    srv.max_frame = max_frame
    srv.secret = secret
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t


def load_secret(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read().strip()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="shared verify sidecar")
    ap.add_argument("--listen", default="127.0.0.1:7900",
                    help="host:port, or unix:/path/to.sock (recommended: "
                         "a TCP port can be squatted after a crash)")
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--secret-file", default="",
                    help="file holding a shared secret; frames are then "
                         "HMAC-authenticated both ways (use for TCP)")
    args = ap.parse_args(argv)
    secret = load_secret(args.secret_file) if args.secret_file else None
    srv, t = serve(args.listen, max_batch=args.max_batch, secret=secret)
    print(f"verify-sidecar: listening on {args.listen}", flush=True)
    try:
        t.join()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
